"""BASELINE config 1: 2-layer MLP with amp O1 semantics (CPU-runnable).

The TPU port of examples/simple + the legacy ``amp.initialize`` flow
(tests/L1/common/main_amp.py shape): policy cast, dynamic loss scaling,
FusedAdam, one jitted train loop.

Run: PYTHONPATH=. python examples/simple/main_amp.py [--opt-level O1]
"""

import argparse

import jax
import jax.numpy as jnp

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import MLP


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--opt-level", default="O1",
                    choices=["O0", "O1", "O2", "O3"])
    ap.add_argument("--loss-scale", default="dynamic")
    ap.add_argument("--steps", type=int, default=50)
    args = ap.parse_args()

    model = MLP([16, 64, 64, 1])
    x = jax.random.normal(jax.random.PRNGKey(0), (512, 16))
    y = jnp.sum(x[:, :4], axis=1, keepdims=True)
    variables = model.init(jax.random.PRNGKey(1), x)

    loss_scale = (None if args.opt_level in ("O0",)
                  else args.loss_scale)
    params, _, policy, scaler = amp.initialize(
        variables["params"], None, args.opt_level, loss_scale=loss_scale)
    opt = FusedAdam(params, lr=1e-2,
                    master_weights=policy.master_weights)
    sstate = scaler.init() if scaler else None

    def loss_fn(p, scale_state):
        xb = policy.cast_inputs(x)
        pred = model.apply({"params": p}, xb).astype(jnp.float32)
        loss = jnp.mean((pred - y) ** 2)
        return scaler.scale(loss, scale_state) if scaler else loss

    p = opt.parameters
    for step in range(args.steps):
        sl, grads = jax.value_and_grad(
            lambda pp: loss_fn(pp, sstate))(p)
        if scaler:
            used_scale = float(sstate.scale)
            grads, found_inf = scaler.unscale(grads, sstate)
            p = opt.step(grads, found_inf=found_inf)
            sstate = scaler.update(sstate, found_inf)
            loss = float(sl) / used_scale
        else:
            p = opt.step(grads)
            loss = float(sl)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:3d}  loss {loss:.5f}"
                  + (f"  scale {float(sstate.scale):.0f}" if scaler else ""))


if __name__ == "__main__":
    main()
