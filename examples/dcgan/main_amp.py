"""DCGAN with amp mixed precision — TPU port of examples/dcgan/main_amp.py.

The reference example's point is amp with MULTIPLE models and optimizers
(``amp.initialize([netD, netG], [optD, optG], ...)``) and two backward
passes per step (errD_real + errD_fake, then errG). Here: two flax models,
two FusedAdam optimizers, one shared DynamicGradScaler policy, bf16 compute
(O1), synthetic data (the reference's --dataset fake mode) so the example is
self-contained.

Run: python examples/dcgan/main_amp.py [--steps N] [--opt_level O1]
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp

import flax.linen as nn

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam

NZ, NGF, NDF, IMG = 64, 32, 32, 32


class Generator(nn.Module):
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, z):
        # z: (b, nz) → (b, 32, 32, 3), mirrors the reference netG conv stack
        x = nn.Dense(4 * 4 * NGF * 4, dtype=self.compute_dtype)(z)
        x = x.reshape(z.shape[0], 4, 4, NGF * 4)
        for mult in (2, 1):
            x = nn.ConvTranspose(NGF * mult, (4, 4), strides=(2, 2),
                                 dtype=self.compute_dtype)(x)
            x = nn.GroupNorm(num_groups=8, dtype=jnp.float32)(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(3, (4, 4), strides=(2, 2),
                             dtype=self.compute_dtype)(x)
        return jnp.tanh(x.astype(jnp.float32))


class Discriminator(nn.Module):
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, img):
        x = img.astype(self.compute_dtype)
        for mult in (1, 2, 4):
            x = nn.Conv(NDF * mult, (4, 4), strides=(2, 2),
                        dtype=self.compute_dtype)(x)
            x = nn.leaky_relu(x, 0.2)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(1, dtype=jnp.float32)(x)[:, 0]


def bce_logits(logits, label):
    return jnp.mean(jnp.maximum(logits, 0) - logits * label
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--lr", type=float, default=2e-4)
    ap.add_argument("--opt_level", default="O1")
    args = ap.parse_args()

    policy = amp.Policy.from_opt_level(args.opt_level, loss_scale="dynamic")
    cd = jnp.bfloat16 if args.opt_level != "O0" else jnp.float32
    netG, netD = Generator(cd), Discriminator(cd)

    key = jax.random.PRNGKey(2809)  # the reference's default manualSeed
    kG, kD, kdata = jax.random.split(key, 3)
    pG = netG.init(kG, jnp.zeros((1, NZ)))
    pD = netD.init(kD, jnp.zeros((1, IMG, IMG, 3)))
    optG = FusedAdam(pG, lr=args.lr, betas=(0.5, 0.999))
    optD = FusedAdam(pD, lr=args.lr, betas=(0.5, 0.999))
    scaler = policy.make_scaler()
    sstate = scaler.init() if scaler else None

    # synthetic "real" images (--dataset fake)
    real = jax.random.uniform(kdata, (args.batch, IMG, IMG, 3), minval=-1,
                              maxval=1)

    @jax.jit
    def d_losses(pD, pG, z, sscale):
        fake = netG.apply(pG, z)
        errD = (bce_logits(netD.apply(pD, real), 1.0)
                + bce_logits(netD.apply(pD, jax.lax.stop_gradient(fake)),
                             0.0))
        return errD * sscale

    @jax.jit
    def g_losses(pG, pD, z, sscale):
        fake = netG.apply(pG, z)
        return bce_logits(netD.apply(pD, fake), 1.0) * sscale

    pG_, pD_ = optG.parameters, optD.parameters
    for step in range(args.steps):
        z = jax.random.normal(jax.random.fold_in(key, step),
                              (args.batch, NZ))
        sscale = sstate.scale if scaler else jnp.float32(1.0)

        # (1) update D: real + fake passes (the reference's two backwards)
        errD, gD = jax.value_and_grad(d_losses)(pD_, pG_, z, sscale)
        if scaler:
            gD, inf_d = scaler.unscale(gD, sstate)
            pD_ = optD.step(gD, found_inf=inf_d)
            sstate = scaler.update(sstate, inf_d)
        else:
            pD_ = optD.step(gD)

        # (2) update G through the (frozen) discriminator
        sscale = sstate.scale if scaler else jnp.float32(1.0)
        errG, gG = jax.value_and_grad(g_losses)(pG_, pD_, z, sscale)
        if scaler:
            gG, inf_g = scaler.unscale(gG, sstate)
            pG_ = optG.step(gG, found_inf=inf_g)
            sstate = scaler.update(sstate, inf_g)
        else:
            pG_ = optG.step(gG)

        d = float(errD) / float(sscale)
        g = float(errG) / float(sscale)
        print(f"step {step:3d}  errD {d:.4f}  errG {g:.4f}"
              + (f"  scale {float(sstate.scale):.0f}" if scaler else ""))

    print("done")


if __name__ == "__main__":
    main()
