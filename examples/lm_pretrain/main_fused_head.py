"""Tiny LM pretrain loop exercising the fused linear+cross-entropy head.

Mirrors the reference's runnable-examples convention
(/root/reference/examples/simple): a GPT-2-tiny backbone trained with
``transformer.linear_cross_entropy`` — the chunked-vocab head whose
logits never materialize in HBM — updated by FusedAdam.

Run (CPU or TPU):
    JAX_PLATFORMS=cpu python examples/lm_pretrain/main_fused_head.py \
        --steps 4 --vocab-chunk 256

With ``--ckpt-dir`` the loop becomes preemptible: it resumes from the
newest valid checkpoint, saves every ``--save-every`` steps through the
atomic CheckpointManager, and a SIGTERM/SIGINT triggers one final
synchronous save before exit (docs/robustness.md). ``--sharded-ckpt``
swaps in the distributed ShardedCheckpointManager: each process stages
only the shards it owns, preemption is agreed across processes (every
host saves the same step), and ``--watchdog-timeout`` arms the collective
watchdog over the commit barriers — all degenerate to the single-process
behavior on one host, so the same flag works from laptop to pod.

With ``--telemetry-jsonl PATH`` every step emits a telemetry row
(``{step, loss, grad_norm, loss_scale, step_ms, tokens_per_s, mfu, ...}``)
through ``apex_tpu.monitor.Telemetry`` — grad/param norms are collected
inside the jitted grad computation, checkpoint saves are charged to the
goodput ledger, and the run ends with a goodput summary line
(docs/observability.md).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
import jax.numpy as jnp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--vocab-chunk", type=int, default=256)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--ckpt-dir", type=str, default=None,
                    help="enable resumable checkpointing into this dir")
    ap.add_argument("--save-every", type=int, default=2)
    ap.add_argument("--sharded-ckpt", action="store_true",
                    help="use the distributed ShardedCheckpointManager "
                         "(two-phase commit, coordinated preemption)")
    ap.add_argument("--watchdog-timeout", type=float, default=None,
                    help="collective watchdog timeout in seconds (with "
                         "--sharded-ckpt)")
    ap.add_argument("--telemetry-jsonl", type=str, default=None,
                    help="emit per-step telemetry rows to this JSONL file")
    ap.add_argument("--trace-jsonl", type=str, default=None,
                    help="export per-step span traces as Perfetto-"
                         "loadable Chrome-trace JSON (with "
                         "--telemetry-jsonl)")
    args = ap.parse_args()

    from apex_tpu.models.gpt2 import GPT2, GPT2Config
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer import linear_cross_entropy

    cfg = GPT2Config.tiny()
    model = GPT2(cfg)

    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (args.batch, args.seq), 0,
                                cfg.vocab_size, jnp.int32)

    full = model.init(jax.random.PRNGKey(1), tokens)
    params = full["params"]

    # split the LM head (tied embedding) out: the fused head consumes
    # hidden states + the embedding matrix directly
    def loss_fn(params):
        hidden = model.apply({"params": params}, tokens,
                             return_hidden=True)
        wte = params["wte"]  # (V, H) tied LM head
        # next-token pairs via the repo's slice convention (gpt2.lm_loss):
        # position i predicts token i+1; the final position has no target
        loss = linear_cross_entropy(
            hidden[:, :-1].reshape(-1, hidden.shape[-1]),
            wte.T.astype(hidden.dtype),
            tokens[:, 1:].reshape(-1), 0.0, None, args.vocab_chunk)
        return jnp.mean(loss)

    opt = FusedAdam(params, lr=args.lr)

    @jax.jit
    def grads_of(params):
        from apex_tpu.monitor.metrics import collect_metrics

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # in-graph metrics: the norms trace into this same jit; values
        # leave as device scalars, nothing syncs until telemetry flushes
        # (loss_scale=1.0 — this example trains unscaled bf16-first)
        tm = collect_metrics(grads=grads, params=params, loss=loss,
                             loss_scale=1.0)
        return loss, grads, tm

    telemetry = None
    if args.telemetry_jsonl or args.trace_jsonl:
        from apex_tpu.monitor import Telemetry
        telemetry = Telemetry(args.telemetry_jsonl,
                              tokens_per_step=args.batch * args.seq,
                              trace_jsonl=args.trace_jsonl)
        telemetry.calibrate(grads_of, params)

    # optional resilience: resumable atomic checkpoints + preemption guard.
    # Console banners are rank-0 gated: an N-host run prints one resume/
    # preempt line, not N interleaved ones (bus events fire on every rank).
    rank0 = jax.process_index() == 0
    manager = guard = watchdog = None
    start_step = 0
    if args.ckpt_dir:
        import numpy as np

        from apex_tpu.resilience import CheckpointManager, PreemptionGuard
        if args.sharded_ckpt:
            from apex_tpu.resilience import (CollectiveWatchdog,
                                             ShardedCheckpointManager,
                                             default_coordinator)
            coord = default_coordinator()
            if args.watchdog_timeout:
                watchdog = CollectiveWatchdog(
                    timeout_s=args.watchdog_timeout, coordinator=coord)
            manager = ShardedCheckpointManager(
                args.ckpt_dir, max_to_keep=2, coordinator=coord,
                watchdog=watchdog)
            # coordinated: a SIGTERM on ANY host stops every process at
            # the same step, so the final sharded save can commit
            guard = PreemptionGuard(coordinator=coord).install()
        else:
            manager = CheckpointManager(args.ckpt_dir, max_to_keep=2)
            guard = PreemptionGuard().install()
        like = {"params": params, "opt": opt.state_dict(), "step": 0}
        restored = manager.restore_latest(like)
        if restored is not None:
            _, tree = restored
            params = tree["params"]
            opt.load_state_dict(jax.tree_util.tree_map(np.asarray,
                                                       tree["opt"]))
            start_step = int(tree["step"]) + 1
            if rank0:
                print(f"resumed from step {start_step - 1}", flush=True)

    def save(step, params):
        manager.save(step, {"params": params, "opt": opt.state_dict(),
                            "step": step})

    l0 = loss = None
    try:
        if telemetry is not None:
            telemetry.start()
        import contextlib

        def span(name):
            # per-step spans only when --trace-jsonl enabled a tracer:
            # each span also lands one mirrored JSONL event, and plain
            # telemetry must keep its events low-rate
            if telemetry is not None and telemetry.tracer is not None:
                return telemetry.span(name)
            return contextlib.nullcontext()

        for step in range(start_step, args.steps):
            with span("train_step"):
                loss, grads, tm = grads_of(params)
                params = opt.step(grads)
            if telemetry is not None:
                # the float(loss) print below is the loop's host sync; the
                # logged metric values stay device arrays until flush
                telemetry.log_step(step, metrics=tm)
            if l0 is None:
                l0 = float(loss)
            print(f"step {step}: loss {float(loss):.4f}", flush=True)
            if manager is not None and step % args.save_every == 0:
                with span("checkpoint"):  # the trace's ckpt-stall leg
                    save(step, params)  # stalls land in the goodput ledger
            if guard is not None and guard.should_stop():
                save(step, params)  # final synchronous save, then stop
                if rank0:
                    print(f"preempted: saved step {step}, exiting",
                          flush=True)
                return
    finally:
        if guard is not None:
            guard.restore()
        if watchdog is not None:
            watchdog.stop()
        if telemetry is not None:
            telemetry.close()
            import json
            print("telemetry:",
                  json.dumps(telemetry.summary()["goodput"]), flush=True)
    # l0 is the first loss seen by THIS process — only meaningful to
    # compare once we have run at least two steps since (a resumed run may
    # have had a single step left)
    if args.steps - start_step >= 2 and loss is not None:
        assert float(loss) < l0, "loss did not fall"
        print(f"OK: fused-head LM loss fell {l0:.4f} -> {float(loss):.4f}")


if __name__ == "__main__":
    main()
