"""Tiny LM pretrain loop exercising the fused linear+cross-entropy head.

Mirrors the reference's runnable-examples convention
(/root/reference/examples/simple): a GPT-2-tiny backbone trained with
``transformer.linear_cross_entropy`` — the chunked-vocab head whose
logits never materialize in HBM — updated by fused Adam.

Since PR 14 the hand-rolled loop is gone: the example drives the
production :class:`apex_tpu.train.Trainer` (docs/training.md) with a
custom ``loss_fn`` — the trainer owns the step, the atomic checkpoints,
the preemption guard, the watchdog, and the telemetry/goodput
accounting; this file is the config plus three print callbacks.

Run (CPU or TPU):
    JAX_PLATFORMS=cpu python examples/lm_pretrain/main_fused_head.py \
        --steps 4 --vocab-chunk 256

With ``--ckpt-dir`` the loop becomes preemptible: it resumes from the
newest valid checkpoint, saves every ``--save-every`` steps through the
atomic CheckpointManager, and a SIGTERM/SIGINT triggers one final
synchronous save before exit (docs/robustness.md). ``--sharded-ckpt``
swaps in the distributed ShardedCheckpointManager: each process stages
only the shards it owns, preemption is agreed across processes (every
host saves the same step), and ``--watchdog-timeout`` arms the collective
watchdog over the commit barriers — all degenerate to the single-process
behavior on one host, so the same flag works from laptop to pod.

With ``--telemetry-jsonl PATH`` every step emits a telemetry row
(``{step, loss, grad_norm, loss_scale, step_ms, tokens_per_s, mfu, ...}``)
through ``apex_tpu.monitor.Telemetry`` — grad/param norms are collected
inside the jitted step, checkpoint saves are charged to the goodput
ledger, and the run ends with a goodput summary line
(docs/observability.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
import jax.numpy as jnp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--vocab-chunk", type=int, default=256)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--ckpt-dir", type=str, default=None,
                    help="enable resumable checkpointing into this dir")
    ap.add_argument("--save-every", type=int, default=2)
    ap.add_argument("--sharded-ckpt", action="store_true",
                    help="use the distributed ShardedCheckpointManager "
                         "(two-phase commit, coordinated preemption)")
    ap.add_argument("--watchdog-timeout", type=float, default=None,
                    help="collective watchdog timeout in seconds (with "
                         "--sharded-ckpt)")
    ap.add_argument("--telemetry-jsonl", type=str, default=None,
                    help="emit per-step telemetry rows to this JSONL file")
    ap.add_argument("--trace-jsonl", type=str, default=None,
                    help="export per-step span traces as Perfetto-"
                         "loadable Chrome-trace JSON (with "
                         "--telemetry-jsonl)")
    args = ap.parse_args()

    from apex_tpu.models.gpt2 import GPT2, GPT2Config
    from apex_tpu.train import TrainConfig, Trainer
    from apex_tpu.transformer import linear_cross_entropy

    cfg = GPT2Config.tiny()
    model = GPT2(cfg)

    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (args.batch, args.seq), 0,
                                cfg.vocab_size, jnp.int32)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]

    # split the LM head (tied embedding) out: the fused head consumes
    # hidden states + the embedding matrix directly
    def loss_fn(params, tokens):
        hidden = model.apply({"params": params}, tokens,
                             return_hidden=True)
        wte = params["wte"]  # (V, H) tied LM head
        # next-token pairs via the repo's slice convention (gpt2.lm_loss):
        # position i predicts token i+1; the final position has no target
        loss = linear_cross_entropy(
            hidden[:, :-1].reshape(-1, hidden.shape[-1]),
            wte.T.astype(hidden.dtype),
            tokens[:, 1:].reshape(-1), 0.0, None, args.vocab_chunk)
        return jnp.mean(loss)

    # the whole former hand-rolled loop, as config: checkpoint cadence,
    # sharded/coordinated mode, watchdog, telemetry — the Trainer
    # composes CheckpointManager + PreemptionGuard + CollectiveWatchdog
    # + Telemetry exactly as this file used to wire by hand
    config = TrainConfig(
        steps=args.steps, batch=args.batch, seq=args.seq,
        vocab=cfg.vocab_size, hidden=cfg.n_embd, lr=args.lr,
        amp="off",  # this example trains unscaled bf16-first, as before
        checkpoint_dir=args.ckpt_dir,
        save_every=args.save_every if args.ckpt_dir else 0,
        sharded_checkpoint=bool(args.sharded_ckpt),
        max_to_keep=2,
        telemetry_jsonl=args.telemetry_jsonl,
        trace_jsonl=args.trace_jsonl,
        watchdog_timeout_s=(args.watchdog_timeout or None))

    coordinator = None
    if args.sharded_ckpt:
        from apex_tpu.resilience import default_coordinator
        coordinator = default_coordinator()
        if coordinator.process_count > 1:
            # multi-host: the trainer data-parallels over the batch (one
            # micro-shard per process — world must divide the batch); on
            # one host this stays exactly the single-shard loop
            import dataclasses
            world = coordinator.process_count
            if args.batch % world:
                raise SystemExit(
                    f"--batch {args.batch} must be divisible by the "
                    f"process count {world} for --sharded-ckpt "
                    f"multi-host runs")
            config = dataclasses.replace(config, world=world,
                                         grad_shards=world)

    losses = []

    def on_step(step, loss):
        losses.append(loss)
        print(f"step {step}: loss {loss:.4f}", flush=True)

    trainer = Trainer(config, coordinator=coordinator, loss_fn=loss_fn,
                      init_params=params, batch_fn=lambda step: tokens,
                      # guard only with a checkpoint dir (the pre-PR-14
                      # behavior): without one there is nothing to save,
                      # so a SIGTERM should just terminate the process
                      install_signal_handlers=bool(args.ckpt_dir))
    try:
        if args.telemetry_jsonl or args.trace_jsonl:
            trainer.calibrate()  # MFU from the XLA cost model
        report = trainer.run(
            on_step=on_step,
            on_resume=lambda step: print(f"resumed from step {step}",
                                         flush=True),
            on_preempt=lambda step: print(
                f"preempted: saved step {step}, exiting", flush=True))
        if trainer.telemetry is not None and (args.telemetry_jsonl
                                              or args.trace_jsonl):
            print("telemetry:",
                  json.dumps(trainer.telemetry.summary()["goodput"]),
                  flush=True)
    finally:
        trainer.close()
    if report["preempted"]:
        return
    # only meaningful once THIS process ran at least two steps (a resumed
    # run may have had a single step left)
    if len(losses) >= 2:
        assert losses[-1] < losses[0], "loss did not fall"
        print(f"OK: fused-head LM loss fell {losses[0]:.4f} -> "
              f"{losses[-1]:.4f}")


if __name__ == "__main__":
    main()
