"""Device→storage checkpoint throughput benchmark — the TPU equivalent of
``apex/contrib/examples/gpu_direct_storage/benchmark_{save,load}.py``.

The reference benchmarks ``_apex_gpu_direct_storage`` (GDSFile save/load),
whose point is moving GPU memory to disk without a host bounce buffer. On
TPU the runtime owns device memory and the direct path is orbax's async
sharded checkpointing (device arrays handed to a background writer;
OCDBT storage format), with a numpy .npz host-staged path as the
"no-GDS" comparison — the same yes-GDS/no-GDS A/B the reference runs.

Usage: python benchmark_save_load.py [workdir]
Prints bytes/sec for each size, save and load, both paths.
"""

import os
import shutil
import sys
import tempfile
import timeit

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.utils import checkpoint as ckpt


def _bench(label, fn, reps=3):
    fn()  # warmup
    t = timeit.timeit(fn, number=reps) / reps
    return t


def run(workdir: str):
    print(f"backend: {jax.default_backend()}")
    for logn in (20, 24, 26):
        size = 2 ** logn
        x = jnp.linspace(0.0, 1.0, size, dtype=jnp.float32)
        jax.block_until_ready(x)
        nbytes = size * 4
        tree = {"x": x}

        orbax_dir = os.path.join(workdir, f"orbax_{size}")
        npz_path = os.path.join(workdir, f"np_{size}.npz")

        def save_orbax():
            if os.path.exists(orbax_dir):
                shutil.rmtree(orbax_dir)
            ckpt.save(orbax_dir, tree)

        def load_orbax():
            return ckpt.restore(orbax_dir, tree)

        def save_np():
            ckpt.save_numpy(npz_path, tree)

        def load_np():
            return ckpt.restore_numpy(npz_path, tree)

        def save_orbax_async():
            # dispatch-side cost only: the background writer overlaps
            # training compute (the GDS "no host bounce" analog); wait()
            # outside the timed region makes it durable
            if os.path.exists(orbax_dir):
                shutil.rmtree(orbax_dir)
            return ckpt.save_async(orbax_dir, tree)

        def save_orbax_async_timed():
            h = save_orbax_async()
            h.wait()

        for label, fn in (("orbax_save", save_orbax),
                          ("orbax_async_save_total", save_orbax_async_timed),
                          ("orbax_load", load_orbax),
                          ("npz_save", save_np),
                          ("npz_load", load_np)):
            try:
                t = _bench(label, fn)
                print(f"{label}: size={size} ({nbytes/2**20:.0f} MiB)  "
                      f"{t*1e3:.1f} ms  {nbytes/t/2**30:.2f} GiB/s")
            except Exception as e:
                print(f"{label}: size={size} FAILED {type(e).__name__}: {e}")


if __name__ == "__main__":
    wd = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="apex_tpu_gds_")
    os.makedirs(wd, exist_ok=True)
    try:
        run(wd)
    finally:
        if len(sys.argv) <= 1:
            shutil.rmtree(wd, ignore_errors=True)
