"""Minimal generation with the apex_tpu.serve engine (CPU-runnable).

A tiny fp32 GPT-2 with random weights, four overlapping requests through
the continuous-batching scheduler: admissions share batched prefills,
decode is ONE jitted step for every slot, completions backfill from the
queue, and the run ends with per-request stats plus the engine's compile
counters (decode compiles exactly once — the serving invariant,
docs/serving.md).

Run: PYTHONPATH=. python examples/serve/generate.py [--requests 4]
     [--max-new-tokens 8] [--temperature 0.8 --top-k 5]
"""

import argparse
import dataclasses
import json

import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.serve import Engine, EngineConfig, Request, ServeScheduler
from apex_tpu.serve.engine import init_gpt2_params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=4)
    ap.add_argument("--num-slots", type=int, default=2)
    ap.add_argument("--prompt-len", type=int, default=6)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    cfg = dataclasses.replace(GPT2Config.tiny(),
                              compute_dtype=jnp.float32)
    engine = Engine(
        cfg, init_gpt2_params(cfg, seed=args.seed),
        EngineConfig(num_slots=args.num_slots, max_len=64,
                     temperature=args.temperature, top_k=args.top_k),
        seed=args.seed)
    engine.aot_compile([args.prompt_len])

    rng = np.random.RandomState(args.seed)
    sched = ServeScheduler(engine)
    for i in range(args.requests):
        prompt = [int(t) for t in rng.randint(0, cfg.vocab_size,
                                              args.prompt_len)]
        sched.submit(Request(request_id=f"req-{i}", tokens=prompt,
                             max_new_tokens=args.max_new_tokens))
    stats = sched.run()

    for rec in stats.requests:
        print(json.dumps(rec, sort_keys=True))
    print(json.dumps({"summary": stats.summary(),
                      "decode_compiles": engine.decode_traces},
                     sort_keys=True))
    assert engine.decode_traces == 1, "decode must compile exactly once"


if __name__ == "__main__":
    main()
