"""BASELINE configs 2-3: ResNet-50 training recipe — the TPU port of
examples/imagenet/main_amp.py (bf16 "amp" + data-parallel + SyncBatchNorm +
FusedAdam over a device mesh; synthetic data stands in for the dataloader).

Run (any host):
  PYTHONPATH=. XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  JAX_PLATFORMS=cpu python examples/imagenet/main_amp.py --tiny
On a TPU slice, drop the env overrides.
"""

import argparse
import functools
import time

import jax
import jax.numpy as jnp
from apex_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.resnet import ResNet18ish, ResNet50
from apex_tpu.optimizers.functional import adam_update
from apex_tpu.parallel import (bucketed_allreduce, get_mesh,
                               init_distributed)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="small model/images for CPU smoke runs")
    ap.add_argument("--batch-per-device", type=int, default=8)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    # multi-host rendezvous: honors MASTER_ADDR/RANK/WORLD_SIZE (the
    # torchrun contract of the reference example) and is a no-op for
    # single-process runs
    rank, nproc = init_distributed()
    mesh = get_mesh("data")
    world = mesh.devices.size
    print(f"process {rank}/{nproc}, devices: {world}")

    if args.tiny:
        model = ResNet18ish(num_classes=10, axis_name="data")
        img = (32, 32)
        classes = 10
    else:
        model = ResNet50(num_classes=1000, axis_name="data")
        img = (224, 224)
        classes = 1000

    B = args.batch_per_device * world
    x = jax.random.normal(jax.random.PRNGKey(0), (B, *img, 3))
    y = jax.random.randint(jax.random.PRNGKey(1), (B,), 0, classes)
    variables = model.init(jax.random.PRNGKey(2), x[:2])
    params, bstats = variables["params"], variables["batch_stats"]
    m = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32),
                               params)
    v = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32),
                               params)

    def local_step(params, bstats, m, v, xb, yb, step):
        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bstats}, xb,
                mutable=["batch_stats"])
            onehot = jax.nn.one_hot(yb, classes)
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * onehot, axis=-1))
            return loss, mut["batch_stats"]

        (loss, new_bstats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = bucketed_allreduce(grads, "data")  # flat-bucket DDP sync
        params, m, v = adam_update(params, grads, m, v, step=step,
                                   lr=args.lr, weight_decay=1e-4)
        return params, new_bstats, m, v, jax.lax.pmean(loss, "data")

    train_step = jax.jit(shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P("data"), P("data"), P()),
        out_specs=(P(), P(), P(), P(), P()), check_vma=False))

    for step in range(1, args.steps + 1):
        t0 = time.perf_counter()
        params, bstats, m, v, loss = train_step(
            params, bstats, m, v, x, y, jnp.int32(step))
        loss.block_until_ready()
        dt = time.perf_counter() - t0
        print(f"step {step:3d}  loss {float(loss):.4f}  "
              f"{B / dt:8.1f} imgs/s")


if __name__ == "__main__":
    main()
