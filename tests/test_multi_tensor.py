"""L0 tests for the multi_tensor substrate (≈ the amp_C kernel family).

Mirrors the reference L0 pattern: fused op vs plain reference under allclose
(tests/L0/run_optimizers, SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
    multi_tensor_unscale_l2norm,
    update_scale_hysteresis,
)


def _tree(key, dtypes=(jnp.float32, jnp.bfloat16)):
    ks = jax.random.split(key, 4)
    return {
        "a": jax.random.normal(ks[0], (33, 7), dtypes[0]),
        "b": [jax.random.normal(ks[1], (128,), dtypes[1]),
              jax.random.normal(ks[2], (5, 5, 5), dtypes[0])],
        "c": jax.random.normal(ks[3], (1,), dtypes[0]),
    }


class TestScale:
    def test_scale(self):
        t = _tree(jax.random.PRNGKey(0))
        out, found = multi_tensor_scale(t, 2.5)
        np.testing.assert_allclose(
            np.asarray(out["a"]), np.asarray(t["a"]) * 2.5, rtol=1e-6)
        assert not bool(found)

    def test_scale_detects_inf_and_nan(self):
        t = _tree(jax.random.PRNGKey(1))
        t["a"] = t["a"].at[0, 0].set(jnp.inf)
        _, found = multi_tensor_scale(t, 1.0)
        assert bool(found)
        t["a"] = t["a"].at[0, 0].set(jnp.nan)
        _, found = multi_tensor_scale(t, 1.0)
        assert bool(found)

    def test_jittable(self):
        t = _tree(jax.random.PRNGKey(2))
        out, found = jax.jit(multi_tensor_scale)(t, jnp.float32(0.5))
        assert out["a"].dtype == t["a"].dtype


class TestAxpby:
    def test_axpby(self):
        x = _tree(jax.random.PRNGKey(3))
        y = _tree(jax.random.PRNGKey(4))
        out, found = multi_tensor_axpby(2.0, x, -1.0, y)
        ref = 2.0 * np.asarray(x["a"]) - np.asarray(y["a"])
        np.testing.assert_allclose(np.asarray(out["a"]), ref, rtol=1e-6)


class TestL2Norm:
    def test_global_matches_numpy(self):
        t = _tree(jax.random.PRNGKey(5), (jnp.float32, jnp.float32))
        g, _ = multi_tensor_l2norm(t)
        flat = np.concatenate([np.asarray(l).ravel()
                               for l in jax.tree_util.tree_leaves(t)])
        np.testing.assert_allclose(float(g), np.linalg.norm(flat), rtol=1e-5)

    def test_per_tensor(self):
        t = [jnp.ones((10,)), 2 * jnp.ones((4,))]
        g, pt = multi_tensor_l2norm(t, per_tensor=True)
        np.testing.assert_allclose(np.asarray(pt),
                                   [np.sqrt(10.0), 4.0], rtol=1e-6)

    def test_unscale_l2norm(self):
        t = [jnp.full((8,), 4.0)]
        out, g, _, found = multi_tensor_unscale_l2norm(t, 0.25)
        np.testing.assert_allclose(np.asarray(out[0]), np.ones(8), rtol=1e-6)
        assert not bool(found)


class TestUpdateScaleHysteresis:
    """State-machine parity with csrc/update_scale_hysteresis.cu:5-41."""

    def test_growth_after_interval(self):
        s, g, h = jnp.float32(2.0), jnp.int32(0), jnp.int32(2)
        for _ in range(3):
            s, g, h = update_scale_hysteresis(s, g, h, False, 2.0, 0.5, 3, 2)
        assert float(s) == 4.0 and int(g) == 0

    def test_backoff_consumes_hysteresis_first(self):
        s, g, h = jnp.float32(8.0), jnp.int32(1), jnp.int32(2)
        s, g, h = update_scale_hysteresis(s, g, h, True, 2.0, 0.5, 100, 2)
        assert float(s) == 8.0 and int(h) == 1 and int(g) == 0
        s, g, h = update_scale_hysteresis(s, g, h, True, 2.0, 0.5, 100, 2)
        # hysteresis exhausted → backoff; NOT replenished by the backoff
        # (update_scale_hysteresis.cu:38-40 replenishes only on clean steps)
        assert float(s) == 4.0 and int(h) == 0
        s, g, h = update_scale_hysteresis(s, g, h, True, 2.0, 0.5, 100, 2)
        assert float(s) == 2.0  # every further inf step backs off

    def test_clean_step_replenishes_hysteresis(self):
        s, g, h = jnp.float32(8.0), jnp.int32(0), jnp.int32(1)
        s, g, h = update_scale_hysteresis(s, g, h, False, 2.0, 0.5, 100, 2)
        assert int(h) == 2

    def test_growth_never_reaches_inf(self):
        # reference guards growth with isfinite (update_scale_hysteresis.cu:28-30)
        s, g, h = jnp.float32(3e38), jnp.int32(0), jnp.int32(1)
        s, g, h = update_scale_hysteresis(s, g, h, False, 2.0, 0.5, 1, 1)
        assert float(s) == jnp.float32(3e38) and jnp.isfinite(s)

    def test_jit_roundtrip(self):
        f = jax.jit(lambda s, g, h, fi: update_scale_hysteresis(
            s, g, h, fi, 2.0, 0.5, 2000, 1))
        s, g, h = f(jnp.float32(65536.0), jnp.int32(0), jnp.int32(1), True)
        assert float(s) == 32768.0
