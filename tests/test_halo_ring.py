"""Halo exchange + ring attention on the 8-device CPU mesh — port of the
spatial-parallel tests (apex/contrib/test bottleneck/peer_memory patterns) and
the long-context story (SURVEY §5)."""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import (HaloExchangerAllGather, HaloExchangerNoComm,
                               HaloExchangerPeer, get_mesh, halo_exchange_1d,
                               left_right_halo_exchange, make_mesh,
                               ring_self_attention)
from apex_tpu.parallel.ring_attention import (zigzag_ring_self_attention,
                                              zigzag_shard, zigzag_unshard)
from apex_tpu.transformer import mha_reference

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    return get_mesh("sp")


class TestHaloExchange:
    def test_left_right_exchange(self, mesh):
        # device i holds rows [i*4, (i+1)*4); halos are 1-row strips
        x = jnp.arange(WORLD * 4 * 3, dtype=jnp.float32).reshape(WORLD * 4, 3)

        @functools.partial(shard_map, mesh=mesh, in_specs=P("sp"),
                           out_specs=(P("sp"), P("sp")), check_vma=False)
        def ex(xb):
            top = xb[:1]
            bottom = xb[-1:]
            l, r = left_right_halo_exchange(top, bottom, "sp")
            return l, r

        left_in, right_in = ex(x)
        left_in = np.asarray(left_in).reshape(WORLD, 1, 3)
        right_in = np.asarray(right_in).reshape(WORLD, 1, 3)
        xn = np.asarray(x).reshape(WORLD, 4, 3)
        for i in range(WORLD):
            if i > 0:  # left neighbor's bottom row
                np.testing.assert_array_equal(left_in[i, 0], xn[i - 1, 3])
            else:
                np.testing.assert_array_equal(left_in[i, 0], 0.0)
            if i < WORLD - 1:  # right neighbor's top row
                np.testing.assert_array_equal(right_in[i, 0], xn[i + 1, 0])
            else:
                np.testing.assert_array_equal(right_in[i, 0], 0.0)

    def test_halo_padded_conv_matches_full(self, mesh):
        """Spatially-sharded 1D conv with halo exchange == full conv
        (the SpatialBottleneck correctness property, bottleneck.py:833)."""
        H, C = WORLD * 8, 4
        x = jax.random.normal(jax.random.PRNGKey(0), (H, C))
        kern = jax.random.normal(jax.random.PRNGKey(1), (3, C))

        def conv_rows(xp):  # 'same' conv over rows via explicit halo
            return sum(xp[i:i + xp.shape[0] - 2] * kern[i]
                       for i in range(3))

        @functools.partial(shard_map, mesh=mesh, in_specs=P("sp"),
                           out_specs=P("sp"), check_vma=False)
        def sharded(xb):
            xpad = halo_exchange_1d(xb, 1, "sp", spatial_axis=0)
            return conv_rows(xpad)

        got = sharded(x)
        xfull = jnp.pad(x, ((1, 1), (0, 0)))
        want = conv_rows(xfull)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    def test_allgather_flavor_matches_ppermute(self, mesh):
        x = jax.random.normal(jax.random.PRNGKey(2), (WORLD * 4, 5))

        @functools.partial(shard_map, mesh=mesh, in_specs=P("sp"),
                           out_specs=(P("sp"), P("sp")), check_vma=False)
        def both(xb):
            a = HaloExchangerPeer("sp")(xb, 1)
            b = HaloExchangerAllGather("sp")(xb, 1)
            return a, b

        a, b = both(x)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_nocomm_zero_halos(self, mesh):
        x = jnp.ones((WORLD * 2, 3))

        @functools.partial(shard_map, mesh=mesh, in_specs=P("sp"),
                           out_specs=P("sp"), check_vma=False)
        def ex(xb):
            return HaloExchangerNoComm("sp")(xb, 1)

        out = np.asarray(ex(x)).reshape(WORLD, 4, 3)
        np.testing.assert_array_equal(out[:, 0], 0.0)
        np.testing.assert_array_equal(out[:, -1], 0.0)


class TestRingAttention:
    B, H, D = 1, 2, 32
    S = WORLD * 128  # 128 per device

    def _qkv(self, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        shape = (self.B, self.H, self.S, self.D)
        return tuple(jax.random.normal(k, shape) * 0.5 for k in ks)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device_reference(self, mesh, causal):
        q, k, v = self._qkv()

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None), check_vma=False)
        def ring(q, k, v):
            return ring_self_attention(q, k, v, "sp", causal=causal)

        got = ring(q, k, v)
        want = mha_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=2e-4)

    def test_zigzag_shard_roundtrip(self):
        x = jnp.arange(WORLD * 4.0).reshape(1, 1, WORLD * 4, 1)
        y = zigzag_unshard(zigzag_shard(x, WORLD), WORLD)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_zigzag_matches_single_device_reference(self, mesh):
        """Balanced causal ring (VERDICT item 6) == full causal attention."""
        q, k, v = self._qkv(seed=4)
        qz, kz, vz = (zigzag_shard(t, WORLD) for t in (q, k, v))

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None), check_vma=False)
        def ring(q, k, v):
            return zigzag_ring_self_attention(q, k, v, "sp")

        got = zigzag_unshard(ring(qz, kz, vz), WORLD)
        want = mha_reference(q, k, v, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=2e-4)

    @pytest.mark.slow
    def test_zigzag_differentiable(self, mesh):
        q, k, v = self._qkv(seed=5)
        qz, kz, vz = (zigzag_shard(t, WORLD) for t in (q, k, v))

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(), check_vma=False)
        def loss(q, k, v):
            o = zigzag_ring_self_attention(q, k, v, "sp")
            return jax.lax.psum(jnp.sum(o * o), "sp")

        gq, gk, gv = jax.grad(loss, (0, 1, 2))(qz, kz, vz)

        def ref_loss(q, k, v):
            return jnp.sum(mha_reference(q, k, v, True) ** 2)

        rq, rk, rv = jax.grad(ref_loss, (0, 1, 2))(q, k, v)
        for g, r, name in zip((gq, gk, gv), (rq, rk, rv), "qkv"):
            np.testing.assert_allclose(
                np.asarray(zigzag_unshard(g, WORLD)), np.asarray(r),
                atol=5e-4, rtol=5e-4, err_msg=f"d{name}")

    @pytest.mark.slow
    def test_differentiable(self, mesh):
        q, k, v = self._qkv(seed=1)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(), check_vma=False)
        def loss(q, k, v):
            o = ring_self_attention(q, k, v, "sp", causal=True)
            return jax.lax.psum(jnp.sum(o * o), "sp")

        gq, gk, gv = jax.grad(loss, (0, 1, 2))(q, k, v)

        def ref_loss(q, k, v):
            return jnp.sum(mha_reference(q, k, v, True) ** 2)

        rq, rk, rv = jax.grad(ref_loss, (0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                                   atol=5e-4, rtol=5e-4)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk),
                                   atol=5e-4, rtol=5e-4)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv),
                                   atol=5e-4, rtol=5e-4)


class TestUlysses:
    """Ulysses all-to-all sequence parallelism vs single-device flash."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        import functools
        from jax.sharding import PartitionSpec as P
        from apex_tpu.ops.pallas.flash_attention import flash_attention
        from apex_tpu.parallel import get_mesh, ulysses_self_attention

        mesh = get_mesh("sp")
        n = len(jax.devices())
        b, h, s, d = 2, n, n * 16, 64
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(k_, (b, h, s, d), jnp.float32) * 0.3
                   for k_ in ks)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(None, None, "sp"),
            out_specs=P(None, None, "sp"), check_vma=False)
        def sharded(q, k, v):
            return ulysses_self_attention(q, k, v, "sp", causal)

        out = sharded(q, k, v)
        ref = flash_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    @pytest.mark.slow
    def test_grad_matches_full_attention(self):
        import functools
        from jax.sharding import PartitionSpec as P
        from apex_tpu.ops.pallas.flash_attention import flash_attention
        from apex_tpu.parallel import get_mesh, ulysses_self_attention

        mesh = get_mesh("sp")
        n = len(jax.devices())
        b, h, s, d = 1, n, n * 8, 64
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q, k, v = (jax.random.normal(k_, (b, h, s, d), jnp.float32) * 0.3
                   for k_ in ks)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(None, None, "sp"),
            out_specs=P(), check_vma=False)
        def loss_sharded(q, k, v):
            o = ulysses_self_attention(q, k, v, "sp", True)
            return jax.lax.psum(jnp.sum(o.astype(jnp.float32) ** 2), "sp")

        g = jax.grad(lambda q: loss_sharded(q, k, v)[()])(q)
        g_ref = jax.grad(lambda q: jnp.sum(
            flash_attention(q, k, v, True).astype(jnp.float32) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   atol=5e-5)

    def test_rejects_h_not_divisible(self):
        import functools
        from jax.sharding import PartitionSpec as P
        from apex_tpu.parallel import get_mesh, ulysses_self_attention

        mesh = get_mesh("sp")
        n = len(jax.devices())
        if n < 2:
            pytest.skip("needs >1 device")
        q = jnp.zeros((1, n - 1, n * 8, 64))

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(None, None, "sp"),
            out_specs=P(None, None, "sp"), check_vma=False)
        def sharded(q):
            return ulysses_self_attention(q, q, q, "sp", False)

        with pytest.raises(Exception):
            sharded(q)


@pytest.mark.slow
class TestRdmaTransport:
    """Pallas remote-DMA peer transport (ops/pallas/remote_copy) vs the
    ppermute collective path — both must produce identical halos (the
    peer_memory push_pull_halos_1d capability, peer_memory.cpp:20-34).

    slow: interpret-mode RDMA emulation dominates tier-1 wall clock; the
    ppermute-collective equivalents above keep the semantics covered in the
    fast tier."""

    def test_peer_shift_matches_ppermute(self, mesh):
        from apex_tpu.ops.pallas.remote_copy import peer_shift
        x = jnp.arange(WORLD * 4 * 3, dtype=jnp.float32).reshape(WORLD * 4, 3)

        @functools.partial(shard_map, mesh=mesh, in_specs=P("sp"),
                           out_specs=P("sp"), check_vma=False)
        def rdma(x):
            return peer_shift(x, "sp", 1)

        @functools.partial(shard_map, mesh=mesh, in_specs=P("sp"),
                           out_specs=P("sp"), check_vma=False)
        def coll(x):
            perm = [(i, (i + 1) % WORLD) for i in range(WORLD)]
            return jax.lax.ppermute(x, "sp", perm)

        np.testing.assert_array_equal(np.asarray(rdma(x)),
                                      np.asarray(coll(x)))

    @pytest.mark.parametrize("halo", [1, 2])
    def test_halo_exchange_rdma_matches_collective(self, mesh, halo):
        from apex_tpu.contrib.peer_memory import PeerHaloExchanger1d
        x = jnp.arange(WORLD * 4 * 3, dtype=jnp.float32).reshape(
            1, WORLD * 4, 3)
        outs = {}
        for transport in ("collective", "rdma"):
            ex = PeerHaloExchanger1d(half_halo=halo, axis_name="sp",
                                     transport=transport)

            @functools.partial(shard_map, mesh=mesh, in_specs=P(None, "sp"),
                               out_specs=P(None, "sp"), check_vma=False)
            def body(x, ex=ex):
                return ex(x, spatial_axis=1)

            outs[transport] = np.asarray(body(x))
        np.testing.assert_array_equal(outs["collective"], outs["rdma"])

    def test_left_right_rdma_matches_collective(self, mesh):
        from apex_tpu.contrib.peer_memory import PeerHaloExchanger1d
        lo = jnp.arange(WORLD * 2 * 3, dtype=jnp.float32).reshape(
            WORLD * 2, 3)
        hi = lo * 10.0
        outs = {}
        for transport in ("collective", "rdma"):
            ex = PeerHaloExchanger1d(axis_name="sp", transport=transport)

            @functools.partial(shard_map, mesh=mesh,
                               in_specs=(P("sp"), P("sp")),
                               out_specs=(P("sp"), P("sp")),
                               check_vma=False)
            def body(lo, hi, ex=ex):
                return ex.left_right_halo_exchange(lo, hi)

            outs[transport] = [np.asarray(a) for a in body(lo, hi)]
        np.testing.assert_array_equal(outs["collective"][0], outs["rdma"][0])
        np.testing.assert_array_equal(outs["collective"][1], outs["rdma"][1])

    def test_halo_exchange_with_pool_landing_bufs(self, mesh):
        """Pool-backed landing buffers, threaded the honest way: arena
        views enter shard_map as ARGUMENTS, the puts land in their
        storage via input/output aliasing, and the returned landed
        buffers re-thread into the next call (allocation-free steady
        state). Halos must match the pool-less rdma path both calls."""
        from apex_tpu.contrib.peer_memory import PeerMemoryPool
        from apex_tpu.ops.pallas.remote_copy import (halo_buf_rows,
                                                     halo_exchange_rdma)

        halo = 2
        rows_per_dev = 8
        x = jnp.arange(WORLD * rows_per_dev * 128,
                       dtype=jnp.float32).reshape(WORLD * rows_per_dev, 128)
        br = halo_buf_rows(rows_per_dev, halo, jnp.float32)

        pool = PeerMemoryPool(static_size=1 << 20)
        # one buffer pair per device slot, entering shard_map sharded so
        # each device's slice is the kernel's (br, 128) landing contract
        lo_b = pool.allocate_peer_tensors((WORLD * br, 128), jnp.float32,
                                          False, False)[0]
        hi_b = pool.allocate_peer_tensors((WORLD * br, 128), jnp.float32,
                                          False, False)[0]

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P("sp"), P("sp"), P("sp")),
                           out_specs=(P("sp"), P("sp"), (P("sp"), P("sp"))),
                           check_vma=False)
        def body(x, lo_in, hi_in):
            lo, hi, landed = halo_exchange_rdma(x, "sp", halo,
                                                bufs=(lo_in, hi_in),
                                                return_bufs=True)
            return lo, hi, landed

        @functools.partial(shard_map, mesh=mesh, in_specs=P("sp"),
                           out_specs=(P("sp"), P("sp")), check_vma=False)
        def plain(x):
            return halo_exchange_rdma(x, "sp", halo)

        want_lo, want_hi = (np.asarray(a) for a in plain(x))
        lo1, hi1, landed = jax.jit(body)(x, lo_b, hi_b)
        np.testing.assert_array_equal(np.asarray(lo1), want_lo)
        np.testing.assert_array_equal(np.asarray(hi1), want_hi)
        # steady state: re-thread the landed buffers into the next call
        lo2, hi2, _ = jax.jit(body)(x, *landed)
        np.testing.assert_array_equal(np.asarray(lo2), want_lo)
        np.testing.assert_array_equal(np.asarray(hi2), want_hi)
        # the pool really sub-allocated arena ranges for the buffers
        assert len(pool.allocations) == 2
        assert all(r["offset"] % pool.alignment == 0
                   for r in pool.allocations)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_attention_rdma_matches_collective(self, mesh, causal):
        b, h, s, d = 1, 2, WORLD * 16, 32
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (b, h, s, d)) * 0.3 for kk in ks)
        outs, grads = {}, {}
        for transport in ("collective", "rdma"):
            @functools.partial(
                shard_map, mesh=mesh, in_specs=P(None, None, "sp"),
                out_specs=P(None, None, "sp"), check_vma=False)
            def body(q, k, v, transport=transport):
                return ring_self_attention(q, k, v, "sp", causal,
                                           transport=transport)

            outs[transport] = np.asarray(body(q, k, v))
            grads[transport] = np.asarray(jax.grad(
                lambda q: jnp.sum(body(q, k, v) ** 2))(q))
        np.testing.assert_allclose(outs["collective"], outs["rdma"],
                                   atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(grads["collective"], grads["rdma"],
                                   atol=1e-6, rtol=1e-6)

    def test_zigzag_rdma_matches_collective(self, mesh):
        b, h, s, d = 1, 2, WORLD * 16, 32
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q, k, v = (jax.random.normal(kk, (b, h, s, d)) * 0.3 for kk in ks)
        qz = zigzag_shard(q, WORLD)
        kz = zigzag_shard(k, WORLD)
        vz = zigzag_shard(v, WORLD)
        outs = {}
        for transport in ("collective", "rdma"):
            @functools.partial(
                shard_map, mesh=mesh, in_specs=P(None, None, "sp"),
                out_specs=P(None, None, "sp"), check_vma=False)
            def body(q, k, v, transport=transport):
                return zigzag_ring_self_attention(q, k, v, "sp",
                                                  transport=transport)

            outs[transport] = np.asarray(jax.grad(
                lambda q: jnp.sum(body(q, kz, vz) ** 2))(qz))
        np.testing.assert_allclose(outs["collective"], outs["rdma"],
                                   atol=1e-6, rtol=1e-6)
