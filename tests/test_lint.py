"""apexlint framework + rule tests (marker: ``lint``).

Three layers:

1. **The repo is clean** — the full rule suite over ``apex_tpu/`` +
   ``tools/`` yields zero active violations and zero unjustified
   suppressions, both in-process and through the CLI (exit 0). A new
   violation anywhere in the repo fails tier-1 here.
2. **Every rule fires and stays quiet** — seeded fixture trees per rule
   (the violation the rule exists for → exit 1; the disciplined spelling
   → exit 0).
3. **Suppression mechanics** — a justified ``# apexlint: disable=`` is
   honored and *counted* in the JSON report; one without justification
   text is itself a violation (APX000) and does not suppress.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.apexlint.core import run_lint  # noqa: E402
from tools.apexlint.cli import main as lint_main  # noqa: E402

pytestmark = pytest.mark.lint


def _fixture(tmp_path, relpath: str, source: str) -> str:
    """Write one fixture module under a synthetic repo root."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return str(path)


def _run(tmp_path, rule: str):
    active, suppressed, _ = run_lint(
        root=str(tmp_path), paths=[str(tmp_path / "apex_tpu")],
        only=[rule])
    return active, suppressed


# --------------------------------------------------------- 1. repo clean

def test_repo_is_clean_with_zero_unjustified_suppressions():
    active, suppressed, ctx = run_lint(root=ROOT)
    assert not active, "\n".join(v.format() for v in active)
    # every suppression that made it here carries its justification
    assert all(v.justification for v in suppressed)
    # the scan actually covered the package (not an empty-walk pass)
    assert len(ctx.files) > 100


def test_cli_clean_run_and_json_report():
    r = subprocess.run([sys.executable, "-m", "tools.apexlint",
                        "--format", "json"],
                       capture_output=True, text=True, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["ok"] is True
    assert doc["violations"] == []
    # the watchdog's every-rank stack dump is the known justified opt-out
    assert doc["suppressed_counts"].get("APX005", 0) >= 3
    assert all(s["justification"] for s in doc["suppressed"])
    assert set(doc["rules"]) == {"APX001", "APX002", "APX003", "APX004",
                                 "APX005"}


def test_console_script_shim_and_rule_listing(capsys):
    from apex_tpu.lint_cli import main as shim_main

    assert shim_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("APX001", "APX002", "APX003", "APX004", "APX005"):
        assert rule_id in out


# ------------------------------------------------- 2. fire/no-fire per rule

def test_apx001_fires_on_host_effects_reachable_from_traced_code(tmp_path):
    _fixture(tmp_path, "apex_tpu/bad.py", """\
        import time
        import jax

        def helper(x):
            t = time.perf_counter()
            publish_event("stamp", seconds=t)
            return x

        @jax.jit
        def step(x):
            return helper(x) + 1

        def body(c, x):
            return c, x.item()

        def run(xs):
            return jax.lax.scan(body, 0, xs)
        """)
    active, _ = _run(tmp_path, "APX001")
    msgs = [v.message for v in active]
    assert len(active) == 3
    assert any("perf_counter" in m for m in msgs)
    assert any("publish_event" in m for m in msgs)
    assert any(".item()" in m for m in msgs)
    # provenance names the traced root
    assert any("step[@jit]" in m for m in msgs)
    assert any("body[scan]" in m for m in msgs)


def test_apx001_quiet_on_pure_traced_code_and_host_only_effects(tmp_path):
    _fixture(tmp_path, "apex_tpu/good.py", """\
        import time
        import jax
        import jax.numpy as jnp

        def pure(x):
            return jnp.tanh(x) * 2.0

        @jax.jit
        def step(x):
            return pure(x)

        def host_loop(xs):
            # host-side timing around the jitted call is exactly right
            t0 = time.perf_counter()
            y = step(xs)
            return y, time.perf_counter() - t0
        """)
    active, _ = _run(tmp_path, "APX001")
    assert not active, [v.format() for v in active]


def test_apx001_flags_metrics_registry_record_in_traced_code(tmp_path):
    """A live-metrics registry mutation reachable from a traced root is
    the silently-wrong-telemetry class: it fires once per TRACE, not per
    step. ``.record()``/``.observe()``/``.inc()`` are all flagged."""
    _fixture(tmp_path, "apex_tpu/metered.py", """\
        import jax
        from apex_tpu.monitor.export import MetricsRegistry

        REG = MetricsRegistry()
        HIST = REG.histogram("step_seconds", "t")
        STEPS = REG.counter("steps_total", "n")

        def account(dt):
            HIST.record(dt)
            STEPS.inc()

        @jax.jit
        def step(x, dt):
            account(dt)
            return x + 1
        """)
    active, _ = _run(tmp_path, "APX001")
    msgs = [v.message for v in active]
    assert len(active) == 2
    assert any(".record()" in m and "metrics sink" in m for m in msgs)
    assert any(".inc()" in m for m in msgs)
    assert all("step[@jit]" in m for m in msgs)


def test_apx001_quiet_on_host_side_metrics_wiring(tmp_path):
    """The real wiring — recording around the jitted call, the scheduler
    tick hook pattern — stays quiet (the repo-wide clean run covers the
    actual serve/metrics.py spelling)."""
    _fixture(tmp_path, "apex_tpu/metered.py", """\
        import time
        import jax
        from apex_tpu.monitor.export import MetricsRegistry

        REG = MetricsRegistry()
        HIST = REG.histogram("step_seconds", "t")

        @jax.jit
        def step(x):
            return x + 1

        def host_loop(xs):
            for x in xs:
                t0 = time.perf_counter()
                y = step(x)
                HIST.record(time.perf_counter() - t0)
            return y
        """)
    active, _ = _run(tmp_path, "APX001")
    assert not active, [v.format() for v in active]


def test_apx001_boundary_functions_end_the_traversal(tmp_path):
    _fixture(tmp_path, "apex_tpu/tuned.py", """\
        import jax

        def tuned_params(kernel, **shape):
            # sanctioned trace-time host work (cache read + provenance)
            with open("/tmp/cache.json") as f:
                pass
            return {"block": 128}

        @jax.jit
        def kernel_wrapper(x):
            p = tuned_params("k", rows=x.shape[0])
            return x * p["block"]
        """)
    active, _ = _run(tmp_path, "APX001")
    assert not active, [v.format() for v in active]


def test_apx001_named_scope_is_not_a_traced_effect(tmp_path):
    """``jax.named_scope`` is pure trace-time metadata (it names the
    lowered StableHLO ``loc(...)`` scopes the cost ledger attributes
    phases on — PR 17) and must stay OUT of APX001's effect catalog:
    the annotated GPT-2 forwards use it inside jitted code everywhere.
    The fire half of the pair proves the rule still sees this fixture."""
    _fixture(tmp_path, "apex_tpu/scoped.py", """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x, w):
            with jax.named_scope("ln_qkv"):
                y = x @ w
            with jax.named_scope("mlp"):
                y = jnp.tanh(y)
            print("leaked")
            return y
        """)
    active, _ = _run(tmp_path, "APX001")
    assert len(active) == 1                  # the print, nothing else
    assert "print() is a host effect" in active[0].message


def test_apx002_fires_on_lock_free_rmw(tmp_path):
    _fixture(tmp_path, "apex_tpu/counter.py", """\
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                self.items = []

            def inc(self):
                with self._lock:
                    self.n += 1
                    self.items.append(self.n)

            def sneaky(self):
                self.n += 1
                self.items.append(0)
        """)
    active, _ = _run(tmp_path, "APX002")
    assert len(active) == 2
    assert all("lock-free" in v.message for v in active)
    assert {v.line for v in active} == {15, 16}


def test_apx002_quiet_on_disciplined_and_marked_code(tmp_path):
    _fixture(tmp_path, "apex_tpu/counter.py", """\
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                self.snapshot = None

            def inc(self):
                with self._lock:
                    self.n += 1

            def _bump(self):
                # caller holds self._lock
                self.n += 1

            def publish(self):
                # plain rebinding outside the lock is the snapshot idiom
                self.snapshot = {"n": 0}
        """)
    active, _ = _run(tmp_path, "APX002")
    assert not active, [v.format() for v in active]


def test_apx002_wrong_lock_is_flagged(tmp_path):
    """Holding *a* lock is not holding *the* lock: two locks 'guarding'
    one name exclude nothing."""
    _fixture(tmp_path, "apex_tpu/twolocks.py", """\
        import threading

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self._dump_lock = threading.Lock()
                self.ring = []

            def on_event(self, rec):
                with self._lock:
                    self.ring.append(rec)

            def drain(self):
                with self._dump_lock:
                    self.ring.pop()
        """)
    active, _ = _run(tmp_path, "APX002")
    assert len(active) == 2          # both disagreeing sites are flagged
    assert all("pick one" in v.message for v in active)


def test_apx002_sees_annotated_and_class_attr_locks(tmp_path):
    """A type annotation (`self._lock: Lock = Lock()`) or the class-attr
    idiom must not blind the rule."""
    _fixture(tmp_path, "apex_tpu/annotated.py", """\
        import threading
        from threading import Lock

        class A:
            def __init__(self):
                self._lock: Lock = threading.Lock()
                self.n = 0

            def inc(self):
                with self._lock:
                    self.n += 1

            def sneaky(self):
                self.n += 1

        class B:
            _lock = threading.Lock()

            def __init__(self):
                self.items = []

            def put(self, x):
                with self._lock:
                    self.items.append(x)

            def sneaky(self):
                self.items.pop()
        """)
    active, _ = _run(tmp_path, "APX002")
    assert len(active) == 2, [v.format() for v in active]
    assert {v.line for v in active} == {14, 27}


def test_apx002_module_level_bus_discipline(tmp_path):
    _fixture(tmp_path, "apex_tpu/bus.py", """\
        import threading

        _lock = threading.Lock()
        _subs = []

        def ok(cb):
            with _lock:
                _subs.append(cb)

        def bad(cb):
            _subs.append(cb)
        """)
    active, _ = _run(tmp_path, "APX002")
    assert len(active) == 1 and active[0].line == 11


def _schema_fixture(tmp_path):
    _fixture(tmp_path, "apex_tpu/monitor/goodput.py", """\
        STALL_EVENTS = {"checkpoint_save_stall": "checkpoint_save"}
        COUNTED_EVENTS = ("overflow_step_skipped",)
        INFO_EVENTS = ("span_open",)
        EVENT_SCHEMA = (frozenset(STALL_EVENTS) | frozenset(COUNTED_EVENTS)
                        | frozenset(INFO_EVENTS))
        """)


def test_apx003_fires_on_unregistered_event(tmp_path):
    _schema_fixture(tmp_path)
    _fixture(tmp_path, "apex_tpu/pub.py", """\
        from apex_tpu.utils.logging import publish_event, structured_warning

        def go():
            publish_event("overflow_step_skipped", steps=1)
            publish_event("totally_new_event", steps=1)
            structured_warning("another_rogue_event")
            publish_event(some_variable)  # non-literal: out of scope
        """)
    active, _ = _run(tmp_path, "APX003")
    assert len(active) == 2
    assert {"totally_new_event" in v.message or
            "another_rogue_event" in v.message for v in active} == {True}


def test_apx003_quiet_when_every_event_registered(tmp_path):
    _schema_fixture(tmp_path)
    _fixture(tmp_path, "apex_tpu/pub.py", """\
        from apex_tpu.utils.logging import publish_event

        def go():
            publish_event("overflow_step_skipped", steps=1)
            publish_event("span_open", emit=False)
            publish_event(event="checkpoint_save_stall", seconds=1.0)
        """)
    active, _ = _run(tmp_path, "APX003")
    assert not active, [v.format() for v in active]


def test_apx004_fires_on_torn_write_and_quiet_on_atomic(tmp_path):
    _fixture(tmp_path, "apex_tpu/bad_checkpoint.py", """\
        import numpy as np

        def save_checkpoint(path, arr):
            np.savez(path, arr=arr)
        """)
    active, _ = _run(tmp_path, "APX004")
    assert len(active) == 1 and "non-atomic" in active[0].message

    good = tmp_path / "apex_tpu" / "bad_checkpoint.py"
    good.write_text(textwrap.dedent("""\
        import numpy as np, os

        def save_checkpoint(path, arr):
            with open(path + '.tmp', 'wb') as f:
                np.savez(f, arr=arr)
            os.replace(path + '.tmp', path)
        """))
    active, _ = _run(tmp_path, "APX004")
    assert not active, [v.format() for v in active]


def test_apx005_fires_on_wall_clock_delta_and_ungated_print(tmp_path):
    _fixture(tmp_path, "apex_tpu/clocks.py", """\
        import time

        class T:
            def __init__(self):
                self._t0 = time.time()

            def elapsed(self):
                return time.time() - self._t0

        def announce():
            print("starting up")
        """)
    active, _ = _run(tmp_path, "APX005")
    assert len(active) == 2
    assert any("monotonic" in v.message for v in active)
    assert any("ungated print" in v.message for v in active)


def test_apx005_sees_annotated_wall_clock_stores(tmp_path):
    _fixture(tmp_path, "apex_tpu/annstore.py", """\
        import time

        class T:
            def __init__(self):
                self._t0: float = time.time()

            def elapsed(self):
                return time.monotonic() - self._t0
        """)
    active, _ = _run(tmp_path, "APX005")
    assert len(active) == 1 and "monotonic" in active[0].message


def test_apx005_quiet_on_monotonic_gated_and_cli_prints(tmp_path):
    _fixture(tmp_path, "apex_tpu/clocks.py", """\
        import time

        CREATED = time.time()   # wall-clock stamp, never subtracted: fine

        def elapsed(t0):
            return time.perf_counter() - t0

        def banner():
            from apex_tpu.utils.logging import is_rank_zero
            if is_rank_zero():
                print("one banner across the fleet")
        """)
    _fixture(tmp_path, "apex_tpu/cli.py", """\
        def main():
            print("a CLI's stdout is its interface")
        """)
    active, _ = _run(tmp_path, "APX005")
    assert not active, [v.format() for v in active]


def test_apx004_covers_serve_resilience_journal_writes(tmp_path):
    """PR-8 coverage proof: a tick-journal ``save`` in
    ``serve/resilience.py`` that skips the .tmp + os.replace discipline
    fires APX004 (the rule's save/dump function-name scope reaches the
    serve package), and the real atomic spelling stays quiet."""
    _fixture(tmp_path, "apex_tpu/serve/resilience.py", """\
        import json

        class TickJournal:
            def save(self, path):
                with open(path, "w") as f:
                    json.dump({"schema": 1}, f)
        """)
    active, _ = _run(tmp_path, "APX004")
    assert len(active) == 1 and "non-atomic" in active[0].message

    good = tmp_path / "apex_tpu" / "serve" / "resilience.py"
    good.write_text(textwrap.dedent("""\
        import json, os

        class TickJournal:
            def save(self, path):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"schema": 1}, f)
                os.replace(tmp, path)
        """))
    active, _ = _run(tmp_path, "APX004")
    assert not active, [v.format() for v in active]


def test_apx005_covers_deadline_sweep_clocks(tmp_path):
    """PR-8 coverage proof: a deadline sweep in ``serve/resilience.py``
    computed from ``time.time()`` deltas fires APX005 (an NTP step would
    expire every in-flight request at once); the monotonic spelling the
    real sweep uses stays quiet."""
    _fixture(tmp_path, "apex_tpu/serve/resilience.py", """\
        import time

        def sweep_deadlines(queue):
            now = time.time()
            return [r for r in queue
                    if (now - r.submit_t) * 1e3 > r.deadline_ms]
        """)
    active, _ = _run(tmp_path, "APX005")
    assert len(active) == 1 and "monotonic" in active[0].message

    good = tmp_path / "apex_tpu" / "serve" / "resilience.py"
    good.write_text(textwrap.dedent("""\
        import time

        def sweep_deadlines(queue):
            now = time.perf_counter()
            return [r for r in queue
                    if (now - r.submit_t) * 1e3 > r.deadline_ms]
        """))
    active, _ = _run(tmp_path, "APX005")
    assert not active, [v.format() for v in active]


def test_apx005_covers_fleet_heartbeat_deadline(tmp_path):
    """PR-11 coverage proof: a fleet heartbeat-miss check computed from
    ``time.time()`` deltas fires APX005 (an NTP step would declare every
    replica dead at once and trigger a fleet-wide failover storm); the
    monotonic spelling the real registry sweep uses stays quiet."""
    _fixture(tmp_path, "apex_tpu/serve/fleet.py", """\
        import time

        def sweep(rows, heartbeat_s, dead_misses):
            now = time.time()
            return [rid for rid, row in rows.items()
                    if (now - row["last_beat"]) / heartbeat_s
                    >= dead_misses]
        """)
    active, _ = _run(tmp_path, "APX005")
    assert len(active) == 1 and "monotonic" in active[0].message

    good = tmp_path / "apex_tpu" / "serve" / "fleet.py"
    good.write_text(textwrap.dedent("""\
        import time

        def sweep(rows, heartbeat_s, dead_misses):
            now = time.perf_counter()
            return [rid for rid, row in rows.items()
                    if (now - row["last_beat"]) / heartbeat_s
                    >= dead_misses]
        """))
    active, _ = _run(tmp_path, "APX005")
    assert not active, [v.format() for v in active]


def test_apx005_covers_fleet_journey_span_stamps(tmp_path):
    """PR-13 coverage proof: a fleet journey span whose failover window
    is computed from ``time.time()`` stamps fires APX005 (an NTP step
    would skew the span's ``seconds`` against the monotonic ledger cause
    and break the exact trace/summary reconciliation); the
    scheduler-clock spelling the real controller stamps spans with stays
    quiet."""
    _fixture(tmp_path, "apex_tpu/serve/fleet.py", """\
        import time

        def close_failover_span(span, attempt_t):
            now = time.time()
            span["seconds"] = now - attempt_t
            span["t1"] = now
            return span
        """)
    active, _ = _run(tmp_path, "APX005")
    assert len(active) == 1 and "monotonic" in active[0].message

    good = tmp_path / "apex_tpu" / "serve" / "fleet.py"
    good.write_text(textwrap.dedent("""\
        import time

        def close_failover_span(span, attempt_t):
            now = time.perf_counter()
            span["seconds"] = now - attempt_t
            span["t1"] = now
            return span
        """))
    active, _ = _run(tmp_path, "APX005")
    assert not active, [v.format() for v in active]


def test_apx002_covers_fleet_registry_heartbeat_thread(tmp_path):
    """PR-11 coverage proof: the replica registry is mutated from every
    replica's heartbeat thread — a lock-free read-modify-write of the
    rows fires APX002 (two threads beating at once would lose beats and
    fabricate a death); the real lock-disciplined spelling stays
    quiet."""
    _fixture(tmp_path, "apex_tpu/serve/fleet.py", """\
        import threading

        class ReplicaRegistry:
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = {}

            def register(self, rid):
                with self._lock:
                    self._rows[rid] = {"beats": 0}

            def heartbeat(self, rid, now):
                # called from the replica's heartbeat thread — lock-free
                self._rows[rid] = {"last_beat": now}
        """)
    active, _ = _run(tmp_path, "APX002")
    assert len(active) == 1
    assert "lock-free" in active[0].message

    good = tmp_path / "apex_tpu" / "serve" / "fleet.py"
    good.write_text(textwrap.dedent("""\
        import threading

        class ReplicaRegistry:
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = {}

            def register(self, rid):
                with self._lock:
                    self._rows[rid] = {"beats": 0}

            def heartbeat(self, rid, now):
                with self._lock:
                    self._rows[rid] = {"last_beat": now}
        """))
    active, _ = _run(tmp_path, "APX002")
    assert not active, [v.format() for v in active]


def test_apx002_covers_autoscaler_handoff_tables(tmp_path):
    """PR-16 coverage proof: the disaggregation controller's handoff
    table and the autoscaler's action state are control-thread-only BY
    DESIGN — they own no lock, so APX002 has nothing to say about the
    real module. But the tempting 'optimization' of letting each
    replica's worker thread commit its own handoffs needs a lock the
    moment it appears: a locked table mutated lock-free from the worker
    callback fires; the lock-disciplined spelling stays quiet."""
    _fixture(tmp_path, "apex_tpu/serve/disagg.py", """\
        import threading

        class HandoffTable:
            def __init__(self):
                self._lock = threading.Lock()
                self._handoffs = {}

            def begin(self, rid, ho):
                with self._lock:
                    self._handoffs[rid] = ho

            def on_clone_done(self, rid):
                # worker-thread callback — lock-free commit
                self._handoffs[rid] = "committed"
        """)
    active, _ = _run(tmp_path, "APX002")
    assert len(active) == 1
    assert "lock-free" in active[0].message

    good = tmp_path / "apex_tpu" / "serve" / "disagg.py"
    good.write_text(textwrap.dedent("""\
        import threading

        class HandoffTable:
            def __init__(self):
                self._lock = threading.Lock()
                self._handoffs = {}

            def begin(self, rid, ho):
                with self._lock:
                    self._handoffs[rid] = ho

            def on_clone_done(self, rid):
                with self._lock:
                    self._handoffs[rid] = "committed"
        """))
    active, _ = _run(tmp_path, "APX002")
    assert not active, [v.format() for v in active]


def test_apx002_covers_topology_reshard_table(tmp_path):
    """PR-19 coverage proof: the real reshard path is pure functions over
    numpy trees (no shared table, nothing for APX002 to say) — but the
    tempting bookkeeping of recording in-flight topology restores in a
    table the supervisor's control thread reads while rank threads
    append conversions needs a lock the moment it appears: a locked
    reshard table mutated lock-free from the restore path fires; the
    lock-disciplined spelling stays quiet."""
    _fixture(tmp_path, "apex_tpu/resilience/topology.py", """\
        import threading

        class ReshardTable:
            def __init__(self):
                self._lock = threading.Lock()
                self._inflight = {}

            def begin(self, step, src, dst):
                with self._lock:
                    self._inflight[step] = (src, dst)

            def on_restored(self, step):
                # rank restore thread — lock-free completion mark
                self._inflight[step] = "done"
        """)
    active, _ = _run(tmp_path, "APX002")
    assert len(active) == 1
    assert "lock-free" in active[0].message

    good = tmp_path / "apex_tpu" / "resilience" / "topology.py"
    good.write_text(textwrap.dedent("""\
        import threading

        class ReshardTable:
            def __init__(self):
                self._lock = threading.Lock()
                self._inflight = {}

            def begin(self, step, src, dst):
                with self._lock:
                    self._inflight[step] = (src, dst)

            def on_restored(self, step):
                with self._lock:
                    self._inflight[step] = "done"
        """))
    active, _ = _run(tmp_path, "APX002")
    assert not active, [v.format() for v in active]


def test_apx002_covers_quant_scale_table(tmp_path):
    """PR-20 coverage proof: the real quantized KV path keeps scales as
    DEVICE arrays in the cache pytree (no host table, nothing for
    APX002 to say) — but the tempting host-side mirror of per-page
    scale amax stats (for requant heuristics) mutated lock-free from
    the page-delivery callback needs a lock the moment it appears: two
    concurrent deliveries would lose updates and mis-scale a requant.
    The lock-disciplined spelling stays quiet."""
    _fixture(tmp_path, "apex_tpu/quant/scale_table.py", """\
        import threading

        class ScaleStatsTable:
            def __init__(self):
                self._lock = threading.Lock()
                self._amax = {}

            def register_page(self, page):
                with self._lock:
                    self._amax[page] = 0.0

            def on_page_delivered(self, page, amax):
                # delivery callback thread — lock-free mutation
                self._amax[page] = amax
        """)
    active, _ = _run(tmp_path, "APX002")
    assert len(active) == 1
    assert "lock-free" in active[0].message

    good = tmp_path / "apex_tpu" / "quant" / "scale_table.py"
    good.write_text(textwrap.dedent("""\
        import threading

        class ScaleStatsTable:
            def __init__(self):
                self._lock = threading.Lock()
                self._amax = {}

            def register_page(self, page):
                with self._lock:
                    self._amax[page] = 0.0

            def on_page_delivered(self, page, amax):
                with self._lock:
                    self._amax[page] = amax
        """))
    active, _ = _run(tmp_path, "APX002")
    assert not active, [v.format() for v in active]


def test_apx005_covers_train_preempt_drain_stamp(tmp_path):
    """PR-14 coverage proof: a trainer preemption drain whose
    ``train_preempt_drain`` seconds are computed from ``time.time()``
    deltas fires APX005 (an NTP step mid-drain would publish a skewed —
    possibly negative — stall into the goodput ledger); the monotonic
    spelling the real trainer stamps the drain with stays quiet."""
    _fixture(tmp_path, "apex_tpu/train/trainer.py", """\
        import time

        def drain(save, publish_event, step):
            t0 = time.time()
            save(step)
            publish_event("train_preempt_drain", step=step,
                          seconds=time.time() - t0)
        """)
    active, _ = _run(tmp_path, "APX005")
    assert len(active) == 1 and "monotonic" in active[0].message

    good = tmp_path / "apex_tpu" / "train" / "trainer.py"
    good.write_text(textwrap.dedent("""\
        import time

        def drain(save, publish_event, step):
            t0 = time.perf_counter()
            save(step)
            publish_event("train_preempt_drain", step=step,
                          seconds=time.perf_counter() - t0)
        """))
    active, _ = _run(tmp_path, "APX005")
    assert not active, [v.format() for v in active]


def test_apx002_covers_supervisor_progress_table(tmp_path):
    """PR-14 coverage proof: the train supervisor's progress table is
    written from every rank thread — a lock-free read-modify-write fires
    APX002 (two ranks reporting at once would lose updates and the
    control thread's status view would lie); the real lock-disciplined
    spelling stays quiet."""
    _fixture(tmp_path, "apex_tpu/train/supervisor.py", """\
        import threading

        class TrainSupervisor:
            def __init__(self):
                self._lock = threading.Lock()
                self._rank_status = {}

            def begin_attempt(self):
                with self._lock:
                    self._rank_status.clear()

            def report(self, rank, step):
                # called from every rank thread — lock-free
                self._rank_status[rank] = {"step": step}
        """)
    active, _ = _run(tmp_path, "APX002")
    assert len(active) == 1
    assert "lock-free" in active[0].message

    good = tmp_path / "apex_tpu" / "train" / "supervisor.py"
    good.write_text(textwrap.dedent("""\
        import threading

        class TrainSupervisor:
            def __init__(self):
                self._lock = threading.Lock()
                self._rank_status = {}

            def begin_attempt(self):
                with self._lock:
                    self._rank_status.clear()

            def report(self, rank, step):
                with self._lock:
                    self._rank_status[rank] = {"step": step}
        """))
    active, _ = _run(tmp_path, "APX002")
    assert not active, [v.format() for v in active]


# --------------------------------------------------- 3. suppressions

def test_justified_suppression_suppresses_and_is_counted(tmp_path):
    _fixture(tmp_path, "apex_tpu/sup.py", """\
        import time

        def elapsed(t0):
            return time.time() - t0  # apexlint: disable=APX005 -- comparing against a file mtime, which is wall clock
        """)
    active, suppressed = _run(tmp_path, "APX005")
    assert not active
    assert len(suppressed) == 1
    assert suppressed[0].justification.startswith("comparing against")

    # and the CLI JSON report carries the count
    r = subprocess.run([sys.executable, "-m", "tools.apexlint",
                        "--root", str(tmp_path), "--rules", "APX005",
                        "--format", "json", str(tmp_path / "apex_tpu")],
                       capture_output=True, text=True, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["ok"] is True
    assert doc["suppressed_counts"] == {"APX005": 1}
    assert doc["suppressed"][0]["justification"].startswith("comparing")


def test_unjustified_suppression_is_itself_a_violation(tmp_path):
    _fixture(tmp_path, "apex_tpu/sup.py", """\
        import time

        def elapsed(t0):
            return time.time() - t0  # apexlint: disable=APX005
        """)
    active, suppressed, _ = run_lint(root=str(tmp_path),
                                     paths=[str(tmp_path / "apex_tpu")])
    assert not suppressed
    rules = sorted(v.rule_id for v in active)
    # the original violation STANDS and the bare disable is flagged
    assert rules == ["APX000", "APX005"]
    assert "justification" in [v for v in active
                               if v.rule_id == "APX000"][0].message


def test_suppression_on_preceding_line_covers_long_statements(tmp_path):
    _fixture(tmp_path, "apex_tpu/sup.py", """\
        import time

        def elapsed(t0):
            # apexlint: disable=APX005 -- wall-clock comparison vs an externally stamped epoch
            return time.time() - t0
        """)
    active, suppressed = _run(tmp_path, "APX005")
    assert not active and len(suppressed) == 1


def test_cli_exit_one_on_seeded_violation_each_rule(tmp_path):
    """The acceptance contract: a seeded violation of each rule exits 1
    through the real CLI."""
    seeds = {
        "APX001": """\
            import jax

            @jax.jit
            def step(x):
                print("tracing", x)
                return x
            """,
        "APX002": """\
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def a(self):
                    with self._lock:
                        self.n += 1

                def b(self):
                    self.n += 1
            """,
        "APX003": None,  # needs the schema fixture, seeded below
        "APX004": """\
            import numpy as np

            def save_checkpoint(path, arr):
                np.savez(path, arr=arr)
            """,
        "APX005": """\
            import time

            def dur(t0):
                return time.time() - t0
            """,
    }
    for rule, src in seeds.items():
        seed_root = tmp_path / rule
        if rule == "APX003":
            _schema_fixture(seed_root)
            _fixture(seed_root, "apex_tpu/pub.py", """\
                from apex_tpu.utils.logging import publish_event

                def go():
                    publish_event("rogue_event")
                """)
        else:
            _fixture(seed_root, "apex_tpu/seed.py", src)
        r = subprocess.run(
            [sys.executable, "-m", "tools.apexlint", "--root",
             str(seed_root), "--rules", rule, str(seed_root / "apex_tpu")],
            capture_output=True, text=True, cwd=ROOT)
        assert r.returncode == 1, \
            f"{rule}: expected exit 1, got {r.returncode}\n{r.stdout}"
        assert rule in r.stdout


def test_unused_suppression_is_flagged_only_when_its_rule_ran(tmp_path):
    _fixture(tmp_path, "apex_tpu/stale.py", """\
        import time

        def now():
            return time.monotonic()  # apexlint: disable=APX005 -- was a time.time delta once, fixed since
        """)
    # APX005 ran and found nothing on that line → the opt-out is stale
    active, suppressed = run_lint(root=str(tmp_path),
                                  paths=[str(tmp_path / "apex_tpu")],
                                  only=["APX005"])[:2]
    assert not suppressed
    assert [v.rule_id for v in active] == ["APX000"]
    assert "unused suppression" in active[0].message
    # a subset run that did NOT include APX005 cannot judge it
    active, suppressed = run_lint(root=str(tmp_path),
                                  paths=[str(tmp_path / "apex_tpu")],
                                  only=["APX004"])[:2]
    assert not active and not suppressed


def test_nonexistent_path_is_a_usage_error_not_a_clean_pass():
    assert lint_main(["--root", ROOT, "no_such_dir_xyz"]) == 2


def test_path_outside_lint_root_is_a_usage_error(tmp_path):
    """A file outside --root has no repo-relative identity: path-scoped
    rules would silently skip it and the run would read clean while
    checking nothing."""
    outside = _fixture(tmp_path, "elsewhere/x.py", "import time\n")
    assert lint_main(["--root", ROOT, outside]) == 2


def test_unknown_rule_id_is_a_usage_error():
    assert lint_main(["--rules", "APX999", "--list-rules"]) == 2
