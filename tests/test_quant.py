"""Block-scale low-precision subsystem (PR 20) — ``apex_tpu.quant``.

Layers under test:

1. **Codec core** — the jax int8/mxfp8 block-scale codecs are
   BIT-EXACT against their pure-numpy fp32 references (codes AND
   scales), and the documented round-trip error bounds hold as tested
   properties across adversarial inputs (zeros, denormal-scale blocks,
   sign mixes, large magnitudes).
2. **Quantized matmul + MXNorm** — per-block weight scales with the
   tune-registry block key; both are TOLERANCE oracles against the
   fp32 computation on the dequantized operand (float association is
   the only difference — the bound is derived, not hand-waved).
3. **The quantized engine** — ``EngineConfig(kv_quant=...)`` holds the
   serving invariants: one decode trace under admit/evict/abort/
   prefix-hit churn, slot-vs-paged bit-exactness at equal block_k
   (quantization is deterministic, so the layouts still agree
   bit-for-bit), the >= 2x KV capacity win in ``kv_cache_bytes``, the
   perplexity delta vs the fp32 engine within ``QUANT_PPL_TOL``, and
   the loud build-time refusal matrix.
4. **Certified migration** — exported quantized pages carry scale
   planes under the SAME payload digest: a flipped scale byte in a
   streamed page is refused (reason "digest") with bit-exact local
   re-prefill, and a codec mismatch between replicas refuses with
   reason "quant_codec" + a counted ``serve_quant_fallback`` event.
5. **The gate + CLIs** — ``resident_tokens_per_hbm_byte`` (higher) and
   ``quant_ppl_delta`` (lower) gate direction-aware on a REAL bench
   capture, quantized captures refuse to gate against fp32 baselines
   (``kv_quant``/``quant_block`` incomparable axes), and both CLIs
   refuse the incompatible flag combinations with clean usage errors.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.quant import (decode_int8, decode_int8_ref, decode_kv,
                            decode_mxfp8, decode_mxfp8_ref, encode_int8,
                            encode_int8_ref, encode_kv, encode_mxfp8,
                            encode_mxfp8_ref, has_float8, check_kv_codec,
                            int8_error_bound, kv_storage_dtype,
                            mx_layer_norm, mxfp8_error_bound,
                            quant_matmul, quantize_weight,
                            resolve_quant_block)
from apex_tpu.resilience.fault_injection import FaultInjector
from apex_tpu.serve.disagg import DisaggController
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.fleet import EngineReplica
from apex_tpu.serve.kv_cache import init_cache, write_token
from apex_tpu.serve.scheduler import Request, ServeScheduler
# bound at collection time: test_chip_worker purges apex_tpu.* from
# sys.modules mid-session (see test_serve for the history)
from apex_tpu.utils.logging import subscribe_events

pytestmark = pytest.mark.serve

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The documented quality tolerance (docs/quantization.md): mean-NLL
# delta of a quantized engine vs its fp32 reference on a forced
# continuation. Measured headroom on this geometry is ~75x (int8
# ~2e-4, mxfp8 ~7e-4 nats).
QUANT_PPL_TOL = 0.05

CFG = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=2,
                 n_head=2, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_gpt2_params(CFG, seed=0)


def _engine(params, **kw):
    kw.setdefault("num_slots", 3)
    kw.setdefault("max_len", 32)
    kw.setdefault("temperature", 0.0)
    return Engine(CFG, params, EngineConfig(**kw), seed=0)


def _tokens(n, seed=7, vocab=97):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, vocab, n)]


def _cases(seed=0):
    """Adversarial codec inputs: zero blocks, mixed signs, tiny and
    huge magnitudes, non-trivial leading shapes."""
    rng = np.random.RandomState(seed)
    return [
        (np.zeros((3, 16), np.float32), 4),
        (rng.randn(5, 8).astype(np.float32), 8),
        (rng.randn(2, 3, 32).astype(np.float32) * 1e4, 16),
        (rng.randn(4, 16).astype(np.float32) * 1e-6, 4),
        (np.where(rng.rand(6, 24) > 0.5, 0.0,
                  rng.randn(6, 24)).astype(np.float32), 8),
    ]


# ------------------------------------------------------- 1. codec core

def test_int8_codec_bit_exact_vs_numpy_reference():
    for x, block in _cases():
        codes, scales = encode_int8(jnp.asarray(x), block)
        rcodes, rscales = encode_int8_ref(x, block)
        np.testing.assert_array_equal(np.asarray(codes), rcodes)
        np.testing.assert_array_equal(np.asarray(scales), rscales)
        got = np.asarray(decode_int8(codes, scales, block))
        np.testing.assert_array_equal(got,
                                      decode_int8_ref(rcodes, rscales,
                                                      block))


def test_int8_round_trip_error_bound_property():
    for x, block in _cases(seed=3):
        codes, scales = encode_int8(jnp.asarray(x), block)
        rt = np.asarray(decode_int8(codes, scales, block))
        bound = int8_error_bound(np.asarray(scales), block, x.shape)
        err = np.abs(rt - x)
        assert (err <= bound).all(), \
            f"int8 bound violated: max err {err.max()} vs {bound.max()}"
    # zero blocks decode exactly (scale 1.0, codes 0)
    z, s = encode_int8(jnp.zeros((2, 8)), 4)
    assert np.asarray(s).min() == 1.0
    np.testing.assert_array_equal(
        np.asarray(decode_int8(z, s, 4)), np.zeros((2, 8), np.float32))


@pytest.mark.skipif(not has_float8(), reason="no float8_e4m3fn")
def test_mxfp8_codec_vs_numpy_reference():
    """Scales BIT-EXACT vs the numpy reference; payloads within ONE
    e4m3 grid step (XLA's compiled f32->f8 convert double-rounds
    through an intermediate precision on near-tie values — see the
    blockscale docstring; the round-trip bound below holds either
    way, and that bound is what the quality gate rides on)."""
    for x, block in _cases(seed=5):
        codes, scales = encode_mxfp8(jnp.asarray(x), block)
        rcodes, rscales = encode_mxfp8_ref(x, block)
        np.testing.assert_array_equal(np.asarray(scales), rscales)
        a = np.asarray(codes).astype(np.float32)
        b = rcodes.astype(np.float32)
        mag = np.maximum(np.abs(b), np.float32(2.0 ** -6))
        ulp = np.maximum(np.exp2(np.floor(np.log2(mag)) - 3),
                         np.float32(2.0 ** -9))
        assert (np.abs(a - b) <= ulp).all(), \
            f"mxfp8 payload drifted past one grid step: " \
            f"{np.abs(a - b).max()}"
        got = np.asarray(decode_mxfp8(codes, scales, block))
        ref = decode_mxfp8_ref(rcodes, rscales, block)
        sb = np.repeat(rscales, block, axis=-1).reshape(x.shape)
        assert (np.abs(got - ref) <= ulp * sb).all()


@pytest.mark.skipif(not has_float8(), reason="no float8_e4m3fn")
def test_mxfp8_error_bound_and_power_of_two_scales():
    for x, block in _cases(seed=9):
        codes, scales = encode_mxfp8(jnp.asarray(x), block)
        s = np.asarray(scales)
        # shared-exponent contract: every scale is an EXACT power of
        # two — frexp mantissa 0.5, not a log2-looks-integral check
        # (which f32 precision passes even for the ulp-off exp2 values
        # the ldexp fix removed)
        assert (np.frexp(s)[0] == 0.5).all()
        # no-inf contract: e4m3fn overflow would be NaN — never emitted
        payload = np.asarray(codes).astype(np.float32)
        assert np.isfinite(payload).all()
        rt = np.asarray(decode_mxfp8(codes, scales, block))
        bound = mxfp8_error_bound(x, s, block)
        err = np.abs(rt - x)
        assert (err <= bound).all(), \
            f"mxfp8 bound violated: max err {err.max()}"


def test_codec_block_validation():
    x = jnp.ones((2, 12))
    for bad in (0, -4, 5, 24):
        with pytest.raises(ValueError, match="quant block"):
            encode_int8(x, bad)


def test_kv_codec_glue_and_refusals():
    assert check_kv_codec(None) is None
    assert kv_storage_dtype(None) is None
    assert kv_storage_dtype("int8") == jnp.int8
    with pytest.raises(ValueError, match="unknown kv_quant codec"):
        check_kv_codec("int4")
    x = jnp.asarray(np.random.RandomState(0).randn(5, 2, 16),
                    jnp.float32)
    codes, scales = encode_kv("int8", x)
    # one scale per (token, head): payload shape minus head_dim
    assert codes.shape == x.shape and scales.shape == x.shape[:-1]
    rt = np.asarray(decode_kv(codes, scales))
    bound = int8_error_bound(np.asarray(scales)[..., None], 16, x.shape)
    assert (np.abs(rt - np.asarray(x)) <= bound).all()


# ------------------------------------------- 2. quant matmul + MXNorm

def test_quant_matmul_within_derived_bound():
    """Tolerance oracle: the quantization error of w is bounded
    elementwise by the codec bound, so |x @ w - quant_matmul| <=
    |x| @ bound — a derived bound, not an eyeballed rtol."""
    rng = np.random.RandomState(11)
    x = rng.randn(5, 32).astype(np.float32)
    w = rng.randn(32, 24).astype(np.float32)
    block = resolve_quant_block(32, 24)
    assert block == 32                   # largest pow2 divisor <= 128
    codes, scales = quantize_weight(jnp.asarray(w), block)
    assert codes.shape == (32, 24) and scales.shape == (1, 24)
    y = np.asarray(quant_matmul(jnp.asarray(x), codes, scales, block))
    ref = x @ w
    wb = int8_error_bound(np.asarray(scales).T, block,
                          (24, 32)).T      # elementwise |w - dq(w)| bound
    slack = np.abs(x) @ wb + 1e-4
    assert (np.abs(y - ref) <= slack).all(), \
        f"quant_matmul drifted past the derived bound: " \
        f"{np.abs(y - ref).max()} vs {slack.min()}"


def test_resolve_quant_block_matrix():
    assert resolve_quant_block(256, 64) == 128   # capped at 128
    assert resolve_quant_block(96, 7) == 32      # pow2 divisor of 96
    assert resolve_quant_block(64, 64, block=16) == 16
    with pytest.raises(ValueError, match="does not divide"):
        resolve_quant_block(64, 64, block=24)


def test_mx_layer_norm_matches_dequant_reference():
    """MXNorm's scale-reusing moments vs manual_layer_norm on the
    dequantized vector: float association is the only difference."""
    from apex_tpu.normalization.fused_layer_norm import manual_layer_norm

    rng = np.random.RandomState(13)
    x = (rng.randn(4, 64) * 3).astype(np.float32)
    block = 16
    codes, scales = encode_int8(jnp.asarray(x), block)
    w = jnp.asarray(rng.randn(64).astype(np.float32))
    b = jnp.asarray(rng.randn(64).astype(np.float32))
    got = np.asarray(mx_layer_norm(codes, scales, w, b, block))
    dq = decode_int8(codes, scales, block)
    ref = np.asarray(manual_layer_norm(dq, w, b, (64,), 1e-5))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    # weight/bias-free form too
    got0 = np.asarray(mx_layer_norm(codes, scales, None, None, block))
    ref0 = np.asarray(manual_layer_norm(dq, None, None, (64,), 1e-5))
    np.testing.assert_allclose(got0, ref0, rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="does not divide"):
        mx_layer_norm(codes, scales, None, None, 24)


# -------------------------------------------- 3. the quantized engine

def test_quant_cache_write_is_masked_and_bounded():
    """kv_cache surgery unit: a quantized write stores codec bytes +
    scales under the SAME mask discipline — masked-off slots' payload
    AND scale bytes stay bit-untouched."""
    cache = init_cache(n_layer=1, num_slots=4, max_len=8, heads=2,
                       head_dim=16, kv_quant="int8")
    assert cache.k.dtype == jnp.int8
    assert cache.k_scale.shape == (1, 4, 8, 2)
    x = np.random.RandomState(0).randn(4, 2, 16).astype(np.float32)
    pos = jnp.zeros((4,), jnp.int32)
    mask = jnp.array([True, False, True, False])
    out = jax.jit(write_token,
                  static_argnums=(1, 6))(cache, 0, jnp.asarray(x),
                                         jnp.asarray(x), pos, mask,
                                         "int8")
    got = np.asarray(out.k[0, 0, 0]).astype(np.float32) \
        * np.asarray(out.k_scale[0, 0, 0])[..., None]
    bound = int8_error_bound(np.asarray(out.k_scale[0, 0, 0])[..., None],
                             16, x[0].shape)
    assert (np.abs(got - x[0]) <= bound).all()
    np.testing.assert_array_equal(np.asarray(out.k[0, 1]),
                                  np.asarray(cache.k[0, 1]))
    np.testing.assert_array_equal(np.asarray(out.k_scale[0, 1]),
                                  np.asarray(cache.k_scale[0, 1]))


def _mixed_requests(n=5, seed0=0, max_new=5):
    return [Request(request_id=f"r{i}",
                    tokens=_tokens(4 + 3 * (i % 4), seed=seed0 + i),
                    max_new_tokens=max_new) for i in range(n)]


def _trace_outputs(eng, reqs, injector=None):
    sched = ServeScheduler(eng, fault_injector=injector)
    for r in reqs:
        sched.submit(r)
    return {r["request_id"]: r for r in sched.run().requests}


@pytest.mark.parametrize("codec", ["int8", "mxfp8"])
def test_quant_decode_compiles_once_across_churn(params, codec):
    """THE one-compile acceptance with kv_quant armed: scales are DATA
    in the cache pytree, so admissions, completions, a scripted abort,
    backfill, and prefix-hit page churn trace decode_step exactly once
    — for BOTH codecs on the paged layout."""
    if codec == "mxfp8" and not has_float8():
        pytest.skip("no float8_e4m3fn")
    eng = _engine(params, num_slots=2, page_size=8, prefix_cache=True,
                  kv_quant=codec)
    inj = FaultInjector(seed=0).abort_request("r2", at_step=4)
    sched = ServeScheduler(eng, fault_injector=inj)
    for i, plen in enumerate((4, 6, 5, 3, 7)):
        sched.submit(Request(request_id=f"r{i}",
                             tokens=_tokens(plen, seed=i),
                             max_new_tokens=4 + i % 3))
    stats = sched.run()
    assert len(stats.requests) == 5
    assert {r["state"] for r in stats.requests} == {"completed",
                                                    "evicted"}
    assert eng.decode_traces == 1, \
        "quantized page/scale churn must not retrace decode_step"
    assert eng.prefill_traces <= 2          # pow2 buckets {4, 8}


def test_quant_paged_bit_exact_vs_quant_slot(params):
    """Encode is deterministic and per-(token, head), so the slot and
    paged layouts still agree BIT-FOR-BIT at equal block_k with
    kv_quant armed — the fp32 layout-parity guarantee survives
    quantization unchanged."""
    slot = _engine(params, block_k=8, kv_quant="int8")
    paged = _engine(params, page_size=8, kv_quant="int8")
    assert slot.block_k == paged.block_k == 8
    base = _trace_outputs(slot, _mixed_requests())
    got = _trace_outputs(paged, _mixed_requests())
    assert {k: v["generated"] for k, v in got.items()} == \
           {k: v["generated"] for k, v in base.items()}
    assert slot.decode_traces == 1 and paged.decode_traces == 1


def test_quant_ppl_delta_within_documented_tolerance(params):
    """Quality gate: mean NLL of a forced continuation under the
    quantized engine stays within QUANT_PPL_TOL nats of the fp32
    engine (the exact reference by construction)."""
    seq = _tokens(24, seed=7)

    def mean_nll(kv_quant):
        eng = _engine(params, keep_prefill_logits=True,
                      kv_quant=kv_quant)
        _, _, logits = eng.prefill({1: seq})
        lg = np.asarray(logits)[:, 1, :].astype(np.float64)
        m = lg.max(-1, keepdims=True)
        lp = lg - m - np.log(np.exp(lg - m).sum(-1, keepdims=True))
        tgt = np.array(seq[1:])
        return float(-lp[np.arange(len(tgt)), tgt].mean())

    ref = mean_nll(None)
    codecs = ["int8"] + (["mxfp8"] if has_float8() else [])
    for codec in codecs:
        delta = abs(mean_nll(codec) - ref)
        assert delta <= QUANT_PPL_TOL, \
            f"{codec} ppl delta {delta} exceeds {QUANT_PPL_TOL}"


def test_quant_kv_capacity_at_least_2x(params):
    """THE capacity acceptance: same geometry, >= 2x fewer KV-cache
    HBM bytes (int8 payload + one fp32 scale per (token, head) vs fp32
    payload). At head_dim=16 the exact ratio is 64/(16+4) = 3.2."""
    fp32 = _engine(params, page_size=8)
    for codec in ("int8",) + (("mxfp8",) if has_float8() else ()):
        q = _engine(params, page_size=8, kv_quant=codec)
        ratio = fp32.kv_cache_bytes / q.kv_cache_bytes
        assert ratio >= 2.0, \
            f"{codec} capacity win {ratio:.2f}x below the 2x floor"
        assert ratio == pytest.approx(3.2)
        assert q.quant_block == 16          # = head_dim, by construction
    assert fp32.quant_block == 0


def test_quant_engine_refusal_matrix(params):
    with pytest.raises(ValueError, match="unknown kv_quant codec"):
        _engine(params, kv_quant="int4")
    with pytest.raises(ValueError, match="requires compute_dtype"):
        bf = GPT2Config(vocab_size=97, n_positions=64, n_embd=32,
                        n_layer=2, n_head=2,
                        compute_dtype=jnp.bfloat16)
        Engine(bf, init_gpt2_params(bf, seed=0),
               EngineConfig(num_slots=2, max_len=32, temperature=0.0,
                            kv_quant="int8"), seed=0)
    with pytest.raises(ValueError, match="incompatible with"):
        _engine(params, kv_quant="int8", spec_draft_len=2)


@pytest.mark.slow
def test_quant_tp2_bit_exact_vs_single_chip(params, tp_devices):
    """Sharding acceptance: per-(token, head) encode is rank-local (no
    cross-head reduction), so a tp=2 quantized engine's greedy stream
    is bit-identical to the single-chip quantized engine at equal
    block_k — scales shard with their pages on the head axis by
    construction."""
    base = _trace_outputs(_engine(params, num_slots=2, kv_quant="int8"),
                          _mixed_requests(n=3))
    got = _trace_outputs(
        _engine(params, num_slots=2, tp=2, kv_quant="int8"),
        _mixed_requests(n=3))
    assert {k: v["generated"] for k, v in got.items()} == \
           {k: v["generated"] for k, v in base.items()}


# ------------------------------------------- 4. certified migration

DCFG = GPT2Config(vocab_size=61, n_positions=32, n_embd=16, n_layer=1,
                  n_head=2, compute_dtype=jnp.float32)
DPAGE = 4


@pytest.fixture(scope="module")
def dparams():
    return init_gpt2_params(DCFG, seed=0)


def _dengine(dparams, **kw):
    kw.setdefault("kv_quant", "int8")
    return Engine(DCFG, dparams,
                  EngineConfig(num_slots=2, max_len=32, temperature=0.0,
                               page_size=DPAGE, num_pages=24,
                               prefix_cache=True, **kw),
                  seed=0).aot_compile([4, 8])


@pytest.fixture(scope="module")
def qengines(dparams):
    """Three int8-quantized paged engines sharing one param pytree:
    prefill + decode + oracle; tests reset()."""
    return [_dengine(dparams) for _ in range(3)]


@pytest.fixture(scope="module")
def fengines(dparams):
    """Two fp32 engines on the same params: the codec-mismatch target
    and its oracle."""
    return [_dengine(dparams, kv_quant=None) for _ in range(2)]


def _dtokens(n, seed=7):
    return _tokens(n, seed=seed, vocab=61)


def _oracle(engine, req):
    sched = ServeScheduler(engine.reset())
    sched.submit(Request(request_id=req.request_id,
                         tokens=list(req.tokens),
                         max_new_tokens=req.max_new_tokens))
    sched.run(max_steps=2_000)
    done, _ = sched.done_since(0)
    rec, = [q.record() for q in done]
    return rec["generated"]


def test_quant_export_import_round_trip(qengines):
    """Quantized pages stream with their scale planes and install into
    a same-codec pool: prefix hits on the receiver, no retrace,
    bit-exact output; a codec-mismatched import is a loud refusal at
    the structural door (the certifying caller refuses earlier)."""
    prompt = _dtokens(8, seed=3)
    a, b = qengines[0].reset(), qengines[1].reset()
    sa = ServeScheduler(a)
    sa.submit(Request(request_id="seed", tokens=list(prompt),
                      max_new_tokens=1))
    sa.run(max_steps=50)
    payloads = sa.export_prefix_pages(list(prompt))
    assert len(payloads) == 2
    for p in payloads:
        assert p["codec"] == "int8"
        assert p["k"].dtype == np.int8
        assert p["k_scale"].dtype == np.float32
        assert set(p) >= {"chain_hash", "k", "v", "k_scale", "v_scale",
                          "digest"}

    sb = ServeScheduler(b)
    first = sb.import_prefix_pages(payloads)
    assert first["installed"] == 2
    traces = b.decode_traces
    sb.submit(Request(request_id="real", tokens=list(prompt),
                      max_new_tokens=4))
    sb.run(max_steps=50)
    done, _ = sb.done_since(0)
    rec, = [q.record() for q in done]
    assert sb.prefix_hits >= 1 and b.decode_traces == traces
    assert rec["generated"] == _oracle(
        qengines[2], Request(request_id="real", tokens=list(prompt),
                             max_new_tokens=4))
    # structural door: a fp32 payload must never install into an int8
    # pool (the bytes would be misread)
    bad = [dict(p, codec=None) for p in payloads]
    with pytest.raises(ValueError, match="codec"):
        sb.import_prefix_pages(bad)


def test_quant_flipped_scale_byte_refused_bit_exact_fallback(qengines):
    """ISSUE 20 acceptance: the payload digest certifies codes ‖ scales
    TOGETHER — one flipped byte in an in-flight k_scale plane (payload
    bytes pristine) is refused exactly like a payload flip (reason
    "digest", nothing installs) and the request completes bit-exactly
    via local re-prefill on the quantized decode replica."""
    req = Request(request_id="c0", tokens=_dtokens(8, seed=11),
                  max_new_tokens=4)
    oracle = _oracle(qengines[2], req)

    handles = [
        EngineReplica("p0", qengines[0].reset(), role="prefill"),
        EngineReplica("d0", qengines[1].reset(), role="decode"),
    ]
    src = handles[0].scheduler
    orig_export = src.export_prefix_pages

    def corrupt_scale_export(tokens):
        payloads = orig_export(tokens)
        if payloads:                   # flip AFTER the digest is stamped
            ks = np.array(payloads[0]["k_scale"], copy=True)
            raw = bytearray(ks.tobytes())
            raw[0] ^= 0x01
            payloads[0]["k_scale"] = np.frombuffer(
                bytes(raw), dtype=ks.dtype).reshape(ks.shape)
        return payloads

    src.export_prefix_pages = corrupt_scale_export
    fleet = DisaggController(handles, heartbeat_ms=25,
                             suspect_misses=5_000, dead_misses=10_000)
    refusals = []
    unsub = subscribe_events(
        lambda r: refusals.append(r)
        if r.get("event") == "serve_handoff_refused" else None)
    try:
        fleet.submit(Request(request_id="c0", tokens=list(req.tokens),
                             max_new_tokens=4))
        stats = fleet.run(max_wall_s=30)
    finally:
        unsub()
        del src.export_prefix_pages

    rec, = stats.requests
    assert rec["state"] == "completed"
    assert rec["generated"] == oracle, \
        "scale-flip fallback drifted from the quantized oracle"
    assert stats.handoffs_refused == 1 and stats.pages_migrated == 0
    assert len(refusals) == 1
    assert refusals[0]["reason"] == "digest"
    assert refusals[0]["page_index"] == 0


def test_quant_codec_mismatch_refused_with_fallback_event(qengines,
                                                          fengines):
    """A quantized prefill replica handing off to an fp32 decode
    replica: bytes are pristine but the pools are incomparable — the
    chain refuses with reason "quant_codec", the counted
    ``serve_quant_fallback`` event fires once, and the request
    completes bit-exactly under the TARGET's own codec."""
    req = Request(request_id="m0", tokens=_dtokens(8, seed=17),
                  max_new_tokens=4)
    oracle = _oracle(fengines[1], req)     # fp32: the target's codec

    fleet = DisaggController(
        [EngineReplica("p0", qengines[0].reset(), role="prefill"),
         EngineReplica("d0", fengines[0].reset(), role="decode")],
        heartbeat_ms=25, suspect_misses=5_000, dead_misses=10_000)
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r)
        if r.get("event") in ("serve_handoff_refused",
                              "serve_quant_fallback") else None)
    try:
        fleet.submit(Request(request_id="m0", tokens=list(req.tokens),
                             max_new_tokens=4))
        stats = fleet.run(max_wall_s=30)
    finally:
        unsub()

    rec, = stats.requests
    assert rec["state"] == "completed"
    assert rec["generated"] == oracle
    assert stats.handoffs_refused == 1 and stats.pages_migrated == 0
    by_event = {r["event"]: r for r in seen}
    assert by_event["serve_handoff_refused"]["reason"] == "quant_codec"
    fb = by_event["serve_quant_fallback"]
    assert fb["source_codec"] == "int8" and fb["target_codec"] is None


def test_quant_pages_event_counted(qengines):
    """Satellite: ``serve_kv_quantized_pages`` is published (and
    COUNTED) when a quantized prefill allocates pages."""
    from apex_tpu.monitor.goodput import COUNTED_EVENTS
    assert "serve_kv_quantized_pages" in COUNTED_EVENTS
    assert "serve_quant_fallback" in COUNTED_EVENTS
    eng = qengines[0].reset()
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r)
        if r.get("event") == "serve_kv_quantized_pages" else None)
    try:
        _trace_outputs(eng, [Request(request_id="q0",
                                     tokens=_dtokens(8, seed=1),
                                     max_new_tokens=2)])
    finally:
        unsub()
    assert seen and seen[0]["codec"] == "int8"
    assert seen[0]["pages"] >= 2


# ------------------------------------------------ 5. the gate + CLIs

def _check_regression():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_regression
    finally:
        sys.path.pop(0)
    return check_regression


def test_gate_directions_for_quant_metrics():
    cr = _check_regression()
    assert not cr.lower_is_better("resident_tokens_per_hbm_byte")
    assert cr.lower_is_better("quant_ppl_delta")
    assert cr.lower_is_better("serve_quant_fallback_total")
    for k in ("kv_quant", "quant_block"):
        assert k in cr.INCOMPARABLE_WORKLOAD_KEYS


def test_quant_bench_capture_and_real_gate_run(tmp_path, capsys):
    """Satellite acceptance, on a REAL quantized bench capture: the
    workload stamps ``kv_quant``/``quant_block`` provenance, the
    capacity metric gates higher-is-better, an injected
    ``quant_ppl_delta`` gates lower-is-better, and a baseline whose
    workload says fp32 is REFUSED (exit 2), never silently compared."""
    from apex_tpu.bench_cli import _serve_bench

    _serve_bench(steps=6, num_slots=2, kv_quant="int8")
    suite = json.loads(capsys.readouterr().out)
    entry = suite["serve_decode"]
    assert entry["workload"]["kv_quant"] == "int8"
    assert entry["workload"]["quant_block"] > 0
    assert entry["resident_tokens_per_hbm_byte"] > 0
    # stamp the quality metric the offline eval writes into captures
    entry["quant_ppl_delta"] = 0.001

    cr = _check_regression()
    cur, base = tmp_path / "cur.json", tmp_path / "base.json"
    cur.write_text(json.dumps(suite))
    base.write_text(json.dumps(suite))
    args = ["--suite", str(base), "--kernels", "serve_decode"]
    assert cr.main([str(cur)] + args) == 0
    # capacity drop regresses (higher-is-better)...
    worse = json.loads(json.dumps(suite))
    worse["serve_decode"]["resident_tokens_per_hbm_byte"] = \
        entry["resident_tokens_per_hbm_byte"] * 0.4
    cur.write_text(json.dumps(worse))
    assert cr.main([str(cur)] + args) == 1
    # ...quality erosion regresses (lower-is-better)...
    worse = json.loads(json.dumps(suite))
    worse["serve_decode"]["quant_ppl_delta"] = 0.02
    cur.write_text(json.dumps(worse))
    assert cr.main([str(cur)] + args) == 1
    # ...and an fp32 baseline is incomparable, not compared
    cur.write_text(json.dumps(suite))
    fp32 = json.loads(json.dumps(suite))
    fp32["serve_decode"]["workload"]["kv_quant"] = None
    fp32["serve_decode"]["workload"]["quant_block"] = 0
    base.write_text(json.dumps(fp32))
    assert cr.main([str(cur)] + args) == 2


@pytest.mark.slow
def test_quant_bench_capacity_vs_fp32_capture(capsys):
    """The headline capacity claim on real captures: same workload,
    quantized pool holds >= 2x the resident tokens per KV HBM byte."""
    from apex_tpu.bench_cli import _serve_bench

    kw = dict(steps=8, num_slots=2, max_len=64, prompt_len="8:16",
              page_size=8, num_pages=17, prefix_cache=True)
    _serve_bench(**kw)
    fp32 = json.loads(capsys.readouterr().out)["serve_decode"]
    _serve_bench(**kw, kv_quant="int8")
    quant = json.loads(capsys.readouterr().out)["serve_decode"]
    assert quant["resident_tokens_per_hbm_byte"] >= \
        2.0 * fp32["resident_tokens_per_hbm_byte"], \
        "quantized KV must multiply resident-token capacity per byte"
    assert quant["workload"]["kv_quant"] == "int8"
    assert fp32["workload"]["kv_quant"] is None


def test_serve_cli_kv_quant_matrix(capsys):
    from apex_tpu.serve.cli import main

    for argv, msg in [
            (["--kv-quant", "int8", "--dtype", "bf16"],
             "needs --dtype fp32"),
            (["--kv-quant", "mxfp8", "--spec-draft-len", "2"],
             "incompatible with --spec-draft-len"),
    ]:
        assert main(argv) == 2, argv
        assert msg in capsys.readouterr().err, argv


def test_bench_cli_kv_quant_matrix(monkeypatch):
    from apex_tpu.bench_cli import _serve_bench
    from apex_tpu.bench_cli import main as bench_main

    with pytest.raises(SystemExit, match="unknown kv_quant codec"):
        _serve_bench(steps=1, kv_quant="int4")
    with pytest.raises(SystemExit, match="incompatible"):
        _serve_bench(steps=1, kv_quant="int8", spec_draft_len=2)
    # --kv-quant without --serve: the serve-only matrix exits 2
    monkeypatch.setattr(sys, "argv",
                        ["apex-tpu-bench", "--kv-quant", "int8"])
    with pytest.raises(SystemExit) as ei:
        bench_main()
    assert ei.value.code == 2
