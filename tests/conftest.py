"""Test harness: run everything on a virtual 8-device CPU mesh.

TPU translation of the reference's multi-process harness
(``apex/distributed_testing/distributed_test_base.py:24-83`` spawns one process
per GPU); here multi-device = 8 virtual CPU devices via XLA_FLAGS, with Pallas
kernels in interpret mode (SURVEY §4 "TPU translation").

Note: the dev image pre-imports jax via a sitecustomize hook with the platform
pinned to the TPU tunnel, so env vars are too late here — we must switch the
platform through jax.config before any backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# pytest's env is already sanitized (CPU forced below), so dryrun_multichip
# may run in-process instead of paying a cold subprocess per call.
os.environ["_APEX_TPU_DRYRUN_INPROC"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


import pytest  # noqa: E402


def pytest_configure(config):
    # fault-injection suite (tests/test_resilience.py): deterministic,
    # CPU-only, fast — runs in tier-1; select alone with `-m fault`
    config.addinivalue_line(
        "markers",
        "fault: deterministic fault-injection resilience tests "
        "(fast, CPU-only, tier-1)")


@pytest.fixture(scope="session")
def tp_devices():
    """The multi-device CPU guarantee for sharded (tensor-parallel)
    tier-1: the early-env XLA_FLAGS hook at the top of this file — set
    BEFORE jax's backend initializes, the ``ThreadProcessGroup``
    fake-multihost precedent — forces an 8-device CPU host, so a
    ``tp=2`` serving mesh is always buildable and sharded tests never
    depend on real chips. Session-scoped and ASSERTING (not skipping):
    if the device pool ever shrinks below 2, the tensor-parallel
    acceptance suite must fail loudly, not silently vanish from tier-1.
    Returns the first two devices (the tp=2 mesh pool)."""
    devs = jax.devices()
    assert len(devs) >= 2, (
        f"the conftest xla_force_host_platform_device_count hook must "
        f"provide >= 2 CPU devices for the tp=2 mesh, got {len(devs)} — "
        f"was XLA initialized before this conftest imported?")
    return devs[:2]
