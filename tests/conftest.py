"""Test harness: run everything on a virtual 8-device CPU mesh.

TPU translation of the reference's multi-process harness
(``apex/distributed_testing/distributed_test_base.py:24-83`` spawns one process
per GPU); here multi-device = 8 virtual CPU devices via XLA_FLAGS, with Pallas
kernels in interpret mode (SURVEY §4 "TPU translation").

Note: the dev image pre-imports jax via a sitecustomize hook with the platform
pinned to the TPU tunnel, so env vars are too late here — we must switch the
platform through jax.config before any backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# pytest's env is already sanitized (CPU forced below), so dryrun_multichip
# may run in-process instead of paying a cold subprocess per call.
os.environ["_APEX_TPU_DRYRUN_INPROC"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


def pytest_configure(config):
    # fault-injection suite (tests/test_resilience.py): deterministic,
    # CPU-only, fast — runs in tier-1; select alone with `-m fault`
    config.addinivalue_line(
        "markers",
        "fault: deterministic fault-injection resilience tests "
        "(fast, CPU-only, tier-1)")
