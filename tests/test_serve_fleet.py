"""Serving fleet tier-1: replica health, failover re-dispatch, hedged
requests, rolling drain, and the fleet chaos invariant.

THE invariant under test (ISSUE 11 acceptance): under a seeded
kill + partition + straggler schedule across >= 3 thread-backed
replicas, **every submitted request reaches exactly one terminal status
fleet-wide**, completed greedy outputs are bit-identical to the
no-fault fleet (routing and failover never change greedy content — the
replicas share params and the PR-5 prefill/decode invariant), and no
surviving replica recompiles (``decode_traces`` delta 0).

Engines are compiled once per module and shared across tests via
``Engine.reset()``; trace-counter assertions use before/after deltas.
The fleet model: a *crashed* replica's unharvested results died with
its memory; a *partitioned* replica keeps decoding but nothing crosses
to the router until the partition heals — and then its duplicates must
lose the first-terminal-wins race, never double-complete.
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.monitor.goodput import GoodputLedger
from apex_tpu.monitor.slo import SLObjective, SLOTracker
from apex_tpu.resilience.fault_injection import FaultInjector
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.fleet import (REPLICA_DEAD, REPLICA_HEALTHY,
                                  REPLICA_SUSPECT, EngineReplica,
                                  FleetController, ReplicaRegistry)
from apex_tpu.serve.metrics import ServeMetrics
from apex_tpu.serve.scheduler import Request, ServeScheduler
# bound at collection time: test_chip_worker purges apex_tpu.* from
# sys.modules mid-session (see test_serve_resilience for the history)
from apex_tpu.utils.logging import subscribe_events

pytestmark = [pytest.mark.serve, pytest.mark.fault]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# deliberately tiny (1 layer, 16-wide): the fleet compiles one decode +
# one prefill bucket PER replica, and three replicas' worth of compile
# time is the fixture cost every test below shares
CFG = GPT2Config(vocab_size=61, n_positions=32, n_embd=16, n_layer=1,
                 n_head=2, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_gpt2_params(CFG, seed=0)


@pytest.fixture(scope="module")
def engines(params):
    """Three 2-slot greedy engines sharing ONE param pytree (identical
    weights — the fleet bit-exactness precondition); tests reset().
    Pre-warmed: a prefill compiling INSIDE a worker tick blocks
    heartbeats long enough to read as a death, which is realistic but
    not what these tests schedule — startup pays the trace, the PR-5
    serving contract."""
    return [Engine(CFG, params,
                   EngineConfig(num_slots=2, max_len=32, temperature=0.0),
                   seed=0).aot_compile([8])
            for _ in range(3)]


def _tokens(n, seed=7, vocab=61):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, vocab, n)]


def _requests(n=6, max_new=4, **kw):
    return [Request(request_id=f"r{i}", tokens=_tokens(4 + i % 3, seed=i),
                    max_new_tokens=max_new, **kw) for i in range(n)]


def _handles(engines, n=3, **kw):
    return [EngineReplica(f"rep{i}", e.reset(), **kw)
            for i, e in enumerate(engines[:n])]


def _assert_exactly_one_terminal_fleetwide(stats, expected_ids):
    recs = stats.requests
    ids = [r["request_id"] for r in recs]
    assert sorted(ids) == sorted(expected_ids), \
        (sorted(set(expected_ids) - set(ids)),
         sorted(set(ids) - set(expected_ids)))
    assert len(ids) == len(set(ids)), "a request settled twice"
    for r in recs:
        assert r["state"] in ("completed", "evicted", "rejected"), r


# -------------------------------------------------- registry health model

def test_registry_escalates_suspect_then_dead():
    """Heartbeat misses escalate watchdog-style: suspect at 2 silent
    intervals, dead at 4 — one event per transition, dead absorbing."""
    t = [0.0]
    reg = ReplicaRegistry(0.05, suspect_misses=2, dead_misses=4,
                          clock=lambda: t[0])
    reg.register("a")
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r) if str(r.get("event", "")).startswith(
            "serve_replica_") else None)
    try:
        t[0] = 0.05
        assert reg.sweep() == [] and reg.state("a") == REPLICA_HEALTHY
        t[0] = 0.11                    # 2.2 missed intervals
        trans = reg.sweep()
        assert [x["new"] for x in trans] == [REPLICA_SUSPECT]
        assert reg.state("a") == REPLICA_SUSPECT
        assert reg.sweep() == []       # no re-announcement
        t[0] = 0.21                    # 4.2 missed intervals
        trans = reg.sweep()
        assert [x["new"] for x in trans] == [REPLICA_DEAD]
        assert reg.sweep() == []       # dead is absorbing
    finally:
        unsub()
    assert [e["event"] for e in seen] == ["serve_replica_suspect",
                                         "serve_replica_dead"]


def test_registry_beat_heals_suspect_never_dead():
    """A beat heals a suspect back to healthy; a dead replica's beats
    (a healed partition) do NOT revive it — its requests were already
    re-dispatched, and quiet re-admission is the double-complete door."""
    t = [0.0]
    reg = ReplicaRegistry(0.05, suspect_misses=2, dead_misses=4,
                          clock=lambda: t[0])
    reg.register("a")
    t[0] = 0.11
    reg.sweep()
    assert reg.state("a") == REPLICA_SUSPECT
    reg.heartbeat("a")
    assert reg.state("a") == REPLICA_HEALTHY
    t[0] = 0.50
    reg.sweep()
    assert reg.state("a") == REPLICA_DEAD
    reg.heartbeat("a")
    assert reg.state("a") == REPLICA_DEAD, \
        "a healed partition must rejoin via restart_replica, not a beat"


def test_registry_validation(engines):
    with pytest.raises(ValueError, match="heartbeat_s"):
        ReplicaRegistry(0.0)
    with pytest.raises(ValueError, match="suspect_misses"):
        ReplicaRegistry(0.05, suspect_misses=4, dead_misses=2)
    with pytest.raises(ValueError, match="replica"):
        FleetController([])
    with pytest.raises(ValueError, match="hedge"):
        FleetController(_handles(engines, n=1), hedge_ms=10.0)


# ------------------------------------------------------- no-fault fleet

def test_fleet_matches_single_scheduler_oracle(engines):
    """Routing across replicas never changes greedy content: the fleet's
    completed outputs are bit-identical to ONE scheduler serving the
    same requests (shared params + slot isolation + the PR-5
    invariant), and the attempt counters equal the fleet record set
    when nothing fails."""
    sched = ServeScheduler(engines[0].reset())
    for r in _requests():
        sched.submit(r)
    base = {r["request_id"]: r["generated"]
            for r in sched.run().requests}

    # generous death budget: a no-fault run must never see a spurious
    # death — under 3-thread CPU contention a decode tick can stall
    # past a tight heartbeat window (the XLA CPU client serializes
    # executions), which is exactly what dead_misses is FOR
    fleet = FleetController(_handles(engines), heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000)
    for r in _requests():
        fleet.submit(r)
    stats = fleet.run(max_wall_s=30)
    got = {r["request_id"]: r["generated"] for r in stats.requests}
    assert got == base
    s = stats.summary()
    assert s["completed"] == 6 and s["failovers"] == 0
    assert s["attempts"] == {"submitted": 6, "completed": 6,
                             "evicted": 0, "deadline_exceeded": 0,
                             "rejected": 0}
    assert s["replica_dead"] == 0


def test_fleet_refuses_duplicate_ids_and_drain_sheds_queued(engines):
    """begin_drain (the SIGTERM contract): new submits refused, and a
    pre-drain request that never reached a slot is shed as a terminal
    RETRIABLE rejection — never served after the drain, never silently
    dropped."""
    fleet = FleetController(_handles(engines, n=2), heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000)
    assert fleet.submit(Request(request_id="x", tokens=_tokens(4)))
    with pytest.raises(ValueError, match="exactly-once"):
        fleet.submit(Request(request_id="x", tokens=_tokens(4)))
    fleet.begin_drain()
    assert fleet.submit(Request(request_id="y",
                                tokens=_tokens(4))) is False
    # x is still QUEUED (no workers have run): the drain sweep sheds it
    fleet.pump()
    rec, = fleet.stats().requests
    assert rec["request_id"] == "x" and rec["state"] == "rejected"
    assert rec["finish_reason"] == "draining" and rec["retriable"]
    # the replica-side queue emptied without a replica-side terminal
    assert all(h.load() == 0 for h in fleet.handles)
    assert all(h.scheduler.done_since(0)[0] == [] for h in fleet.handles)
    stats = fleet.run(max_wall_s=30)     # settles instantly: all terminal
    assert [r["request_id"] for r in stats.requests] == ["x"]


def test_drain_wait_false_cannot_wedge_draining(engines):
    """Review regression: drain(wait=False) on a BUSY replica must not
    leave it draining forever — any later pump marks it drained the
    moment its last in-flight request leaves, and restart_replica then
    accepts it."""
    fleet = FleetController(_handles(engines, n=2), heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000)
    for r in _requests(4, max_new=4):
        fleet.submit(r)
    fleet.start()
    drained = []
    unsub = subscribe_events(
        lambda r: drained.append(r)
        if r.get("event") == "serve_replica_drained" else None)
    try:
        fleet.drain("rep0", wait=False)  # rep0 is busy: stays draining
        stats = fleet.run(max_wall_s=30)  # run() pumps; rep0 idles out
    finally:
        unsub()
    assert all(r["state"] == "completed" for r in stats.requests)
    assert fleet.registry.state("rep0") == "drained"
    assert len(drained) == 1
    fleet.restart_replica("rep0")
    assert fleet.registry.state("rep0") == REPLICA_HEALTHY


# ------------------------------------------------------ THE chaos smoke

def test_fleet_chaos_smoke(engines):
    """ISSUE 11 acceptance: one seeded schedule combining a replica
    kill, a network partition, and a straggler across 3 replicas.
    Every submitted request reaches exactly one terminal status
    fleet-wide, completed greedy outputs are bit-identical to the
    no-fault fleet, and no surviving replica recompiles."""
    fleet = FleetController(_handles(engines), heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000)
    for r in _requests():
        fleet.submit(r)
    base = {r["request_id"]: r["generated"]
            for r in fleet.run(max_wall_s=30).requests}
    traces = [e.decode_traces for e in engines]

    # the killed and partitioned replicas stop beating ENTIRELY, so
    # their deaths are certain at any budget — the generous dead_misses
    # only protects the straggling survivor from a spurious death under
    # CPU-contention tick stalls (which would leave nobody admitting)
    inj = (FaultInjector(seed=0)
           .kill_replica("rep1", at_tick=3)
           .partition_replica("rep2", at_tick=4)
           .straggler_replica("rep0", 0.01, at_tick=2, ticks=3))
    fleet = FleetController(_handles(engines), heartbeat_ms=25,
                            suspect_misses=50, dead_misses=200,
                            hedge_ms=150.0, fault_injector=inj)
    for r in _requests():
        fleet.submit(r)
    with GoodputLedger() as led:
        stats = fleet.run(max_wall_s=45)

    assert [e.decode_traces for e in engines] == traces, \
        "a surviving replica retraced decode across the chaos schedule"
    _assert_exactly_one_terminal_fleetwide(
        stats, [f"r{i}" for i in range(6)])
    got = {r["request_id"]: r for r in stats.requests}
    for rid, gen in base.items():
        assert got[rid]["state"] == "completed"
        assert got[rid]["generated"] == gen, \
            f"{rid} drifted across kill+partition+straggler"
    s = stats.summary()
    assert s["replica_dead"] == 2          # the kill and the partition
    assert s["failovers"] >= 1
    g = led.summary()
    assert g["events"]["serve_replica_dead"] == 2
    assert g["events"].get("serve_failover", 0) == s["failovers"]
    # the failover span is a timed loss cause on the ledger
    assert g["lost_by_cause"].get("serve_failover", 0.0) >= 0.0


# ---------------------------------------------------------------- hedging

def test_hedge_fires_exactly_once_and_first_terminal_wins(engines):
    """A straggling primary trips the hedge: exactly one
    serve_hedge_fired, the fast replica's completion wins, the loser is
    aborted replica-side, and the fleet records exactly one terminal
    status. (Heartbeat thresholds are generous so the straggler is slow,
    not dead — hedging is the remedy under test, not failover.)"""
    inj = FaultInjector(seed=0).straggler_replica("rep0", 0.05,
                                                  at_tick=1, ticks=60)
    fleet = FleetController(_handles(engines, n=2), heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000,
                            hedge_ms=40.0, fault_injector=inj)
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r)
        if r.get("event") == "serve_hedge_fired" else None)
    try:
        fleet.submit(Request(request_id="h0", tokens=_tokens(5),
                             max_new_tokens=4))
        stats = fleet.run(max_wall_s=30)
    finally:
        unsub()
    assert len(seen) == 1
    assert seen[0]["primary"] == "rep0" and seen[0]["hedge"] == "rep1"
    s = stats.summary()
    assert s["hedge_fired"] == 1 and s["requests"] == 1
    rec, = stats.requests
    # the WINNER is a race by design (first terminal wins — usually the
    # fast replica, but the straggler can still land first): assert the
    # contract, not the racer. Under greedy decoding either copy's
    # output is bit-identical, so the race never changes content.
    assert rec["state"] == "completed"
    assert rec["replica"] in ("rep0", "rep1")
    # the loser's abort is an attempt-level eviction, never a second
    # fleet record
    assert s["attempts"]["submitted"] == 2


# --------------------------------------------- partition heal / dedup

def test_partition_heal_never_double_completes(engines):
    """A partitioned replica keeps decoding while the router declares it
    dead and fails over. When the partition heals, its duplicate
    completions surface at harvest — and must lose first-terminal-wins:
    one record per request, and the healed replica stays out of the
    routing pool until an explicit restart."""
    import time

    inj = FaultInjector(seed=0).partition_replica("rep0", at_tick=2)
    fleet = FleetController(_handles(engines, n=2), heartbeat_ms=25,
                            suspect_misses=50, dead_misses=200,
                            fault_injector=inj)
    for r in _requests(3, max_new=6):
        fleet.submit(r)
    fleet.start()
    t0 = time.perf_counter()
    while not fleet.all_terminal():
        fleet.pump()
        assert time.perf_counter() - t0 < 30, "fleet wedged"
        time.sleep(0.002)
    rep0 = fleet.handles[0]
    # the partitioned replica finished (some of) its copies in the dark
    t0 = time.perf_counter()
    while not any(r.state == "completed"
                  for r in rep0.scheduler.done_since(0)[0]):
        assert time.perf_counter() - t0 < 30, \
            "partitioned replica never completed its dark copies"
        time.sleep(0.002)
    dark = sum(r.state == "completed"
               for r in rep0.scheduler.done_since(0)[0])
    inj.heal_replica("rep0")
    t0 = time.perf_counter()
    while rep0.partitioned:
        assert time.perf_counter() - t0 < 10
        time.sleep(0.002)
    for _ in range(5):
        fleet.pump()               # harvest the healed replica's backlog
    fleet.stop()
    stats = fleet.stats()
    _assert_exactly_one_terminal_fleetwide(stats, ["r0", "r1", "r2"])
    assert all(r["state"] == "completed" for r in stats.requests)
    assert dark >= 1
    # duplicates existed fleet-wide (dark copies + survivor re-runs)...
    assert stats.attempts["completed"] >= 3 + dark - \
        sum(r["replica"] == "rep0" for r in stats.requests)
    # ...and the healed replica is still dead to the router
    assert fleet.registry.state("rep0") == REPLICA_DEAD
    assert fleet._route().replica_id == "rep1"
    assert stats.summary()["replica_dead"] == 1


# ------------------------------------------------- drain / rolling restart

def test_drain_migrates_queued_without_terminal_records(engines):
    """Drain before the workers ever run: still-queued requests migrate
    to peers through pop_queued — no terminal record anywhere, the
    drained replica empties, and the fleet still completes everything
    after a restart."""
    fleet = FleetController(_handles(engines, n=2), heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000)
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r) if r.get("event") in
        ("serve_failover", "serve_replica_drained",
         "serve_replica_restarted") else None)
    try:
        for r in _requests(4, max_new=3):
            fleet.submit(r)
        rep0, rep1 = fleet.handles
        assert rep0.load() == 2 and rep1.load() == 2
        migrated = fleet.drain("rep0", wait=False)
        assert migrated == 2
        assert rep0.load() == 0 and rep1.load() == 4
        # migration is NOT a terminal status on either side
        assert rep0.scheduler.done_since(0)[0] == []
        drains = [e for e in seen if e["event"] == "serve_failover"]
        assert len(drains) == 2
        assert all(e["cause"] == "drain" and e["to_replica"] == "rep1"
                   for e in drains)
        assert [e["event"] for e in seen if "replica" in e.get(
            "event", "")] or True
        fleet.restart_replica("rep0")
        stats = fleet.run(max_wall_s=30)
    finally:
        unsub()
    assert all(r["state"] == "completed" for r in stats.requests)
    assert stats.summary()["migrations"] == 2
    assert [e["event"] for e in seen
            if e["event"].startswith("serve_replica_")] == \
        ["serve_replica_drained", "serve_replica_restarted"]


def test_rolling_restart_keeps_capacity_and_loses_nothing(engines):
    """ISSUE 11 acceptance: rolling drain keeps >= N-1 replicas
    admitting at all times and loses zero in-flight requests — queued
    ones migrate, running ones finish, every replica restarts exactly
    once with zero recompiles."""
    fleet = FleetController(_handles(engines), heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000)
    for r in _requests(9, max_new=6):
        fleet.submit(r)
    fleet.start()
    traces = [e.decode_traces for e in engines]
    result = fleet.rolling_restart(max_wall_s=30)
    stats = fleet.run(max_wall_s=30)
    assert result["restarted"] == 3
    assert result["min_admitting"] >= 2, \
        "capacity dropped below N-1 during the rolling restart"
    _assert_exactly_one_terminal_fleetwide(
        stats, [f"r{i}" for i in range(9)])
    assert all(r["state"] == "completed" for r in stats.requests), \
        "rolling restart lost an in-flight request"
    s = stats.summary()
    assert s["replica_restarted"] == 3 and s["replica_dead"] == 0
    assert [e.decode_traces for e in engines] == traces, \
        "a clean restart must keep every compiled artifact"


def test_restart_requires_drained_or_dead(engines):
    fleet = FleetController(_handles(engines, n=2), heartbeat_ms=25)
    with pytest.raises(ValueError, match="drain"):
        fleet.restart_replica("rep0")


def test_hedge_copy_rejection_never_settles_live_request(engines):
    """Review regression: one hedge copy shed by admission control must
    NOT become the request's fleet-terminal status (nor abort the other
    copy a healthy replica is actively serving) — the live copy IS the
    retry. Driven clock-injected with no workers, so the race is
    deterministic."""
    from apex_tpu.serve.resilience import AdmissionController

    t = [0.0]
    handles = [EngineReplica("rep0", engines[0].reset(),
                             admission=AdmissionController(
                                 max_queue=1, shed_policy="shed-oldest")),
               EngineReplica("rep1", engines[1].reset())]
    fleet = FleetController(handles, heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000,
                            hedge_ms=100.0, clock=lambda: t[0])
    fleet.submit(Request(request_id="h0", tokens=_tokens(4),
                         max_new_tokens=3))        # queues on rep0
    t[0] = 0.2
    fleet.pump()                                   # hedge fires to rep1
    assert fleet.hedges_fired == 1
    # a later submit sheds h0's rep0 copy (shed-oldest, queue full)
    fleet.submit(Request(request_id="filler", tokens=_tokens(4, seed=9),
                         max_new_tokens=3))
    done, _ = handles[0].scheduler.done_since(0)
    assert [r.request_id for r in done] == ["h0"]  # the shed copy
    fleet.pump()                                   # harvests the shed
    freq = fleet._requests["h0"]
    assert freq.record is None, \
        "a shed hedge copy settled a request rep1 is still serving"
    assert "rep1" in freq.attempts                 # live copy untouched
    assert handles[1].scheduler.load() == 1
    assert fleet.retries == 0                      # dropped, not retried


# ------------------------------------------------- burn-rate shed routing

def test_burn_rate_sheds_routing(engines):
    """PR-10 burn rates as a routing signal: a replica whose SLO
    short-window burn is at/above the shed factor receives new load
    only when every alternative burns too."""
    def tracker(clock):
        return SLOTracker([SLObjective.shed_frac(0.1, min_events=4)],
                          clock=clock)

    t = [1000.0]
    clock = lambda: t[0]                                     # noqa: E731
    mets = [ServeMetrics(slo=tracker(clock)) for _ in range(2)]
    handles = [EngineReplica(f"rep{i}", e.reset(), metrics=m)
               for i, (e, m) in enumerate(zip(engines, mets))]
    fleet = FleetController(handles, heartbeat_ms=25,
                            shed_burn_factor=2.0)
    assert fleet._route().replica_id == "rep0"   # equal: index tiebreak
    for _ in range(8):
        mets[0].slo.observe("shed", bad=True, t=t[0])
    mets[0].slo.evaluate(now=t[0])
    assert handles[0].burn_short_max() >= 2.0
    assert fleet._route().replica_id == "rep1", \
        "a budget-burning replica must shed new load"
    # both burning: routing still works (shedding everywhere beats
    # serving nowhere)
    for _ in range(8):
        mets[1].slo.observe("shed", bad=True, t=t[0])
    mets[1].slo.evaluate(now=t[0])
    assert fleet._route() is not None


# ------------------------------------------------ fleet metrics merge

def test_merged_replica_snapshots_reconcile_with_fleet_summary(
        engines, tmp_path):
    """ISSUE 11 acceptance: per-replica ServeMetrics snapshots fold
    through tools/metrics_merge.py into one fleet view whose counters
    reconcile EXACTLY with the fleet summary's attempt-level section —
    family by family, including the hedge loser's eviction."""
    inj = FaultInjector(seed=0).straggler_replica("rep0", 0.05,
                                                  at_tick=1, ticks=60)
    handles = [EngineReplica(f"rep{i}", e.reset(),
                             metrics=ServeMetrics())
               for i, e in enumerate(engines[:2])]
    fleet = FleetController(handles, heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000,
                            hedge_ms=40.0, fault_injector=inj)
    for r in _requests(5, max_new=3):
        fleet.submit(r)
    stats = fleet.run(max_wall_s=30)
    s = stats.summary()
    assert s["hedge_fired"] >= 1       # at least one duplicate attempt

    from apex_tpu.monitor.export import write_snapshot

    paths = []
    for i, h in enumerate(handles):
        p = str(tmp_path / f"rank{i}.json")
        write_snapshot(h.metrics.registry, p, meta={"replica": i})
        paths.append(p)
    merged_path = str(tmp_path / "fleet.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "metrics_merge.py"),
         *paths, "-o", merged_path],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    merged = json.load(open(merged_path))

    def total(name):
        fam = merged["metrics"].get(name, {"series": []})
        return sum(x["value"] for x in fam["series"])

    want = s["attempts"]
    assert total("serve_requests_submitted_total") == want["submitted"]
    assert total("serve_requests_completed_total") == want["completed"]
    assert total("serve_requests_evicted_total") == want["evicted"]
    assert total("serve_requests_rejected_total") == want["rejected"]
    assert total("serve_deadline_exceeded_total") == \
        want["deadline_exceeded"]
    # the duplicate attempts are visible: more attempts than requests
    assert want["submitted"] > s["requests"] - 1 + s["hedge_fired"] - 1


# --------------------------------------------------- gate direction hints

def test_fleet_counters_gate_lower_is_better():
    """A 0 -> N failover/hedge/replica-death storm must gate as a
    regression, never a win (and never be skipped off a zero
    baseline)."""
    sys.path.insert(0, ROOT)
    try:
        from tools.check_regression import compare, lower_is_better
    finally:
        sys.path.remove(ROOT)
    for name in ("failovers", "serve_decode.failovers", "hedge_fired",
                 "replica_dead"):
        assert lower_is_better(name), name
    results, _ = compare({"failovers": (3.0, None)},
                         {"failovers": (0.0, None)}, tolerance=0.10)
    assert results[0]["regressed"] is True
    results, _ = compare({"failovers": (0.0, None)},
                         {"failovers": (0.0, None)}, tolerance=0.10)
    assert results[0]["regressed"] is False


# --------------------------------------------------------------- the CLI

def test_fleet_cli_usage_errors():
    """Inert or contradictory fleet flag combinations are clean exit-2
    usage errors BEFORE any compile (milliseconds, not trace time).
    PR 13 lifted the PR-11 restrictions: --trace-jsonl /
    --flight-recorder / --metrics-port are now fleet citizens, so only
    the still-genuinely-inert combos stay refused — --max-restarts (the
    one-scheduler supervisor) and --trace-sample with no trace file to
    sample into."""
    from apex_tpu.serve.cli import main

    for argv in (["--hedge-ms", "20"],
                 ["--heartbeat-ms", "20"],
                 ["--drain-on", "SIGTERM"],
                 ["--replicas", "0"],
                 ["--replicas", "2", "--heartbeat-ms", "0"],
                 ["--replicas", "2", "--max-restarts", "1"],
                 ["--trace-sample", "0.5"],
                 ["--replicas", "2", "--trace-sample", "0.5"],
                 ["--trace-sample", "1.5", "--trace-jsonl", "t.json"],
                 ["--trace-sample", "0", "--trace-jsonl", "t.json"]):
        assert main(argv) == 2, argv


def test_bench_fleet_usage_errors():
    from apex_tpu.bench_cli import _serve_bench

    for kw in ({"hedge_ms": 5.0}, {"heartbeat_ms": 5.0},
               {"replicas": 0},
               {"replicas": 2, "heartbeat_ms": 0.0},
               {"trace_sample": 0.5},                  # no --trace-jsonl
               {"trace_sample": 2.0, "trace_jsonl": "t.json"}):
        with pytest.raises(SystemExit, match="apex-tpu-bench"):
            _serve_bench(2, 2, None, **kw)


@pytest.mark.slow
def test_fleet_cli_end_to_end(capsys, tmp_path):
    """In-process --replicas e2e: per-request records, the fleet summary
    with failovers/hedge_fired/migrations, one decode compile per
    replica, and per-replica + merged snapshots on disk. Rides slow
    (the PR-5 CLI-subprocess precedent): it compiles two fresh
    tiny-preset engines, and the tier-1 budget is carried by the six
    mandated fleet tests above — the exit-2 usage matrices stay
    tier-1."""
    from apex_tpu.serve.cli import main

    snap = str(tmp_path / "fleet_snap.json")
    trace = str(tmp_path / "fleet_trace.json")
    rc = main(["--config", "tiny", "--replicas", "2", "--requests", "4",
               "--prompt-len", "4", "--max-new-tokens", "3",
               "--num-slots", "2", "--max-len", "32",
               "--temperature", "0", "--heartbeat-ms", "250",
               "--hedge-ms", "5000", "--metrics-snapshot", snap,
               "--trace-jsonl", trace, "--trace-sample", "1.0"])
    captured = capsys.readouterr()
    assert rc == 0, captured.err      # the usage message names the cause
    lines = [json.loads(l) for l in
             captured.out.strip().splitlines()]
    recs, final = lines[:-1], lines[-1]
    assert len(recs) == 4
    assert all(r["state"] == "completed" for r in recs)
    assert all(r["replica"] in ("r0", "r1") for r in recs)
    s = final["summary"]
    assert s["failovers"] == 0 and s["hedge_fired"] == 0
    assert s["migrations"] == 0 and s["replicas"] == 2
    assert final["decode_compiles"] == [1, 1], \
        "fleet tracing must add zero compiles"
    # PR 13: the journey files landed (fleet plane + one per replica)
    # and the sampling provenance rode the final line
    assert final["trace"]["sampled"] == 4
    assert final["trace"]["promoted"] == 0
    for p in (trace, trace + ".r0", trace + ".r1"):
        assert os.path.exists(p), p
    # one mergeable snapshot per replica + the merged fleet view, and
    # the merged counters reconcile with the attempts section
    assert os.path.exists(snap + ".r0") and os.path.exists(snap + ".r1")
    merged = json.load(open(snap))
    got = sum(x["value"] for x in
              merged["metrics"]["serve_requests_submitted_total"]
              ["series"])
    assert got == s["attempts"]["submitted"]


@pytest.mark.slow
def test_bench_fleet_entry(capsys):
    """--serve --replicas bench: the serve_decode entry carries the
    fleet resilience counters and the workload provenance records
    replicas/hedge_ms/heartbeat_ms (never gated across incomparable
    configs). Slow for the same reason as the CLI e2e: two more fresh
    engine compiles."""
    from apex_tpu.bench_cli import _serve_bench

    _serve_bench(4, 2, None, replicas=2, hedge_ms=5000.0,
                 heartbeat_ms=25.0)
    doc = json.loads(capsys.readouterr().out)
    e = doc["serve_decode"]
    assert e["value"] > 0
    for k in ("failovers", "hedge_fired", "replica_dead", "migrations"):
        assert e[k] == 0, k
    w = e["workload"]
    assert w["replicas"] == 2
    assert w["hedge_ms"] == 5000.0 and w["heartbeat_ms"] == 25.0
