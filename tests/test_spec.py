"""Speculative decoding (PR 18) — the exact acceptance oracle.

Layers under test:

1. **The seam** — ``parse_policy`` grammar incl. the beam-like refusal,
   the ``NGramDrafter``'s lookup order + total fallbacks, and
   ``sample_with_policy`` reducing to the legacy sampler at default
   knobs.
2. **Exactness** — greedy speculative streams are bit-identical to the
   one-token engine for ``draft_len ∈ {1, 2, 4}`` on the slot AND paged
   engines AND at tp=2 exact; a pathological drafter (0% acceptance)
   degrades throughput to exactly the one-token floor, never
   correctness.
3. **One-compile invariant** — a spec-armed scheduler churned through
   admit/evict/abort/prefix-hit keeps ``verify_traces == 1`` and
   ``decode_traces`` flat (the verify step IS the decode step when
   speculation is armed).
4. **The gate + CLI matrix** — check_regression treats the new families
   higher-is-better and REFUSES cross-config comparisons on the spec
   workload axes; both CLIs refuse inert/unverifiable spec flags before
   any compile.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.scheduler import Request, ServeScheduler
from apex_tpu.serve.spec import (KNOWN_UNVERIFIABLE, DecodePolicy,
                                 NGramDrafter, parse_policy,
                                 sample_with_policy)

pytestmark = pytest.mark.serve

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# n_head=4 so the same params serve the tp=2 exactness leg
CFG = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=2,
                 n_head=4, compute_dtype=jnp.float32)

PROMPTS = [[5, 6, 7, 5, 6, 7, 5], [11, 12, 13, 11, 12], [3, 4],
           [20, 21, 22, 23, 20, 21]]


@pytest.fixture(scope="module")
def params():
    return init_gpt2_params(CFG, seed=0)


def _engine(params, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 48)
    kw.setdefault("temperature", 0.0)
    return Engine(CFG, params, EngineConfig(**kw), seed=0)


def _serve(params, prompts=PROMPTS, drafter=None, **kw):
    eng = _engine(params, **kw)
    sched = ServeScheduler(eng, drafter=drafter)
    for i, p in enumerate(prompts):
        sched.submit(Request(request_id=f"r{i}", tokens=list(p),
                             max_new_tokens=12))
    stats = sched.run()
    streams = {r["request_id"]: r["generated"] for r in stats.requests}
    return streams, stats, eng


# ------------------------------------------------------- 1. the seam

def test_parse_policy_grammar():
    assert parse_policy("greedy") == DecodePolicy("greedy",
                                                  temperature=0.0)
    assert parse_policy("top_p") == DecodePolicy("top_p", top_p=0.9)
    assert parse_policy("top_p=0.5,t=0.7") \
        == DecodePolicy("top_p", top_p=0.5, temperature=0.7)
    assert parse_policy("min_p") == DecodePolicy("min_p", min_p=0.05)
    assert parse_policy("min_p=0.2") \
        == DecodePolicy("min_p", min_p=0.2)
    sp = parse_policy("spec(top_p=0.8)", spec_draft_len=2)
    assert sp.spec and sp.top_p == 0.8

    with pytest.raises(ValueError, match="unknown decode policy"):
        parse_policy("nucleus")
    with pytest.raises(ValueError, match="takes no parameters"):
        parse_policy("greedy,t=0.5")
    with pytest.raises(ValueError, match=r"in \(0, 1\]"):
        parse_policy("top_p=0")
    with pytest.raises(ValueError, match=r"in \[0, 1\)"):
        parse_policy("min_p=1.0")
    with pytest.raises(ValueError, match="needs speculation armed"):
        parse_policy("spec(greedy)")
    with pytest.raises(ValueError, match="does not nest"):
        parse_policy("spec(spec(greedy))", spec_draft_len=2)
    # beam-like: refused either way, with the oracle-specific message
    # exactly when speculation would have to verify it
    for name in KNOWN_UNVERIFIABLE:
        with pytest.raises(ValueError, match="is not supported"):
            parse_policy(name)
        with pytest.raises(ValueError, match="cannot be verified"):
            parse_policy(name, spec_draft_len=1)


def test_ngram_drafter_lookup_and_fallbacks():
    d = NGramDrafter(max_n=3)
    # trailing bigram [1, 2] recurs: its continuation 3 is the proposal,
    # and the extended working history keeps the copy going
    assert d.draft([1, 2, 3, 4, 1, 2], 3) == [3, 4, 1]
    # no self-match -> corpus lookup
    d.observe([7, 8, 9, 7, 8])
    assert d.draft([8, 9], 1) == [7]
    # nothing anywhere -> repeat-last-token (total, deterministic)
    fresh = NGramDrafter()
    assert fresh.draft([42], 3) == [42, 42, 42]
    assert fresh.draft([1, 2, 3], 2) == fresh.draft([1, 2, 3], 2)
    assert fresh.draft([5], 0) == []


def test_sample_with_policy_defaults_reduce_to_legacy():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(jax.random.PRNGKey(1), (4, 23)) * 3.0
    # greedy rows (temps <= 0): exact argmax, bit-identical to legacy
    pol = {"temps": jnp.zeros(4), "top_ps": jnp.ones(4),
           "min_ps": jnp.zeros(4)}
    out = sample_with_policy(logits, rng, pol)
    assert (np.asarray(out)
            == np.asarray(jnp.argmax(logits, axis=-1))).all()
    # default knobs at t=1: the keep mask is all-true, so the draw IS
    # plain temperature sampling on the same key
    pol = {"temps": jnp.ones(4), "top_ps": jnp.ones(4),
           "min_ps": jnp.zeros(4)}
    out = sample_with_policy(logits, rng, pol)
    plain = jax.random.categorical(rng, logits.astype(jnp.float32),
                                   axis=-1)
    assert (np.asarray(out) == np.asarray(plain)).all()
    # top_p never empties the support: p -> 0 collapses to argmax
    pol = {"temps": jnp.ones(4), "top_ps": jnp.full(4, 1e-9),
           "min_ps": jnp.zeros(4)}
    out = sample_with_policy(logits, rng, pol)
    assert (np.asarray(out)
            == np.asarray(jnp.argmax(logits, axis=-1))).all()


def test_policy_mixing_in_one_batch_single_trace(params):
    """Per-request policies are DATA: mixing greedy and top_p rows in
    one batch rides one decode trace, and the greedy rows match the
    policy-off oracle stream bit for bit."""
    base, _, _ = _serve(params)
    eng = _engine(params, decode_policy="greedy")
    sched = ServeScheduler(eng)
    for i, p in enumerate(PROMPTS):
        sched.submit(Request(
            request_id=f"r{i}", tokens=list(p), max_new_tokens=12,
            policy="top_p=0.9" if i % 2 else "greedy"))
    stats = sched.run()
    assert eng.decode_traces == 1
    streams = {r["request_id"]: r["generated"] for r in stats.requests}
    for i in (0, 2):          # the greedy rows are the oracle's
        assert streams[f"r{i}"] == base[f"r{i}"]


# ------------------------------------------------------ 2. exactness

# tier-1 keeps the boundary drafts (1 = degenerate single-token, 4 =
# engine max); the interior cell rides the slow tier
@pytest.mark.parametrize("draft_len", [
    1, pytest.param(2, marks=pytest.mark.slow), 4])
def test_greedy_spec_bit_identical_slot_and_paged(params, draft_len):
    base, base_stats, _ = _serve(params)
    assert base_stats.summary()["accepted_tokens_per_step"] == 1.0

    streams, stats, eng = _serve(params, spec_draft_len=draft_len)
    assert streams == base
    assert eng.verify_traces == 1
    assert eng.decode_traces == 0     # every tick ran the verify step
    s = stats.summary()
    assert s["accepted_tokens_per_step"] >= 1.0
    assert stats.decode_tokens == base_stats.decode_tokens
    # multi-token commits finish in fewer steps, never more
    assert stats.decode_steps <= base_stats.decode_steps

    paged, pstats, peng = _serve(params, spec_draft_len=draft_len,
                                 page_size=8, num_pages=32)
    assert paged == base
    assert peng.verify_traces == 1 and peng.decode_traces == 0
    if draft_len >= 2:
        # the periodic prompts make the n-gram drafter actually land
        assert pstats.summary()["accepted_tokens_per_step"] > 1.0


def test_greedy_spec_bit_identical_tp2_exact(params, tp_devices):
    base, _, _ = _serve(params)
    streams, _, eng = _serve(params, spec_draft_len=2, tp=2)
    assert streams == base            # sharded verify == one-chip oracle
    assert eng.verify_traces == 1 and eng.decode_traces == 0


class _WrongDrafter:
    """Pathological drafter: proposes (oracle_token + 1) mod vocab at
    every position, so the exact acceptance test rejects EVERY draft —
    the worst case speculation must survive with zero correctness
    loss."""

    def __init__(self, oracle_streams):
        self._by_prompt = {tuple(PROMPTS[i]): oracle_streams[f"r{i}"]
                          for i in range(len(PROMPTS))}

    def draft(self, history, k):
        hist = [int(t) for t in history]
        for prompt, gen in self._by_prompt.items():
            if tuple(hist[:len(prompt)]) == prompt:
                done = len(hist) - len(prompt)
                return [(gen[done + j] + 1) % CFG.vocab_size
                        if done + j < len(gen) else 0
                        for j in range(k)]
        return [0] * k


def test_pathological_drafter_floors_at_one_token(params):
    base, base_stats, _ = _serve(params)
    streams, stats, eng = _serve(params, spec_draft_len=2,
                                 drafter=_WrongDrafter(base))
    assert streams == base            # zero correctness loss
    s = stats.summary()
    assert s["spec_accept_rate"] == 0.0
    # every verify step committed exactly its one bonus token: the
    # throughput floor IS the one-token engine's
    assert s["accepted_tokens_per_step"] == 1.0
    assert stats.decode_steps == base_stats.decode_steps
    assert eng.verify_traces == 1


# ----------------------------------------- 3. one-compile under churn

def test_spec_traces_flat_under_churn(params):
    """Admit/evict/abort/prefix-hit churn through a spec-armed paged
    engine: one verify trace, one prefill trace per bucket, zero decode
    traces — the invariant the whole PR rides on."""
    eng = _engine(params, num_slots=2, spec_draft_len=2, page_size=8,
                  num_pages=48, prefix_cache=True)
    sched = ServeScheduler(eng)
    shared = [9, 8, 7, 6, 5, 4, 3, 2]        # one full shared page
    # wave 1: overcommit the two slots (queueing + backfill churn)
    for i in range(4):
        sched.submit(Request(request_id=f"a{i}",
                             tokens=shared + [30 + i],
                             max_new_tokens=6))
    sched.submit(Request(request_id="doomed", tokens=[1, 2, 3],
                         max_new_tokens=6))
    while sched.step():
        if sched.decode_steps == 2:
            sched.abort("doomed")            # mid-stream/queued abort
    hits_before = sched.prefix_hits
    # wave 2: same shared prefix -> prefix-hit admissions re-enter the
    # SAME verify executable
    for i in range(2):
        sched.submit(Request(request_id=f"b{i}",
                             tokens=shared + [60 + i],
                             max_new_tokens=4))
    stats = sched.run()
    assert sched.prefix_hits > hits_before
    assert eng.verify_traces == 1
    assert eng.decode_traces == 0
    done = {r["request_id"]: r["state"] for r in stats.requests}
    assert done["doomed"] == "evicted"
    assert all(done[f"b{i}"] == "completed" for i in range(2))
    # token accounting counts tokens, not steps
    assert stats.decode_tokens >= stats.decode_slot_steps > 0


def test_spec_journal_recover_restores_counters(params):
    """Warm restart (PR-14) carries the spec counters: the recovered
    scheduler's accounting continues from the snapshot, not from
    zero."""
    from apex_tpu.serve.resilience import TickJournal

    eng = _engine(params, num_slots=2, spec_draft_len=2)
    sched = ServeScheduler(eng, journal=TickJournal())
    for i in range(2):
        sched.submit(Request(request_id=f"r{i}",
                             tokens=list(PROMPTS[i]),
                             max_new_tokens=8))
    for _ in range(3):
        sched.step()
    want = (sched.decode_slot_steps, sched.spec_proposed,
            sched.spec_accepted)
    assert want[0] > 0
    sched.decode_slot_steps = sched.spec_proposed = 0
    sched.spec_accepted = 0                  # simulate torn-tick loss
    sched.recover(error="injected")
    assert (sched.decode_slot_steps, sched.spec_proposed,
            sched.spec_accepted) == want
    sched.run()


def test_spec_engine_validation(params):
    with pytest.raises(ValueError, match="spec_draft_len"):
        _engine(params, spec_draft_len=-1)
    with pytest.raises(ValueError, match="max_len"):
        _engine(params, spec_draft_len=48, max_len=48)
    eng = _engine(params, num_slots=2)
    with pytest.raises(ValueError, match="spec_decode_step needs"):
        eng.spec_decode_step(np.zeros(2, np.int32),
                             np.zeros((2, 1), np.int32),
                             np.zeros(2, np.int32),
                             np.zeros(2, bool))


# --------------------------------------------- 4. the gate + CLI matrix

def _check_regression():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_regression
    finally:
        sys.path.pop(0)
    return check_regression


def _suite_doc(atps, rate, tps, workload):
    return {"serve_decode": {
        "metric": "serve_decode_tokens_per_s", "value": tps,
        "unit": "tokens_per_s", "accepted_tokens_per_step": atps,
        "spec_accept_rate": rate, "spec_tokens_per_s": tps,
        "workload": dict(workload)}}


def test_gate_directions_and_spec_axes(tmp_path):
    cr = _check_regression()
    for name in ("serve_decode.accepted_tokens_per_step",
                 "serve_decode.spec_accept_rate",
                 "serve_decode.spec_tokens_per_s"):
        assert not cr.lower_is_better(name), name

    spec_wl = {"spec": True, "draft_len": 2, "decode_policy": None}
    # legacy baselines carry NO spec keys: missing = speculation off,
    # and the gate must REFUSE, not compare
    legacy = _suite_doc(1.0, 0.0, 300.0, {})
    cur = _suite_doc(1.9, 0.5, 500.0, spec_wl)
    bad = cr.incomparable_entries(cur, legacy)
    assert "spec" in bad.get("serve_decode", "")
    # differing widths refuse too; identical spec configs compare
    assert cr.incomparable_entries(
        cur, _suite_doc(1.5, 0.3, 400.0,
                        {**spec_wl, "draft_len": 4}))
    assert cr.incomparable_entries(cur, _suite_doc(
        1.5, 0.3, 400.0, spec_wl)) == {}

    # a REAL gate run (PR-15 precedent): same config, worse acceptance
    # -> exit 1; legacy baseline -> exit 2 (nothing comparable)
    cur_p = str(tmp_path / "cur.json")
    json.dump(cur, open(cur_p, "w"))
    same = str(tmp_path / "same.json")
    json.dump(cur, open(same, "w"))
    assert cr.main([cur_p, "--suite", same,
                    "--kernels", "serve_decode"]) == 0
    worse = str(tmp_path / "worse.json")
    json.dump(_suite_doc(1.9, 0.5, 500.0, spec_wl), open(cur_p, "w"))
    json.dump(_suite_doc(2.5, 0.8, 500.0, spec_wl), open(worse, "w"))
    assert cr.main([cur_p, "--suite", worse,
                    "--kernels", "serve_decode"]) == 1
    legacy_p = str(tmp_path / "legacy.json")
    json.dump(legacy, open(legacy_p, "w"))
    assert cr.main([cur_p, "--suite", legacy_p,
                    "--kernels", "serve_decode"]) == 2


def test_serve_cli_spec_flag_matrix(capsys):
    """Inert or unverifiable spec flags are loud exit-2 usage errors
    BEFORE any params or compile work (PR-10 precedent) — in-process:
    the validation runs in milliseconds, a subprocess would only pay a
    jax import to reach the same lines."""
    from apex_tpu.serve.cli import main

    for argv, msg in [
            (["--spec-draft-len", "0"], "must be >= 1"),
            (["--spec-draft-len", "-3"], "must be >= 1"),
            (["--decode-policy", "nucleus"], "unknown decode policy"),
            (["--decode-policy", "beam"], "is not supported"),
            (["--spec-draft-len", "2", "--decode-policy", "beam"],
             "cannot be verified"),
            (["--decode-policy", "spec(greedy)"],
             "needs speculation armed"),
    ]:
        assert main(argv) == 2, argv
        assert msg in capsys.readouterr().err, argv


def test_bench_cli_spec_flag_matrix():
    from apex_tpu.bench_cli import _serve_bench

    with pytest.raises(SystemExit, match="must be >= 1"):
        _serve_bench(steps=1, spec_draft_len=0)
    with pytest.raises(SystemExit, match="is not supported"):
        _serve_bench(steps=1, decode_policy="best_of")
    with pytest.raises(SystemExit, match="cannot be verified"):
        _serve_bench(steps=1, spec_draft_len=2, decode_policy="beam")
    with pytest.raises(SystemExit, match="unknown decode policy"):
        _serve_bench(steps=1, decode_policy="banana")
    # --spec-draft-len outside --serve mode falls in the serve-only
    # refusal (subprocess: the matrix lives in main's argv routing)
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.bench_cli",
         "--spec-draft-len", "2"],
        cwd=ROOT, capture_output=True, text=True, timeout=600)
    assert r.returncode == 2
    assert "needs --serve" in r.stderr
