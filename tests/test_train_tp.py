"""dp×tp training + topology-portable checkpoints (markers: ``train`` +
``fault``).

The PR-19 acceptance claims, proven deterministically on the
fake-multihost harness + the conftest-forced 8-device CPU mesh:

- **tp composes with dp bit-exactly**: ``TrainConfig(tp=2)`` runs each
  grad micro-shard's forward/backward over the PR-15 head-axis mesh
  (gather-compute-slice — pure concatenation combine, no float add
  crosses a rank), and BOTH identities survive the composition:
  tp=2 ≡ tp=1 on one chip, and world 1 ≡ world 2 with tp armed;
- **THE chaos train-then-serve headline**: the PR-14 chaos schedule
  (preempt ×2, elastic 2→1→2, crash-on-step, crash-mid-save) on a
  dp×tp=2 GPT-2 trainer ends bit-identical to the uninterrupted
  single-chip oracle, the committed checkpoint's manifest carries the
  dp×tp ``layout`` block, and the restored params serve through a tp=2
  ``Engine`` with decode logits bit-equal to a single-chip prefill of
  the trained params;
- **topology-portable restore**: a checkpoint written at tp=2 restores
  onto a tp=1 job automatically (the sharded manager reassembles leaves
  topology-independently), publishing a counted
  ``train_topology_restored`` — and the resumed run stays bit-exact;
- **reshard is a digest-verified pure permutation**: dense → tp_serving
  → dense is byte-identical, and the storage-layer numpy transform is
  bit-identical to the serving stack's ``permute_qkv``/``unpermute_qkv``;
- **storage chaos**: a single bit-flip in one committed blob
  (``corrupt_checkpoint_blob``) quarantines exactly that step and falls
  back to the last good commit bit-exactly; a torn manifest is refused
  loudly (quarantined, never half-restored).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
from apex_tpu.resilience import (FaultInjector, ShardedCheckpointManager,
                                 SingleProcessCoordinator)
from apex_tpu.resilience.checkpoint_manager import CheckpointManager
from apex_tpu.resilience.topology import (FORMAT_TP_SERVING, ReshardError,
                                          layout_block, reshard,
                                          tree_digests)
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.tp import permute_qkv, tp_param_specs, unpermute_qkv
from apex_tpu.train import TrainConfig, Trainer, TrainSupervisor
from apex_tpu.train.cli import main as train_cli_main
from apex_tpu.utils.logging import subscribe_events

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.train, pytest.mark.fault]

# the serve-suite GPT-2 (same shape as tests/test_serve_tp.py): 4 heads,
# head_dim 8 — tp=2 gives each rank 2 heads; fp32 so bit-equality is
# meaningful end to end
CFG = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=2,
                 n_head=4, compute_dtype=jnp.float32)
_GPT2 = GPT2(CFG)


def _gpt2_loss(params, tokens):
    return lm_loss(_GPT2, params, tokens)


def _gpt2_batch(step):
    rng = np.random.RandomState(100003 * 23 + int(step))
    return jnp.asarray(rng.randint(0, CFG.vocab_size, (8, 16)), jnp.int32)


def _gcfg(**kw):
    base = dict(steps=12, batch=8, seq=16, vocab=97, hidden=32,
                grad_shards=2, seed=23)
    base.update(kw)
    return TrainConfig(**base)


def _cfg(seed, **kw):
    base = dict(steps=10, batch=8, seq=12, vocab=64, hidden=24,
                grad_shards=2, seed=seed)
    base.update(kw)
    return TrainConfig(**base)


def _assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _tokens(n, seed=7):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, CFG.vocab_size, n)]


@pytest.fixture
def events():
    collected = []
    unsub = subscribe_events(collected.append)
    yield collected
    unsub()


def _named(events, name):
    return [e for e in events if e.get("event") == name]


# --------------------------------------------- dp×tp bit-identity (builtin)

def test_world_sizes_bit_identical_with_tp_armed(tp_devices):
    """Both identities through the composition: tp=2 on the mesh equals
    tp=1 on one chip bit-for-bit, and world 1 equals world 2 with tp=2
    armed — each grad micro-shard's shard_map forward/backward changes
    nothing the dp reduction can see."""
    ref = Trainer(_cfg(seed=33))
    ref.run()
    oracle = jax.tree_util.tree_map(np.asarray, ref.params)
    ref.close()

    t2 = Trainer(_cfg(seed=33, tp=2))
    t2.run()
    try:
        _assert_trees_equal(t2.params, oracle)
    finally:
        t2.close()

    sup = TrainSupervisor(_cfg(seed=33, world=2, tp=2))
    rep = sup.run()
    assert rep["final_step"] == 9 and not rep["preempted"]
    _assert_trees_equal(sup.params(), oracle)
    assert rep["goodput"]["steps"] == 10 and rep["steps_retried"] == 0


# ------------------------------------------------ THE chaos train-then-serve

def test_chaos_dp_tp_train_then_serve_bit_identical(tmp_path, events,
                                                    tp_devices):
    """Headline: THE PR-14 chaos schedule (preempt ×2, elastic 2→1→2,
    crash-on-step, crash-mid-save) on a dp×tp=2 GPT-2 trainer — final
    params bit-identical to the uninterrupted single-chip oracle, the
    committed manifest carries the dp×tp layout block, zero recompiles
    across every leg (the custom-fns cache), and the trained checkpoint
    serves through a tp=2 Engine with decode logits bit-equal to a
    single-chip prefill of the same params."""
    steps = 12
    init = init_gpt2_params(CFG, seed=0)
    spec = {"params": tp_param_specs(CFG, "exact")}

    ref = Trainer(_gcfg(), loss_fn=_gpt2_loss, init_params=init,
                  batch_fn=_gpt2_batch)
    ref.run()
    oracle = jax.tree_util.tree_map(np.asarray, ref.params)
    ref.close()

    inj = (FaultInjector(seed=23)
           .preempt_at_step(3, rank=1)       # drain -> resize 2 -> 1
           .preempt_at_step(7, rank=0)       # drain -> resize 1 -> 2
           .crash_on_train_step(9)           # warm restart, same topology
           .crash_during_checkpoint_save(8))  # death mid-commit
    cfg = _gcfg(world=2, tp=2, checkpoint_dir=str(tmp_path), save_every=2)
    sup = TrainSupervisor(cfg, injector=inj, max_restarts=3,
                          backoff_s=0.01, world_schedule=[2, 1, 2],
                          loss_fn=_gpt2_loss, init_params=init,
                          batch_fn=_gpt2_batch, tp_spec=spec)
    rep = sup.run()
    assert not rep["preempted"] and rep["final_step"] == steps - 1
    assert rep["preempt_drains"] == 2 and rep["restarts"] == 2
    _assert_trees_equal(sup.params(), oracle)
    # exactly-once accounting + zero recompiles: every restart / resize
    # leg reused the ONE compiled tp step (the (loss_fn, static_key)
    # cache), so the chaos run never paid a second GPT-2 grad compile
    assert rep["goodput"]["steps"] == steps
    counts = sup.trace_counts()
    assert counts["shard_grads"] == 1 and counts["apply"] == 1, counts
    # same tp throughout: the restores were same-topology, no reshard
    assert not _named(events, "train_topology_restored")

    # the committed manifest records WHO wrote it: the dp×tp layout block
    mgr = ShardedCheckpointManager(
        str(tmp_path), coordinator=SingleProcessCoordinator())
    layout = mgr.validate(mgr.latest_step())["layout"]
    assert layout["storage"] == "sharded"
    assert layout["tp"] == 2 and layout["grad_shards"] == 2
    assert layout["world"] == 2

    # train-then-serve: restore the committed step, load the params into
    # a tp=2 serving Engine (head-major qkv permutation happens at param
    # load), and hold its incremental decode LOGITS bit-equal to a
    # single-chip prefill of the trained params
    probe = Trainer(cfg, loss_fn=_gpt2_loss, init_params=init,
                    batch_fn=_gpt2_batch, tp_spec=spec)
    restored = mgr.restore_latest(probe._tree(0))
    probe.close()
    assert restored is not None and restored[0] == steps - 1
    dense = jax.tree_util.tree_map(np.asarray, restored[1]["params"])
    _assert_trees_equal(dense, oracle)

    e_kw = dict(num_slots=3, max_len=32, temperature=0.0, block_k=8)
    served = jax.tree_util.tree_map(jnp.asarray, dense)  # device-resident
    keeper = Engine(CFG, served,
                    EngineConfig(keep_prefill_logits=True, **e_kw))
    seq = _tokens(12, seed=9)
    _, _, all_logits = keeper.prefill({1: seq})
    all_logits = np.asarray(all_logits)              # [P, B, V]
    tp_eng = Engine(CFG, served, EngineConfig(tp=2, **e_kw))
    tp_eng.prefill({1: seq[:5]})
    for j in range(5, len(seq)):
        forced = np.array([0, seq[j], 0], np.int32)
        _, logits = tp_eng.decode_step(forced,
                                       np.array([False, True, False]))
        a, b = all_logits[j, 1], np.asarray(logits)[1]
        assert a.dtype == np.float32
        assert np.array_equal(a, b), \
            f"served pos {j} drifted: max|d|={np.abs(a - b).max()}"


# --------------------------------------------- topology-portable restore

def test_restore_across_tp_topologies_reshards_bit_exact(tmp_path,
                                                         events,
                                                         tp_devices):
    """A checkpoint written by a tp=2 job restores onto a tp=1 job
    automatically (the sharded manager reassembles leaves topology-
    independently and places them with the restore target's sharding —
    restore onto a different tp IS the reshard), publishes ONE counted
    ``train_topology_restored`` naming both topologies, and the resumed
    run ends bit-identical to the uninterrupted tp=1 oracle."""
    ref = Trainer(_cfg(seed=31))
    ref.run()
    oracle = jax.tree_util.tree_map(np.asarray, ref.params)
    ref.close()

    leg_a = Trainer(_cfg(seed=31, steps=4, tp=2,
                         checkpoint_dir=str(tmp_path), save_every=2))
    leg_a.run()
    leg_a.close()
    mgr = ShardedCheckpointManager(
        str(tmp_path), coordinator=SingleProcessCoordinator())
    assert mgr.latest_step() == 3
    assert mgr.validate(3)["layout"]["tp"] == 2

    leg_b = Trainer(_cfg(seed=31, checkpoint_dir=str(tmp_path),
                         save_every=2))
    rep = leg_b.run()
    try:
        assert rep["restored_from"] == 3 and rep["final_step"] == 9
        _assert_trees_equal(leg_b.params, oracle)
    finally:
        leg_b.close()
    moved = _named(events, "train_topology_restored")
    assert len(moved) == 1
    assert moved[0]["from_tp"] == 2 and moved[0]["to_tp"] == 1


# --------------------------------------------- reshard: pure permutation

def test_reshard_dense_tp_serving_round_trip_byte_identical():
    """``dense → tp_serving → dense`` is byte-identical (digest-verified
    on every call), and the storage-layer numpy permutation is
    bit-identical to the serving stack's permute/unpermute pair."""
    rng = np.random.RandomState(0)
    qkv_k = rng.randn(32, 96).astype(np.float32)
    qkv_b = rng.randn(96).astype(np.float32)
    tree = {"wte": rng.randn(97, 32).astype(np.float32),
            "h_0": {"attn_qkv": {"kernel": qkv_k, "bias": qkv_b},
                    "mlp_fc_w": rng.randn(32, 128).astype(np.float32)}}
    dense_l = layout_block(world=2, grad_shards=2, tp=1)
    serve_l = layout_block(tp=2, fmt=FORMAT_TP_SERVING, n_head=4,
                           head_dim=8)
    served = reshard(tree, dense_l, serve_l)
    # bit-identical to the serving stack's own transform
    pk, pb = permute_qkv(qkv_k, qkv_b, 4, 8, 2)
    np.testing.assert_array_equal(served["h_0"]["attn_qkv"]["kernel"], pk)
    np.testing.assert_array_equal(served["h_0"]["attn_qkv"]["bias"], pb)
    uk, ub = unpermute_qkv(pk, pb, 4, 8, 2)
    np.testing.assert_array_equal(uk, qkv_k)
    np.testing.assert_array_equal(ub, qkv_b)
    # non-qkv leaves pass through untouched
    np.testing.assert_array_equal(served["wte"], tree["wte"])
    # the round trip is byte-identical, proven by digest
    back = reshard(served, serve_l, dense_l)
    assert tree_digests(back) == tree_digests(tree)
    # same-format reshard is a numpy pass-through
    same = reshard(tree, dense_l, dense_l)
    assert tree_digests(same) == tree_digests(tree)


def test_reshard_refuses_bad_layouts():
    with pytest.raises(ReshardError, match="unknown layout format"):
        layout_block(fmt="bogus")
    tree = {"attn_qkv": {"kernel": np.zeros((4, 12), np.float32),
                         "bias": np.zeros(12, np.float32)}}
    with pytest.raises(ReshardError, match="unknown layout format"):
        reshard(tree, {"format": "bogus"}, {"format": "dense"})
    with pytest.raises(ReshardError, match="n_head/head_dim"):
        # a tp_serving target without model geometry cannot permute
        reshard(tree, layout_block(),
                {"world": 1, "grad_shards": 1, "tp": 2,
                 "format": FORMAT_TP_SERVING})


# ------------------------------------------------------- storage chaos

def test_corrupt_blob_quarantines_once_and_falls_back_bit_exact(
        tmp_path, events, tp_devices):
    """A single bit-flip in ONE committed blob: restore quarantines
    exactly that step (one ``checkpoint_quarantined``, republished as a
    counted ``train_ckpt_quarantined``), falls back to the last good
    commit, and the recovered run ends bit-identical to the oracle. A
    torn manifest is likewise refused loudly — quarantined, never
    half-restored."""
    ref = Trainer(_cfg(seed=35))
    ref.run()
    oracle = jax.tree_util.tree_map(np.asarray, ref.params)
    ref.close()

    cfg = _cfg(seed=35, checkpoint_dir=str(tmp_path), save_every=2)
    first = Trainer(cfg)
    first.run()
    first.close()
    mgr = ShardedCheckpointManager(
        str(tmp_path), coordinator=SingleProcessCoordinator())
    latest = mgr.latest_step()
    assert latest == 9

    inj = FaultInjector(seed=35).corrupt_checkpoint_blob(latest, leaf=0)
    second = Trainer(cfg, injector=inj)
    rep = second.run()
    try:
        # the rotted step 9 was refused; step 8 restored; 9 re-ran
        assert rep["restored_from"] == 8 and rep["final_step"] == 9
        _assert_trees_equal(second.params, oracle)
        q = getattr(second.manager, "last_quarantined", None)
        assert q is not None and len(q) == 1 and q[0]["step"] == latest
    finally:
        second.close()
    assert len(_named(events, "checkpoint_quarantined")) == 1
    counted = _named(events, "train_ckpt_quarantined")
    assert len(counted) == 1 and counted[0]["step"] == latest
    assert any(n.endswith(".corrupt") for n in os.listdir(tmp_path))

    # torn manifest: truncated JSON in the newest commit — refused
    # loudly (quarantined), the previous commit restores instead
    newest = mgr.latest_step()
    mpath = os.path.join(mgr.step_path(newest), "manifest.json")
    with open(mpath, "wb") as f:
        f.write(b'{"format_version": 1, "leav')
    probe = Trainer(cfg)
    like = probe._tree(0)
    out = mgr.restore_latest(like)
    probe.close()
    assert out is not None and out[0] < newest
    assert any(q["step"] == newest for q in mgr.last_quarantined)


# ------------------------------------------------- config + CLI matrix

def test_config_validation_refuses_bad_tp_geometry():
    with pytest.raises(ValueError, match=">= 1"):
        TrainConfig(tp=0).validate()
    with pytest.raises(ValueError, match="divide hidden"):
        TrainConfig(tp=3, hidden=32).validate()
    with pytest.raises(ValueError, match="sharded_checkpoint"):
        TrainConfig(tp=2, hidden=32, checkpoint_dir="/x",
                    sharded_checkpoint=False).validate()


@pytest.mark.parametrize("argv,fragment", [
    (["--tp", "0"], ">= 1"),
    (["--tp", "3"], "divide hidden"),
    (["--tp", "2", "--grad-shards", "2", "--checkpoint-dir", "/tmp/x",
      "--elastic", "2x2:1x1"], "tp resize refused"),
    (["--tp", "2", "--grad-shards", "2", "--checkpoint-dir", "/tmp/x",
      "--elastic", "2xbanana"], "colon-separated"),
    (["--tp", "2", "--world", "8", "--grad-shards", "8"], "envelope"),
])
def test_train_cli_tp_exit2_matrix(argv, fragment, capsys):
    """The tp flag matrix refuses loudly (exit 2) before anything
    compiles: bad degree, non-dividing hidden, a live tp resize spelled
    into the world schedule, and a dp×tp envelope larger than the
    host's device pool."""
    rc = train_cli_main(argv)
    assert rc == 2
    err = capsys.readouterr().err
    assert fragment in err, err


# ------------------------------------------------- jax-free inspection

def test_ckpt_inspect_jax_free_dump_and_digest_gate(tmp_path):
    """``tools/ckpt_inspect.py`` dumps a committed step's layout block
    and digests with jax POISONED in the subprocess (importing it would
    explode — proving the forensic tool never touches jax), and exits 2
    on a flipped blob byte or a torn manifest."""
    ck = tmp_path / "ck"
    mgr = CheckpointManager(str(ck))
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": np.int64(7)}
    mgr.save(3, tree, layout=layout_block(world=1, grad_shards=2, tp=2))

    poison = tmp_path / "poison" / "jax"
    poison.mkdir(parents=True)
    (poison / "__init__.py").write_text(
        "raise ImportError('ckpt_inspect must not import jax')")
    env = dict(os.environ, PYTHONPATH=str(tmp_path / "poison"))
    tool = os.path.join(ROOT, "tools", "ckpt_inspect.py")

    out = subprocess.run([sys.executable, tool, str(ck)],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["step"] == 3 and doc["storage"] == "dense"
    assert doc["layout"]["tp"] == 2 and doc["layout"]["grad_shards"] == 2
    assert doc["blobs_verified"] == 2 and doc["all_steps"] == [3]
    assert all(e["blake2b"] for e in doc["leaves"])

    # a missing step is a usage error, loudly
    out = subprocess.run([sys.executable, tool, str(ck), "--step", "7"],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 2 and "not committed" in out.stderr

    # flip one bit of one committed blob -> exit 2 naming the file
    step_dir = os.path.join(str(ck), "step_00000003")
    blob = sorted(n for n in os.listdir(step_dir) if n.endswith(".npy"))[0]
    path = os.path.join(step_dir, blob)
    data = bytearray(open(path, "rb").read())
    data[-1] ^= 0x01
    open(path, "wb").write(bytes(data))
    out = subprocess.run([sys.executable, tool, str(ck)],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 2 and "mismatch" in out.stderr

    # torn manifest -> exit 2, named as torn
    with open(os.path.join(step_dir, "manifest.json"), "wb") as f:
        f.write(b'{"num_leaves": 2, "leaves": [')
    out = subprocess.run([sys.executable, tool, str(ck)],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 2 and "torn" in out.stderr


# ------------------------------------------------- bench + gate wiring

def test_bench_train_chaos_tp_provenance_and_gate_refusal(capsys,
                                                          monkeypatch,
                                                          tp_devices):
    """``apex-tpu-bench --train-chaos --tp 2`` stamps the tensor axis
    into workload provenance; the regression gate refuses a dp×tp
    capture against a legacy dp-only baseline (missing key = tp 1)
    instead of pretending to compare, and the new counted event names
    gate lower-is-better."""
    import apex_tpu.bench_cli as bench_cli

    tools_path = os.path.join(ROOT, "tools")
    if tools_path not in sys.path:
        sys.path.insert(0, tools_path)
    import check_regression

    monkeypatch.setattr(sys, "argv",
                        ["apex-tpu-bench", "--train-chaos", "--steps",
                         "6", "--tp", "2"])
    bench_cli.main()
    out = capsys.readouterr().out
    suite = json.loads(out[out.index("{"):])
    entry = suite["train_chaos"]
    assert entry["workload"]["tp"] == 2
    assert entry["step_recompiles"] == 1  # zero-recompile under the mesh
    # a healthy chaos run quarantines nothing and never reshards
    assert entry["ckpt_quarantined"] == 0
    assert entry["topology_restored"] == 0

    legacy = {"train_chaos": json.loads(json.dumps(entry))}
    del legacy["train_chaos"]["workload"]["tp"]  # pre-tp-axis baseline
    bad = check_regression.incomparable_entries(suite, legacy)
    assert "train_chaos" in bad and "tp=2" in bad["train_chaos"]

    # a quarantine storm / reshard churn gates as a regression off the
    # healthy 0 baseline (flat counter names, as the bench stamps them)
    assert check_regression.lower_is_better("ckpt_quarantined")
    assert check_regression.lower_is_better("topology_restored")

    # bad tp geometry is a loud exit 2 before anything compiles
    monkeypatch.setattr(sys, "argv",
                        ["apex-tpu-bench", "--train-chaos", "--tp", "3"])
    with pytest.raises(SystemExit) as exc:
        bench_cli.main()
    assert exc.value.code == 2
    assert "divide the bench model's hidden" in capsys.readouterr().err
