"""Live serving metrics tier-1: mergeable registry, per-tenant
accounting, SLO burn rates, export surfaces.

The acceptance claims under test:

- **one percentile rule** — the scheduler's exact end-of-run summary and
  the histogram quantile estimator share :func:`percentile`'s
  nearest-rank rule (the seed's ``summary()`` used ``len//2`` indexing
  for TTFT but round-half-even linear indexing for step fields);
- **exact merge** — folding N per-rank snapshots is bit-identical to
  recording the union stream into one registry (counts/buckets exact,
  quantiles identical), and ``tools/metrics_merge.py`` is that fold as a
  no-jax CLI;
- **bounded error** — a histogram quantile estimate ``e`` for exact
  value ``q`` satisfies ``q <= e <= q * HIST_GROWTH`` inside the
  bucketed range (the scheduler's exact sorted-list percentiles are the
  oracle);
- **live scrape during decode** — an in-process serve loop scraped over
  HTTP mid-run returns Prometheus text + JSON whose per-tenant counters
  sum to the exact end-of-run summary, with ``decode_traces == 1``;
- **exactly-one breach/recovery** — an induced deadline storm raises ONE
  ``serve_slo_breach`` and its drain ONE ``serve_slo_recovered``, never
  a flap per tick;
- ``check_regression`` gates a metrics snapshot directly with the same
  direction hints the serve bench uses.

Engine-driven tests share one compiled engine via ``Engine.reset()``
(the test_serve idiom); everything else is host-only and fast.
"""

import json
import math
import subprocess
import sys
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.monitor.export import (HIST_GROWTH, HIST_LO, HIST_MAX_INDEX,
                                     MetricsExporter, MetricsRegistry,
                                     bucket_index, bucket_upper,
                                     histogram_quantile, merge_snapshots,
                                     percentile, snapshot_to_prometheus,
                                     write_snapshot)
from apex_tpu.monitor.slo import SLObjective, SLOTracker, parse_slo_specs
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.metrics import ServeMetrics
from apex_tpu.serve.scheduler import Request, ServeScheduler, ServeStats
# bound at collection time: test_chip_worker purges apex_tpu.* from
# sys.modules mid-session, and a function-local re-import after that
# would subscribe to a FRESH bus the (old) modules never publish to
from apex_tpu.utils.logging import subscribe_events

import os

pytestmark = pytest.mark.monitor

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=2,
                 n_head=2, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def engine3():
    """Shared greedy 3-slot engine; tests reset() it — compiled once."""
    return Engine(CFG, init_gpt2_params(CFG, seed=0),
                  EngineConfig(num_slots=3, max_len=32, temperature=0.0),
                  seed=0)


def _tokens(n, seed=7):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, CFG.vocab_size, n)]


# ------------------------------------------------- the one percentile rule

def test_percentile_nearest_rank():
    vals = [30.0, 10.0, 20.0, 40.0]
    assert percentile(vals, 0.0) == 10.0     # rank clamps to 1: the min
    assert percentile(vals, 0.25) == 10.0    # ceil(.25*4) = 1
    assert percentile(vals, 0.50) == 20.0    # ceil(.50*4) = 2
    assert percentile(vals, 0.51) == 30.0    # ceil(.51*4) = 3
    assert percentile(vals, 0.99) == 40.0
    assert percentile(vals, 1.0) == 40.0
    assert percentile([], 0.5) == 0.0
    assert percentile([7.0], 0.99) == 7.0


def test_scheduler_summary_uses_the_shared_percentile_rule():
    """The satellite fix: ttft_p50 no longer uses len//2 indexing and the
    step fields no longer use a different rounding — every percentile
    field is the same nearest-rank helper, and ttft_p99_ms (the live SLO
    oracle) is now a summary field too."""
    steps = [0.004, 0.001, 0.003, 0.002, 0.010]
    reqs = [{"state": "completed", "ttft_s": t}
            for t in (0.5, 0.1, 0.3, 0.2)]
    stats = ServeStats(requests=reqs, decode_steps=5, decode_step_s=steps,
                       decode_tokens=15, total_new_tokens=19, wall_s=1.0)
    s = stats.summary()
    assert s["p50_step_ms"] == round(percentile(steps, 0.50) * 1e3, 3)
    assert s["p99_step_ms"] == round(percentile(steps, 0.99) * 1e3, 3)
    assert s["ttft_p50_ms"] == round(percentile([0.1, 0.2, 0.3, 0.5],
                                                0.50) * 1e3, 3) == 200.0
    assert s["ttft_p99_ms"] == 500.0
    # the old len//2 indexing would have answered 300.0 for the median
    assert s["ttft_p50_ms"] != 300.0


# ------------------------------------------------------- bucket geometry

def test_bucket_index_fixed_boundaries():
    assert bucket_index(0.0) == 0
    assert bucket_index(HIST_LO) == 0        # at the lower edge
    assert bucket_index(-5.0) == 0           # negatives land low, no crash
    assert bucket_index(float("nan")) == 0   # poisoned sample, no crash
    assert bucket_index(float("inf")) == HIST_MAX_INDEX
    assert bucket_index(1e12) == HIST_MAX_INDEX
    # monotonic, and the value sits inside its bucket's (lower, upper]
    prev = -1
    for v in (2e-6, 1e-4, 0.01, 0.5, 1.0, 7.3, 500.0):
        idx = bucket_index(v)
        assert idx >= prev or v < 1e-5
        assert v <= bucket_upper(idx) < v * HIST_GROWTH + 1e-18
        prev = idx


def test_histogram_quantile_error_bound():
    """The documented contract: for an exact nearest-rank percentile q in
    the bucketed range, the streaming estimate e satisfies
    q <= e <= q * HIST_GROWTH."""
    rng = np.random.RandomState(3)
    vals = list(np.exp(rng.uniform(np.log(1e-4), np.log(30.0), 500)))
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "t")
    for v in vals:
        h.record(v)
    series = h.labels()
    for p in (0.01, 0.25, 0.50, 0.90, 0.99, 1.0):
        exact = percentile(vals, p)
        est = series.quantile(p)
        assert exact <= est <= exact * HIST_GROWTH, (p, exact, est)


# ---------------------------------------------------------- registry core

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", "requests")
    c.inc()
    c.inc(2.5)
    assert c.labels().value == 3.5
    with pytest.raises(ValueError):
        c.labels().inc(-1.0)
    g = reg.gauge("depth", "queue depth")
    g.set(7)
    g.inc(3)
    assert g.labels().value == 10.0
    h = reg.histogram("lat_seconds", "t")
    for v in (0.5, 1.5, 2.5):
        h.record(v)
    s = h.labels()
    assert s.count == 3 and s.sum == pytest.approx(4.5)
    state = s.state()
    assert state["min"] == 0.5 and state["max"] == 2.5


def test_family_getters_idempotent_and_kind_mismatch_loud():
    reg = MetricsRegistry()
    a = reg.counter("x_total", "x")
    assert reg.counter("x_total") is a
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")
    with pytest.raises(ValueError, match="agg"):
        reg.gauge("g", agg="median")


def test_label_cardinality_bounded_overflow_folds_to_other():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "t", labels=("tenant",), max_series=2)
    c.inc(tenant="a")
    c.inc(tenant="b")
    c.inc(tenant="c")          # past max_series: folds
    c.inc(tenant="d")          # same fold series
    c.inc(tenant="a")          # existing series still addressable
    series = {tuple(s.labels.items()): s.value for s in c.series()}
    assert series[(("tenant", "a"),)] == 2.0
    assert series[(("tenant", "b"),)] == 1.0
    assert series[(("tenant", "__other__"),)] == 2.0
    assert len(series) == 3    # a tenant explosion cannot grow the scrape
    with pytest.raises(ValueError, match="labels"):
        c.inc(user="a")        # undeclared label name


# ------------------------------------------------------------ exact merge

def _record_stream(reg, stream):
    c = reg.counter("done_total", "d", labels=("tenant",))
    h = reg.histogram("lat_seconds", "t")
    for tenant, v in stream:
        c.inc(tenant=tenant)
        h.record(v)


def test_merging_rank_snapshots_equals_recording_the_union_stream():
    """THE mergeable-histogram property (the aggregation seam multi-chip
    serving reuses): counters/bucket counts exact, quantiles identical."""
    rng = np.random.RandomState(11)
    streams = []
    for r in range(3):
        n = 40 + 30 * r
        streams.append([
            (f"t{int(rng.randint(0, 3))}",
             float(np.exp(rng.uniform(np.log(1e-4), np.log(5.0)))))
            for _ in range(n)])
    ranks = []
    for stream in streams:
        reg = MetricsRegistry()
        _record_stream(reg, stream)
        ranks.append(reg.snapshot(meta={"rank": len(ranks)}))
    union = MetricsRegistry()
    _record_stream(union, [s for stream in streams for s in stream])

    merged = merge_snapshots(ranks)
    want = union.snapshot()
    assert merged["meta"] == {"merged_from": 3}
    # counters: per-tenant values identical
    got_c = {tuple(sorted(s["labels"].items())): s["value"]
             for s in merged["metrics"]["done_total"]["series"]}
    want_c = {tuple(sorted(s["labels"].items())): s["value"]
              for s in want["metrics"]["done_total"]["series"]}
    assert got_c == want_c
    # histogram: count and EVERY bucket exact, sum to fp tolerance
    got_h = merged["metrics"]["lat_seconds"]["series"][0]
    want_h = want["metrics"]["lat_seconds"]["series"][0]
    assert got_h["count"] == want_h["count"] == sum(map(len, streams))
    assert got_h["buckets"] == want_h["buckets"]
    assert got_h["sum"] == pytest.approx(want_h["sum"])
    assert got_h["min"] == want_h["min"]
    assert got_h["max"] == want_h["max"]
    # quantiles computed over the merged buckets == the union registry's
    for p in (0.5, 0.9, 0.99):
        assert histogram_quantile(got_h["buckets"], got_h["count"], p) \
            == histogram_quantile(want_h["buckets"], want_h["count"], p)
    # and within the documented bound of the exact union percentile
    exact = percentile([v for s in streams for _, v in s], 0.99)
    est = histogram_quantile(got_h["buckets"], got_h["count"], 0.99)
    assert exact <= est <= exact * HIST_GROWTH


def test_merge_gauge_aggregations():
    snaps = []
    for v in (3.0, 9.0, 5.0):
        reg = MetricsRegistry()
        reg.gauge("res", agg="sum").set(v)
        reg.gauge("peak", agg="max").set(v)
        reg.gauge("free", agg="min").set(v)
        reg.gauge("last", agg="last").set(v)
        snaps.append(reg.snapshot())
    m = merge_snapshots(snaps)["metrics"]
    assert m["res"]["series"][0]["value"] == 17.0
    assert m["peak"]["series"][0]["value"] == 9.0
    assert m["free"]["series"][0]["value"] == 3.0
    assert m["last"]["series"][0]["value"] == 5.0


def test_merge_propagates_provenance_meta():
    """A fleet merge must not drop provenance: check_regression's
    device-mismatch guard reads snapshot meta, so agreeing keys pass
    through RAW (a bool stays a bool — ``bool("False")`` is truthy) and
    a mixed fleet joins with "|" so it matches NEITHER side's baseline."""
    def snap(device_kind, interpret_mode):
        reg = MetricsRegistry()
        reg.counter("x_total", "x").inc()
        return reg.snapshot(meta={"device_kind": device_kind,
                                  "interpret_mode": interpret_mode,
                                  "git": "abc123"})

    same = merge_snapshots([snap("cpu", False), snap("cpu", False)])
    assert same["meta"]["device_kind"] == "cpu"
    assert same["meta"]["interpret_mode"] is False   # raw, not "False"
    assert same["meta"]["git"] == "abc123"
    assert same["meta"]["merged_from"] == 2
    mixed = merge_snapshots([snap("cpu", True), snap("TPU v5e", False)])
    assert mixed["meta"]["device_kind"] == "TPU v5e|cpu"
    assert mixed["meta"]["interpret_mode"] == "False|True"


def test_histogram_poisoned_samples_do_not_break_the_snapshot():
    """NaN/inf samples are COUNTED (bucket 0 / overflow) but must not
    contaminate sum/min/max: one NaN would make the sum NaN forever and
    NaN/Infinity are not valid JSON — a single bad sample would break
    every later /metrics.json scrape."""
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "t")
    h.record(float("nan"))          # first sample: must not pin min/max
    h.record(float("inf"))
    h.record(0.5)
    state = reg.snapshot()["metrics"]["lat_seconds"]["series"][0]
    assert state["count"] == 3
    assert state["sum"] == 0.5 and state["min"] == 0.5 \
        and state["max"] == 0.5
    assert state["buckets"][str(bucket_index(0.5))] == 1
    assert state["buckets"][str(HIST_MAX_INDEX)] == 1   # inf: overflow
    assert state["buckets"]["0"] == 1                   # nan: bucket 0
    # strict-JSON serializable (RFC 8259: no NaN/Infinity literals)
    json.dumps(reg.snapshot(), allow_nan=False)


def test_merge_refuses_incompatible_snapshots():
    reg = MetricsRegistry()
    reg.counter("x", "x").inc()
    good = reg.snapshot()
    with pytest.raises(ValueError, match="schema"):
        merge_snapshots([good, {"schema": "other/v9"}])
    with pytest.raises(ValueError, match="at least one"):
        merge_snapshots([])
    other = MetricsRegistry()
    other.gauge("x", "x").set(1.0)
    with pytest.raises(ValueError, match="type mismatch"):
        merge_snapshots([good, other.snapshot()])
    hreg = MetricsRegistry()
    hreg.histogram("h", "h").record(1.0)
    a, b = hreg.snapshot(), json.loads(json.dumps(hreg.snapshot()))
    b["metrics"]["h"]["growth"] = 2.0   # somebody else's bucket scheme
    with pytest.raises(ValueError, match="geometry"):
        merge_snapshots([a, b])
    # gauge agg is the one field where merge SEMANTICS differ per
    # declaration — a cross-build mismatch must refuse like type/geometry,
    # never fold first-doc-wins under the wrong aggregation
    g1, g2 = MetricsRegistry(), MetricsRegistry()
    g1.gauge("free", "f", agg="min").set(0.5)
    g2.gauge("free", "f", agg="sum").set(0.5)
    with pytest.raises(ValueError, match="agg"):
        merge_snapshots([g1.snapshot(), g2.snapshot()])


# -------------------------------------------------------- export surfaces

def test_prometheus_text_exposition_format():
    reg = MetricsRegistry()
    reg.counter("reqs_total", "all requests",
                labels=("tenant",)).inc(3, tenant='evil"\nco')
    h = reg.histogram("lat_seconds", "latency")
    for v in (0.5, 0.5, 2.0):
        h.record(v)
    text = reg.prometheus_text()
    lines = text.splitlines()
    assert "# TYPE reqs_total counter" in lines
    # label values escaped per the exposition format
    assert r'reqs_total{tenant="evil\"\nco"} 3' in lines
    assert "# TYPE lat_seconds histogram" in lines
    # cumulative buckets, then +Inf == count, then sum/count
    bucket_lines = [l for l in lines if l.startswith("lat_seconds_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in bucket_lines]
    assert counts == sorted(counts) and counts[-1] == 3
    assert bucket_lines[-1].startswith('lat_seconds_bucket{le="+Inf"}')
    assert "lat_seconds_count 3" in lines
    assert any(l.startswith("lat_seconds_sum 3") for l in lines)
    # a merged snapshot renders through the same path
    assert snapshot_to_prometheus(merge_snapshots([reg.snapshot()])) \
        .splitlines()[0].startswith("# HELP")
    # le labels come from the SNAPSHOT'S serialized geometry, never this
    # build's constants — a capture under different lo/growth must
    # render its own bucket edges
    foreign = json.loads(json.dumps(reg.snapshot()))
    fam = foreign["metrics"]["lat_seconds"]
    fam["lo"], fam["growth"] = 1.0, 2.0
    first_idx = min(int(k) for k in fam["series"][0]["buckets"])
    text2 = snapshot_to_prometheus(foreign)
    assert f'le="{1.0 * 2.0 ** first_idx:.10g}"' in text2


def test_write_snapshot_atomic_and_bus_event(tmp_path):
    reg = MetricsRegistry()
    reg.counter("x_total", "x").inc(2)
    events = []
    # function-local import, DELIBERATELY inverted from the module-level
    # idiom above: export.py publishes through a deferred call-time
    # import (it must stay stdlib-only at import time), so after
    # test_chip_worker's mid-session sys.modules purge it publishes to
    # the FRESH bus — the subscription must resolve at call time too
    from apex_tpu.utils.logging import subscribe_events as _sub
    unsub = _sub(events.append)
    try:
        path = str(tmp_path / "snap.json")
        write_snapshot(reg, path, meta={"rank": 0})
        doc = json.loads(open(path).read())
        assert doc["schema"] == "apex_tpu.metrics/v1"
        assert doc["meta"] == {"rank": 0}
        assert not os.path.exists(path + ".tmp")   # committed, not torn
        assert [e["event"] for e in events] == ["metrics_snapshot"]
    finally:
        unsub()


def test_exporter_scrapes_text_and_json(tmp_path):
    reg = MetricsRegistry()
    reg.counter("scraped_total", "x").inc(5)
    events = []
    # call-time import: matches the exporter's deferred publish_event
    # import (see test_write_snapshot_atomic_and_bus_event)
    from apex_tpu.utils.logging import subscribe_events as _sub
    unsub = _sub(events.append)
    snap_path = str(tmp_path / "final.json")
    try:
        with MetricsExporter(reg, port=0, snapshot_path=snap_path,
                             meta={"rank": 1}) as exp:
            base = f"http://127.0.0.1:{exp.port}"
            text = urllib.request.urlopen(base + "/metrics",
                                          timeout=5).read().decode()
            assert "scraped_total 5" in text
            doc = json.loads(urllib.request.urlopen(
                base + "/metrics.json", timeout=5).read())
            assert doc["schema"] == "apex_tpu.metrics/v1"
            assert doc["meta"] == {"rank": 1}
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(base + "/nope", timeout=5)
        scrapes = [e for e in events if e["event"] == "metrics_scrape"]
        assert {e["path"] for e in scrapes} == {"/metrics",
                                               "/metrics.json"}
        # stop() committed the per-rank snapshot artifact
        final = json.loads(open(snap_path).read())
        assert final["metrics"]["scraped_total"]["series"][0]["value"] == 5
    finally:
        unsub()


# ------------------------------------------------------------ SLO tracker

def _clock():
    """Deterministic injectable clock."""
    state = {"t": 1000.0}

    def now():
        return state["t"]

    now.advance = lambda dt: state.__setitem__("t", state["t"] + dt)
    return now


def test_slo_breach_and_recovery_fire_exactly_once():
    clock = _clock()
    obj = SLObjective.shed_frac(0.1, min_events=4, short_window_s=10.0,
                                long_window_s=50.0)
    tr = SLOTracker([obj], clock=clock)
    events = []
    unsub = subscribe_events(events.append)
    try:
        for _ in range(4):
            tr.observe("shed", bad=True)
        # a sustained storm evaluated every tick raises ONE breach
        for _ in range(5):
            tr.evaluate()
            clock.advance(0.5)
        breaches = [e for e in events if e["event"] == "serve_slo_breach"]
        assert len(breaches) == 1
        assert breaches[0]["objective"] == "shed_frac"
        assert breaches[0]["burn_short"] == pytest.approx(10.0)
        # good traffic dilutes the short-window burn under the factor
        for _ in range(60):
            tr.observe("shed", bad=False)
        for _ in range(5):
            tr.evaluate()
            clock.advance(0.5)
        recs = [e for e in events if e["event"] == "serve_slo_recovered"]
        assert len(recs) == 1
        assert tr.summary()["shed_frac"]["breached"] is False
        assert tr.summary()["shed_frac"]["breaches"] == 1
    finally:
        unsub()


def test_slo_min_events_and_window_pruning():
    clock = _clock()
    obj = SLObjective.deadline_miss_frac(0.5, min_events=8,
                                         short_window_s=10.0,
                                         long_window_s=50.0)
    tr = SLOTracker([obj], clock=clock)
    for _ in range(7):
        tr.observe("deadline", bad=True)
    # burning hot, but below min_events: one bad tick must not page
    assert tr.evaluate() == []
    assert tr.summary()["deadline_miss_frac"]["breached"] is False
    # events age out of the short window (totals prune with them)
    clock.advance(11.0)
    tr.evaluate()
    s = tr.summary()["deadline_miss_frac"]
    assert s["short_events"] == 0 and s["long_events"] == 7


def test_slo_latency_objective_classifies_against_threshold():
    clock = _clock()
    tr = SLOTracker([SLObjective.ttft_p99_ms(50.0, min_events=2,
                                             short_window_s=10.0,
                                             long_window_s=50.0)],
                    clock=clock)
    tr.observe("ttft", value=0.010)    # under 50ms: good
    tr.observe("ttft", value=0.500)    # over: bad
    tr.observe("ttft", bad=True)       # verdict-only: no latency, skipped
    s = tr.summary()["ttft_p99_ms"]
    assert s["short_events"] == 2
    assert s["burn_short"] == pytest.approx(0.5 / 0.01)


def test_slo_validation_and_spec_parsing():
    with pytest.raises(ValueError, match="source"):
        SLObjective(name="x", source="nope", bad_frac_budget=0.1)
    with pytest.raises(ValueError, match="bad_frac_budget"):
        SLObjective(name="x", source="shed", bad_frac_budget=0.0)
    with pytest.raises(ValueError, match="window"):
        SLObjective(name="x", source="shed", bad_frac_budget=0.1,
                    short_window_s=60.0, long_window_s=60.0)
    # a zero/negative span would prune every event per evaluate() —
    # armed but structurally inert (breach can never fire): refuse loudly
    with pytest.raises(ValueError, match="positive"):
        SLObjective(name="x", source="shed", bad_frac_budget=0.1,
                    short_window_s=0.0, long_window_s=300.0)
    with pytest.raises(ValueError, match="positive"):
        parse_slo_specs(["shed_frac=0.1"], short_window_s=-5.0,
                        long_window_s=300.0)
    with pytest.raises(ValueError, match="duplicate"):
        SLOTracker([SLObjective.shed_frac(0.1),
                    SLObjective.shed_frac(0.2)])
    objs = parse_slo_specs(["ttft_p99_ms=50", "shed_frac=0.1"],
                           short_window_s=5.0, long_window_s=25.0)
    assert [o.name for o in objs] == ["ttft_p99_ms", "shed_frac"]
    assert objs[0].threshold_s == pytest.approx(0.050)
    assert objs[0].short_window_s == 5.0
    for bad in ("nope=1", "ttft_p99_ms", "shed_frac=zero",
                "shed_frac=-1"):
        with pytest.raises(ValueError):
            parse_slo_specs([bad])


# ------------------------------------------------- training-side registry

def test_telemetry_records_into_registry():
    reg = MetricsRegistry()
    from apex_tpu.monitor import Telemetry

    tel = Telemetry(None, goodput=False, mirror_events=False,
                    registry=reg)
    try:
        tel.log_step(0, step_ms=10.0)
        tel.log_step(1, step_ms=20.0, skipped=True)
    finally:
        tel.close()
    assert reg.counter("train_steps_total").labels().value == 2
    assert reg.counter("train_skipped_steps_total").labels().value == 1
    h = reg.histogram("train_step_seconds").labels()
    assert h.count == 2 and h.sum == pytest.approx(0.030)


# --------------------------------------------------------- tools: the CLI

def test_metrics_merge_cli_equals_union(tmp_path):
    rng = np.random.RandomState(5)
    paths, all_vals = [], []
    for r in range(2):
        reg = MetricsRegistry()
        vals = [float(v) for v in np.exp(
            rng.uniform(np.log(1e-3), np.log(2.0), 25))]
        all_vals.extend(vals)
        h = reg.histogram("lat_seconds", "t")
        for v in vals:
            h.record(v)
        reg.counter("done_total", "d").inc(len(vals))
        p = str(tmp_path / f"rank{r}.json")
        write_snapshot(reg, p, meta={"rank": r})
        paths.append(p)
    out = str(tmp_path / "fleet.json")
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "metrics_merge.py"),
         *paths, "-o", out], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    union = MetricsRegistry()
    uh = union.histogram("lat_seconds", "t")
    for v in all_vals:
        uh.record(v)
    union.counter("done_total", "d").inc(len(all_vals))
    merged = json.loads(open(out).read())
    want = union.snapshot()
    assert merged["metrics"]["done_total"]["series"][0]["value"] == 50
    assert merged["metrics"]["lat_seconds"]["series"][0]["buckets"] \
        == want["metrics"]["lat_seconds"]["series"][0]["buckets"]
    # --prometheus renders the merged view through the shared formatter
    r2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "metrics_merge.py"),
         *paths, "--prometheus"], capture_output=True, text=True)
    assert r2.returncode == 0 and "done_total 50" in r2.stdout
    # a non-snapshot input is a usage error, never a fabricated fleet view
    bad = str(tmp_path / "bad.json")
    open(bad, "w").write('{"schema": "other"}')
    r3 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "metrics_merge.py"),
         paths[0], bad], capture_output=True, text=True)
    assert r3.returncode == 2 and "schema" in r3.stderr


def test_check_regression_gates_snapshots_directly(tmp_path):
    from tools.check_regression import main as gate

    def snap(path, ttft_scale, rejected):
        reg = MetricsRegistry()
        sm = ServeMetrics(reg)
        for i in range(20):
            sm.submitted.inc(tenant=f"t{i % 2}")
            sm.ttft.record(0.010 * ttft_scale, tenant=f"t{i % 2}")
        for _ in range(rejected):
            sm.submitted.inc(tenant="t0")
            sm.rejected.inc(tenant="t0")
        write_snapshot(reg, path)

    base = str(tmp_path / "base.json")
    same = str(tmp_path / "same.json")
    worse = str(tmp_path / "worse.json")
    snap(base, 1.0, 0)
    snap(same, 1.0, 0)
    snap(worse, 4.0, 5)      # 4x TTFT and a 5/25 shed_frac
    assert gate([same, base]) == 0
    assert gate([worse, base]) == 1
    # direction hints: ttft_p99_ms regresses as lower-is-better, and
    # shed_frac's 0 -> N move gates even from the zero baseline (the
    # _frac higher-is-better family must NOT claim it)
    from tools.check_regression import (load_metrics, lower_is_better)
    cur = load_metrics(worse, warmup=0)
    assert "ttft_p99_ms" in cur and "shed_frac" in cur
    assert cur["shed_frac"][0] == pytest.approx(5 / 25)
    assert lower_is_better("shed_frac")
    assert lower_is_better("deadline_miss_frac")
    assert not lower_is_better("prefix_hit_frac")
    # more mid-stream evictions is strictly worse — without the hint a
    # 0 -> N eviction storm would gate as an improvement
    assert lower_is_better("serve_requests_evicted_total")
    # the snapshot quantile rule is LOADED from monitor.export, never a
    # second spelling that could silently diverge from the exporter's
    from tools.check_regression import _export_module
    assert _export_module().histogram_quantile is not None
    # only *_seconds histograms become _p50_ms/_p99_ms: a token-count
    # distribution scaled by 1e3 and forced lower-is-better via the ms
    # unit would gate silently wrong in value AND direction
    from tools.check_regression import metrics_from_snapshot
    nreg = MetricsRegistry()
    nreg.histogram("prompt_tokens", "not a latency").record(128.0)
    nreg.histogram("wait_seconds", "a latency").record(0.5)
    derived = metrics_from_snapshot(nreg.snapshot())
    assert "wait_p99_ms" in derived
    assert not any(k.startswith("prompt_tokens") for k in derived)


def test_serve_cli_inapplicable_metric_flags_are_usage_errors(capsys):
    """Silently ignoring a metrics/SLO spec would leave the user
    believing it is configured: --slo-window with no --slo objective,
    and --tenants with --stdin (stdin lines carry no tenant identity),
    both exit 2 with the fix spelled out."""
    from apex_tpu.serve.cli import main
    assert main(["--slo-window", "30:150", "--requests", "1"]) == 2
    assert "--slo-window needs" in capsys.readouterr().err
    assert main(["--stdin", "--tenants", "4"]) == 2
    assert "--tenants" in capsys.readouterr().err
    # an unbindable port fails in milliseconds with exit 2 — BEFORE the
    # engine pays for params + compiles, never a raw OSError traceback
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        taken = s.getsockname()[1]
        assert main(["--requests", "1",
                     "--metrics-port", str(taken)]) == 2
    assert "cannot bind" in capsys.readouterr().err
    # bench: --tenants without a metrics surface is armed-but-inert —
    # the labels reach no observable output; refuse loudly (and cheaply:
    # before the engine builds)
    from apex_tpu.bench_cli import _serve_bench
    with pytest.raises(SystemExit, match="tenants"):
        _serve_bench(steps=1, tenants=2)


# ---------------------------------------------- live serving e2e (serve)

@pytest.mark.serve
def test_live_scrape_during_decode_reconciles_with_exact_summary(engine3):
    """THE acceptance e2e: scrape a RUNNING serve loop over HTTP; the
    per-tenant counters sum to the scheduler's exact end-of-run summary,
    histogram p50/p99 match the exact sorted-list percentiles within the
    documented bucket error — and decode still compiled exactly once."""
    eng = engine3.reset()
    t0 = eng.decode_traces
    metrics = ServeMetrics()
    sched = ServeScheduler(eng, metrics=metrics)
    tenants = [None, "acme", "acme", "globex", None, "acme"]
    for i, tenant in enumerate(tenants):
        sched.submit(Request(request_id=f"r{i}", tokens=_tokens(6, i),
                             max_new_tokens=4, tenant=tenant))
    with MetricsExporter(metrics.registry, port=0) as exp:
        # a few ticks in, requests still in flight: scrape LIVE
        for _ in range(3):
            sched.step()
        base = f"http://127.0.0.1:{exp.port}"
        live_text = urllib.request.urlopen(base + "/metrics",
                                           timeout=5).read().decode()
        live = json.loads(urllib.request.urlopen(base + "/metrics.json",
                                                 timeout=5).read())
        while sched.step():
            pass
    assert 'serve_requests_admitted_total{tenant="acme"}' in live_text
    live_admitted = sum(s["value"] for s in
                        live["metrics"]["serve_requests_admitted_total"]
                        ["series"])
    assert 0 < live_admitted <= 6          # mid-run view, monotonic
    assert eng.decode_traces == 1          # scrapes never touched the jit

    stats = sched.stats()
    s = stats.summary()
    snap = metrics.registry.snapshot()

    def total(name):
        return sum(x["value"]
                   for x in snap["metrics"][name].get("series", []))

    assert total("serve_requests_submitted_total") == s["requests"] == 6
    assert total("serve_requests_completed_total") == s["completed"] == 6
    assert total("serve_requests_rejected_total") == s["rejected"] == 0
    assert total("serve_deadline_exceeded_total") \
        == s["deadline_exceeded"] == 0
    assert total("serve_generated_tokens_total") == s["new_tokens"]
    # per-tenant split is what was submitted per tenant
    by_tenant = {x["labels"]["tenant"]: x["value"] for x in
                 snap["metrics"]["serve_requests_completed_total"]
                 ["series"]}
    assert by_tenant == {"default": 2.0, "acme": 3.0, "globex": 1.0}
    # streaming TTFT quantiles vs the exact oracle, within the bound
    hist = snap["metrics"]["serve_ttft_seconds"]["series"]
    buckets, count = {}, 0
    for x in hist:
        count += x["count"]
        for k, n in x["buckets"].items():
            buckets[int(k)] = buckets.get(int(k), 0) + n
    exact_ttfts = [r["ttft_s"] for r in stats.requests if "ttft_s" in r]
    assert count == len(exact_ttfts) == 6
    for p, field in ((0.50, "ttft_p50_ms"), (0.99, "ttft_p99_ms")):
        exact = s[field] / 1e3
        est = histogram_quantile(buckets, count, p)
        assert exact <= est * 1.001 and est <= exact * HIST_GROWTH * 1.001
    # the compact live summary agrees too
    assert metrics.summary()["totals"][
        "serve_requests_completed_total"] == 6


def test_terminal_requests_with_first_token_are_ttft_witnesses():
    """A request that reached its first token and THEN expired (or was
    evicted) witnessed a TTFT the exact summary counts — the histogram
    and the ttft SLO stream must count it too, or under deadline
    pressure the live p99 reads systematically better than the oracle
    (the worst TTFTs are exactly the requests that die by deadline)."""
    import types

    slo = SLOTracker([SLObjective.ttft_p99_ms(
        1e-6, min_events=1, burn_factor=1.0)])
    sm = ServeMetrics(slo=slo)
    dead = types.SimpleNamespace(tenant="t0", generated=[1, 2],
                                 ttft_s=0.5, latency_s=0.9)
    sm.on_deadline(dead)
    sm.on_evict(dead, "aborted")
    fam = sm.registry.snapshot()["metrics"]["serve_ttft_seconds"]
    assert fam["series"][0]["count"] == 2       # both witnessed
    slo.evaluate()
    state = slo.summary()["ttft_p99_ms"]
    assert state["short_events"] == 2 and state["breached"]


def test_every_terminal_status_feeds_every_fraction_window_once():
    """The live fraction denominators must match the documented
    objectives (deadline_miss_frac over TERMINAL requests, shed_frac
    over everything that asked): one completion, one rejection, one
    deadline miss, one eviction → each window holds 4 events with
    exactly one bad. Before this, rejected/evicted requests fed no
    deadline event, so 60 rejections + 10 misses read as 10/40 = the
    budget and paged the operator while the true miss frac held."""
    import types

    slo = SLOTracker([
        SLObjective.deadline_miss_frac(0.5, min_events=100),
        SLObjective.shed_frac(0.5, min_events=100)])
    sm = ServeMetrics(slo=slo)
    req = types.SimpleNamespace(tenant=None, generated=[1],
                                ttft_s=0.01, latency_s=0.02)
    sm.on_complete(req)
    sm.on_reject(req, "queue_full")
    sm.on_deadline(req)
    sm.on_evict(req, "aborted")
    slo.evaluate()
    state = slo.summary()
    for name, bad_frac in (("deadline_miss_frac", 0.25),
                           ("shed_frac", 0.25)):
        assert state[name]["short_events"] == 4, (name, state[name])
        assert state[name]["burn_short"] == pytest.approx(
            bad_frac / 0.5), (name, state[name])


@pytest.mark.serve
def test_final_tick_completions_reach_the_exit_slo_state(engine3):
    """Completions landing on the LAST decode tick must feed that tick's
    evaluate(): with a one-request run whose only completion is the
    final tick's, the breach must publish before run() exits and the
    exit snapshot's breached gauge must reflect it (the tick used to
    evaluate BEFORE the accept loop, leaving the exit state one tick
    stale and the breach unpublished)."""
    eng = engine3.reset()
    slo = SLOTracker([SLObjective.ttft_p99_ms(
        1e-6, min_events=1, burn_factor=1.0)])   # any real TTFT is bad
    metrics = ServeMetrics(slo=slo)
    sched = ServeScheduler(eng, metrics=metrics)
    events = []
    unsub = subscribe_events(events.append)
    try:
        sched.submit(Request(request_id="only", tokens=_tokens(4),
                             max_new_tokens=2))
        sched.run()
    finally:
        unsub()
    assert [e["event"] for e in events
            if e["event"].startswith("serve_slo")] == ["serve_slo_breach"]
    g = metrics.registry.gauge("serve_slo_breached").labels(
        objective="ttft_p99_ms")
    assert g.value == 1.0


@pytest.mark.serve
def test_deadline_storm_raises_exactly_one_breach_recovery_pair(engine3):
    """An induced deadline storm (queued requests expiring with ZERO
    decode steps run — the idle-tick path) breaches once; draining it
    with good traffic recovers once. Never a flap per tick."""
    eng = engine3.reset()
    t0 = eng.decode_traces
    slo = SLOTracker([SLObjective.deadline_miss_frac(
        0.5, min_events=8, burn_factor=1.0)])
    metrics = ServeMetrics(slo=slo)
    sched = ServeScheduler(eng, metrics=metrics)
    events = []
    unsub = subscribe_events(events.append)
    try:
        # the storm: already-expired deadlines, swept before admission
        for i in range(8):
            sched.submit(Request(request_id=f"dead{i}",
                                 tokens=_tokens(4, i),
                                 max_new_tokens=4, deadline_ms=1e-3))
        for _ in range(4):          # several evaluations of one storm
            sched.step()
        assert eng.decode_traces == t0  # breached with zero decode steps
        # the drain: good traffic dilutes the short-window burn
        for i in range(10):
            sched.submit(Request(request_id=f"ok{i}",
                                 tokens=_tokens(4, 100 + i),
                                 max_new_tokens=2))
        while sched.step():
            pass
    finally:
        unsub()
    names = [e["event"] for e in events
             if e["event"].startswith("serve_slo")]
    assert names == ["serve_slo_breach", "serve_slo_recovered"]
    breach = next(e for e in events if e["event"] == "serve_slo_breach")
    assert breach["objective"] == "deadline_miss_frac"
    assert breach["burn_short"] >= 1.0
    s = sched.stats().summary()
    assert s["deadline_exceeded"] == 8 and s["completed"] == 10
    assert eng.decode_traces == 1        # metrics+SLO stayed off the jit
    # the burn gauges mirrored the live state per tick
    g = metrics.registry.gauge("serve_slo_breached").labels(
        objective="deadline_miss_frac")
    assert g.value == 0.0                # recovered by the end
