"""Optimizer parity harness — the TPU port of
``tests/L0/run_optimizers/test_fused_optimizer.py``: run the fused optimizer vs
the reference implementation (torch.optim on CPU) on identical params/grads and
assert closeness per step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.optimizers import (FusedAdagrad, FusedAdam, FusedLAMB,
                                 FusedMixedPrecisionLamb, FusedNovoGrad,
                                 FusedSGD)

SHAPES = [(37,), (4, 11), (64, 3, 3)]
STEPS = 5


def _make_params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), len(SHAPES))
    return [jax.random.normal(k, s, jnp.float32) for k, s in zip(ks, SHAPES)]


def _make_grads(step, seed=100):
    ks = jax.random.split(jax.random.PRNGKey(seed + step), len(SHAPES))
    return [jax.random.normal(k, s, jnp.float32) for k, s in zip(ks, SHAPES)]


def _to_torch(params):
    return [torch.nn.Parameter(torch.tensor(np.asarray(p))) for p in params]


def _assert_close(jax_params, torch_params, tol=1e-5):
    for jp, tp in zip(jax_params, torch_params):
        np.testing.assert_allclose(np.asarray(jp),
                                   tp.detach().numpy(), atol=tol, rtol=tol)


def _run_torch(opt, tparams, steps=STEPS):
    for step in range(1, steps + 1):
        grads = _make_grads(step)
        for p, g in zip(tparams, grads):
            p.grad = torch.tensor(np.asarray(g))
        opt.step()


class TestFusedAdam:
    @pytest.mark.parametrize("adam_w,wd", [(True, 0.0), (True, 0.01),
                                           (False, 0.0), (False, 0.01)])
    def test_vs_torch(self, adam_w, wd):
        params = _make_params()
        opt = FusedAdam(params, lr=1e-3, weight_decay=wd, adam_w_mode=adam_w)
        tparams = _to_torch(params)
        cls = torch.optim.AdamW if adam_w else torch.optim.Adam
        topt = cls(tparams, lr=1e-3, weight_decay=wd, eps=1e-8)
        for step in range(1, STEPS + 1):
            opt.step(_make_grads(step))
        _run_torch(topt, tparams)
        _assert_close(opt.parameters, tparams)

    def test_flat_pallas_path_matches_tree(self):
        params = _make_params()
        o1 = FusedAdam(params, lr=1e-3, weight_decay=0.01)
        o2 = FusedAdam(params, lr=1e-3, weight_decay=0.01, use_flat=True)
        for step in range(1, 4):
            g = _make_grads(step)
            o1.step(g)
            o2.step(g)
        for a, b in zip(o1.parameters, o2.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-6, rtol=2e-6)

    def test_found_inf_skips_step(self):
        params = _make_params()
        opt = FusedAdam(params, lr=1e-3)
        before = [np.asarray(p) for p in params]
        opt.step(_make_grads(1), found_inf=True)
        for b, a in zip(before, opt.parameters):
            np.testing.assert_array_equal(b, np.asarray(a))

    def test_overflow_steps_do_not_advance_bias_correction(self):
        """Reference semantics: the step counter advances only on applied
        steps (fused_adam.py:181), so early-overflow runs keep bc1 correct."""
        params = _make_params()
        o1 = FusedAdam(params, lr=1e-3)
        o2 = FusedAdam(params, lr=1e-3)
        for _ in range(10):  # ten skipped (overflow) steps on o2
            o2.step(_make_grads(99), found_inf=True)
        g = _make_grads(1)
        o1.step(g)
        o2.step(g)
        assert int(o1._step) == 1 and int(o2._step) == 1
        for a, b in zip(o1.parameters, o2.parameters):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_caller_held_params_survive_next_step(self):
        """step() must not donate buffers the caller may still hold."""
        params = _make_params()
        opt = FusedAdam(params, lr=1e-3)
        snapshot = opt.step(_make_grads(1))
        opt.step(_make_grads(2))
        _ = [np.asarray(p) for p in snapshot]  # must not raise

    def test_flat_state_dict_roundtrip(self):
        params = _make_params()
        opt = FusedAdam(params, lr=1e-3, use_flat=True)
        opt.step(_make_grads(1))
        sd = opt.state_dict()
        opt2 = FusedAdam(_make_params(seed=9), lr=1e-3, use_flat=True)
        opt2.load_state_dict(sd)
        g = _make_grads(2)
        opt.step(g)
        opt2.step(g)
        for a, b in zip(opt.parameters, opt2.parameters):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_inv_scale(self):
        params = _make_params()
        o1 = FusedAdam(params, lr=1e-3)
        o2 = FusedAdam(params, lr=1e-3)
        g = _make_grads(1)
        o1.step(g)
        o2.step([x * 128.0 for x in g], inv_scale=1.0 / 128.0)
        for a, b in zip(o1.parameters, o2.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_master_weights_bf16(self):
        params32 = _make_params()
        params16 = [p.astype(jnp.bfloat16) for p in params32]
        opt = FusedAdam(params16, lr=1e-2, master_weights=True)
        # torch reference starts from the same bf16-rounded values the
        # master copy is initialized from
        tparams = _to_torch([p.astype(jnp.float32) for p in params16])
        topt = torch.optim.AdamW(tparams, lr=1e-2, weight_decay=0.0, eps=1e-8)
        for step in range(1, STEPS + 1):
            opt.step(_make_grads(step))
        _run_torch(topt, tparams)
        # master fp32 weights track torch closely; bf16 copy to bf16 precision
        _assert_close(opt.master_parameters, tparams, tol=1e-5)
        for jp, tp in zip(opt.parameters, tparams):
            assert jp.dtype == jnp.bfloat16
            np.testing.assert_allclose(np.asarray(jp, np.float32),
                                       tp.detach().numpy(), atol=2e-2,
                                       rtol=2e-2)

    def test_amsgrad_raises(self):
        with pytest.raises(RuntimeError):
            FusedAdam(_make_params(), amsgrad=True)


class TestFusedSGD:
    @pytest.mark.parametrize("momentum,dampening,nesterov,wd",
                             [(0.0, 0.0, False, 0.0),
                              (0.9, 0.0, False, 0.0),
                              (0.9, 0.0, True, 0.0),
                              (0.9, 0.1, False, 0.01),
                              (0.9, 0.0, False, 1e-4)])
    def test_vs_torch(self, momentum, dampening, nesterov, wd):
        params = _make_params()
        opt = FusedSGD(params, lr=0.1, momentum=momentum, dampening=dampening,
                       nesterov=nesterov, weight_decay=wd)
        tparams = _to_torch(params)
        topt = torch.optim.SGD(tparams, lr=0.1, momentum=momentum,
                               dampening=dampening, nesterov=nesterov,
                               weight_decay=wd)
        for step in range(1, STEPS + 1):
            opt.step(_make_grads(step))
        _run_torch(topt, tparams)
        _assert_close(opt.parameters, tparams)


class TestFusedAdagrad:
    @pytest.mark.parametrize("wd", [0.0, 0.01])
    def test_vs_torch(self, wd):
        params = _make_params()
        opt = FusedAdagrad(params, lr=0.1, eps=1e-10, weight_decay=wd)
        tparams = _to_torch(params)
        topt = torch.optim.Adagrad(tparams, lr=0.1, eps=1e-10,
                                   weight_decay=wd)
        for step in range(1, STEPS + 1):
            opt.step(_make_grads(step))
        _run_torch(topt, tparams)
        _assert_close(opt.parameters, tparams, tol=1e-4)


class TestFusedLAMB:
    def test_runs_and_descends(self):
        """LAMB has no torch reference; check trust-ratio update direction and
        the global-norm clip (reference test pattern: tests/L0 test_lamb.py
        builds its own python reference)."""
        params = _make_params()
        opt = FusedLAMB(params, lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
        loss0 = sum(float(jnp.sum(p * p)) for p in params)
        for step in range(1, STEPS + 1):
            # gradient of 0.5*||p||^2 is p → LAMB should shrink the params
            # (fresh buffers: params are donated into the jitted step)
            opt.step([jnp.array(np.asarray(p)) for p in opt.parameters])
        loss1 = sum(float(jnp.sum(jnp.square(p))) for p in opt.parameters)
        assert loss1 < loss0

    def test_matches_python_reference_one_step(self):
        params = [jnp.array([[1.0, 2.0], [3.0, 4.0]], jnp.float32)]
        grads = [jnp.array([[0.1, 0.2], [0.3, 0.4]], jnp.float32)]
        lr, b1, b2, eps, wd = 0.01, 0.9, 0.999, 1e-6, 0.0
        opt = FusedLAMB(params, lr=lr, betas=(b1, b2), eps=eps,
                        weight_decay=wd, max_grad_norm=10.0)
        opt.step(grads)
        # python reference (grad norm below clip → no clipping)
        g = np.asarray(grads[0])
        p = np.asarray(params[0])
        m = (1 - b1) * g
        v = (1 - b2) * g * g
        mhat = m / (1 - b1)
        vhat = v / (1 - b2)
        upd = mhat / (np.sqrt(vhat) + eps)
        ratio = np.linalg.norm(p) / np.linalg.norm(upd)
        ref = p - lr * ratio * upd
        np.testing.assert_allclose(np.asarray(opt.parameters[0]), ref,
                                   rtol=1e-5, atol=1e-6)


class TestFusedNovoGrad:
    def test_matches_python_reference(self):
        """Python reference mirrors tests/L0/run_optimizers/test_fused_novograd.py."""
        params = _make_params()
        lr, b1, b2, eps, wd = 1e-2, 0.95, 0.98, 1e-8, 0.01
        opt = FusedNovoGrad(params, lr=lr, betas=(b1, b2), eps=eps,
                            weight_decay=wd, grad_averaging=False,
                            bias_correction=False, norm_type=2)
        ref_p = [np.asarray(p) for p in params]
        ref_m = [np.zeros_like(p) for p in ref_p]
        ref_v = [0.0 for _ in ref_p]
        for step in range(1, STEPS + 1):
            grads = _make_grads(step)
            opt.step(grads)
            for i, g in enumerate(grads):
                g = np.asarray(g)
                gn2 = float((g * g).sum())
                ref_v[i] = gn2 if step == 1 else b2 * ref_v[i] + (1 - b2) * gn2
                denom = np.sqrt(ref_v[i]) + eps
                ref_m[i] = b1 * ref_m[i] + (g / denom + wd * ref_p[i])
                ref_p[i] = ref_p[i] - lr * ref_m[i]
        for a, b in zip(opt.parameters, ref_p):
            np.testing.assert_allclose(np.asarray(a), b, rtol=1e-4, atol=1e-5)


class TestFusedMixedPrecisionLamb:
    def test_low_precision_params_fp32_state(self):
        params = _make_params()
        opt = FusedMixedPrecisionLamb(params, lr=1e-2)
        for p in opt.parameters:
            assert p.dtype == jnp.bfloat16
        for m in jax.tree_util.tree_leaves(opt.state["m"]):
            assert m.dtype == jnp.float32
        opt.step(_make_grads(1))
        # master weights moved, lp params are their cast
        for lp, mw in zip(opt.parameters, opt.state["master"]):
            np.testing.assert_allclose(np.asarray(lp, np.float32),
                                       np.asarray(mw), rtol=1e-2, atol=1e-2)


class TestStateDict:
    def test_roundtrip(self):
        params = _make_params()
        opt = FusedAdam(params, lr=1e-3)
        opt.step(_make_grads(1))
        sd = opt.state_dict()
        opt2 = FusedAdam(_make_params(seed=7), lr=1e-3)
        opt2.load_state_dict(sd)
        g = _make_grads(2)
        opt.step(g)
        opt2.step(g)
        for a, b in zip(opt.parameters, opt2.parameters):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestFusedSGDFlat:
    @pytest.mark.parametrize("momentum,nesterov,wd",
                             [(0.0, False, 0.0), (0.9, False, 1e-4),
                              (0.9, True, 0.0)])
    def test_flat_pallas_matches_tree(self, momentum, nesterov, wd):
        params = _make_params()
        o1 = FusedSGD(params, lr=0.1, momentum=momentum, nesterov=nesterov,
                      weight_decay=wd)
        o2 = FusedSGD(params, lr=0.1, momentum=momentum, nesterov=nesterov,
                      weight_decay=wd, use_flat=True)
        for step in range(1, 4):
            g = _make_grads(step)
            o1.step(g)
            o2.step(g)
        for a, b in zip(o1.parameters, o2.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_flat_found_inf_noop(self):
        params = _make_params()
        opt = FusedSGD(params, lr=0.1, momentum=0.9, use_flat=True)
        before = [np.asarray(p) for p in params]
        opt.step(_make_grads(1), found_inf=True)
        for b, a in zip(before, opt.parameters):
            np.testing.assert_array_equal(b, np.asarray(a))
        # first real step still initializes the momentum buffer correctly
        opt.step(_make_grads(1))
        ref = FusedSGD(params, lr=0.1, momentum=0.9)
        ref.step(_make_grads(1))
        for a, b in zip(opt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)


class TestFusedSGDFlatMaster:
    def test_flat_master_weights_accumulate_fp32(self):
        """bf16 params + use_flat + master_weights: tiny updates below bf16
        resolution must still accumulate (in the fp32 flat master)."""
        p16 = [jnp.ones((128,), jnp.bfloat16)]
        opt = FusedSGD(p16, lr=1e-4, master_weights=True, use_flat=True)
        assert opt._flat_p.dtype == jnp.float32
        for _ in range(4):
            opt.step([jnp.full((128,), 0.5, jnp.bfloat16)])
        master = np.asarray(opt._flat_p[:128])
        np.testing.assert_allclose(master, 1.0 - 4 * 1e-4 * 0.5, rtol=1e-5)
        assert opt.parameters[0].dtype == jnp.bfloat16


class TestFlatTreeParity:
    """Flat Pallas path vs tree path bit-comparability for every optimizer
    with a flat kernel (VERDICT item 8; reference: one multi_tensor_apply
    launch over the whole list vs per-tensor math must agree)."""

    def _run_pair(self, mk, steps=4, **step_kw):
        params = _make_params()
        o_flat = mk(params, True)
        o_tree = mk(params, False)
        for s in range(1, steps + 1):
            g = _make_grads(s)
            o_flat.step(g, **step_kw)
            o_tree.step(g, **step_kw)
        for a, b in zip(o_flat.parameters, o_tree.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)
        return o_flat, o_tree

    def test_adam(self):
        self._run_pair(lambda p, f: FusedAdam(p, lr=1e-2, weight_decay=0.01,
                                              use_flat=f))

    def test_lamb(self):
        self._run_pair(lambda p, f: FusedLAMB(p, lr=1e-2, weight_decay=0.01,
                                              max_grad_norm=1.0, use_flat=f))

    def test_lamb_nvlamb_no_bias_correction(self):
        self._run_pair(lambda p, f: FusedLAMB(
            p, lr=1e-2, weight_decay=0.0, use_nvlamb=True,
            bias_correction=False, grad_averaging=False, use_flat=f))

    def test_novograd(self):
        self._run_pair(lambda p, f: FusedNovoGrad(
            p, lr=1e-2, weight_decay=0.01, use_flat=f))

    def test_novograd_init_zero_bias_correction(self):
        self._run_pair(lambda p, f: FusedNovoGrad(
            p, lr=1e-2, init_zero=True, bias_correction=True,
            grad_averaging=True, use_flat=f))

    def test_adagrad(self):
        self._run_pair(lambda p, f: FusedAdagrad(p, lr=1e-2,
                                                 weight_decay=0.01,
                                                 use_flat=f))

    def test_adagrad_w_mode(self):
        self._run_pair(lambda p, f: FusedAdagrad(
            p, lr=1e-2, weight_decay=0.01, adagrad_w_mode=True, use_flat=f))

    def test_found_inf_noop_flat(self):
        params = _make_params()
        for mk in (lambda p: FusedLAMB(p, use_flat=True),
                   lambda p: FusedNovoGrad(p, use_flat=True),
                   lambda p: FusedAdagrad(p, use_flat=True)):
            opt = mk(params)
            before = [np.asarray(p) for p in opt.parameters]
            opt.step(_make_grads(1), found_inf=True)
            for b, a in zip(before, opt.parameters):
                np.testing.assert_array_equal(b, np.asarray(a))
            assert int(opt._step) == 0

    def test_lamb_loss_scale_unscale(self):
        params = _make_params()
        o1 = FusedLAMB(params, lr=1e-2, use_flat=True)
        o2 = FusedLAMB(params, lr=1e-2, use_flat=True)
        g = _make_grads(1)
        o1.step(g)
        o2.step([x * 64.0 for x in g], inv_scale=1.0 / 64.0)
        for a, b in zip(o1.parameters, o2.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)


class TestFlatResume:
    """load_state_dict must refresh the flat master buffer (review-found
    stale-_flat_p resume bug) and accept tree-path checkpoints."""

    @pytest.mark.parametrize("mk", [
        lambda p, f: FusedAdagrad(p, lr=1e-2, use_flat=f),
        lambda p, f: FusedNovoGrad(p, lr=1e-2, use_flat=f),
        lambda p, f: FusedLAMB(p, lr=1e-2, use_flat=f),
        lambda p, f: FusedAdam(p, lr=1e-2, use_flat=f),
    ], ids=["adagrad", "novograd", "lamb", "adam"])
    def test_flat_resume_matches_source(self, mk):
        src = mk(_make_params(), True)
        src.step(_make_grads(1))
        dst = mk(_make_params(seed=9), True)
        dst.load_state_dict(src.state_dict())
        g = _make_grads(2)
        src.step(g)
        dst.step(g)
        for a, b in zip(src.parameters, dst.parameters):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("mk", [
        lambda p, f: FusedAdagrad(p, lr=1e-2, use_flat=f),
        lambda p, f: FusedNovoGrad(p, lr=1e-2, use_flat=f),
        lambda p, f: FusedLAMB(p, lr=1e-2, use_flat=f),
        lambda p, f: FusedAdam(p, lr=1e-2, use_flat=f),
    ], ids=["adagrad", "novograd", "lamb", "adam"])
    def test_tree_checkpoint_loads_into_flat(self, mk):
        src = mk(_make_params(), False)  # tree path
        src.step(_make_grads(1))
        dst = mk(_make_params(seed=9), True)  # flat path
        dst.load_state_dict(src.state_dict())
        g = _make_grads(2)
        src.step(g)
        dst.step(g)
        for a, b in zip(src.parameters, dst.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)
