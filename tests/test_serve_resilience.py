"""Serving resilience tier-1: deadlines, admission control / load
shedding, graceful degradation, and crash-recovering warm restart.

THE chaos invariant under test (ISSUE 8 acceptance): under any seeded
``FaultInjector`` schedule — decode-step crashes, latency spikes, queue
storms, deadlines, bounded queues — **every submitted request reaches
exactly one terminal status** (completed / evicted / aborted / rejected /
deadline-exceeded), no request is ever silently lost, surviving slots'
greedy outputs stay bit-identical to an uncrashed run, and
``Engine.decode_traces`` does not grow across a ``recover()`` (the
compiled executables are reused, never retraced).

Engines are compiled once per geometry and shared across tests via
``Engine.reset()`` (the PR-5 contract); trace-counter assertions use
before/after deltas so sharing stays airtight.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.monitor.goodput import GoodputLedger
from apex_tpu.resilience.fault_injection import (FaultInjector,
                                                 SimulatedCrash)
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.resilience import (SHED_POLICIES, AdmissionController,
                                       ServeSupervisor, TickJournal)
from apex_tpu.serve.scheduler import (TERMINAL_STATES, Request,
                                      ServeScheduler)
# bound at collection time: test_chip_worker purges apex_tpu.* from
# sys.modules mid-session, and a function-local re-import after that
# would subscribe to a FRESH bus while the (old) scheduler module keeps
# publishing to the original one
from apex_tpu.utils.logging import subscribe_events

pytestmark = [pytest.mark.serve, pytest.mark.fault]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=2,
                 n_head=2, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_gpt2_params(CFG, seed=0)


@pytest.fixture(scope="module")
def greedy2(params):
    """Shared greedy 2-slot engine; tests reset() it — compiled once."""
    return Engine(CFG, params,
                  EngineConfig(num_slots=2, max_len=32, temperature=0.0),
                  seed=0)


def _tokens(n, seed=7, vocab=97):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, vocab, n)]


def _requests(n=4, max_new=6, **kw):
    return [Request(request_id=f"r{i}", tokens=_tokens(5, seed=i),
                    max_new_tokens=max_new, **kw) for i in range(n)]


def _assert_exactly_one_terminal(sched, expected_ids):
    """The chaos invariant: every submitted id has exactly one record,
    every record is terminal, nothing extra, nothing in flight."""
    recs = sched.stats().requests
    ids = [r["request_id"] for r in recs]
    assert sorted(ids) == sorted(expected_ids), \
        (sorted(set(expected_ids) - set(ids)),
         sorted(set(ids) - set(expected_ids)))
    assert len(ids) == len(set(ids)), "a request was accounted twice"
    for r in recs:
        assert r["state"] in TERMINAL_STATES, r
    assert not sched.queue and all(s is None for s in sched.slots)


# ------------------------------------------------------------- deadlines

def test_deadline_expires_queued_and_running(greedy2):
    """A latency spike pushes a running request past its budget; a
    queued-but-never-admitted request times out too. Both land as
    terminal deadline records with the lost time charged to the ledger."""
    inj = FaultInjector(seed=0).latency_spike(1, 0.25)
    sched = ServeScheduler(greedy2.reset(), fault_injector=inj)
    sched.submit(Request(request_id="slow", tokens=_tokens(5),
                         max_new_tokens=20))
    sched.submit(Request(request_id="tight", tokens=_tokens(5, seed=1),
                         max_new_tokens=20, deadline_ms=100.0))
    sched.submit(Request(request_id="waiting", tokens=_tokens(5, seed=2),
                         max_new_tokens=4, deadline_ms=50.0))
    with GoodputLedger() as led:
        stats = sched.run()
    recs = {r["request_id"]: r for r in stats.requests}
    assert recs["slow"]["state"] == "completed"
    for rid in ("tight", "waiting"):
        assert recs[rid]["state"] == "evicted"
        assert recs[rid]["finish_reason"] == "deadline"
    g = led.summary()
    assert g["events"]["serve_deadline_exceeded"] == 2
    # the whole submit-to-expiry span is a counted loss cause
    assert g["lost_by_cause"]["serve_deadline_exceeded"] > 0.1
    s = stats.summary()
    assert s["deadline_exceeded"] == 2 and s["completed"] == 1
    _assert_exactly_one_terminal(sched, ["slow", "tight", "waiting"])


def test_generous_deadline_never_fires(greedy2):
    sched = ServeScheduler(greedy2.reset())
    for r in _requests(3, deadline_ms=60_000.0):
        sched.submit(r)
    stats = sched.run()
    assert all(r["state"] == "completed" for r in stats.requests)
    assert stats.summary()["deadline_exceeded"] == 0


# ----------------------------------------------------- admission control

def test_reject_newest_bounds_the_backlog(greedy2):
    adm = AdmissionController(max_queue=2, shed_policy="reject-newest")
    sched = ServeScheduler(greedy2.reset(), admission=adm)
    with GoodputLedger() as led:
        verdicts = [sched.submit(r) for r in _requests(5)]
        stats = sched.run()
    assert verdicts == [True, True, False, False, False]
    recs = {r["request_id"]: r for r in stats.requests}
    for rid in ("r2", "r3", "r4"):
        assert recs[rid]["state"] == "rejected"
        assert recs[rid]["finish_reason"] == "queue_full"
        assert recs[rid]["retriable"] is True
    assert recs["r0"]["state"] == recs["r1"]["state"] == "completed"
    g = led.summary()
    assert g["events"]["serve_request_rejected"] == 3
    assert "serve_rejected" in g["lost_by_cause"]
    assert stats.summary()["shed_rate"] == pytest.approx(3 / 5)
    _assert_exactly_one_terminal(sched, [f"r{i}" for i in range(5)])


def test_shed_oldest_evicts_the_longest_waiter(greedy2):
    adm = AdmissionController(max_queue=2, shed_policy="shed-oldest")
    sched = ServeScheduler(greedy2.reset(), admission=adm)
    verdicts = [sched.submit(r) for r in _requests(4)]
    assert verdicts == [True, True, True, True]   # newest always admitted
    stats = sched.run()
    recs = {r["request_id"]: r for r in stats.requests}
    # r0/r1 (oldest queued) were shed to make room for r2/r3
    for rid in ("r0", "r1"):
        assert recs[rid]["state"] == "rejected"
        assert recs[rid]["finish_reason"] == "shed"
    for rid in ("r2", "r3"):
        assert recs[rid]["state"] == "completed"


def test_priority_sheds_strictly_lower_priority_only(greedy2):
    adm = AdmissionController(max_queue=2, shed_policy="priority")
    sched = ServeScheduler(greedy2.reset(), admission=adm)
    lo = Request(request_id="lo", tokens=_tokens(5), max_new_tokens=3,
                 priority=0)
    mid = Request(request_id="mid", tokens=_tokens(5, seed=1),
                  max_new_tokens=3, priority=1)
    hi = Request(request_id="hi", tokens=_tokens(5, seed=2),
                 max_new_tokens=3, priority=2)
    peer = Request(request_id="peer", tokens=_tokens(5, seed=3),
                   max_new_tokens=3, priority=0)
    assert sched.submit(lo) and sched.submit(mid)
    assert sched.submit(hi)             # sheds lo (lowest priority)
    assert lo.state == "rejected" and lo.finish_reason == "shed"
    assert not sched.submit(peer)       # no strictly-lower victim left
    assert peer.finish_reason == "priority"
    stats = sched.run()
    recs = {r["request_id"]: r for r in stats.requests}
    assert recs["mid"]["state"] == recs["hi"]["state"] == "completed"
    _assert_exactly_one_terminal(sched, ["lo", "mid", "hi", "peer"])


def test_shed_policy_validation():
    with pytest.raises(ValueError, match="shed_policy"):
        AdmissionController(max_queue=1, shed_policy="drop-table")
    with pytest.raises(ValueError, match="max_queue"):
        AdmissionController(max_queue=0)
    assert set(SHED_POLICIES) == {"reject-newest", "shed-oldest",
                                  "priority"}


# -------------------------------------------------- graceful degradation

def test_degraded_mode_clamps_admitted_budgets(greedy2):
    """A queue storm holding the backlog at the high watermark flips
    degraded mode; requests admitted while degraded get their token
    budget clamped; the mode clears once the queue drains — both
    transitions on the bus. (``sustain_ticks=1`` here so the clear is
    observable before the drained loop goes idle; the sustained-overload
    hysteresis is unit-tested below.)"""
    adm = AdmissionController(max_queue=8, queue_high=2, sustain_ticks=1,
                              degraded_max_new_tokens=1)
    inj = FaultInjector(seed=3).queue_storm(0, 6, prompt_len=4,
                                            max_new_tokens=8)
    sched = ServeScheduler(greedy2.reset(), fault_injector=inj,
                           admission=adm)
    sched.submit(Request(request_id="warm", tokens=_tokens(4),
                         max_new_tokens=8))
    with GoodputLedger() as led:
        stats = sched.run()
    g = led.summary()
    assert g["events"]["serve_degraded_mode"] == 2   # entered + cleared
    recs = {r["request_id"]: r for r in stats.requests}
    # requests admitted under degradation finished after ONE token (the
    # clamp); the backlog pressure is what drove it there
    clamped = [r for r in recs.values()
               if r["state"] == "completed" and r["new_tokens"] == 1]
    assert clamped, "no request was ever clamped"
    assert not adm.degraded                          # cleared at drain
    _assert_exactly_one_terminal(
        sched, ["warm"] + [f"storm-{i}" for i in range(6)])


def test_degraded_mode_requires_sustained_overload():
    """The hysteresis contract: a one-tick spike never flips the mode in
    either direction — only ``sustain_ticks`` CONSECUTIVE overloaded
    (resp. calm) ticks do."""
    adm = AdmissionController(max_queue=8, queue_high=4, sustain_ticks=3,
                              degraded_max_new_tokens=2)
    assert adm.on_tick(5) is None and adm.on_tick(5) is None
    assert adm.on_tick(0) is None          # spike broken: counter resets
    assert adm.on_tick(5) is None and adm.on_tick(5) is None
    assert adm.on_tick(5) is True and adm.degraded
    assert adm.clamp(16) == 2
    assert adm.on_tick(0) is None and adm.on_tick(0) is None
    assert adm.on_tick(5) is None          # calm streak broken
    assert adm.degraded
    assert adm.on_tick(0) is None and adm.on_tick(0) is None
    assert adm.on_tick(0) is False and not adm.degraded
    assert adm.clamp(16) == 16


def test_hbm_pressure_counts_as_overload():
    adm = AdmissionController(degraded_max_new_tokens=2, sustain_ticks=1,
                              hbm_frac_high=0.9)
    assert not adm.overloaded(queue_depth=0)
    adm.note_hbm({"bytes_in_use": 95, "bytes_limit": 100})
    assert adm.overloaded(queue_depth=0)
    assert adm.on_tick(0) is True and adm.degraded
    assert adm.clamp(16) == 2
    adm.note_hbm({"bytes_in_use": 10, "bytes_limit": 100})
    assert adm.on_tick(0) is False and not adm.degraded
    assert adm.clamp(16) == 16


def test_pool_low_watermark_counts_as_overload(paged2):
    """PR-9 satellite: a drained paged-KV free list is an overload
    signal like queue depth and HBM pressure — and the scheduler feeds
    ``Engine.free_page_frac`` to the controller each tick, so sustained
    pool pressure clamps admitted budgets end-to-end."""
    adm = AdmissionController(degraded_max_new_tokens=2, sustain_ticks=1,
                              pool_frac_low=0.10)
    assert not adm.overloaded(queue_depth=0)
    adm.note_pool(0.05)                    # below the low watermark
    assert adm.overloaded(queue_depth=0)
    assert adm.on_tick(0) is True and adm.degraded
    assert adm.clamp(16) == 2
    adm.note_pool(0.8)
    assert adm.on_tick(0) is False and not adm.degraded
    adm.note_pool(None)                    # no signal: state unchanged
    assert not adm.overloaded(queue_depth=0)

    # end-to-end: a drained overcommitted pool degrades admitted budgets.
    # free_page_frac counts free + LRU-evictable index pages (a completed
    # request's cached pages are allocatable on demand — PR 16), so the
    # watermark sits above the in-use-dominated fraction r1 pins (3/8
    # pages held while it decodes), not the raw free-list level.
    # r0 finishes fast and frees its slot while long-running r1 keeps
    # holding pages, so r2 is admitted INTO the drained-pool window and
    # gets the clamp
    eng = paged2.reset()
    sysp = _tokens(8, seed=99)
    adm = AdmissionController(degraded_max_new_tokens=3, sustain_ticks=1,
                              pool_frac_low=0.70)
    sched = ServeScheduler(eng, admission=adm)
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r)
        if r.get("event") == "serve_degraded_mode" else None)
    try:
        for rid, tail, max_new in (("r0", 3, 2), ("r1", 4, 8),
                                   ("r2", 5, 8)):
            sched.submit(Request(request_id=rid,
                                 tokens=sysp + _tokens(tail, seed=ord(
                                     rid[-1])),
                                 max_new_tokens=max_new))
        stats = sched.run()
    finally:
        unsub()
    assert any(e["entered"] for e in seen)
    recs = {r["request_id"]: r for r in stats.requests}
    assert all(r["state"] == "completed" for r in recs.values())
    assert recs["r1"]["new_tokens"] == 8      # pre-overload budget kept
    assert recs["r2"]["new_tokens"] == 3, \
        "the degraded-window admission should have been clamped to 3"


# ------------------------------------------------ warm restart / chaos

def _run_supervised(eng, injector, requests, *, max_restarts=2,
                    journal=None):
    sched = ServeScheduler(eng, fault_injector=injector,
                           journal=journal or TickJournal())
    for r in requests:
        sched.submit(r)
    stats = ServeSupervisor(sched, max_restarts=max_restarts,
                            sleep=lambda s: None).run()
    return sched, stats


def test_crash_recover_drain_smoke(greedy2):
    """THE tier-1 chaos acceptance: one schedule combining a decode-step
    crash, a latency spike, and a queue storm. Every submitted request
    (initial + storm) reaches exactly one terminal status, surviving
    requests' greedy outputs are bit-identical to an uncrashed run, and
    decode compiles exactly zero additional times across the recovery."""
    base_sched = ServeScheduler(greedy2.reset())
    for r in _requests(4):
        base_sched.submit(r)
    base = {r["request_id"]: r["generated"]
            for r in base_sched.run().requests}
    traces_before = greedy2.decode_traces

    inj = (FaultInjector(seed=0)
           .crash_on_decode_step(2)
           .latency_spike(4, 0.02)
           .queue_storm(3, 3, prompt_len=4, max_new_tokens=2))
    sched, stats = _run_supervised(greedy2.reset(), inj, _requests(4))
    assert greedy2.decode_traces == traces_before, \
        "recover() must reuse the compiled decode executable"
    assert stats.restarts == 1
    _assert_exactly_one_terminal(
        sched, [f"r{i}" for i in range(4)] + [f"storm-{i}"
                                              for i in range(3)])
    recs = {r["request_id"]: r for r in stats.requests}
    for rid, gen in base.items():
        assert recs[rid]["state"] == "completed"
        assert recs[rid]["generated"] == gen, \
            f"{rid} drifted across the warm restart"


def test_warm_restart_determinism_greedy(greedy2):
    """Crash at every early tick in turn: greedy outputs always equal the
    uncrashed run — recovery re-prefill is bit-exact by the PR-5
    prefill/decode invariant and the journal rollback replays the torn
    tick identically."""
    base_sched = ServeScheduler(greedy2.reset())
    for r in _requests(3):
        base_sched.submit(r)
    base = {r["request_id"]: r["generated"]
            for r in base_sched.run().requests}
    for crash_at in (0, 1, 4):
        inj = FaultInjector(seed=0).crash_on_decode_step(crash_at)
        sched, stats = _run_supervised(greedy2.reset(), inj, _requests(3))
        assert stats.restarts == 1, crash_at
        got = {r["request_id"]: r["generated"] for r in stats.requests}
        assert got == base, f"crash at step {crash_at} changed outputs"


def test_warm_restart_replays_sampled_stream(params):
    """The PRNG key path is journaled and restored: a temperature>0
    stream continues bit-for-bit across a crash — the strictest form of
    'surviving slots stay bit-identical'."""
    eng = Engine(CFG, params,
                 EngineConfig(num_slots=2, max_len=32, temperature=0.8,
                              top_k=5), seed=0)
    base_sched = ServeScheduler(eng)
    for r in _requests(2, max_new=8):
        base_sched.submit(r)
    base = {r["request_id"]: r["generated"]
            for r in base_sched.run().requests}
    inj = FaultInjector(seed=0).crash_on_decode_step(3)
    sched, stats = _run_supervised(eng.reset(0), inj,
                                   _requests(2, max_new=8))
    assert stats.restarts == 1
    got = {r["request_id"]: r["generated"] for r in stats.requests}
    assert got == base, "sampled stream diverged across the restart"


def test_post_snapshot_admission_survives_crash(greedy2):
    """Review regression: a request submitted AND admitted inside the
    crashing tick (a storm arrival taking a free slot) exists in neither
    the snapshot's queue nor its slots nor the live queue — recover()
    must roll it back to queued, not forget it."""
    base_sched = ServeScheduler(greedy2.reset())
    base_sched.submit(Request(request_id="r0", tokens=_tokens(5, seed=0),
                              max_new_tokens=8))
    base = base_sched.run().requests[0]["generated"]

    inj = (FaultInjector(seed=0)
           .queue_storm(2, 2, prompt_len=4, max_new_tokens=3)
           .crash_on_decode_step(2))
    sched = ServeScheduler(greedy2.reset(), fault_injector=inj,
                           journal=TickJournal())
    # one long request on a 2-slot engine: a slot stays free for the
    # storm arrival to be admitted in the very tick that crashes
    sched.submit(Request(request_id="r0", tokens=_tokens(5, seed=0),
                         max_new_tokens=8))
    stats = ServeSupervisor(sched, max_restarts=2,
                            sleep=lambda s: None).run()
    assert stats.restarts == 1
    _assert_exactly_one_terminal(sched, ["r0", "storm-0", "storm-1"])
    recs = {r["request_id"]: r for r in stats.requests}
    assert all(r["state"] == "completed" for r in recs.values())
    assert recs["r0"]["generated"] == base


def test_failed_recovery_still_drains(greedy2, monkeypatch):
    """Review regression: when recover() itself raises (the likeliest
    production shape — the re-prefill hits the same dead runtime), the
    supervisor must still drain every live request to a terminal status
    before propagating."""
    inj = FaultInjector(seed=0).crash_on_decode_step(2)
    sched = ServeScheduler(greedy2.reset(), fault_injector=inj,
                           journal=TickJournal())
    for r in _requests(4):
        sched.submit(r)

    def broken_recover(error=None):
        raise RuntimeError("re-prefill hit the dead runtime too")

    monkeypatch.setattr(sched, "recover", broken_recover)
    with pytest.raises(RuntimeError, match="dead runtime"):
        ServeSupervisor(sched, max_restarts=2,
                        sleep=lambda s: None).run()
    _assert_exactly_one_terminal(sched, [f"r{i}" for i in range(4)])
    assert {r["finish_reason"] for r in sched.stats().requests} == \
        {"engine_failure"}


def test_restart_budget_exhausted_drains_and_rejects(greedy2):
    """When recovery keeps failing, the supervisor stops pretending:
    every still-live request is drained to a terminal status (queued →
    rejected-retriable, in-flight → evicted), the engine is never
    touched again, and the fatal error propagates."""
    inj = FaultInjector(seed=0).crash_on_decode_step(2, times=5)
    sched = ServeScheduler(greedy2.reset(), fault_injector=inj,
                           journal=TickJournal())
    for r in _requests(4):
        sched.submit(r)
    with GoodputLedger() as led:
        with pytest.raises(SimulatedCrash):
            ServeSupervisor(sched, max_restarts=1,
                            sleep=lambda s: None).run()
    assert sched.restarts == 1
    _assert_exactly_one_terminal(sched, [f"r{i}" for i in range(4)])
    recs = {r["request_id"]: r for r in sched.stats().requests}
    assert {r["finish_reason"] for r in recs.values()} == \
        {"engine_failure"}
    queued = [r for r in recs.values() if r["state"] == "rejected"]
    inflight = [r for r in recs.values() if r["state"] == "evicted"]
    assert queued and inflight
    assert all(r["retriable"] for r in queued)
    assert led.summary()["events"]["serve_engine_restart"] == 1


def test_supervisor_requires_a_journal(greedy2):
    with pytest.raises(ValueError, match="journal"):
        ServeSupervisor(ServeScheduler(greedy2.reset()))


def test_recover_without_snapshot_refuses(greedy2):
    sched = ServeScheduler(greedy2.reset(), journal=TickJournal())
    with pytest.raises(RuntimeError, match="snapshot"):
        sched.recover()


# ----------------------------------------------------------- the journal

def test_journal_persists_atomically(tmp_path, greedy2):
    """The on-disk journal commits via .tmp + os.replace (APX004): after
    a run the file is one complete JSON document with the schema the
    recovery/postmortem tooling expects, and no .tmp straggler remains."""
    path = str(tmp_path / "serve_journal.json")
    sched = ServeScheduler(greedy2.reset(),
                           journal=TickJournal(path, every=1))
    for r in _requests(3):
        sched.submit(r)
    sched.run()
    assert os.path.exists(path) and not os.path.exists(path + ".tmp")
    doc = json.loads(open(path).read())
    assert doc["schema"] == 1
    assert set(doc) >= {"decode_steps", "decode_tokens", "engine",
                        "slots", "queued"}
    assert set(doc["engine"]) == {"rng", "last_tokens", "lengths"}
    # object refs never leak into the serialized view
    assert all(e is None or set(e) == {"request_id", "prompt",
                                       "generated"}
               for e in doc["slots"])


def test_journal_cadence_bounds_disk_writes(tmp_path, greedy2):
    calls = []
    journal = TickJournal(str(tmp_path / "j.json"), every=4)
    orig = journal.save
    journal.save = lambda *a, **k: (calls.append(1), orig(*a, **k))[1]
    sched = ServeScheduler(greedy2.reset(), journal=journal)
    for r in _requests(2):
        sched.submit(r)
    sched.run()
    assert journal.ticks_recorded > len(calls) >= 1


def test_restore_sampling_state_integrity_check(greedy2):
    eng = greedy2.reset()
    eng.prefill({0: _tokens(5)})
    state = eng.sampling_state()
    eng.reset()
    with pytest.raises(ValueError, match="integrity"):
        eng.restore_sampling_state(state, slots=[0])  # nothing re-prefilled


# --------------------------------------------------------- queued aborts

def test_queued_abort_charges_queue_wait(greedy2):
    """Satellite regression: aborting a still-queued request publishes
    its wasted queue time (before PR 8 the wait silently vanished from
    the ledger)."""
    waits = []
    unsub = subscribe_events(
        lambda r: waits.append(r) if r.get("event") == "serve_queue_wait"
        and r.get("request_id") == "r2" else None)
    try:
        inj = FaultInjector(seed=0).abort_request("r2", at_step=1)
        sched = ServeScheduler(greedy2.reset(), fault_injector=inj)
        for r in _requests(3):
            sched.submit(r)
        sched.run()
    finally:
        unsub()
    assert len(waits) == 1 and waits[0]["seconds"] >= 0.0


# --------------------------------------------------------------- the CLI

def test_serve_cli_resilience_flags(capsys):
    """In-process CLI e2e: --max-queue shedding surfaces retriable
    rejections per request, and the summary carries the SLO fields."""
    from apex_tpu.serve.cli import main

    rc = main(["--config", "tiny", "--requests", "4", "--prompt-len", "4",
               "--max-new-tokens", "3", "--num-slots", "2",
               "--max-len", "32", "--temperature", "0",
               "--max-queue", "2", "--shed-policy", "reject-newest",
               "--max-restarts", "1", "--deadline-ms", "60000"])
    assert rc == 0
    lines = [json.loads(l) for l in
             capsys.readouterr().out.strip().splitlines()]
    recs, summary = lines[:-1], lines[-1]
    assert len(recs) == 4
    rejected = [r for r in recs if r["state"] == "rejected"]
    assert len(rejected) == 2
    assert all(r["retriable"] is True for r in rejected)
    s = summary["summary"]
    assert s["rejected"] == 2 and s["shed_rate"] == pytest.approx(0.5)
    assert s["deadline_exceeded"] == 0 and s["restarts"] == 0
    assert summary["decode_compiles"] == 1


# ------------------------------------------ warm restart under paging

@pytest.fixture(scope="module")
def paged2(params):
    """Shared 2-slot paged+prefix greedy engine for the paging
    resilience tests; reset() keeps the compile."""
    return Engine(CFG, params,
                  EngineConfig(num_slots=2, max_len=32, temperature=0.0,
                               page_size=8, prefix_cache=True), seed=0)


def _prefix_requests(n=4, max_new=6):
    """Mixed requests sharing one full-page system prefix, so shared
    read-only pages are resident (and index-pinned) at crash time."""
    sysp = _tokens(8, seed=99)
    return [Request(request_id=f"r{i}",
                    tokens=sysp + _tokens(3 + i % 3, seed=i),
                    max_new_tokens=max_new) for i in range(n)]


def test_chaos_smoke_under_paging(paged2):
    """ISSUE 9 acceptance: THE PR-8 chaos smoke re-run on a paged engine
    with shared prefix pages — decode-step crash + latency spike + queue
    storm. Every submitted request reaches exactly one terminal status,
    surviving greedy outputs are bit-identical to the uncrashed paged
    run, and decode_traces delta is 0 across the recovery."""
    base_sched = ServeScheduler(paged2.reset())
    for r in _prefix_requests(4):
        base_sched.submit(r)
    base = {r["request_id"]: r["generated"]
            for r in base_sched.run().requests}
    traces_before = paged2.decode_traces

    inj = (FaultInjector(seed=0)
           .crash_on_decode_step(2)
           .latency_spike(4, 0.02)
           .queue_storm(3, 3, prompt_len=4, max_new_tokens=2))
    sched, stats = _run_supervised(paged2.reset(), inj,
                                   _prefix_requests(4))
    assert paged2.decode_traces == traces_before, \
        "paged recover() must reuse the compiled decode executable"
    assert stats.restarts == 1
    _assert_exactly_one_terminal(
        sched, [f"r{i}" for i in range(4)] + [f"storm-{i}"
                                              for i in range(3)])
    recs = {r["request_id"]: r for r in stats.requests}
    for rid, gen in base.items():
        assert recs[rid]["state"] == "completed"
        assert recs[rid]["generated"] == gen, \
            f"{rid} drifted across the paged warm restart"


def test_warm_restart_paged_determinism_and_journal(paged2):
    """Crash at every early tick in turn: the paged engine's greedy
    outputs always equal the uncrashed run (recovery re-prefill through
    shared pages is bit-exact), and the journal payload records the page
    accounting — tables, refcounts, prefix-index size — for the
    postmortem."""
    base_sched = ServeScheduler(paged2.reset())
    for r in _prefix_requests(3):
        base_sched.submit(r)
    base = {r["request_id"]: r["generated"]
            for r in base_sched.run().requests}
    journal = None
    for crash_at in (0, 1, 4):
        journal = TickJournal()
        inj = FaultInjector(seed=0).crash_on_decode_step(crash_at)
        sched, stats = _run_supervised(paged2.reset(), inj,
                                       _prefix_requests(3),
                                       journal=journal)
        assert stats.restarts == 1, crash_at
        got = {r["request_id"]: r["generated"] for r in stats.requests}
        assert got == base, \
            f"paged crash at step {crash_at} changed outputs"
    payload = journal.to_payload()
    pg = payload["paging"]
    assert pg["page_size"] == 8
    assert len(pg["refcounts"]) == pg["num_pages"]
    assert len(pg["page_table"]) == 2           # [num_slots][max_pages]
    assert all(len(row) == 4 for row in pg["page_table"])


def test_slot_journal_document_unchanged(greedy2):
    """Pre-paging journal consumers see an unchanged document: a slot
    engine's payload carries no 'paging' key at all."""
    sched = ServeScheduler(greedy2.reset(), journal=TickJournal())
    for r in _requests(2, max_new=2):
        sched.submit(r)
    sched.run()
    assert "paging" not in sched.journal.to_payload()


def test_paged_recovery_reprefills_only_unshared_pages(paged2):
    """recover() keeps the pool bytes and the prefix index (shared pages
    are read-only — the crash cannot have torn them): each surviving
    slot's recovery re-prefill HITS the index for its prompt pages and
    scans only the generated tail — proven by the hit counters, and only
    the original prompt ever enters the index (generated-token pages
    must not pin it)."""
    inj = FaultInjector(seed=0).crash_on_decode_step(3)
    sched, stats = _run_supervised(paged2.reset(), inj,
                                   _prefix_requests(2, max_new=8))
    assert stats.restarts == 1
    recs = {r["request_id"]: r for r in stats.requests}
    assert all(r["state"] == "completed" for r in recs.values())
    # the cold admission batch can't hit (inserts land post-batch), so
    # both hits are the recovery re-prefills riding the surviving index
    assert paged2.prefix_hits == 2
    assert paged2.prefix_hit_tokens == 16       # one 8-token page each
    # index holds ONLY prompt-page hashes: prompts are 11/12 tokens ->
    # one full page each, deduped to the single shared sysp chunk
    assert len(paged2.prefix) == 1


# ------------------------------------------------------- the slow sweep

@pytest.mark.slow
def test_chaos_schedule_sweep(greedy2):
    """Seeded fault-schedule sweep: crashes at different ticks, latency
    spikes, queue storms, deadlines, and bounded queues in combination.
    The invariant holds for every schedule, and any request that
    completes under two different schedules produced prefix-consistent
    greedy output (degradation may clamp lengths; greedy content never
    drifts)."""
    by_prompt = {}
    for seed in range(4):
        rng = np.random.RandomState(seed)
        inj = FaultInjector(seed=seed)
        crash_at = int(rng.randint(0, 5))
        inj.crash_on_decode_step(crash_at)
        if seed % 2:
            inj.latency_spike(int(rng.randint(0, 6)), 0.03)
        storm_n = int(rng.randint(2, 5))
        inj.queue_storm(int(rng.randint(1, 4)), storm_n, prompt_len=4,
                        max_new_tokens=3)
        adm = AdmissionController(max_queue=6,
                                  shed_policy=SHED_POLICIES[seed % 3],
                                  degraded_max_new_tokens=2,
                                  queue_high=3, sustain_ticks=2)
        reqs = [Request(request_id=f"r{i}", tokens=_tokens(5, seed=i),
                        max_new_tokens=5,
                        deadline_ms=5_000.0 if i % 2 else None,
                        priority=i % 3)
                for i in range(5)]
        sched = ServeScheduler(greedy2.reset(), fault_injector=inj,
                               admission=adm, journal=TickJournal())
        for r in reqs:
            sched.submit(r)
        stats = ServeSupervisor(sched, max_restarts=3,
                                sleep=lambda s: None).run()
        assert stats.restarts >= 1
        expected = [f"r{i}" for i in range(5)] + \
            [f"storm-{i}" for i in range(storm_n)]
        _assert_exactly_one_terminal(sched, expected)
        for rec in stats.requests:
            if rec["state"] != "completed":
                continue
            key = tuple(CFG.vocab_size * 0 + t for t in (
                reqs[int(rec["request_id"][1:])].tokens
                if rec["request_id"].startswith("r") else []))
            if not key:
                continue
            gen, prev = rec["generated"], by_prompt.get(key)
            if prev is not None:
                n = min(len(gen), len(prev))
                assert gen[:n] == prev[:n], \
                    f"{rec['request_id']} drifted across schedules"
            if prev is None or len(gen) > len(prev):
                by_prompt[key] = gen
    assert by_prompt, "no request ever completed across the sweep"
