"""DDP + SyncBatchNorm on the virtual 8-device CPU mesh — port of
tests/distributed/DDP/ddp_race_condition_test.py and
tests/distributed/synced_batchnorm/* (SURVEY §4: multi-device single host
replaces the reference's one-process-per-GPU harness)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from apex_tpu.utils.compat import shard_map

from apex_tpu.parallel import (DistributedDataParallel, SyncBatchNorm,
                               bucketed_allreduce, get_mesh,
                               sync_batch_norm_stats)

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= WORLD, "conftest must provide 8 cpu devices"
    return get_mesh("data")


class TestBucketedAllreduce:
    @pytest.mark.parametrize("message_size", [1, 64, 1 << 22])
    def test_mean_allreduce_matches_manual(self, mesh, message_size):
        """message_size=1 reproduces the race-condition test's pathological
        one-bucket-per-tensor setting (ddp_race_condition_test.py:41)."""
        grads = {
            "w": jnp.arange(WORLD * 24, dtype=jnp.float32).reshape(WORLD, 24),
            "b": jnp.ones((WORLD, 7), jnp.float32) * jnp.arange(
                WORLD, dtype=jnp.float32)[:, None],
        }

        @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"),
                           check_vma=False)
        def sync(g):
            return bucketed_allreduce(g, "data", message_size)

        out = sync(grads)
        for k in grads:
            want = np.broadcast_to(
                np.asarray(grads[k]).mean(0, keepdims=True),
                grads[k].shape)
            np.testing.assert_allclose(np.asarray(out[k]), want, rtol=1e-6)

    def test_mixed_dtype_grads_keep_precision(self, mesh):
        """fp32 grads must not be degraded through a bf16 flat bucket
        (reference DDP buckets per dtype)."""
        tiny = 1e-6  # representable in fp32, rounds to 0 contribution in bf16
        grads = {
            "a": jnp.ones((WORLD, 4), jnp.bfloat16),
            "b": jnp.full((WORLD, 4), 1.0 + tiny, jnp.float32),
        }

        @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"), check_vma=False)
        def sync(g):
            return bucketed_allreduce(g, "data", message_size=1 << 20)

        out = sync(grads)
        assert out["b"].dtype == jnp.float32
        # fp32 psum rounding is ~1e-7; bf16 degradation would err by 1e-6
        np.testing.assert_allclose(np.asarray(out["b"]), 1.0 + tiny,
                                   rtol=0, atol=3e-7)

    def test_predivide_factor(self, mesh):
        g = {"w": jnp.ones((WORLD, 16), jnp.float32)}

        @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"), check_vma=False)
        def sync(g):
            return bucketed_allreduce(g, "data",
                                      gradient_predivide_factor=WORLD)

        out = sync(g)
        np.testing.assert_allclose(np.asarray(out["w"]), 1.0, rtol=1e-6)

    def test_ddp_value_and_grad(self, mesh):
        ddp = DistributedDataParallel(axis_name="data", delay_allreduce=True)
        params = {"w": jnp.full((4,), 2.0)}
        x = jnp.arange(WORLD * 4, dtype=jnp.float32).reshape(WORLD, 4)

        def loss_fn(p, xb):
            return jnp.sum(p["w"] * xb)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("data")),
                           out_specs=(P("data"), P()), check_vma=False)
        def step(p, xb):
            loss, grads = ddp.value_and_grad(loss_fn)(p, xb[0])
            return loss[None], grads

        loss, grads = step(params, x)
        np.testing.assert_allclose(np.asarray(grads["w"]),
                                   np.asarray(x).mean(0), rtol=1e-6)


class TestDDPOverlapEvidence:
    """Overlap/race evidence for the bucketed DDP allreduce (VERDICT r2
    item 9; reference tests/distributed/DDP/ddp_race_condition_test.py:41
    hammers overlap-allreduce-with-backward with message_size=1 and
    injected delays).

    On TPU, overlap is the XLA latency-hiding scheduler's job; what the
    framework must guarantee — and what these tests pin — is (a) each
    bucket lowers to its OWN all-reduce with no data dependence on other
    buckets' backward ops, so the scheduler is free to interleave them
    with compute, and (b) injected communication latency (the reference's
    add_delay fault hook) cannot change numerics — the dataflow-race
    freedom the reference's test exists to check."""

    def _make_step(self, mesh, delay_ms):
        from apex_tpu.contrib.nccl_p2p import add_delay

        def step_fn(p, xb, yb):
            def loss_fn(p):
                h = jnp.tanh(xb @ p["w1"])
                h = jnp.tanh(h @ p["w2"])
                return jnp.mean((h @ p["w3"] - yb) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(p)
            if delay_ms:
                # latency on the FIRST bucket produced by backward (w3's
                # grad is ready first in reverse-mode order… w1's last) —
                # the reference injects on the eagerly-synced bucket
                grads = dict(grads, w3=add_delay(delay_ms, grads["w3"]))
            grads = bucketed_allreduce(grads, "data", message_size=1)
            new_p = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g,
                                           p, grads)
            return jax.lax.pmean(loss, "data"), new_p

        return functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=(P(), P()), check_vma=False)(step_fn)

    def _data(self):
        k = jax.random.split(jax.random.PRNGKey(0), 5)
        p = {"w1": jax.random.normal(k[0], (16, 32)) * 0.3,
             "w2": jax.random.normal(k[1], (32, 32)) * 0.3,
             "w3": jax.random.normal(k[2], (32, 8)) * 0.3}
        x = jax.random.normal(k[3], (WORLD * 4, 16))
        y = jax.random.normal(k[4], (WORLD * 4, 8))
        return p, x, y

    def test_injected_latency_does_not_change_numerics(self, mesh):
        """ddp_race_condition semantics: a delayed bucket allreduce must
        produce bit-identical training results — under XLA dataflow there
        is no buffer for the race to corrupt."""
        p, x, y = self._data()
        loss0, p0 = jax.jit(self._make_step(mesh, 0))(p, x, y)
        loss1, p1 = jax.jit(self._make_step(mesh, 2))(p, x, y)
        np.testing.assert_array_equal(np.asarray(loss0), np.asarray(loss1))
        for a, b in zip(jax.tree_util.tree_leaves(p0),
                        jax.tree_util.tree_leaves(p1)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_buckets_lower_to_independent_collectives(self, mesh):
        """Evidence the scheduler CAN overlap: with message_size=1 each
        grad leaf LOWERS to its own all_reduce (three independent
        collectives with no cross-bucket data dependence — exactly the
        structure overlap requires), with or without the injected delay.
        XLA's all-reduce combiner may later re-coalesce small buckets (the
        compiler-side analog of the reference's own bucket coalescing) —
        that is its scheduling prerogative, so the assertion is on the
        lowered program, plus a check that a collective survives
        optimization."""
        p, x, y = self._data()
        for delay in (0, 2):
            lowered = jax.jit(self._make_step(mesh, delay)).lower(p, x, y)
            n_ar = lowered.as_text().count("stablehlo.all_reduce")
            # loss pmean adds one; the three grad buckets are the rest
            assert n_ar >= 4, f"expected >=4 lowered all_reduces, got {n_ar}"
            assert "all-reduce" in lowered.compile().as_text()


class TestSyncBatchNorm:
    def test_stats_match_global_batch(self, mesh):
        """Per-device stats merged over the axis == stats of the full batch
        (two_gpu parity test pattern, synced_batchnorm/)."""
        x = jax.random.normal(jax.random.PRNGKey(0), (WORLD * 4, 16),
                              jnp.float32)

        @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                           out_specs=(P(), P(), P()), check_vma=False)
        def stats(xb):
            m, v, c = sync_batch_norm_stats(xb, (0,), "data")
            return m, v, c

        mean, var, count = stats(x)
        np.testing.assert_allclose(np.asarray(mean), np.asarray(x).mean(0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(var), np.asarray(x).var(0),
                                   rtol=1e-4, atol=1e-6)
        assert float(count) == WORLD * 4

    def test_stats_large_mean_no_cancellation(self):
        """|mean| >> std must not cancel catastrophically: the one-pass
        E[d²]−E[d]² form is computed on d = x − shift where shift defaults
        to the first sample per channel. fp32 E[x²]−mean² at mean=1000,
        std=0.1 would have ~0.06 absolute error vs the true var 0.01 —
        every caller (groupbn included) must get the robust path without
        opting in."""
        x = (1000.0
             + 0.1 * jax.random.normal(jax.random.PRNGKey(1), (4096, 4),
                                       jnp.float32))
        mean, var, _ = sync_batch_norm_stats(x, (0,), None)
        np.testing.assert_allclose(np.asarray(var),
                                   np.asarray(x).var(0), rtol=1e-3)
        np.testing.assert_allclose(np.asarray(mean),
                                   np.asarray(x).mean(0), rtol=1e-6)
        # explicit shift and negative reduce axes
        shift = jnp.full((4,), 1000.0, jnp.float32)
        _, var_s, _ = sync_batch_norm_stats(x, (-2,), None, shift=shift)
        np.testing.assert_allclose(np.asarray(var_s),
                                   np.asarray(x).var(0), rtol=1e-3)
        # NHWC-style multi-axis reduce with a large offset
        x4 = x.reshape(64, 8, 8, 4)
        _, var4, _ = sync_batch_norm_stats(x4, (0, 1, 2), None)
        np.testing.assert_allclose(np.asarray(var4),
                                   np.asarray(x).var(0), rtol=1e-3)

    def test_module_matches_full_batch_bn(self, mesh):
        """SyncBN over shards == plain BN over the concatenated batch."""
        C = 12
        x = jax.random.normal(jax.random.PRNGKey(1), (WORLD * 2, 5, C))
        bn = SyncBatchNorm(num_features=C, axis_name="data")
        variables = bn.init(jax.random.PRNGKey(2), x,
                            use_running_average=False)

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("data")),
                           out_specs=P("data"), check_vma=False)
        def apply_sharded(v, xb):
            y, _ = bn.apply(v, xb, use_running_average=False,
                            mutable=["batch_stats"])
            return y

        y_sharded = apply_sharded(variables, x)
        bn_local = SyncBatchNorm(num_features=C, axis_name=None)
        v_local = bn_local.init(jax.random.PRNGKey(2), x,
                                use_running_average=False)
        y_full, _ = bn_local.apply(v_local, x, use_running_average=False,
                                   mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_sharded), np.asarray(y_full),
                                   atol=1e-5, rtol=1e-5)

    def test_different_batch_size_per_rank_unsupported_shapes(self, mesh):
        # shard_map requires equal shards; the reference's
        # two_gpu_test_different_batch_size.py scenario maps to padded batches
        # on TPU — documented behavior, here we just verify equal-shard path.
        pass

    def test_channels_first_layout(self, mesh):
        C = 6
        x = jax.random.normal(jax.random.PRNGKey(3), (WORLD, C, 4, 4))
        bn = SyncBatchNorm(num_features=C, axis_name=None, channel_axis=1)
        v = bn.init(jax.random.PRNGKey(4), x, use_running_average=False)
        y, _ = bn.apply(v, x, use_running_average=False,
                        mutable=["batch_stats"])
        m = np.asarray(y).mean(axis=(0, 2, 3))
        np.testing.assert_allclose(m, 0.0, atol=1e-5)

    def test_fuse_relu(self, mesh):
        C = 4
        x = jax.random.normal(jax.random.PRNGKey(5), (16, C))
        bn = SyncBatchNorm(num_features=C, axis_name=None, fuse_relu=True)
        v = bn.init(jax.random.PRNGKey(6), x, use_running_average=False)
        y, _ = bn.apply(v, x, use_running_average=False,
                        mutable=["batch_stats"])
        assert float(np.asarray(y).min()) >= 0.0


class TestMeshLayer:
    """Rendezvous + fabric helpers (nccl_p2p.cpp:20-22 bootstrap analog,
    torchrun env contract, multislice DCN×ICI meshes)."""

    def test_init_distributed_single_process_noop(self, monkeypatch):
        from apex_tpu.parallel import init_distributed
        for var in ("WORLD_SIZE", "RANK", "MASTER_ADDR", "MASTER_PORT"):
            monkeypatch.delenv(var, raising=False)
        idx, count = init_distributed()
        assert idx == 0 and count == 1

    def test_init_distributed_world1_env(self, monkeypatch):
        """torchrun --nproc_per_node=1 exports MASTER_ADDR too; world size 1
        must short-circuit regardless (and must not touch
        jax.distributed.initialize, which refuses post-backend-init)."""
        from apex_tpu.parallel import init_distributed
        monkeypatch.setenv("WORLD_SIZE", "1")
        monkeypatch.setenv("MASTER_ADDR", "127.0.0.1")
        monkeypatch.setenv("MASTER_PORT", "29500")
        monkeypatch.setenv("RANK", "0")
        idx, count = init_distributed()
        assert idx == 0 and count == 1

    def test_topology_mesh_size_error_propagates(self):
        from apex_tpu.parallel import make_topology_mesh
        with pytest.raises(Exception):
            make_topology_mesh([3], ["dp"])  # 3 does not divide 8 devices

    def test_topology_mesh_covers_all_devices(self):
        from apex_tpu.parallel import make_topology_mesh
        n = len(jax.devices())
        mesh = make_topology_mesh([2, n // 2], ["dp", "tp"])
        assert mesh.devices.shape == (2, n // 2)
        assert len(set(d.id for d in mesh.devices.flat)) == n

    def test_hybrid_mesh_axis_layout(self):
        """DCN axes outermost, ICI innermost; falls back to row-major on
        backends without multislice topology (this CPU mesh)."""
        from apex_tpu.parallel import make_hybrid_mesh
        n = len(jax.devices())
        mesh = make_hybrid_mesh([2], [1, n // 2], ["dp", "fsdp", "tp"])
        assert mesh.axis_names == ("dp", "fsdp", "tp")
        assert mesh.devices.shape == (2, 1, n // 2)
        # a psum over every axis must see all devices exactly once
        assert len(set(d.id for d in mesh.devices.flat)) == n

    def test_hybrid_mesh_runs_collective(self):
        import functools
        from jax.sharding import PartitionSpec as P
        from apex_tpu.parallel import make_hybrid_mesh
        n = len(jax.devices())
        mesh = make_hybrid_mesh([2], [n // 2], ["dp", "tp"])

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=P("dp", "tp"), out_specs=P(),
                           check_vma=False)
        def total(x):
            return jax.lax.psum(jnp.sum(x), ("dp", "tp"))

        x = jnp.arange(n * 4.0).reshape(2, (n // 2) * 4)
        np.testing.assert_allclose(float(total(x)[()]), float(x.sum()))
