"""Tensor-parallel serving tier-1: mesh-sharded decode, bit-exact vs the
single-chip engine.

The acceptance claims under test (docs/serving.md "Tensor-parallel
decode"):

- **bit-exactness** — a ``tp=2`` engine in the default ``exact`` sync
  mode produces greedy AND sampled token streams (and raw logits)
  bit-identical in fp32 to the single-chip engine at equal ``block_k``,
  on both cache layouts (slot and paged, prefix-hit churn included).
  The mechanism: per-rank compute is the single-chip forward on column
  slices (per-column matmul determinism), and the cross-rank combine is
  pure concatenation (``all_gather``) — no float add ever crosses a
  rank boundary.
- **one compile per mesh shape** — admit/evict/abort/prefix-hit churn
  on the sharded engine traces decode exactly once
  (``Engine.decode_traces``), same as the single-chip invariant.
- **the collective contract** — ``expected_collectives`` (2 gathers per
  layer exact, 4 half-psums overlap/TokenWeave, 2 half-psums relaxed)
  equals the count in the ACTUAL lowered StableHLO
  (``Engine.decode_collectives``), and relaxed < overlap < the naive
  2-per-layer × unsplit baseline in all-reduce pressure.
- **the merge seam** — per-rank metrics snapshots fold through
  ``merge_snapshots`` into the fleet view (ranks/heads/KV bytes sum to
  the engine totals), and ``check_regression`` REFUSES to gate a tp=2
  capture against a single-chip baseline.

Engines are compiled once per geometry and shared via ``Engine.reset()``
(the test_serve precedent); the trace-counter tests build fresh engines.
All of it runs on the conftest-forced multi-device CPU host (the
``tp_devices`` fixture) — sharded tier-1 never depends on real chips.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.resilience.fault_injection import FaultInjector
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.scheduler import Request, ServeScheduler
from apex_tpu.serve.tp import (count_collectives, expected_collectives,
                               serving_mesh)
# bound at collection time (test_chip_worker purges apex_tpu.* from
# sys.modules mid-session; a function-local re-import would subscribe
# to a FRESH bus the old engine module never publishes to)
from apex_tpu.utils.logging import subscribe_events

pytestmark = pytest.mark.serve

CFG = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=2,
                 n_head=4, compute_dtype=jnp.float32)


def _tokens(n, seed=7, vocab=97):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, vocab, n)]


@pytest.fixture(scope="module")
def params():
    return init_gpt2_params(CFG, seed=0)


def _engine(params, **kw):
    kw.setdefault("num_slots", 3)
    kw.setdefault("max_len", 32)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("block_k", 8)     # equal chunk geometry: the
    #                                 bit-exactness precondition
    seed = kw.pop("seed", 0)
    return Engine(CFG, params, EngineConfig(**kw), seed=seed)


@pytest.fixture(scope="module")
def base8(params, tp_devices):
    """Single-chip slot oracle at block_k=8."""
    return _engine(params)


@pytest.fixture(scope="module")
def tp2(params, tp_devices):
    """tp=2 slot engine, exact sync (THE sharded default)."""
    return _engine(params, tp=2)


@pytest.fixture(scope="module")
def paged1(params, tp_devices):
    """Single-chip paged oracle (page_size 8, prefix index on)."""
    return _engine(params, page_size=8, prefix_cache=True)


@pytest.fixture(scope="module")
def tp2_paged(params, tp_devices):
    """tp=2 paged engine: head-sharded pool, replicated page table."""
    return _engine(params, page_size=8, prefix_cache=True, tp=2)


def _mixed_requests(n=5, seed0=0, max_new=5):
    return [Request(request_id=f"r{i}",
                    tokens=_tokens(4 + 3 * (i % 4), seed=seed0 + i),
                    max_new_tokens=max_new) for i in range(n)]


def _trace_outputs(eng, reqs, injector=None):
    sched = ServeScheduler(eng, fault_injector=injector)
    for r in reqs:
        sched.submit(r)
    return {r["request_id"]: r for r in sched.run().requests}


# ------------------------------------------------------------- the mesh


def test_serving_mesh_shape(tp_devices):
    mesh = serving_mesh(2)
    assert mesh.shape == {"tp": 2}
    with pytest.raises(ValueError, match="devices"):
        serving_mesh(10 ** 6)


def test_tp_engine_validation_matrix(params, tp_devices):
    """Every bad mesh geometry is a clear build-time ValueError, never a
    bad lowering (the CLI exit-2 matrix rides these messages)."""
    for kw, msg in (
            (dict(tp=3), "divide n_head"),
            (dict(tp=0), ">= 1"),
            (dict(tp=2, tp_sync="bogus"), "tp_sync"),
            (dict(tp_sync="relaxed"), "tp >= 2"),
            (dict(tp=10 ** 6), None),      # ValueError either way: the
            #   head check fires before the device-pool check for a tp
            #   this large; both are build-time refusals
    ):
        with pytest.raises(ValueError, match=msg):
            _engine(params, **kw)


# ------------------------------------------- bit-exactness (THE oracle)


def test_tp_bit_exact_vs_single_chip_greedy(base8, tp2):
    """THE sharded acceptance: an identical mixed-length request trace
    through the single-chip engine (the oracle) and the tp=2 mesh
    produces bit-identical greedy streams at equal block_k."""
    assert tp2.tp == 2 and tp2.block_k == base8.block_k == 8
    base = _trace_outputs(base8.reset(), _mixed_requests())
    got = _trace_outputs(tp2.reset(), _mixed_requests())
    assert {k: v["generated"] for k, v in got.items()} == \
           {k: v["generated"] for k, v in base.items()}
    assert {k: v["finish_reason"] for k, v in got.items()} == \
           {k: v["finish_reason"] for k, v in base.items()}


def test_tp_decode_logits_bit_exact_vs_single_prefill(params, tp2,
                                                      tp_devices):
    """Strongest oracle form: the tp=2 engine's incremental decode
    LOGITS equal the single-chip engine's full-sequence prefill logits
    bit-for-bit in fp32 — crossing the mesh boundary AND the
    prefill/decode boundary in one assertion."""
    seq = _tokens(12)
    keeper = _engine(params, keep_prefill_logits=True)
    _, _, all_logits = keeper.prefill({1: seq})
    all_logits = np.asarray(all_logits)              # [P, B, V]
    inc = tp2.reset()
    inc.prefill({1: seq[:5]})
    for j in range(5, len(seq)):
        forced = np.array([0, seq[j], 0], np.int32)
        _, logits = inc.decode_step(forced,
                                    np.array([False, True, False]))
        a, b = all_logits[j, 1], np.asarray(logits)[1]
        assert a.dtype == np.float32
        assert np.array_equal(a, b), \
            f"tp decode pos {j} drifted: max|d|={np.abs(a - b).max()}"


def test_tp_paged_bit_exact_vs_single_chip(paged1, tp2_paged):
    """The paged pool under the mesh: head-sharded page bytes behind a
    REPLICATED page table, prefix-hit + COW churn included — greedy
    streams bit-identical to the single-chip paged engine (itself held
    bit-exact to the slot engine by test_serve)."""
    sysp = _tokens(16, seed=42)                  # two full shared pages
    reqs = lambda: [Request(request_id=f"p{i}",          # noqa: E731
                            tokens=sysp + _tokens(3 + i, seed=100 + i),
                            max_new_tokens=4) for i in range(4)]
    base = _trace_outputs(paged1.reset(), reqs())
    got = _trace_outputs(tp2_paged.reset(), reqs())
    assert {k: v["generated"] for k, v in got.items()} == \
           {k: v["generated"] for k, v in base.items()}
    # the churn was real: later admissions hit the shared prefix pages
    assert tp2_paged.prefix_hits >= 1


@pytest.mark.slow
def test_tp_bit_exact_sampled(params, tp_devices):
    """Seeded sampling crosses the mesh bit-for-bit: logits are
    bit-identical (exact mode) and the PRNG key path is identical (the
    key is engine state split once per call, sampling runs on the full
    replicated logits outside shard_map) — so sampled streams match
    token-for-token.

    Slow tier: greedy tp-vs-single-chip parity stays in tier-1; this
    adds the PRNG-path leg on top of bit-identical logits."""
    kw = dict(temperature=0.8, top_k=5)
    base = _trace_outputs(_engine(params, **kw),
                          _mixed_requests(max_new=6))
    got = _trace_outputs(_engine(params, tp=2, **kw),
                         _mixed_requests(max_new=6))
    assert {k: v["generated"] for k, v in got.items()} == \
           {k: v["generated"] for k, v in base.items()}


# ------------------------------------- one compile per mesh shape


@pytest.mark.fault
def test_tp_decode_compiles_once_across_churn(params, tp_devices):
    """The one-compile invariant survives the mesh: admissions,
    completions, a scripted mid-stream abort, prefix-hit admissions,
    and backfill churn on a tp=2 PAGED engine trace decode exactly once
    — one compile per mesh shape, proven by counters, with the
    serve_tp_mesh_ready provenance event published at build."""
    events = []
    unsub = subscribe_events(events.append)
    try:
        eng = _engine(params, num_slots=2, page_size=8,
                      prefix_cache=True, tp=2)
        inj = FaultInjector(seed=0).abort_request("c2", at_step=4)
        sched = ServeScheduler(eng, fault_injector=inj)
        sysp = _tokens(8, seed=9)
        for i, plen in enumerate((4, 6, 5, 3)):
            sched.submit(Request(request_id=f"c{i}",
                                 tokens=sysp + _tokens(plen, seed=i),
                                 max_new_tokens=4 + i % 3))
        stats = sched.run()
    finally:
        unsub()
    assert len(stats.requests) == 4
    assert eng.decode_traces == 1, \
        "mesh-sharded decode must compile once per mesh shape"
    mesh_ev = [e for e in events if e["event"] == "serve_tp_mesh_ready"]
    assert len(mesh_ev) == 1 and mesh_ev[0]["tp"] == 2


# ------------------------------------------- the collective contract


def test_tp_collective_counts_exact_overlap_relaxed(params, tp_devices):
    """The overlap-seam unit: per-mode collective counts in the ACTUAL
    lowered decode step equal the documented contract — exact = 2
    all-gathers/layer (combine by concatenation), overlap = the two
    per-layer all-reduces each split in two slot halves (TokenWeave),
    relaxed = ONE deferred all-reduce per layer — and exact mode's
    logits are bit-identical to the replicated reference."""
    ref = _engine(params, num_slots=2)
    prompt = {0: _tokens(6, seed=1), 1: _tokens(4, seed=2)}
    _, ref_logits, _ = ref.prefill(dict(prompt))

    got = {}
    for sync in ("exact", "overlap", "relaxed"):
        eng = _engine(params, num_slots=2, tp=2, tp_sync=sync)
        # serve FIRST through the plain jit path (no aot_compile), so
        # the collective count below exercises the risky ordering: its
        # internal .lower() must hit the jit's trace cache, never trace
        # decode a second time (the one-compile invariant would read 2)
        _, logits, _ = eng.prefill(dict(prompt))
        eng.decode_step(eng.last_tokens, np.array([True, True]))
        assert eng.decode_traces == 1
        counts = eng.decode_collectives()
        assert eng.decode_traces == 1, \
            "decode_collectives() re-traced a compiled engine"
        want = expected_collectives(CFG.n_layer, sync)
        assert counts["all_gather"] == want["all_gather"], (sync, counts)
        assert counts["all_reduce"] == want["all_reduce"], (sync, counts)
        assert counts["all_to_all"] == counts["permute"] == 0
        assert counts == {**counts, **eng.tp_collectives_per_step()}
        got[sync] = np.asarray(logits)

    # exact IS the replicated reference, bit for bit
    assert np.array_equal(got["exact"], np.asarray(ref_logits))
    # overlap reorders partial sums only: ulp-level, never bit-claimed
    assert np.allclose(got["overlap"], got["exact"], atol=1e-4)
    assert np.isfinite(got["relaxed"]).all()
    # the pressure ordering the two papers buy: TokenWeave splits hide
    # latency at equal volume; relaxed halves the all-reduce count
    assert expected_collectives(CFG.n_layer, "relaxed")["all_reduce"] \
        < expected_collectives(CFG.n_layer, "overlap")["all_reduce"]


def test_count_collectives_text_unit():
    txt = ('stablehlo.all_reduce x stablehlo.all_reduce y '
           'stablehlo.all_gather z collective_permute w')
    assert count_collectives(txt) == {
        "all_gather": 1, "all_reduce": 2, "all_to_all": 0, "permute": 1}
    with pytest.raises(ValueError, match="tp_sync"):
        expected_collectives(2, "bogus")


# ------------------------------------------------- the PR-10 merge seam


def test_tp_rank_snapshots_fold_through_merge(tp2):
    """Per-rank metrics fold through merge_snapshots into the fleet
    view — the PR-10 aggregation seam used for its designed purpose:
    each rank reports its OWN shard and the fold reconstructs the
    engine totals exactly."""
    from apex_tpu.monitor.export import merge_snapshots

    eng = tp2.reset()
    eng.prefill({0: _tokens(5)})
    for _ in range(3):
        eng.decode_step(eng.last_tokens, np.array([True, False, False]))
    docs = eng.tp_rank_snapshots(meta={"device_kind": "cpu"})
    assert len(docs) == 2
    merged = merge_snapshots(docs)
    vals = {name: fam["series"][0]["value"]
            for name, fam in merged["metrics"].items()}
    assert vals["serve_tp_ranks"] == 2
    assert vals["serve_tp_rank_heads"] == CFG.n_head
    assert vals["serve_tp_rank_kv_bytes"] == eng.kv_cache_bytes
    per_step = sum(eng.tp_collectives_per_step().values())
    assert vals["serve_tp_rank_collectives_total"] == \
        eng.decode_calls * per_step * 2
    # mesh-shape provenance survives the fold (the comparability axis
    # check_regression refuses on); per-file rank identity does not
    assert merged["meta"]["tp"] == 2
    assert "tp_rank" not in merged["meta"]


def test_tp_single_chip_has_no_rank_files(base8):
    assert base8.tp_rank_snapshots() == []
    assert base8.tp_collectives_per_step() == {"all_gather": 0,
                                               "all_reduce": 0}


# --------------------------------------------------- tune registry axis


@pytest.mark.tune
def test_decode_attention_tp_shards_axis_registered():
    """The decode_attention shape key carries the tp_shards axis (a
    winner tuned unsharded must never apply to a mesh shard) and
    CODE_VERSIONS bumped so stale v2 entries invalidate cleanly."""
    from apex_tpu.tune import CODE_VERSIONS
    from apex_tpu.tune import registry

    assert CODE_VERSIONS["decode_attention"] >= 3
    spec = registry.spec("decode_attention")
    k1 = spec.shape_key({"max_len": 32, "page_size": 0, "heads": 2,
                         "d": 8})
    k2 = spec.shape_key({"max_len": 32, "page_size": 0, "heads": 2,
                         "d": 8, "tp_shards": 2})
    assert k1 != k2
    assert ("tp_shards", 1) in k1 and ("tp_shards", 2) in k2


def test_tp_engines_resolve_distinct_block_k_keys(base8, tp2):
    """Both engines resolved a block_k under their own key (per-shard
    heads + tp_shards axis); pinning block_k=8 made them EQUAL — the
    bit-exactness precondition the oracle tests above ride."""
    assert base8.block_k == tp2.block_k == 8


# --------------------------------------------------------- CLI + bench


@pytest.mark.slow
def test_serve_cli_tp_smoke_and_rank_snapshots(tmp_path, capsys):
    """In-process ``apex-tpu-serve --tp 2``: bit-identical greedy output
    to the --tp 1 run, decode compiles once, the final line carries the
    mesh provenance, and --metrics-snapshot writes PATH.tpK per rank
    plus the merged PATH.tp fleet view.

    Slow tier: the two full serve runs cost ~10s; the tp engine
    bit-exactness and flag matrix stay in tier-1 via the in-process
    tests above and ``test_serve_cli_tp_exit2_matrix``."""
    from apex_tpu.serve.cli import main

    snap = str(tmp_path / "tp.json")
    argv = ["--config", "tiny", "--dtype", "fp32", "--requests", "3",
            "--max-new-tokens", "4", "--temperature", "0",
            "--max-len", "32", "--seed", "0"]
    assert main(argv) == 0
    single = [json.loads(l) for l in
              capsys.readouterr().out.strip().splitlines()]
    assert main(argv + ["--tp", "2", "--metrics-snapshot", snap]) == 0
    sharded = [json.loads(l) for l in
               capsys.readouterr().out.strip().splitlines()]
    # per-request records bit-identical (drop the timing fields)
    strip = lambda recs: [{k: v for k, v in r.items()          # noqa: E731
                           if k in ("request_id", "generated",
                                    "finish_reason")}
                          for r in recs[:-1]]
    assert strip(sharded) == strip(single)
    final = sharded[-1]
    assert final["decode_compiles"] == 1
    assert final["tp"] == {"tp": 2, "sync": "exact",
                           "collectives_per_decode_step":
                               {"all_gather": 2 * 2, "all_reduce": 0}}
    for suffix in (".tp0", ".tp1", ".tp"):
        assert os.path.exists(snap + suffix), suffix
    merged = json.load(open(snap + ".tp"))
    ranks = merged["metrics"]["serve_tp_ranks"]["series"][0]["value"]
    assert ranks == 2 and merged["meta"]["tp"] == 2


def test_serve_cli_tp_exit2_matrix(capsys):
    """Contradictory/inert tp flag combinations are loud exit-2 usage
    errors BEFORE any params/compile work."""
    from apex_tpu.serve.cli import main

    # --tp 2 --replicas 2 is no longer here: PR 16 made it the
    # fleet-of-meshes configuration (see test_serve_disagg)
    for argv in (["--tp", "3"],                       # 3 ∤ n_head=4
                 ["--tp", "0"],
                 ["--tp-sync", "relaxed"],            # sync without mesh
                 ["--tp-sync", "overlap"]):
        assert main(argv) == 2, argv
    capsys.readouterr()


def test_bench_tp_capture_and_gate_refusal(tmp_path, capsys):
    """A --tp-stamped serve_decode capture: workload provenance records
    the mesh shape, the capture gates cleanly against itself, and
    check_regression REFUSES to gate it against a single-chip baseline
    (exit 2, INCOMPARABLE) — in either direction."""
    from apex_tpu.bench_cli import _serve_bench
    from tools.check_regression import incomparable_entries, main as gate

    cap = str(tmp_path / "tp2.json")
    _serve_bench(6, 2, cap, max_len=32, tp=2, tp_sync="exact")
    capsys.readouterr()
    doc = json.load(open(cap))
    wl = doc["serve_decode"]["workload"]
    assert wl["tp"] == 2 and wl["tp_sync"] == "exact"

    # self-gate: comparable, passes
    assert gate([cap, "--suite", cap, "--kernels", "serve_decode"]) == 0
    out = capsys.readouterr().out
    assert "INCOMPARABLE" not in out

    # synthetic single-chip baseline: same numbers, tp=1 — the refusal
    base = json.loads(json.dumps(doc))
    base["serve_decode"]["workload"]["tp"] = 1
    base["serve_decode"]["workload"]["tp_sync"] = None
    basep = str(tmp_path / "tp1.json")
    json.dump(base, open(basep, "w"))
    assert incomparable_entries(doc, base) == {
        "serve_decode": "workload.tp=2 vs baseline workload.tp=1"}
    # a LEGACY baseline without the key at all is single-chip too
    del base["serve_decode"]["workload"]["tp"]
    assert "serve_decode" in incomparable_entries(doc, base)
    rc = gate([cap, "--suite", basep, "--kernels", "serve_decode"])
    out = capsys.readouterr().out
    assert rc == 2 and "INCOMPARABLE" in out    # nothing left to gate
