"""Span-tree tracing, HBM accounting, flight recorder (marker: ``trace``).

The acceptance claims under test:

- spans form a correct tree (shared ``trace_id``, parent links), carry
  exact caller-stamped durations, and ride the event bus; a DISABLED
  tracer publishes nothing and yields ``None`` spans (zero overhead);
- Chrome-trace export is loadable JSON — including the unterminated
  array a crashed run leaves (what Perfetto tolerates);
- ``prof.annotate`` mirrors into the span tracer; ``profile()`` refuses
  to nest; ``StepTimer`` works as a context manager;
- ``MemoryAccountant``/static ``memory_analysis`` publish
  ``hbm_snapshot`` events that the goodput ledger folds into its summary;
- the flight recorder's ring stays bounded under a FaultInjector
  overflow storm, dumps atomically with the documented schema, keeps the
  previous dump when a dump itself dies mid-write, and auto-dumps on
  preemption and watchdog escalation — the postmortem acceptance path.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.amp.grad_scaler import DynamicGradScaler
from apex_tpu.monitor import GoodputLedger, MemoryAccountant, Tracer
from apex_tpu.monitor.flight import FlightRecorder, thread_stacks
from apex_tpu.monitor.memory import (publish_compiled_memory,
                                     sample_device_memory)
from apex_tpu.monitor.trace import (ChromeTraceWriter, read_chrome_trace,
                                    spans_by_trace)
from apex_tpu.resilience import FaultInjector, resilient_step
from apex_tpu.resilience.distributed import CollectiveWatchdog
from apex_tpu.resilience.preemption import PreemptionGuard
from apex_tpu.utils import prof
from apex_tpu.utils.logging import publish_event, subscribe_events

pytestmark = pytest.mark.trace


@pytest.fixture
def bus():
    recs = []
    unsub = subscribe_events(recs.append)
    yield recs
    unsub()


class _FakeHBMDev:
    """Injectable device with allocator stats (CPU backends report none)."""

    def __init__(self, bytes_in_use=1000, peak=2000):
        self._stats = {"bytes_in_use": bytes_in_use,
                       "peak_bytes_in_use": peak, "bytes_limit": 10_000}

    def memory_stats(self):
        return dict(self._stats)


# ------------------------------------------------------------- span tree

def test_span_tree_parenting_and_ids(bus):
    tr = Tracer()
    with tr.span("root", a=1) as root:
        with tr.span("child") as child:
            assert tr.current() is child
        assert tr.current() is root
    recs = tr.completed_records()
    assert [r["name"] for r in recs] == ["child", "root"]
    child_rec, root_rec = recs
    assert child_rec["trace_id"] == root_rec["trace_id"]
    assert child_rec["parent_id"] == root_rec["span_id"]
    assert root_rec["parent_id"] is None
    assert root_rec["attrs"] == {"a": 1}
    # both transitions rode the bus, in open/close order
    names = [(r["event"], r["name"]) for r in bus
             if r.get("event", "").startswith("span_")]
    assert names == [("span_open", "root"), ("span_open", "child"),
                     ("span_close", "child"), ("span_close", "root")]


def test_manual_spans_use_caller_stamps():
    """Lifecycle spans (serve requests) reuse the instrumented component's
    own clock reads — durations are exact, not approximate."""
    tr = Tracer()
    s = tr.begin("queue", trace_id="request:r0", t0=100.0)
    assert s.trace_id == "request:r0"
    tr.end(s, t1=100.25, queue_wait_s=0.25)
    rec = tr.completed_records()[0]
    assert rec["dur_ms"] == pytest.approx(250.0)
    assert rec["attrs"]["queue_wait_s"] == 0.25
    # end is idempotent: a second close cannot rewrite the record
    tr.end(s, t1=999.0)
    assert tr.completed_records()[0]["t1"] == pytest.approx(100.25)


def test_disabled_tracer_is_inert(bus):
    tr = Tracer(enabled=False)
    with tr.span("x") as s:
        assert s is None
    assert tr.begin("y") is None
    tr.end(None)  # must be a safe no-op: call sites carry no guards
    assert not tr.completed_records() and not tr.open_spans()
    assert not [r for r in bus if r.get("event", "").startswith("span_")]


def test_span_exception_marks_status_error():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("doomed"):
            raise ValueError("boom")
    rec = tr.completed_records()[0]
    assert rec["status"] == "error" and rec["t1"] >= rec["t0"]
    assert not tr.open_spans()


# ------------------------------------------------------- chrome export

def test_chrome_trace_writer_roundtrip(tmp_path):
    path = str(tmp_path / "trace.json")
    tr = Tracer()
    with ChromeTraceWriter(path):
        with tr.trace("req-a"):
            with tr.span("prefill"):
                pass
        with tr.trace("req-b"):
            pass
    events = read_chrome_trace(path)
    assert json.load(open(path)) == events  # close() left strict JSON
    xs = [e for e in events if e.get("ph") == "X"]
    assert {e["name"] for e in xs} == {"req-a", "prefill", "req-b"}
    for e in xs:
        assert e["dur"] >= 0 and "ts" in e and e["pid"] == os.getpid()
    # one tid track per trace, each named by a metadata event
    metas = [e for e in events if e.get("ph") == "M"]
    assert len({e["tid"] for e in xs}) == 2 and len(metas) == 2


def test_chrome_trace_tolerates_crashed_file(tmp_path):
    """A run killed mid-stream leaves an unterminated array — it must
    still parse (Perfetto does; so does our reader)."""
    path = str(tmp_path / "crash.json")
    w = ChromeTraceWriter(path)
    tr = Tracer()
    with tr.trace("only"):
        pass
    w._f.flush()          # simulate death: no close(), no "]"
    w._unsubscribe()
    events = read_chrome_trace(path)
    assert [e["name"] for e in events if e.get("ph") == "X"] == ["only"]
    w.close()


def test_spans_by_trace_groups():
    tr = Tracer()
    with tr.trace("a"):
        pass
    with tr.trace("b"):
        pass
    groups = spans_by_trace(tr.completed_records())
    assert len(groups) == 2
    for spans in groups.values():
        assert len(spans) == 1


# ------------------------------------------------- prof.py satellites

def test_annotate_mirrors_to_enabled_tracer():
    # annotate resolves the trace module BY NAME at call time, so this
    # test must too (test_chip_worker's purge can split identities)
    import importlib

    prof_mod = importlib.import_module("apex_tpu.utils.prof")
    trace_mod = importlib.import_module("apex_tpu.monitor.trace")
    tr = trace_mod.Tracer()
    prev = trace_mod.set_tracer(tr)
    try:
        with prof_mod.annotate("phase", step=3):
            pass
    finally:
        trace_mod.set_tracer(prev)
    rec = tr.completed_records()[0]
    assert rec["name"] == "phase" and rec["attrs"] == {"step": 3}
    # with the default (disabled) tracer, annotate is the raw jax range
    assert trace_mod.get_tracer().enabled is False
    with prof_mod.annotate("plain"):
        pass  # no tracer side effects
    assert len(tr.completed_records()) == 1


def test_profile_rejects_nesting(monkeypatch):
    monkeypatch.setattr(jax.profiler, "start_trace", lambda logdir: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    with prof.profile("/tmp/outer"):
        with pytest.raises(RuntimeError, match="not reentrant"):
            with prof.profile("/tmp/inner"):
                pass
    # the guard resets: a fresh capture works after the region closes
    with prof.profile("/tmp/again"):
        pass


def test_steptimer_context_manager():
    t = prof.StepTimer()
    with t:
        x = jnp.ones((4,)) * 2
        t.block(x)     # sync on the output at exit
    assert t.count == 1 and t.last >= 0.0
    with t:
        pass           # un-armed: plain wall clock
    assert t.count == 2
    assert t._block_on is None
    # an aborted step records nothing (a partial duration would skew avg)
    with pytest.raises(ValueError):
        with t:
            raise ValueError("step died")
    assert t.count == 2


# ------------------------------------------------------ hbm accounting

def test_memory_accountant_samples_and_cadence(bus):
    mem = MemoryAccountant(device=_FakeHBMDev(), every=2)
    assert mem.tick("t") is None          # 1st tick skipped (every=2)
    assert mem.tick("t") is not None      # 2nd publishes
    assert mem.samples == 1 and mem.peak_bytes_in_use == 2000
    snaps = [r for r in bus if r.get("event") == "hbm_snapshot"]
    assert len(snaps) == 1
    assert snaps[0]["kind"] == "sampled" and snaps[0]["bytes_in_use"] == 1000


def test_memory_accountant_silent_without_stats(bus):
    class NoStats:
        def memory_stats(self):
            return None

    mem = MemoryAccountant(device=NoStats())
    assert mem.sample("t") is None        # silence, never fake zeros
    assert not [r for r in bus if r.get("event") == "hbm_snapshot"]


def test_static_memory_analysis_published(bus):
    compiled = jax.jit(lambda x: x * 2).lower(
        jnp.ones((8, 8), jnp.float32)).compile()
    rec = publish_compiled_memory("unit", compiled, note="test")
    assert rec is not None
    assert rec["reserved_bytes"] == rec["argument_size_in_bytes"] + \
        rec["output_size_in_bytes"] + rec["temp_size_in_bytes"]
    snap = [r for r in bus if r.get("event") == "hbm_snapshot"][0]
    assert snap["kind"] == "static" and snap["name"] == "unit"
    assert snap["note"] == "test"


def test_ledger_summarizes_hbm():
    with GoodputLedger() as led:
        sample_device_memory("t", device=_FakeHBMDev(peak=4096))
        compiled = jax.jit(lambda x: x + 1).lower(
            jnp.ones((4,), jnp.float32)).compile()
        publish_compiled_memory("unit", compiled)
    hbm = led.summary()["hbm"]
    assert hbm["samples"] == 2
    assert hbm["peak_bytes_in_use"] == 4096
    assert hbm["static_peak_bytes"] > 0
    # runs with no snapshots keep the summary key-compatible with PR-2
    assert "hbm" not in GoodputLedger().summary()


# ----------------------------------------------------- flight recorder

def test_flight_ring_bounded_under_overflow_storm(tmp_path):
    """FaultInjector NaN burst through a traced resilient_step with the
    recorder attached: every step adds span + overflow records, the ring
    holds exactly ``capacity``, and the dump counts the drops."""
    inj = FaultInjector(seed=1).nan_burst(start=0, length=6)
    scaler = DynamicGradScaler(init_scale=2.0 ** 8, growth_interval=1000)
    tracer = Tracer()
    path = str(tmp_path / "storm_flight.json")
    fr = FlightRecorder(path, capacity=8, tracer=tracer).attach()

    def train_step(params, sstate, grads):
        new = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params,
                                     grads)
        from apex_tpu.multi_tensor.functional import tree_check_finite
        return new, tree_check_finite(grads), jnp.float32(1.0)

    step = resilient_step(train_step, scaler, tracer=tracer,
                          max_consecutive_overflows=3)
    params = {"w": jnp.ones((4,))}
    sstate = scaler.init()
    grads = {"w": jnp.full((4,), 0.5)}
    for i in range(6):
        params, sstate, _inf, _loss = step(params, sstate,
                                           inj.poison_grads(grads, i))
    fr.detach()
    assert step.skipped_steps == 6
    assert len(fr.events) == 8                 # the bound held
    assert fr.total_events > 8
    d = json.load(open(fr.dump("test")))
    assert d["dropped_events"] == d["total_events"] - len(d["events"])

    # one trace per train step: root + forward_backward + unscale children
    roots = [r for r in tracer.completed_records()
             if r["name"] == "train_step"]
    assert len(roots) == 6
    by_trace = spans_by_trace(tracer.completed_records())
    for root in roots:
        names = {s["name"] for s in by_trace[root["trace_id"]]}
        assert names == {"train_step", "forward_backward",
                         "unscale_grad_norm"}


def test_flight_dump_schema_and_atomicity(tmp_path, monkeypatch):
    import sys

    # resolve the module BACKING the class: test_chip_worker's purge can
    # leave a reimported apex_tpu.monitor.flight coexisting with the
    # collection-time one these tests hold — patch the one in use
    flight_mod = sys.modules[FlightRecorder.__module__]

    path = str(tmp_path / "flight.json")
    tracer = Tracer()
    fr = FlightRecorder(path, capacity=16, tracer=tracer).attach()
    sample_device_memory("t", device=_FakeHBMDev())
    publish_event("serve_decode_step", seconds=0.001, active=1)
    open_span = tracer.begin("decode", trace_id="request:r9")
    fr.dump("manual")
    fr.detach()

    d = json.load(open(path))
    for key in ("schema", "reason", "t", "pid", "capacity", "total_events",
                "dropped_events", "events", "open_spans", "hbm_snapshot",
                "thread_stacks"):
        assert key in d, key
    assert d["reason"] == "manual" and d["schema"] == 1
    assert d["hbm_snapshot"]["bytes_in_use"] == 1000
    assert [s["name"] for s in d["open_spans"]] == ["decode"]
    assert any("test_flight_dump" in "".join(frames)
               for frames in d["thread_stacks"].values())
    assert not os.path.exists(path + ".tmp")   # staging was replaced away

    # a dump that dies mid-write must leave the PREVIOUS dump intact
    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(flight_mod.json, "dump", boom)
    with pytest.raises(OSError):
        fr.dump("second")
    assert json.load(open(path))["reason"] == "manual"
    tracer.end(open_span)


def test_flight_guard_dumps_on_fatal_exception(tmp_path):
    """The one death with no bus record: guard() (used by
    ServeScheduler.run) dumps and re-raises the original error."""
    path = str(tmp_path / "exc_flight.json")
    fr = FlightRecorder(path, capacity=8).attach()
    publish_event("serve_decode_step", seconds=0.001, active=1)
    with pytest.raises(RuntimeError, match="engine died"):
        with fr.guard("serve"):
            raise RuntimeError("engine died")
    fr.detach()
    d = json.load(open(path))
    assert d["reason"] == "exception:RuntimeError:serve"
    assert any(r.get("event") == "serve_decode_step" for r in d["events"])


def test_flight_auto_dump_on_preemption(tmp_path):
    """The postmortem acceptance path: a preemption request leaves a dump
    with the open spans, last-N events, and the hbm snapshot — with zero
    wiring beyond attach() (the trigger record rides the bus)."""
    path = str(tmp_path / "preempt_flight.json")
    tracer = Tracer()
    fr = FlightRecorder(path, capacity=32, tracer=tracer).attach()
    sample_device_memory("t", device=_FakeHBMDev(peak=7777))
    span = tracer.begin("decode", trace_id="request:r1")
    guard = PreemptionGuard()            # no handlers needed for the test
    guard.request_stop()
    assert guard.should_stop()           # announce -> preemption_requested
    fr.detach()
    d = json.load(open(path))
    assert d["reason"] == "preemption_requested"
    assert [s["name"] for s in d["open_spans"]] == ["decode"]
    assert d["hbm_snapshot"]["peak_bytes_in_use"] == 7777
    assert any(r.get("event") == "preemption_requested"
               for r in d["events"])
    tracer.end(span)


def test_flight_auto_dump_on_watchdog_escalation(tmp_path, capsys):
    path = str(tmp_path / "stall_flight.json")
    fr = FlightRecorder(path, capacity=32).attach()
    wd = CollectiveWatchdog(timeout_s=0.02, escalate="dump")
    with wd:
        with wd.watch("allreduce:grads"):
            deadline = time.time() + 2.0
            while not os.path.exists(path) and time.time() < deadline:
                time.sleep(0.005)
    fr.detach()
    d = json.load(open(path))
    assert d["reason"] == "collective_stall"
    stall = [r for r in d["events"]
             if r.get("event") == "collective_stall"][0]
    assert stall["name"] == "allreduce:grads" and stall["escalate"] == "dump"
    # the watchdog's stderr stack dump shares the flight formatting
    assert "thread stacks" in capsys.readouterr().err


def test_thread_stacks_sees_all_threads():
    import threading

    done = threading.Event()
    started = threading.Event()

    def worker():
        started.set()
        done.wait(5.0)

    t = threading.Thread(target=worker, name="flight-test-worker",
                         daemon=True)
    t.start()
    started.wait(5.0)
    try:
        stacks = thread_stacks()
    finally:
        done.set()
        t.join(5.0)
    assert any("flight-test-worker" in label for label in stacks)
    assert all(isinstance(frames, list) for frames in stacks.values())
