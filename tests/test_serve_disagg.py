"""Disaggregated prefill/decode tier-1: chain-hash-certified page
streaming, exactly-once across the handoff, the drain-flush gate, the
SLO-driven autoscaler under seeded diurnal traffic, and fleet-of-meshes
(tp x replicas) bit-exactness.

THE invariant under test (ISSUE 16 acceptance): under a seeded schedule
mixing kill-prefill + corrupt-page-in-flight + stall-handoff, every
greedy completion is bit-identical to the non-disaggregated fleet (a
refused or lost handoff degrades to a local re-prefill — the PR-5
invariant makes that bit-exact), every request settles exactly once
fleet-wide, and no surviving replica recompiles (``decode_traces``
delta 0).

Engines are compiled once per module and shared via ``Engine.reset()``;
the autoscaler test runs fully clock-injected (no worker threads), so
its diurnal day replays deterministically.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.monitor.goodput import GoodputLedger
from apex_tpu.monitor.slo import SLObjective, SLOTracker
from apex_tpu.resilience.fault_injection import FaultInjector
from apex_tpu.serve.disagg import (Autoscaler, DisaggController,
                                   DiurnalTraffic)
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.fleet import (REPLICA_DRAINED, REPLICA_DRAINING,
                                  EngineReplica, FleetController)
from apex_tpu.serve.metrics import ServeMetrics
from apex_tpu.serve.resilience import AdmissionController
from apex_tpu.serve.scheduler import Request, ServeScheduler
# bound at collection time: test_chip_worker purges apex_tpu.* from
# sys.modules mid-session (see test_serve_resilience for the history)
from apex_tpu.utils.logging import subscribe_events

pytestmark = [pytest.mark.serve, pytest.mark.fault]

CFG = GPT2Config(vocab_size=61, n_positions=32, n_embd=16, n_layer=1,
                 n_head=2, compute_dtype=jnp.float32)
PAGE = 4


@pytest.fixture(scope="module")
def params():
    return init_gpt2_params(CFG, seed=0)


@pytest.fixture(scope="module")
def engines(params):
    """Four 2-slot greedy PAGED engines sharing one param pytree (the
    fleet bit-exactness precondition) — enough for 1 prefill + 2 decode
    + 1 oracle; tests reset()."""
    return [Engine(CFG, params,
                   EngineConfig(num_slots=2, max_len=32, temperature=0.0,
                                page_size=PAGE, num_pages=24,
                                prefix_cache=True),
                   seed=0).aot_compile([4, 8])
            for _ in range(4)]


@pytest.fixture(scope="module")
def tp_engines(params):
    """Two tp=2 replicas, each owning its OWN serving mesh — the
    fleet-of-meshes configuration PR 15 left mutually exclusive."""
    return [Engine(CFG, params,
                   EngineConfig(num_slots=2, max_len=32,
                                temperature=0.0, tp=2),
                   seed=0).aot_compile([8])
            for _ in range(2)]


def _tokens(n, seed=7, vocab=61):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, vocab, n)]


def _requests(n=6, max_new=4, **kw):
    # lens 6..8: every prompt spans >= 1 full page (handoff-eligible),
    # len 8 spans two — the chain has a link to break
    return [Request(request_id=f"r{i}", tokens=_tokens(6 + i % 3, seed=i),
                    max_new_tokens=max_new, **kw) for i in range(n)]


def _oracle(engine, reqs):
    """Greedy outputs from a plain single-engine scheduler — the
    bit-exactness reference every disaggregated path must match."""
    sched = ServeScheduler(engine.reset())
    for r in reqs:
        sched.submit(Request(request_id=r.request_id,
                             tokens=list(r.tokens),
                             max_new_tokens=r.max_new_tokens))
    sched.run(max_steps=2_000)
    done, _ = sched.done_since(0)
    return {q.request_id: q.record()["generated"] for q in done}


def _disagg_handles(engines, prefills=1, decodes=2):
    hs = [EngineReplica(f"p{i}", engines[i].reset(), role="prefill")
          for i in range(prefills)]
    hs += [EngineReplica(f"d{i}", engines[prefills + i].reset(),
                         role="decode")
           for i in range(decodes)]
    return hs


def _assert_exactly_one_terminal_fleetwide(stats, expected_ids):
    recs = stats.requests
    ids = [r["request_id"] for r in recs]
    assert sorted(ids) == sorted(expected_ids), \
        (sorted(set(expected_ids) - set(ids)),
         sorted(set(ids) - set(expected_ids)))
    assert len(ids) == len(set(ids)), "a request settled twice"
    for r in recs:
        assert r["state"] in ("completed", "evicted", "rejected"), r


# ---------------------------------------------- page export/import seam

def test_export_import_bit_exact_and_duplicate_idempotent(engines):
    """The transport seam under the handoff: committed pages exported
    from one engine install into another, admission finds them as
    prefix hits, greedy output is bit-identical — and re-importing the
    same stream is a no-op (duplicate-stream exactly-once)."""
    prompt = _tokens(8, seed=3)
    a, b = engines[0].reset(), engines[1].reset()
    sa = ServeScheduler(a)
    sa.submit(Request(request_id="seed", tokens=list(prompt),
                      max_new_tokens=1))
    sa.run(max_steps=50)

    payloads = sa.export_prefix_pages(list(prompt))
    assert len(payloads) == 2              # 8 tokens / page_size 4
    for p in payloads:
        assert set(p) >= {"chain_hash", "k", "v", "digest"}

    sb = ServeScheduler(b)
    first = sb.import_prefix_pages(payloads)
    assert first["installed"] == 2 and first["duplicate"] == 0
    again = sb.import_prefix_pages(payloads)
    assert again["installed"] == 0 and again["duplicate"] == 2, \
        "a duplicate stream must be absorbed, not double-installed"

    traces = b.decode_traces
    sb.submit(Request(request_id="real", tokens=list(prompt),
                      max_new_tokens=4))
    sb.run(max_steps=50)
    done, _ = sb.done_since(0)
    rec, = [q.record() for q in done]
    assert sb.prefix_hits >= 1, "migrated pages were not reused"
    assert b.decode_traces == traces, "imported pages forced a retrace"
    assert rec["generated"] == _oracle(engines[2], [Request(
        request_id="real", tokens=list(prompt), max_new_tokens=4)])["real"]


# ------------------------------------------- corruption: refuse + fallback

def test_single_bit_flip_refused_then_bit_exact_fallback(engines):
    """ISSUE 16 satellite: one flipped bit in an in-flight K payload is
    caught by the payload digest, the receiver refuses the chain
    (exactly one ``serve_handoff_refused``), installs nothing, and the
    request completes bit-exactly via local re-prefill."""
    req = Request(request_id="c0", tokens=_tokens(8, seed=11),
                  max_new_tokens=4)
    oracle = _oracle(engines[2], [req])

    inj = FaultInjector(seed=0).corrupt_page_in_flight(nth=1)
    fleet = DisaggController(
        _disagg_handles(engines, prefills=1, decodes=1),
        heartbeat_ms=25, suspect_misses=5_000, dead_misses=10_000,
        fault_injector=inj)
    refusals = []
    unsub = subscribe_events(
        lambda r: refusals.append(r)
        if r.get("event") == "serve_handoff_refused" else None)
    try:
        fleet.submit(Request(request_id="c0", tokens=list(req.tokens),
                             max_new_tokens=4))
        with GoodputLedger() as led:
            stats = fleet.run(max_wall_s=30)
    finally:
        unsub()

    rec, = stats.requests
    assert rec["state"] == "completed"
    assert rec["generated"] == oracle["c0"], \
        "refusal fallback drifted from the no-disagg oracle"
    assert stats.handoffs == 1 and stats.handoffs_refused == 1
    assert stats.handoffs_delivered == 0
    assert stats.pages_migrated == 0, \
        "a refused chain must truncate BEFORE the corrupt page"
    assert len(refusals) == 1
    assert refusals[0]["reason"] == "digest"
    assert refusals[0]["page_index"] == 0
    g = led.summary()
    assert g["events"]["serve_handoff_refused"] == 1
    assert g["events"].get("serve_page_migrated", 0) == 0


def test_torn_chain_truncates_but_keeps_certified_prefix(engines):
    """Corruption mid-chain: pages before the break install (certified
    individually), the tail is refused, decode re-prefills only the
    uncovered suffix — still bit-exact."""
    req = Request(request_id="t0", tokens=_tokens(8, seed=13),
                  max_new_tokens=4)
    oracle = _oracle(engines[2], [req])

    inj = FaultInjector(seed=0).corrupt_page_in_flight(nth=2)
    fleet = DisaggController(
        _disagg_handles(engines, prefills=1, decodes=1),
        heartbeat_ms=25, suspect_misses=5_000, dead_misses=10_000,
        fault_injector=inj)
    fleet.submit(Request(request_id="t0", tokens=list(req.tokens),
                         max_new_tokens=4))
    stats = fleet.run(max_wall_s=30)
    rec, = stats.requests
    assert rec["state"] == "completed"
    assert rec["generated"] == oracle["t0"]
    assert stats.handoffs_refused == 1
    assert stats.pages_migrated == 1, \
        "the certified prefix of a torn chain should still land"


# ------------------------------------------------- headline chaos smoke

def test_disagg_chaos_bit_exact_exactly_once_no_recompiles(engines):
    """ISSUE 16 acceptance: a seeded schedule mixing a prefill-replica
    kill, an in-flight page corruption, and a stalled handoff against a
    1-prefill + 2-decode fleet. Greedy completions stay bit-identical
    to the same requests on a non-disaggregated fleet, every request
    settles exactly once, no surviving replica recompiles, and the
    handoff ledger reconciles with the goodput ledger event-for-event."""
    reqs = _requests()
    base_handles = [EngineReplica(f"u{i}", engines[1 + i].reset(),
                                  role="unified") for i in range(2)]
    base_fleet = DisaggController(base_handles, heartbeat_ms=25,
                                  suspect_misses=5_000,
                                  dead_misses=10_000)
    assert base_fleet.disagg is False      # degrades to the base router
    for r in _requests():
        base_fleet.submit(r)
    base = {r["request_id"]: r["generated"]
            for r in base_fleet.run(max_wall_s=30).requests}

    handles = _disagg_handles(engines)
    traces = [h.engine.decode_traces for h in handles]
    inj = (FaultInjector(seed=0)
           .kill_prefill_replica("p0", at_tick=3)
           .corrupt_page_in_flight(nth=2)
           .stall_handoff(0.02, at_handoff=1))
    fleet = DisaggController(handles, heartbeat_ms=25,
                             suspect_misses=50, dead_misses=200,
                             hedge_ms=150.0, fault_injector=inj)
    for r in reqs:
        fleet.submit(r)
    with GoodputLedger() as led:
        stats = fleet.run(max_wall_s=45)

    assert handles[0].crashed, "the seeded prefill kill never fired"
    assert [h.engine.decode_traces for h in handles] == traces, \
        "a replica retraced decode across the disaggregation chaos"
    _assert_exactly_one_terminal_fleetwide(
        stats, [f"r{i}" for i in range(6)])
    got = {r["request_id"]: r for r in stats.requests}
    for rid, gen in base.items():
        assert got[rid]["state"] == "completed"
        assert got[rid]["generated"] == gen, \
            f"{rid} drifted across kill+corrupt+stall"
    # every begun handoff resolves exactly once, through exactly one door
    assert stats.handoffs >= 1
    assert (stats.handoffs_delivered + stats.handoffs_refused
            + stats.handoffs_abandoned) == stats.handoffs
    g = led.summary()
    assert g["events"].get("serve_page_migrated", 0) == \
        stats.pages_migrated
    assert g["events"].get("serve_handoff_refused", 0) == \
        stats.handoffs_refused
    assert g["events"].get("serve_handoff_wait", 0) == stats.handoffs, \
        "a handoff resolved without charging its wait (or twice)"
    s = stats.summary()
    assert s["prefill_jobs"] == stats.handoffs
    # the clone accounting note on DisaggStats: real completions =
    # attempts completed - prefill jobs completed
    assert s["attempts"]["completed"] >= len(
        [r for r in stats.requests if r["state"] == "completed"])


# ----------------------------------------------- drain flushes handoffs

def test_draining_prefill_flushes_inflight_handoffs_before_drained(
        engines):
    """ISSUE 16 bugfix regression: a draining prefill replica holding a
    committed-but-undelivered handoff must flush it (pages land, the
    real request dispatches) BEFORE ``serve_replica_drained`` — never
    report drained with pages still in flight. Clock-free and
    worker-free, so the interleaving is exact."""
    prompt = _tokens(8, seed=17)
    oracle = _oracle(engines[2], [Request(
        request_id="f0", tokens=list(prompt), max_new_tokens=3)])

    inj = FaultInjector(seed=0).stall_handoff(60.0, at_handoff=1)
    handles = _disagg_handles(engines, prefills=1, decodes=1)
    p0, d0 = handles
    fleet = DisaggController(handles, heartbeat_ms=25,
                             suspect_misses=5_000, dead_misses=10_000,
                             fault_injector=inj)
    order = []
    unsub = subscribe_events(
        lambda r: order.append(r["event"])
        if r.get("event") in ("serve_page_migrated",
                              "serve_replica_drained") else None)
    try:
        fleet.submit(Request(request_id="f0", tokens=list(prompt),
                             max_new_tokens=3))
        for _ in range(10):                 # commit the clone prefill
            p0.scheduler.step()
        p0.publish_progress()
        fleet.pump()                        # commit seen; stalled 60s
        assert p0.pending_handoffs == 1
        assert fleet.handoffs_delivered == 0

        fleet.drain("p0", wait=False)
        assert fleet.registry.state("p0") == REPLICA_DRAINING, \
            "drained with a committed handoff still in flight"
        fleet.pump()                        # DRAINING overrides the stall
        assert fleet.handoffs_delivered == 1
        assert fleet.pages_migrated == 2
        assert p0.pending_handoffs == 0
        assert fleet.registry.state("p0") == REPLICA_DRAINED
        assert "serve_page_migrated" in order \
            and "serve_replica_drained" in order
        assert order.index("serve_page_migrated") \
            < order.index("serve_replica_drained"), \
            "drained was announced before the flush landed"

        for _ in range(20):                 # finish the real request
            d0.scheduler.step()
        d0.publish_progress()
        fleet.pump()
        rec = fleet._requests["f0"].record
        assert rec is not None and rec["state"] == "completed"
        assert rec["generated"] == oracle["f0"]
        assert d0.scheduler.prefix_hits >= 1, \
            "the flushed pages were not what decode admitted from"
    finally:
        unsub()


# ------------------------------------------------------- autoscaler e2e

def test_autoscaler_diurnal_scale_up_down_without_flapping(engines):
    """ISSUE 16 acceptance: one clock-injected diurnal day (trough ->
    peak -> trough) against an SLO-armed decode pool. The peak burns
    the shed budget -> at least one scale-up; the falling edge recovers
    -> at least one scale-down; capacity never leaves
    [min_replicas, max_replicas]; hysteresis + cooldown bound total
    actions; burn ends recovered."""
    t = [1_000.0]
    clock = lambda: t[0]                                     # noqa: E731

    def tracker():
        return SLOTracker([SLObjective.shed_frac(
            0.1, min_events=4, short_window_s=20.0,
            long_window_s=100.0)], clock=clock)

    def handle(rid, engine):
        return EngineReplica(
            rid, engine.reset(), role="decode",
            admission=AdmissionController(max_queue=2),
            metrics=ServeMetrics(slo=tracker()))

    fleet = DisaggController([handle("d0", engines[0])],
                             heartbeat_ms=25, suspect_misses=10**9,
                             dead_misses=2 * 10**9, clock=clock)
    spawned = []

    def factory():
        h = handle(f"d{1 + len(spawned)}", engines[1 + len(spawned)])
        spawned.append(h.replica_id)
        return h

    scaler = Autoscaler(fleet, role="decode", min_replicas=1,
                        max_replicas=2, factory=factory, up_burn=1.0,
                        down_burn=0.25, evals=2, cooldown_s=10.0,
                        clock=clock)
    fleet.autoscaler = scaler               # pump() ticks it

    day_s = 240.0
    # peak ~1 rps against ~0.66 rps of single-replica service below
    mean_rps = 0.625
    traffic = DiurnalTraffic(
        day_s=day_s, seed=3, prompt_lens=(4,), max_new_tokens=4,
        vocab=CFG.vocab_size, clock=clock,
        capacity_scale=mean_rps / (2_000_000 * 8.0 / 86400.0))
    traffic.start(t[0])

    active_trace, burn_trace, first_up_t = [], [], None
    for _ in range(int(day_s / 2.0)):
        t[0] += 2.0
        for r in traffic.due(t[0]):
            fleet.submit(r)
        for h in fleet.handles:             # bounded service per tick
            if not h.crashed:
                h.scheduler.step()
                h.publish_progress()
                h.metrics.slo.evaluate(now=t[0])
        fleet.pump()
        active_trace.append(len(scaler.active()))
        burn_trace.append(scaler.signals()["burn"])
        if scaler.scale_ups and first_up_t is None:
            first_up_t = t[0]

    assert traffic.emitted >= 100, "the diurnal day produced no load"
    assert scaler.scale_ups >= 1, \
        f"peak never scaled up (max burn {max(burn_trace):.2f})"
    assert scaler.scale_downs >= 1, \
        f"trough never scaled down (min burn {min(burn_trace):.2f})"
    assert min(active_trace) >= 1, "capacity fell below min_replicas"
    assert max(active_trace) <= 2, "capacity exceeded max_replicas"
    assert scaler.scale_ups + scaler.scale_downs <= 6, \
        f"flapping: {scaler.scale_ups} ups / {scaler.scale_downs} downs"
    assert max(burn_trace) >= scaler.up_burn     # pressure was real
    assert burn_trace[-1] < scaler.up_burn, \
        "burn never recovered after scaling"


def test_autoscaler_warm_restart_prefers_drained_standby(engines):
    """A scale-up with a DRAINED standby warm-restarts it instead of
    cold-spawning — zero recompiles, no factory call."""
    t = [0.0]
    clock = lambda: t[0]                                     # noqa: E731
    mets = [ServeMetrics(slo=SLOTracker(
        [SLObjective.shed_frac(0.1, min_events=4)], clock=clock))
        for _ in range(2)]
    handles = [EngineReplica(f"d{i}", engines[i].reset(), role="decode",
                             metrics=m)
               for i, m in enumerate(mets)]
    fleet = DisaggController(handles, heartbeat_ms=25,
                             suspect_misses=5_000, dead_misses=10_000,
                             clock=clock)
    calls = []
    scaler = Autoscaler(fleet, role="decode", min_replicas=1,
                        max_replicas=2,
                        factory=lambda: calls.append(1),
                        evals=1, cooldown_s=0.0, clock=clock)
    fleet.drain("d1", wait=False)
    fleet.pump()                            # idle replica drains at once
    assert fleet.registry.state("d1") == REPLICA_DRAINED
    traces = handles[1].engine.decode_traces

    for _ in range(8):
        mets[0].slo.observe("shed", bad=True, t=t[0])
    mets[0].slo.evaluate(now=t[0])
    assert scaler.tick() == "up"
    assert fleet.registry.state("d1") == "healthy"
    assert calls == [], "cold-spawned despite a warm standby"
    assert handles[1].engine.decode_traces == traces, \
        "a warm restart must keep every compiled artifact"
    assert scaler.scale_ups == 1 and scaler.spawned == 0


def test_autoscaler_and_controller_validation(engines, params):
    fleet = DisaggController(
        [EngineReplica("d0", engines[0].reset(), role="decode")],
        heartbeat_ms=25, suspect_misses=5_000, dead_misses=10_000)
    with pytest.raises(ValueError, match="role"):
        Autoscaler(fleet, role="router")
    with pytest.raises(ValueError, match="min_replicas"):
        Autoscaler(fleet, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="down_burn"):
        Autoscaler(fleet, up_burn=0.5, down_burn=0.5)
    with pytest.raises(ValueError, match="free_frac"):
        Autoscaler(fleet, up_free_frac=0.6, down_free_frac=0.5)
    # a fleet of only prefill replicas serves nobody
    with pytest.raises(ValueError, match="serves nobody"):
        DisaggController(
            [EngineReplica("p0", engines[0].reset(), role="prefill")],
            heartbeat_ms=25)
    # disaggregation without a prefix index has nothing to stream through
    slot_engine = Engine(CFG, params,
                         EngineConfig(num_slots=2, max_len=32,
                                      temperature=0.0), seed=0)
    with pytest.raises(ValueError, match="prefix"):
        DisaggController(
            [EngineReplica("p0", engines[0].reset(), role="prefill"),
             EngineReplica("d0", slot_engine, role="decode")],
            heartbeat_ms=25)


# ------------------------------------------------------ diurnal traffic

def test_diurnal_traffic_seeded_curve_and_volume():
    def stream(seed):
        tr = DiurnalTraffic(day_s=100.0, seed=seed, prompt_lens=(4, 6),
                            capacity_scale=2.0 / (2_000_000 * 8.0
                                                  / 86400.0),
                            clock=lambda: 0.0).start(0.0)
        out = []
        for i in range(1, 101):
            out.extend((r.request_id, tuple(r.tokens))
                       for r in tr.due(float(i)))
        return tr, out

    tr1, s1 = stream(5)
    _, s2 = stream(5)
    _, s3 = stream(6)
    assert s1 == s2, "same seed + same clock readings must replay"
    assert s1 != s3
    # sinusoid: trough at phase 0, peak at half-day, ratio as configured
    assert math.isclose(tr1.rate_at(50.0) / tr1.rate_at(100.0), 4.0,
                        rel_tol=1e-6)
    # volume integrates to mean_rps * day_s (2 rps * 100 s) +- residue
    assert abs(len(s1) - 200) <= 4
    with pytest.raises(RuntimeError, match="start"):
        DiurnalTraffic().due(1.0)
    with pytest.raises(ValueError, match="peak_to_trough"):
        DiurnalTraffic(peak_to_trough=0.5)


# --------------------------------------------------- fleet of meshes

def test_fleet_of_meshes_tp_replicas_bit_exact(engines, tp_engines):
    """PR 15's open edge: tp=2 composed with replicas=2. Each replica
    owns its own serving mesh, compiles once, and the fleet's greedy
    outputs match the single-chip oracle bit-for-bit."""
    for e in tp_engines:
        assert e.mesh is not None and e.mesh.shape["tp"] == 2
    reqs = [Request(request_id=f"m{i}", tokens=_tokens(8, seed=20 + i),
                    max_new_tokens=4) for i in range(3)]
    oracle = _oracle(engines[0], reqs)      # tp=1 single-chip reference

    handles = [EngineReplica(f"r{i}", e.reset(), role="unified")
               for i, e in enumerate(tp_engines)]
    traces = [e.decode_traces for e in tp_engines]
    fleet = FleetController(handles, heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000)
    for r in reqs:
        fleet.submit(Request(request_id=r.request_id,
                             tokens=list(r.tokens), max_new_tokens=4))
    stats = fleet.run(max_wall_s=30)
    _assert_exactly_one_terminal_fleetwide(stats, [r.request_id
                                                   for r in reqs])
    for rec in stats.requests:
        assert rec["state"] == "completed"
        assert rec["generated"] == oracle[rec["request_id"]], \
            f"{rec['request_id']} drifted on the sharded fleet"
    assert [e.decode_traces for e in tp_engines] == traces, \
        "a tp replica recompiled decode under fleet serving"


# ------------------------------------------- regression-gate semantics

def test_check_regression_handoff_counters_and_disagg_provenance():
    """ISSUE 16 satellite: refusal/autoscale counters are
    lower-is-better (0 -> N regresses even against a zero baseline),
    and a disaggregated suite entry is refused against a unified
    baseline instead of being numerically compared."""
    from tools.check_regression import compare, incomparable_entries

    rows, _ = compare({"serve_decode.handoff_refused": (3.0, None)},
                      {"serve_decode.handoff_refused": (0.0, None)}, 0.1)
    row, = rows
    assert row["direction"] == "lower"
    assert row["regressed"] and row["ratio"] == float("inf")
    rows, _ = compare({"serve_decode.autoscale_actions": (5.0, None)},
                      {"serve_decode.autoscale_actions": (0.0, None)},
                      0.1)
    assert rows[0]["regressed"], "autoscale churn growth must regress"
    rows, _ = compare({"serve_decode.handoff_refused": (0.0, None)},
                      {"serve_decode.handoff_refused": (0.0, None)}, 0.1)
    assert not rows[0]["regressed"]

    wl = {"tp": 1, "tp_sync": None, "disagg": True, "roles": "1:2",
          "diurnal": False}
    cur = {"serve_decode": {"value": 10.0, "workload": dict(wl)}}
    base = {"serve_decode": {"value": 10.0,
                             "workload": dict(wl, disagg=False,
                                              roles=None)}}
    assert incomparable_entries(cur, base) == {
        "serve_decode": "workload.disagg=True vs baseline "
                        "workload.disagg=False"}
    base_roles = {"serve_decode": {"value": 10.0,
                                   "workload": dict(wl, roles="2:1")}}
    assert incomparable_entries(cur, base_roles) == {
        "serve_decode": "workload.roles=1:2 vs baseline "
                        "workload.roles=2:1"}
    # a legacy baseline without the axis means its default (unified):
    # refused against a disagg run, comparable against a unified one
    legacy = {"serve_decode": {"value": 10.0, "workload": {"tp": 1}}}
    assert "serve_decode" in incomparable_entries(cur, legacy)
    unified = {"serve_decode": {
        "value": 10.0, "workload": dict(wl, disagg=False, roles=None)}}
    assert incomparable_entries(unified, legacy) == {}
    diurnal = {"serve_decode": {"value": 10.0,
                                "workload": dict(wl, disagg=False,
                                                 roles=None,
                                                 diurnal=True)}}
    assert "diurnal" in incomparable_entries(diurnal, legacy).get(
        "serve_decode", "")


# --------------------------------------------------------- CLI matrix

def test_serve_cli_disagg_flag_matrix():
    """Contradictory disaggregation/autoscale flag combinations exit 2
    with a diagnostic, before any engine is built."""
    from apex_tpu.serve.cli import main as serve_main

    bad = [
        ["--roles", "1:2"],                          # needs paging
        ["--roles", "0:2", "--page-size", "4", "--prefix-cache"],
        ["--roles", "x:y", "--page-size", "4", "--prefix-cache"],
        ["--roles", "1:1", "--replicas", "3",
         "--page-size", "4", "--prefix-cache"],      # 3 != 1+1
        ["--roles", "1:1", "--replicas", "1",
         "--page-size", "4", "--prefix-cache"],
        ["--autoscale", "--replicas", "2"],          # needs --slo
        ["--min-replicas", "2"],                     # needs --autoscale
        ["--autoscale", "--replicas", "2",
         "--slo", "ttft_p99_ms=500", "--min-replicas", "3",
         "--max-replicas", "2"],
    ]
    for argv in bad:
        assert serve_main(argv) == 2, argv


def test_bench_cli_disagg_flag_matrix(monkeypatch):
    import sys

    from apex_tpu.bench_cli import _serve_bench
    from apex_tpu.bench_cli import main as bench_main

    with pytest.raises(SystemExit, match="apex-tpu-bench"):
        _serve_bench(4, roles="1:1")                 # needs --disagg
    with pytest.raises(SystemExit, match="apex-tpu-bench"):
        _serve_bench(4, disagg=True)                 # needs paging
    with pytest.raises(SystemExit, match="apex-tpu-bench"):
        _serve_bench(4, disagg=True, page_size=4, prefix_cache=True,
                     replicas=1)
    with pytest.raises(SystemExit, match="apex-tpu-bench"):
        _serve_bench(4, disagg=True, page_size=4, prefix_cache=True,
                     roles="1:0")
    with pytest.raises(SystemExit, match="apex-tpu-bench"):
        _serve_bench(4, disagg=True, page_size=4, prefix_cache=True,
                     roles="2:2", replicas=3)
    with pytest.raises(SystemExit, match="apex-tpu-bench"):
        _serve_bench(4, diurnal=True)                # needs a fleet
    monkeypatch.setattr(sys, "argv", ["apex-tpu-bench", "--disagg"])
    with pytest.raises(SystemExit):
        bench_main()                                 # needs --serve
