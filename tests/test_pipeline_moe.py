"""Pipeline (pp) + expert (ep) parallelism tests on the 8-device CPU mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import make_mesh
from apex_tpu.parallel.moe import moe_ffn_ep, top1_dispatch
from apex_tpu.parallel.pipeline import (pipeline_apply, stack_stage_params,
                                        unstack_local)

D = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stages(p, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), p)
    return [{"w": jax.random.normal(k, (D, D)) * 0.5,
             "b": jnp.zeros((D,))} for k in ks]


class TestPipeline:
    @pytest.mark.parametrize("p,m", [(2, 4), (4, 8)])
    def test_matches_sequential(self, p, m):
        mesh = make_mesh([p], ["pp"])
        stages = _stages(p)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.PRNGKey(1), (m * 2, D))

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P("pp"), P()), out_specs=P(),
                           check_vma=False)
        def run(sp, x):
            return pipeline_apply(_stage_fn, unstack_local(sp), x, "pp", m)

        got = run(stacked, x)
        want = x
        for s in stages:
            want = _stage_fn(s, want)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.slow
    def test_backward_matches_sequential(self):
        p, m = 4, 8
        mesh = make_mesh([p], ["pp"])
        stages = _stages(p, seed=2)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.PRNGKey(3), (m, D))

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P("pp"), P()), out_specs=P(),
                           check_vma=False)
        def fwd(sp, x):
            return pipeline_apply(_stage_fn, unstack_local(sp), x, "pp", m)

        def ref_loss(stages, x):
            y = x
            for s in stages:
                y = _stage_fn(s, y)
            return jnp.sum(y * y)

        # grads THROUGH the pipelined shard_map (autodiff transposes the
        # GPipe schedule: reverse ppermutes, reverse scan)
        gx = jax.grad(lambda x: jnp.sum(fwd(stacked, x) ** 2))(x)
        gs = jax.grad(lambda sp: jnp.sum(fwd(sp, x) ** 2))(stacked)
        rx = jax.grad(lambda x: ref_loss(stages, x))(x)
        rs = [jax.grad(lambda s, i=i: ref_loss(
            stages[:i] + [s] + stages[i + 1:], x))(stages[i])
            for i in range(p)]
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   atol=1e-4, rtol=1e-4)
        for i in range(p):
            np.testing.assert_allclose(np.asarray(gs["w"][i]),
                                       np.asarray(rs[i]["w"]),
                                       atol=1e-4, rtol=1e-4)


class TestMoE:
    def test_dispatch_respects_capacity(self):
        logits = jnp.array([[9., 0.], [9., 0.], [9., 0.], [0., 9.]])
        dispatch, combine = top1_dispatch(logits, 2, capacity=2)
        # three tokens want expert 0 but capacity is 2 → one dropped
        assert float(dispatch[:, 0].sum()) == 2.0
        assert float(dispatch[3, 1].sum()) == 1.0
        assert float(dispatch[2].sum()) == 0.0  # dropped token

    def test_ep_matches_single_device(self):
        """EP over 4 devices == same MoE computed densely on one device."""
        ep, e, d, h, t = 4, 8, 16, 32, 64
        mesh = make_mesh([ep], ["ep"])
        k = jax.random.split(jax.random.PRNGKey(0), 4)
        gate_w = jax.random.normal(k[0], (d, e)) * 0.5
        w1 = jax.random.normal(k[1], (e, d, h)) * 0.2
        w2 = jax.random.normal(k[2], (e, h, d)) * 0.2
        x = jax.random.normal(k[3], (t, d))
        cap = int(t / e * 1.25)

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(), P(), P("ep"), P("ep")),
                           out_specs=P(), check_vma=False)
        def run(x, gw, w1l, w2l):
            return moe_ffn_ep(x, gw, w1l, w2l, "ep")

        got = run(x, gate_w, w1, w2)

        # dense reference with identical routing
        logits = x @ gate_w
        dispatch, combine = top1_dispatch(logits, e, cap)
        exp_in = jnp.einsum("tec,td->ecd", dispatch, x)
        z = jax.nn.gelu(jnp.einsum("ecd,edh->ech", exp_in, w1))
        out = jnp.einsum("ech,ehd->ecd", z, w2)
        want = jnp.einsum("tec,ecd->td", combine, out)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    @pytest.mark.slow
    def test_ep_differentiable(self):
        ep, e, d, h, t = 2, 4, 8, 16, 32
        mesh = make_mesh([ep], ["ep"])
        k = jax.random.split(jax.random.PRNGKey(1), 4)
        gate_w = jax.random.normal(k[0], (d, e)) * 0.5
        w1 = jax.random.normal(k[1], (e, d, h)) * 0.2
        w2 = jax.random.normal(k[2], (e, h, d)) * 0.2
        x = jax.random.normal(k[3], (t, d))

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(), P(), P("ep"), P("ep")),
                           out_specs=P(), check_vma=False)
        def loss(x, gw, w1l, w2l):
            y = moe_ffn_ep(x, gw, w1l, w2l, "ep")
            return jnp.sum(y * y)

        g = jax.grad(loss, argnums=(0, 2))(x, gate_w, w1, w2)
        for leaf in g:
            assert bool(jnp.all(jnp.isfinite(leaf)))
