"""The elastic production trainer + training chaos harness (markers:
``train`` + ``fault``).

The acceptance claims, proven deterministically on the fake-multihost
``ThreadProcessGroup`` harness:

- the Trainer is a **bit-equality oracle** of a hand-rolled loop built
  from the same public primitives — the composition (ResilientStep,
  sharded reduction, accounting) adds nothing to the math;
- updates are **world-size independent** (the canonical shard-indexed
  reduction), which is what elastic 2→1→2 restarts ride;
- a coordinated preemption drains every rank at the same step, commits
  ONE final checkpoint, and accounts exactly-once;
- a crash mid-checkpoint-commit leaves the previous committed step
  restorable (the atomic-commit discipline, injected at the trainer);
- a same-topology supervisor restart adds **zero recompiles** (trace
  counters on every jitted step-path function stay at 1);
- THE chaos smoke: preempt + crash-on-step + crash-during-save +
  elastic resize in one seeded schedule completes with bit-identical
  final params vs the uninterrupted oracle, exactly-once step accounting
  in the goodput ledger, and zero recompiles on the same-topology
  restarts.
"""

import functools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.monitor.export import MetricsRegistry
from apex_tpu.optimizers.functional import adam_update
from apex_tpu.resilience import (FaultInjector, ShardedCheckpointManager,
                                 SimulatedCrash, SingleProcessCoordinator)
from apex_tpu.train import (TrainConfig, Trainer, TrainSupervisor,
                            make_scaler, tiny_lm_batch, tiny_lm_params)
from apex_tpu.train.cli import main as train_cli_main
from apex_tpu.utils.logging import subscribe_events

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.train, pytest.mark.fault]


def _cfg(seed, **kw):
    base = dict(steps=10, batch=8, seq=12, vocab=64, hidden=24,
                grad_shards=2, seed=seed)
    base.update(kw)
    return TrainConfig(**base)


def _oracle_params(seed, **kw):
    """Uninterrupted single-rank reference run (params only)."""
    tr = Trainer(_cfg(seed, **kw))
    tr.run()
    try:
        return tr.params
    finally:
        tr.close()


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.fixture
def events():
    collected = []
    unsub = subscribe_events(collected.append)
    yield collected
    unsub()


def _named(events, name):
    return [e for e in events if e.get("event") == name]


# ------------------------------------------------ hand-rolled oracle

def test_trainer_matches_hand_rolled_loop_bit_exact():
    """The Trainer IS the hand-rolled loop: same public primitives
    (seeded init/batches, scaler, canonical shard-order reduction, fused
    Adam, skip-on-overflow, floor), composed by hand — final params
    bit-identical, and the loss falls."""
    cfg = _cfg(seed=11)
    scaler = make_scaler(cfg)
    G, inv = cfg.grad_shards, 1.0 / cfg.grad_shards

    def loss_fn(p, tokens):
        x = p["emb"][tokens[:, :-1]]
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        logp = jax.nn.log_softmax((h @ p["head"]).astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, tokens[:, 1:, None], axis=-1)
        return jnp.mean(nll)

    @jax.jit
    def shard_grads(p, sstate, tokens):
        def scaled(p):
            loss = loss_fn(p, tokens)
            return scaler.scale(loss, sstate), loss

        (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(p)
        return grads, loss

    @jax.jit
    def apply(p, m, v, sstate, gsum, t):
        grads = jax.tree_util.tree_map(lambda g: g * inv, gsum)
        grads, _, found_inf = scaler.unscale_and_norm(grads, sstate)
        new_p, m, v = adam_update(p, grads, m, v, step=t + 1, lr=cfg.lr,
                                  found_inf=found_inf)
        # the ResilientStep post-step, by hand: keep old values on
        # overflow, advance the scale state machine, apply the floor
        kept = jax.tree_util.tree_map(
            lambda n, o: jnp.where(found_inf, o, n), (new_p, m, v),
            (p, m, v))
        sstate = scaler.update(sstate, found_inf)
        sstate = sstate._replace(scale=jnp.maximum(
            sstate.scale, jnp.float32(cfg.scale_floor)))
        return kept, sstate

    params = tiny_lm_params(cfg)
    zeros = lambda x: jnp.zeros_like(x, jnp.float32)  # noqa: E731
    m = jax.tree_util.tree_map(zeros, params)
    v = jax.tree_util.tree_map(zeros, params)
    sstate = scaler.init()
    losses = []
    for t in range(cfg.steps):
        tokens = tiny_lm_batch(cfg, t)
        shards = tokens.reshape((G, cfg.batch // G, cfg.seq))
        parts = [shard_grads(params, sstate, shards[i]) for i in range(G)]
        gsum = functools.reduce(
            lambda a, b: jax.tree_util.tree_map(jnp.add, a, b),
            (g for g, _ in parts))
        losses.append(float(
            functools.reduce(jnp.add, (l for _, l in parts)) * inv))
        (params, m, v), sstate = apply(params, m, v, sstate, gsum,
                                       jnp.int32(t))

    trainer = Trainer(_cfg(seed=11))
    step_losses = []
    trainer.run(on_step=lambda t, loss: step_losses.append(loss))
    try:
        _assert_trees_equal(trainer.params, params)
        _assert_trees_equal((trainer.m, trainer.v), (m, v))
        # per-step losses identical too (not just the endpoint), and the
        # run actually trained (params moved; the lm_pretrain example
        # covers loss-falls on real structure — these tokens are random)
        np.testing.assert_allclose(step_losses, losses, rtol=0, atol=0)
        assert len(set(step_losses)) > 1
    finally:
        trainer.close()


def test_world_sizes_produce_bit_identical_updates():
    """The canonical shard-indexed reduction: world 1 and world 2 runs of
    the same config produce bit-identical params — the foundation every
    elastic restore stands on."""
    oracle = _oracle_params(seed=12)
    sup = TrainSupervisor(_cfg(seed=12, world=2))
    rep = sup.run()
    assert rep["final_step"] == 9 and not rep["preempted"]
    _assert_trees_equal(sup.params(), oracle)
    # exactly-once: every step productive, none replayed
    assert rep["goodput"]["steps"] == 10
    assert rep["steps_retried"] == 0


# ------------------------------------------------ preemption drain

def test_coordinated_preemption_drains_once_and_resumes(tmp_path,
                                                        events):
    """A preemption on rank 1 is agreed collectively: both ranks drain at
    the same step, ONE final checkpoint commits, rank 0 publishes exactly
    one timed train_preempt_drain, accounting is exactly-once across the
    drain + resume, and the resumed job finishes bit-identical to the
    uninterrupted oracle."""
    oracle = _oracle_params(seed=13)
    inj = FaultInjector(seed=13).preempt_at_step(4, rank=1)
    cfg = _cfg(seed=13, world=2, checkpoint_dir=str(tmp_path))
    sup = TrainSupervisor(cfg, injector=inj, world_schedule=[2])
    rep = sup.run()
    assert rep["preempted"] and rep["preempt_drains"] == 1
    drained_at = rep["final_step"]
    assert drained_at == 4  # the agreement lands at the SAME boundary
    drains = _named(events, "train_preempt_drain")
    assert len(drains) == 1 and drains[0]["step"] == drained_at
    assert drains[0]["seconds"] > 0  # timed: the ledger charges it
    assert "train_preempt_drain" in rep["goodput"]["lost_by_cause"]
    # ONE final checkpoint at the drain step, atomically committed
    mgr = ShardedCheckpointManager(str(tmp_path),
                                   coordinator=SingleProcessCoordinator())
    assert mgr.latest_step() == drained_at
    assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))
    # exactly-once across drain + resume: the two jobs' ledgers
    # partition the step indices
    assert rep["goodput"]["steps"] == drained_at + 1
    sup2 = TrainSupervisor(cfg, world_schedule=[2])
    rep2 = sup2.run()
    assert not rep2["preempted"] and rep2["final_step"] == 9
    assert rep2["goodput"]["steps"] == 10 - (drained_at + 1)
    _assert_trees_equal(sup2.params(), oracle)


# ------------------------------------------------ crash mid-commit

def test_crash_mid_checkpoint_save_keeps_previous_commit(tmp_path):
    """A death on the first write into a checkpoint's .tmp staging leaves
    the previous committed step fully restorable (nothing half-written is
    ever visible), and the recovered run finishes bit-identical."""
    oracle = _oracle_params(seed=14)
    inj = FaultInjector(seed=14).crash_during_checkpoint_save(6)
    cfg = _cfg(seed=14, checkpoint_dir=str(tmp_path), save_every=2)
    trainer = Trainer(cfg, injector=inj)
    with pytest.raises(SimulatedCrash):
        trainer.run()
    trainer.close()
    # the crashed step 6 never committed; step 4's commit is intact
    mgr = ShardedCheckpointManager(str(tmp_path),
                                   coordinator=SingleProcessCoordinator())
    assert mgr.latest_step() == 4
    # recovery: a fresh attempt restores step 4, replays, and the
    # re-save of step 6 (schedule consumed) commits cleanly
    trainer2 = Trainer(cfg, injector=inj)
    rep = trainer2.run()
    try:
        assert rep["restored_from"] == 4
        assert rep["final_step"] == 9
        _assert_trees_equal(trainer2.params, oracle)
    finally:
        trainer2.close()
    assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))


def test_restart_budget_exhaustion_preserves_last_commit(tmp_path):
    """A fault that outlives the restart budget propagates (the job
    fails loudly) — and the last committed checkpoint is still the
    restore target, not a torn write."""
    inj = FaultInjector(seed=15).crash_on_train_step(5, times=10)
    cfg = _cfg(seed=15, checkpoint_dir=str(tmp_path), save_every=2)
    sup = TrainSupervisor(cfg, injector=inj, max_restarts=1,
                          backoff_s=0.01)
    with pytest.raises(SimulatedCrash):
        sup.run()
    assert sup.restarts == 1
    mgr = ShardedCheckpointManager(str(tmp_path),
                                   coordinator=SingleProcessCoordinator())
    assert mgr.latest_step() == 4  # steps 0..4 ran; 4 was the last save
    assert mgr.restore_latest(Trainer(cfg)._tree(0)) is not None


# ------------------------------------------------ elastic restarts

def test_elastic_2_1_2_restore_bit_exact(tmp_path, events):
    """Acceptance: drain at world 2, resume at world 1, finish back at
    world 2 — every leg restores the same sharded checkpoint at a
    different data-parallel world size, publishes train_elastic_resized,
    and the final params are bit-identical to the uninterrupted run."""
    oracle = _oracle_params(seed=16)
    inj = (FaultInjector(seed=16)
           .preempt_at_step(3, rank=1)
           .preempt_at_step(6, rank=0))
    cfg = _cfg(seed=16, world=2, checkpoint_dir=str(tmp_path))
    sup = TrainSupervisor(cfg, injector=inj, world_schedule=[2, 1, 2])
    rep = sup.run()
    assert not rep["preempted"] and rep["final_step"] == 9
    assert rep["preempt_drains"] == 2
    assert rep["worlds"] == [2, 1, 2]
    _assert_trees_equal(sup.params(), oracle)
    resizes = [(e["from_world"], e["to_world"])
               for e in _named(events, "train_elastic_resized")]
    assert (2, 1) in resizes and (1, 2) in resizes
    # exactly-once accounting spans all three legs (one supervisor ledger)
    assert rep["goodput"]["steps"] == 10
    assert rep["goodput"]["skipped_steps"] == 0


# ------------------------------------------------ zero recompiles

def test_same_topology_restart_adds_zero_recompiles(tmp_path, events):
    """A supervisor warm restart reuses every compiled artifact: across a
    crash + restart + replay, each jitted step-path function (per-shard
    grads, post-exchange apply, ResilientStep post) traces exactly once,
    replayed steps charge train_replay (never productive twice), and the
    result is bit-identical."""
    oracle = _oracle_params(seed=17)
    inj = FaultInjector(seed=17).crash_on_train_step(6)
    cfg = _cfg(seed=17, checkpoint_dir=str(tmp_path), save_every=2)
    sup = TrainSupervisor(cfg, injector=inj, max_restarts=2,
                          backoff_s=0.01)
    rep = sup.run()
    assert rep["restarts"] == 1 and rep["final_step"] == 9
    counts = sup.trace_counts()
    assert counts == {"shard_grads": 1, "apply": 1, "post": 1}, counts
    _assert_trees_equal(sup.params(), oracle)
    # rollback to step 4's commit replays 5 before reaching the crash
    # point — accounted as train_replay, productive steps exactly-once
    assert rep["steps_retried"] == 1
    assert len(_named(events, "train_step_replayed")) == 1
    assert rep["goodput"]["steps"] == 10
    assert rep["goodput"]["lost_by_cause"]["train_replay"] > 0
    assert len(_named(events, "train_restart")) == 1


# ------------------------------------------------ THE chaos smoke

def test_chaos_schedule_bit_identical_and_exactly_once(tmp_path, events):
    """Acceptance: one seeded schedule mixing coordinated preemption,
    elastic resize (2 -> 1 -> 2), a fatal mid-step crash, and a death
    mid-checkpoint-commit completes with (a) bit-identical final params
    vs the uninterrupted oracle, (b) exactly-once step accounting in the
    goodput ledger, (c) zero recompiles on the same-topology restarts."""
    steps = 12
    oracle = _oracle_params(seed=18, steps=steps)
    inj = (FaultInjector(seed=18)
           .preempt_at_step(3, rank=1)       # drain -> resize 2 -> 1
           .preempt_at_step(7, rank=0)       # drain -> resize 1 -> 2
           .crash_on_train_step(9)           # warm restart, same topology
           .crash_during_checkpoint_save(8))  # death mid-commit
    cfg = _cfg(seed=18, steps=steps, world=2,
               checkpoint_dir=str(tmp_path), save_every=2)
    sup = TrainSupervisor(cfg, injector=inj, max_restarts=3,
                          backoff_s=0.01, world_schedule=[2, 1, 2])
    rep = sup.run()
    assert not rep["preempted"] and rep["final_step"] == steps - 1
    assert rep["preempt_drains"] == 2
    assert rep["restarts"] == 2  # crash-step + crash-save, both survived
    # (a) bit-identical to the uninterrupted run
    _assert_trees_equal(sup.params(), oracle)
    # (b) exactly-once: every step index productive once; replays ride
    # the train_replay cause, never the productive count
    good = rep["goodput"]
    assert good["steps"] == steps and good["skipped_steps"] == 0
    assert rep["steps_retried"] >= 1
    assert good["lost_by_cause"]["train_replay"] > 0
    assert good["events"]["train_preempt_drain"] == 2
    assert good["events"]["train_restart"] == 2
    # (c) zero recompiles: the step-path functions traced once for the
    # ENTIRE job — restarts and resizes reused every executable (post is
    # per-trainer: one trace per (world, rank=0..n) trainer, never more)
    counts = sup.trace_counts()
    assert counts["shard_grads"] == 1 and counts["apply"] == 1, counts
    n_trainers = len(sup._trainers)
    assert counts["post"] == n_trainers, (counts, n_trainers)
    # every checkpoint on disk is a committed one (no torn staging)
    assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))


# ------------------------------------------------ overflow storms

def test_overflow_burst_replays_bit_exact_across_restart(tmp_path):
    """Scaler state rides the checkpoint: a NaN burst (skip-on-overflow +
    backoff) followed by a crash restart replays the identical stream —
    final params bit-identical to the same burst without the crash."""
    cfg_plain = _cfg(seed=19)
    inj_a = FaultInjector(seed=19).nan_burst(3, 2)
    ref = Trainer(cfg_plain, injector=inj_a)
    rep_ref = ref.run()
    assert rep_ref["skipped_steps"] == 2
    burst_params = ref.params
    ref.close()

    inj_b = (FaultInjector(seed=19).nan_burst(3, 2)
             .crash_on_train_step(7))
    cfg = _cfg(seed=19, checkpoint_dir=str(tmp_path), save_every=2)
    sup = TrainSupervisor(cfg, injector=inj_b, max_restarts=1,
                          backoff_s=0.01)
    rep = sup.run()
    assert rep["restarts"] == 1
    assert rep["skipped_steps"] == 2
    assert rep["goodput"]["skipped_steps"] == 2
    _assert_trees_equal(sup.params(), burst_params)


# ------------------------------------------------ watchdog + registry

def test_watchdog_surfaces_straggler_rank(events):
    """A straggling rank stalls its peers inside the gradient exchange:
    the collective watchdog turns the silent wait into a
    collective_stall event naming the exchange."""
    inj = FaultInjector(seed=20).straggler_rank(1, delay_s=0.4, at_step=2)
    cfg = _cfg(seed=20, steps=4, world=2, watchdog_timeout_s=0.05)
    sup = TrainSupervisor(cfg, injector=inj)
    rep = sup.run()
    assert rep["final_step"] == 3
    stalls = _named(events, "collective_stall")
    assert any(e["name"].startswith("train_allgather") for e in stalls)


def test_metrics_registry_seam_counts_training_ranks(tmp_path):
    """Telemetry(registry=...) is the serving-grade metrics seam: a
    training run lands step counters + the step-seconds histogram in a
    mergeable registry exactly like a serving rank would."""
    reg = MetricsRegistry()
    sup = TrainSupervisor(_cfg(seed=21, steps=5), registry=reg)
    rep = sup.run()
    assert rep["final_step"] == 4
    snap = reg.snapshot()
    series = snap["metrics"]
    assert series["train_steps_total"]["series"][0]["value"] == 5
    hist = series["train_step_seconds"]["series"][0]
    assert hist["count"] == 5


def test_supervisor_status_table_tracks_rank_progress():
    sup = TrainSupervisor(_cfg(seed=22, steps=4, world=2))
    rep = sup.run()
    assert rep["final_step"] == 3
    status = sup.status()
    assert set(status) == {0, 1}
    assert all(v["step"] == 3 for v in status.values())


# ------------------------------------------------ config + CLI matrix

def test_config_validation_refuses_bad_geometry():
    with pytest.raises(ValueError, match="divide grad_shards"):
        TrainConfig(world=3, grad_shards=4).validate()
    with pytest.raises(ValueError, match="divide batch"):
        TrainConfig(batch=6, grad_shards=4).validate()
    with pytest.raises(ValueError, match="needs checkpoint_dir"):
        TrainConfig(save_every=2).validate()
    with pytest.raises(ValueError, match="sharded_checkpoint"):
        TrainConfig(world=2, grad_shards=2, checkpoint_dir="/x",
                    sharded_checkpoint=False).validate()
    with pytest.raises(ValueError, match="amp"):
        TrainConfig(amp="fp8").validate()


@pytest.mark.parametrize("argv,fragment", [
    (["--elastic", "2:1", "--grad-shards", "2"], "--checkpoint-dir"),
    (["--elastic", "2:1", "--grad-shards", "2", "--world", "2",
      "--checkpoint-dir", "/tmp/x"], "replaces --world"),
    (["--chaos", "crash-step:3", "--max-restarts", "0",
      "--checkpoint-dir", "/tmp/x"], "restart budget"),
    (["--chaos", "crash-step:3"], "--checkpoint-dir"),
    (["--chaos", "crash-step:banana", "--checkpoint-dir", "/tmp/x"],
     "malformed"),
    (["--steps", "4", "--chaos", "preempt:9",
      "--checkpoint-dir", "/tmp/x"], "never fire"),
    (["--chaos", "explode:3", "--checkpoint-dir", "/tmp/x"],
     "expected crash-step"),
    (["--steps", "24", "--save-every", "4", "--checkpoint-dir",
      "/tmp/x", "--chaos", "crash-save:9"], "never saved"),
    (["--world", "3", "--grad-shards", "4"], "divide"),
    (["--grad-shards", "3", "--batch", "8"], "divide"),
    (["--save-every", "2"], "checkpoint_dir"),
    (["--steps", "0"], ">= 1"),
    (["--watchdog-timeout", "0"], "> 0"),
    (["--elastic", "2:x", "--checkpoint-dir", "/tmp/x"],
     "colon-separated"),
])
def test_train_cli_exit2_usage_matrix(argv, fragment, capsys):
    """Contradictory or inert flag combinations refuse loudly (exit 2)
    before any params are built or anything compiles — the serve/fleet
    CLI precedent."""
    rc = train_cli_main(argv)
    assert rc == 2
    err = capsys.readouterr().err
    assert fragment in err, err


def test_train_cli_chaos_smoke_end_to_end(tmp_path, capsys):
    """The CLI happy path: a chaos schedule (crash + preempt/relaunch)
    under the supervisor, clean exit 0, and a JSON job report whose
    counters reconcile."""
    rc = train_cli_main([
        "--steps", "8", "--batch", "8", "--seq", "10", "--vocab", "64",
        "--hidden", "16", "--grad-shards", "2",
        "--checkpoint-dir", str(tmp_path), "--save-every", "2",
        "--max-restarts", "2", "--elastic", "1:1",
        "--chaos", "crash-step:3,preempt:5"])
    assert rc == 0
    out = capsys.readouterr().out
    report = json.loads(out.strip().splitlines()[-1])
    assert report["final_step"] == 7 and not report["preempted"]
    assert report["restarts"] == 1 and report["preempt_drains"] == 1
    assert report["goodput"]["steps"] == 8  # exactly-once via the CLI too


# ------------------------------------------------ bench + gate wiring

def test_bench_train_chaos_mode_and_gate_direction(tmp_path, capsys,
                                                   monkeypatch):
    """`apex-tpu-bench --train-chaos` emits a suite entry whose
    resilience counters the regression gate reads as lower-is-better —
    a 0 -> N restart storm gates as a regression, never a win — with
    trainer workload provenance nested (never lifted into the gated
    metrics)."""
    import sys as _sys

    import apex_tpu.bench_cli as bench_cli

    sys_path = os.path.join(ROOT, "tools")
    if sys_path not in _sys.path:
        _sys.path.insert(0, sys_path)
    import check_regression

    monkeypatch.setattr(_sys, "argv",
                        ["apex-tpu-bench", "--train-chaos", "--steps",
                         "6"])
    bench_cli.main()
    out = capsys.readouterr().out
    suite = json.loads(out[out.index("{"):])
    entry = suite["train_chaos"]
    assert entry["unit"] == "steps_per_s" and entry["value"] > 0
    for key in ("restarts", "preempt_drains", "steps_retried",
                "step_recompiles"):
        assert key in entry
        assert check_regression.lower_is_better(f"train_chaos.{key}")
    assert entry["step_recompiles"] == 1  # the zero-recompile contract
    # provenance: world/parallelism/amp nested under workload — config,
    # not a gated metric
    wl = entry["workload"]
    assert {"world", "grad_shards", "amp_dtype"} <= set(wl)
    metrics = check_regression.metrics_from_suite(suite)
    assert "train_chaos.workload" not in metrics
    assert "train_chaos.restarts" in metrics
    # a healthy 0-restart baseline vs this chaos capture: the counters
    # gate as regressions off the zero baseline (PR-8 precedent)
    baseline = dict(metrics)
    baseline["train_chaos.restarts"] = (0.0, None)
    results, _ = check_regression.compare(metrics, baseline, 0.1)
    row = {r["metric"]: r for r in results}["train_chaos.restarts"]
    assert row["direction"] == "lower" and row["regressed"]


# ------------------------------------------------ slow chaos sweep

@pytest.mark.slow
@pytest.mark.parametrize("seed", [31, 32, 33])
def test_chaos_sweep_seeded_schedules(tmp_path, seed):
    """Sweep: per-seed schedules mixing every trainer fault; each run
    must end bit-identical to its own uninterrupted oracle with
    exactly-once accounting."""
    steps = 12
    oracle = _oracle_params(seed=seed, steps=steps)
    inj = (FaultInjector(seed=seed)
           .preempt_at_step(2 + seed % 3, rank=seed % 2)
           .crash_on_train_step(6 + seed % 2)
           .crash_during_checkpoint_save(8)
           .nan_burst(4, 1))
    oracle_inj = FaultInjector(seed=seed).nan_burst(4, 1)
    ref = Trainer(_cfg(seed=seed, steps=steps), injector=oracle_inj)
    ref.run()
    oracle = ref.params
    ref.close()
    cfg = _cfg(seed=seed, steps=steps, world=2,
               checkpoint_dir=str(tmp_path), save_every=2)
    sup = TrainSupervisor(cfg, injector=inj, max_restarts=3,
                          backoff_s=0.01, world_schedule=[2, 1])
    rep = sup.run()
    assert not rep["preempted"] and rep["final_step"] == steps - 1
    _assert_trees_equal(sup.params(), oracle)
    assert rep["goodput"]["steps"] == steps
