"""Queue mechanics of tools/chip_worker.py (round-acceptance infra).

Tests drive the pure parts (fail counting, module purging, status writes)
without initializing any backend.
"""

import importlib
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import chip_worker  # noqa: E402


@pytest.fixture()
def qdirs(tmp_path, monkeypatch):
    q = tmp_path / "chipq"
    done = q / "done"
    failed = q / "failed"
    for d in (q, done, failed):
        d.mkdir(parents=True)
    monkeypatch.setattr(chip_worker, "QDIR", str(q))
    monkeypatch.setattr(chip_worker, "DONE", str(done))
    monkeypatch.setattr(chip_worker, "FAILED", str(failed))
    monkeypatch.setattr(chip_worker, "STATUS", str(q / "status.json"))
    return q, done, failed


class TestFailCount:
    def test_counts_only_own_markers(self, qdirs):
        _, _, failed = qdirs
        (failed / "q010_x.py.1.json").write_text("{}")
        (failed / "q010_x.py.2.json").write_text("{}")
        (failed / "q020_y.py.1.json").write_text("{}")
        assert chip_worker._fail_count("q010_x.py") == 2
        assert chip_worker._fail_count("q020_y.py") == 1
        assert chip_worker._fail_count("q030_z.py") == 0

    def test_missing_dir_is_zero(self, qdirs, monkeypatch):
        monkeypatch.setattr(chip_worker, "FAILED",
                            str(qdirs[0] / "nonexistent"))
        assert chip_worker._fail_count("q010_x.py") == 0


class TestPurge:
    def test_purges_repo_modules_not_thirdparty(self):
        import bench  # noqa: F401  (repo module; should be purged)
        assert "bench" in sys.modules
        before_np = sys.modules.get("numpy")
        chip_worker.purge_repo_modules()
        assert "bench" not in sys.modules
        assert not any(m == "apex_tpu" or m.startswith("apex_tpu.")
                       for m in sys.modules)
        assert sys.modules.get("numpy") is before_np
        importlib.import_module("bench")  # restore for other tests


class TestStatus:
    def test_status_write_atomic_and_stamped(self, qdirs):
        chip_worker.write_status(phase="testing", backend="cpu")
        st = json.load(open(chip_worker.STATUS))
        assert st["phase"] == "testing"
        assert st["pid"] == os.getpid()
        assert "t" in st
        assert not os.path.exists(chip_worker.STATUS + ".tmp")


class TestRooflineAPI:
    def test_matmul_cost_model(self):
        import jax
        import jax.numpy as jnp

        from apex_tpu.utils.prof import roofline
        r = roofline(lambda a, b: a @ b, jnp.ones((256, 256)),
                     jnp.ones((256, 256)), chip="v5e", measured_ms=1.0)
        assert r["flops"] >= 2 * 256 ** 3 * 0.9
        assert r["bound"] in ("mxu", "hbm")
        assert 0 < r["achieved_frac"] < 1
