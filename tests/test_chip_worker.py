"""Queue mechanics of tools/chip_worker.py (round-acceptance infra).

Tests drive the pure parts (fail counting, module purging, status writes)
without initializing any backend.
"""

import importlib
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import chip_worker  # noqa: E402


@pytest.fixture()
def qdirs(tmp_path, monkeypatch):
    q = tmp_path / "chipq"
    done = q / "done"
    failed = q / "failed"
    for d in (q, done, failed):
        d.mkdir(parents=True)
    monkeypatch.setattr(chip_worker, "QDIR", str(q))
    monkeypatch.setattr(chip_worker, "DONE", str(done))
    monkeypatch.setattr(chip_worker, "FAILED", str(failed))
    monkeypatch.setattr(chip_worker, "STATUS", str(q / "status.json"))
    return q, done, failed


class TestFailCount:
    def test_counts_only_own_markers(self, qdirs):
        _, _, failed = qdirs
        (failed / "q010_x.py.1.json").write_text("{}")
        (failed / "q010_x.py.2.json").write_text("{}")
        (failed / "q020_y.py.1.json").write_text("{}")
        assert chip_worker._fail_count("q010_x.py") == 2
        assert chip_worker._fail_count("q020_y.py") == 1
        assert chip_worker._fail_count("q030_z.py") == 0

    def test_missing_dir_is_zero(self, qdirs, monkeypatch):
        monkeypatch.setattr(chip_worker, "FAILED",
                            str(qdirs[0] / "nonexistent"))
        assert chip_worker._fail_count("q010_x.py") == 0


class TestRetryBackoff:
    """ADVICE r4: a failed job must cool down between retries so a
    transient relay outage can't burn all 3 attempts within seconds."""

    def test_fresh_job_runnable(self, qdirs):
        assert chip_worker.job_runnable("q010_x.py", 600)

    def test_done_job_not_runnable(self, qdirs):
        _, done, _ = qdirs
        (done / "q010_x.py.json").write_text("{}")
        assert not chip_worker.job_runnable("q010_x.py", 0)

    def test_recent_failure_defers(self, qdirs):
        _, _, failed = qdirs
        (failed / "q010_x.py.1.json").write_text("{}")  # mtime = now
        assert not chip_worker.job_runnable("q010_x.py", 600)
        # zero backoff ⇒ immediately retryable (legacy behavior)
        assert chip_worker.job_runnable("q010_x.py", 0)

    def test_cooled_failure_retries(self, qdirs):
        _, _, failed = qdirs
        m = failed / "q010_x.py.1.json"
        m.write_text("{}")
        old = os.path.getmtime(m) - 1000
        os.utime(m, (old, old))
        assert chip_worker.job_runnable("q010_x.py", 600)

    def test_fail_cap_parks_job(self, qdirs):
        _, _, failed = qdirs
        for i in (1, 2, 3):
            m = failed / f"q010_x.py.{i}.json"
            m.write_text("{}")
            old = os.path.getmtime(m) - 10000
            os.utime(m, (old, old))
        assert not chip_worker.job_runnable("q010_x.py", 0)


class TestPurge:
    def test_purges_repo_modules_not_thirdparty(self):
        import bench  # noqa: F401  (repo module; should be purged)
        assert "bench" in sys.modules
        before_np = sys.modules.get("numpy")
        chip_worker.purge_repo_modules()
        assert "bench" not in sys.modules
        assert not any(m == "apex_tpu" or m.startswith("apex_tpu.")
                       for m in sys.modules)
        assert sys.modules.get("numpy") is before_np
        importlib.import_module("bench")  # restore for other tests


class TestStatus:
    def test_status_write_atomic_and_stamped(self, qdirs):
        chip_worker.write_status(phase="testing", backend="cpu")
        st = json.load(open(chip_worker.STATUS))
        assert st["phase"] == "testing"
        assert st["pid"] == os.getpid()
        assert "t" in st
        assert not os.path.exists(chip_worker.STATUS + ".tmp")


class TestRooflineAPI:
    def test_matmul_cost_model(self):
        import jax
        import jax.numpy as jnp

        from apex_tpu.utils.prof import roofline
        r = roofline(lambda a, b: a @ b, jnp.ones((256, 256)),
                     jnp.ones((256, 256)), chip="v5e", measured_ms=1.0)
        assert r["flops"] >= 2 * 256 ** 3 * 0.9
        assert r["bound"] in ("mxu", "hbm")
        assert 0 < r["achieved_frac"] < 1


@pytest.mark.slow
class TestWorkerEndToEnd:
    def test_runs_queue_and_exits(self, tmp_path):
        """Drive the real worker main() in a subprocess against a
        throwaway queue: one passing job, one failing job (retried to the
        cap), STOP honored, markers and status written.

        Slow tier: the subprocess pays a full interpreter + jax import
        (~16s); the queue/retry/STOP semantics it exercises stay in
        tier-1 via the in-process unit tests above."""
        q = tmp_path / "q"
        (q / "done").mkdir(parents=True)
        (q / "failed").mkdir()
        (q / "q010_ok.py").write_text(
            "open(%r, 'w').write('ran')\n" % str(tmp_path / "touch.txt"))
        (q / "q020_bad.py").write_text("raise RuntimeError('boom')\n")
        # no STOP file: CHIPQ_IDLE_EXIT_S=1 exits once the queue drains
        # (a pre-created STOP would exit before any job ran)

        env = dict(os.environ)
        kept = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                if p and "axon" not in p]
        env["PYTHONPATH"] = os.pathsep.join(kept + [ROOT])
        env["JAX_PLATFORMS"] = "cpu"
        env["CHIPQ_DIR"] = str(q)
        env["CHIPQ_ALLOW_CPU"] = "1"
        env["CHIPQ_IDLE_EXIT_S"] = "1"
        # retry backoff is covered by TestRetryBackoff; here let the
        # failing job burn its 3 attempts immediately so the end-to-end
        # run stays fast
        env["CHIPQ_RETRY_BACKOFF_S"] = "0"
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "chip_worker.py")],
            env=env, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        assert (tmp_path / "touch.txt").read_text() == "ran"
        assert (q / "done" / "q010_ok.py.json").exists()
        fails = sorted(os.listdir(q / "failed"))
        assert fails == ["q020_bad.py.1.json", "q020_bad.py.2.json",
                         "q020_bad.py.3.json"], fails
        st = json.load(open(q / "status.json"))
        assert st["phase"] == "exited"
