"""LayerNorm/RMSNorm parity — port of tests/L0/run_fused_layer_norm (~30
parametrizations: fused vs torch.nn.LayerNorm / manual RMS, fp32/bf16,
affine/no-affine, memory-efficient)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.normalization import (FusedLayerNorm, FusedRMSNorm,
                                    fused_layer_norm, fused_layer_norm_affine,
                                    fused_rms_norm, fused_rms_norm_affine,
                                    manual_rms_norm)

HIDDEN = 256  # lane-friendly → exercises the Pallas kernels (interpret on CPU)
BATCH = 6
SEQ = 4


def _x(dtype=jnp.float32, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (BATCH, SEQ, HIDDEN),
                             dtype)


def _wb(dtype=jnp.float32):
    k1, k2 = jax.random.split(jax.random.PRNGKey(42))
    w = 1.0 + 0.1 * jax.random.normal(k1, (HIDDEN,), dtype)
    b = 0.1 * jax.random.normal(k2, (HIDDEN,), dtype)
    return w, b


def _torch_ln(x, w, b, eps=1e-5):
    tx = torch.tensor(np.asarray(x, np.float32), requires_grad=True)
    ln = torch.nn.LayerNorm(HIDDEN, eps=eps)
    with torch.no_grad():
        ln.weight.copy_(torch.tensor(np.asarray(w, np.float32)))
        ln.bias.copy_(torch.tensor(np.asarray(b, np.float32)))
    y = ln(tx)
    return tx, ln, y


class TestForwardParity:
    @pytest.mark.parametrize("mem_eff", [False, True])
    def test_layer_norm_affine_vs_torch(self, mem_eff):
        x = _x()
        w, b = _wb()
        y = fused_layer_norm_affine(x, w, b, HIDDEN, 1e-5, mem_eff)
        _, _, ty = _torch_ln(x, w, b)
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   atol=1e-5, rtol=1e-5)

    def test_layer_norm_noaffine(self):
        x = _x()
        y = fused_layer_norm(x, HIDDEN)
        ty = torch.nn.functional.layer_norm(
            torch.tensor(np.asarray(x)), (HIDDEN,))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5,
                                   rtol=1e-5)

    @pytest.mark.parametrize("mem_eff", [False, True])
    def test_rms_norm_affine_vs_manual(self, mem_eff):
        x = _x(seed=3)
        w, _ = _wb()
        y = fused_rms_norm_affine(x, w, HIDDEN, 1e-5, mem_eff)
        ref = manual_rms_norm(x, w, HIDDEN, 1e-5)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5,
                                   rtol=1e-5)

    def test_bf16_io_fp32_stats(self):
        x = _x(jnp.bfloat16, seed=5)
        w, b = _wb()
        y = fused_layer_norm_affine(x, w, b, HIDDEN)
        assert y.dtype == jnp.bfloat16
        ref = torch.nn.functional.layer_norm(
            torch.tensor(np.asarray(x, np.float32)), (HIDDEN,),
            torch.tensor(np.asarray(w)), torch.tensor(np.asarray(b)))
        np.testing.assert_allclose(np.asarray(y, np.float32), ref.numpy(),
                                   atol=3e-2, rtol=3e-2)

    def test_odd_hidden_fallback(self):
        # 100 not lane-aligned → jnp fallback path
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 100))
        y = fused_layer_norm(x, 100)
        ty = torch.nn.functional.layer_norm(torch.tensor(np.asarray(x)),
                                            (100,))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5,
                                   rtol=1e-5)


class TestBackwardParity:
    @pytest.mark.parametrize("mem_eff", [False, True])
    def test_layer_norm_grads_vs_torch(self, mem_eff):
        x = _x(seed=7)
        w, b = _wb()

        def loss(x, w, b):
            y = fused_layer_norm_affine(x, w, b, HIDDEN, 1e-5, mem_eff)
            return jnp.sum(y * y)

        dx, dw, db = jax.grad(loss, argnums=(0, 1, 2))(x, w, b)

        tx, ln, ty = _torch_ln(x, w, b)
        (ty * ty).sum().backward()
        np.testing.assert_allclose(np.asarray(dx), tx.grad.numpy(),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(dw), ln.weight.grad.numpy(),
                                   atol=1e-3, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(db), ln.bias.grad.numpy(),
                                   atol=1e-3, rtol=1e-4)

    @pytest.mark.parametrize("mem_eff", [False, True])
    def test_rms_norm_grads_vs_jnp_reference(self, mem_eff):
        x = _x(seed=8)
        w, _ = _wb()

        def loss_fused(x, w):
            return jnp.sum(jnp.square(
                fused_rms_norm_affine(x, w, HIDDEN, 1e-5, mem_eff)))

        def loss_ref(x, w):
            return jnp.sum(jnp.square(manual_rms_norm(x, w, HIDDEN, 1e-5)))

        dx, dw = jax.grad(loss_fused, (0, 1))(x, w)
        rx, rw = jax.grad(loss_ref, (0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(rx), atol=1e-4,
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(rw), atol=1e-3,
                                   rtol=1e-4)


class TestModules:
    def test_fused_layer_norm_module(self):
        m = FusedLayerNorm(HIDDEN)
        x = _x()
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        ty = torch.nn.functional.layer_norm(torch.tensor(np.asarray(x)),
                                            (HIDDEN,))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5,
                                   rtol=1e-5)

    def test_fused_rms_norm_module_jit_grad(self):
        m = FusedRMSNorm(HIDDEN)
        x = _x()
        params = m.init(jax.random.PRNGKey(0), x)

        @jax.jit
        def step(params, x):
            return jax.grad(
                lambda p: jnp.sum(m.apply(p, x) ** 2))(params)

        g = step(params, x)
        assert jnp.all(jnp.isfinite(g["params"]["weight"]))
