"""The examples/ entry points stay runnable (the reference ships runnable
examples/{simple,dcgan,imagenet}; a bit-rotted example is a broken
component). Subprocess smoke with tiny step counts on CPU."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args):
    env = dict(os.environ)
    kept = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(kept + [ROOT])
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run([sys.executable] + args, cwd=ROOT, env=env,
                          capture_output=True, text=True, timeout=600)


@pytest.mark.parametrize("args", [
    ["examples/simple/main_amp.py", "--steps", "4"],
    # dcgan is the heaviest example subprocess (two compiled models); the
    # simple + lm_pretrain smokes keep the entry points covered in tier-1
    pytest.param(["examples/dcgan/main_amp.py", "--steps", "2",
                  "--batch", "4"], marks=pytest.mark.slow),
    # the Trainer seam this example migrated onto is exercised directly by
    # tests/test_train_elastic.py in tier-1; the subprocess rides slow
    pytest.param(["examples/lm_pretrain/main_fused_head.py", "--steps", "3",
                  "--vocab-chunk", "128"], marks=pytest.mark.slow),
    # the serve CLI smoke in tests/test_serve.py covers the same engine
    # path in tier-1; the example subprocess rides the slow tier
    pytest.param(["examples/serve/generate.py", "--requests", "3",
                  "--max-new-tokens", "3"], marks=pytest.mark.slow),
])
def test_example_runs(args):
    r = _run(args)
    assert r.returncode == 0, r.stderr[-2000:]
    assert r.stdout.strip(), "example produced no output"
