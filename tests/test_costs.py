"""Compiled-step cost observatory (PR 17) — phase-attributed ledgers.

Layers under test:

1. **The walk itself** — a toy jitted function with ``jax.named_scope``
   markers attributes FLOPs/bytes to the right phases, multiplies scan
   bodies by their trip counts, and reconciles phase sums against the
   executable total EXACTLY (the reconciliation IS the test — PR-13
   trace_explain precedent).
2. **The engine surface** — ``Engine.cost_ledger()`` rides the saved
   AOT artifacts (never re-tracing: ``decode_traces`` stays 1), is
   byte-deterministic across extractions, reconciles for the slot AND
   paged engines, and — at tp=2 exact — its counted collectives equal
   the PR-15 ``expected_collectives`` contract.
3. **The gate + diff tools** — the new ledger metric families are
   direction-aware in check_regression, a doctored +10%-bytes ledger
   FAILS the gate (exit 1), incomparable workload axes are refused
   (exit 2), and ``tools/cost_diff.py`` runs in a jax-poisoned
   subprocess (exit 0 clean / exit 2 on doctored provenance).
4. **The CLI matrix** — the new ``--cost-ledger``/``--chip-spec`` flags
   are loud usage errors when inert or contradictory (PR-10 precedent).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.monitor import costs
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# n_head=4 so the same params serve tp=2 (the test_serve_tp geometry)
CFG = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=2,
                 n_head=4, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_gpt2_params(CFG, seed=0)


def _engine(params, **kw):
    kw.setdefault("num_slots", 3)
    kw.setdefault("max_len", 32)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("block_k", 8)
    return Engine(CFG, params, EngineConfig(**kw), seed=0)


def _assert_reconciles(rec):
    """Phase sums == executable totals, exactly (no tolerance: both
    sides are integers accumulated by the same deterministic walk, and
    the ledger's contract is EXACT attribution)."""
    for field in ("ops", "flops", "hbm_bytes", "transcendentals"):
        assert sum(p[field] for p in rec["phases"].values()) \
            == rec["total"][field], field


# --------------------------------------------------------- 1. the walk

def test_walk_attributes_phases_and_reconciles():
    def f(x, w):
        with jax.named_scope("ln_qkv"):
            y = x @ w
        with jax.named_scope("mlp"):
            y = jnp.tanh(y)

        def body(c, t):
            with jax.named_scope("attention"):
                return c + t * 2.0, t

        c, _ = jax.lax.scan(body, jnp.zeros_like(y), jnp.stack([y] * 5))
        return c

    x = jnp.ones((4, 8), jnp.float32)
    w = jnp.ones((8, 8), jnp.float32)
    rec = costs.walk_module(
        costs.stablehlo_debug_text(jax.jit(f).lower(x, w)))
    _assert_reconciles(rec)
    # the matmul lands in ln_qkv: 2*4*8*8 = 512 flops (+ any epilogue)
    assert rec["phases"]["ln_qkv"]["flops"] >= 512
    # tanh is transcendental and lands in mlp
    assert rec["phases"]["mlp"]["transcendentals"] > 0
    # the scan body is outlined into a private func and must be priced
    # once per trip: 5 trips × (4*8 mul + 4*8 add) = 320, in attention
    assert rec["phases"]["attention"]["flops"] >= 320
    assert rec["total"]["arithmetic_intensity"] > 0


def test_walk_multiplies_while_bodies_by_trip_count():
    def body(c, t):
        return c * 1.5 + t, t

    def f(xs):
        c, _ = jax.lax.scan(body, jnp.float32(0.0), xs)
        return c

    one = costs.walk_module(costs.stablehlo_debug_text(
        jax.jit(f).lower(jnp.ones((1,), jnp.float32))))
    ten = costs.walk_module(costs.stablehlo_debug_text(
        jax.jit(f).lower(jnp.ones((10,), jnp.float32))))
    # same program, 10× the trips: the scanned-body flops scale with
    # the trip count (not the module's static op count)
    assert ten["total"]["flops"] >= 10 * one["total"]["flops"] > 0
    assert "notes" not in ten     # trip count statically resolved


def test_walk_ignores_phase_named_source_paths(tmp_path):
    """MLIR loc bodies quote source FILE paths alongside named_scope
    paths — code traced from a directory that happens to be named after
    a phase (here ``verify/``) must not have its ops claimed by that
    phase."""
    import importlib.util

    mod_dir = tmp_path / "verify"
    mod_dir.mkdir()
    src = mod_dir / "user_drive.py"
    src.write_text("import jax.numpy as jnp\n\n\n"
                   "def f(x):\n"
                   "    return jnp.tanh(x) @ x\n")
    spec = importlib.util.spec_from_file_location("_phase_path_mod", src)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = costs.walk_module(costs.stablehlo_debug_text(
        jax.jit(mod.f).lower(jnp.ones((4, 4), jnp.float32))))
    _assert_reconciles(rec)
    assert rec["phases"]["verify"]["ops"] == 0
    assert rec["phases"]["other"]["ops"] > 0


def test_expected_collective_ops_contract_and_unknown_mode():
    # the PR-15 contract, spelled once (serve/tp.py delegates here)
    assert costs.expected_collective_ops(12, "exact") \
        == {"all_gather": 24, "all_reduce": 0}
    assert costs.expected_collective_ops(12, "overlap") \
        == {"all_gather": 0, "all_reduce": 48}
    assert costs.expected_collective_ops(12, "relaxed") \
        == {"all_gather": 0, "all_reduce": 24}
    with pytest.raises(ValueError, match="unknown tp_sync"):
        costs.expected_collective_ops(2, "banana")
    with pytest.raises(ValueError, match="unknown chip spec"):
        costs.build_ledger({}, {}, chip="v99x")


# ------------------------------------------------ 2. the engine surface

def test_cost_ledger_deterministic_and_reconciles(params):
    eng = _engine(params)
    led1 = eng.cost_ledger(prompt_buckets=[8])
    assert eng.decode_traces == 1      # rode the saved artifacts
    led2 = eng.cost_ledger(prompt_buckets=[8])
    assert eng.decode_traces == 1
    # byte-identical: no wall clocks, no env reads in the ledger body
    assert json.dumps(led1, sort_keys=True) \
        == json.dumps(led2, sort_keys=True)
    assert led1["schema"] == costs.LEDGER_SCHEMA
    assert set(led1["executables"]) == {"decode", "prefill_8"}
    for rec in led1["executables"].values():
        _assert_reconciles(rec)
        # every annotated phase is populated in the decode/prefill step
        for ph in ("ln_qkv", "attention", "mlp", "sampling"):
            assert rec["phases"][ph]["ops"] > 0, ph
    d = led1["derived"]
    assert d["decode_ops_total"] == \
        led1["executables"]["decode"]["total"]["ops"]
    assert d["decode_flops_per_token"] > 0
    assert d["decode_hbm_bytes_per_token"] > 0
    # cpu chip spec: roofline present but marked non-gating
    assert led1["chip_spec"] == "cpu" and led1["gating"] is False
    gm = costs.ledger_gate_metrics(led1)
    assert "predicted_mfu" not in gm
    assert gm["decode_flops_per_token"] == d["decode_flops_per_token"]
    # ...while a real chip spec gates the roofline families too
    v5p = eng.cost_ledger(chip="v5p")
    gm5 = costs.ledger_gate_metrics(v5p)
    assert 0 < gm5["predicted_mfu"] <= 1
    assert gm5["predicted_step_time_us"] > 0


def test_cost_ledger_paged_reconciles(params):
    eng = _engine(params, page_size=8, prefix_cache=True)
    led = eng.cost_ledger(prompt_buckets=[8])
    for rec in led["executables"].values():
        _assert_reconciles(rec)
    assert led["workload"]["page_size"] == 8
    # paged vs slot is an incomparable axis: the gate must refuse
    slot = _engine(params).cost_ledger()
    assert any("page_size" in r
               for r in costs.provenance_mismatch(led, slot))


def test_cost_ledger_quantized_reconciles(params):
    """PR-20 ride-along: a kv_quant engine's ledger reconciles exactly
    (the in-step encode/dequant arithmetic and the int8 KV traffic are
    walked like any other op), stamps ``kv_quant``/``quant_block``
    provenance so quantized ledgers refuse to gate against fp32 ones,
    and its decode step moves FEWER HBM bytes per token than the fp32
    engine's — the capacity claim, visible in the static byte model."""
    eng = _engine(params, kv_quant="int8")
    led = eng.cost_ledger()
    for rec in led["executables"].values():
        _assert_reconciles(rec)
    assert led["workload"]["kv_quant"] == "int8"
    assert led["workload"]["quant_block"] == 8     # = head_dim
    plain = _engine(params).cost_ledger()
    assert any("kv_quant" in r
               for r in costs.provenance_mismatch(led, plain))
    assert "kv_quant" not in plain["workload"] \
        or plain["workload"]["kv_quant"] is None
    assert led["derived"]["decode_hbm_bytes_per_token"] \
        < plain["derived"]["decode_hbm_bytes_per_token"]


def test_cost_ledger_tp2_exact_matches_pr15_contract(params, tp_devices):
    eng = _engine(params, num_slots=2, tp=2)
    led = eng.cost_ledger()
    dec = led["executables"]["decode"]
    _assert_reconciles(dec)
    # the ledger's counted collectives == the PR-15 contract == the
    # engine's own count_collectives (three independent spellings)
    expect = costs.expected_collective_ops(CFG.n_layer, "exact")
    nonzero = {k: v for k, v in expect.items() if v}
    counted = {k: v for k, v in dec["collectives"].items() if v}
    assert counted == nonzero == {
        k: v for k, v in eng.decode_collectives().items() if v}
    assert led["contract"]["expected"] == expect
    # collective phase carries exactly those ops
    assert dec["phases"]["collective"]["ops"] == sum(expect.values())
    # tp pricing table covers every sync mode, exact's op count agrees
    pricing = led["collective_pricing"]
    assert set(pricing) == set(costs.SYNC_MODES)
    assert pricing["exact"]["ops"] == expect
    assert all(p["bytes_on_wire_per_step"] > 0 for p in pricing.values())


def test_cost_ledger_survives_reset_without_relowering(params):
    """Satellite 6: ``cost_ledger()`` after ``reset()`` (warm restart)
    rides the RETAINED prefill lowerings — no re-trace, no re-lower."""
    eng = _engine(params, num_slots=2).aot_compile(prompt_buckets=[8])
    before = eng.cost_ledger()
    assert eng.decode_traces == 1 and eng.prefill_traces == 1
    eng.reset()
    after = eng.cost_ledger()
    assert eng.decode_traces == 1 and eng.prefill_traces == 1
    assert json.dumps(before, sort_keys=True) \
        == json.dumps(after, sort_keys=True)
    assert "prefill_8" in after["executables"]


def test_cost_ledger_spec_verify_entry(params):
    """PR-18 ride-along: a spec-armed engine's ledger carries the
    verify executable from the SAME retained lowerings (no re-trace,
    works after reset), its verify phase is populated via the model's
    final_scope threading, and the spec workload axes make spec-off
    ledgers refuse rather than compare."""
    eng = Engine(CFG, params,
                 EngineConfig(num_slots=3, max_len=32, temperature=0.0,
                              block_k=8, spec_draft_len=2), seed=0)
    led = eng.cost_ledger(prompt_buckets=[8])
    assert eng.decode_traces == 1 and eng.verify_traces == 1
    assert set(led["executables"]) == {"decode", "prefill_8", "verify"}
    ver = led["executables"]["verify"]
    _assert_reconciles(ver)
    # the verify phase holds the final LN + logits work of all K+1
    # scanned positions (final_scope="verify"); the inner phases and the
    # acceptance sampler keep their own attribution
    for ph in ("ln_qkv", "attention", "mlp", "sampling", "verify"):
        assert ver["phases"][ph]["ops"] > 0, ph
    # decode/prefill entries keep "verify" EMPTY: their final scope is
    # still "sampling", so the new phase never leaks attribution
    assert led["executables"]["decode"]["phases"]["verify"]["ops"] == 0
    assert led["workload"]["spec_draft_len"] == 2
    # byte-deterministic across reset, still no re-trace (warm restart)
    eng.reset()
    led2 = eng.cost_ledger(prompt_buckets=[8])
    assert eng.decode_traces == 1 and eng.verify_traces == 1
    assert json.dumps(led, sort_keys=True) \
        == json.dumps(led2, sort_keys=True)
    # spec on/off is an incomparable ledger axis (missing key = off)
    plain = _engine(params).cost_ledger()
    assert any("spec_draft_len" in r
               for r in costs.provenance_mismatch(led, plain))
    assert "spec_draft_len" not in plain["workload"] \
        or plain["workload"]["spec_draft_len"] == 0


# --------------------------------------------- 3. the gate + diff tools

def _check_regression():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_regression
    finally:
        sys.path.pop(0)
    return check_regression


def test_gate_directions_for_ledger_families():
    cr = _check_regression()
    for name in ("cost_ledger.decode_flops_per_token",
                 "cost_ledger.decode_hbm_bytes_per_token",
                 "cost_ledger.decode_ops_total",
                 "cost_ledger.decode.attention_flops_per_token",
                 "cost_ledger.predicted_step_time_us"):
        assert cr.lower_is_better(name), name
    assert not cr.lower_is_better("cost_ledger.predicted_mfu")


def test_gate_passes_identical_and_fails_doctored_bytes(params, tmp_path):
    """ISSUE acceptance: a doctored +10% hbm-bytes ledger FAILS the
    gate; identical ledgers pass; a different workload axis is refused
    (exit 2), never silently compared."""
    cr = _check_regression()
    led = _engine(params).cost_ledger()
    cur, base = str(tmp_path / "cur.json"), str(tmp_path / "base.json")
    json.dump(led, open(cur, "w"))
    json.dump(led, open(base, "w"))
    assert cr.main([cur, "--suite", base]) == 0

    worse = json.loads(json.dumps(led))
    worse["derived"]["decode_hbm_bytes_per_token"] = \
        led["derived"]["decode_hbm_bytes_per_token"] * 1.10
    json.dump(worse, open(cur, "w"))
    assert cr.main([cur, "--suite", base]) == 1

    json.dump(led, open(cur, "w"))
    other = json.loads(json.dumps(led))
    other["workload"]["tp"] = 2
    json.dump(other, open(base, "w"))
    assert cr.main([cur, "--suite", base]) == 2


def test_cost_diff_runs_in_jax_free_subprocess(params, tmp_path):
    """tools/cost_diff.py with a poisoned jax shim on PYTHONPATH: exit 0
    on comparable ledgers (rendering the per-phase deltas), exit 2 on
    doctored provenance — jax never imports (the shim raises)."""
    led = _engine(params).cost_ledger()
    cur = str(tmp_path / "cur.json")
    base = str(tmp_path / "base.json")
    moved = json.loads(json.dumps(led))
    moved["derived"]["decode_flops_per_token"] *= 1.5
    moved["executables"]["decode"]["phases"]["mlp"]["flops"] += 1000
    json.dump(led, open(cur, "w"))
    json.dump(moved, open(base, "w"))

    shim = tmp_path / "nojax"
    shim.mkdir()
    (shim / "jax.py").write_text(
        'raise ImportError("jax must not be imported by cost_diff")')
    env = dict(os.environ, PYTHONPATH=str(shim))

    def diff(*extra):
        return subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "cost_diff.py"),
             cur, base, *extra],
            capture_output=True, text=True, env=env)

    proc = diff()
    assert proc.returncode == 0, proc.stderr
    assert "decode_flops_per_token" in proc.stdout
    assert "mlp" in proc.stdout
    doc = json.loads(diff("--json").stdout)
    assert doc["derived"]["decode_flops_per_token"]["ratio"] \
        == pytest.approx(1 / 1.5, rel=1e-4)

    doctored = json.loads(json.dumps(led))
    doctored["workload"]["dtype"] = "bf16"
    json.dump(doctored, open(base, "w"))
    proc = diff()
    assert proc.returncode == 2
    assert "INCOMPARABLE" in proc.stderr and "dtype" in proc.stderr


# ------------------------------------------------------ 4. CLI matrix

def test_bench_cli_cost_ledger_flag_matrix(monkeypatch, tmp_path):
    from apex_tpu.bench_cli import _serve_bench
    from apex_tpu.bench_cli import main as bench_main

    with pytest.raises(SystemExit, match="needs --cost-ledger"):
        _serve_bench(2, 2, chip_spec="v5p")          # inert --chip-spec
    with pytest.raises(SystemExit, match="unknown --chip-spec"):
        _serve_bench(2, 2, cost_ledger=str(tmp_path / "l.json"),
                     chip_spec="v99x")
    with pytest.raises(SystemExit, match="pick two paths"):
        _serve_bench(2, 2, cost_ledger=str(tmp_path / "same.json"),
                     metrics_snapshot=str(tmp_path / "same.json"))
    # --cost-ledger without --serve: the pre-parse matrix exits 2
    monkeypatch.setattr(sys, "argv",
                        ["apex-tpu-bench", "--cost-ledger", "x.json"])
    with pytest.raises(SystemExit) as ei:
        bench_main()
    assert ei.value.code == 2


@pytest.mark.slow
def test_bench_cli_emits_provenance_stamped_ledger(tmp_path, capsys):
    """The full surface in-process: ``--serve --cost-ledger`` writes the
    schema'd, provenance-stamped ledger next to the suite capture, and
    the file round-trips through the gate against itself."""
    from apex_tpu.bench_cli import _serve_bench

    path = str(tmp_path / "ledger.json")
    _serve_bench(4, 2, cost_ledger=path, chip_spec="v5p")
    capsys.readouterr()
    doc = json.load(open(path))
    assert doc["schema"] == costs.LEDGER_SCHEMA
    assert doc["chip_spec"] == "v5p" and doc["gating"] is True
    for k in ("device_kind", "git", "captured"):
        assert k in doc["meta"], k
    for rec in doc["executables"].values():
        _assert_reconciles(rec)
    cr = _check_regression()
    assert cr.main([path, "--suite", path]) == 0
