"""Deviceless v5e compile regression for the distributed stack.

tools/stack_aot.py compiles the ZeRO optimizers (all state layouts, both
LAMB sync modes and clip points), the TP×SP and PP×TP(+MoE) GPT-2 train
steps, and the DDP/SyncBN/Ulysses shard_map paths against a compile-only
4-device v5e client. This test keeps every case green.
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# slow: compiles the entire distributed stack AOT in a subprocess — a
# minutes-scale job that belongs with the long-running integration checks,
# not the fast CPU tier
@pytest.mark.slow
def test_distributed_stack_compiles_for_v5e(tmp_path):
    env = dict(os.environ)
    kept = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(kept + [ROOT])
    env["JAX_PLATFORMS"] = "cpu"
    out = tmp_path / "STACK_AOT.json"
    env["STACK_AOT_OUT"] = str(out)  # never clobber the committed artifact
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "stack_aot.py")],
        env=env, capture_output=True, text=True, timeout=850, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-3000:]
    art = json.load(open(out))
    assert art["ok"] is True
    failed = [n for n, e in art["cases"].items() if not e["ok"]]
    assert not failed, failed
    # every distributed case must actually contain collectives (a
    # partition-free compile would mean the sharding was silently dropped)
    for name, e in art["cases"].items():
        colls = e.get("collectives", {})
        assert sum(colls.values()) > 0, (name, colls)
    # the LAMB grad-sync modes must compile to DIFFERENT collective
    # structure on TPU, mirroring the CPU-mesh HLO test
    # (test_grad_sync_modes_different_collectives); grads are lowered
    # unpinned in the harness precisely so this distinction can surface
    rs = art["cases"]["dist_lamb_rs_ar"]["collectives"]
    fa = art["cases"]["dist_lamb_full_ar"]["collectives"]
    assert rs != fa, (rs, fa)
