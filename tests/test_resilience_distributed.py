"""Distributed resilience: sharded checkpoints, coordinated preemption,
collective watchdog (marker: ``fault``).

Everything runs on the 8-virtual-CPU-device harness, with
``ThreadProcessGroup`` threads standing in for processes. The acceptance
claims are proven here deterministically:

- a tree saved under one mesh shape restores **bit-exact** under another
  mesh/device count (8→4 and 4→8), including through fake multi-process
  two-phase commits;
- a FaultInjector kill at **every** write call of a sharded save — plus
  death between the per-process shard commit and the global-manifest
  publish, and death at the commit replace itself — leaves
  ``restore_latest`` returning the previous committed step;
- the watchdog surfaces an injected straggler as a ``collective_stall``
  event within the configured timeout (and the goodput ledger charges the
  new cause);
- lost/duplicated shard files and corrupt steps are detected, skipped,
  and quarantined (``<step>.corrupt``) so retention only counts steps
  that verify;
- a preemption on any fake host stops every process at the same step,
  with exactly one console banner.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.monitor import GoodputLedger
from apex_tpu.resilience import (CheckpointManager, CollectiveStallError,
                                 CollectiveWatchdog, FaultInjector,
                                 JaxCoordinator, PreemptionGuard,
                                 ShardedCheckpointManager, SimulatedCrash,
                                 SingleProcessCoordinator,
                                 ThreadProcessGroup)
from apex_tpu.utils.logging import subscribe_events

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.fault


def _mesh(n: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("x",))


def _tree_on(mesh: Mesh, seed: float = 0.0):
    """Mixed tree: a sharded matrix, a replicated bf16 vector, a scalar —
    the three shard-ownership cases (unique regions, replica dedup, 0-d)."""
    sh = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    return {
        "b": jax.device_put(jnp.ones((8,), jnp.bfloat16) * (1.0 + seed),
                            sh(P())),
        "s": jax.device_put(jnp.float32(3.5 + seed), sh(P())),
        "w": jax.device_put(jnp.arange(64.0).reshape(16, 4) + seed,
                            sh(P("x", None))),
    }


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.fixture
def events():
    collected = []
    unsub = subscribe_events(collected.append)
    yield collected
    unsub()


def _names(events):
    return [e["event"] for e in events]


# ------------------------------------------------- sharded round-trip

def test_sharded_roundtrip_layout_and_bit_identical(tmp_path, events):
    m = ShardedCheckpointManager(str(tmp_path),
                                 coordinator=SingleProcessCoordinator())
    t = _tree_on(_mesh(8), 1.0)
    m.save(1, t)
    files = sorted(os.listdir(m.step_path(1)))
    # replica dedup: the replicated vector and the scalar each commit ONE
    # shard file, the 8-way matrix commits 8; plus both manifest layers
    assert files.count("manifest.json") == 1
    assert files.count("pmanifest_00000.json") == 1
    assert sum(f.startswith("leaf_00000") for f in files) == 1  # b
    assert sum(f.startswith("leaf_00001") for f in files) == 1  # s
    assert sum(f.startswith("leaf_00002") for f in files) == 8  # w
    step, back = m.restore_latest(_tree_on(_mesh(8), 0.0))
    assert step == 1
    _assert_tree_equal(back, t)
    assert "checkpoint_save_stall" in _names(events)
    assert "checkpoint_restore_stall" in _names(events)


@pytest.mark.parametrize("save_n,restore_n", [(8, 4), (4, 8), (8, 1)])
def test_elastic_restore_across_mesh_shapes(tmp_path, save_n, restore_n):
    """Acceptance: save under one mesh shape, restore bit-exact under a
    different device count — leaves reassemble from shard metadata, not
    from topology assumptions — and land with the target sharding."""
    m = ShardedCheckpointManager(str(tmp_path),
                                 coordinator=SingleProcessCoordinator())
    t = _tree_on(_mesh(save_n), 2.0)
    m.save(7, t)
    like = _tree_on(_mesh(restore_n), 0.0)
    step, back = m.restore_latest(like)
    assert step == 7
    _assert_tree_equal(back, t)
    assert back["w"].sharding == like["w"].sharding
    assert len(back["w"].sharding.device_set) == restore_n


def test_restore_into_unsharded_like(tmp_path):
    """A plain-numpy `like` (no shardings at all) still restores bit-exact
    — elastic down to a single host with no mesh."""
    m = ShardedCheckpointManager(str(tmp_path),
                                 coordinator=SingleProcessCoordinator())
    t = _tree_on(_mesh(8), 3.0)
    m.save(2, t)
    like = jax.tree_util.tree_map(lambda x: np.zeros(x.shape, x.dtype), t)
    step, back = m.restore_latest(like)
    assert step == 2
    _assert_tree_equal(back, t)


# --------------------------------------------- fake multi-process commit

def test_two_phase_commit_across_fake_processes(tmp_path):
    """Two fake processes each stage only the shards they own; the rank-0
    publish assembles full coverage; an elastic restore on a different
    mesh is bit-exact."""
    t = _tree_on(_mesh(8), 4.0)
    grp = ThreadProcessGroup(2)

    def worker(coord, rank):
        mgr = ShardedCheckpointManager(str(tmp_path), coordinator=coord)
        mgr.save(1, t)

    for rank, (_, exc) in enumerate(grp.run(worker)):
        assert exc is None, f"rank {rank}: {exc!r}"
    committed = os.path.join(str(tmp_path), "step_00000001")
    names = set(os.listdir(committed))
    assert {"manifest.json", "pmanifest_00000.json",
            "pmanifest_00001.json"} <= names
    # ownership split: devices 0-3 -> rank 0, devices 4-7 -> rank 1; the
    # sharded matrix's 8 regions split 4/4 between the two pmanifests
    counts = []
    for r in range(2):
        pm = json.loads(open(os.path.join(
            committed, f"pmanifest_{r:05d}.json")).read())
        counts.append(sum(1 for e in pm["shards"] if e["leaf"] == 2))
    assert counts == [4, 4]
    m = ShardedCheckpointManager(str(tmp_path),
                                 coordinator=SingleProcessCoordinator())
    step, back = m.restore_latest(_tree_on(_mesh(4), 0.0))
    assert step == 1
    _assert_tree_equal(back, t)


def test_peer_death_mid_commit_breaks_survivor_out(tmp_path):
    """Rank 0 dies between its shard commit and the global publish: the
    surviving rank gets CollectiveStallError (not a forever-hang) and the
    previous committed step is fully intact."""
    t1 = _tree_on(_mesh(8), 1.0)
    ShardedCheckpointManager(
        str(tmp_path),
        coordinator=SingleProcessCoordinator()).save(1, t1)

    inj = FaultInjector().crash_on_write(r"/manifest\.json$")
    grp = ThreadProcessGroup(2, barrier_timeout_s=10.0)

    def worker(coord, rank):
        fs = inj.filesystem() if rank == 0 else None
        mgr = ShardedCheckpointManager(
            str(tmp_path), coordinator=coord,
            **({"fs": fs} if fs is not None else {}), retries=0)
        mgr.save(2, _tree_on(_mesh(8), 9.0))

    results = grp.run(worker)
    assert isinstance(results[0][1], SimulatedCrash)
    assert isinstance(results[1][1], CollectiveStallError)
    m = ShardedCheckpointManager(str(tmp_path),
                                 coordinator=SingleProcessCoordinator())
    assert m.all_steps() == [1]
    step, back = m.restore_latest(_tree_on(_mesh(8), 0.0))
    assert step == 1
    _assert_tree_equal(back, t1)


# ------------------------------------------- kill-at-every-commit-point

def test_kill_at_every_write_point_recovers_previous_step(tmp_path):
    """Property: crash at EVERY individual write call of a sharded save —
    every shard file, the per-process manifest, the global manifest — and
    at the commit replace itself; restore_latest always returns the
    previous committed step bit-identically, and a recovery save then
    commits cleanly on top."""
    # count the writes one sharded save performs
    probe = FaultInjector()
    d0 = tmp_path / "probe"
    ShardedCheckpointManager(
        str(d0), coordinator=SingleProcessCoordinator(),
        fs=probe.filesystem()).save(1, _tree_on(_mesh(8), 1.0))
    writes_per_save = probe.write_calls
    assert writes_per_save == 12  # 10 shard files + pmanifest + gmanifest

    t1 = _tree_on(_mesh(8), 1.0)
    for n in range(1, writes_per_save + 1):
        d = tmp_path / f"kill_{n:02d}"
        ShardedCheckpointManager(
            str(d), coordinator=SingleProcessCoordinator()).save(1, t1)
        inj = FaultInjector(seed=n).torn_write(n, fraction=0.4)
        crashy = ShardedCheckpointManager(
            str(d), coordinator=SingleProcessCoordinator(),
            fs=inj.filesystem(), retries=0)
        with pytest.raises(SimulatedCrash):
            crashy.save(2, _tree_on(_mesh(8), 9.0))
        m = ShardedCheckpointManager(str(d),
                                     coordinator=SingleProcessCoordinator())
        assert m.all_steps() == [1], f"write {n}: step 2 leaked a commit"
        step, back = m.restore_latest(_tree_on(_mesh(8), 0.0))
        assert step == 1, f"write {n}"
        _assert_tree_equal(back, t1)
        m.save(3, _tree_on(_mesh(8), 3.0))  # recovery save GCs the .tmp
        assert m.latest_step() == 3
        assert not any(x.endswith(".tmp") for x in os.listdir(d))


def test_kill_at_commit_replace_itself(tmp_path):
    """Death at the atomic publish: staging is complete (global manifest
    included) but the replace never ran — still invisible to restore."""
    t1 = _tree_on(_mesh(8), 1.0)
    ShardedCheckpointManager(
        str(tmp_path), coordinator=SingleProcessCoordinator()).save(1, t1)
    inj = FaultInjector().crash_on_replace(r"/step_00000002$")
    crashy = ShardedCheckpointManager(
        str(tmp_path), coordinator=SingleProcessCoordinator(),
        fs=inj.filesystem(), retries=0)
    with pytest.raises(SimulatedCrash):
        crashy.save(2, _tree_on(_mesh(8), 9.0))
    tmp = os.path.join(str(tmp_path), "step_00000002.tmp")
    assert os.path.exists(os.path.join(tmp, "manifest.json"))  # staged...
    m = ShardedCheckpointManager(str(tmp_path),
                                 coordinator=SingleProcessCoordinator())
    assert m.all_steps() == [1]  # ...but never committed
    step, back = m.restore_latest(_tree_on(_mesh(8), 0.0))
    assert step == 1
    _assert_tree_equal(back, t1)


# ---------------------------------------------------- damaged commits

def test_lost_shard_quarantined_with_event(tmp_path, events):
    m = ShardedCheckpointManager(str(tmp_path),
                                 coordinator=SingleProcessCoordinator())
    t1 = _tree_on(_mesh(8), 1.0)
    m.save(1, t1)
    m.save(2, _tree_on(_mesh(8), 2.0))
    inj = FaultInjector(seed=5)
    lost = inj.lose_shard(m.step_path(2), match=r"leaf_00002")
    assert not os.path.exists(lost)

    step, back = m.restore_latest(_tree_on(_mesh(8), 0.0))
    assert step == 1
    _assert_tree_equal(back, t1)
    # the damaged step is quarantined: renamed aside, out of retention
    assert m.all_steps() == [1]
    assert os.path.isdir(m.step_path(2) + ".corrupt")
    quarantined = [e for e in events
                   if e["event"] == "checkpoint_quarantined"]
    assert quarantined and quarantined[0]["step"] == 2


def test_duplicated_shard_detected_by_checksum(tmp_path):
    """A shard file clobbered with another shard's bytes (misdirected
    retry / duplicated object): same file present, wrong content — the
    CRC catches it and restore falls back to the previous step."""
    m = ShardedCheckpointManager(str(tmp_path),
                                 coordinator=SingleProcessCoordinator())
    t1 = _tree_on(_mesh(8), 1.0)
    m.save(1, t1)
    m.save(2, _tree_on(_mesh(8), 2.0))
    FaultInjector(seed=3).duplicate_shard(m.step_path(2),
                                          match=r"leaf_00002")
    step, back = m.restore_latest(_tree_on(_mesh(8), 0.0))
    assert step == 1
    _assert_tree_equal(back, t1)
    assert os.path.isdir(m.step_path(2) + ".corrupt")


def test_drop_write_lost_shard_at_save_time(tmp_path):
    """A write the filesystem silently swallowed (lost shard file): the
    manifest lists it, the file is gone — coverage validation refuses the
    step instead of half-restoring."""
    t1 = _tree_on(_mesh(8), 1.0)
    ShardedCheckpointManager(
        str(tmp_path), coordinator=SingleProcessCoordinator()).save(1, t1)
    inj = FaultInjector().drop_write(r"leaf_00002\.part_003\.npy$")
    m = ShardedCheckpointManager(str(tmp_path),
                                 coordinator=SingleProcessCoordinator(),
                                 fs=inj.filesystem())
    m.save(2, _tree_on(_mesh(8), 9.0))  # commits, one shard file missing
    clean = ShardedCheckpointManager(str(tmp_path),
                                     coordinator=SingleProcessCoordinator())
    step, back = clean.restore_latest(_tree_on(_mesh(8), 0.0))
    assert step == 1
    _assert_tree_equal(back, t1)


def test_layout_mismatch_skips_without_quarantine(tmp_path, events):
    """Pointing the wrong manager at a directory skips the other layout's
    steps cleanly (no KeyError mid-restore) and does NOT quarantine them —
    the data is valid, the manager is wrong."""
    dense = CheckpointManager(str(tmp_path))
    dense.save(1, {"w": jnp.ones((4,))})
    sharded = ShardedCheckpointManager(
        str(tmp_path), coordinator=SingleProcessCoordinator())
    assert sharded.restore_latest({"w": jnp.zeros((4,))}) is None
    assert os.path.isdir(dense.step_path(1))  # untouched, not .corrupt
    assert "checkpoint_quarantined" not in _names(events)
    # and the right manager still restores it
    step, back = dense.restore_latest({"w": jnp.zeros((4,))})
    assert step == 1

    t = _tree_on(_mesh(8), 1.0)
    sharded.save(2, t)
    # dense manager over a sharded step: clean skip (falls back to its own
    # layout's newest step), no quarantine
    assert CheckpointManager(str(tmp_path)).restore_latest(
        {"w": jnp.zeros((4,))})[0] == 1
    assert os.path.isdir(sharded.step_path(2))
    assert "checkpoint_quarantined" not in _names(events)


def test_quarantine_keeps_retention_honest(tmp_path):
    """Satellite: corrupt steps no longer count toward max_to_keep — the
    pre-fix behavior rotated GOOD steps out while corpses accumulated."""
    m = CheckpointManager(str(tmp_path), max_to_keep=2)
    trees = {s: {"w": jnp.full((4,), float(s))} for s in (1, 2, 3, 4)}
    for s in (1, 2, 3):
        m.save(s, trees[s])
    assert m.all_steps() == [2, 3]
    # the newest commit rots on disk
    mpath = os.path.join(m.step_path(3), "manifest.json")
    open(mpath, "wb").write(b"{not json")
    step, back = m.restore_latest({"w": jnp.zeros((4,))})
    assert step == 2
    assert m.all_steps() == [2]
    assert os.path.isdir(m.step_path(3) + ".corrupt")
    # the next save retains step 2 — the corrupt step no longer occupies a
    # retention slot
    m.save(4, trees[4])
    assert m.all_steps() == [2, 4]
    _assert_tree_equal(m.restore(2, {"w": jnp.zeros((4,))}), trees[2])


# ------------------------------------------------------------ watchdog

def test_watchdog_surfaces_straggler_within_timeout(events):
    """Acceptance: an injected straggler host shows up as a
    collective_stall event (with the barrier name and the time waited)
    while the barrier is still pending, and the goodput ledger charges
    the full stall to the new cause."""
    inj = FaultInjector().straggler(rank=1, delay_s=0.35, name="allreduce")
    grp = ThreadProcessGroup(2, injector=inj)
    led = GoodputLedger().attach()
    wd = CollectiveWatchdog(timeout_s=0.05, poll_s=0.01)

    def worker(coord, rank):
        t0 = time.perf_counter()
        with wd.watch("allreduce:grads"):
            coord.barrier("allreduce:grads")
        return time.perf_counter() - t0

    results = grp.run(worker)
    wd.stop()
    led.detach()
    assert all(exc is None for _, exc in results), results
    stalls = [e for e in events if e["event"] == "collective_stall"]
    assert stalls, "straggler was never surfaced"
    assert stalls[0]["name"] == "allreduce:grads"
    # detected within the configured timeout (plus poll jitter), long
    # before the 0.35s straggler actually arrived
    assert 0.05 <= stalls[0]["waited_s"] < 0.3
    # detection + cleared records together charge ~the actual stall time
    assert "collective_stall_cleared" in _names(events)
    lost = led.summary()["lost_by_cause"]["collective_stall"]
    assert lost >= 0.3
    assert wd.stalls  # the watchdog object keeps its own record


def test_watchdog_wired_into_sharded_save_barriers(tmp_path, events):
    """The manager's commit barriers are watched: a straggler process
    stalls the staged-barrier long enough for the watchdog to report."""
    t = _tree_on(_mesh(8), 1.0)
    inj = FaultInjector().straggler(rank=1, delay_s=0.3, name="ckpt_staged")
    grp = ThreadProcessGroup(2, injector=inj)
    wd = CollectiveWatchdog(timeout_s=0.05, poll_s=0.01)

    def worker(coord, rank):
        ShardedCheckpointManager(str(tmp_path), coordinator=coord,
                                 watchdog=wd).save(1, t)

    results = grp.run(worker)
    wd.stop()
    assert all(exc is None for _, exc in results), results
    stalls = [e for e in events if e["event"] == "collective_stall"]
    assert any(e["name"].startswith("ckpt_staged") for e in stalls)
    # the save still committed once the straggler arrived
    m = ShardedCheckpointManager(str(tmp_path),
                                 coordinator=SingleProcessCoordinator())
    step, back = m.restore_latest(_tree_on(_mesh(8), 0.0))
    assert step == 1
    _assert_tree_equal(back, t)


def test_watchdog_escalation_dump_and_abort(events, capsys):
    aborted = []
    wd = CollectiveWatchdog(timeout_s=0.03, poll_s=0.01, escalate="abort",
                            abort_fn=aborted.append)
    with wd.watch("stuck_collective"):
        time.sleep(0.12)
    wd.stop()
    assert aborted == ["stuck_collective"]
    err = capsys.readouterr().err
    assert "collective_stall" in err
    assert "thread" in err  # the all-thread stack dump ran
    assert "collective_stall_abort" in _names(events)


def test_watchdog_quiet_when_nothing_stalls(events):
    wd = CollectiveWatchdog(timeout_s=5.0, poll_s=0.01)
    with wd.watch("fast"):
        pass
    wd.stop()
    assert "collective_stall" not in _names(events)
    assert not wd.stalls


# ------------------------------------------------ coordinated preemption

def test_coordinated_preemption_stops_all_ranks_same_step(events, capsys):
    """A stop request on ANY fake host is agreed via the coordinator:
    every process leaves its loop at the same step, the console banner
    prints once (rank 0), and the bus event fires on every rank."""
    grp = ThreadProcessGroup(2)
    stop_steps = [None, None]

    def trainer(coord, rank):
        guard = PreemptionGuard(coordinator=coord)
        for step in range(10):
            if rank == 1 and step == 3:
                guard.request_stop()  # "SIGTERM" lands on host 1 only
            if guard.should_stop():
                stop_steps[rank] = step
                break
        return stop_steps[rank]

    results = grp.run(trainer)
    assert all(exc is None for _, exc in results), results
    assert stop_steps == [3, 3]
    bus = [e for e in events if e["event"] == "preemption_requested"]
    assert len(bus) == 2  # every rank publishes for its own consumers
    assert {e["origin"] for e in bus} == {"request_stop", "peer"}
    err = capsys.readouterr().err
    assert err.count('"event": "preemption_requested"') == 1  # one banner


def test_jax_coordinator_single_process_degenerates():
    c = JaxCoordinator()
    assert (c.process_index, c.process_count) == (0, 1)
    c.barrier("noop")  # must not hang or compile anything
    assert c.all_any(False) is False
    assert c.all_any(True) is True


# ------------------------------------------------------ durability lint

def test_check_durability_sharded_rules(tmp_path):
    r = subprocess.run([sys.executable,
                        os.path.join(ROOT, "tools", "check_durability.py")],
                       capture_output=True, text=True, cwd=ROOT)
    assert r.returncode == 0, r.stderr

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from check_durability import _check_file
    finally:
        sys.path.pop(0)
    shard_dir = tmp_path / "resilience"
    shard_dir.mkdir()
    # rule 1: a sharded write landing outside .tmp staging is flagged even
    # through the write_bytes seam
    bad = shard_dir / "distributed_bad.py"
    bad.write_text(
        "def save_shard(fs, final_path, blob):\n"
        "    fs.write_bytes(final_path, blob)\n")
    msgs = [m for _, m in _check_file(str(bad))]
    assert any("outside .tmp staging" in m for m in msgs), msgs
    # the same write against the staging dir is clean
    good = shard_dir / "distributed_good.py"
    good.write_text(
        "import os\n"
        "def save_shard(fs, tmp, name, blob):\n"
        "    fs.write_bytes(os.path.join(tmp, name), blob)\n")
    assert not _check_file(str(good))
    # rule 2: publishing via os.rename instead of os.replace is flagged
    renamey = shard_dir / "distributed_rename.py"
    renamey.write_text(
        "import os\n"
        "def commit(tmp, final):  # .tmp staging present, rename is not\n"
        "    os.rename(tmp, final)\n")
    msgs = [m for _, m in _check_file(str(renamey))]
    assert any("os.replace" in m for m in msgs), msgs
