"""Fleet request journeys tier-1 (ISSUE 13): cross-replica tracing,
tail-capture sampling, jax-free latency attribution.

THE invariants under test:

- **one journey per request** — the PR-11 chaos schedule (kill +
  partition + straggle) with tracing armed yields exactly one fleet
  trace per submitted request, failover/hedge spans reconcile with the
  fleet summary counters and the goodput ledger's timed causes
  (bit-for-bit on the rounded attr values), and ``decode_traces`` delta
  is 0 on every survivor with tracing + metrics + flight recorder all
  armed;
- **tail capture** — at ``--trace-sample 0.1`` every bad-outcome
  request's full journey is promoted into the trace file while the
  happy path holds to the deterministic seeded sample;
- **jax-free attribution** — ``tools/trace_explain.py`` merges the
  fleet + per-replica files and passes its reconciliation in a
  subprocess where importing jax raises.

Engines are compiled once per module and shared via ``Engine.reset()``
(the test_serve_fleet pattern); trace-counter assertions use
before/after deltas.
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.monitor import journey as journey_mod
from apex_tpu.monitor.flight import FlightRecorder
from apex_tpu.monitor.goodput import STALL_EVENTS, GoodputLedger
from apex_tpu.monitor.trace import (ChromeTraceWriter, TailCaptureRouter,
                                    TraceSampler, Tracer)
from apex_tpu.resilience.fault_injection import FaultInjector
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.fleet import (EngineReplica, FleetController,
                                  FleetTraceHarness, ReplicaRegistry)
from apex_tpu.serve.metrics import ServeMetrics
from apex_tpu.serve.resilience import AdmissionController
from apex_tpu.serve.scheduler import Request, ServeScheduler
# bound at collection time: test_chip_worker purges apex_tpu.* from
# sys.modules mid-session (see test_serve_resilience for the history)
from apex_tpu.utils.logging import publish_event, subscribe_events

pytestmark = [pytest.mark.serve, pytest.mark.trace]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = GPT2Config(vocab_size=61, n_positions=32, n_embd=16, n_layer=1,
                 n_head=2, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_gpt2_params(CFG, seed=0)


@pytest.fixture(scope="module")
def engines(params):
    """Three 2-slot greedy engines sharing ONE param pytree, pre-warmed
    (a prefill compiling inside a worker tick reads as a death)."""
    return [Engine(CFG, params,
                   EngineConfig(num_slots=2, max_len=32, temperature=0.0),
                   seed=0).aot_compile([8])
            for _ in range(3)]


def _tokens(n, seed=7, vocab=61):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, vocab, n)]


def _requests(n=6, max_new=4, **kw):
    return [Request(request_id=f"r{i}", tokens=_tokens(4 + i % 3, seed=i),
                    max_new_tokens=max_new, **kw) for i in range(n)]


def _journey_trace_ids(records):
    """Trace ids that have a journey ROOT present in the capture."""
    return {str(r["trace_id"]) for r in records
            if r.get("parent_id") is None
            and str(r["trace_id"]).startswith("journey:")}


# ----------------------------------------------------------------- units

def test_sampler_deterministic_and_bounded():
    s1 = TraceSampler(0.3, seed=42)
    s2 = TraceSampler(0.3, seed=42)
    keys = [f"journey:r{i}" for i in range(500)]
    assert [s1.sampled(k) for k in keys] == [s2.sampled(k) for k in keys]
    frac = sum(s1.sampled(k) for k in keys) / len(keys)
    assert 0.15 < frac < 0.45       # seeded hash, roughly the rate
    assert TraceSampler(1.0).sampled("anything")
    for bad in (0.0, -0.1, 1.5):
        with pytest.raises(ValueError, match="rate"):
            TraceSampler(bad)


def test_timed_cause_map_matches_goodput_schema():
    """journey.py cannot import goodput (jax-free by design) so it
    carries its own copy of the serve timed-cause map — THIS assertion
    is what keeps the two from drifting."""
    serve = {k: v for k, v in STALL_EVENTS.items()
             if k.startswith("serve_")}
    assert journey_mod.SERVE_TIMED_CAUSES == serve


def test_tail_capture_router_promotes_and_drops(tmp_path):
    """Unit, no fleet: an unsampled journey buffers in its ring; a bad
    terminal promotes it (serve_trace_promoted published, spans in the
    file), a happy terminal drops it, and a sampled journey streams."""
    path = str(tmp_path / "router.json")
    tracer = Tracer()
    # rate tiny: neither unit journey is head-sampled (asserted)
    router = TailCaptureRouter(
        {"": ChromeTraceWriter(path, subscribe=False)},
        sample_rate=1e-9, sample_seed=0, ring_spans=8)
    promoted = []
    unsub = subscribe_events(
        lambda r: promoted.append(r)
        if r.get("event") == "serve_trace_promoted" else None)
    try:
        assert not router.sampler.sampled("request:u1")
        assert not router.sampler.sampled("request:u2")
        for rid, ev in (("u1", "serve_request_completed"),
                        ("u2", "serve_deadline_exceeded")):
            root = tracer.begin("request", trace_id=f"request:{rid}",
                                t0=0.0, request_id=rid)
            child = tracer.begin("decode", parent=root, t0=0.0)
            tracer.end(child, t1=0.5)
            tracer.end(root, t1=1.0)
            publish_event(ev, request_id=rid, seconds=0.0,
                          emit=False)
    finally:
        unsub()
        router.close()
    stats = router.stats()
    assert stats == {"sampled": 0, "promoted": 1, "dropped": 1}
    assert len(promoted) == 1 and promoted[0]["request_id"] == "u2"
    recs = journey_mod.load_trace_files([path])
    tids = {r["trace_id"] for r in recs}
    assert tids == {"request:u2"}, "the happy journey leaked (or the "\
        "bad one was dropped)"
    assert len(recs) == 2           # its FULL ring: decode + root


def test_reject_at_submit_journey_is_promotable(engines, tmp_path):
    """Review regression: a submit-time admission rejection is a BAD
    outcome — its trace root must open BEFORE the verdict, or the
    journey has zero spans and tail capture has nothing to promote
    (the file would silently miss exactly the requests being shed).
    Scheduler + admission are bound at collection time like every other
    import here — a function-local import would re-bind them to a fresh
    bus after test_chip_worker's purge and the router would never hear
    the rejection."""
    path = str(tmp_path / "reject.json")
    tracer = Tracer()
    router = TailCaptureRouter(
        {"": ChromeTraceWriter(path, subscribe=False)},
        sample_rate=1e-9, sample_seed=0)
    try:
        sched = ServeScheduler(
            engines[0].reset(), tracer=tracer,
            admission=AdmissionController(max_queue=1,
                                          shed_policy="reject-newest"))
        assert sched.submit(Request(request_id="keep",
                                    tokens=_tokens(4),
                                    max_new_tokens=2))
        assert sched.submit(Request(request_id="shed-me",
                                    tokens=_tokens(4, seed=9),
                                    max_new_tokens=2)) is False
        sched.run()
    finally:
        router.close()
    recs = journey_mod.load_trace_files([path])
    tids = {r["trace_id"] for r in recs}
    assert "request:shed-me" in tids, \
        "the rejected-at-submit journey never reached the trace file"
    shed = [r for r in recs if r["trace_id"] == "request:shed-me"]
    assert {"request", "reject"} <= {r["name"] for r in shed}
    assert router.stats()["promoted"] >= 1


def test_flight_recorder_replica_death_postmortem(tmp_path):
    """A serve_replica_dead record auto-dumps the per-replica recorder —
    scoped by trigger_filter to ITS replica, with the registry row as
    context — while the peer replica's recorder stays quiet."""
    t = [0.0]
    reg = ReplicaRegistry(0.05, suspect_misses=2, dead_misses=4,
                          clock=lambda: t[0])
    reg.register("a")
    reg.register("b")
    recorders = {}
    for rid in ("a", "b"):
        recorders[rid] = FlightRecorder(
            str(tmp_path / f"flight.{rid}.json"),
            trigger_filter=lambda rec, rid=rid:
            rec.get("replica") in (None, rid),
            context_fn=lambda rid=rid: reg.row(rid)).attach()
    try:
        t[0] = 0.30                  # replica "a" and "b" both silent...
        reg.heartbeat("b")           # ...but b beat just in time
        reg.sweep()                  # a -> dead (one event, replica="a")
    finally:
        for fr in recorders.values():
            fr.detach()
    assert os.path.exists(recorders["a"].path)
    assert not os.path.exists(recorders["b"].path), \
        "a peer's death must not dump every replica's recorder"
    d = json.load(open(recorders["a"].path))
    assert d["reason"] == "serve_replica_dead"
    assert d["context"]["replica"] == "a"
    assert d["context"]["state"] == "dead"
    assert any(r.get("event") == "serve_replica_dead"
               for r in d["events"])


def test_fleet_metrics_exporter_merged_and_per_replica_routes():
    import urllib.request

    from apex_tpu.monitor.export import (FleetMetricsExporter,
                                         MetricsRegistry)

    regs = {"r0": MetricsRegistry(), "r1": MetricsRegistry()}
    regs["r0"].counter("serve_requests_completed_total").inc(3)
    regs["r1"].counter("serve_requests_completed_total").inc(4)
    exp = FleetMetricsExporter(regs, port=0,
                               meta={"device_kind": "cpu"}).start()
    try:
        base = f"http://127.0.0.1:{exp.port}"

        def get(path):
            return urllib.request.urlopen(base + path, timeout=5).read()

        merged = json.loads(get("/metrics.json"))
        total = sum(s["value"] for s in merged["metrics"]
                    ["serve_requests_completed_total"]["series"])
        assert total == 7
        assert merged["meta"]["merged_from"] == 2
        r0 = json.loads(get("/metrics/r0.json"))
        assert r0["meta"]["replica"] == "r0"
        assert sum(s["value"] for s in r0["metrics"]
                   ["serve_requests_completed_total"]["series"]) == 3
        text = get("/metrics").decode()
        assert "serve_requests_completed_total" in text
        assert "serve_requests" in get("/metrics/r1").decode()
        with pytest.raises(urllib.error.HTTPError):
            get("/metrics/nope")
    finally:
        exp.stop()


def test_lockfree_progress_snapshot_semantics(engines):
    """The (load, done_count) probe is a published snapshot, not a live
    query: a direct scheduler mutation is invisible until someone
    publishes — which every controller-side mutation path and every
    worker tick does."""
    h = EngineReplica("rep0", engines[0].reset())
    assert h.load() == 0 and h.done_count == 0
    h.scheduler.submit(Request(request_id="x", tokens=_tokens(4),
                               max_new_tokens=2))
    assert h.load() == 0, "a snapshot, not a live read"
    h.publish_progress()
    assert h.load() == 1 and h.done_count == 0
    assert h.scheduler.progress() == (1, 0)


# ----------------------------------------- journeys reconcile (no fault)

def test_fleet_journeys_reconcile_no_fault(engines, tmp_path):
    """Every request is exactly one journey; the replica's
    queue/prefill/decode spans nest under the fleet attempt span in the
    SAME trace; attribution reconciles exactly with the summary + the
    ledger's timed causes; and decode compiles exactly once per replica
    with tracing + metrics + flight recorder ALL armed."""
    path = str(tmp_path / "trace.json")
    harness = FleetTraceHarness(path, ["rep0", "rep1"], sample_rate=1.0)
    handles = [EngineReplica(f"rep{i}", e.reset(),
                             metrics=ServeMetrics(),
                             tracer=harness.tracer_for(f"rep{i}"))
               for i, e in enumerate(engines[:2])]
    recorders = [FlightRecorder(str(tmp_path / f"fl.rep{i}.json"),
                                tracer=harness.tracer_for(f"rep{i}")
                                ).attach()
                 for i in range(2)]
    traces = [e.decode_traces for e in engines[:2]]
    fleet = FleetController(handles, heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000,
                            tracer=harness.fleet_tracer)
    events = []
    unsub = subscribe_events(
        lambda r: events.append(r) if "event" in r else None)
    try:
        for r in _requests():
            fleet.submit(r)
        stats = fleet.run(max_wall_s=30)
    finally:
        unsub()
        for fr in recorders:
            fr.detach()
        harness.close()
    assert [e.decode_traces for e in engines[:2]] == traces, \
        "tracing+metrics+flight must add ZERO compiles"

    records = journey_mod.load_trace_files(harness.paths)
    summary = stats.summary()
    assert _journey_trace_ids(records) == \
        {f"journey:r{i}" for i in range(6)}
    # the replica-side request root is a CHILD of the fleet attempt span
    by_trace = journey_mod.spans_by_trace(records)
    for tid, spans in by_trace.items():
        if not tid.startswith("journey:"):
            continue
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        att, = by_name["attempt"]
        req_root, = by_name["request"]
        assert req_root["parent_id"] == att["span_id"]
        assert {"queue", "prefill", "decode", "complete",
                "terminal", "fleet_queue"} <= set(by_name)
        # every span of the journey shares the one trace id — that IS
        # the cross-replica propagation contract
        assert {s["trace_id"] for s in spans} == {tid}
    journeys = journey_mod.attribute_journeys(records)
    causes, counts = journey_mod.ledger_causes(events)
    problems = journey_mod.reconcile(journeys, records, summary=summary,
                                     causes=causes, counts=counts)
    assert problems == []
    # the exact record values rode the spans: ttfts match bit-for-bit
    got = sorted(j["ttft_s"] for j in journeys)
    want = sorted(r["ttft_s"] for r in stats.requests)
    assert got == want
    assert harness.stats()["sampled"] == 6
    assert harness.stats()["promoted"] == 0


# --------------------------------------------- THE chaos smoke, traced

@pytest.mark.fault
def test_fleet_chaos_journeys_reconcile(engines, tmp_path):
    """ISSUE 13 acceptance: the PR-11 chaos schedule (kill + partition
    + straggle) with tracing + metrics + per-replica flight recorders
    ALL armed yields exactly one fleet trace per submitted request,
    failover/hedge spans reconcile with the fleet summary counters and
    the ledger's timed causes, decode_traces delta is 0 on every
    replica, and the dead replicas' postmortems auto-dumped."""
    inj = (FaultInjector(seed=0)
           .kill_replica("rep1", at_tick=3)
           .partition_replica("rep2", at_tick=4)
           .straggler_replica("rep0", 0.01, at_tick=2, ticks=3))
    path = str(tmp_path / "chaos.json")
    ids = ["rep0", "rep1", "rep2"]
    harness = FleetTraceHarness(path, ids, sample_rate=1.0)
    handles = [EngineReplica(rid, e.reset(), metrics=ServeMetrics(),
                             tracer=harness.tracer_for(rid))
               for rid, e in zip(ids, engines)]
    traces = [e.decode_traces for e in engines]
    fleet = FleetController(handles, heartbeat_ms=25,
                            suspect_misses=50, dead_misses=200,
                            hedge_ms=150.0, fault_injector=inj,
                            tracer=harness.fleet_tracer)
    recorders = [FlightRecorder(
        str(tmp_path / f"fl.{rid}.json"),
        tracer=harness.tracer_for(rid),
        trigger_filter=lambda rec, rid=rid:
        rec.get("replica") in (None, rid),
        context_fn=lambda rid=rid: fleet.registry.row(rid)).attach()
        for rid in ids]
    events = []
    unsub = subscribe_events(
        lambda r: events.append(r) if "event" in r else None)
    try:
        for r in _requests():
            fleet.submit(r)
        with GoodputLedger() as led:
            stats = fleet.run(max_wall_s=45)
    finally:
        unsub()
        for fr in recorders:
            fr.detach()
        harness.close()
    assert [e.decode_traces for e in engines] == traces, \
        "a replica retraced decode under chaos with tracing + metrics " \
        "+ flight recorders armed"
    # the killed and the partitioned replica each left a postmortem
    # whose context row says dead
    for rid in ("rep1", "rep2"):
        d = json.load(open(tmp_path / f"fl.{rid}.json"))
        assert d["reason"] in ("serve_replica_dead",
                               "serve_replica_suspect")
        assert d["context"]["replica"] == rid
    summary = stats.summary()
    assert summary["replica_dead"] == 2

    records = journey_mod.load_trace_files(harness.paths)
    assert _journey_trace_ids(records) == \
        {f"journey:r{i}" for i in range(6)}, \
        "want exactly one journey per submitted request"
    journeys = journey_mod.attribute_journeys(records)
    causes, counts = journey_mod.ledger_causes(events)
    problems = journey_mod.reconcile(journeys, records, summary=summary,
                                     causes=causes, counts=counts)
    assert problems == [], problems
    # the span attrs and the ledger folded the SAME rounded seconds
    g = led.summary()
    span_failover = sum(
        float((s.get("attrs") or {}).get("seconds", 0.0))
        for s in records if s["name"] == "failover")
    assert span_failover == pytest.approx(
        g["lost_by_cause"].get("serve_failover", 0.0), abs=1e-9)
    assert sum(j["failovers"] for j in journeys) == summary["failovers"]
    assert sum(j["hedged"] for j in journeys) == summary["hedge_fired"]


# ------------------------------------------------------- tail capture

def test_tail_capture_promotes_every_bad_outcome_at_low_rate(
        engines, tmp_path):
    """ISSUE 13 acceptance: at --trace-sample 0.1 tail capture records
    100% of bad-outcome requests (a queued deadline storm) while the
    happy path holds to the deterministic seeded sample."""
    path = str(tmp_path / "sampled.json")
    ids = ["rep0", "rep1"]
    harness = FleetTraceHarness(path, ids, sample_rate=0.1,
                                sample_seed=3)
    handles = [EngineReplica(rid, e.reset(),
                             tracer=harness.tracer_for(rid))
               for rid, e in zip(ids, engines)]
    fleet = FleetController(handles, heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000,
                            tracer=harness.fleet_tracer)
    happy = [f"h{i}" for i in range(8)]
    bad = [f"b{i}" for i in range(4)]
    promoted_events = []
    unsub = subscribe_events(
        lambda r: promoted_events.append(r)
        if r.get("event") == "serve_trace_promoted" else None)
    try:
        for i, rid in enumerate(happy):
            fleet.submit(Request(request_id=rid,
                                 tokens=_tokens(4, seed=i),
                                 max_new_tokens=3))
        for i, rid in enumerate(bad):
            # an impossible deadline: the first tick's sweep expires it
            # (finish_reason "deadline" — a bad outcome by contract)
            fleet.submit(Request(request_id=rid,
                                 tokens=_tokens(4, seed=40 + i),
                                 max_new_tokens=3, deadline_ms=0.01))
        stats = fleet.run(max_wall_s=30)
    finally:
        unsub()
        harness.close()
    by_state = {r["request_id"]: r for r in stats.requests}
    assert all(by_state[rid]["finish_reason"] == "deadline"
               for rid in bad)
    assert all(by_state[rid]["state"] == "completed" for rid in happy)

    captured = _journey_trace_ids(
        journey_mod.load_trace_files(harness.paths))
    sampler = harness.router.sampler
    sampled_happy = {f"journey:{rid}" for rid in happy
                     if sampler.sampled(f"journey:{rid}")}
    # every bad-outcome journey is captured — sampled or promoted —
    # and the happy path is EXACTLY the deterministic head sample
    assert captured == sampled_happy | {f"journey:{rid}"
                                        for rid in bad}, captured
    want_promoted = sum(not sampler.sampled(f"journey:{rid}")
                        for rid in bad)
    assert harness.stats()["promoted"] == want_promoted
    assert len(promoted_events) == want_promoted
    assert harness.stats()["dropped"] == len(happy) - len(sampled_happy)
    assert want_promoted >= 1, "schedule produced nothing to promote"
    assert len(sampled_happy) < len(happy), \
        "every happy journey sampled: the sample rate did nothing"


# -------------------------------------------- trace_explain, jax-free

def test_trace_explain_reconciles_in_jax_free_subprocess(
        engines, tmp_path):
    """ISSUE 13 acceptance: tools/trace_explain.py runs with no jax
    importable (a poisoned jax shim raises on import), reconciles a
    traced fleet capture (exit 0), and FAILS loudly (exit 1) when the
    summary is doctored — the reconciliation IS the test."""
    path = str(tmp_path / "ex.json")
    ids = ["rep0", "rep1"]
    harness = FleetTraceHarness(path, ids, sample_rate=1.0)
    handles = [EngineReplica(rid, e.reset(),
                             tracer=harness.tracer_for(rid))
               for rid, e in zip(ids, engines)]
    fleet = FleetController(handles, heartbeat_ms=25,
                            suspect_misses=5_000, dead_misses=10_000,
                            tracer=harness.fleet_tracer)
    events = []
    unsub = subscribe_events(
        lambda r: events.append(r) if "event" in r else None)
    try:
        for r in _requests(4, max_new=3):
            fleet.submit(r)
        stats = fleet.run(max_wall_s=30)
    finally:
        unsub()
        harness.close()
    events_path = str(tmp_path / "events.jsonl")
    with open(events_path, "w") as f:
        for rec in events:
            f.write(json.dumps(rec, default=str) + "\n")
    summary_path = str(tmp_path / "summary.json")
    json.dump({"summary": stats.summary(),
               "trace": harness.stats()}, open(summary_path, "w"))
    shim = tmp_path / "nojax"
    shim.mkdir()
    (shim / "jax.py").write_text(
        'raise ImportError("jax must not be imported by trace_explain")')
    env = dict(os.environ)
    env["PYTHONPATH"] = str(shim)

    def explain(summary_file):
        return subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "tools", "trace_explain.py"),
             *harness.paths, "--events", events_path,
             "--summary", summary_file,
             "--perfetto", str(tmp_path / "merged.json")],
            capture_output=True, text=True, env=env)

    proc = explain(summary_path)
    assert proc.returncode == 0, proc.stderr
    assert "reconciled" in proc.stderr
    assert "dominant=" in proc.stdout
    merged = json.load(open(tmp_path / "merged.json"))
    tracks = {e["args"]["name"] for e in merged if e.get("ph") == "M"}
    assert tracks == {"fleet", "rep0", "rep1"}

    # doctor the summary: one phantom failover -> exit 1, named mismatch
    doctored = {"summary": {**stats.summary(),
                            "failovers": stats.summary()["failovers"] + 1},
                "trace": harness.stats()}
    doctored_path = str(tmp_path / "doctored.json")
    json.dump(doctored, open(doctored_path, "w"))
    proc = explain(doctored_path)
    assert proc.returncode == 1
    assert "MISMATCH" in proc.stderr
