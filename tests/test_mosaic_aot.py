"""Deviceless Mosaic compile regression (VERDICT r4 item 2).

The interpret-mode suite is blind to Mosaic compile errors (layout, tiling,
VMEM budget) — tools/mosaic_aot.py compiles the whole kernel zoo against a
compile-only v5e topology built from the baked-in libtpu, no chip or relay
needed. This test keeps that property green: every kernel tag must compile.

(The round-4 relay outage proved the need: the RDMA halo kernel carried a
tile-misaligned HBM slice for two rounds that interpret mode executed
happily and Mosaic rejects outright.)
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# slow: a full kernel-zoo AOT compile is a minutes-scale subprocess — far
# the heaviest single test — and belongs with the other long-running
# integration checks, not the fast CPU tier
@pytest.mark.slow
def test_kernel_zoo_compiles_for_v5e(tmp_path):
    env = dict(os.environ)
    kept = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(kept + [ROOT])
    env["JAX_PLATFORMS"] = "cpu"
    out = tmp_path / "MOSAIC_AOT.json"
    env["MOSAIC_AOT_OUT"] = str(out)  # never clobber the committed artifact
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "mosaic_aot.py")],
        env=env, capture_output=True, text=True, timeout=850, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-3000:]
    art = json.load(open(out))
    assert art["ok"] is True
    failed = [
        f"{k}:{t}" for k, rec in art["kernels"].items()
        for t, e in rec["tags"].items() if not e["ok"]]
    assert not failed, failed
    # the multi-device RDMA ring and ring attention must be among them
    assert "remote_copy" in art["kernels"]
    assert "ring_attention" in art["kernels"]
    # memory-structure regressions the compile-only client can prove:
    # flash attention must stay O(s·d), far under the ~1.07 GB a
    # materialized (b4·h16) 2048x2048 fp32 score matrix would need
    fa = art["kernels"]["flash_attention"]["tags"]
    for tag in ("causal_fwd_b4h16s2048", "dropout_fwd"):
        tmp = fa[tag].get("hbm_tmp_bytes")
        if tmp is not None:
            assert tmp < 400e6, (tag, tmp)
    # the flat Adam kernel streams fully in place: zero temp HBM
    ad = art["kernels"]["fused_adam_flat"]["tags"]
    for tag, e in ad.items():
        if e.get("hbm_tmp_bytes") is not None:
            assert e["hbm_tmp_bytes"] < 1e6, (tag, e)
