"""Model zoo + graft entry integration tests (BASELINE configs 2/5 shapes,
tiny sizes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
from apex_tpu.models.resnet import ResNet18ish


class TestGPT2:
    def test_forward_and_loss(self):
        cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=64,
                         n_layer=2, n_head=2)
        model = GPT2(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0, 128)
        params = model.init(jax.random.PRNGKey(1), tokens)
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 64, 128)
        loss = lm_loss(model, params, tokens)
        # random init → loss ≈ ln(vocab)
        assert abs(float(loss) - np.log(128)) < 1.0

    def test_train_step_descends(self):
        from apex_tpu.optimizers.functional import adam_update

        cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=32,
                         n_layer=1, n_head=2)
        model = GPT2(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 32), 0, 64)
        params = model.init(jax.random.PRNGKey(1), tokens)
        m = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32),
                                   params)
        v = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32),
                                   params)

        @jax.jit
        def step(params, m, v, s):
            loss, grads = jax.value_and_grad(
                lambda p: lm_loss(model, p, tokens))(params)
            params, m, v = adam_update(params, grads, m, v, step=s, lr=1e-2)
            return params, m, v, loss

        losses = []
        for i in range(10):
            params, m, v, loss = step(params, m, v, jnp.int32(i + 1))
            losses.append(float(loss))
        assert losses[-1] < losses[0] - 0.5


class TestResNet:
    def test_forward_train_and_eval(self):
        model = ResNet18ish(num_classes=10)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(1), x)
        y, mutated = model.apply(variables, x, mutable=["batch_stats"])
        assert y.shape == (2, 10)
        assert y.dtype == jnp.float32
        y_eval = model.apply(
            {"params": variables["params"],
             "batch_stats": mutated["batch_stats"]},
            x, use_running_average=True)
        assert bool(jnp.all(jnp.isfinite(y_eval)))

    @pytest.mark.slow
    def test_grads_finite(self):
        # slow tier: the conv backward compile is ~15s and forward
        # coverage above keeps ResNet in tier-1
        model = ResNet18ish(num_classes=4)
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 16, 3))
        variables = model.init(jax.random.PRNGKey(3), x)

        def loss(p):
            y, _ = model.apply({"params": p,
                                "batch_stats": variables["batch_stats"]},
                               x, mutable=["batch_stats"])
            return jnp.mean(y ** 2)

        g = jax.grad(loss)(variables["params"])
        for leaf in jax.tree_util.tree_leaves(g):
            assert bool(jnp.all(jnp.isfinite(leaf)))


@pytest.mark.slow
class TestGraftEntry:
    def test_entry_compiles(self):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as ge
        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == args[1].shape[0]

    @pytest.mark.parametrize("n", [1, 2, 4, 8])
    def test_dryrun_multichip(self, n):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as ge
        ge.dryrun_multichip(n)


class TestBert:
    def test_forward_and_mlm_loss(self):
        from apex_tpu.models.bert import Bert, BertConfig, mlm_loss
        cfg = BertConfig.tiny()
        model = Bert(cfg)
        ids = jax.random.randint(jax.random.PRNGKey(0), (2, 128), 0,
                                 cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(1), ids)
        logits = model.apply(params, ids)
        assert logits.shape == (2, 128, cfg.vocab_size)
        labels = ids.at[:, ::4].set(-1)  # ignore 1/4 positions
        loss = mlm_loss(model, params, ids, labels)
        assert np.isfinite(float(loss))

    @pytest.mark.slow
    def test_attn_mask_path(self):
        # slow tier: a second full Bert compile for the masked branch;
        # the unmasked forward above keeps Bert in tier-1
        from apex_tpu.models.bert import Bert, BertConfig
        cfg = BertConfig.tiny()
        model = Bert(cfg)
        ids = jax.random.randint(jax.random.PRNGKey(2), (2, 64), 0,
                                 cfg.vocab_size)
        mask = jnp.ones((2, 64), jnp.int32).at[:, 50:].set(0)
        params = model.init(jax.random.PRNGKey(3), ids)
        out = model.apply(params, ids, attn_mask=mask)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_pretrain_with_fused_lamb_descends(self):
        """Config 4 shape: BERT + FusedLAMB + RMSNorm + xentropy."""
        from apex_tpu.models.bert import Bert, BertConfig, mlm_loss
        from apex_tpu.optimizers import FusedLAMB
        cfg = BertConfig(vocab_size=64, max_position_embeddings=32,
                         hidden_size=32, num_hidden_layers=1,
                         num_attention_heads=2, intermediate_size=64)
        model = Bert(cfg)
        ids = jax.random.randint(jax.random.PRNGKey(4), (4, 32), 0, 64)
        params = model.init(jax.random.PRNGKey(5), ids)
        opt = FusedLAMB(params, lr=5e-3)

        @jax.jit
        def grads_fn(p):
            return jax.value_and_grad(
                lambda pp: mlm_loss(model, pp, ids, ids))(p)

        losses = []
        p = opt.parameters
        for _ in range(8):
            loss, g = grads_fn(p)
            p = opt.step(g)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestProf:
    def test_step_timer_and_annotate(self):
        from apex_tpu.utils.prof import StepTimer, annotate
        t = StepTimer()
        t.start()
        with annotate("test_region"):
            x = jnp.sum(jnp.ones((128, 128)) @ jnp.ones((128, 128)))
        dt = t.stop(block_on=x)
        assert dt > 0 and t.avg > 0


@pytest.mark.slow
class TestReturnHidden:
    def test_hidden_matmul_equals_logits(self):
        """return_hidden=True exposes the pre-logits states the fused
        LM head consumes: hidden @ wte.T must equal the normal logits.

        Slow tier: two full GPT-2 compiles for a static numeric identity
        that the fused-head training tests exercise end-to-end anyway."""
        cfg = GPT2Config.tiny()
        model = GPT2(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0,
                                    cfg.vocab_size, jnp.int32)
        params = model.init(jax.random.PRNGKey(1), tokens)
        logits = model.apply(params, tokens)
        hidden = model.apply(params, tokens, return_hidden=True)
        wte = params["params"]["wte"]
        again = jnp.einsum("bsh,vh->bsv", hidden,
                           wte.astype(hidden.dtype),
                           preferred_element_type=jnp.float32)
        np.testing.assert_allclose(np.asarray(again), np.asarray(logits),
                                   rtol=1e-5, atol=1e-5)
