"""Transformer kernel pack parity tests (megatron softmax family, RoPE,
xentropy, fused dense/MLP, wgrad accumulation) — apex contrib test pattern:
fused op vs jnp/torch reference under allclose."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.contrib.xentropy import (SoftmaxCrossEntropyLoss,
                                       softmax_cross_entropy_loss)
from apex_tpu.transformer import (MLP, FusedDense, FusedDenseGeluDense,
                                  dense_gelu_dense, fused_rope,
                                  fused_rope_cached, fused_rope_thd,
                                  generic_scaled_masked_softmax, linear_bias,
                                  mlp_forward, scaled_masked_softmax,
                                  scaled_softmax,
                                  scaled_upper_triang_masked_softmax,
                                  wgrad_gemm_accum_fp32)


class TestScaledSoftmax:
    def test_scaled_softmax_vs_jax(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 16))
        y = scaled_softmax(x, 0.5)
        ref = jax.nn.softmax(x * 0.5, axis=-1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)

    def test_masked_matches_reference_fill(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 4, 8))
        mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.3,
                                    (2, 1, 4, 8)).astype(jnp.uint8)
        y = scaled_masked_softmax(x, mask, 2.0)
        filled = np.where(np.asarray(mask, bool), -10000.0,
                          np.asarray(x) * 2.0)
        ref = jax.nn.softmax(jnp.asarray(filled), axis=-1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)

    def test_fully_masked_row_is_zero(self):
        """Reference zeros fully-masked rows (scaled_masked_softmax.h:297)."""
        x = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 2, 8))
        mask = jnp.ones((1, 1, 2, 8), jnp.uint8)
        y = scaled_masked_softmax(x, mask, 1.0)
        np.testing.assert_array_equal(np.asarray(y), 0.0)

    def test_causal(self):
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 2, 6, 6))
        y = scaled_upper_triang_masked_softmax(x, 1.0)
        yn = np.asarray(y)
        # strictly-upper-triangular entries must be exactly zero
        for i in range(6):
            for j in range(i + 1, 6):
                np.testing.assert_array_equal(yn[..., i, j], 0.0)
        np.testing.assert_allclose(yn.sum(-1), 1.0, atol=1e-6)

    def test_backward_matches_autodiff(self):
        x = jax.random.normal(jax.random.PRNGKey(5), (2, 2, 4, 8))

        def fused(x):
            return jnp.sum(scaled_softmax(x, 1.7) ** 2)

        def ref(x):
            return jnp.sum(jax.nn.softmax(x * 1.7, axis=-1) ** 2)

        np.testing.assert_allclose(np.asarray(jax.grad(fused)(x)),
                                   np.asarray(jax.grad(ref)(x)), atol=1e-5)

    def test_generic_same_as_masked(self):
        x = jax.random.normal(jax.random.PRNGKey(6), (1, 1, 4, 300))
        y1 = generic_scaled_masked_softmax(x, None, 1.0)
        y2 = scaled_softmax(x, 1.0)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))


class TestRoPE:
    def _ref_rope(self, x, freqs):
        # NeoX rotate-half reference
        d2 = freqs.shape[-1]
        cos = np.cos(freqs)[:, None, None, :]
        sin = np.sin(freqs)[:, None, None, :]
        xh = np.asarray(x[..., :d2], np.float32)
        rot = np.concatenate([-xh[..., d2 // 2:], xh[..., : d2 // 2]], -1)
        out = xh * cos + rot * sin
        return np.concatenate([out, np.asarray(x[..., d2:], np.float32)], -1)

    def test_sbhd_full_rotary(self):
        s, b, h, d = 6, 2, 3, 8
        x = jax.random.normal(jax.random.PRNGKey(0), (s, b, h, d))
        freqs = jax.random.normal(jax.random.PRNGKey(1), (s, d)) * 0.1
        y = fused_rope(x, freqs)
        np.testing.assert_allclose(np.asarray(y),
                                   self._ref_rope(x, np.asarray(freqs)),
                                   atol=1e-5)

    def test_partial_rotary_passthrough(self):
        s, b, h, d = 4, 1, 2, 8
        d2 = 4
        x = jax.random.normal(jax.random.PRNGKey(2), (s, b, h, d))
        freqs = jnp.ones((s, d2)) * 0.3
        y = fused_rope(x, freqs)
        np.testing.assert_array_equal(np.asarray(y[..., d2:]),
                                      np.asarray(x[..., d2:]))

    def test_backward_is_inverse_rotation(self):
        s, b, h, d = 4, 2, 2, 8
        x = jax.random.normal(jax.random.PRNGKey(3), (s, b, h, d))
        # real RoPE freqs: the two rotate-half halves share angles, making the
        # map orthogonal (so ||grad of sum(y^2)|| == 2||y||)
        half = jax.random.normal(jax.random.PRNGKey(4), (s, d // 2)) * 0.2
        freqs = jnp.concatenate([half, half], axis=-1)

        def loss(x):
            return jnp.sum(fused_rope(x, freqs) ** 2)

        g = jax.grad(loss)(x)
        # rotation is orthogonal: ||grad|| == ||2*rope(x)||
        np.testing.assert_allclose(float(jnp.linalg.norm(g)),
                                   float(2 * jnp.linalg.norm(
                                       fused_rope(x, freqs))), rtol=1e-5)

    def test_thd_packed_matches_per_sequence(self):
        d = 8
        lens = [3, 5, 2]
        cu = jnp.array([0, 3, 8, 10], jnp.int32)
        total = 10
        x = jax.random.normal(jax.random.PRNGKey(5), (total, 2, d))
        freqs = jax.random.normal(jax.random.PRNGKey(6), (8, d)) * 0.1
        y = fused_rope_thd(x, cu, freqs)
        # each sequence rotated from position 0
        off = 0
        for ln in lens:
            seq = x[off:off + ln][:, None, :, :]  # (s,1,h,d) sbhd
            ref = fused_rope(seq, freqs[:ln])
            np.testing.assert_allclose(np.asarray(y[off:off + ln]),
                                       np.asarray(ref[:, 0]), atol=1e-5)
            off += ln

    def test_position_offset_single_token_parity(self):
        """The serving contract: one decode token at absolute position t
        rotates exactly like token t of the full-sequence call —
        bit-identical (RoPE is elementwise per token row)."""
        s, b, h, d = 12, 2, 3, 8
        x = jax.random.normal(jax.random.PRNGKey(7), (s, b, h, d))
        freqs = jax.random.normal(jax.random.PRNGKey(8), (s, d)) * 0.1
        full = fused_rope(x, freqs)
        for t in (0, 5, s - 1):
            one = fused_rope(x[t:t + 1], freqs, position_offset=t)
            np.testing.assert_array_equal(np.asarray(one),
                                          np.asarray(full[t:t + 1]))
        # a window (decode chunk) too, and under jit with a traced offset
        # (tight-allclose there: XLA vectorizes cos/sin differently per
        # fused shape, so cross-shape bitwise claims stop at eager ops)
        win = fused_rope(x[4:9], freqs, position_offset=4)
        np.testing.assert_array_equal(np.asarray(win),
                                      np.asarray(full[4:9]))
        jwin = jax.jit(lambda xx, off: fused_rope(xx, freqs,
                                                  position_offset=off))
        np.testing.assert_allclose(np.asarray(jwin(x[4:9], jnp.int32(4))),
                                   np.asarray(full[4:9]), atol=1e-6,
                                   rtol=0)

    def test_position_offset_cached_variant_parity(self):
        s, b, h, d = 10, 1, 2, 8
        x = jax.random.normal(jax.random.PRNGKey(9), (s, b, h, d))
        f = jax.random.normal(jax.random.PRNGKey(10), (s, d)) * 0.2
        cos, sin = jnp.cos(f), jnp.sin(f)
        full = fused_rope_cached(x, cos[:, None, None, :],
                                 sin[:, None, None, :])
        for t in (0, 3, s - 1):
            one = fused_rope_cached(x[t:t + 1], cos[:, None, None, :],
                                    sin[:, None, None, :],
                                    position_offset=t)
            np.testing.assert_array_equal(np.asarray(one),
                                          np.asarray(full[t:t + 1]))


class TestXentropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_vs_torch(self, smoothing):
        logits = jax.random.normal(jax.random.PRNGKey(0), (16, 50))
        labels = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 50)
        loss = softmax_cross_entropy_loss(logits, labels, smoothing)
        tl = torch.tensor(np.asarray(logits), requires_grad=True)
        tt = torch.tensor(np.asarray(labels), dtype=torch.long)
        tloss = torch.nn.functional.cross_entropy(
            tl, tt, label_smoothing=smoothing, reduction="none")
        np.testing.assert_allclose(np.asarray(loss), tloss.detach().numpy(),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_grad_vs_torch(self, smoothing):
        logits = jax.random.normal(jax.random.PRNGKey(2), (8, 20))
        labels = jax.random.randint(jax.random.PRNGKey(3), (8,), 0, 20)
        g = jax.grad(lambda x: jnp.sum(
            softmax_cross_entropy_loss(x, labels, smoothing)))(logits)
        tl = torch.tensor(np.asarray(logits), requires_grad=True)
        tt = torch.tensor(np.asarray(labels), dtype=torch.long)
        torch.nn.functional.cross_entropy(
            tl, tt, label_smoothing=smoothing, reduction="sum").backward()
        np.testing.assert_allclose(np.asarray(g), tl.grad.numpy(), atol=1e-5)

    def test_padding_idx(self):
        logits = jax.random.normal(jax.random.PRNGKey(4), (6, 10))
        labels = jnp.array([1, 2, 0, 0, 3, 0])
        loss = softmax_cross_entropy_loss(logits, labels, 0.0, padding_idx=0)
        assert float(loss[2]) == 0.0 and float(loss[3]) == 0.0
        g = jax.grad(lambda x: jnp.sum(
            softmax_cross_entropy_loss(x, labels, 0.0, 0)))(logits)
        np.testing.assert_array_equal(np.asarray(g[2]), 0.0)

    def test_module_mean_reduction(self):
        crit = SoftmaxCrossEntropyLoss(smoothing=0.1, padding_idx=0)
        logits = jax.random.normal(jax.random.PRNGKey(5), (4, 7),
                                   jnp.bfloat16)
        labels = jnp.array([1, 0, 2, 3])
        loss = crit(logits, labels)
        assert loss.dtype == jnp.float32  # half_to_float


class TestFusedDense:
    def test_linear_bias_vs_torch(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 6, 32))
        w = jax.random.normal(jax.random.PRNGKey(1), (16, 32)) * 0.1
        b = jax.random.normal(jax.random.PRNGKey(2), (16,))
        y = linear_bias(x, w, b)
        ty = torch.nn.functional.linear(
            torch.tensor(np.asarray(x)), torch.tensor(np.asarray(w)),
            torch.tensor(np.asarray(b)))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5)

    def test_dense_gelu_dense_fwd_bwd_vs_torch(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (5, 16))
        w1 = jax.random.normal(jax.random.PRNGKey(4), (32, 16)) * 0.2
        b1 = jax.random.normal(jax.random.PRNGKey(5), (32,)) * 0.1
        w2 = jax.random.normal(jax.random.PRNGKey(6), (8, 32)) * 0.2
        b2 = jax.random.normal(jax.random.PRNGKey(7), (8,)) * 0.1

        y = dense_gelu_dense(x, w1, b1, w2, b2)
        grads = jax.grad(lambda *a: jnp.sum(dense_gelu_dense(*a) ** 2),
                         argnums=(0, 1, 2, 3, 4))(x, w1, b1, w2, b2)

        tx = torch.tensor(np.asarray(x), requires_grad=True)
        tw1 = torch.tensor(np.asarray(w1), requires_grad=True)
        tb1 = torch.tensor(np.asarray(b1), requires_grad=True)
        tw2 = torch.tensor(np.asarray(w2), requires_grad=True)
        tb2 = torch.tensor(np.asarray(b2), requires_grad=True)
        th = torch.nn.functional.linear(tx, tw1, tb1)
        ta = torch.nn.functional.gelu(th)
        ty = torch.nn.functional.linear(ta, tw2, tb2)
        np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(),
                                   atol=1e-5)
        (ty ** 2).sum().backward()
        for g, t in zip(grads, (tx, tw1, tb1, tw2, tb2)):
            np.testing.assert_allclose(np.asarray(g), t.grad.numpy(),
                                       atol=1e-4, rtol=1e-4)

    def test_modules_init_apply(self):
        m = FusedDenseGeluDense(16, 32, 8)
        x = jnp.ones((2, 16))
        v = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(v, x)
        assert y.shape == (2, 8)
        m2 = FusedDense(16, 4)
        v2 = m2.init(jax.random.PRNGKey(1), x)
        assert m2.apply(v2, x).shape == (2, 4)


class TestMLP:
    @pytest.mark.parametrize("activation", ["relu", "sigmoid", "none"])
    @pytest.mark.parametrize("use_bias", [True, False])
    def test_vs_torch_sequential(self, activation, use_bias):
        """Port of tests/L0/run_mlp/test_mlp.py: apex MLP vs nn.Sequential."""
        sizes = [13, 27, 17, 5]
        m = MLP(sizes, use_bias=use_bias, activation=activation)
        x = jax.random.normal(jax.random.PRNGKey(0), (7, 13))
        v = m.init(jax.random.PRNGKey(1), x)
        y = m.apply(v, x)

        layers = []
        for i in range(len(sizes) - 1):
            lin = torch.nn.Linear(sizes[i], sizes[i + 1], bias=use_bias)
            with torch.no_grad():
                lin.weight.copy_(torch.tensor(np.asarray(
                    v["params"][f"weight_{i}"])))
                if use_bias:
                    lin.bias.copy_(torch.tensor(np.asarray(
                        v["params"][f"bias_{i}"])))
            layers.append(lin)
            if i < len(sizes) - 2:
                if activation == "relu":
                    layers.append(torch.nn.ReLU())
                elif activation == "sigmoid":
                    layers.append(torch.nn.Sigmoid())
        ref = torch.nn.Sequential(*layers)(torch.tensor(np.asarray(x)))
        np.testing.assert_allclose(np.asarray(y), ref.detach().numpy(),
                                   atol=1e-5)

    def test_grads_flow(self):
        m = MLP([8, 16, 4])
        x = jax.random.normal(jax.random.PRNGKey(2), (3, 8))
        v = m.init(jax.random.PRNGKey(3), x)
        g = jax.grad(lambda vv: jnp.sum(m.apply(vv, x) ** 2))(v)
        for leaf in jax.tree_util.tree_leaves(g):
            assert bool(jnp.all(jnp.isfinite(leaf)))


class TestWgrad:
    def test_fp32_accumulation(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (6, 4, 16),
                              jnp.bfloat16)
        dy = jax.random.normal(jax.random.PRNGKey(1), (6, 4, 8),
                               jnp.bfloat16)
        main = jnp.ones((8, 16), jnp.float32)
        out = wgrad_gemm_accum_fp32(x, dy, main)
        ref = np.ones((8, 16)) + np.einsum(
            "bso,bsi->oi", np.asarray(dy, np.float32),
            np.asarray(x, np.float32))
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-2)


class TestPallasSoftmaxKernel:
    """The TPU-routed Pallas softmax kernel (ops/pallas/softmax_kernel.py),
    parity-tested in interpret mode against the jnp reference path that CPU
    callers use (the kernel is what runs on the chip)."""

    def _ref(self, x, mask, scale, causal):
        x32 = np.asarray(x, np.float32) * scale
        if mask is not None:
            x32 = np.where(np.broadcast_to(np.asarray(mask, bool), x32.shape),
                           -10000.0, x32)
        if causal:
            sq, sk = x32.shape[-2:]
            tri = np.triu(np.ones((sq, sk), bool), 1)
            x32 = np.where(tri, -10000.0, x32)
        m = x32.max(-1, keepdims=True)
        e = np.exp(x32 - m)
        y = e / e.sum(-1, keepdims=True)
        return np.where(m <= -10000.0, 0.0, y)

    @pytest.mark.parametrize("sk", [128, 300, 1024])
    def test_fwd_parity(self, sk):
        from apex_tpu.ops.pallas.softmax_kernel import softmax_fwd_pallas
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 12, sk))
        y = softmax_fwd_pallas(x, None, scale=0.7, causal=False,
                               interpret=True)
        np.testing.assert_allclose(np.asarray(y),
                                   self._ref(x, None, 0.7, False), atol=1e-6)

    def test_fwd_causal_and_ragged(self):
        from apex_tpu.ops.pallas.softmax_kernel import softmax_fwd_pallas
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 11, 11))
        y = softmax_fwd_pallas(x, None, scale=1.3, causal=True,
                               interpret=True)
        np.testing.assert_allclose(np.asarray(y),
                                   self._ref(x, None, 1.3, True), atol=1e-6)

    @pytest.mark.parametrize("sq", [512, 640, 300])
    def test_fwd_causal_chunked_fetch(self, sq):
        """The chunked-fetch causal path (column chunks above the diagonal
        never staged; stale-scratch region masked before the exp) must be
        bit-faithful to the row-complete reference at multi-row-block,
        multi-chunk shapes, including non-128-multiple lengths."""
        from apex_tpu.ops.pallas.softmax_kernel import softmax_fwd_pallas
        x = jax.random.normal(jax.random.PRNGKey(7), (2, sq, sq))
        y = softmax_fwd_pallas(x, None, scale=0.7, causal=True,
                               interpret=True)
        np.testing.assert_allclose(np.asarray(y),
                                   self._ref(x, None, 0.7, True), atol=1e-6)

    @pytest.mark.parametrize("bm,h", [(6, 1), (1, 1), (2, 3)])
    def test_fwd_mask_broadcast(self, bm, h):
        """(b, 1, sq, sk)-style mask sharing across h heads, flattened."""
        from apex_tpu.ops.pallas.softmax_kernel import softmax_fwd_pallas
        B = 6
        x = jax.random.normal(jax.random.PRNGKey(2), (B, 8, 160))
        mask = jax.random.bernoulli(jax.random.PRNGKey(3), 0.3,
                                    (bm, 8, 160)).astype(jnp.uint8)
        y = softmax_fwd_pallas(x, mask, scale=1.0, causal=False, h=h,
                               interpret=True)
        mask_full = jnp.repeat(mask, B // bm, axis=0)
        np.testing.assert_allclose(np.asarray(y),
                                   self._ref(x, mask_full, 1.0, False),
                                   atol=1e-6)

    def test_fully_masked_rows_zero(self):
        from apex_tpu.ops.pallas.softmax_kernel import softmax_fwd_pallas
        x = jax.random.normal(jax.random.PRNGKey(4), (1, 4, 128))
        mask = jnp.ones((1, 4, 128), jnp.uint8)
        y = softmax_fwd_pallas(x, mask, scale=1.0, causal=False,
                               interpret=True)
        np.testing.assert_array_equal(np.asarray(y), 0.0)

    def test_bwd_parity(self):
        from apex_tpu.ops.pallas.softmax_kernel import (softmax_bwd_pallas,
                                                        softmax_fwd_pallas)
        x = jax.random.normal(jax.random.PRNGKey(5), (2, 8, 200))
        dy = jax.random.normal(jax.random.PRNGKey(6), (2, 8, 200))
        scale = 1.9

        def ref_fn(x):
            return jax.nn.softmax(x * scale, axis=-1)

        y, vjp = jax.vjp(ref_fn, x)
        (dx_ref,) = vjp(dy)
        yk = softmax_fwd_pallas(x, None, scale=scale, causal=False,
                                interpret=True)
        dx = softmax_bwd_pallas(yk, dy, scale=scale, interpret=True)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                                   atol=1e-5)

    def test_route_rules(self, monkeypatch):
        """Shape acceptance/rejection logic, with the CPU interpret
        short-circuit disabled so the rules themselves are exercised."""
        import apex_tpu.transformer.softmax as sm
        monkeypatch.setattr(sm, "interpret_default", lambda: False)
        x = jnp.zeros((2, 4, 8, 16))
        # accepts: equal dims / megatron (b,1,sq,sk) / all-ones, and
        # computes the head-broadcast factor for the flattened batch
        assert sm._pallas_route(x, None, 1.0, True) == (True, 1)
        assert sm._pallas_route(x, jnp.zeros((2, 4, 8, 16)), 1.0,
                                False) == (True, 1)
        assert sm._pallas_route(x, jnp.zeros((2, 1, 8, 16)), 1.0,
                                False) == (True, 4)
        assert sm._pallas_route(x, jnp.zeros((1, 1, 8, 16)), 1.0,
                                False) == (True, 8)
        assert sm._pallas_route(x, jnp.zeros((2, 1, 1, 16)), 1.0,
                                False) == (True, 4)
        # rejects: sq mismatch, non-broadcast lead, sk mismatch, huge rows
        assert not sm._pallas_route(x, jnp.zeros((2, 4, 3, 16)), 1.0,
                                    False)[0]
        assert not sm._pallas_route(x, jnp.zeros((2, 3, 8, 16)), 1.0,
                                    False)[0]
        assert not sm._pallas_route(x, jnp.zeros((2, 4, 8, 32)), 1.0,
                                    False)[0]
        huge = jax.ShapeDtypeStruct((1, 1, 8, 32768), jnp.float32)
        assert not sm._pallas_route(huge, None, 1.0, False)[0]
        # and the short-circuit itself
        monkeypatch.setattr(sm, "interpret_default", lambda: True)
        assert not sm._pallas_route(x, None, 1.0, False)[0]

    def test_routed_surface_fwd_bwd_parity(self, monkeypatch):
        """Execute the actual TPU routing glue (_pallas_softmax custom_vjp,
        reshape + h wiring behind the public scaled_* functions) by forcing
        the route open while the kernel itself runs in interpret mode —
        otherwise this plumbing is only exercised on the real chip."""
        import apex_tpu.transformer.softmax as sm
        b, h, sq, sk = 2, 3, 8, 160
        x = jax.random.normal(jax.random.PRNGKey(11), (b, h, sq, sk))
        mask = jax.random.bernoulli(jax.random.PRNGKey(12), 0.3,
                                    (b, 1, sq, sk)).astype(jnp.uint8)
        dy = jax.random.normal(jax.random.PRNGKey(13), (b, h, sq, sk))

        def run_all():
            outs = {}
            for name, fn in [
                ("masked", lambda x: sm.scaled_masked_softmax(x, mask, 1.4)),
                ("causal", lambda x: sm.scaled_upper_triang_masked_softmax(
                    x[..., :sq], 0.9)),
                ("plain", lambda x: sm.scaled_softmax(x, 2.0)),
            ]:
                y, vjp = jax.vjp(fn, x)
                (dx,) = vjp(dy[..., :y.shape[-1]])
                outs[name] = (np.asarray(y), np.asarray(dx))
            return outs

        jnp_path = run_all()  # interpret_default() True → jnp implementation
        monkeypatch.setattr(sm, "interpret_default", lambda: False)
        routed = run_all()    # route open; kernel falls to interpret mode
        for name in jnp_path:
            np.testing.assert_allclose(routed[name][0], jnp_path[name][0],
                                       atol=1e-6, err_msg=f"{name} fwd")
            np.testing.assert_allclose(routed[name][1], jnp_path[name][1],
                                       atol=1e-5, err_msg=f"{name} bwd")


class TestLinearCrossEntropy:
    """Chunked-vocab fused linear+CE head (beyond-reference): must match
    the dense logits path (contrib.xentropy on hidden @ weight) in loss
    AND grads while never materializing the logits."""

    def _dense_ref(self, hidden, weight, labels, smoothing=0.0,
                   padding_idx=None, logit_scale=1.0):
        from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

        logits = (hidden @ weight).astype(jnp.float32) * logit_scale
        return softmax_cross_entropy_loss(logits, labels, smoothing,
                                          padding_idx)

    @pytest.mark.parametrize("v,chunk", [(1000, 256), (777, 256),
                                         (512, 512), (130, 64),
                                         (100, 256), (50, 8192)])
    def test_loss_matches_dense(self, v, chunk):
        from apex_tpu.transformer import linear_cross_entropy

        n, h = 64, 96
        hd = jax.random.normal(jax.random.PRNGKey(0), (n, h)) * 0.5
        w = jax.random.normal(jax.random.PRNGKey(1), (h, v)) * 0.1
        lb = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
        got = linear_cross_entropy(hd, w, lb, 0.0, None, chunk)
        want = self._dense_ref(hd, w, lb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_smoothing_and_padding(self):
        from apex_tpu.transformer import linear_cross_entropy

        n, h, v = 48, 64, 500
        hd = jax.random.normal(jax.random.PRNGKey(0), (n, h)) * 0.5
        w = jax.random.normal(jax.random.PRNGKey(1), (h, v)) * 0.1
        lb = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
        lb = lb.at[::7].set(-100)
        got = linear_cross_entropy(hd, w, lb, 0.1, -100, 128)
        want = self._dense_ref(hd, w, lb, smoothing=0.1, padding_idx=-100)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        assert np.all(np.asarray(got)[::7] == 0.0)

    def test_grads_match_dense(self):
        from apex_tpu.transformer import linear_cross_entropy

        n, h, v, chunk = 32, 64, 300, 128
        hd = jax.random.normal(jax.random.PRNGKey(0), (n, h)) * 0.5
        w = jax.random.normal(jax.random.PRNGKey(1), (h, v)) * 0.1
        lb = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
        lb = lb.at[3].set(-100)

        def fused(hd, w):
            return jnp.mean(linear_cross_entropy(hd, w, lb, 0.05, -100,
                                                 chunk))

        def dense(hd, w):
            return jnp.mean(self._dense_ref(hd, w, lb, smoothing=0.05,
                                            padding_idx=-100))

        gf = jax.grad(fused, argnums=(0, 1))(hd, w)
        gd = jax.grad(dense, argnums=(0, 1))(hd, w)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_bf16_inputs_finite_and_close(self):
        from apex_tpu.transformer import linear_cross_entropy

        n, h, v = 64, 128, 1000
        hd = (jax.random.normal(jax.random.PRNGKey(0), (n, h)) * 0.5
              ).astype(jnp.bfloat16)
        w = (jax.random.normal(jax.random.PRNGKey(1), (h, v)) * 0.1
             ).astype(jnp.bfloat16)
        lb = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
        got = linear_cross_entropy(hd, w, lb, 0.0, None, 256)
        want = self._dense_ref(hd, w, lb)
        assert got.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)
        g = jax.grad(lambda hd: jnp.mean(
            linear_cross_entropy(hd, w, lb, 0.0, None, 256)))(hd)
        assert g.dtype == jnp.bfloat16
        assert bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))

    def test_logit_scale(self):
        from apex_tpu.transformer import linear_cross_entropy

        n, h, v = 16, 32, 100
        hd = jax.random.normal(jax.random.PRNGKey(0), (n, h))
        w = jax.random.normal(jax.random.PRNGKey(1), (h, v)) * 0.1
        lb = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)
        got = linear_cross_entropy(hd, w, lb, 0.0, None, 64, 0.125)
        want = self._dense_ref(hd, w, lb, logit_scale=0.125)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
