"""Deprecated contrib optimizer surface (legacy ``fused_adam_cuda`` flow) —
parity with the reference semantics of
apex/contrib/optimizers/fused_{adam,sgd}.py: explicit ``grads=``,
``output_params=`` low-precision copy-out, ``scale`` divisor,
``eps_inside_sqrt``, momentum first-step buffer = grad."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.optimizers import FusedAdam, FusedLAMB, FusedSGD


def _np(x):
    return np.asarray(x, np.float64)


class TestDeprecatedFusedAdam:
    def _ref_step(self, p, g, m, v, *, lr, b1, b2, eps, wd, step, scale,
                  eps_inside):
        """Mirror of adam_cuda_kernel (fused_adam_cuda_kernel.cu:49-60 with
        host step_size :182-189): raw v in the denom, bias correction folded
        into step_size, decay joins the update term after the moments."""
        g = g / scale
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        bc1 = 1 - b1 ** step
        bc2 = 1 - b2 ** step
        step_size = lr * np.sqrt(bc2) / bc1
        if eps_inside:
            denom = np.sqrt(v + eps)
        else:
            denom = np.sqrt(v) + eps
        return p - step_size * (m / denom + wd * p), m, v

    @pytest.mark.parametrize("eps_inside,scale", [(False, 1.0), (True, 4.0)])
    def test_step_parity(self, eps_inside, scale):
        key = jax.random.PRNGKey(0)
        p = [jax.random.normal(key, (31,), jnp.float32),
             jax.random.normal(jax.random.PRNGKey(1), (7, 5), jnp.float32)]
        g = [jax.random.normal(jax.random.PRNGKey(2), (31,), jnp.float32),
             jax.random.normal(jax.random.PRNGKey(3), (7, 5), jnp.float32)]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            opt = FusedAdam(p, lr=1e-2, weight_decay=0.01,
                            eps_inside_sqrt=eps_inside)
        ref_p = [_np(x) for x in p]
        ref_m = [np.zeros_like(x) for x in ref_p]
        ref_v = [np.zeros_like(x) for x in ref_p]
        params = p
        for step in range(1, 4):
            scaled = [x * scale for x in g]
            params = opt.step(grads=scaled, scale=scale)
            for i in range(2):
                ref_p[i], ref_m[i], ref_v[i] = self._ref_step(
                    ref_p[i], _np(g[i]), ref_m[i], ref_v[i], lr=1e-2,
                    b1=0.9, b2=0.999, eps=1e-8, wd=0.01, step=step,
                    scale=1.0, eps_inside=eps_inside)
        for got, want in zip(params, ref_p):
            np.testing.assert_allclose(_np(got), want, rtol=2e-5, atol=2e-6)

    def test_output_params_lowprec_copy(self):
        p = [jnp.ones((8,), jnp.float32)]
        g = [jnp.full((8,), 0.5, jnp.bfloat16)]
        out = [jnp.zeros((8,), jnp.bfloat16)]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            opt = FusedAdam(p, lr=1e-2)
        params, out_lp = opt.step(grads=g, output_params=out)
        assert out_lp[0].dtype == jnp.bfloat16
        np.testing.assert_allclose(_np(out_lp[0]),
                                   _np(params[0].astype(jnp.bfloat16)))


class TestDeprecatedFusedSGD:
    def test_momentum_first_step_is_grad(self):
        p = [jnp.ones((16,), jnp.float32)]
        g = [jnp.full((16,), 2.0, jnp.float32)]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            opt = FusedSGD(p, lr=0.1, momentum=0.9)
        params = opt.step(grads=g)
        # first step: buf = g (not (1-damp)*g), p -= lr*g
        np.testing.assert_allclose(_np(params[0]), 1.0 - 0.1 * 2.0,
                                   rtol=1e-6)
        params = opt.step(grads=g)
        # second: buf = 0.9*2 + 2 = 3.8
        np.testing.assert_allclose(_np(params[0]),
                                   1.0 - 0.1 * 2.0 - 0.1 * 3.8, rtol=1e-6)

    def test_scale_and_wd_after_momentum(self):
        p = [jnp.full((4,), 2.0, jnp.float32)]
        g = [jnp.full((4,), 8.0, jnp.float32)]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            opt = FusedSGD(p, lr=0.5, momentum=0.0, weight_decay=0.1,
                           wd_after_momentum=True)
        params = opt.step(grads=g, scale=4.0)
        # g/scale = 2; wd after: g += 0.1*2 = 2.2; p = 2 - 0.5*2.2
        np.testing.assert_allclose(_np(params[0]), 2.0 - 0.5 * 2.2,
                                   rtol=1e-6)


class TestDeprecatedFusedLAMB:
    """Parity of the legacy contrib FusedLAMB (explicit-grads flow) vs the
    modern apex_tpu.optimizers.FusedLAMB (tree path) — same math chain:
    global-norm clip, Adam direction, per-tensor trust ratio
    (reference apex/contrib/optimizers/fused_lamb.py:112-230)."""

    @pytest.mark.parametrize("adam_w_mode", [True, False])
    def test_parity_vs_modern(self, adam_w_mode):
        from apex_tpu.optimizers import FusedLAMB as ModernLAMB
        p = [jax.random.normal(jax.random.PRNGKey(0), (33,), jnp.float32),
             jax.random.normal(jax.random.PRNGKey(1), (5, 9), jnp.float32)]
        gs = [[jax.random.normal(jax.random.PRNGKey(10 * s + i), leaf.shape,
                                 jnp.float32)
               for i, leaf in enumerate(p)] for s in range(3)]
        kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0,
                  adam_w_mode=adam_w_mode)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            legacy = FusedLAMB(p, **kw)
        modern = ModernLAMB(p, use_flat=False, **kw)
        for g in gs:
            got = legacy.step(grads=g)
            want = modern.step(g)
        for a, b in zip(got, want):
            np.testing.assert_allclose(_np(a), _np(b), rtol=2e-5, atol=2e-6)

    def test_scale_divisor_and_output_params(self):
        p = [jnp.ones((16,), jnp.float32)]
        g = [jnp.full((16,), 0.5, jnp.float32)]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ref = FusedLAMB([p[0]], lr=0.1)
            want = ref.step(grads=g)
            opt = FusedLAMB(p, lr=0.1)
            params, out = opt.step(grads=[g[0] * 8.0], scale=8.0,
                                   output_params=[jnp.zeros((16,),
                                                            jnp.bfloat16)])
        np.testing.assert_allclose(_np(params[0]), _np(want[0]), rtol=1e-6)
        assert out[0].dtype == jnp.bfloat16

    def test_found_inf_skips_step(self):
        p = [jnp.ones((8,), jnp.float32)]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            opt = FusedLAMB(p, lr=0.1)
        got = opt.step(grads=[jnp.ones((8,))], found_inf=jnp.bool_(True))
        np.testing.assert_array_equal(_np(got[0]), 1.0)
        assert opt.state_dict()["step"] == 0
        opt.step(grads=[jnp.ones((8,))])
        assert opt.state_dict()["step"] == 1


class TestLoggingUtils:
    def test_average_meter_and_metric_logger(self, tmp_path):
        from apex_tpu.utils import AverageMeter, MetricLogger
        m = AverageMeter("loss", ":.2f")
        m.update(2.0)
        m.update(4.0)
        assert m.avg == 3.0
        path = tmp_path / "metrics.jsonl"
        ml = MetricLogger(jsonl_path=str(path))
        ml.log(1, loss=jnp.float32(1.5), lr=0.1)
        ml.log(2, loss=jnp.float32(0.5), lr=0.1)
        s = ml.summary()
        assert abs(s["loss"] - 1.0) < 1e-6
        lines = path.read_text().strip().splitlines()
        assert len(lines) == 2

    def test_one_time_warning_once(self, capsys):
        from apex_tpu.utils.logging import one_time_warning
        one_time_warning("only-once-xyz")
        one_time_warning("only-once-xyz")
        assert capsys.readouterr().err.count("only-once-xyz") == 1


class TestModernCallingConvention:
    def test_tuple_params_container(self):
        """Params pytree that IS a tuple must not be mangled by the
        result unzip (regression: is_leaf=tuple matched the container)."""
        p = (jnp.ones((4,)), jnp.ones((2, 2)))
        g = (jnp.full((4,), 0.5), jnp.full((2, 2), 0.5))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            opt = FusedAdam(p, lr=0.1)
        out = opt.step(grads=g)
        assert isinstance(out, tuple) and len(out) == 2
        assert out[0].shape == (4,) and out[1].shape == (2, 2)
        out = opt.step(grads=g)  # second step exercises state structure
        assert out[1].shape == (2, 2)

    def test_fp16_optimizer_wraps_legacy_adam(self):
        """The reference pairing: FP16_Optimizer over the deprecated
        contrib FusedAdam (modern step(grads, lr=, inv_scale=, found_inf=)
        convention accepted)."""
        from apex_tpu.contrib.optimizers import FP16_Optimizer
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            opt = FP16_Optimizer(FusedAdam([jnp.ones((8,))], lr=0.1),
                                 dynamic_loss_scale=True,
                                 dynamic_loss_args={"init_scale": 64.0})
        p = opt.step([jnp.full((8,), 64.0)])  # true grad 1.0
        assert not np.allclose(np.asarray(p[0]), 1.0)
        # overflow grads: step skipped, scale halved
        p2 = opt.step([jnp.full((8,), np.inf)])
        np.testing.assert_array_equal(np.asarray(p2[0]), np.asarray(p[0]))
        assert opt.loss_scale == 32.0

    def test_legacy_sgd_found_inf_skips(self):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            opt = FusedSGD([jnp.ones((4,))], lr=0.1, momentum=0.9)
        p = opt.step(grads=[jnp.ones((4,))], found_inf=jnp.bool_(True))
        np.testing.assert_array_equal(np.asarray(p[0]), 1.0)
        p = opt.step(grads=[jnp.ones((4,))], found_inf=jnp.bool_(False))
        assert float(p[0][0]) < 1.0

    def test_traced_found_inf_step_count_consistent(self):
        """Under jit (traced found_inf) the Adam step counter and the SGD
        first-step flag go data-dependent instead of silently advancing on
        skipped steps: skip-then-apply must equal a single applied step."""
        import jax

        g = jnp.full((4,), 0.5)

        def adam_two_steps(skip_first):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                opt = FusedAdam([jnp.ones((4,))], lr=0.1)
            opt.step(grads=[g], found_inf=skip_first)
            (p,) = opt.step(grads=[g], found_inf=jnp.bool_(False))
            return p

        skip_then_apply = jax.jit(adam_two_steps)(jnp.bool_(True))

        def adam_one_step():
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                opt = FusedAdam([jnp.ones((4,))], lr=0.1)
            (p,) = opt.step(grads=[g], found_inf=False)
            return p

        np.testing.assert_allclose(np.asarray(skip_then_apply),
                                   np.asarray(adam_one_step()), atol=5e-6)

        def sgd_two_steps(skip_first):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                opt = FusedSGD([jnp.ones((4,))], lr=0.1, momentum=0.9)
            opt.step(grads=[g], found_inf=skip_first)
            (p,) = opt.step(grads=[g], found_inf=jnp.bool_(False))
            return p

        sgd_skip = jax.jit(sgd_two_steps)(jnp.bool_(True))

        def sgd_one_step():
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                opt = FusedSGD([jnp.ones((4,))], lr=0.1, momentum=0.9)
            (p,) = opt.step(grads=[g], found_inf=False)
            return p

        # first applied step must use the momentum-init (buf = g) path
        np.testing.assert_allclose(np.asarray(sgd_skip),
                                   np.asarray(sgd_one_step()), atol=5e-6)


class TestReversibleAdamUndo:
    """reversible_adam + maybe_adam_undo roundtrip
    (fused_adam_cuda_kernel.cu:421-560)."""

    def _state(self, seed=0, n=513):
        ks = jax.random.split(jax.random.PRNGKey(seed), 4)
        p = jax.random.normal(ks[0], (n,), jnp.float32)
        g = jax.random.normal(ks[1], (n,), jnp.float32)
        m = jax.random.normal(ks[2], (n,)) * 0.1
        v = jnp.abs(jax.random.normal(ks[3], (n,))) * 0.01
        return p, g, m, v

    def test_roundtrip_exact_fp32(self):
        from apex_tpu.contrib.optimizers.fused_adam import (maybe_adam_undo,
                                                            reversible_adam)
        p, g, m, v = self._state()
        kw = dict(step_size=0.01, betas=(0.9, 0.999), eps=1e-8,
                  weight_decay=0.01, grad_scale=2.0)
        p1, m1, v1, ovf = reversible_adam([p], [g], [m], [v], **kw)
        assert not bool(ovf)
        p0, m0, v0 = maybe_adam_undo(p1, [g], m1, v1, **kw)
        np.testing.assert_allclose(_np(p0[0]), _np(p), rtol=2e-6, atol=2e-6)
        np.testing.assert_allclose(_np(m0[0]), _np(m), rtol=2e-6, atol=2e-7)
        np.testing.assert_allclose(_np(v0[0]), _np(v), rtol=2e-5, atol=1e-8)

    def test_per_element_finite_skip_and_overflow(self):
        from apex_tpu.contrib.optimizers.fused_adam import reversible_adam
        p, g, m, v = self._state()
        g = g.at[7].set(jnp.inf).at[100].set(jnp.nan)
        p1, m1, v1, ovf = reversible_adam([p], [g], [m], [v], step_size=0.01)
        assert bool(ovf)
        # non-finite lanes untouched, others updated
        np.testing.assert_array_equal(_np(p1[0][7]), _np(p[7]))
        np.testing.assert_array_equal(_np(m1[0][100]), _np(m[100]))
        assert not np.allclose(_np(p1[0][0]), _np(p[0]))

    def test_output_dtype_copy_out(self):
        from apex_tpu.contrib.optimizers.fused_adam import reversible_adam
        p, g, m, v = self._state()
        p1, m1, v1, ovf, copy = reversible_adam(
            [p], [g], [m], [v], step_size=0.01, output_dtype=jnp.bfloat16)
        assert copy[0].dtype == jnp.bfloat16
        np.testing.assert_allclose(_np(copy[0]), _np(p1[0]), rtol=1e-2)

    def test_undo_gated_by_flag(self):
        from apex_tpu.contrib.optimizers.fused_adam import maybe_adam_undo
        p, g, m, v = self._state()
        p0, m0, v0 = maybe_adam_undo([p], [g], [m], [v], step_size=0.01,
                                     overflow_flag=False)
        np.testing.assert_array_equal(_np(p0[0]), _np(p))
        np.testing.assert_array_equal(_np(v0[0]), _np(v))

    def test_class_undo_step_roundtrip(self):
        p, g = self._state()[:2]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            opt = FusedAdam([p], lr=0.01, weight_decay=0.01)
        for s in range(3):
            opt.step(grads=[g * (1 + s)], scale=2.0)
        snap = _np(opt.parameters[0])
        opt.step(grads=[g * 4], scale=2.0)
        opt.undo_step([g * 4], scale=2.0)
        assert opt._step == 3
        np.testing.assert_allclose(_np(opt.parameters[0]), snap,
                                   rtol=2e-6, atol=2e-6)
        # counter realigned: stepping again reproduces the un-done step
        redo = opt.step(grads=[g * 4], scale=2.0)
        assert opt._step == 4

    def test_undo_first_step_v_clamped(self):
        from apex_tpu.contrib.optimizers.fused_adam import (maybe_adam_undo,
                                                            reversible_adam)
        p, g, _, _ = self._state()
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)
        p1, m1, v1, _ = reversible_adam([p], [g], [m], [v], step_size=0.01)
        p0, m0, v0 = maybe_adam_undo(p1, [g], m1, v1, step_size=0.01)
        assert bool(jnp.all(v0[0] >= 0.0))
        np.testing.assert_allclose(_np(p0[0]), _np(p), rtol=2e-5, atol=2e-5)

    def test_undo_with_grad_norm_clipping(self):
        p, g = self._state()[:2]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            opt = FusedAdam([p], lr=0.01, max_grad_norm=0.5)
        gnorm = jnp.sqrt(jnp.sum(g ** 2))  # >> max_grad_norm: clip active
        opt.step(grads=[g], grad_norms=gnorm)
        snap = _np(opt.parameters[0])
        opt.step(grads=[g * 2], grad_norms=gnorm * 2)
        opt.undo_step([g * 2], grad_norms=gnorm * 2)
        np.testing.assert_allclose(_np(opt.parameters[0]), snap,
                                   rtol=2e-6, atol=2e-6)


class TestCheckFiniteMaybeCast:
    """strided_check_finite + maybe_cast (fused_adam_cuda_kernel.cu:331-418)."""

    def test_strided_check_finite(self):
        from apex_tpu.contrib.optimizers.fused_adam import \
            strided_check_finite
        p = jnp.ones((64,))
        assert not bool(strided_check_finite([p]))
        bad = p.at[7].set(jnp.nan)
        assert bool(strided_check_finite([bad]))
        # stride 4 skips index 7 -> clean sample
        assert not bool(strided_check_finite([bad], stride=4))
        # index 8 lands on the stride-4 grid
        assert bool(strided_check_finite([p.at[8].set(jnp.inf)], stride=4))
        # OR semantics without clear
        assert bool(strided_check_finite([p], clear_overflow_first=False,
                                         overflow_flag=True))

    def test_maybe_cast(self):
        from apex_tpu.contrib.optimizers.fused_adam import maybe_cast
        pin = [jnp.arange(8, dtype=jnp.float32) * 0.1]
        pout = [jnp.zeros(8, jnp.bfloat16)]
        got = maybe_cast(pin, pout, overflow_flag=False)
        assert got[0].dtype == jnp.bfloat16
        np.testing.assert_allclose(_np(got[0]), _np(pin[0]), rtol=1e-2)
        kept = maybe_cast(pin, pout, overflow_flag=True)
        np.testing.assert_array_equal(_np(kept[0]), _np(pout[0]))
