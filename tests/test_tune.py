"""Autotuner tests (ISSUE 3): cache round-trip + corruption fallback,
deterministic keys, heuristic fallback, interpret-mode isolation,
empty-cache bit-for-bit tile parity, the apex-tpu-tune CPU smoke, and the
BENCH_BASELINE.json regression gate.

All CPU-only and fast — tier-1; select alone with ``-m tune``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import tune
from apex_tpu.tune.api import pow2_bucket, tuned_params

pytestmark = pytest.mark.tune

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tmp_cache(tmp_path, monkeypatch):
    """Point the process-wide tune cache at a fresh tmp file."""
    path = str(tmp_path / "tune_cache.json")
    monkeypatch.setenv("APEX_TPU_TUNE_CACHE", path)
    tune.invalidate()
    yield path
    tune.invalidate()


# ------------------------------------------------------------------ cache


class TestCache:
    def test_round_trip(self, tmp_cache):
        c = tune.TuneCache(tmp_cache)
        key = tune.cache_key("layer_norm", (("rows", 8192), ("hidden", 4096)),
                             jnp.bfloat16, "v5e")
        c.put(key, {"block_rows": 64}, meta={"ms": 0.1})
        c.save()
        reloaded = tune.TuneCache(tmp_cache)
        assert reloaded.get(key) == {"params": {"block_rows": 64},
                                     "meta": {"ms": 0.1}}
        assert len(reloaded) == 1

    def test_deterministic_keys_across_processes(self, tmp_cache):
        args = ("flash_attention", (("sq", 2048), ("sk", 2048), ("d", 64),
                                    ("causal", True)), "bfloat16", "v5e")
        key = tune.cache_key(*args)
        # key ordering is canonical regardless of pair order
        shuffled = tuple(reversed(args[1]))
        assert tune.cache_key(args[0], shuffled, args[2], args[3]) == key
        # and identical in a fresh interpreter (no per-process state).
        # cache.py is loaded standalone — its module level is stdlib-only
        # by design, so the subprocess skips the jax import entirely
        cache_py = os.path.join(REPO, "apex_tpu", "tune", "cache.py")
        out = subprocess.run(
            [sys.executable, "-c",
             "import importlib.util; "
             f"spec = importlib.util.spec_from_file_location('tc', {cache_py!r}); "
             "m = importlib.util.module_from_spec(spec); "
             "spec.loader.exec_module(m); "
             "print(m.cache_key('flash_attention', (('sq', 2048), "
             "('sk', 2048), ('d', 64), ('causal', True)), 'bfloat16', "
             "'v5e'))"],
            capture_output=True, text=True, check=True)
        assert out.stdout.strip() == key

    def test_dtype_canonicalization(self):
        a = tune.cache_key("softmax", (("sk", 128),), jnp.bfloat16, "cpu")
        b = tune.cache_key("softmax", (("sk", 128),), "bfloat16", "cpu")
        c = tune.cache_key("softmax", (("sk", 128),),
                           jnp.dtype(jnp.bfloat16), "cpu")
        assert a == b == c

    def test_float_key_material_rejected(self):
        with pytest.raises(TypeError):
            tune.cache_key("softmax", (("scale", 0.125),), None, "cpu")

    def test_corrupt_file_falls_back_empty(self, tmp_cache, capsys):
        with open(tmp_cache, "w") as f:
            f.write('{"entries": [truncated...')
        c = tune.TuneCache(tmp_cache)
        assert len(c) == 0
        rec = json.loads(capsys.readouterr().err.strip().splitlines()[-1])
        assert rec["event"] == "tune_cache_corrupt"
        # and lookups with the corrupt file on disk use the heuristics
        got = tuned_params("layer_norm", (("rows", 64),),
                           {"block_rows": 32}, interpret=False)
        assert got == {"block_rows": 32}

    def test_wrong_schema_falls_back_empty(self, tmp_cache):
        with open(tmp_cache, "w") as f:
            json.dump({"schema": 999, "entries": {"k": {"params": {}}}}, f)
        assert len(tune.TuneCache(tmp_cache)) == 0


# ----------------------------------------------------------- tuned_params


class TestTunedParams:
    def test_miss_returns_defaults_unchanged(self, tmp_cache):
        defaults = {"block_rows": 256}
        got = tuned_params("layer_norm", (("rows", 8192), ("hidden", 4096)),
                           defaults, dtype=jnp.bfloat16, interpret=False)
        assert got == defaults and got is not defaults

    def test_hit_merges_known_keys_only(self, tmp_cache):
        shape_key = (("rows", 8192), ("hidden", 4096))
        key = tune.cache_key("layer_norm", shape_key, jnp.bfloat16,
                             tune.device_key())
        c = tune.default_cache()
        c.put(key, {"block_rows": 64, "evil_kwarg": 1})
        c.save()
        got = tuned_params("layer_norm", shape_key, {"block_rows": 256},
                           dtype=jnp.bfloat16, interpret=False)
        assert got == {"block_rows": 64}

    def test_interpret_never_consults_cache(self, tmp_cache, monkeypatch):
        # a lookup in interpret mode must not even touch the cache object
        # (patch the name api.py actually calls, not the defining module)
        import apex_tpu.tune.api as tune_api

        def boom():
            raise AssertionError("interpret-mode lookup touched the cache")

        monkeypatch.setattr(tune_api, "default_cache", boom)
        got = tuned_params("layer_norm", (("rows", 64), ("hidden", 128)),
                           {"block_rows": 8}, interpret=True)
        assert got == {"block_rows": 8}
        # ...and the interpret kernels go through that same short circuit
        from apex_tpu.ops.pallas.layer_norm_kernel import ln_fwd_pallas

        x = jax.random.normal(jax.random.PRNGKey(0), (16, 128))
        y, _, _ = ln_fwd_pallas(x, None, None, eps=1e-5, rms=False,
                                interpret=True)
        assert y.shape == (16, 128)

    def test_force_compiled_aot_skips_cache(self, tmp_cache, monkeypatch):
        # deviceless AOT (APEX_TPU_FORCE_COMPILED=1) must not consult the
        # cache: device_key() would name the host, not the compile target,
        # and committed AOT artifacts must not depend on stray cache files
        shape_key = (("rows", 64), ("hidden", 128))
        key = tune.cache_key("layer_norm", shape_key, jnp.float32,
                             tune.device_key())
        c = tune.default_cache()
        c.put(key, {"block_rows": 16})
        c.save()
        monkeypatch.setenv("APEX_TPU_FORCE_COMPILED", "1")
        got = tuned_params("layer_norm", shape_key, {"block_rows": 64},
                           dtype=jnp.float32, interpret=False)
        assert got == {"block_rows": 64}

    def test_validate_rejects_bad_entry(self, tmp_cache):
        # flat optimizer entries are keyed dtype-agnostic (dtype=None)
        shape_key = (("rows", 128),)
        key = tune.cache_key("fused_adam", shape_key, None,
                             tune.device_key())
        c = tune.default_cache()
        c.put(key, {"block_rows": 100})  # not sublane-aligned
        c.save()
        from apex_tpu.ops.pallas.fused_adam_kernel import _flat_block_rows

        assert _flat_block_rows("fused_adam", 128, jnp.float32, False,
                                None) == 128  # heuristic min(512, rows)

    def test_flat_entries_shared_across_dtypes(self, tmp_cache):
        # warm at one dtype; the master-weight (fp32) and bf16 paths must
        # both pick the entry up — flat lookups are keyed dtype=None
        key = tune.cache_key("fused_adam", (("rows", 2048),), None,
                             tune.device_key())
        c = tune.default_cache()
        c.put(key, {"block_rows": 256})
        c.save()
        from apex_tpu.ops.pallas.fused_adam_kernel import _flat_block_rows

        for dt in (jnp.bfloat16, jnp.float32):
            assert _flat_block_rows("fused_adam", 2048, dt, False,
                                    None) == 256

    def test_selection_publishes_kernel_autotune_event(self, tmp_cache):
        from apex_tpu.utils.logging import subscribe_events

        shape_key = (("rows", 4096), ("hidden", 512))
        key = tune.cache_key("layer_norm", shape_key, jnp.float32,
                             tune.device_key())
        c = tune.default_cache()
        c.put(key, {"block_rows": 32})
        c.save()
        events = []
        unsub = subscribe_events(events.append)
        try:
            got = tuned_params("layer_norm", shape_key, {"block_rows": 256},
                              dtype=jnp.float32, interpret=False)
        finally:
            unsub()
        assert got == {"block_rows": 32}
        auto = [e for e in events if e["event"] == "kernel_autotune"]
        assert auto and auto[0]["source"] == "cache"
        assert auto[0]["params"] == {"block_rows": 32}
        assert auto[0]["key"] == key


# --------------------------------------- empty cache == heuristics, exact


class TestEmptyCacheBitForBit:
    """With no cache entry, every kernel must reproduce the pre-autotuner
    tile choices exactly (the shared tiling helpers ARE the old inline
    heuristics, and the compiled-path lookup falls through to them)."""

    def test_layer_norm(self, tmp_cache):
        from apex_tpu.ops.pallas.layer_norm_kernel import (_block_rows,
                                                           _pick_block_rows)
        from apex_tpu.ops.pallas.tiling import norm_block_rows

        for rows, hidden in [(64, 128), (8192, 4096), (8, 65536),
                             (1000, 256), (256, 131072)]:
            legacy = _seed_ln_pick(rows, hidden)
            assert _pick_block_rows(rows, hidden) == legacy
            assert norm_block_rows(rows, hidden) == legacy
            assert _block_rows(rows, hidden, jnp.bfloat16,
                               interpret=False) == legacy

    def test_softmax(self, tmp_cache):
        from apex_tpu.ops.pallas.softmax_kernel import (_block_rows,
                                                        _pick_rows)

        for skp, sq, itemsize, mask in [(128, 64, 2, False),
                                        (1024, 1024, 4, True),
                                        (16384, 8, 2, False),
                                        (2048, 333, 4, False)]:
            legacy = _seed_sm_pick(skp, sq, itemsize, mask)
            assert _pick_rows(skp, sq, itemsize, mask) == legacy
            assert _block_rows(skp, sq, itemsize, mask, jnp.bfloat16,
                               interpret=False) == legacy

    def test_group_norm(self, tmp_cache):
        from apex_tpu.ops.pallas.group_norm_kernel import (_hw_block,
                                                           _pick_hw_block)

        for hw, c in [(64, 64), (4096, 256), (16384, 2048), (1000, 128)]:
            legacy = _seed_gn_pick(hw, c)
            assert _pick_hw_block(hw, c) == legacy
            assert _hw_block(hw, c, jnp.bfloat16, interpret=False) == legacy

    def test_flash_attention_defaults(self, tmp_cache):
        from apex_tpu.ops.pallas.flash_attention import (DEFAULT_BLOCK_K,
                                                         DEFAULT_BLOCK_Q,
                                                         _resolve_blocks)

        assert _resolve_blocks(2048, 2048, 64, True, jnp.bfloat16,
                               None, None) == (DEFAULT_BLOCK_Q,
                                               DEFAULT_BLOCK_K)

    def test_flat_optimizers(self, tmp_cache):
        from apex_tpu.ops.pallas.fused_adam_kernel import (_flat_block_rows,
                                                           _pick_block_rows)

        for rows in [8, 512, 7813, 7812496]:
            legacy = min(512, rows)
            assert _pick_block_rows(rows) == legacy
            assert _flat_block_rows("fused_adam", rows, jnp.bfloat16,
                                    False, None) == legacy
            # explicit arg always wins
            assert _flat_block_rows("fused_adam", rows, jnp.bfloat16,
                                    False, 128) == 128

    def test_warmed_cache_changes_selection(self, tmp_cache):
        """The inverse control: a valid warmed entry IS picked up."""
        from apex_tpu.ops.pallas.layer_norm_kernel import _block_rows

        rows, hidden = 8192, 4096
        tune.record_tuned("layer_norm",
                          (("rows", pow2_bucket(rows)), ("hidden", hidden)),
                          {"block_rows": 64}, dtype=jnp.bfloat16)
        tune.invalidate()
        assert _block_rows(rows, hidden, jnp.bfloat16,
                           interpret=False) == 64
        # interpret mode still ignores it
        assert _block_rows(rows, hidden, jnp.bfloat16,
                           interpret=True) == _seed_ln_pick(rows, hidden)


# seed-era reference implementations (verbatim from the pre-PR3 kernels),
# kept here as the bit-for-bit oracle the shared helpers must match


def _seed_ln_pick(rows, hidden):
    budget = 2 * 1024 * 1024 // max(hidden * 4, 1)
    br = 256
    while br > budget and br > 8:
        br //= 2
    while rows % br != 0 and br > 8:
        br //= 2
    return max(br, 8)


def _seed_sm_pick(skp, sq, itemsize, has_mask):
    def round_up(n, m):
        return -(-n // m) * m

    bytes_per_elt = 2 * (2 * itemsize + (4 if has_mask else 0)) + 8
    br = (10 << 20) // (skp * bytes_per_elt)
    br = max(8, min(512, round_up(br, 8) if br >= 8 else 8))
    return min(br, round_up(sq, 8))


def _seed_gn_pick(hw, c):
    budget = max((2 * 1024 * 1024) // max(c * 4, 1), 8)
    blk = 1 << (budget.bit_length() - 1)
    blk = min(blk, hw)
    while hw % blk != 0 and blk > 8:
        blk //= 2
    return max(blk, 8)


# ------------------------------------------------- flash block validation


class TestFlashBlockValidation:
    def _qkv(self, s=64, d=64):
        k = jax.random.split(jax.random.PRNGKey(0), 3)
        return tuple(jax.random.normal(k_, (1, 2, s, d)) * 0.1 for k_ in k)

    def test_misaligned_block_q_raises(self):
        from apex_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = self._qkv()
        with pytest.raises(ValueError, match="multiple of 8"):
            flash_attention(q, k, v, True, block_q=100)

    def test_misaligned_block_k_raises(self):
        from apex_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = self._qkv()
        with pytest.raises(ValueError, match="multiple of 128"):
            flash_attention(q, k, v, True, block_k=100)

    def test_nonpositive_raises(self):
        from apex_tpu.ops.pallas.flash_attention import validate_blocks

        with pytest.raises(ValueError):
            validate_blocks(0, 128, 64, 64)
        with pytest.raises(ValueError):
            validate_blocks(8, -128, 64, 64)

    def test_valid_explicit_blocks_accepted(self):
        from apex_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = self._qkv()
        o = flash_attention(q, k, v, True, block_q=16, block_k=128)
        assert o.shape == q.shape
        # parity with the default-block path (same math, different grid)
        o2 = flash_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(o2),
                                   rtol=2e-5, atol=2e-5)


# --------------------------------------------------- search + CLI smoke


class TestSearchAndCli:
    def test_every_consulting_kernel_is_warmable(self):
        # every kernel with a CODE_VERSIONS entry (i.e. whose entry point
        # consults the cache) must have a registry spec — otherwise its
        # lookup path is permanently dead code
        from apex_tpu.tune import registry

        assert set(tune.CODE_VERSIONS) == set(registry.kernels())
        for name in registry.kernels():
            spec = registry.spec(name)
            assert spec.default_shapes, name
            shape = dict(spec.default_shapes[0])
            cands = spec.candidates(shape)
            assert spec.defaults(shape) in cands, name

    def test_flat_optimizer_specs_run(self, tmp_cache):
        from apex_tpu.tune.search import autotune_kernel

        for kernel in ("fused_lamb", "fused_novograd", "fused_adagrad"):
            res = autotune_kernel(kernel, {"numel": 1024}, "float32",
                                  iters=1, max_candidates=1)
            assert "best" in res, res
            assert res["key"].startswith(f"{kernel}|")

    def test_autotune_kernel_writes_winner(self, tmp_cache):
        from apex_tpu.tune.search import autotune_kernel

        res = autotune_kernel("layer_norm", {"rows": 64, "hidden": 256},
                              "float32", iters=1, max_candidates=2)
        assert "best" in res and res["key"].startswith("layer_norm|")
        tune.invalidate()
        assert tune.default_cache().get(res["key"])["params"] == res["best"]
        # the default candidate is always part of the sweep
        tried = [r["params"] for r in res["candidates"]]
        assert res["default"] in tried

    def test_cli_end_to_end_smoke(self, tmp_cache, tmp_path, capsys):
        from apex_tpu.tune.cli import main as tune_main
        from apex_tpu.utils.logging import subscribe_events

        spec = tmp_path / "workload.json"
        spec.write_text(json.dumps([
            {"kernel": "layer_norm", "shape": {"rows": 32, "hidden": 128},
             "dtype": "float32"},
            {"kernel": "fused_sgd", "shape": {"numel": 1024},
             "dtype": "float32"},
        ]))
        events = []
        unsub = subscribe_events(events.append)
        try:
            rc = tune_main(["--spec", str(spec), "--iters", "1",
                            "--max-candidates", "2"])
        finally:
            unsub()
        assert rc == 0
        doc = json.load(open(tmp_cache))
        assert doc["schema"] == 1 and len(doc["entries"]) == 2
        assert any(k.startswith("layer_norm|") for k in doc["entries"])
        assert any(k.startswith("fused_sgd|") for k in doc["entries"])
        auto = [e for e in events if e["event"] == "kernel_autotune"]
        assert {e["kernel"] for e in auto} == {"layer_norm", "fused_sgd"}
        assert all(e["source"] == "search" for e in auto)
        lines = [json.loads(line) for line in
                 capsys.readouterr().out.strip().splitlines()]
        assert lines[-1]["tuned"] == 2 and lines[-1]["failed"] == 0

    def test_cli_rejects_unknown_kernel(self, tmp_cache, tmp_path):
        from apex_tpu.tune.cli import main as tune_main

        spec = tmp_path / "workload.json"
        spec.write_text(json.dumps([{"kernel": "nope", "shape": {}}]))
        with pytest.raises((SystemExit, KeyError)):
            tune_main(["--spec", str(spec)])


# ------------------------------------------------------- baseline gate


class TestBaselineGate:
    BASELINE = os.path.join(REPO, "BENCH_BASELINE.json")

    def _run(self, args):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import check_regression
        finally:
            sys.path.pop(0)
        return check_regression.main(args)

    def test_committed_baseline_self_compare_passes(self, capsys):
        assert os.path.exists(self.BASELINE), \
            "BENCH_BASELINE.json must be committed (apex-tpu-bench " \
            "--kernels ... --emit-baseline)"
        rc = self._run([self.BASELINE, "--suite", self.BASELINE])
        assert rc == 0
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["regressions"] == 0 and summary["compared"] > 0
        assert "per_kernel" in summary
        # the committed gate covers at least two kernels (acceptance)
        assert len(summary["per_kernel"]) >= 2

    def test_regression_detected_per_kernel(self, tmp_path, capsys):
        base = json.load(open(self.BASELINE))
        cur = json.loads(json.dumps(base))
        cur["layer_norm"]["value"] = base["layer_norm"]["value"] * 3.0
        cur_path = tmp_path / "cur.json"
        cur_path.write_text(json.dumps(cur))
        rc = self._run([str(cur_path), "--suite", self.BASELINE])
        assert rc == 1
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["per_kernel"]["layer_norm"]["regressions"] >= 1
        # the untouched kernels stay green
        assert summary["per_kernel"]["fused_adam_1b"]["regressions"] == 0

    def test_kernel_subset_filter(self, tmp_path, capsys):
        base = json.load(open(self.BASELINE))
        cur = json.loads(json.dumps(base))
        cur["layer_norm"]["value"] = base["layer_norm"]["value"] * 3.0
        cur_path = tmp_path / "cur.json"
        cur_path.write_text(json.dumps(cur))
        # gating only fused_adam_1b ignores the layer_norm regression
        rc = self._run([str(cur_path), "--suite", self.BASELINE,
                        "--kernels", "fused_adam_1b"])
        assert rc == 0
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert list(summary["per_kernel"]) == ["fused_adam_1b"]

    def test_usage_requires_exactly_one_baseline(self):
        assert self._run([self.BASELINE]) == 2
        assert self._run([self.BASELINE, self.BASELINE,
                          "--suite", self.BASELINE]) == 2


# --------------------------------------------------- bench_cli --kernels


class TestBenchSubset:
    def test_emit_baseline_subset(self, tmp_path, monkeypatch):
        from apex_tpu import bench_cli

        out = tmp_path / "B.json"
        monkeypatch.setattr(sys, "argv",
                            ["apex-tpu-bench", "--kernels", "layer_norm",
                             "--emit-baseline", str(out)])
        bench_cli.main()
        doc = json.load(open(out))
        assert doc["subset"] == ["layer_norm"]
        assert doc["complete"] is False  # a subset is never a full suite
        assert "value" in doc["layer_norm"]
        assert "fused_adam_1b" not in doc

    def test_unknown_kernel_raises(self, monkeypatch):
        from apex_tpu import bench_cli

        monkeypatch.setattr(sys, "argv",
                            ["apex-tpu-bench", "--kernels", "not_a_bench"])
        with pytest.raises(ValueError, match="unknown bench"):
            bench_cli.main()
