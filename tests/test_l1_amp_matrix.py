"""L1 integration: the amp cross-product matrix on a small conv model —
TPU port of tests/L1/common/run_test.sh:29-49 (opt levels O0-O3 ×
loss_scale {None, 1.0, 128.0, dynamic} × keep_batchnorm_fp32), with the
compare.py pattern: O1 vs O0 end states stay close; every cell trains.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models.resnet import ResNet18ish
from apex_tpu.optimizers import FusedAdam

STEPS = 4


def _data():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 16, 16, 3))
    y = jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 4)
    return x, y


def _train(opt_level, loss_scale, keep_bn_fp32, steps=STEPS, lr=1e-3,
           return_opt=False):
    x, y = _data()
    policy = amp.Policy.from_opt_level(opt_level, loss_scale=loss_scale,
                                       keep_batchnorm_fp32=keep_bn_fp32)
    compute = jnp.float32 if opt_level == "O0" else jnp.bfloat16
    model = ResNet18ish(num_classes=4, compute_dtype=compute)
    variables = model.init(jax.random.PRNGKey(2), x)
    params = policy.cast_params(variables["params"]) \
        if opt_level in ("O2", "O3") else variables["params"]
    bstats = variables["batch_stats"]
    opt = FusedAdam(params, lr=lr, master_weights=policy.master_weights)
    scaler = policy.make_scaler()
    sstate = scaler.init() if scaler else None

    losses = []
    p = opt.parameters
    for step in range(steps):
        def loss_fn(p):
            logits, _ = model.apply({"params": p, "batch_stats": bstats},
                                    x, mutable=["batch_stats"])
            onehot = jax.nn.one_hot(y, 4)
            loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot,
                                     axis=-1))
            return scaler.scale(loss, sstate) if scaler else loss

        sl, grads = jax.value_and_grad(loss_fn)(p)
        if scaler:
            grads, found_inf = scaler.unscale(grads, sstate)
            p = opt.step(grads, found_inf=found_inf)
            sstate = scaler.update(sstate, found_inf)
            losses.append(float(sl) / float(sstate.scale))
        else:
            p = opt.step(grads)
            losses.append(float(sl))
    if return_opt:
        return losses, p, opt
    return losses, p


# The FULL reference matrix (tests/L1/common/run_test.sh:29-49): every
# opt-level × loss-scale × keep-bn cell, with the reference's own skip rule
# (O1 + an explicit keep_batchnorm flag is skipped, run_test.sh:67-71) —
# 40 cells, no sampling.
# the first cell of each opt level pays that level's full jit compile
# (fp32 for O0, fresh bf16 traces for O1/O2) — the three heaviest cells in
# the suite; they run in the slow tier.
_SLOW_CELLS = {("O0", None, None), ("O1", None, None), ("O2", None, None)}


def _tier1_cell(ol, ls, bn):
    """Tier-1 keeps ONE matrix row per DISTINCT code path — the dynamic
    scaler column at every opt level (the full scale/unscale/update
    machinery, and each level's first-trace warm-up has to land
    somewhere), the O3 no-scaler cell (amp without a scaler), and the O2
    cell that explicitly OPTS OUT of fp32 batchnorm under a static scale
    (keep_bn=False: master weights × the bn low-precision cast). The
    static 1.0/128.0 columns re-run the dynamic cells' policy machinery
    with a different constant (128.0 stays covered tier-1 by that O2 bn
    cell and test_o2_master_weights_are_fp32); keep-bn=True stays
    covered end to end by test_o1_close_to_o0's O1(dynamic, bn=True)
    run. Everything else rides the slow tier at ~4-8s/cell — the full
    40-cell matrix still runs without `-m 'not slow'` (tier-1 budget:
    ROADMAP.md)."""
    if bn is None:
        return ls == "dynamic" or (ol, ls) == ("O3", None)
    return (ol, ls, bn) == ("O2", 128.0, False)


MATRIX = [
    pytest.param(ol, ls, bn,
                 marks=[] if (ol, ls, bn) not in _SLOW_CELLS
                 and _tier1_cell(ol, ls, bn) else [pytest.mark.slow])
    for ol in ("O0", "O1", "O2", "O3")
    for ls in (None, 1.0, 128.0, "dynamic")
    for bn in (None, True, False)
    if not (ol == "O1" and bn is not None)
]
assert len(MATRIX) == 40


class TestAmpMatrix:
    @pytest.mark.parametrize("opt_level,loss_scale,keep_bn", MATRIX)
    def test_cell_trains(self, opt_level, loss_scale, keep_bn):
        losses, params = _train(opt_level, loss_scale, keep_bn)
        assert all(np.isfinite(l) for l in losses), losses
        # training moves: loss at end differs from start
        assert losses[-1] != losses[0]

    def test_o1_close_to_o0(self):
        """compare.py pattern: the O1 run tracks the fp32 run closely over a
        few steps (bf16 tolerance)."""
        l0, p0 = _train("O0", None, None)
        l1, p1 = _train("O1", "dynamic", True)
        assert abs(l0[-1] - l1[-1]) < 0.2 * abs(l0[0]) + 0.1

    def test_o2_master_weights_are_fp32(self):
        _, params, opt = _train("O2", 128.0, True, steps=1, return_opt=True)
        # model params low precision, optimizer masters fp32 (the O2 contract)
        for leaf in jax.tree_util.tree_leaves(params):
            assert leaf.dtype == jnp.bfloat16
        assert "master" in opt.state
        for leaf in jax.tree_util.tree_leaves(opt.state["master"]):
            assert leaf.dtype == jnp.float32


@pytest.mark.slow
class TestL1FullScale:
    """Round-2 scale-up (VERDICT item 9): the REAL ResNet-50 class at 64×64,
    20 steps — the reference L1 recipe shape (tests/L1/common/main_amp.py)
    at CI-tractable resolution. Marked slow: deselect with -m 'not slow'."""

    def test_resnet50_o1_trains(self):
        from apex_tpu.models.resnet import ResNet50
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 64, 64, 3))
        y = jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 10)
        policy = amp.Policy.from_opt_level("O1", loss_scale="dynamic",
                                           keep_batchnorm_fp32=True)
        model = ResNet50(num_classes=10, compute_dtype=jnp.bfloat16)
        variables = model.init(jax.random.PRNGKey(2), x)
        params, bstats = variables["params"], variables["batch_stats"]
        opt = FusedAdam(params, lr=1e-3)
        scaler = policy.make_scaler()
        sstate = scaler.init()

        @jax.jit
        def fwd(p, bstats, sscale):
            def loss_fn(p):
                logits, upd = model.apply(
                    {"params": p, "batch_stats": bstats}, x,
                    mutable=["batch_stats"])
                onehot = jax.nn.one_hot(y, 10)
                loss = -jnp.mean(jnp.sum(
                    jax.nn.log_softmax(logits) * onehot, axis=-1))
                return loss * sscale, upd["batch_stats"]

            (sl, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
            return sl, bs, grads

        losses = []
        p = opt.parameters
        for step in range(20):
            sl, bstats, grads = fwd(p, bstats, sstate.scale)
            grads, found_inf = scaler.unscale(grads, sstate)
            p = opt.step(grads, found_inf=found_inf)
            sstate = scaler.update(sstate, found_inf)
            losses.append(float(sl) / float(sstate.scale))
        assert np.isfinite(losses).all(), losses
        assert min(losses[10:]) < losses[0], losses


@pytest.mark.slow
class TestL1DistributedMatrix:
    """dp-sharded matrix variant ≈ tests/L1/common/run_test.sh:29-49
    distributed mode (cross_product_distributed/run.sh): DDP grad psum +
    SyncBatchNorm over the data axis, amp cells on the 8-device mesh."""

    @pytest.mark.parametrize("opt_level,loss_scale",
                             [("O1", "dynamic"), ("O2", 128.0)])
    def test_distributed_cell_trains(self, opt_level, loss_scale):
        import functools

        from apex_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from apex_tpu.models.resnet import ResNet18ish
        from apex_tpu.optimizers.functional import adam_update
        from apex_tpu.parallel import get_mesh

        mesh = get_mesh("data")
        policy = amp.Policy.from_opt_level(opt_level,
                                           loss_scale=loss_scale,
                                           keep_batchnorm_fp32=True)
        model = ResNet18ish(num_classes=4, axis_name="data")
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 16, 16, 3))
        y = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 4)
        variables = model.init(jax.random.PRNGKey(2), x[:2])
        params, bstats = variables["params"], variables["batch_stats"]
        m0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), params)
        v0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), params)
        scaler = policy.make_scaler()
        sstate = scaler.init() if scaler else None
        scale_val = sstate.scale if scaler else jnp.float32(1.0)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P("data"), P("data"), P(), P()),
            out_specs=(P(), P(), P(), P(), P()), check_vma=False)
        def train_step(params, m, v, bstats, x, y, step, sscale):
            def loss_fn(p):
                logits, upd = model.apply(
                    {"params": p, "batch_stats": bstats}, x,
                    mutable=["batch_stats"])
                onehot = jax.nn.one_hot(y, 4)
                loss = -jnp.mean(jnp.sum(
                    jax.nn.log_softmax(logits) * onehot, axis=-1))
                return loss * sscale, upd["batch_stats"]

            (sl, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params)
            # flat-bucket DDP allreduce (apex_C flatten capability)
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, "data"), grads)
            inv = 1.0 / sscale
            grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
            found_inf = jnp.logical_not(jnp.all(jnp.stack([
                jnp.all(jnp.isfinite(g)) for g in
                jax.tree_util.tree_leaves(grads)])))
            params, m, v = adam_update(params, grads, m, v, step=step,
                                       lr=1e-3, found_inf=found_inf)
            return params, m, v, bs, jax.lax.pmean(sl, "data")

        losses = []
        state = (params, m0, v0, bstats)
        jit_step = jax.jit(train_step)
        for step in range(1, 5):
            *state, sl = jit_step(*state, x, y, jnp.int32(step),
                                  scale_val)
            state = tuple(state)
            if scaler:
                losses.append(float(sl) / float(scale_val))
            else:
                losses.append(float(sl))
        assert np.isfinite(losses).all(), losses
        assert losses[-1] != losses[0]
