"""L1 integration: the amp cross-product matrix on a small conv model —
TPU port of tests/L1/common/run_test.sh:29-49 (opt levels O0-O3 ×
loss_scale {None, 1.0, 128.0, dynamic} × keep_batchnorm_fp32), with the
compare.py pattern: O1 vs O0 end states stay close; every cell trains.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.models.resnet import ResNet18ish
from apex_tpu.optimizers import FusedAdam

STEPS = 4


def _data():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 16, 16, 3))
    y = jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 4)
    return x, y


def _train(opt_level, loss_scale, keep_bn_fp32, steps=STEPS, lr=1e-3,
           return_opt=False):
    x, y = _data()
    policy = amp.Policy.from_opt_level(opt_level, loss_scale=loss_scale,
                                       keep_batchnorm_fp32=keep_bn_fp32)
    compute = jnp.float32 if opt_level == "O0" else jnp.bfloat16
    model = ResNet18ish(num_classes=4, compute_dtype=compute)
    variables = model.init(jax.random.PRNGKey(2), x)
    params = policy.cast_params(variables["params"]) \
        if opt_level in ("O2", "O3") else variables["params"]
    bstats = variables["batch_stats"]
    opt = FusedAdam(params, lr=lr, master_weights=policy.master_weights)
    scaler = policy.make_scaler()
    sstate = scaler.init() if scaler else None

    losses = []
    p = opt.parameters
    for step in range(steps):
        def loss_fn(p):
            logits, _ = model.apply({"params": p, "batch_stats": bstats},
                                    x, mutable=["batch_stats"])
            onehot = jax.nn.one_hot(y, 4)
            loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot,
                                     axis=-1))
            return scaler.scale(loss, sstate) if scaler else loss

        sl, grads = jax.value_and_grad(loss_fn)(p)
        if scaler:
            grads, found_inf = scaler.unscale(grads, sstate)
            p = opt.step(grads, found_inf=found_inf)
            sstate = scaler.update(sstate, found_inf)
            losses.append(float(sl) / float(sstate.scale))
        else:
            p = opt.step(grads)
            losses.append(float(sl))
    if return_opt:
        return losses, p, opt
    return losses, p


MATRIX = [
    (ol, ls, bn)
    for ol in ("O0", "O1", "O2", "O3")
    for ls in (None, 1.0, 128.0, "dynamic")
    for bn in (None, True, False)
    # trim: bn flag only meaningful off-O0; sample the cross product the way
    # run_test.sh does rather than all 48 cells
    if not (ol == "O0" and (ls is not None or bn is not None))
][:20]


class TestAmpMatrix:
    @pytest.mark.parametrize("opt_level,loss_scale,keep_bn", MATRIX)
    def test_cell_trains(self, opt_level, loss_scale, keep_bn):
        losses, params = _train(opt_level, loss_scale, keep_bn)
        assert all(np.isfinite(l) for l in losses), losses
        # training moves: loss at end differs from start
        assert losses[-1] != losses[0]

    def test_o1_close_to_o0(self):
        """compare.py pattern: the O1 run tracks the fp32 run closely over a
        few steps (bf16 tolerance)."""
        l0, p0 = _train("O0", None, None)
        l1, p1 = _train("O1", "dynamic", True)
        assert abs(l0[-1] - l1[-1]) < 0.2 * abs(l0[0]) + 0.1

    def test_o2_master_weights_are_fp32(self):
        _, params, opt = _train("O2", 128.0, True, steps=1, return_opt=True)
        # model params low precision, optimizer masters fp32 (the O2 contract)
        for leaf in jax.tree_util.tree_leaves(params):
            assert leaf.dtype == jnp.bfloat16
        assert "master" in opt.state
        for leaf in jax.tree_util.tree_leaves(opt.state["master"]):
            assert leaf.dtype == jnp.float32
