"""Serving engine tier-1: static-shape KV cache, one-jit decode,
continuous batching.

The acceptance claims under test:

- **parity** — incremental decode logits are bit-identical (fp32) to
  full-sequence prefill logits: prefill and decode share ONE single-token
  forward at one fixed ``[num_slots]`` shape, so there is no second
  numeric path to drift;
- **one compile** — a scripted trace that admits, completes, evicts, and
  backfills requests mid-stream traces ``decode_step`` exactly once
  (``Engine.decode_traces``);
- **isolation** — a FaultInjector-scripted mid-stream abort leaves every
  other request's token stream bit-identical (per-slot reductions cannot
  see other slots' bytes);
- termination (EOS / max-new-tokens / context), greedy + seeded-sampling
  determinism, the serve bench + regression gate, and both CLIs.

Engines are compiled once per geometry and shared across tests via
``Engine.reset()`` (state drop, zero recompiles — itself part of the
serving contract); the one-jit acceptance tests get fresh engines so
their trace counters stay airtight.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.monitor.goodput import GoodputLedger
from apex_tpu.resilience.fault_injection import FaultInjector
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.kv_cache import init_cache, write_token
from apex_tpu.serve.scheduler import Request, ServeScheduler
# bound at collection time: test_chip_worker purges apex_tpu.* from
# sys.modules mid-session, and a function-local re-import after that
# would subscribe to a FRESH bus while the (old) scheduler module keeps
# publishing to the original one
from apex_tpu.utils.logging import subscribe_events

pytestmark = pytest.mark.serve

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=2,
                 n_head=2, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_gpt2_params(CFG, seed=0)


def _engine(params, **kw):
    kw.setdefault("num_slots", 3)
    kw.setdefault("max_len", 32)
    kw.setdefault("temperature", 0.0)
    seed = kw.pop("seed", 0)
    return Engine(CFG, params, EngineConfig(**kw), seed=seed)


@pytest.fixture(scope="module")
def greedy3(params):
    """Shared greedy 3-slot engine; tests reset() it — compiled once."""
    return _engine(params)


@pytest.fixture(scope="module")
def greedy2(params):
    return _engine(params, num_slots=2)


@pytest.fixture(scope="module")
def keeper3(params):
    """3-slot greedy engine that keeps per-position prefill logits."""
    return _engine(params, keep_prefill_logits=True)


def _tokens(n, seed=7, vocab=97):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, vocab, n)]


# ------------------------------------------------------------ kv cache

def test_kv_cache_ops_are_static_and_masked():
    cache = init_cache(n_layer=2, num_slots=4, max_len=16, heads=2,
                       head_dim=8)
    k = jnp.ones((4, 2, 8)) * jnp.arange(1, 5)[:, None, None]
    pos = jnp.zeros((4,), jnp.int32)
    mask = jnp.array([True, False, True, False])
    out = jax.jit(write_token, static_argnums=1)(cache, 0, k, k, pos, mask)
    assert out.k.shape == cache.k.shape  # static shapes, whatever the mask
    got = np.asarray(out.k[0, :, 0, 0, 0])
    np.testing.assert_array_equal(got, [1.0, 0.0, 3.0, 0.0])
    # masked-off slots' bytes are bit-untouched
    np.testing.assert_array_equal(np.asarray(out.k[0, 1]),
                                  np.asarray(cache.k[0, 1]))


# -------------------------------------------------------------- parity

def test_prefill_vs_incremental_decode_bit_exact(greedy3, keeper3):
    """THE serving invariant: decode token j's logits == full prefill's
    position-j logits, bit-for-bit in fp32."""
    seq = _tokens(12)
    _, _, all_logits = keeper3.reset().prefill({1: seq})
    all_logits = np.asarray(all_logits)          # [P, B, V]

    inc = greedy3.reset()
    inc.prefill({1: seq[:5]})
    for j in range(5, len(seq)):
        forced = np.array([0, seq[j], 0], np.int32)
        _, logits = inc.decode_step(forced, np.array([False, True, False]))
        a, b = all_logits[j, 1], np.asarray(logits)[1]
        assert a.dtype == np.float32
        assert np.array_equal(a, b), \
            f"decode pos {j} drifted: max|d|={np.abs(a - b).max()}"
    assert inc.lengths[1] == len(seq)


def test_prefill_last_logits_match_kept_logits(keeper3):
    seq = _tokens(9, seed=3)
    _, last, all_logits = keeper3.reset().prefill({0: seq})
    np.testing.assert_array_equal(np.asarray(last)[0],
                                  np.asarray(all_logits)[len(seq) - 1, 0])


# ----------------------------------------------------- one-jit invariant

def test_decode_compiles_once_across_admit_evict_backfill(params):
    """Scripted multi-request trace — staggered admissions, completions,
    a mid-stream abort, and backfill — compiles decode_step exactly once
    and one prefill per prompt bucket. Fresh engine: the trace counters
    are the assertion."""
    eng = _engine(params, num_slots=2)
    inj = FaultInjector(seed=0).abort_request("r2", at_step=4)
    sched = ServeScheduler(eng, fault_injector=inj)
    for i, plen in enumerate((4, 6, 5, 3, 7)):
        sched.submit(Request(request_id=f"r{i}",
                             tokens=_tokens(plen, seed=i),
                             max_new_tokens=4 + i % 3))
    stats = sched.run()
    assert len(stats.requests) == 5
    assert {r["state"] for r in stats.requests} == {"completed", "evicted"}
    assert eng.decode_traces == 1, \
        "slot membership changes must not retrace decode_step"
    # prompts bucket to pow2: {4, 8} at most
    assert eng.prefill_traces <= 2


def test_aot_compile_then_serve_traces_once(params):
    eng = _engine(params, num_slots=2).aot_compile(prompt_buckets=[8])
    assert eng.decode_traces == 1
    sched = ServeScheduler(eng)
    for i in range(3):
        sched.submit(Request(request_id=i, tokens=_tokens(6, seed=i),
                             max_new_tokens=3))
    sched.run()
    assert eng.decode_traces == 1      # served entirely from the AOT exe
    assert eng.prefill_traces == 1
    # reset drops state but keeps the compiled artifacts — including
    # the retained prefill LOWERINGS (PR 17): cost_ledger() on the warm-
    # restarted engine extracts from the saved artifacts, never
    # re-tracing or re-lowering
    assert set(eng._prefill_lowered) == {8}
    eng.reset()
    assert np.asarray(eng.cache.lengths).max() == 0
    assert set(eng._prefill_lowered) == {8}
    ledger = eng.cost_ledger()
    assert set(ledger["executables"]) == {"decode", "prefill_8"}
    assert eng.decode_traces == 1 and eng.prefill_traces == 1
    sched = ServeScheduler(eng)
    sched.submit(Request(request_id="again", tokens=_tokens(6),
                         max_new_tokens=2))
    sched.run()
    assert eng.decode_traces == 1 and eng.prefill_traces == 1


# --------------------------------------------------------- termination

def test_eos_terminates_request(greedy2):
    # greedy decode is deterministic: discover the first generated token,
    # then rerun with that token as EOS — must stop after exactly 1 token
    sched = ServeScheduler(greedy2.reset())
    sched.submit(Request(request_id="probe", tokens=_tokens(5),
                         max_new_tokens=4))
    first = sched.run().requests[0]["generated"][0]

    sched2 = ServeScheduler(greedy2.reset())
    sched2.submit(Request(request_id="eos", tokens=_tokens(5),
                          max_new_tokens=16, eos_id=int(first)))
    rec = sched2.run().requests[0]
    assert rec["finish_reason"] == "eos"
    assert rec["new_tokens"] == 1
    assert rec["generated"][-1] == int(first)


def test_max_new_tokens_terminates(greedy3):
    sched = ServeScheduler(greedy3.reset())
    sched.submit(Request(request_id=0, tokens=_tokens(5),
                         max_new_tokens=5))
    rec = sched.run().requests[0]
    assert rec["finish_reason"] == "length"
    assert rec["new_tokens"] == 5


def test_context_full_terminates(greedy2):
    eng = greedy2.reset()
    sched = ServeScheduler(eng)
    sched.submit(Request(request_id=0, tokens=_tokens(28),
                         max_new_tokens=100))
    rec = sched.run().requests[0]
    assert rec["finish_reason"] == "context"
    assert rec["new_tokens"] == 4          # 28 + 4 == max_len == 32
    # slot freed at completion: lengths reset
    assert eng.lengths.max() == 0
    # the RAW engine refuses to decode a context-full slot (a clipped
    # cache write would silently corrupt the newest K/V row)
    eng.reset()
    eng.prefill({0: _tokens(31)})
    eng.decode_step(eng.last_tokens, np.array([True, False]))  # -> 32
    with pytest.raises(ValueError, match="max_len"):
        eng.decode_step(eng.last_tokens, np.array([True, False]))


def test_oversized_prompt_rejected(greedy2):
    sched = ServeScheduler(greedy2.reset())
    with pytest.raises(ValueError, match="no room"):
        sched.submit(Request(request_id=0, tokens=_tokens(32)))
    with pytest.raises(ValueError, match="empty"):
        sched.submit(Request(request_id=1, tokens=[]))


# ----------------------------------------------- eviction isolation

def _run_trace(eng, injector=None, n=4):
    sched = ServeScheduler(eng.reset(), fault_injector=injector)
    for i in range(n):
        sched.submit(Request(request_id=f"r{i}", tokens=_tokens(5, seed=i),
                             max_new_tokens=6))
    sched.run()
    return {r["request_id"]: r for r in sched.stats().requests}


@pytest.mark.fault
def test_mid_stream_abort_leaves_other_slots_bit_identical(greedy2):
    """FaultInjector aborts r1 mid-decode; every other request's token
    stream must match the abort-free run bit-for-bit (static shapes make
    slot arithmetic independent of slot membership)."""
    base = _run_trace(greedy2)
    inj = FaultInjector(seed=0).abort_request("r1", at_step=2)
    with GoodputLedger() as led:
        faulted = _run_trace(greedy2, injector=inj)
    assert faulted["r1"]["state"] == "evicted"
    assert faulted["r1"]["finish_reason"] == "aborted"
    for rid in ("r0", "r2", "r3"):
        assert faulted[rid]["state"] == "completed"
        assert faulted[rid]["generated"] == base[rid]["generated"], rid
    assert led.summary()["events"]["serve_request_evicted"] == 1


@pytest.mark.fault
def test_abort_of_still_queued_request(greedy2):
    """Satellite regression (PR 8): aborting a request that was never
    admitted must remove it from the queue, account it exactly once,
    publish the abort event — and charge its wasted queue time as a
    ``serve_queue_wait`` loss (previously the wait silently vanished).
    Both entry points: a direct cross-thread-style abort() call and the
    FaultInjector-scripted path."""
    # direct call, before any tick: 3 requests, 2 slots -> "c" queued
    sched = ServeScheduler(greedy2.reset())
    for rid in ("a", "b", "c"):
        sched.submit(Request(request_id=rid, tokens=_tokens(5),
                             max_new_tokens=3))
    assert sched.abort("c") is True
    assert all(r.request_id != "c" for r in sched.queue)
    assert sched.abort("c") is False      # terminal: never re-accounted
    stats = sched.run()
    recs = {r["request_id"]: r for r in stats.requests}
    assert len(stats.requests) == 3
    assert recs["c"]["state"] == "evicted"
    assert recs["c"]["finish_reason"] == "aborted"
    assert recs["c"]["new_tokens"] == 0
    assert recs["a"]["state"] == recs["b"]["state"] == "completed"

    # injector path mid-run, with the event + queue-wait accounting
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r)
        if r.get("request_id") == "r2"
        and r.get("event") in ("serve_request_evicted",
                               "serve_queue_wait") else None)
    try:
        inj = FaultInjector(seed=0).abort_request("r2", at_step=1)
        sched = ServeScheduler(greedy2.reset(), fault_injector=inj)
        for i in range(3):
            sched.submit(Request(request_id=f"r{i}",
                                 tokens=_tokens(5, seed=i),
                                 max_new_tokens=4))
        stats = sched.run()
    finally:
        unsub()
    recs = {r["request_id"]: r for r in stats.requests}
    assert recs["r2"]["state"] == "evicted"
    assert recs["r2"]["finish_reason"] == "aborted"
    evicted = [r for r in seen if r["event"] == "serve_request_evicted"]
    waits = [r for r in seen if r["event"] == "serve_queue_wait"]
    assert len(evicted) == 1 and evicted[0]["reason"] == "aborted"
    assert len(waits) == 1 and waits[0]["seconds"] >= 0.0


# -------------------------------------------------------- determinism

def test_greedy_is_deterministic_and_argmax(greedy3, keeper3):
    seq = _tokens(6)
    first, last_logits, _ = keeper3.reset().prefill({0: seq})
    assert first[0] == int(np.asarray(last_logits)[0].argmax())
    runs = []
    for _ in range(2):
        s = ServeScheduler(greedy3.reset())
        s.submit(Request(request_id=0, tokens=seq, max_new_tokens=8))
        runs.append(s.run().requests[0]["generated"])
    assert runs[0] == runs[1]


def test_sampled_decode_replays_under_fixed_key(params):
    eng = _engine(params, temperature=0.8, top_k=5)

    def run(seed):
        s = ServeScheduler(eng.reset(seed))
        s.submit(Request(request_id=0, tokens=_tokens(6),
                         max_new_tokens=8))
        return s.run().requests[0]["generated"]

    assert run(1) == run(1)          # threaded PRNG: same seed, same stream
    assert run(1) != run(2)          # and the key actually matters


def test_top_k_restricts_to_top_k(params, keeper3):
    seq = _tokens(6)
    _, last_logits, _ = keeper3.reset().prefill({0: seq})
    top5 = set(np.argsort(np.asarray(last_logits)[0])[-5:].tolist())
    eng = _engine(params, temperature=1.5, top_k=5)
    for seed in range(2):
        first, _, _ = eng.reset(seed).prefill({0: seq})
        assert int(first[0]) in top5


# ------------------------------------------------------------ tracing

def test_request_traces_reconcile_with_stats(greedy2):
    """THE tracing acceptance: every completed request is exactly one
    trace with queue/prefill/decode/complete spans whose durations equal
    the scheduler's own TTFT/latency accounting (same clock reads), the
    scheduler trace carries one decode_tick per step, and tracing adds
    ZERO compiles (the one-jit invariant holds with it on)."""
    from apex_tpu.monitor import Tracer, spans_by_trace

    eng = greedy2.reset()
    tracer = Tracer()
    sched = ServeScheduler(eng, tracer=tracer)
    for i in range(4):
        sched.submit(Request(request_id=f"r{i}",
                             tokens=_tokens(5, seed=i), max_new_tokens=4))
    stats = sched.run()
    assert eng.decode_traces == 1          # tracing retraced nothing
    by_trace = spans_by_trace(tracer.completed_records())
    recs = {r["request_id"]: r for r in stats.requests}
    assert len(recs) == 4
    tol = 2e-3  # span stamps round to the microsecond; ttft to 1e-6 s
    for rid, rec in recs.items():
        spans = {s["name"]: s for s in by_trace[f"request:{rid}"]}
        assert set(spans) == {"request", "queue", "prefill", "decode",
                              "complete"}, rid
        q, p, d = spans["queue"], spans["prefill"], spans["decode"]
        root = spans["request"]
        assert abs((q["t1"] - q["t0"]) + (p["t1"] - p["t0"])
                   - rec["ttft_s"]) < tol
        assert abs((root["t1"] - root["t0"]) - rec["latency_s"]) < tol
        assert abs((d["t1"] - d["t0"])
                   - (rec["latency_s"] - rec["ttft_s"])) < tol
        assert root["attrs"]["new_tokens"] == rec["new_tokens"]
        for s in spans.values():
            assert s["status"] == "ok"
    ticks = [s for s in by_trace["serve:scheduler"]
             if s["name"] == "decode_tick"]
    assert len(ticks) == stats.decode_steps
    assert not tracer.open_spans()         # run() closed everything


@pytest.mark.fault
def test_aborted_request_trace_marks_abort(greedy2):
    from apex_tpu.monitor import Tracer, spans_by_trace

    tracer = Tracer()
    inj = FaultInjector(seed=0).abort_request("r1", at_step=2)
    sched = ServeScheduler(greedy2.reset(), fault_injector=inj,
                           tracer=tracer)
    for i in range(2):
        sched.submit(Request(request_id=f"r{i}",
                             tokens=_tokens(5, seed=i), max_new_tokens=6))
    sched.run()
    spans = {s["name"]: s for s in spans_by_trace(
        tracer.completed_records())["request:r1"]}
    assert "abort" in spans and "complete" not in spans
    assert spans["request"]["status"] == "cancelled"
    assert spans["request"]["attrs"]["finish_reason"] == "aborted"
    # the surviving request completed normally
    other = spans_by_trace(tracer.completed_records())["request:r0"]
    assert {s["name"] for s in other} >= {"request", "complete"}


def test_untraced_scheduler_publishes_no_spans(greedy3):
    """Tracing disabled (the default) adds nothing: no span records on
    the bus, no per-request bookkeeping, and — asserted everywhere else
    in this file — no extra compiles."""
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r) if str(r.get("event", "")).startswith(
            "span_") else None)
    try:
        sched = ServeScheduler(greedy3.reset())
        sched.submit(Request(request_id=0, tokens=_tokens(5),
                             max_new_tokens=2))
        sched.run()
    finally:
        unsub()
    assert not seen
    assert sched.tracer is None and not sched._req_spans


# -------------------------------------------------- scheduler / events

def test_backfill_and_queue_wait_accounting(greedy2):
    with GoodputLedger() as led:
        sched = ServeScheduler(greedy2.reset())
        for i in range(5):
            sched.submit(Request(request_id=i, tokens=_tokens(5, seed=i),
                                 max_new_tokens=3))
        stats = sched.run()
    s = stats.summary()
    assert s["completed"] == 5
    g = led.summary()
    assert g["events"]["serve_request_admitted"] == 5
    assert g["events"]["serve_request_completed"] == 5
    assert g["events"]["serve_decode_step"] == stats.decode_steps
    # 3 of 5 requests waited for a slot: queue-wait is a goodput cause
    assert g["lost_by_cause"].get("serve_queue_wait", 0.0) > 0.0
    assert s["tokens_per_s"] > 0
    assert s["p99_step_ms"] >= s["p50_step_ms"] >= 0


def test_stats_record_shape(greedy3):
    sched = ServeScheduler(greedy3.reset())
    sched.submit(Request(request_id="x", tokens=_tokens(5),
                         max_new_tokens=2))
    rec = sched.run().requests[0]
    for key in ("request_id", "state", "finish_reason", "prompt_tokens",
                "new_tokens", "generated", "ttft_s", "latency_s",
                "tokens_per_s"):
        assert key in rec, key


# --------------------------------------------------- tuned geometry

def test_decode_attention_block_drives_geometry(params):
    """An explicit (valid) block_k changes the partial-reduction order but
    both engine paths share it — parity must survive the non-default
    geometry; an invalid one must be rejected loudly."""
    seq = _tokens(8)
    full = _engine(params, keep_prefill_logits=True, block_k=8)
    _, _, all_logits = full.prefill({1: seq})
    inc = _engine(params, block_k=8)
    inc.prefill({1: seq[:4]})
    for j in range(4, len(seq)):
        forced = np.array([0, seq[j], 0], np.int32)
        _, logits = inc.decode_step(forced,
                                    np.array([False, True, False]))
        assert np.array_equal(np.asarray(all_logits)[j, 1],
                              np.asarray(logits)[1])
    with pytest.raises(ValueError, match="divide"):
        _engine(params, block_k=7)


def test_decode_attention_registered_with_tune():
    from apex_tpu.tune import CODE_VERSIONS
    from apex_tpu.tune import registry

    assert "decode_attention" in CODE_VERSIONS
    spec = registry.spec("decode_attention")
    shape = dict(spec.default_shapes[0])
    cands = spec.candidates(shape)
    assert spec.defaults(shape) in cands
    # the build runs the real decode attention at a small geometry
    small = {"b": 2, "max_len": 64, "heads": 2, "d": 8}
    p = spec.defaults(small)
    step, state, consts = spec.build(small, jnp.float32, p)
    out = step(0, state, *consts)
    assert out.shape == state.shape


# ----------------------------------- paged KV pool + prefix caching

@pytest.fixture(scope="module")
def paged3(params):
    """Shared 3-slot paged greedy engine (page_size 8, slot-equivalent
    pool, prefix index on); tests reset() it — compiled once. The prefix
    index only fires on page-aligned shared prompts, so parity tests
    with distinct prompts ride the plain paged path."""
    return _engine(params, page_size=8, prefix_cache=True)


@pytest.fixture(scope="module")
def slot8(params):
    """Slot-cache greedy oracle pinned to block_k=8 — the paged
    engine's chunk geometry. Bit-exactness across layouts holds at
    EQUAL block_k (only the K/V fetch differs then); at different
    block_k the softmax partial-sum order differs by design, exactly
    like two block_k values on the same layout."""
    return _engine(params, block_k=8)


def _mixed_requests(n=5, seed0=0, max_new=5):
    """Mixed-length prompts on 3 slots: staggered completions force
    eviction + backfill mid-trace."""
    return [Request(request_id=f"r{i}",
                    tokens=_tokens(4 + 3 * (i % 4), seed=seed0 + i),
                    max_new_tokens=max_new) for i in range(n)]


def _trace_outputs(eng, reqs, injector=None):
    sched = ServeScheduler(eng, fault_injector=injector)
    for r in reqs:
        sched.submit(r)
    return {r["request_id"]: r for r in sched.run().requests}


def test_paged_bit_exact_vs_slot_greedy(slot8, paged3):
    """THE paged acceptance: an identical mixed-length request trace
    through the slot engine (the oracle) and the paged engine produces
    bit-identical greedy streams — the chunked-softmax arithmetic is
    shared verbatim, only the K/V fetch differs. Both engines run the
    same block_k (paged3's page-sized default): equal chunk geometry is
    the bit-exactness precondition, and the autotuner keys it per
    layout so a deployment pins it the same way."""
    assert paged3.block_k == slot8.block_k == 8
    base = _trace_outputs(slot8.reset(), _mixed_requests())
    got = _trace_outputs(paged3.reset(), _mixed_requests())
    assert {k: v["generated"] for k, v in got.items()} == \
           {k: v["generated"] for k, v in base.items()}
    assert {k: v["finish_reason"] for k, v in got.items()} == \
           {k: v["finish_reason"] for k, v in base.items()}


def test_paged_decode_logits_bit_exact_vs_slot_prefill(params, paged3):
    """Strongest oracle form: a PAGED engine's incremental decode logits
    equal the SLOT engine's full-sequence prefill logits bit-for-bit in
    fp32 — crossing both the layout and the prefill/decode path (at the
    shared block_k=8 chunk geometry)."""
    seq = _tokens(12)
    keeper = _engine(params, keep_prefill_logits=True, block_k=8)
    _, _, all_logits = keeper.prefill({1: seq})
    all_logits = np.asarray(all_logits)          # [P, B, V]
    inc = paged3.reset()
    inc.prefill({1: seq[:5]})
    for j in range(5, len(seq)):
        forced = np.array([0, seq[j], 0], np.int32)
        _, logits = inc.decode_step(forced, np.array([False, True, False]))
        a, b = all_logits[j, 1], np.asarray(logits)[1]
        assert a.dtype == np.float32
        assert np.array_equal(a, b), \
            f"paged decode pos {j} drifted: max|d|={np.abs(a - b).max()}"


@pytest.mark.slow
def test_paged_bit_exact_vs_slot_sampled(params):
    """Seeded sampling: the PRNG key is engine state split once per
    prefill/decode call in BOTH layouts, so identical traces consume
    identical key paths — sampled streams match token-for-token.

    Slow tier: the greedy paged-vs-slot parity above pins the layout
    equivalence in tier-1; this adds the PRNG-path leg."""
    kw = dict(temperature=0.8, top_k=5, block_k=8)
    base = _trace_outputs(_engine(params, **kw), _mixed_requests(max_new=6))
    got = _trace_outputs(_engine(params, page_size=8, **kw),
                         _mixed_requests(max_new=6))
    assert {k: v["generated"] for k, v in got.items()} == \
           {k: v["generated"] for k, v in base.items()}


def test_paged_decode_compiles_once_across_admit_evict_backfill(params):
    """The one-compile invariant survives paging: page tables are data,
    so admissions, completions, a scripted mid-stream abort, and
    backfill (page alloc/release/COW churn included) trace decode_step
    exactly once. Fresh engine: the counters are the assertion."""
    eng = _engine(params, num_slots=2, page_size=8, prefix_cache=True)
    inj = FaultInjector(seed=0).abort_request("r2", at_step=4)
    sched = ServeScheduler(eng, fault_injector=inj)
    for i, plen in enumerate((4, 6, 5, 3, 7)):
        sched.submit(Request(request_id=f"r{i}",
                             tokens=_tokens(plen, seed=i),
                             max_new_tokens=4 + i % 3))
    stats = sched.run()
    assert len(stats.requests) == 5
    assert {r["state"] for r in stats.requests} == {"completed", "evicted"}
    assert eng.decode_traces == 1, \
        "page-table churn must not retrace decode_step"
    assert eng.prefill_traces <= 2          # pow2 buckets {4, 8}


def test_prefix_hit_skips_prefill(paged3):
    """A request whose prompt prefix is resident skips prefill for those
    pages: asserted by the engine's scan counters (never wall clock), the
    serve_prefix_hit event fires, and the stream built on shared pages is
    bit-identical to a cold prefill."""
    eng = paged3.reset()
    sysp = _tokens(16, seed=42)              # two full pages
    warm_prompt = sysp + _tokens(5, seed=2)
    # cold baseline for the WARM request (fresh engine state, no index)
    base = _trace_outputs(eng, [Request(request_id="b",
                                        tokens=list(warm_prompt),
                                        max_new_tokens=3)])
    eng.reset()
    # cold run seeds the index (a request can't hit its own admission)
    _trace_outputs(eng, [Request(request_id="a",
                                 tokens=sysp + _tokens(5, seed=1),
                                 max_new_tokens=3)])
    assert eng.prefix_hits == 0
    scanned_cold = eng.prefill_scanned_tokens
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r)
        if r.get("event") == "serve_prefix_hit" else None)
    try:
        got = _trace_outputs(eng, [Request(request_id="b",
                                           tokens=list(warm_prompt),
                                           max_new_tokens=3)])
    finally:
        unsub()
    assert eng.prefix_hits == 1 and eng.prefix_hit_tokens == 16
    # the warm prefill scanned only the 5-token tail's pow2 bucket (8),
    # not the 21-token prompt's (32): the prefill-work skip, in counters
    assert eng.prefill_scanned_tokens - scanned_cold == 8
    assert len(seen) == 1
    assert seen[0]["hit_tokens"] == 16 and seen[0]["hit_pages"] == 2
    assert seen[0]["scanned_tokens"] == 5
    # shared read-only pages hold the same bytes a cold prefill writes
    assert got["b"]["generated"] == base["b"]["generated"]


def test_prefix_cache_cow_tail_page(paged3):
    """A fully-cached prompt caps its hit one token short (the final
    prompt token must re-run to seed sampling), which copies the
    boundary page (copy-on-write) before appending — and stays
    bit-exact."""
    eng = paged3.reset()
    sysp = _tokens(16, seed=42)              # exactly two pages
    cold = _trace_outputs(eng, [Request(request_id="cold",
                                        tokens=list(sysp),
                                        max_new_tokens=4)])
    warm = _trace_outputs(eng, [Request(request_id="warm",
                                        tokens=list(sysp),
                                        max_new_tokens=4)])
    # 1 full page shared + COW of the second: 15 of 16 tokens reused
    assert eng.prefix_hits == 1 and eng.prefix_hit_tokens == 15
    assert warm["warm"]["generated"] == cold["cold"]["generated"]
    # the index's read-only page survived the COW append untouched: a
    # third identical prompt hits the same 15 tokens again
    warm2 = _trace_outputs(eng, [Request(request_id="w2",
                                         tokens=list(sysp),
                                         max_new_tokens=4)])
    assert eng.prefix_hit_tokens == 30
    assert warm2["w2"]["generated"] == cold["cold"]["generated"]


def test_engine_reset_clears_pool_and_prefix_index(paged3):
    """Satellite regression: reset() must return every page to the free
    list and drop the prefix index — tests share compiled engines across
    scenarios, and a leaked refcount would poison the next one."""
    eng = paged3.reset()
    sysp = _tokens(16, seed=42)
    first = _trace_outputs(eng, [
        Request(request_id=f"r{i}", tokens=sysp + _tokens(3, seed=i),
                max_new_tokens=3) for i in range(2)])
    # completed requests released their pages; the index still pins the
    # shared prefix pages — exactly what reset() must reclaim
    assert len(eng.prefix) == 2
    assert eng.pool.free_count < eng.pool.capacity
    eng.reset()
    assert eng.pool.free_count == eng.pool.capacity
    assert all(rc == 0 for rc in eng.pool.refcount[1:])
    assert len(eng.prefix) == 0
    assert eng.prefix_hits == 0 and eng.prefill_calls == 0
    assert np.asarray(eng.cache.lengths).max() == 0
    # the scenario replays bit-identically on the reset engine
    again = _trace_outputs(eng, [
        Request(request_id=f"r{i}", tokens=sysp + _tokens(3, seed=i),
                max_new_tokens=3) for i in range(2)])
    assert {k: v["generated"] for k, v in again.items()} == \
           {k: v["generated"] for k, v in first.items()}


def test_paged_geometry_validation(params):
    """Bad pool geometry is a clear build-time ValueError, never a bad
    gather at trace time."""
    with pytest.raises(ValueError, match="divide"):
        _engine(params, page_size=5)              # 32 % 5 != 0
    with pytest.raises(ValueError, match="divide page_size"):
        _engine(params, page_size=8, block_k=16)  # chunk spans 2 pages
    with pytest.raises(ValueError, match="null page"):
        _engine(params, page_size=8, num_pages=4)  # < max_pages + 1
    with pytest.raises(ValueError, match="prefix_cache"):
        _engine(params, prefix_cache=True)        # needs the pool
    with pytest.raises(ValueError, match="num_pages"):
        _engine(params, num_pages=9)              # needs page_size


def test_overcommitted_pool_stalls_then_completes(slot8, params):
    """An overcommitted pool (the point of paging) admits what fits and
    stalls the queue head until completions free pages — the stall is
    charged to serve_page_alloc_fail (a timed cause distinct from
    queue_wait), every request completes, and outputs still match the
    slot oracle bit-for-bit."""
    base = _trace_outputs(slot8.reset(), _mixed_requests())
    # 5 allocatable pages against ~2-page reservations: two requests fit,
    # the third stalls on pages while a SLOT sits free — KV-bound, not
    # slot-bound (admission order shifts, per-slot greedy streams don't)
    eng = _engine(params, page_size=8, num_pages=6)
    stalls = []
    unsub = subscribe_events(
        lambda r: stalls.append(r)
        if r.get("event") == "serve_page_alloc_fail" else None)
    try:
        with GoodputLedger() as led:
            got = _trace_outputs(eng, _mixed_requests())
    finally:
        unsub()
    assert {k: v["generated"] for k, v in got.items()} == \
           {k: v["generated"] for k, v in base.items()}
    s = led.summary()
    assert s["events"].get("serve_page_alloc_fail", 0) >= 1
    assert s["lost_by_cause"].get("serve_page_alloc_fail", 0.0) > 0.0
    # every published stall is a REAL cross-tick window — an admission
    # that merely rides along while the head stays blocked must not
    # close-and-reopen the window as a spurious ~0s event
    assert all(e["seconds"] > 1e-4 for e in stalls), stalls
    assert eng.decode_traces == 1


def test_idle_tick_releases_pages_for_stalled_admission(params):
    """Review regression: when every running request terminates without
    a decode step following (an abort on an otherwise-idle tick), the
    deferred device-side eviction must still run — before the fix a
    paged engine's pages stayed refcounted, the queue head's page probe
    failed forever, and the scheduler livelocked with a free slot, a
    non-empty queue, and decode_steps pinned below max_steps."""
    eng = _engine(params, num_slots=2, page_size=8, num_pages=6)
    sched = ServeScheduler(eng)
    # the hog reserves 4 of the 5 allocatable pages
    sched.submit(Request(request_id="hog", tokens=_tokens(10),
                         max_new_tokens=20))
    sched.step()
    sched.submit(Request(request_id="r1", tokens=_tokens(9, seed=3),
                         max_new_tokens=4))
    assert sched.abort("hog") is True
    # bounded manual ticks (never run(): the pre-fix failure mode is an
    # unbounded loop) — r1 must get the hog's pages and complete
    for _ in range(40):
        if not sched.step():
            break
    recs = {r["request_id"]: r for r in sched.stats().requests}
    assert recs["r1"]["state"] == "completed", recs
    assert recs["hog"]["state"] == "evicted"


def test_admission_probe_protects_batch_hits(params):
    """Review hardening: the admission probe threads a protect set
    across a batch, so a page one member plans to share is never counted
    as evictable headroom for a later member — otherwise prefill (which
    protects the whole batch's hits from eviction) would free fewer
    pages than the probes assumed and fail allocation mid-batch."""
    eng = _engine(params, num_slots=3, max_len=16, page_size=8,
                  num_pages=5, prefix_cache=True)
    p1, p2 = _tokens(8, seed=21), _tokens(8, seed=22)
    _trace_outputs(eng, [
        Request(request_id="s1", tokens=p1 + [1], max_new_tokens=1),
        Request(request_id="s2", tokens=p2 + [2], max_new_tokens=1)])
    assert len(eng.prefix) == 2 and eng.pool.free_count == 2
    # hold the remaining free pages in a live slot: every further page
    # must now come from evicting an index entry
    eng.prefill({0: _tokens(9, seed=30)}, budgets={0: 1})
    assert eng.pool.free_count == 0
    protect: set = set()
    # member 1 hits p1's page and takes the last evictable (p2's) as
    # its fresh page
    c1 = eng.admission_page_cost(p1 + [5, 6], 1, 0, protect=protect)
    assert c1 == 1 and protect
    # member 2 needs one page; p1's page must NOT count as its headroom
    # (member 1 is sharing it) — before the fix this probe passed and
    # prefill raised PagePoolExhausted mid-batch
    assert eng.admission_page_cost(_tokens(6, seed=31), 1, c1,
                                   protect=protect) is None


def test_stall_window_closes_when_stalled_head_leaves_queue(params):
    """Review regression: a queue head stalled on pages that then leaves
    the queue WITHOUT being admitted (abort here; deadline expiry and
    load shedding share ``_stall_head_removed``) must close-and-charge
    the stall window at its departure — before the fix the window stayed
    open and the NEXT admission charged the whole intervening idle span
    to ``serve_page_alloc_fail`` as phantom lost capacity."""
    eng = _engine(params, num_slots=2, page_size=8, num_pages=6)
    sched = ServeScheduler(eng)
    stalls = []
    unsub = subscribe_events(
        lambda r: stalls.append(r)
        if r.get("event") == "serve_page_alloc_fail" else None)
    try:
        # the hog reserves 4 of the 5 allocatable pages; "big" needs 2
        # pages and stalls at the head
        sched.submit(Request(request_id="hog", tokens=_tokens(10),
                             max_new_tokens=20))
        sched.step()
        sched.submit(Request(request_id="big", tokens=_tokens(9, seed=3),
                             max_new_tokens=4))
        sched.step()
        assert sched._alloc_stall_t0 is not None   # window open
        time.sleep(0.03)                           # real blocked span
        assert sched.abort("big") is True
        # closed AT removal: the blocked span is charged, nothing after
        assert sched._alloc_stall_t0 is None
        assert len(stalls) == 1 and stalls[0]["seconds"] >= 0.03
        time.sleep(0.2)                            # idle, pool unchanged
        # "late" fits the remaining free page and admits instantly: no
        # second stall event, and in particular none spanning the idle
        sched.submit(Request(request_id="late", tokens=_tokens(3, seed=4),
                             max_new_tokens=2))
        for _ in range(60):
            if not sched.step():
                break
    finally:
        unsub()
    recs = {r["request_id"]: r for r in sched.stats().requests}
    assert recs["late"]["state"] == "completed", recs
    assert len(stalls) == 1, stalls
    assert eng.decode_traces == 1


def test_combine_chunks_fetches_each_chunk_once():
    """Review perf regression: ``_combine_chunks`` materializes each
    chunk's (K, V) exactly once — a second ``fetch(i)`` per chunk traced
    four page-table gathers where two suffice (and actually executed
    them under interpret=True)."""
    from apex_tpu.serve.attention import _combine_chunks, cached_attention

    rng = np.random.RandomState(0)
    k = rng.randn(2, 16, 2, 4).astype(np.float32)
    v = rng.randn(2, 16, 2, 4).astype(np.float32)
    q = jnp.asarray(rng.randn(2, 2, 4).astype(np.float32))
    pos = jnp.asarray([5, 9], dtype=jnp.int32)
    calls = []

    def fetch(i):
        calls.append(i)
        sl = slice(i * 4, (i + 1) * 4)
        return jnp.asarray(k[:, sl]), jnp.asarray(v[:, sl])

    out = _combine_chunks(q, pos, 16, 4, jnp.float32(0.5), fetch)
    assert sorted(calls) == [0, 1, 2, 3], calls    # once per chunk
    # and the single-fetch path is the SAME numbers the public slot
    # entry point produces at the same block_k
    ref = cached_attention(q, jnp.asarray(k), jnp.asarray(v), pos,
                           scale=0.5, block_k=4)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_plan_admission_empty_prompt():
    """Review regression: an empty prompt (legal on the slot path — only
    ``ServeScheduler.submit`` rejects it) must plan zero shared tokens
    instead of ``use=-1`` whose tail-page remainder indexed ``hits[-1]``
    on an empty hit list."""
    from apex_tpu.serve import paging

    for idx in (None, paging.PrefixIndex(page_size=8)):
        plan = paging.plan_admission([], 4, 32, 8, idx)
        assert plan["use"] == 0 and plan["shared_pages"] == 0
        assert plan["cow_src"] is None and plan["tail"] == []
        assert plan["hits"] == []
        assert plan["new_pages"] == plan["total_pages"] >= 1


def test_decode_attention_page_geometry_registered():
    """Satellite: page_size is a shape-key axis of the decode_attention
    autotuner (slot=0 and paged winners never collide), candidates must
    divide the page, and CODE_VERSIONS invalidates v1 slot-only
    entries."""
    from apex_tpu.tune import CODE_VERSIONS
    from apex_tpu.tune import registry

    assert CODE_VERSIONS["decode_attention"] >= 2
    spec = registry.spec("decode_attention")
    k_slot = spec.shape_key({"max_len": 64, "heads": 2, "d": 8})
    k_paged = spec.shape_key({"max_len": 64, "page_size": 16,
                              "heads": 2, "d": 8})
    assert k_slot != k_paged
    assert ("page_size", 0) in k_slot and ("page_size", 16) in k_paged
    paged_shape = {"b": 2, "max_len": 64, "page_size": 16,
                   "heads": 2, "d": 8}
    cands = spec.candidates(paged_shape)
    assert cands and all(16 % c["block_k"] == 0 for c in cands)
    assert spec.defaults(paged_shape) in cands
    # the registry's default shapes warm BOTH layouts
    assert any(s.get("page_size") for s in spec.default_shapes)
    # the paged build runs the real page-table gather path
    p = spec.defaults(paged_shape)
    step, q, consts = spec.build(paged_shape, jnp.float32, p)
    assert step(0, q, *consts).shape == q.shape


# ------------------------------------------------------------ CLIs

def _cli_env():
    env = dict(os.environ)
    kept = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(kept + [ROOT])
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_serve_cli_smoke(tmp_path):
    """The scripted-serve acceptance: one CLI run with --trace-jsonl
    yields a Perfetto-loadable trace where every completed request has
    exactly one trace with queue/prefill/decode/complete spans — and the
    run still compiles decode exactly once."""
    tpath = str(tmp_path / "serve_trace.json")
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.serve.cli", "--config", "tiny",
         "--requests", "3", "--prompt-len", "4", "--max-new-tokens", "4",
         "--num-slots", "2", "--max-len", "32", "--temperature", "0",
         "--aot", "--trace-jsonl", tpath],
        cwd=ROOT, env=_cli_env(), capture_output=True, text=True,
        timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
    recs, summary = lines[:-1], lines[-1]
    assert len(recs) == 3
    assert all(rec["state"] == "completed" for rec in recs)
    assert summary["decode_compiles"] == 1
    assert summary["summary"]["new_tokens"] == 12

    from apex_tpu.monitor.trace import read_chrome_trace

    events = read_chrome_trace(tpath)           # strict JSON when closed
    xs = [e for e in events if e.get("ph") == "X"]
    per_trace = {}
    for e in xs:
        per_trace.setdefault(e["args"]["trace_id"], set()).add(e["name"])
    for rec in recs:
        spans = per_trace[f"request:{rec['request_id']}"]
        assert spans == {"request", "queue", "prefill", "decode",
                         "complete"}, rec["request_id"]
        # durations reconcile with the CLI's own accounting (±1 tick)
        root = next(e for e in xs
                    if e["args"]["trace_id"]
                    == f"request:{rec['request_id']}"
                    and e["name"] == "request")
        tick_ms = summary["summary"]["p99_step_ms"] + 1.0
        assert abs(root["dur"] / 1e3 - rec["latency_s"] * 1e3) <= tick_ms
    assert "serve:scheduler" in per_trace       # the tick track


@pytest.mark.slow
def test_serve_cli_stdin_stream():
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.serve.cli", "--stdin",
         "--max-new-tokens", "2", "--num-slots", "2", "--max-len", "32",
         "--temperature", "0"],
        input="1 2 3\n7, 8, 9, 10\n", cwd=ROOT, env=_cli_env(),
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
    assert len(lines) == 3
    assert {rec["prompt_tokens"] for rec in lines[:-1]} == {3, 4}


def test_serve_cli_rejects_bad_tokens():
    # input validation runs BEFORE params/compile: this fails fast
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.serve.cli", "--stdin",
         "--config", "tiny"],
        input="999999\n", cwd=ROOT, env=_cli_env(), capture_output=True,
        text=True, timeout=600)
    assert r.returncode == 2
    assert "vocab" in r.stderr


def test_bench_serve_smoke_and_regression_gate(tmp_path, capsys):
    """``apex-tpu-bench --serve`` emits the BENCH_SUITE shape; the
    regression gate compares it direction-aware (latency lower-is-better,
    throughput higher-is-better). In-process (the CLI smoke above covers
    the subprocess entry; a second jax import would only burn budget)."""
    from apex_tpu.bench_cli import _serve_bench

    _serve_bench(steps=6, num_slots=2)
    suite = json.loads(capsys.readouterr().out)
    entry = suite["serve_decode"]
    assert entry["value"] > 0 and entry["unit"] == "tokens_per_s"
    for k in ("p50_ms", "p99_ms", "ttft_ms"):
        assert entry[k] >= 0
    # capture provenance is stamped (device-kind gate satellite): on this
    # CPU harness the capture must say so
    for k in ("device_kind", "interpret_mode", "git", "captured"):
        assert k in suite, k
    assert suite["interpret_mode"] is True

    base = dict(suite)
    path_cur = tmp_path / "cur.json"
    path_base = tmp_path / "base.json"
    path_cur.write_text(json.dumps(suite))
    path_base.write_text(json.dumps(base))

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_regression
    finally:
        sys.path.pop(0)
    # identical capture: gate passes
    assert check_regression.main([str(path_cur), "--suite",
                                  str(path_base),
                                  "--kernels", "serve_decode"]) == 0
    # direction-aware: higher latency AND lower throughput both regress
    worse = json.loads(json.dumps(suite))
    worse["serve_decode"]["p99_ms"] = entry["p99_ms"] * 10 + 1
    worse["serve_decode"]["value"] = entry["value"] / 10
    path_cur.write_text(json.dumps(worse))
    assert check_regression.main([str(path_cur), "--suite",
                                  str(path_base),
                                  "--kernels", "serve_decode"]) == 1
    # ...and a FASTER capture (lower latency, higher tokens/s) passes
    better = json.loads(json.dumps(suite))
    better["serve_decode"]["p99_ms"] = entry["p99_ms"] / 10
    better["serve_decode"]["value"] = entry["value"] * 10
    path_cur.write_text(json.dumps(better))
    assert check_regression.main([str(path_cur), "--suite",
                                  str(path_base),
                                  "--kernels", "serve_decode"]) == 0
    # SLO counters gate from a ZERO baseline: the healthy default
    # workload ships rejected=0/shed_rate=0, and a capture that starts
    # shedding must regress — a base==0 ratio skip would let it ship
    assert entry["rejected"] == 0 and entry["shed_rate"] == 0.0
    shedding = json.loads(json.dumps(suite))
    shedding["serve_decode"]["rejected"] = 5
    shedding["serve_decode"]["shed_rate"] = 0.31
    path_cur.write_text(json.dumps(shedding))
    assert check_regression.main([str(path_cur), "--suite",
                                  str(path_base),
                                  "--kernels", "serve_decode"]) == 1


def test_serve_cli_paged_usage_errors(capsys):
    """``apex-tpu-serve --page-size --prefix-cache``: bad geometry is a
    clean usage error — exit 2 before anything compiles."""
    from apex_tpu.serve import cli

    # pool geometry that can't exist: exit 2 + the engine's message
    assert cli.main(["--config", "tiny", "--max-len", "32",
                     "--page-size", "7", "--requests", "1"]) == 2
    assert "divide" in capsys.readouterr().err
    # --prefix-cache without --page-size: same clean refusal
    assert cli.main(["--config", "tiny", "--max-len", "32",
                     "--prefix-cache", "--requests", "1"]) == 2
    assert "prefix_cache" in capsys.readouterr().err


@pytest.mark.slow
def test_serve_cli_paged_smoke(capsys, monkeypatch):
    """A shared-prefix stdin stream serves through the paged CLI with
    one decode compile and a real prefix hit. In-process (the subprocess
    smoke above covers the entry point). Slow tier: the paged engine
    compile (~11s) duplicates layout coverage the paged-vs-slot
    bit-exact tests keep in tier-1; the CLI flag plumbing stays tier-1
    via ``test_serve_cli_paged_usage_errors``."""
    import io

    from apex_tpu.serve import cli

    # one slot serializes the two requests, so the second admission sees
    # the first's prompt pages resident: a real end-to-end prefix hit
    prefix = " ".join(str(t) for t in range(1, 9))     # one full page
    monkeypatch.setattr("sys.stdin", io.StringIO(
        f"{prefix} 11\n{prefix} 12\n"))
    rc = cli.main(["--config", "tiny", "--stdin", "--max-len", "32",
                   "--num-slots", "1", "--max-new-tokens", "2",
                   "--temperature", "0", "--page-size", "8",
                   "--prefix-cache"])
    assert rc == 0
    lines = [json.loads(l)
             for l in capsys.readouterr().out.strip().splitlines()]
    recs, summary = lines[:-1], lines[-1]
    assert all(rec["state"] == "completed" for rec in recs)
    assert summary["decode_compiles"] == 1
    assert summary["summary"]["prefix_hits"] == 1
    assert summary["summary"]["prefix_hit_rate"] == 0.5
    assert summary["summary"]["peak_resident_tokens"] > 0


def test_serve_bench_usage_errors_exit_clean():
    """Review regression: bad pool geometry and a malformed
    ``--prompt-len`` spec are usage errors — one clean message via
    SystemExit (like the adjacent shared-prefix check), never a raw
    ValueError traceback."""
    from apex_tpu.bench_cli import _serve_bench

    with pytest.raises(SystemExit, match="page_size=7 must"):
        _serve_bench(steps=2, max_len=32, page_size=7)
    with pytest.raises(SystemExit, match="--prompt-len"):
        _serve_bench(steps=2, prompt_len="0:4")


@pytest.mark.slow
def test_paged_bench_capacity_and_gate(tmp_path, capsys):
    """ISSUE 9 bench acceptance: on a mixed-length shared-prefix
    workload, the paged capture shows >= 2x resident tokens per HBM byte
    vs the slot capture at the same workload, prefix_hit_rate > 0, and
    the capture gates through check_regression with page_size provenance
    (a lower hit rate regresses).

    Slow tier: two full ``_serve_bench`` compiles at max_len=128 are the
    single heaviest tier-1 item (~48s); the regression-gate direction
    coverage stays in tier-1 via
    ``test_bench_serve_smoke_and_regression_gate`` and the paged
    layout's correctness via the paged-vs-slot bit-exact tests."""
    from apex_tpu.bench_cli import _serve_bench

    # mixed 8..24-token prompts + a 16-token fleet-wide system prefix on
    # a max_len=128 context: the slot layout reserves 128 tokens/slot
    # for ~48-token requests — the waste paging reclaims
    kw = dict(steps=16, num_slots=4, max_len=128, prompt_len="8:24",
              shared_prefix=16)
    _serve_bench(**kw)
    slot = json.loads(capsys.readouterr().out)["serve_decode"]
    # equal workload, pool sized to the actual working set: 4 slots x 4
    # own pages + 2 shared prefix pages + the null page
    _serve_bench(**kw, page_size=8, num_pages=19, prefix_cache=True)
    suite = json.loads(capsys.readouterr().out)
    paged = suite["serve_decode"]

    assert paged["prefix_hit_rate"] > 0.0
    assert slot["prefix_hit_rate"] == 0.0
    assert paged["resident_tokens_per_hbm_byte"] >= \
        2.0 * slot["resident_tokens_per_hbm_byte"], \
        "paging must multiply resident-token capacity per HBM byte"
    # provenance: the pool geometry rides the workload record, so SLO/
    # capacity numbers are never gated across incomparable configs
    assert paged["workload"]["page_size"] == 8
    assert paged["workload"]["prefix_cache"] is True
    assert paged["workload"]["shared_prefix"] == 16
    assert slot["workload"]["page_size"] == 0

    path_cur = tmp_path / "cur.json"
    path_base = tmp_path / "base.json"
    path_base.write_text(json.dumps(suite))
    path_cur.write_text(json.dumps(suite))
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_regression
    finally:
        sys.path.pop(0)
    assert check_regression.main([str(path_cur), "--suite",
                                  str(path_base),
                                  "--kernels", "serve_decode"]) == 0
    # prefix_hit_rate is higher-is-better: losing the hits regresses
    worse = json.loads(json.dumps(suite))
    worse["serve_decode"]["prefix_hit_rate"] = \
        paged["prefix_hit_rate"] * 0.2
    path_cur.write_text(json.dumps(worse))
    assert check_regression.main([str(path_cur), "--suite",
                                  str(path_base),
                                  "--kernels", "serve_decode"]) == 1


# --------------------------------------------- gpt2 position offsets

def test_gpt2_learned_position_offset_parity(params):
    """GPT2(position_offset=k) reads wpe[k:k+s] — proven by rolling the
    embedding table: a model whose wpe is pre-shifted by k at offset 0
    equals the original model at offset k."""
    from apex_tpu.models.gpt2 import GPT2

    model = GPT2(CFG)
    tokens = jnp.asarray(np.array([_tokens(6, seed=5)], np.int32))
    k = 9
    inner = dict(params["params"])
    wpe = np.asarray(params["params"]["wpe"])
    inner["wpe"] = jnp.asarray(np.roll(wpe, -k, axis=0))
    shifted = {"params": inner}
    a = model.apply(params, tokens, position_offset=k)
    b = model.apply(shifted, tokens)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # (traced offsets are exercised by the serve engine itself: prefill
    # passes scan-carried positions through the same wpe slice)
