"""Serving engine tier-1: static-shape KV cache, one-jit decode,
continuous batching.

The acceptance claims under test:

- **parity** — incremental decode logits are bit-identical (fp32) to
  full-sequence prefill logits: prefill and decode share ONE single-token
  forward at one fixed ``[num_slots]`` shape, so there is no second
  numeric path to drift;
- **one compile** — a scripted trace that admits, completes, evicts, and
  backfills requests mid-stream traces ``decode_step`` exactly once
  (``Engine.decode_traces``);
- **isolation** — a FaultInjector-scripted mid-stream abort leaves every
  other request's token stream bit-identical (per-slot reductions cannot
  see other slots' bytes);
- termination (EOS / max-new-tokens / context), greedy + seeded-sampling
  determinism, the serve bench + regression gate, and both CLIs.

Engines are compiled once per geometry and shared across tests via
``Engine.reset()`` (state drop, zero recompiles — itself part of the
serving contract); the one-jit acceptance tests get fresh engines so
their trace counters stay airtight.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.monitor.goodput import GoodputLedger
from apex_tpu.resilience.fault_injection import FaultInjector
from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
from apex_tpu.serve.kv_cache import init_cache, write_token
from apex_tpu.serve.scheduler import Request, ServeScheduler
# bound at collection time: test_chip_worker purges apex_tpu.* from
# sys.modules mid-session, and a function-local re-import after that
# would subscribe to a FRESH bus while the (old) scheduler module keeps
# publishing to the original one
from apex_tpu.utils.logging import subscribe_events

pytestmark = pytest.mark.serve

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = GPT2Config(vocab_size=97, n_positions=64, n_embd=32, n_layer=2,
                 n_head=2, compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_gpt2_params(CFG, seed=0)


def _engine(params, **kw):
    kw.setdefault("num_slots", 3)
    kw.setdefault("max_len", 32)
    kw.setdefault("temperature", 0.0)
    seed = kw.pop("seed", 0)
    return Engine(CFG, params, EngineConfig(**kw), seed=seed)


@pytest.fixture(scope="module")
def greedy3(params):
    """Shared greedy 3-slot engine; tests reset() it — compiled once."""
    return _engine(params)


@pytest.fixture(scope="module")
def greedy2(params):
    return _engine(params, num_slots=2)


@pytest.fixture(scope="module")
def keeper3(params):
    """3-slot greedy engine that keeps per-position prefill logits."""
    return _engine(params, keep_prefill_logits=True)


def _tokens(n, seed=7, vocab=97):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, vocab, n)]


# ------------------------------------------------------------ kv cache

def test_kv_cache_ops_are_static_and_masked():
    cache = init_cache(n_layer=2, num_slots=4, max_len=16, heads=2,
                       head_dim=8)
    k = jnp.ones((4, 2, 8)) * jnp.arange(1, 5)[:, None, None]
    pos = jnp.zeros((4,), jnp.int32)
    mask = jnp.array([True, False, True, False])
    out = jax.jit(write_token, static_argnums=1)(cache, 0, k, k, pos, mask)
    assert out.k.shape == cache.k.shape  # static shapes, whatever the mask
    got = np.asarray(out.k[0, :, 0, 0, 0])
    np.testing.assert_array_equal(got, [1.0, 0.0, 3.0, 0.0])
    # masked-off slots' bytes are bit-untouched
    np.testing.assert_array_equal(np.asarray(out.k[0, 1]),
                                  np.asarray(cache.k[0, 1]))


# -------------------------------------------------------------- parity

def test_prefill_vs_incremental_decode_bit_exact(greedy3, keeper3):
    """THE serving invariant: decode token j's logits == full prefill's
    position-j logits, bit-for-bit in fp32."""
    seq = _tokens(12)
    _, _, all_logits = keeper3.reset().prefill({1: seq})
    all_logits = np.asarray(all_logits)          # [P, B, V]

    inc = greedy3.reset()
    inc.prefill({1: seq[:5]})
    for j in range(5, len(seq)):
        forced = np.array([0, seq[j], 0], np.int32)
        _, logits = inc.decode_step(forced, np.array([False, True, False]))
        a, b = all_logits[j, 1], np.asarray(logits)[1]
        assert a.dtype == np.float32
        assert np.array_equal(a, b), \
            f"decode pos {j} drifted: max|d|={np.abs(a - b).max()}"
    assert inc.lengths[1] == len(seq)


def test_prefill_last_logits_match_kept_logits(keeper3):
    seq = _tokens(9, seed=3)
    _, last, all_logits = keeper3.reset().prefill({0: seq})
    np.testing.assert_array_equal(np.asarray(last)[0],
                                  np.asarray(all_logits)[len(seq) - 1, 0])


# ----------------------------------------------------- one-jit invariant

def test_decode_compiles_once_across_admit_evict_backfill(params):
    """Scripted multi-request trace — staggered admissions, completions,
    a mid-stream abort, and backfill — compiles decode_step exactly once
    and one prefill per prompt bucket. Fresh engine: the trace counters
    are the assertion."""
    eng = _engine(params, num_slots=2)
    inj = FaultInjector(seed=0).abort_request("r2", at_step=4)
    sched = ServeScheduler(eng, fault_injector=inj)
    for i, plen in enumerate((4, 6, 5, 3, 7)):
        sched.submit(Request(request_id=f"r{i}",
                             tokens=_tokens(plen, seed=i),
                             max_new_tokens=4 + i % 3))
    stats = sched.run()
    assert len(stats.requests) == 5
    assert {r["state"] for r in stats.requests} == {"completed", "evicted"}
    assert eng.decode_traces == 1, \
        "slot membership changes must not retrace decode_step"
    # prompts bucket to pow2: {4, 8} at most
    assert eng.prefill_traces <= 2


def test_aot_compile_then_serve_traces_once(params):
    eng = _engine(params, num_slots=2).aot_compile(prompt_buckets=[8])
    assert eng.decode_traces == 1
    sched = ServeScheduler(eng)
    for i in range(3):
        sched.submit(Request(request_id=i, tokens=_tokens(6, seed=i),
                             max_new_tokens=3))
    sched.run()
    assert eng.decode_traces == 1      # served entirely from the AOT exe
    assert eng.prefill_traces == 1
    # reset drops state but keeps the compiled artifacts
    eng.reset()
    assert np.asarray(eng.cache.lengths).max() == 0
    sched = ServeScheduler(eng)
    sched.submit(Request(request_id="again", tokens=_tokens(6),
                         max_new_tokens=2))
    sched.run()
    assert eng.decode_traces == 1 and eng.prefill_traces == 1


# --------------------------------------------------------- termination

def test_eos_terminates_request(greedy2):
    # greedy decode is deterministic: discover the first generated token,
    # then rerun with that token as EOS — must stop after exactly 1 token
    sched = ServeScheduler(greedy2.reset())
    sched.submit(Request(request_id="probe", tokens=_tokens(5),
                         max_new_tokens=4))
    first = sched.run().requests[0]["generated"][0]

    sched2 = ServeScheduler(greedy2.reset())
    sched2.submit(Request(request_id="eos", tokens=_tokens(5),
                          max_new_tokens=16, eos_id=int(first)))
    rec = sched2.run().requests[0]
    assert rec["finish_reason"] == "eos"
    assert rec["new_tokens"] == 1
    assert rec["generated"][-1] == int(first)


def test_max_new_tokens_terminates(greedy3):
    sched = ServeScheduler(greedy3.reset())
    sched.submit(Request(request_id=0, tokens=_tokens(5),
                         max_new_tokens=5))
    rec = sched.run().requests[0]
    assert rec["finish_reason"] == "length"
    assert rec["new_tokens"] == 5


def test_context_full_terminates(greedy2):
    eng = greedy2.reset()
    sched = ServeScheduler(eng)
    sched.submit(Request(request_id=0, tokens=_tokens(28),
                         max_new_tokens=100))
    rec = sched.run().requests[0]
    assert rec["finish_reason"] == "context"
    assert rec["new_tokens"] == 4          # 28 + 4 == max_len == 32
    # slot freed at completion: lengths reset
    assert eng.lengths.max() == 0
    # the RAW engine refuses to decode a context-full slot (a clipped
    # cache write would silently corrupt the newest K/V row)
    eng.reset()
    eng.prefill({0: _tokens(31)})
    eng.decode_step(eng.last_tokens, np.array([True, False]))  # -> 32
    with pytest.raises(ValueError, match="max_len"):
        eng.decode_step(eng.last_tokens, np.array([True, False]))


def test_oversized_prompt_rejected(greedy2):
    sched = ServeScheduler(greedy2.reset())
    with pytest.raises(ValueError, match="no room"):
        sched.submit(Request(request_id=0, tokens=_tokens(32)))
    with pytest.raises(ValueError, match="empty"):
        sched.submit(Request(request_id=1, tokens=[]))


# ----------------------------------------------- eviction isolation

def _run_trace(eng, injector=None, n=4):
    sched = ServeScheduler(eng.reset(), fault_injector=injector)
    for i in range(n):
        sched.submit(Request(request_id=f"r{i}", tokens=_tokens(5, seed=i),
                             max_new_tokens=6))
    sched.run()
    return {r["request_id"]: r for r in sched.stats().requests}


@pytest.mark.fault
def test_mid_stream_abort_leaves_other_slots_bit_identical(greedy2):
    """FaultInjector aborts r1 mid-decode; every other request's token
    stream must match the abort-free run bit-for-bit (static shapes make
    slot arithmetic independent of slot membership)."""
    base = _run_trace(greedy2)
    inj = FaultInjector(seed=0).abort_request("r1", at_step=2)
    with GoodputLedger() as led:
        faulted = _run_trace(greedy2, injector=inj)
    assert faulted["r1"]["state"] == "evicted"
    assert faulted["r1"]["finish_reason"] == "aborted"
    for rid in ("r0", "r2", "r3"):
        assert faulted[rid]["state"] == "completed"
        assert faulted[rid]["generated"] == base[rid]["generated"], rid
    assert led.summary()["events"]["serve_request_evicted"] == 1


@pytest.mark.fault
def test_abort_of_still_queued_request(greedy2):
    """Satellite regression (PR 8): aborting a request that was never
    admitted must remove it from the queue, account it exactly once,
    publish the abort event — and charge its wasted queue time as a
    ``serve_queue_wait`` loss (previously the wait silently vanished).
    Both entry points: a direct cross-thread-style abort() call and the
    FaultInjector-scripted path."""
    # direct call, before any tick: 3 requests, 2 slots -> "c" queued
    sched = ServeScheduler(greedy2.reset())
    for rid in ("a", "b", "c"):
        sched.submit(Request(request_id=rid, tokens=_tokens(5),
                             max_new_tokens=3))
    assert sched.abort("c") is True
    assert all(r.request_id != "c" for r in sched.queue)
    assert sched.abort("c") is False      # terminal: never re-accounted
    stats = sched.run()
    recs = {r["request_id"]: r for r in stats.requests}
    assert len(stats.requests) == 3
    assert recs["c"]["state"] == "evicted"
    assert recs["c"]["finish_reason"] == "aborted"
    assert recs["c"]["new_tokens"] == 0
    assert recs["a"]["state"] == recs["b"]["state"] == "completed"

    # injector path mid-run, with the event + queue-wait accounting
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r)
        if r.get("request_id") == "r2"
        and r.get("event") in ("serve_request_evicted",
                               "serve_queue_wait") else None)
    try:
        inj = FaultInjector(seed=0).abort_request("r2", at_step=1)
        sched = ServeScheduler(greedy2.reset(), fault_injector=inj)
        for i in range(3):
            sched.submit(Request(request_id=f"r{i}",
                                 tokens=_tokens(5, seed=i),
                                 max_new_tokens=4))
        stats = sched.run()
    finally:
        unsub()
    recs = {r["request_id"]: r for r in stats.requests}
    assert recs["r2"]["state"] == "evicted"
    assert recs["r2"]["finish_reason"] == "aborted"
    evicted = [r for r in seen if r["event"] == "serve_request_evicted"]
    waits = [r for r in seen if r["event"] == "serve_queue_wait"]
    assert len(evicted) == 1 and evicted[0]["reason"] == "aborted"
    assert len(waits) == 1 and waits[0]["seconds"] >= 0.0


# -------------------------------------------------------- determinism

def test_greedy_is_deterministic_and_argmax(greedy3, keeper3):
    seq = _tokens(6)
    first, last_logits, _ = keeper3.reset().prefill({0: seq})
    assert first[0] == int(np.asarray(last_logits)[0].argmax())
    runs = []
    for _ in range(2):
        s = ServeScheduler(greedy3.reset())
        s.submit(Request(request_id=0, tokens=seq, max_new_tokens=8))
        runs.append(s.run().requests[0]["generated"])
    assert runs[0] == runs[1]


def test_sampled_decode_replays_under_fixed_key(params):
    eng = _engine(params, temperature=0.8, top_k=5)

    def run(seed):
        s = ServeScheduler(eng.reset(seed))
        s.submit(Request(request_id=0, tokens=_tokens(6),
                         max_new_tokens=8))
        return s.run().requests[0]["generated"]

    assert run(1) == run(1)          # threaded PRNG: same seed, same stream
    assert run(1) != run(2)          # and the key actually matters


def test_top_k_restricts_to_top_k(params, keeper3):
    seq = _tokens(6)
    _, last_logits, _ = keeper3.reset().prefill({0: seq})
    top5 = set(np.argsort(np.asarray(last_logits)[0])[-5:].tolist())
    eng = _engine(params, temperature=1.5, top_k=5)
    for seed in range(2):
        first, _, _ = eng.reset(seed).prefill({0: seq})
        assert int(first[0]) in top5


# ------------------------------------------------------------ tracing

def test_request_traces_reconcile_with_stats(greedy2):
    """THE tracing acceptance: every completed request is exactly one
    trace with queue/prefill/decode/complete spans whose durations equal
    the scheduler's own TTFT/latency accounting (same clock reads), the
    scheduler trace carries one decode_tick per step, and tracing adds
    ZERO compiles (the one-jit invariant holds with it on)."""
    from apex_tpu.monitor import Tracer, spans_by_trace

    eng = greedy2.reset()
    tracer = Tracer()
    sched = ServeScheduler(eng, tracer=tracer)
    for i in range(4):
        sched.submit(Request(request_id=f"r{i}",
                             tokens=_tokens(5, seed=i), max_new_tokens=4))
    stats = sched.run()
    assert eng.decode_traces == 1          # tracing retraced nothing
    by_trace = spans_by_trace(tracer.completed_records())
    recs = {r["request_id"]: r for r in stats.requests}
    assert len(recs) == 4
    tol = 2e-3  # span stamps round to the microsecond; ttft to 1e-6 s
    for rid, rec in recs.items():
        spans = {s["name"]: s for s in by_trace[f"request:{rid}"]}
        assert set(spans) == {"request", "queue", "prefill", "decode",
                              "complete"}, rid
        q, p, d = spans["queue"], spans["prefill"], spans["decode"]
        root = spans["request"]
        assert abs((q["t1"] - q["t0"]) + (p["t1"] - p["t0"])
                   - rec["ttft_s"]) < tol
        assert abs((root["t1"] - root["t0"]) - rec["latency_s"]) < tol
        assert abs((d["t1"] - d["t0"])
                   - (rec["latency_s"] - rec["ttft_s"])) < tol
        assert root["attrs"]["new_tokens"] == rec["new_tokens"]
        for s in spans.values():
            assert s["status"] == "ok"
    ticks = [s for s in by_trace["serve:scheduler"]
             if s["name"] == "decode_tick"]
    assert len(ticks) == stats.decode_steps
    assert not tracer.open_spans()         # run() closed everything


@pytest.mark.fault
def test_aborted_request_trace_marks_abort(greedy2):
    from apex_tpu.monitor import Tracer, spans_by_trace

    tracer = Tracer()
    inj = FaultInjector(seed=0).abort_request("r1", at_step=2)
    sched = ServeScheduler(greedy2.reset(), fault_injector=inj,
                           tracer=tracer)
    for i in range(2):
        sched.submit(Request(request_id=f"r{i}",
                             tokens=_tokens(5, seed=i), max_new_tokens=6))
    sched.run()
    spans = {s["name"]: s for s in spans_by_trace(
        tracer.completed_records())["request:r1"]}
    assert "abort" in spans and "complete" not in spans
    assert spans["request"]["status"] == "cancelled"
    assert spans["request"]["attrs"]["finish_reason"] == "aborted"
    # the surviving request completed normally
    other = spans_by_trace(tracer.completed_records())["request:r0"]
    assert {s["name"] for s in other} >= {"request", "complete"}


def test_untraced_scheduler_publishes_no_spans(greedy3):
    """Tracing disabled (the default) adds nothing: no span records on
    the bus, no per-request bookkeeping, and — asserted everywhere else
    in this file — no extra compiles."""
    seen = []
    unsub = subscribe_events(
        lambda r: seen.append(r) if str(r.get("event", "")).startswith(
            "span_") else None)
    try:
        sched = ServeScheduler(greedy3.reset())
        sched.submit(Request(request_id=0, tokens=_tokens(5),
                             max_new_tokens=2))
        sched.run()
    finally:
        unsub()
    assert not seen
    assert sched.tracer is None and not sched._req_spans


# -------------------------------------------------- scheduler / events

def test_backfill_and_queue_wait_accounting(greedy2):
    with GoodputLedger() as led:
        sched = ServeScheduler(greedy2.reset())
        for i in range(5):
            sched.submit(Request(request_id=i, tokens=_tokens(5, seed=i),
                                 max_new_tokens=3))
        stats = sched.run()
    s = stats.summary()
    assert s["completed"] == 5
    g = led.summary()
    assert g["events"]["serve_request_admitted"] == 5
    assert g["events"]["serve_request_completed"] == 5
    assert g["events"]["serve_decode_step"] == stats.decode_steps
    # 3 of 5 requests waited for a slot: queue-wait is a goodput cause
    assert g["lost_by_cause"].get("serve_queue_wait", 0.0) > 0.0
    assert s["tokens_per_s"] > 0
    assert s["p99_step_ms"] >= s["p50_step_ms"] >= 0


def test_stats_record_shape(greedy3):
    sched = ServeScheduler(greedy3.reset())
    sched.submit(Request(request_id="x", tokens=_tokens(5),
                         max_new_tokens=2))
    rec = sched.run().requests[0]
    for key in ("request_id", "state", "finish_reason", "prompt_tokens",
                "new_tokens", "generated", "ttft_s", "latency_s",
                "tokens_per_s"):
        assert key in rec, key


# --------------------------------------------------- tuned geometry

def test_decode_attention_block_drives_geometry(params):
    """An explicit (valid) block_k changes the partial-reduction order but
    both engine paths share it — parity must survive the non-default
    geometry; an invalid one must be rejected loudly."""
    seq = _tokens(8)
    full = _engine(params, keep_prefill_logits=True, block_k=8)
    _, _, all_logits = full.prefill({1: seq})
    inc = _engine(params, block_k=8)
    inc.prefill({1: seq[:4]})
    for j in range(4, len(seq)):
        forced = np.array([0, seq[j], 0], np.int32)
        _, logits = inc.decode_step(forced,
                                    np.array([False, True, False]))
        assert np.array_equal(np.asarray(all_logits)[j, 1],
                              np.asarray(logits)[1])
    with pytest.raises(ValueError, match="divide"):
        _engine(params, block_k=7)


def test_decode_attention_registered_with_tune():
    from apex_tpu.tune import CODE_VERSIONS
    from apex_tpu.tune import registry

    assert "decode_attention" in CODE_VERSIONS
    spec = registry.spec("decode_attention")
    shape = dict(spec.default_shapes[0])
    cands = spec.candidates(shape)
    assert spec.defaults(shape) in cands
    # the build runs the real decode attention at a small geometry
    small = {"b": 2, "max_len": 64, "heads": 2, "d": 8}
    p = spec.defaults(small)
    step, state, consts = spec.build(small, jnp.float32, p)
    out = step(0, state, *consts)
    assert out.shape == state.shape


# ------------------------------------------------------------ CLIs

def _cli_env():
    env = dict(os.environ)
    kept = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(kept + [ROOT])
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_serve_cli_smoke(tmp_path):
    """The scripted-serve acceptance: one CLI run with --trace-jsonl
    yields a Perfetto-loadable trace where every completed request has
    exactly one trace with queue/prefill/decode/complete spans — and the
    run still compiles decode exactly once."""
    tpath = str(tmp_path / "serve_trace.json")
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.serve.cli", "--config", "tiny",
         "--requests", "3", "--prompt-len", "4", "--max-new-tokens", "4",
         "--num-slots", "2", "--max-len", "32", "--temperature", "0",
         "--aot", "--trace-jsonl", tpath],
        cwd=ROOT, env=_cli_env(), capture_output=True, text=True,
        timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
    recs, summary = lines[:-1], lines[-1]
    assert len(recs) == 3
    assert all(rec["state"] == "completed" for rec in recs)
    assert summary["decode_compiles"] == 1
    assert summary["summary"]["new_tokens"] == 12

    from apex_tpu.monitor.trace import read_chrome_trace

    events = read_chrome_trace(tpath)           # strict JSON when closed
    xs = [e for e in events if e.get("ph") == "X"]
    per_trace = {}
    for e in xs:
        per_trace.setdefault(e["args"]["trace_id"], set()).add(e["name"])
    for rec in recs:
        spans = per_trace[f"request:{rec['request_id']}"]
        assert spans == {"request", "queue", "prefill", "decode",
                         "complete"}, rec["request_id"]
        # durations reconcile with the CLI's own accounting (±1 tick)
        root = next(e for e in xs
                    if e["args"]["trace_id"]
                    == f"request:{rec['request_id']}"
                    and e["name"] == "request")
        tick_ms = summary["summary"]["p99_step_ms"] + 1.0
        assert abs(root["dur"] / 1e3 - rec["latency_s"] * 1e3) <= tick_ms
    assert "serve:scheduler" in per_trace       # the tick track


@pytest.mark.slow
def test_serve_cli_stdin_stream():
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.serve.cli", "--stdin",
         "--max-new-tokens", "2", "--num-slots", "2", "--max-len", "32",
         "--temperature", "0"],
        input="1 2 3\n7, 8, 9, 10\n", cwd=ROOT, env=_cli_env(),
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
    assert len(lines) == 3
    assert {rec["prompt_tokens"] for rec in lines[:-1]} == {3, 4}


def test_serve_cli_rejects_bad_tokens():
    # input validation runs BEFORE params/compile: this fails fast
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.serve.cli", "--stdin",
         "--config", "tiny"],
        input="999999\n", cwd=ROOT, env=_cli_env(), capture_output=True,
        text=True, timeout=600)
    assert r.returncode == 2
    assert "vocab" in r.stderr


def test_bench_serve_smoke_and_regression_gate(tmp_path, capsys):
    """``apex-tpu-bench --serve`` emits the BENCH_SUITE shape; the
    regression gate compares it direction-aware (latency lower-is-better,
    throughput higher-is-better). In-process (the CLI smoke above covers
    the subprocess entry; a second jax import would only burn budget)."""
    from apex_tpu.bench_cli import _serve_bench

    _serve_bench(steps=6, num_slots=2)
    suite = json.loads(capsys.readouterr().out)
    entry = suite["serve_decode"]
    assert entry["value"] > 0 and entry["unit"] == "tokens_per_s"
    for k in ("p50_ms", "p99_ms", "ttft_ms"):
        assert entry[k] >= 0
    # capture provenance is stamped (device-kind gate satellite): on this
    # CPU harness the capture must say so
    for k in ("device_kind", "interpret_mode", "git", "captured"):
        assert k in suite, k
    assert suite["interpret_mode"] is True

    base = dict(suite)
    path_cur = tmp_path / "cur.json"
    path_base = tmp_path / "base.json"
    path_cur.write_text(json.dumps(suite))
    path_base.write_text(json.dumps(base))

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_regression
    finally:
        sys.path.pop(0)
    # identical capture: gate passes
    assert check_regression.main([str(path_cur), "--suite",
                                  str(path_base),
                                  "--kernels", "serve_decode"]) == 0
    # direction-aware: higher latency AND lower throughput both regress
    worse = json.loads(json.dumps(suite))
    worse["serve_decode"]["p99_ms"] = entry["p99_ms"] * 10 + 1
    worse["serve_decode"]["value"] = entry["value"] / 10
    path_cur.write_text(json.dumps(worse))
    assert check_regression.main([str(path_cur), "--suite",
                                  str(path_base),
                                  "--kernels", "serve_decode"]) == 1
    # ...and a FASTER capture (lower latency, higher tokens/s) passes
    better = json.loads(json.dumps(suite))
    better["serve_decode"]["p99_ms"] = entry["p99_ms"] / 10
    better["serve_decode"]["value"] = entry["value"] * 10
    path_cur.write_text(json.dumps(better))
    assert check_regression.main([str(path_cur), "--suite",
                                  str(path_base),
                                  "--kernels", "serve_decode"]) == 0
    # SLO counters gate from a ZERO baseline: the healthy default
    # workload ships rejected=0/shed_rate=0, and a capture that starts
    # shedding must regress — a base==0 ratio skip would let it ship
    assert entry["rejected"] == 0 and entry["shed_rate"] == 0.0
    shedding = json.loads(json.dumps(suite))
    shedding["serve_decode"]["rejected"] = 5
    shedding["serve_decode"]["shed_rate"] = 0.31
    path_cur.write_text(json.dumps(shedding))
    assert check_regression.main([str(path_cur), "--suite",
                                  str(path_base),
                                  "--kernels", "serve_decode"]) == 1


# --------------------------------------------- gpt2 position offsets

def test_gpt2_learned_position_offset_parity(params):
    """GPT2(position_offset=k) reads wpe[k:k+s] — proven by rolling the
    embedding table: a model whose wpe is pre-shifted by k at offset 0
    equals the original model at offset k."""
    from apex_tpu.models.gpt2 import GPT2

    model = GPT2(CFG)
    tokens = jnp.asarray(np.array([_tokens(6, seed=5)], np.int32))
    k = 9
    inner = dict(params["params"])
    wpe = np.asarray(params["params"]["wpe"])
    inner["wpe"] = jnp.asarray(np.roll(wpe, -k, axis=0))
    shifted = {"params": inner}
    a = model.apply(params, tokens, position_offset=k)
    b = model.apply(shifted, tokens)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # (traced offsets are exercised by the serve engine itself: prefill
    # passes scan-carried positions through the same wpe slice)
