"""Native helper library tests (apex_tpu/_csrc) — native vs Python-fallback
bit-parity for the planners, and roundtrip for the packers."""

import numpy as np
import pytest

from apex_tpu._native import api as napi
from apex_tpu._native.build import get_lib, native_available


class TestPlanners:
    def test_native_compiles(self):
        # g++ is baked into the image; the native path must actually build
        assert native_available()

    def test_plan_flat_matches_python(self, monkeypatch):
        sizes = [37, 1, 0, 576, 128, 129]
        n_off, n_pad, n_tot = napi.plan_flat(sizes)
        monkeypatch.setattr("apex_tpu._native.api.get_lib", lambda: None)
        p_off, p_pad, p_tot = napi.plan_flat(sizes)
        np.testing.assert_array_equal(n_off, p_off)
        np.testing.assert_array_equal(n_pad, p_pad)
        assert n_tot == p_tot

    def test_plan_buckets_matches_python(self, monkeypatch):
        sizes = [10, 20, 10, 30, 5, 100]
        dts = [0, 1, 0, 1, 0, 0]
        n_ids, n_nb = napi.plan_buckets(sizes, dts, 15)
        monkeypatch.setattr("apex_tpu._native.api.get_lib", lambda: None)
        p_ids, p_nb = napi.plan_buckets(sizes, dts, 15)
        np.testing.assert_array_equal(n_ids, p_ids)
        assert n_nb == p_nb

    def test_fragments_cover_leaves_exactly(self):
        offsets = [0, 128, 256, 896]
        sizes = [100, 128, 600, 64]
        fr = napi.plan_fragments(offsets, sizes, 256)
        # every leaf's fragments tile [0, size) without gaps/overlap
        for i, sz in enumerate(sizes):
            sel = fr["leaf"] == i
            lb = np.sort(fr["leaf_begin"][sel])
            le = np.sort(fr["leaf_end"][sel])
            assert lb[0] == 0 and le[-1] == sz
            np.testing.assert_array_equal(le[:-1], lb[1:])


class TestPackers:
    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(0)
        arrays = [rng.standard_normal(s).astype(dt) for s, dt in
                  [((128,), np.float32), ((16, 16), np.float32),
                   ((7,), np.float64)]]
        offs = [0, 1024, 3072]
        buf = napi.pack_arrays(arrays, offs, 4096)
        back = napi.unpack_arrays(buf, offs, [a.shape for a in arrays],
                                  [a.dtype for a in arrays])
        for a, b in zip(arrays, back):
            np.testing.assert_array_equal(a, b)

    def test_threaded_pack_matches_serial(self):
        rng = np.random.default_rng(1)
        arrays = [rng.standard_normal(64).astype(np.float32)
                  for _ in range(32)]
        offs = [i * 256 for i in range(32)]
        b1 = napi.pack_arrays(arrays, offs, 32 * 256, num_threads=1)
        b8 = napi.pack_arrays(arrays, offs, 32 * 256, num_threads=8)
        used = np.zeros(32 * 256, bool)
        for o in offs:
            used[o:o + 256] = True
        np.testing.assert_array_equal(b1[used], b8[used])
