"""Telemetry pipeline tests (marker: ``monitor``).

Covers the observability contract end to end: in-graph ``TrainMetrics``
stay in-graph (no host callbacks traced into the step, the step remains
ONE jitted call), the JSONL schema round-trips, the goodput ledger's
arithmetic holds under injected overflow storms, the bench regression gate
passes/fails correctly, and ``apex-tpu-bench --telemetry-jsonl`` emits
schema-valid rows on CPU.
"""

import json
import math
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.amp.grad_scaler import DynamicGradScaler
from apex_tpu.monitor import (GoodputLedger, Telemetry, TrainMetrics,
                              collect_metrics, read_jsonl, validate_row)
from apex_tpu.monitor.telemetry import PERF_ROW_KEYS
from apex_tpu.resilience import FaultInjector, resilient_step
from apex_tpu.utils.logging import (MetricLogger, publish_event,
                                    structured_warning, subscribe_events)
from apex_tpu.utils.prof import StepTimer, detect_chip, roofline

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.monitor


def _params():
    return {"w": jnp.full((4, 4), 2.0), "b": jnp.ones((8,), jnp.bfloat16)}


# ------------------------------------------------------------ in-graph

def test_collect_metrics_values_under_jit():
    params = _params()
    grads = jax.tree_util.tree_map(lambda x: jnp.ones_like(x) * 0.5, params)

    @jax.jit
    def step(params, grads):
        return collect_metrics(grads=grads, params=params,
                               loss=jnp.float32(2.5), loss_scale=8.0)

    tm = step(params, grads)
    n = sum(p.size for p in jax.tree_util.tree_leaves(params))
    np.testing.assert_allclose(float(tm.grad_norm),
                               math.sqrt(n * 0.25), rtol=1e-5)
    np.testing.assert_allclose(float(tm.param_norm),
                               math.sqrt(16 * 4.0 + 8 * 1.0), rtol=1e-2)
    assert float(tm.loss) == 2.5
    assert float(tm.loss_scale) == 8.0
    assert not bool(tm.found_inf)
    assert tm.update_norm is None  # not collected -> absent, still a pytree


def test_collect_metrics_traces_no_host_callbacks():
    """The acceptance guarantee: metric collection adds no host syncs —
    the jaxpr of a collecting step contains no callback primitives and the
    whole step stays ONE jitted call that returns the metrics."""
    params = _params()
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    def step(params, grads):
        new = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)
        tm = collect_metrics(grads=grads, params=new, loss_scale=1.0)
        return new, tm

    jaxpr = str(jax.make_jaxpr(step)(params, grads))
    assert "callback" not in jaxpr  # covers pure_callback/io_callback/debug
    jitted = jax.jit(step)
    new, tm = jitted(params, grads)  # one call yields params AND metrics
    assert isinstance(tm, TrainMetrics)
    assert isinstance(tm.grad_norm, jax.Array)


def test_found_inf_detects_nan():
    grads = {"w": jnp.array([1.0, jnp.nan])}
    tm = jax.jit(lambda g: collect_metrics(grads=g))(grads)
    assert bool(tm.found_inf)


def test_scaler_unscale_and_norm_fused():
    scaler = DynamicGradScaler(init_scale=4.0)
    state = scaler.init()
    grads = {"w": jnp.full((8,), 4.0)}
    out, gnorm, found_inf = scaler.unscale_and_norm(grads, state)
    np.testing.assert_allclose(np.asarray(out["w"]), np.full((8,), 1.0))
    np.testing.assert_allclose(float(gnorm), math.sqrt(8.0), rtol=1e-6)
    assert not bool(found_inf)


# ------------------------------------------------------------ telemetry

def test_telemetry_jsonl_schema_roundtrip(tmp_path):
    path = str(tmp_path / "run.jsonl")
    tel = Telemetry(path, tokens_per_step=256.0, flops_per_step=1e9,
                    chip="v5e").start()
    params = _params()
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    tm = jax.jit(lambda p, g: collect_metrics(
        grads=g, params=p, loss=jnp.float32(1.0), loss_scale=1.0))(
            params, grads)
    for i in range(3):
        tel.log_step(i, metrics=tm)
    tel.close()
    rows, events = read_jsonl(path)
    assert len(rows) == 3 and not events
    for row in rows:
        validate_row(row, require=PERF_ROW_KEYS)
        assert row["tokens_per_s"] > 0
        assert row["mfu"] >= 0
        assert row["loss_scale"] == 1.0


def test_telemetry_mirrors_structured_events(tmp_path):
    path = str(tmp_path / "run.jsonl")
    tel = Telemetry(path)
    structured_warning("overflow_storm", consecutive_overflows=8)
    with tel.span("save"):
        pass
    tel.close()
    # events published after close must NOT land in the file
    structured_warning("after_close")
    _, events = read_jsonl(path)
    names = [e["event"] for e in events]
    assert "overflow_storm" in names
    assert "span" in names
    assert "after_close" not in names
    span = next(e for e in events if e["event"] == "span")
    assert span["name"] == "save" and span["ms"] >= 0


def test_telemetry_no_sync_until_flush(tmp_path, monkeypatch):
    """log_step buffers device arrays; flush() does ONE batched
    device_get for the whole buffer (the MetricLogger satellite)."""
    calls = []
    real = jax.device_get
    monkeypatch.setattr(jax, "device_get", lambda x: calls.append(1) or real(x))
    logger = MetricLogger(str(tmp_path / "m.jsonl"))
    for i in range(5):
        logger.log(i, loss=jnp.float32(i), norm=jnp.float32(2 * i))
    assert calls == []  # nothing fetched while buffering
    logger.flush()
    assert len(calls) == 1  # one host sync for 10 buffered device scalars


def test_goodput_ledger_arithmetic():
    led = GoodputLedger()
    led.record_step(1.0)
    led.record_step(1.0)
    led.record_step(0.5, productive=False)
    led.record_stall("checkpoint_save", 0.5)
    s = led.summary()
    assert s["steps"] == 3 and s["skipped_steps"] == 1
    assert s["productive_s"] == pytest.approx(2.0)
    assert s["lost_s"] == pytest.approx(1.0)
    assert s["goodput_frac"] == pytest.approx(2.0 / 3.0)
    assert s["lost_by_cause"] == {"checkpoint_save": pytest.approx(0.5),
                                  "overflow_skip": pytest.approx(0.5)}


def test_goodput_ledger_subscribes_to_stall_events():
    with GoodputLedger() as led:
        publish_event("checkpoint_save_stall", step=3, seconds=1.25)
        publish_event("checkpoint_restore_stall", step=3, seconds=0.25)
    # detached: later events must not be counted
    publish_event("checkpoint_save_stall", step=4, seconds=99.0)
    s = led.summary()
    assert s["lost_by_cause"]["checkpoint_save"] == pytest.approx(1.25)
    assert s["lost_by_cause"]["checkpoint_restore"] == pytest.approx(0.25)
    assert s["events"]["checkpoint_save_stall"] == 1


def test_distributed_resilience_events_registered():
    """The distributed-resilience events are part of the telemetry schema:
    collective_stall (+cleared) is a timed goodput cause, quarantine and
    watchdog aborts are counted degradation signals."""
    from apex_tpu.monitor.goodput import COUNTED_EVENTS, STALL_EVENTS

    assert STALL_EVENTS["collective_stall"] == "collective_stall"
    assert STALL_EVENTS["collective_stall_cleared"] == "collective_stall"
    assert "checkpoint_quarantined" in COUNTED_EVENTS
    assert "collective_stall_abort" in COUNTED_EVENTS

    with GoodputLedger() as led:
        publish_event("collective_stall", name="allreduce", seconds=0.5)
        publish_event("collective_stall_cleared", name="allreduce",
                      seconds=0.25)
        publish_event("checkpoint_quarantined", step=3, reason="crc")
    s = led.summary()
    assert s["lost_by_cause"]["collective_stall"] == pytest.approx(0.75)
    assert s["events"]["checkpoint_quarantined"] == 1
    assert s["events"]["collective_stall"] == 1


def test_serve_events_registered():
    """Every serving event the serve package publishes must be part of
    the goodput event schema: queue wait is a timed cause, the request
    lifecycle and per-step latency are counted signals. The source grep
    makes an UNREGISTERED serve_* event a tier-1 failure, the same
    contract PR-4 established for the distributed-resilience events."""
    import os
    import re

    import apex_tpu.serve as serve_pkg
    from apex_tpu.monitor.goodput import COUNTED_EVENTS, STALL_EVENTS

    assert STALL_EVENTS["serve_queue_wait"] == "serve_queue_wait"
    for name in ("serve_request_admitted", "serve_request_completed",
                 "serve_request_evicted", "serve_decode_step"):
        assert name in COUNTED_EVENTS, name

    published = set()
    pkg_dir = os.path.dirname(serve_pkg.__file__)
    for fname in os.listdir(pkg_dir):
        if fname.endswith(".py"):
            with open(os.path.join(pkg_dir, fname)) as f:
                published |= set(re.findall(
                    r'publish_event\(\s*"(serve_[a-z_]+)"', f.read()))
    assert published, "serve package publishes no events?"
    unregistered = published - set(COUNTED_EVENTS) - set(STALL_EVENTS)
    assert not unregistered, \
        f"serve events missing from the goodput schema: {unregistered}"

    with GoodputLedger() as led:
        publish_event("serve_queue_wait", seconds=0.5, request_id="r0")
        publish_event("serve_request_admitted", request_id="r0", slot=1)
        publish_event("serve_decode_step", seconds=0.001, active=2)
        publish_event("serve_request_completed", request_id="r0", slot=1)
    s = led.summary()
    assert s["lost_by_cause"]["serve_queue_wait"] == pytest.approx(0.5)
    assert s["events"]["serve_request_admitted"] == 1
    assert s["events"]["serve_decode_step"] == 1


def test_repo_wide_event_schema_audit():
    """EVERY literal ``publish_event``/``structured_warning`` call site in
    the package must use a name registered in the goodput/event schema
    (STALL | COUNTED | INFO) — so a new subsystem cannot ship an event no
    monitoring consumer knows about. The audit itself is apexlint rule
    APX003 (AST-based, one source of truth — this test delegates instead
    of keeping its own regex scan, and proves the rule still *fires*)."""
    sys.path.insert(0, ROOT)
    try:
        from tools.apexlint.core import LintContext
        from tools.apexlint.rules.event_schema import (EventSchemaRule,
                                                       load_event_schema)
    finally:
        sys.path.pop(0)
    from apex_tpu.monitor.goodput import EVENT_SCHEMA

    # the rule audits against the same schema the runtime exposes
    assert load_event_schema(ROOT) == EVENT_SCHEMA

    ctx = LintContext(ROOT, [os.path.join(ROOT, "apex_tpu")])
    violations = list(EventSchemaRule().check(ctx))
    assert not violations, \
        "events missing from the monitor.goodput schema:\n" + \
        "\n".join(v.format() for v in violations)

    # sanity: the rule still SEES the real call sites — a refactor that
    # blinds the audit (renamed publish funcs, moved schema) must fail
    # here, not silently pass (the seed had ≈31 sites across ≥10 files)
    from tools.apexlint.rules.event_schema import _event_name_arg
    import ast as _ast

    sites = []
    for sf in ctx.iter_files(under="apex_tpu"):
        for node in _ast.walk(sf.tree):
            if isinstance(node, _ast.Call):
                arg = _event_name_arg(node)
                if arg is not None:
                    sites.append((sf.path, arg.value))
    assert len(sites) >= 25, sites
    assert len({p for p, _ in sites}) >= 10


def test_raising_subscriber_isolated_once(capsys):
    """The subscribe_events docstring contract: a raising subscriber is
    reported exactly once (even raising DIFFERENT exceptions each time)
    and every event still reaches the remaining subscribers."""
    calls = []
    n = [0]

    def bad(rec):
        n[0] += 1
        raise ValueError(f"boom {n[0]}")   # distinct message per raise

    def good(rec):
        calls.append(rec["event"])

    unsub_bad = subscribe_events(bad)
    unsub_good = subscribe_events(good)
    try:
        for _ in range(3):
            publish_event("span", name="x")
    finally:
        unsub_bad()
        unsub_good()
    assert calls == ["span"] * 3           # delivery survived the raiser
    assert capsys.readouterr().err.count("raised ValueError") == 1


def test_unsubscribe_during_publish_is_safe():
    seen = []
    unsubs = {}

    def s1(rec):
        seen.append("s1")
        unsubs["s2"]()                     # removes s2 mid-delivery

    def s2(rec):
        seen.append("s2")

    unsubs["s1"] = subscribe_events(s1)
    unsubs["s2"] = subscribe_events(s2)
    try:
        # snapshot semantics: s2 still sees THIS publish...
        publish_event("span", name="a")
        # ...and is gone for the next one
        publish_event("span", name="b")
    finally:
        unsubs["s1"]()
        unsubs["s2"]()                     # idempotent second call
    assert seen == ["s1", "s2", "s1"]


def test_telemetry_trace_jsonl_exports_chrome_trace(tmp_path):
    """Telemetry(trace_jsonl=...) enables the process tracer for the run,
    streams completed spans as Perfetto-loadable Chrome-trace JSON, keeps
    the high-rate span_open/span_close records OUT of the metric JSONL
    mirror, and restores the previous tracer on close."""
    from apex_tpu.monitor import read_chrome_trace
    from apex_tpu.monitor.trace import get_tracer

    path = str(tmp_path / "run.jsonl")
    tpath = str(tmp_path / "trace.json")
    prev = get_tracer()
    tel = Telemetry(path, trace_jsonl=tpath)
    assert get_tracer() is tel.tracer and tel.tracer.enabled
    with tel.span("checkpoint"):
        pass
    tel.close()
    assert get_tracer() is prev
    xs = [e for e in read_chrome_trace(tpath) if e.get("ph") == "X"]
    assert [e["name"] for e in xs] == ["checkpoint"]
    _, events = read_jsonl(path)
    names = [e["event"] for e in events]
    assert "span" in names                        # the legacy aggregate
    assert "span_open" not in names and "span_close" not in names


def test_checkpoint_save_publishes_stall_event(tmp_path):
    # call-time imports for BOTH sides: test_chip_worker's module purge can
    # leave collection-time and re-imported apex_tpu identities coexisting,
    # and publisher + subscriber must share one event-bus module
    from apex_tpu.monitor.goodput import GoodputLedger as Ledger
    from apex_tpu.resilience import CheckpointManager

    with Ledger() as led:
        CheckpointManager(str(tmp_path)).save(1, _params())
    assert led.events.get("checkpoint_save_stall") == 1
    assert led.lost_by_cause["checkpoint_save"] > 0


# ---------------------------------------------- overflow-storm goodput

@pytest.mark.fault
def test_goodput_under_injected_overflow_storm(tmp_path):
    """FaultInjector NaN burst through resilient_step with telemetry:
    every poisoned step is skipped, charged as lost time, and the emitted
    rows carry the overflow flag and the backed-off scale."""
    inj = FaultInjector(seed=3).nan_burst(start=2, length=3)
    scaler = DynamicGradScaler(init_scale=2.0 ** 8, growth_interval=1000)
    path = str(tmp_path / "storm.jsonl")
    tel = Telemetry(path, tokens_per_step=1.0).start()

    params = {"w": jnp.ones((4,))}

    def train_step(params, sstate, grads):
        new = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params,
                                     grads)
        from apex_tpu.multi_tensor.functional import tree_check_finite
        return new, tree_check_finite(grads), jnp.float32(1.0)

    step = resilient_step(train_step, scaler, telemetry=tel)
    sstate = scaler.init()
    grads = {"w": jnp.full((4,), 0.5)}
    total = 8
    for i in range(total):
        g = inj.poison_grads(grads, i)
        params, sstate, found_inf, _loss = step(params, sstate, g)
    tel.close()

    assert step.skipped_steps == 3
    g = tel.ledger.summary()
    assert g["steps"] == total
    assert g["skipped_steps"] == 3
    assert g["events"]["overflow_step_skipped"] == 3
    assert g["lost_by_cause"]["overflow_skip"] > 0
    assert 0.0 < g["goodput_frac"] < 1.0
    assert g["productive_s"] + g["lost_s"] == pytest.approx(
        sum(v for v in g["lost_by_cause"].values()) + g["productive_s"])

    rows, _events = read_jsonl(path)
    assert len(rows) == total
    skipped_rows = [r for r in rows if r["found_inf"]]
    assert len(skipped_rows) == 3
    # params kept + scale backed off on the skipped steps; update_norm and
    # param_norm were collected in-graph by the resilient post-step
    for r in rows:
        assert "param_norm" in r and "update_norm" in r
        assert "loss_scale" in r and r["loss"] == 1.0


# ------------------------------------------------------------ satellites

def test_steptimer_stop_before_start_raises():
    t = StepTimer()
    with pytest.raises(RuntimeError, match="before start"):
        t.stop()
    t.start()
    assert t.stop() >= 0.0
    t.reset()
    with pytest.raises(RuntimeError):
        t.stop()


class _FakeDev:
    def __init__(self, platform, kind):
        self.platform = platform
        self.device_kind = kind


@pytest.mark.parametrize("kind,expected", [
    ("TPU v5e", "v5e"), ("TPU v5 lite", "v5e"), ("TPU v6e", "v6e"),
    ("TPU v6 lite", "v6e"), ("TPU v5p", "v5p"), ("TPU v5", "v5p"),
])
def test_detect_chip_known_kinds(kind, expected):
    assert detect_chip([_FakeDev("tpu", kind)]) == expected


def test_detect_chip_cpu_and_unknown():
    assert detect_chip([_FakeDev("cpu", "cpu")]) is None
    # unknown TPU generation: warns once, returns None (env fallback)
    assert detect_chip([_FakeDev("tpu", "TPU v9 hyper")]) is None


def test_roofline_uses_detected_chip(monkeypatch):
    # patch + call through the SAME module object (see identity note above)
    import apex_tpu.utils.prof as prof

    monkeypatch.setattr(prof, "detect_chip", lambda devices=None: "v6e")
    out = prof.roofline(lambda x: x @ x, jnp.ones((64, 64)))
    assert out["chip"] == "v6e"
    assert out["flops"] >= 0


# ------------------------------------------------------- regression gate

def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def _gate(current, baseline, *extra):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_regression.py"),
         current, baseline, *extra],
        capture_output=True, text=True, timeout=120)


def test_check_regression_pass_and_fail(tmp_path):
    rows = [{"step": i, "loss": 4.0, "grad_norm": 1.0, "loss_scale": 1.0,
             "step_ms": 10.0, "tokens_per_s": 1000.0, "mfu": 0.02}
            for i in range(5)]
    base = str(tmp_path / "base.jsonl")
    _write_jsonl(base, rows)

    same = str(tmp_path / "same.jsonl")
    _write_jsonl(same, rows)
    r = _gate(same, base)
    assert r.returncode == 0, r.stdout + r.stderr

    slow = str(tmp_path / "slow.jsonl")
    _write_jsonl(slow, [{**row, "step_ms": row["step_ms"] * 1.2}
                        for row in rows])
    r = _gate(slow, base)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout and "step_ms" in r.stdout

    # within tolerance at 25%: the same 20% slowdown passes
    r = _gate(slow, base, "--tolerance", "0.25")
    assert r.returncode == 0, r.stdout + r.stderr


def test_check_regression_throughput_direction(tmp_path):
    base = str(tmp_path / "b.jsonl")
    cur = str(tmp_path / "c.jsonl")
    _write_jsonl(base, [{"step": 0, "tokens_per_s": 1000.0},
                        {"step": 1, "tokens_per_s": 1000.0}])
    _write_jsonl(cur, [{"step": 0, "tokens_per_s": 700.0},
                       {"step": 1, "tokens_per_s": 700.0}])
    r = _gate(cur, base, "--warmup", "0")
    assert r.returncode == 1  # throughput DROP is a regression
    r = _gate(base, cur, "--warmup", "0")
    assert r.returncode == 0  # throughput gain is not


def test_check_regression_single_row_jsonl(tmp_path):
    """A one-row capture is a single JSON dict too — it must be read as a
    telemetry row, not misclassified as an (empty) suite."""
    base = str(tmp_path / "b.jsonl")
    cur = str(tmp_path / "c.jsonl")
    _write_jsonl(base, [{"step": 0, "step_ms": 10.0}])
    _write_jsonl(cur, [{"step": 0, "step_ms": 13.0}])
    assert _gate(base, base).returncode == 0
    assert _gate(cur, base).returncode == 1


def test_telemetry_flush_every_bounds_buffer(tmp_path):
    path = str(tmp_path / "m.jsonl")
    tel = Telemetry(path, flush_every=2).start()
    for i in range(5):
        tel.log_step(i, loss=jnp.float32(i))
    # 4 rows flushed by the every-2 policy; row 5 still buffered
    rows, _ = read_jsonl(path)
    assert len(rows) == 4
    tel.close()
    rows, _ = read_jsonl(path)
    assert len(rows) == 5


def test_check_regression_device_kind_mismatch(tmp_path):
    """Capture provenance satellite: a CPU-smoke capture gating a TPU
    baseline warns LOUDLY, and --fail-device-mismatch makes it exit 1
    even when every metric is within tolerance."""
    entry = {"metric": "a_ms", "value": 10.0, "unit": "ms"}
    base = {"device_kind": "TPU v5e", "interpret_mode": False,
            "bench_a": entry}
    cur = {"device_kind": "TPU v3 (cpu-smoke)", "interpret_mode": True,
           "bench_a": entry}
    basep, curp = str(tmp_path / "b.json"), str(tmp_path / "c.json")
    with open(basep, "w") as f:
        json.dump(base, f)
    with open(curp, "w") as f:
        json.dump(cur, f)
    r = _gate(curp, basep)
    assert r.returncode == 0               # warn-only by default
    assert "device-kind mismatch" in r.stderr
    r = _gate(curp, basep, "--fail-device-mismatch")
    assert r.returncode == 1
    # same kinds: silent, flag or not
    r = _gate(basep, basep, "--fail-device-mismatch")
    assert r.returncode == 0 and "mismatch" not in r.stderr
    # legacy captures without the stamps keep gating without noise
    legacy = {"bench_a": entry}
    with open(curp, "w") as f:
        json.dump(legacy, f)
    r = _gate(curp, basep, "--fail-device-mismatch")
    assert r.returncode == 0 and "mismatch" not in r.stderr
    # vocabularies never mix: a new capture (device_kind "cpu" + chip
    # "cpu-smoke") against the committed legacy baseline (chip only)
    # compares chip-vs-chip — identical hardware must NOT flag...
    with open(basep, "w") as f:
        json.dump({"chip": "cpu-smoke", "bench_a": entry}, f)
    with open(curp, "w") as f:
        json.dump({"device_kind": "cpu", "chip": "cpu-smoke",
                   "bench_a": entry}, f)
    r = _gate(curp, basep, "--fail-device-mismatch")
    assert r.returncode == 0 and "mismatch" not in r.stderr
    # ...while a REAL chip difference still does
    with open(basep, "w") as f:
        json.dump({"chip": "v5e", "bench_a": entry}, f)
    r = _gate(curp, basep, "--fail-device-mismatch")
    assert r.returncode == 1 and "device-kind mismatch" in r.stderr
    # same chip but interpret-mode capture vs compiled baseline: still
    # not comparable (interpret Pallas on a TPU host != the real chip)
    with open(basep, "w") as f:
        json.dump({"device_kind": "TPU v5e", "interpret_mode": False,
                   "bench_a": entry}, f)
    with open(curp, "w") as f:
        json.dump({"device_kind": "TPU v5e", "interpret_mode": True,
                   "bench_a": entry}, f)
    r = _gate(curp, basep, "--fail-device-mismatch")
    assert r.returncode == 1 and "interpret_mode" in r.stderr


def test_check_regression_suite_baseline(tmp_path):
    suite = {"backend": "cpu", "complete": True,
             "bench_a": {"metric": "a_ms", "value": 10.0, "unit": "ms",
                         "step_ms": 10.0}}
    basep = str(tmp_path / "BENCH_BASE.json")
    with open(basep, "w") as f:
        json.dump(suite, f)
    worse = {"backend": "cpu", "complete": True,
             "bench_a": {"metric": "a_ms", "value": 13.0, "unit": "ms",
                         "step_ms": 13.0}}
    curp = str(tmp_path / "cur.json")
    with open(curp, "w") as f:
        json.dump(worse, f)
    assert _gate(basep, basep).returncode == 0
    assert _gate(curp, basep).returncode == 1
    assert _gate(str(tmp_path / "nope.json"), basep).returncode == 2


# ----------------------------------------------------------- bench smoke

def _run_cli(args, timeout=600):
    env = dict(os.environ)
    kept = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(kept + [ROOT])
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run([sys.executable, "-m", "apex_tpu.bench_cli"]
                          + args, cwd=ROOT, env=env, capture_output=True,
                          text=True, timeout=timeout)


def test_bench_cli_telemetry_smoke(tmp_path):
    """Tier-1 gate: ``apex-tpu-bench --telemetry-jsonl`` runs a few steps
    on CPU and every emitted row validates against the schema with the
    acceptance keys present. ``--trace-jsonl`` on the same run exports a
    Perfetto-loadable Chrome trace with one train_step trace per step
    and captures the calibrated step's static memory reservation."""
    path = str(tmp_path / "bench.jsonl")
    tpath = str(tmp_path / "bench_trace.json")
    # pre-seed the file with a stale row: a per-run sink must truncate, or
    # mixed-run medians would skew the regression gate; the '=' flag form
    # must be recognized too
    with open(path, "w") as f:
        f.write(json.dumps({"step": 99, "stale": True}) + "\n")
    r = _run_cli([f"--telemetry-jsonl={path}", f"--trace-jsonl={tpath}",
                  "--steps", "4"])
    assert r.returncode == 0, r.stderr[-2000:]
    headline = json.loads(r.stdout.strip().splitlines()[-1])
    assert headline["metric"] == "telemetry_train_step_ms_lm_tiny"
    assert headline["value"] > 0
    assert headline["goodput"] == pytest.approx(1.0)

    rows, events = read_jsonl(path)
    assert len(rows) == 4  # the stale pre-run row was truncated away
    for row in rows:
        validate_row(row, require=PERF_ROW_KEYS)
        assert row["step_ms"] > 0
        assert row["tokens_per_s"] > 0
        assert row["loss_scale"] == 2.0 ** 12
    # calibrate's AOT point published its static memory reservation
    assert any(e["event"] == "hbm_snapshot" and e.get("kind") == "static"
               for e in events)

    from apex_tpu.monitor.trace import read_chrome_trace

    xs = [e for e in read_chrome_trace(tpath) if e.get("ph") == "X"]
    assert [e["name"] for e in xs] == ["train_step"] * 4
    # per-step spans line up with the logged rows (same wall clock)
    durs_ms = sorted(e["dur"] / 1e3 for e in xs)
    assert durs_ms[0] > 0


def test_bench_fatal_step_leaves_flight_dump(tmp_path, monkeypatch):
    """A fatal exception inside the telemetry bench's step loop has no
    bus record — the armed flight recorder's guard must still dump, and
    teardown must restore the process tracer and terminate the Chrome
    trace (in-process; a subprocess would only burn budget)."""
    import apex_tpu.bench_cli as bc
    from apex_tpu.monitor.trace import get_tracer, read_chrome_trace

    real = bc._make_telemetry_step

    def exploding():
        step, state, tokens, tps = real()
        calls = [0]

        def bad_step(i, st, tk):
            calls[0] += 1
            if calls[0] >= 3:       # past calibrate + warmup: mid-loop
                raise RuntimeError("xla died")
            return step(i, st, tk)

        bad_step.lower = step.lower     # calibrate path stays intact
        return bad_step, state, tokens, tps

    monkeypatch.setattr(bc, "_make_telemetry_step", exploding)
    fpath = str(tmp_path / "f.json")
    tpath = str(tmp_path / "t.json")
    with pytest.raises(RuntimeError, match="xla died"):
        bc._telemetry_bench(None, steps=10, trace_jsonl=tpath,
                            flight_path=fpath)
    d = json.loads(open(fpath).read())
    assert d["reason"] == "exception:RuntimeError:telemetry_bench"
    assert get_tracer() is not None and not get_tracer().enabled
    read_chrome_trace(tpath)            # terminated, parseable
    # the recorder unsubscribed: later events don't touch the dump
    mtime = os.path.getmtime(fpath)
    from apex_tpu.utils.logging import publish_event
    publish_event("preemption_requested", level="warning")
    assert os.path.getmtime(fpath) == mtime


def test_bench_cli_step_is_single_jitted_call():
    """The telemetry bench's step function is ONE jitted callable whose
    single invocation yields the new state AND the metrics — and its
    trace contains no host callbacks."""
    from apex_tpu.bench_cli import _make_telemetry_step
    # resolved at call time alongside bench_cli so both share one module
    # identity even after test_chip_worker's purge (see note above)
    from apex_tpu.monitor.metrics import TrainMetrics as TM

    step, state, tokens, tokens_per_step = _make_telemetry_step()
    assert hasattr(step, "lower")  # a jit-wrapped callable, not a python loop
    jaxpr = str(jax.make_jaxpr(step)(0, state, tokens))
    assert "callback" not in jaxpr
    (params, m, v, sstate), tm = step(0, state, tokens)
    assert isinstance(tm, TM)
    assert tm.grad_norm is not None and tm.loss_scale is not None
    assert tokens_per_step == tokens.shape[0] * (tokens.shape[1] - 1)
