"""amp policy + dynamic grad scaler tests (≈ tests/L1 amp cross-product
semantics, scaled down to unit level; full matrix in test_integration.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam


class TestPolicy:
    def test_opt_levels(self):
        for ol, pdt, cdt in [("O0", jnp.float32, jnp.float32),
                             ("O1", jnp.float32, jnp.bfloat16),
                             ("O2", jnp.bfloat16, jnp.bfloat16),
                             ("O3", jnp.bfloat16, jnp.bfloat16)]:
            p = amp.Policy.from_opt_level(ol)
            assert p.param_dtype == pdt and p.compute_dtype == cdt
        assert amp.Policy.from_opt_level("O2").master_weights
        assert not amp.Policy.from_opt_level("O3").keep_batchnorm_fp32

    def test_initialize(self):
        params = {"w": jnp.ones((4, 4))}
        cast, opt, policy, scaler = amp.initialize(
            params, None, "O2", loss_scale="dynamic")
        assert cast["w"].dtype == jnp.bfloat16
        assert scaler is not None

    def test_static_scaler(self):
        p = amp.Policy.from_opt_level("O1", loss_scale=128.0)
        sc = p.make_scaler()
        st = sc.init()
        assert float(st.scale) == 128.0
        st2 = sc.update(st, jnp.bool_(True))
        assert float(st2.scale) == 128.0  # static: no backoff


class TestDynamicGradScaler:
    def test_full_fp16_flow_jitted(self):
        """scale → unscale+check → conditional step → scale update, one jit."""
        scaler = amp.DynamicGradScaler(init_scale=1024.0, growth_interval=2)
        params = [jnp.ones((8,), jnp.float32)]
        opt_state = {"m": [jnp.zeros((8,))], "v": [jnp.zeros((8,))]}

        from apex_tpu.optimizers.functional import adam_update

        @jax.jit
        def train_step(params, opt_state, scaler_state, x):
            def loss_fn(p):
                return jnp.sum(p[0] * x)

            loss, grads = jax.value_and_grad(
                lambda p: scaler.scale(loss_fn(p), scaler_state))(params)
            grads, found_inf = scaler.unscale(grads, scaler_state)
            p, m, v = adam_update(params, grads, opt_state["m"],
                                  opt_state["v"], step=1, lr=1e-2,
                                  found_inf=found_inf)
            return p, {"m": m, "v": v}, scaler.update(scaler_state, found_inf), loss

        st = scaler.init()
        x = jnp.ones((8,))
        p, s, st, loss = train_step(params, opt_state, st, x)
        assert float(loss) == 1024.0 * 8.0
        assert not np.allclose(np.asarray(p[0]), 1.0)  # step applied
        # now poison the grads via x=inf → found_inf → no step + backoff
        p2, s2, st2, _ = train_step(p, s, st, jnp.full((8,), jnp.inf))
        np.testing.assert_array_equal(np.asarray(p2[0]), np.asarray(p[0]))
        assert float(st2.scale) == float(st.scale) * 0.5

    def test_growth(self):
        scaler = amp.DynamicGradScaler(init_scale=2.0, growth_interval=2)
        st = scaler.init()
        st = scaler.update(st, jnp.bool_(False))
        st = scaler.update(st, jnp.bool_(False))
        assert float(st.scale) == 4.0


class TestGradScalerFacade:
    def test_step_skips_on_overflow(self):
        params = [jnp.ones((4,), jnp.float32)]
        opt = FusedAdam(params, lr=0.1)
        scaler = amp.GradScaler(init_scale=64.0)
        bad = [jnp.array([jnp.inf, 1.0, 1.0, 1.0], jnp.float32)]
        p = scaler.step(opt, bad)
        np.testing.assert_array_equal(np.asarray(p[0]), np.ones(4))
        assert scaler.get_scale() == 32.0
        good = [jnp.full((4,), 64.0)]  # = scale × true grad of 1.0
        p = scaler.step(opt, good)
        assert not np.allclose(np.asarray(p[0]), 1.0)
