"""Gradient parity for the dp×tp×sp parallel GPT-2 train step: grads computed
on a multi-device mesh must equal single-device autodiff (the review finding
that AdamW scale-invariance can mask a world-size factor — this pins it)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import axis_size, shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.models.gpt2_parallel import (_forward_local, _grad_sync_specs,
                                           choose_mesh_shape, init_opt_state,
                                           init_params, make_train_step,
                                           param_specs)
from apex_tpu.parallel.mesh import make_mesh

CFG = GPT2Config(vocab_size=64, n_positions=256, n_embd=64, n_layer=1,
                 n_head=8)


def _data(batch=8):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, 256), 0,
                                CFG.vocab_size, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones(tokens.shape, jnp.float32).at[:, -1].set(0.0)
    return tokens, targets, mask


def _grads_on_mesh(params, data, dp, tp, sp):
    mesh = make_mesh([dp, tp, sp], ["dp", "tp", "sp"])
    pspecs = param_specs(CFG)
    sync_axes = _grad_sync_specs(CFG)

    def local(params, tokens, targets, mask):
        grads = jax.grad(
            lambda p: _forward_local(CFG, p, tokens, targets, mask))(params)
        n_total = (axis_size("dp") * axis_size("tp")
                   * axis_size("sp"))

        def sync(g, axes):
            for ax in axes.split("|"):
                g = jax.lax.psum(g, ax)
            return g / n_total

        return jax.tree_util.tree_map(sync, grads, sync_axes)

    f = shard_map(local, mesh=mesh,
                  in_specs=(pspecs, P("dp", "sp"), P("dp", "sp"),
                            P("dp", "sp")),
                  out_specs=pspecs, check_vma=False)
    return jax.jit(f)(params, *data)


# tier-1 runs the all-axes (2,2,2) cell (dp+tp+sp parity at once); the
# single-axis cells stay in the slow tier
@pytest.mark.parametrize("shape", [
    pytest.param((2, 1, 1), marks=pytest.mark.slow),
    pytest.param((1, 2, 1), marks=pytest.mark.slow),
    pytest.param((1, 1, 2), marks=pytest.mark.slow),
    (2, 2, 2)])
def test_parallel_grads_match_single_device(shape):
    params = init_params(CFG, jax.random.PRNGKey(0))
    data = _data()
    ref = _grads_on_mesh(params, data, 1, 1, 1)
    got = _grads_on_mesh(params, data, *shape)
    flat_r = jax.tree_util.tree_leaves(ref)
    flat_g = jax.tree_util.tree_leaves(got)
    for a, b in zip(flat_g, flat_r):
        # bf16 compute → reduction-order noise across shardings; the bound
        # still rules out any world-size scaling factor (2x would blow rtol)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-3, rtol=0.05)


@pytest.mark.slow
def test_train_step_descends_on_mesh():
    params = init_params(CFG, jax.random.PRNGKey(0))
    opt_state = init_opt_state(params)
    mesh = make_mesh([2, 2, 2], ["dp", "tp", "sp"])
    step_fn = make_train_step(CFG, mesh, lr=3e-3)
    tokens, targets, mask = _data()
    losses = []
    for i in range(5):
        params, opt_state, loss = step_fn(params, opt_state, tokens, targets,
                                          mask, jnp.int32(i + 1))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_choose_mesh_shape():
    assert choose_mesh_shape(8) == (2, 2, 2)
    assert choose_mesh_shape(4) == (2, 2, 1)
    assert choose_mesh_shape(2) == (2, 1, 1)
    assert choose_mesh_shape(1) == (1, 1, 1)


@pytest.mark.slow
class TestPipelineComposed:
    """Round-2 pp/ep composition (VERDICT item 5): the 1F1B-pipelined model
    must match the non-pp model, and the 5-axis MoE variant must train."""

    def _data(self, batch=4):
        tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, 256), 0,
                                    CFG.vocab_size, jnp.int32)
        targets = jnp.roll(tokens, -1, axis=1)
        mask = jnp.ones(tokens.shape, jnp.float32).at[:, -1].set(0.0)
        return tokens, targets, mask

    def test_pp_loss_and_grads_match_non_pp(self):
        from apex_tpu.models.gpt2_parallel import (init_params_pp,
                                                   make_train_step_pp)
        cfg = GPT2Config(vocab_size=64, n_positions=256, n_embd=64,
                         n_layer=2, n_head=8)
        tokens, targets, mask = self._data()
        key = jax.random.PRNGKey(0)

        mesh_a = make_mesh([2, 2, 2], ["dp", "tp", "sp"])
        p_a = init_params(cfg, key)
        step_a = make_train_step(cfg, mesh_a, lr=1e-3)
        pa, sta, loss_a = step_a(p_a, init_opt_state(p_a), tokens, targets,
                                 mask, jnp.int32(1))

        mesh_b = make_mesh([1, 2, 2, 2, 1],
                           ["dp", "pp", "tp", "sp", "ep"])
        p_b = init_params_pp(cfg, key)
        step_b = make_train_step_pp(cfg, mesh_b, lr=1e-3,
                                    num_microbatches=2)
        pb, stb, loss_b = step_b(p_b, init_opt_state(p_b), tokens, targets,
                                 mask, jnp.int32(1))

        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
        # Adam first-moment state == grads at step 1 (up to (1-b1) scale):
        # the strongest cross-layout grad parity check
        m_a = np.stack([np.asarray(b["wq"]) for b in sta[0]["blocks"]])
        m_b = np.asarray(stb[0]["blocks"]["wq"])
        np.testing.assert_allclose(m_a, m_b, atol=2e-5, rtol=2e-2)
        wte_ma = np.asarray(sta[0]["wte"])
        wte_mb = np.asarray(stb[0]["shared"]["wte"])
        np.testing.assert_allclose(wte_ma, wte_mb, atol=2e-5, rtol=2e-2)

    def test_pp_descends_multiple_steps(self):
        from apex_tpu.models.gpt2_parallel import (init_params_pp,
                                                   make_train_step_pp)
        cfg = GPT2Config(vocab_size=64, n_positions=256, n_embd=64,
                         n_layer=2, n_head=8)
        tokens, targets, mask = self._data()
        mesh = make_mesh([1, 2, 2, 2, 1], ["dp", "pp", "tp", "sp", "ep"])
        p = init_params_pp(cfg, jax.random.PRNGKey(0))
        st = init_opt_state(p)
        step = make_train_step_pp(cfg, mesh, lr=3e-3, num_microbatches=4)
        losses = []
        for i in range(5):
            p, st, loss = step(p, st, tokens, targets, mask,
                               jnp.int32(1 + i))
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()

    def test_moe_5axis_trains(self):
        from apex_tpu.models.gpt2_parallel import (init_params_pp,
                                                   make_train_step_pp)
        cfg = GPT2Config(vocab_size=64, n_positions=256, n_embd=64,
                         n_layer=2, n_head=8)
        tokens, targets, mask = self._data()
        mesh = make_mesh([1, 2, 2, 1, 2], ["dp", "pp", "tp", "sp", "ep"])
        p = init_params_pp(cfg, jax.random.PRNGKey(0), moe_experts=4)
        st = init_opt_state(p)
        step = make_train_step_pp(cfg, mesh, lr=3e-3, num_microbatches=2,
                                  moe_experts=4)
        losses = []
        for i in range(5):
            p, st, loss = step(p, st, tokens, targets, mask,
                               jnp.int32(1 + i))
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()


@pytest.mark.slow
def test_ulysses_strategy_matches_ring():
    """The composed dp×tp×sp step with sp_strategy='ulysses' computes the
    same loss trajectory as the ring strategy (same math, different comm).
    CFG has 8 heads, tp=2 → h_local=4, sp=2 divides it."""
    params = init_params(CFG, jax.random.PRNGKey(0))
    mesh = make_mesh([2, 2, 2], ["dp", "tp", "sp"])
    tokens, targets, mask = _data()

    losses = {}
    for strat in ("ring", "ulysses"):
        p = jax.tree_util.tree_map(lambda x: x, params)
        st = init_opt_state(p)
        step_fn = make_train_step(CFG, mesh, lr=3e-3, sp_strategy=strat)
        ls = []
        for i in range(3):
            p, st, loss = step_fn(p, st, tokens, targets, mask,
                                  jnp.int32(i + 1))
            ls.append(float(loss))
        losses[strat] = ls
    np.testing.assert_allclose(losses["ulysses"], losses["ring"],
                               rtol=2e-2, atol=2e-3)
    assert losses["ulysses"][-1] < losses["ulysses"][0]
