"""Checkpoint subsystem tests (v1 gather + v2 sharded orbax semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.parallel import get_mesh
from apex_tpu.utils.checkpoint import (restore, restore_numpy, save,
                                       save_numpy)


def test_orbax_sharded_roundtrip(tmp_path):
    mesh = get_mesh("data")
    shard = NamedSharding(mesh, P("data"))
    tree = {"w": jax.device_put(jnp.arange(64, dtype=jnp.float32), shard),
            "b": jnp.ones((3,))}
    save(str(tmp_path / "ck"), tree)
    back = restore(str(tmp_path / "ck"), tree)
    np.testing.assert_array_equal(np.asarray(back["w"]), np.arange(64))
    assert back["w"].sharding == shard  # re-sharded onto the mesh


def test_numpy_roundtrip(tmp_path):
    tree = {"a": jnp.arange(10), "nested": [jnp.ones((2, 2))]}
    save_numpy(str(tmp_path / "ck2"), tree)
    back = restore_numpy(str(tmp_path / "ck2"), tree)
    np.testing.assert_array_equal(np.asarray(back["a"]), np.arange(10))
    np.testing.assert_array_equal(np.asarray(back["nested"][0]),
                                  np.ones((2, 2)))


def test_optimizer_state_dict_through_checkpoint(tmp_path):
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam)
    mesh = get_mesh("data")
    params = [jnp.ones((64,)), jnp.zeros((32, 4))]
    opt = DistributedFusedAdam(params, mesh, lr=1e-2)
    opt.step([jnp.ones((64,)), jnp.ones((32, 4))])
    save_numpy(str(tmp_path / "opt"), opt.state_dict())
    sd = restore_numpy(str(tmp_path / "opt"), opt.state_dict())
    opt2 = DistributedFusedAdam(params, mesh, lr=1e-2)
    opt2.load_state_dict(jax.tree_util.tree_map(np.asarray, sd))
    g = [jnp.ones((64,)) * 2, jnp.ones((32, 4))]
    opt.step(g)
    opt2.step(g)
    for a, b in zip(opt.parameters, opt2.parameters):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_save_async_overlaps_and_is_durable(tmp_path):
    """save_async returns before the checkpoint is durable; wait() makes it
    so and a restore round-trips (the GDS async-save story)."""
    from apex_tpu.utils import checkpoint as ckpt
    tree = {"w": jnp.arange(64.0).reshape(8, 8),
            "b": jnp.ones((8,), jnp.bfloat16)}
    path = str(tmp_path / "async_ckpt")
    handle = ckpt.save_async(path, tree)
    handle.wait()
    out = ckpt.restore(path, like=tree)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(tree["w"]))
    assert out["b"].dtype == jnp.bfloat16
