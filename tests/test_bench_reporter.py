"""bench.py's cache-first reporter logic — the round-acceptance path.

These tests pin the wedge-proofing contracts: a stale or incomplete or
CPU capture must never be emitted as a TPU record, and worker detection
must not be fooled by a dead pid or a foreign process.
"""

import json
import os
import time

import pytest

import bench


@pytest.fixture()
def cache(tmp_path, monkeypatch):
    path = tmp_path / "BENCH_TPU_CACHE.json"
    monkeypatch.setattr(bench, "_CACHE", str(path))
    return path


def _suite(**over):
    s = {"backend": "tpu", "chip": "v5e", "complete": True,
         "captured": time.strftime("%Y-%m-%dT%H:%M:%S"),
         "git": "abc1234",
         "fused_adam_1b": {"metric": "m", "value": 1.0, "unit": "ms",
                           "vs_baseline": 1.2}}
    s.update(over)
    return s


class TestLoadCache:
    def test_accepts_fresh_complete_tpu(self, cache):
        cache.write_text(json.dumps(_suite()))
        assert bench._load_cache() is not None

    def test_rejects_cpu_backend(self, cache):
        cache.write_text(json.dumps(_suite(backend="cpu")))
        assert bench._load_cache() is None

    def test_rejects_incomplete_unless_asked(self, cache):
        cache.write_text(json.dumps(_suite(complete=False)))
        assert bench._load_cache() is None
        assert bench._load_cache(require_complete=False) is not None

    def test_rejects_stale_capture(self, cache):
        old = time.strftime("%Y-%m-%dT%H:%M:%S",
                            time.localtime(time.time() - 15 * 3600))
        cache.write_text(json.dumps(_suite(captured=old)))
        assert bench._load_cache() is None

    def test_rejects_missing_captured_stamp(self, cache):
        s = _suite()
        del s["captured"]
        cache.write_text(json.dumps(s))
        assert bench._load_cache() is None

    def test_rejects_failed_headline(self, cache):
        cache.write_text(json.dumps(_suite(
            fused_adam_1b={"error": "boom"})))
        assert bench._load_cache() is None

    def test_rejects_truncated_json(self, cache):
        cache.write_text(json.dumps(_suite())[:40])
        assert bench._load_cache() is None


class TestWorkerAlive:
    def _status(self, tmp_path, monkeypatch, **kw):
        qdir = tmp_path / "tools" / "chipq"
        qdir.mkdir(parents=True)
        monkeypatch.setattr(bench, "_HERE", str(tmp_path))
        kw.setdefault("t", "now")
        (qdir / "status.json").write_text(json.dumps(kw))

    def test_dead_pid_not_alive(self, tmp_path, monkeypatch):
        # find a free pid: fork-less heuristic, very large pids are unused
        self._status(tmp_path, monkeypatch, pid=2 ** 22 - 3,
                     phase="running")
        assert not bench._worker_alive()

    def test_exited_phase_not_alive(self, tmp_path, monkeypatch):
        self._status(tmp_path, monkeypatch, pid=os.getpid(),
                     phase="exited")
        assert not bench._worker_alive()

    def test_foreign_process_not_alive(self, tmp_path, monkeypatch):
        # pid 1 is alive but is the init process, not chip_worker (our own
        # pid would be unusable here: the pytest cmdline itself contains
        # "test_chip_worker.py")
        self._status(tmp_path, monkeypatch, pid=1, phase="running")
        assert not bench._worker_alive()

    def test_missing_status_not_alive(self, tmp_path, monkeypatch):
        monkeypatch.setattr(bench, "_HERE", str(tmp_path))
        assert not bench._worker_alive()


class TestAtomicWrite:
    def test_no_partial_file_visible(self, tmp_path):
        path = tmp_path / "x.json"
        bench.atomic_write_json(str(path), {"a": 1})
        assert json.load(open(path)) == {"a": 1}
        assert not os.path.exists(str(path) + ".tmp")
