"""Flash-attention Pallas kernel parity vs the unfused megatron-softmax path
(mha_reference) — fwd and bwd, causal and full."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.pallas.flash_attention import flash_attention
from apex_tpu.transformer import SelfMultiheadAttn, mha_reference

B, H, S, D = 2, 2, 256, 64  # two q/k blocks at block size 128


def _qkv(seed=0, dtype=jnp.float32, s=S):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, H, s, D), dtype)
    k = jax.random.normal(ks[1], (B, H, s, D), dtype)
    v = jax.random.normal(ks[2], (B, H, s, D), dtype)
    return q, k, v


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        o = flash_attention(q, k, v, causal)
        ref = mha_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_single_block(self):
        q, k, v = _qkv(seed=1, s=128)
        o = flash_attention(q, k, v, True)
        ref = mha_reference(q, k, v, True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_bf16_io(self):
        q, k, v = _qkv(seed=2, dtype=jnp.bfloat16)
        o = flash_attention(q, k, v, True)
        assert o.dtype == jnp.bfloat16
        ref = mha_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                            v.astype(jnp.float32), True)
        np.testing.assert_allclose(np.asarray(o, np.float32),
                                   np.asarray(ref), atol=3e-2, rtol=3e-2)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        q, k, v = _qkv(seed=3)

        def f_fused(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal) ** 2)

        gf = jax.grad(f_fused, (0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, (0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")


class TestSelfMultiheadAttn:
    def test_module_runs_and_differentiates(self):
        m = SelfMultiheadAttn(embed_dim=128, num_heads=4, causal=True,
                              use_rope=True)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 128, 128))
        v = m.init(jax.random.PRNGKey(1), x)
        y = m.apply(v, x)
        assert y.shape == x.shape
        g = jax.grad(lambda vv: jnp.sum(m.apply(vv, x) ** 2))(v)
        for leaf in jax.tree_util.tree_leaves(g):
            assert bool(jnp.all(jnp.isfinite(leaf)))

    def test_odd_seq_falls_back(self):
        m = SelfMultiheadAttn(embed_dim=32, num_heads=2, causal=True)
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 50, 32))
        v = m.init(jax.random.PRNGKey(3), x)
        assert m.apply(v, x).shape == x.shape
