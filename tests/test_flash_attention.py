"""Flash-attention Pallas kernel parity vs the unfused megatron-softmax path
(mha_reference) — fwd and bwd, causal and full."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.pallas.flash_attention import flash_attention
from apex_tpu.transformer import SelfMultiheadAttn, mha_reference

B, H, S, D = 2, 2, 256, 64  # two q/k blocks at block size 128


def _qkv(seed=0, dtype=jnp.float32, s=S):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, H, s, D), dtype)
    k = jax.random.normal(ks[1], (B, H, s, D), dtype)
    v = jax.random.normal(ks[2], (B, H, s, D), dtype)
    return q, k, v


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        o = flash_attention(q, k, v, causal)
        ref = mha_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_single_block(self):
        q, k, v = _qkv(seed=1, s=128)
        o = flash_attention(q, k, v, True)
        ref = mha_reference(q, k, v, True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_bf16_io(self):
        q, k, v = _qkv(seed=2, dtype=jnp.bfloat16)
        o = flash_attention(q, k, v, True)
        assert o.dtype == jnp.bfloat16
        ref = mha_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                            v.astype(jnp.float32), True)
        np.testing.assert_allclose(np.asarray(o, np.float32),
                                   np.asarray(ref), atol=3e-2, rtol=3e-2)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        q, k, v = _qkv(seed=3)

        def f_fused(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal) ** 2)

        gf = jax.grad(f_fused, (0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, (0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")


def _full_softmax_ref(q, k, v, causal=False, bias=None, mask=None,
                      scale=None):
    """Materialized-scores oracle with hard (-inf) masking; fully-masked
    rows produce zero output (megatron generic masked softmax semantics)."""
    import math
    s_ = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * s_
    if bias is not None:
        s = s + bias
    if mask is not None:
        s = jnp.where(mask, -jnp.inf, s)
    if causal:
        sq, sk = s.shape[-2:]
        cm = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(cm, s, -jnp.inf)
    m = jnp.max(s, -1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m)
    tot = jnp.sum(p, -1, keepdims=True)
    p = p / jnp.where(tot > 0, tot, 1.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


class TestFlashGenerality:
    """Round-2 kernel generality: arbitrary mask/bias, ragged lengths,
    dropout (VERDICT item 4; reference capability
    csrc/megatron/scaled_masked_softmax.h:211 + fast_multihead_attn)."""

    @pytest.mark.parametrize("sq,sk", [(127, 127), (384, 1000), (1000, 384),
                                       (64, 200)])
    def test_ragged_lengths(self, sq, sk):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (2, 2, sq, D)) * 0.5
        k = jax.random.normal(ks[1], (2, 2, sk, D)) * 0.5
        v = jax.random.normal(ks[2], (2, 2, sk, D)) * 0.5
        np.testing.assert_allclose(
            np.asarray(flash_attention(q, k, v)),
            np.asarray(_full_softmax_ref(q, k, v)), atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(flash_attention(q, k, v, True)),
            np.asarray(_full_softmax_ref(q, k, v, causal=True)),
            atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("sq,sk", [(256, 256), (127, 384)])
    def test_arbitrary_mask(self, sq, sk):
        ks = jax.random.split(jax.random.PRNGKey(1), 4)
        q = jax.random.normal(ks[0], (2, 2, sq, D)) * 0.5
        k = jax.random.normal(ks[1], (2, 2, sk, D)) * 0.5
        v = jax.random.normal(ks[2], (2, 2, sk, D)) * 0.5
        mask = jax.random.bernoulli(ks[3], 0.3, (2, 1, sq, sk))
        mask = mask.at[:, :, 5].set(True)  # one fully-masked row
        o = flash_attention(q, k, v, mask=mask)
        ref = _full_softmax_ref(q, k, v, mask=mask)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        # fully-masked row → exactly zero (generic masked softmax behavior)
        assert np.abs(np.asarray(o[:, :, 5])).max() == 0.0

    def test_masked_grads(self):
        ks = jax.random.split(jax.random.PRNGKey(2), 4)
        q = jax.random.normal(ks[0], (1, 2, 256, D)) * 0.5
        k = jax.random.normal(ks[1], (1, 2, 256, D)) * 0.5
        v = jax.random.normal(ks[2], (1, 2, 256, D)) * 0.5
        mask = jax.random.bernoulli(ks[3], 0.25, (1, 1, 256, 256))

        def f(impl):
            def inner(q, k, v):
                return jnp.sum(impl(q, k, v) ** 2)
            return jax.grad(inner, (0, 1, 2))(q, k, v)

        gf = f(lambda q, k, v: flash_attention(q, k, v, mask=mask))
        gr = f(lambda q, k, v: _full_softmax_ref(q, k, v, mask=mask))
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")

    def test_additive_bias_grads(self):
        """Bias is differentiable through the kernel (dbias = dlogits)."""
        ks = jax.random.split(jax.random.PRNGKey(3), 4)
        q = jax.random.normal(ks[0], (1, 2, 130, D)) * 0.5
        k = jax.random.normal(ks[1], (1, 2, 130, D)) * 0.5
        v = jax.random.normal(ks[2], (1, 2, 130, D)) * 0.5
        bias = jax.random.normal(ks[3], (1, 1, 130, 130)) * 0.5

        gf = jax.grad(lambda q, k, v, b: jnp.sum(
            flash_attention(q, k, v, bias=b) ** 2), (0, 1, 2, 3))(
                q, k, v, bias)
        gr = jax.grad(lambda q, k, v, b: jnp.sum(
            _full_softmax_ref(q, k, v, bias=b) ** 2), (0, 1, 2, 3))(
                q, k, v, bias)
        for a, b, name in zip(gf, gr, ["q", "k", "v", "bias"]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")

    def test_per_head_bias_broadcast_grad(self):
        ks = jax.random.split(jax.random.PRNGKey(4), 4)
        q = jax.random.normal(ks[0], (2, 3, 128, D)) * 0.5
        k = jax.random.normal(ks[1], (2, 3, 128, D)) * 0.5
        v = jax.random.normal(ks[2], (2, 3, 128, D)) * 0.5
        bias = jax.random.normal(ks[3], (1, 3, 128, 128)) * 0.5
        gf = jax.grad(lambda b: jnp.sum(
            flash_attention(q, k, v, bias=b) ** 2))(bias)
        gr = jax.grad(lambda b: jnp.sum(
            _full_softmax_ref(q, k, v, bias=b) ** 2))(bias)
        assert gf.shape == bias.shape
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-4, rtol=5e-4)


class TestFlashDropout:
    def test_deterministic_and_seed_varying(self):
        q, k, v = _qkv(seed=5)
        o0 = flash_attention(q, k, v, dropout_p=0.3, dropout_seed=7)
        o1 = flash_attention(q, k, v, dropout_p=0.3, dropout_seed=7)
        o2 = flash_attention(q, k, v, dropout_p=0.3, dropout_seed=8)
        assert np.allclose(np.asarray(o0), np.asarray(o1))
        assert not np.allclose(np.asarray(o0), np.asarray(o2))

    def test_zero_rate_matches_plain(self):
        q, k, v = _qkv(seed=6)
        o = flash_attention(q, k, v, dropout_p=0.0, dropout_seed=1)
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(flash_attention(q, k, v)),
                                   atol=1e-6, rtol=1e-6)

    def test_keep_rate_statistics(self):
        """Fraction of dropped attention entries ≈ dropout_p."""
        q, k, v = _qkv(seed=7)
        # v == identity-ish probe: use v = ones so output row = sum of kept
        # normalized probs / (1-p); its mean over many rows ≈ 1
        v1 = jnp.ones_like(v)
        o = flash_attention(q, k, v1, dropout_p=0.25, dropout_seed=3)
        # E[o] = 1 (each prob kept w.p. 0.75, scaled by 1/0.75)
        assert abs(float(jnp.mean(o[..., 0])) - 1.0) < 0.05

    def test_grad_matches_reference_with_same_mask(self):
        """Autodiff through the dropout kernel == reference attention using
        the identical regenerated keep-mask (exact, not statistical)."""
        import math
        ks = jax.random.split(jax.random.PRNGKey(8), 3)
        q = jax.random.normal(ks[0], (1, 2, 256, D)) * 0.5
        k = jax.random.normal(ks[1], (1, 2, 256, D)) * 0.5
        v = jax.random.normal(ks[2], (1, 2, 256, D)) * 0.5
        p_drop, seed = 0.3, 11

        # regenerate the kernel's keep mask with the same hash
        from apex_tpu.ops.pallas.flash_attention import _dropout_keep

        class _Seed:
            def __getitem__(self, _):
                return jnp.int32(seed)

        keeps = []
        for b_ in range(2):  # b*h = 2
            keeps.append(_dropout_keep(_Seed(), jnp.int32(b_), jnp.int32(0),
                                       jnp.int32(0), 256, 256, p_drop))
        keep = jnp.stack(keeps).reshape(1, 2, 256, 256)

        def ref_drop(q, k, v):
            s_ = 1.0 / math.sqrt(q.shape[-1])
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s_
            p = jax.nn.softmax(s, -1) * keep
            return jnp.einsum("bhqk,bhkd->bhqd", p, v)

        of = flash_attention(q, k, v, dropout_p=p_drop, dropout_seed=seed,
                             block_q=256, block_k=256)
        orf = ref_drop(q, k, v)
        np.testing.assert_allclose(np.asarray(of), np.asarray(orf),
                                   atol=2e-5, rtol=2e-5)

        gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, dropout_p=p_drop, dropout_seed=seed, block_q=256,
            block_k=256) ** 2), (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(ref_drop(q, k, v) ** 2),
                      (0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")


class TestSelfMultiheadAttn:
    def test_module_runs_and_differentiates(self):
        m = SelfMultiheadAttn(embed_dim=128, num_heads=4, causal=True,
                              use_rope=True)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 128, 128))
        v = m.init(jax.random.PRNGKey(1), x)
        y = m.apply(v, x)
        assert y.shape == x.shape
        g = jax.grad(lambda vv: jnp.sum(m.apply(vv, x) ** 2))(v)
        for leaf in jax.tree_util.tree_leaves(g):
            assert bool(jnp.all(jnp.isfinite(leaf)))

    def test_odd_seq_falls_back(self):
        m = SelfMultiheadAttn(embed_dim=32, num_heads=2, causal=True)
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 50, 32))
        v = m.init(jax.random.PRNGKey(3), x)
        assert m.apply(v, x).shape == x.shape
