"""Resilience subsystem: fault-injection tests (marker: ``fault``).

Every production failure the subsystem claims to survive is reproduced
here deterministically: torn writes and manifest corruption (restore_latest
recovers the newest good step bit-identically), transient EIO (retry with
backoff), preemption signals (save-and-stop through PreemptionGuard), and
NaN/Inf overflow storms (the scale never collapses below the floor).
"""

import errno
import io
import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.amp.grad_scaler import DynamicGradScaler
from apex_tpu.resilience import (CheckpointCorruptError, CheckpointError,
                                 CheckpointManager, FaultInjector,
                                 PreemptionGuard, SimulatedCrash,
                                 resilient_step, skip_on_overflow)
from apex_tpu.utils.logging import structured_warning

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(seed: float = 0.0):
    return {"w": jnp.arange(16.0).reshape(4, 4) + seed,
            "b": jnp.ones((8,), jnp.bfloat16) * (1.0 + seed),
            "step": jnp.int32(seed)}


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------- manager

def test_roundtrip_bit_identical_and_retention(tmp_path):
    m = CheckpointManager(str(tmp_path), max_to_keep=2)
    trees = {s: _tree(s) for s in (1, 2, 3)}
    for s, t in trees.items():
        m.save(s, t)
    assert m.all_steps() == [2, 3]  # step 1 rotated out
    step, back = m.restore_latest(_tree())
    assert step == 3
    _assert_tree_equal(back, trees[3])


def test_restore_latest_empty_dir(tmp_path):
    m = CheckpointManager(str(tmp_path))
    assert m.restore_latest(_tree()) is None
    assert m.latest_step() is None


@pytest.mark.fault
def test_torn_write_mid_save_is_invisible(tmp_path):
    """A crash mid-save (torn leaf write) leaves only an uncommitted .tmp:
    restore_latest still returns the previous step, bit-identical, and the
    next successful save garbage-collects the staging dir."""
    good = _tree(1)
    CheckpointManager(str(tmp_path)).save(1, good)

    inj = FaultInjector(seed=7).torn_write(2, fraction=0.3)
    m = CheckpointManager(str(tmp_path), fs=inj.filesystem(), retries=0)
    with pytest.raises(SimulatedCrash):
        m.save(2, _tree(2))
    assert any(n.endswith(".tmp") for n in os.listdir(tmp_path))
    assert m.all_steps() == [1]

    step, back = m.restore_latest(_tree())
    assert step == 1
    _assert_tree_equal(back, good)

    m.save(3, _tree(3))  # recovery save prunes the stale .tmp
    assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))


@pytest.mark.fault
def test_kill_mid_save_plus_manifest_corruption_resumes_bit_identical(
        tmp_path, capsys):
    """Acceptance: kill mid-save of the newest step AND corrupt the newest
    committed manifest — restore_latest recovers the newest valid step with
    bit-identical state and training resumes from it."""
    m0 = CheckpointManager(str(tmp_path), max_to_keep=None)
    params = {"w": jnp.full((4, 4), 0.5), "m": jnp.zeros((4, 4))}

    @jax.jit
    def train_step(p):
        return jax.tree_util.tree_map(lambda x: x * 1.5 + 0.25, p)

    history = {}
    for step in range(1, 3):
        params = train_step(params)
        history[step] = params
        m0.save(step, params)

    # the process "dies" partway through saving step 3
    inj = FaultInjector(seed=3).torn_write(1, fraction=0.6)
    killed = CheckpointManager(str(tmp_path), fs=inj.filesystem(), retries=0)
    with pytest.raises(SimulatedCrash):
        killed.save(3, train_step(params))
    # ... and the newest *committed* checkpoint rots on disk
    manifest = os.path.join(m0.step_path(2), "manifest.json")
    raw = open(manifest, "rb").read()
    open(manifest, "wb").write(raw[:len(raw) // 2])

    # a fresh process resumes
    m1 = CheckpointManager(str(tmp_path))
    restored = m1.restore_latest(jax.tree_util.tree_map(
        lambda x: jnp.zeros_like(x), params))
    assert restored is not None
    step, state = restored
    assert step == 1
    _assert_tree_equal(state, history[1])  # bit-identical
    err = capsys.readouterr().err
    assert "checkpoint_skipped_corrupt" in err

    # training continues from the recovered state and recomputes step 2
    recomputed = train_step(state)
    _assert_tree_equal(recomputed, history[2])


@pytest.mark.fault
def test_corrupt_leaf_checksum_detected(tmp_path):
    m = CheckpointManager(str(tmp_path))
    m.save(1, _tree(1))
    leaf = os.path.join(m.step_path(1), "leaf_00000.npy")
    data = bytearray(open(leaf, "rb").read())
    data[-1] ^= 0xFF  # same length, different bytes: only the CRC sees it
    open(leaf, "wb").write(bytes(data))
    with pytest.raises(CheckpointCorruptError, match="checksum"):
        m.restore(1, _tree())
    assert m.restore_latest(_tree()) is None


@pytest.mark.fault
def test_transient_eio_retries_with_backoff(tmp_path, capsys):
    sleeps = []
    inj = FaultInjector().fail_write(1, err=errno.EIO, count=2)
    m = CheckpointManager(str(tmp_path), fs=inj.filesystem(), retries=3,
                          backoff_base=0.05, sleep=sleeps.append)
    m.save(5, _tree(5))
    assert m.all_steps() == [5]
    assert sleeps == [0.05, 0.1]  # exponential backoff, injected sleep
    assert capsys.readouterr().err.count("checkpoint_save_retry") == 2
    step, back = m.restore_latest(_tree())
    assert step == 5
    _assert_tree_equal(back, _tree(5))


@pytest.mark.fault
def test_retry_exhaustion_raises_checkpoint_error(tmp_path):
    inj = FaultInjector().fail_write(1, err=errno.ENOSPC, count=50)
    m = CheckpointManager(str(tmp_path), fs=inj.filesystem(), retries=2,
                          sleep=lambda s: None)
    with pytest.raises(CheckpointError, match="after 3 attempts"):
        m.save(1, _tree())
    assert m.all_steps() == []


# ------------------------------------------------------------- preemption

@pytest.mark.fault
def test_preemption_signal_saves_and_stops(tmp_path):
    m = CheckpointManager(str(tmp_path))
    inj = FaultInjector()
    params = _tree(0)
    saved_at = []
    with PreemptionGuard() as guard:
        for step in range(100):
            params = jax.tree_util.tree_map(lambda x: x, params)
            if step == 3:
                inj.fire_preemption(signal.SIGTERM)
            if guard.should_stop():
                m.save(step, params)
                saved_at.append(step)
                break
    assert saved_at == [3]
    assert guard.received_signal == signal.SIGTERM
    assert m.all_steps() == [3]
    # handlers restored after the with-block
    assert signal.getsignal(signal.SIGTERM) not in (guard._handler,)


@pytest.mark.fault
def test_resave_same_step_is_crash_safe(tmp_path):
    """Re-saving an existing step never deletes the old commit before the
    new one lands: a crash while staging the re-save leaves the original
    restorable, and a successful re-save replaces content with no
    .old/.tmp debris."""
    m = CheckpointManager(str(tmp_path))
    first = _tree(1)
    m.save(1, first)

    inj = FaultInjector().torn_write(1, fraction=0.5)
    crashy = CheckpointManager(str(tmp_path), fs=inj.filesystem(), retries=0)
    with pytest.raises(SimulatedCrash):
        crashy.save(1, _tree(9))  # dies staging the re-save
    step, back = m.restore_latest(_tree())
    assert step == 1
    _assert_tree_equal(back, first)

    second = _tree(5)
    m.save(1, second)  # successful re-save replaces the content
    step, back = m.restore_latest(_tree())
    assert step == 1
    _assert_tree_equal(back, second)
    assert not any(n.endswith((".tmp", ".old")) for n in os.listdir(tmp_path))


def test_preemption_raise_on_signal_unwinds_and_finalizes(tmp_path):
    """raise_on_signal: straight-line work (no step loop) unwinds at the
    signal, on_preempt still runs once, and the with-block exits cleanly."""
    m = CheckpointManager(str(tmp_path))
    state = _tree(4)
    reached_end = False
    with PreemptionGuard(on_preempt=lambda: m.save(7, state),
                         raise_on_signal=True) as guard:
        os.kill(os.getpid(), signal.SIGTERM)
        reached_end = True  # never reached: the handler raises
    assert not reached_end
    assert guard.should_stop()
    assert m.all_steps() == [7]
    _assert_tree_equal(m.restore(7, _tree()), state)


def test_preemption_finalize_runs_once():
    calls = []
    guard = PreemptionGuard(on_preempt=lambda: calls.append(1))
    with guard:
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.should_stop()
    assert calls == [1]  # __exit__ ran the final save
    assert guard.finalize() is False  # idempotent
    assert calls == [1]


# ---------------------------------------------------------- overflow storm

@pytest.mark.fault
def test_overflow_storm_never_collapses_scale(capsys):
    """30-step NaN/Inf burst: every bad step is skipped (params frozen),
    the scale never goes below the floor, degraded mode announces itself
    once, and training resumes when gradients are finite again."""
    inj = FaultInjector(seed=11).nan_burst(start=2, length=30)
    scaler = DynamicGradScaler(init_scale=2.0 ** 10, growth_interval=4)

    def step_fn(params, sstate, grads):
        found_inf = jnp.any(jnp.stack([
            jnp.any(~jnp.isfinite(g))
            for g in jax.tree_util.tree_leaves(grads)]))
        new = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)
        return new, found_inf, jnp.float32(0.0)

    step = resilient_step(step_fn, scaler, max_consecutive_overflows=4)
    assert scaler.min_scale is None  # caller's scaler is never mutated
    params = {"w": jnp.ones((4,))}
    sstate = scaler.init()
    clean_grads = {"w": jnp.full((4,), 0.5)}

    for i in range(40):
        grads = inj.poison_grads(clean_grads, i)
        before = params
        params, sstate, found_inf, _loss = step(params, sstate, grads)
        assert float(sstate.scale) >= step.scale_floor
        if inj.grads_faulty(i):
            _assert_tree_equal(params, before)  # bad step skipped
    assert step.degraded and step.skipped_steps == 30
    assert bool(jnp.all(jnp.isfinite(params["w"])))
    # params moved once the storm passed
    assert float(params["w"][0]) != 1.0

    err = capsys.readouterr().err
    assert err.count('"event": "overflow_storm"') == 1

    step.reset_degraded()
    assert not step.degraded and step.consecutive_overflows == 0


def test_skip_on_overflow_is_jittable():
    @jax.jit
    def f(new, old, bad):
        return skip_on_overflow(new, old, bad)

    new, old = {"a": jnp.ones((3,))}, {"a": jnp.zeros((3,))}
    np.testing.assert_array_equal(
        np.asarray(f(new, old, jnp.bool_(True))["a"]), np.zeros((3,)))
    np.testing.assert_array_equal(
        np.asarray(f(new, old, jnp.bool_(False))["a"]), np.ones((3,)))


def test_scaler_min_scale_and_freeze_growth():
    scaler = DynamicGradScaler(init_scale=4.0, growth_interval=1,
                               min_scale=2.0)
    state = scaler.init()
    state = scaler.update(state, jnp.bool_(True))   # 4 -> 2
    assert float(state.scale) == 2.0
    state = scaler.update(state, jnp.bool_(True))   # clamped at floor
    assert float(state.scale) == 2.0
    frozen = scaler.update(state, jnp.bool_(False), freeze_growth=True)
    assert float(frozen.scale) == 2.0               # growth suppressed
    grown = scaler.update(state, jnp.bool_(False))
    assert float(grown.scale) == 4.0                # normal growth works


# ------------------------------------------------- utils.checkpoint fixes

def test_save_numpy_atomic_no_tmp_left(tmp_path):
    from apex_tpu.utils.checkpoint import restore_numpy, save_numpy
    tree = {"a": jnp.arange(6.0)}
    path = str(tmp_path / "ck")
    save_numpy(path, tree)
    assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))
    _assert_tree_equal(restore_numpy(path, tree), tree)


@pytest.mark.fault
def test_save_numpy_crash_mid_write_preserves_previous(tmp_path,
                                                       monkeypatch):
    from apex_tpu.utils import checkpoint as ckpt
    tree = {"a": jnp.arange(6.0)}
    path = str(tmp_path / "ck")
    ckpt.save_numpy(path, tree)

    def boom(f, **kw):
        f.write(b"partial")
        raise SimulatedCrash("died mid-savez")

    monkeypatch.setattr(ckpt.np, "savez", boom)
    with pytest.raises(SimulatedCrash):
        ckpt.save_numpy(path, {"a": jnp.zeros((6,))})
    # the committed checkpoint is untouched by the torn staging write
    _assert_tree_equal(ckpt.restore_numpy(path, tree), tree)


def test_restore_numpy_accepts_both_spellings(tmp_path):
    from apex_tpu.utils.checkpoint import restore_numpy, save_numpy
    tree = {"a": jnp.arange(4.0)}
    base = str(tmp_path / "ck")
    save_numpy(base, tree)
    _assert_tree_equal(restore_numpy(base, tree), tree)
    _assert_tree_equal(restore_numpy(base + ".npz", tree), tree)


def test_restore_numpy_missing_names_candidates(tmp_path):
    from apex_tpu.utils.checkpoint import restore_numpy
    base = str(tmp_path / "nope")
    with pytest.raises(FileNotFoundError) as ei:
        restore_numpy(base, {"a": jnp.zeros(1)})
    assert "nope" in str(ei.value) and "nope.npz" in str(ei.value)


def test_async_save_handle_surfaces_writer_failure():
    from apex_tpu.utils.checkpoint import AsyncSaveHandle

    class FailingCkptr:
        closed = False

        def wait_until_finished(self):
            raise IOError("disk full in background writer")

        def close(self):
            self.closed = True

    ckptr = FailingCkptr()
    h = AsyncSaveHandle(ckptr, "/ckpt/step_7")
    with pytest.raises(RuntimeError, match=r"/ckpt/step_7.*disk full"):
        h.wait()
    assert ckptr.closed
    # a failed save must never later read as durable: every wait() re-raises
    with pytest.raises(RuntimeError, match=r"/ckpt/step_7.*disk full"):
        h.wait()


# ----------------------------------------------------- logging + tooling

def test_structured_warning_record_and_json():
    buf = io.StringIO()
    rec = structured_warning("unit_test_event", stream=buf, value=3,
                             scale=jnp.float32(2.0))
    assert rec["event"] == "unit_test_event" and rec["level"] == "warning"
    parsed = json.loads(buf.getvalue())
    assert parsed["value"] == 3 and parsed["scale"] == 2.0


@pytest.mark.fault
def test_check_durability_tool_clean_and_catches_violation(tmp_path):
    # the durability checker is apexlint rule APX004 now — the canonical
    # entry point is the linter; the old script stays a working shim
    r = subprocess.run([sys.executable, "-m", "tools.apexlint",
                        "--rules", "APX004", "apex_tpu"],
                       capture_output=True, text=True, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run([sys.executable,
                        os.path.join(ROOT, "tools", "check_durability.py")],
                       capture_output=True, text=True, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from check_durability import _check_file
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad_checkpoint.py"
    bad.write_text(
        "import numpy as np\n"
        "def save_checkpoint(path, arr):\n"
        "    np.savez(path, arr=arr)\n")
    assert _check_file(str(bad)), "non-atomic checkpoint write not flagged"
    good = tmp_path / "good_checkpoint.py"
    good.write_text(
        "import numpy as np, os\n"
        "def save_checkpoint(path, arr):\n"
        "    with open(path + '.tmp', 'wb') as f:\n"
        "        np.savez(f, arr=arr)\n"
        "    os.replace(path + '.tmp', path)\n")
    assert not _check_file(str(good))
