"""Contrib package tests — the in-package test pattern of
apex/contrib/test/<pkg>/test_*.py (every package gets coverage; parity vs
python/torch references)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.contrib.conv_bias_relu import (conv_bias, conv_bias_mask_relu,
                                             conv_bias_relu)
from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.group_norm import GroupNorm, group_norm_nhwc
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.layer_norm import FastLayerNorm
from apex_tpu.contrib.openfold_triton import FusedAdamSWA
from apex_tpu.contrib.optimizers import FP16_Optimizer
from apex_tpu.contrib.sparsity import ASP, create_mask
from apex_tpu.contrib.transducer import (TransducerJoint, transducer_joint,
                                         transducer_loss)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import get_mesh


class TestClipGrad:
    def test_vs_torch(self):
        grads = [jax.random.normal(jax.random.PRNGKey(i), (7, 5)) * 3
                 for i in range(3)]
        clipped, total = clip_grad_norm_(grads, 1.0)
        tg = [torch.nn.Parameter(torch.tensor(np.asarray(g)))
              for g in grads]
        for p, g in zip(tg, grads):
            p.grad = torch.tensor(np.asarray(g))
        tnorm = torch.nn.utils.clip_grad_norm_(tg, 1.0)
        np.testing.assert_allclose(float(total), float(tnorm), rtol=1e-5)
        for a, b in zip(clipped, tg):
            np.testing.assert_allclose(np.asarray(a), b.grad.numpy(),
                                       atol=1e-6)

    def test_no_clip_when_under(self):
        grads = [jnp.ones((4,)) * 0.01]
        clipped, total = clip_grad_norm_(grads, 10.0)
        np.testing.assert_allclose(np.asarray(clipped[0]),
                                   np.asarray(grads[0]), rtol=1e-6)


class TestFocalLoss:
    def test_matches_manual_sigmoid_focal(self):
        k = 5
        logits = jax.random.normal(jax.random.PRNGKey(0), (8, k))
        targets = jnp.array([0, 1, 2, -1, 5, 3, 0, 2])  # -1 ignore
        npos = jnp.float32(4.0)
        loss = focal_loss(logits, targets, npos, k, 0.25, 2.0, 0.0)
        # manual reference
        x = np.asarray(logits, np.float64)
        t = np.asarray(targets)
        onehot = np.zeros((8, k))
        for i, ti in enumerate(t):
            if ti >= 1:
                onehot[i, ti - 1] = 1.0
        p = 1 / (1 + np.exp(-x))
        ce = -(onehot * np.log(p) + (1 - onehot) * np.log(1 - p))
        pt = p * onehot + (1 - p) * (1 - onehot)
        at = 0.25 * onehot + 0.75 * (1 - onehot)
        per = at * (1 - pt) ** 2 * ce
        per[t < 0] = 0.0
        ref = per.sum() / 4.0
        np.testing.assert_allclose(float(loss), ref, rtol=1e-4)

    def test_grad_finite_and_zero_for_ignored(self):
        k = 4
        logits = jax.random.normal(jax.random.PRNGKey(1), (6, k))
        targets = jnp.array([1, -1, 2, 0, 4, -1])
        g = jax.grad(lambda x: focal_loss(x, targets, jnp.float32(3), k))(
            logits)
        assert bool(jnp.all(jnp.isfinite(g)))
        np.testing.assert_array_equal(np.asarray(g[1]), 0.0)
        np.testing.assert_array_equal(np.asarray(g[5]), 0.0)


class TestIndexMul2d:
    def test_forward_and_double_backward(self):
        in1 = jax.random.normal(jax.random.PRNGKey(0), (10, 4))
        in2 = jax.random.normal(jax.random.PRNGKey(1), (6, 4))
        idx = jnp.array([0, 3, 3, 9, 1, 0])
        out = index_mul_2d(in1, in2, idx)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(in1)[np.asarray(idx)]
                                   * np.asarray(in2), rtol=1e-6)
        # scatter-add grad for repeated indices
        g1 = jax.grad(lambda a: jnp.sum(index_mul_2d(a, in2, idx)))(in1)
        row0 = np.asarray(in2)[0] + np.asarray(in2)[5]  # idx 0 twice
        np.testing.assert_allclose(np.asarray(g1[0]), row0, rtol=1e-6)
        # double backward exists
        h = jax.hessian(
            lambda a: jnp.sum(index_mul_2d(a, in2, idx) ** 2))(in1[:2])
        assert np.all(np.isfinite(np.asarray(h)))


class TestGroupNorm:
    def test_vs_torch(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 16))
        w = 1 + 0.1 * jax.random.normal(jax.random.PRNGKey(1), (16,))
        b = 0.1 * jax.random.normal(jax.random.PRNGKey(2), (16,))
        y = group_norm_nhwc(x, 4, w, b)
        tx = torch.tensor(np.asarray(x)).permute(0, 3, 1, 2)
        ty = torch.nn.functional.group_norm(
            tx, 4, torch.tensor(np.asarray(w)), torch.tensor(np.asarray(b)))
        np.testing.assert_allclose(np.asarray(y),
                                   ty.permute(0, 2, 3, 1).numpy(),
                                   atol=1e-5)

    def test_fused_silu(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (1, 2, 2, 8))
        y = group_norm_nhwc(x, 2, None, None, act="silu")
        y0 = group_norm_nhwc(x, 2, None, None)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(y0 * jax.nn.sigmoid(y0)), atol=1e-6)

    def test_module(self):
        m = GroupNorm(num_groups=2, num_channels=8, act="silu")
        x = jnp.ones((1, 2, 2, 8))
        v = m.init(jax.random.PRNGKey(0), x)
        assert m.apply(v, x).shape == x.shape


class TestFastLayerNorm:
    def test_matches_torch(self):
        m = FastLayerNorm(hidden_size=256)
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 256))
        v = m.init(jax.random.PRNGKey(1), x)
        y = m.apply(v, x)
        ty = torch.nn.functional.layer_norm(torch.tensor(np.asarray(x)),
                                            (256,))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-5)


class TestGroupBN:
    def test_bn_group_subsets(self):
        """bn_group=4 on an 8-device axis: stats reduced within each half
        (the test_groups.py scenario)."""
        mesh = get_mesh("data")
        C = 6
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 2, 2, C))
        bn = BatchNorm2d_NHWC(num_features=C, axis_name="data", bn_group=4,
                              world_size=8)
        v = bn.init(jax.random.PRNGKey(1), x[:2])

        @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("data")),
                           out_specs=P("data"), check_vma=False)
        def apply(v, xb):
            y, _ = bn.apply(v, xb, use_running_average=False,
                            mutable=["batch_stats"])
            return y

        y = apply(v, x)
        yn = np.asarray(y)
        # normalize first half with first-half stats == zero mean per group
        first = yn[:8].reshape(-1, C)
        np.testing.assert_allclose(first.mean(0), 0.0, atol=1e-4)

    def test_fuse_add_relu(self):
        C = 4
        x = jax.random.normal(jax.random.PRNGKey(2), (4, 2, 2, C))
        z = jax.random.normal(jax.random.PRNGKey(3), (4, 2, 2, C))
        bn = BatchNorm2d_NHWC(num_features=C, fuse_relu=True)
        v = bn.init(jax.random.PRNGKey(4), x)
        y, _ = bn.apply(v, x, z, use_running_average=False,
                        mutable=["batch_stats"])
        assert float(np.asarray(y).min()) >= 0.0


class TestConvBiasReLU:
    def test_matches_composed(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 3))
        w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, 8)) * 0.1
        b = jax.random.normal(jax.random.PRNGKey(2), (8,)) * 0.1
        y = conv_bias_relu(x, w, b, stride=1, padding=1)
        y0 = conv_bias(x, w, b, stride=1, padding=1)
        np.testing.assert_allclose(np.asarray(y),
                                   np.maximum(np.asarray(y0), 0), atol=1e-6)
        mask = (jax.random.uniform(jax.random.PRNGKey(3),
                                   y0.shape) > 0.5).astype(jnp.float32)
        ym = conv_bias_mask_relu(x, w, b, mask, stride=1, padding=1)
        np.testing.assert_allclose(
            np.asarray(ym), np.maximum(np.asarray(y0) * np.asarray(mask), 0),
            atol=1e-6)


class TestTransducer:
    def test_joint(self):
        f = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 4))
        g = jax.random.normal(jax.random.PRNGKey(1), (2, 5, 4))
        h = transducer_joint(f, g)
        ref = np.asarray(f)[:, :, None, :] + np.asarray(g)[:, None, :, :]
        np.testing.assert_allclose(np.asarray(h), ref, atol=1e-6)
        hr = TransducerJoint(relu=True)(f, g)
        np.testing.assert_allclose(np.asarray(hr), np.maximum(ref, 0),
                                   atol=1e-6)

    def test_loss_matches_bruteforce(self):
        """Enumerate all monotone alignments for a tiny case."""
        T, U, V = 3, 3, 4  # 2 labels
        key = jax.random.PRNGKey(0)
        logits = jax.random.normal(key, (1, T, U, V))
        lp = jax.nn.log_softmax(logits, axis=-1)
        labels = jnp.array([[1, 2]])
        loss = transducer_loss(lp, labels, jnp.array([T]), jnp.array([U - 1]))

        lpn = np.asarray(lp[0], np.float64)
        lab = [1, 2]
        # brute force: paths of T blanks + U-1 labels
        import itertools
        total = -np.inf
        steps = ["B"] * T + ["L"] * (U - 1)
        for perm in set(itertools.permutations(steps)):
            t = u = 0
            logp = 0.0
            ok = True
            for s in perm:
                if s == "B":
                    if t >= T:
                        ok = False
                        break
                    logp += lpn[t, u, 0]
                    t += 1
                else:
                    if u >= U - 1 or t >= T:
                        ok = False
                        break
                    logp += lpn[t, u, lab[u]]
                    u += 1
            # must consume exactly T blanks ending at t==T (last blank from
            # (T-1, U-1)); standard RNNT: path ends after blank at (T-1,U-1)
            if ok and t == T and u == U - 1:
                total = np.logaddexp(total, logp)
        np.testing.assert_allclose(float(loss[0]), -total, rtol=1e-4)

    def test_loss_grad_finite(self):
        lp = jax.nn.log_softmax(
            jax.random.normal(jax.random.PRNGKey(1), (2, 4, 3, 5)), axis=-1)
        labels = jnp.array([[1, 2], [3, 4]])
        g = jax.grad(lambda x: jnp.sum(transducer_loss(
            x, labels, jnp.array([4, 3]), jnp.array([2, 2]))))(lp)
        assert bool(jnp.all(jnp.isfinite(g)))


class TestASP:
    def test_mask_is_2_of_4(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (8, 16))
        m = create_mask(w, "m4n2_1d")
        mn = np.asarray(m).reshape(8, 4, 4)
        np.testing.assert_array_equal(mn.sum(-1), 2)
        # keeps the two largest magnitudes per group
        wn = np.abs(np.asarray(w)).reshape(8, 4, 4)
        kept = np.sort(np.where(mn, wn, 0).sum(-1))
        top2 = np.sort(np.sort(wn, axis=-1)[..., -2:].sum(-1))
        np.testing.assert_allclose(kept, top2, rtol=1e-6)

    def test_prune_and_optimizer_wrap(self):
        params = [jax.random.normal(jax.random.PRNGKey(0), (8, 8)),
                  jax.random.normal(jax.random.PRNGKey(1), (8,))]
        asp = ASP()
        opt = FusedAdam(params, lr=0.1)
        pruned = asp.prune_trained_model(params, opt)
        opt._params = pruned
        m = np.asarray(asp.masks[0])
        assert m.sum() == m.size // 2
        assert np.asarray(asp.masks[1]).all()  # 1-D not pruned
        p = opt.step([jnp.ones((8, 8)), jnp.ones((8,))])
        # pruned positions stay exactly zero after the step
        np.testing.assert_array_equal(np.asarray(p[0])[~m], 0.0)

    def test_checkpoint_roundtrip(self):
        params = [jax.random.normal(jax.random.PRNGKey(2), (4, 8))]
        asp = ASP()
        asp.init_model_for_pruning(params)
        asp.compute_sparse_masks(params)
        sd = asp.state_dict()
        asp2 = ASP()
        asp2.load_state_dict(sd)
        np.testing.assert_array_equal(np.asarray(asp.masks[0]),
                                      np.asarray(asp2.masks[0]))


class TestFusedAdamSWA:
    def test_ema_tracks_params(self):
        params = [jnp.ones((16,))]
        opt = FusedAdamSWA(params, lr=0.1, swa_decay_rate=0.5)
        for _ in range(5):
            opt.step([jnp.ones((16,))])
        p = float(np.asarray(opt.parameters[0])[0])
        s = float(np.asarray(opt.swa_parameters[0])[0])
        assert p < 1.0 and p < s < 1.0  # EMA lags the moving params


class TestFP16Optimizer:
    def test_dynamic_scaling_flow(self):
        params = [jnp.ones((8,), jnp.float32)]
        opt = FP16_Optimizer(FusedAdam(params, lr=0.1),
                             dynamic_loss_scale=True,
                             dynamic_loss_args={"init_scale": 64.0})
        scaled_grads = [jnp.full((8,), 64.0)]  # true grad 1.0
        p = opt.step(scaled_grads)
        assert not np.allclose(np.asarray(p[0]), 1.0)
        bad = [jnp.full((8,), jnp.inf)]
        p2 = opt.step(bad)
        np.testing.assert_array_equal(np.asarray(p2[0]), np.asarray(p[0]))
        assert opt.loss_scale == 32.0


class TestASPFlatOptimizers:
    def test_flat_fused_adam_respects_masks(self):
        params = [jax.random.normal(jax.random.PRNGKey(0), (8, 8))]
        asp = ASP()
        opt = FusedAdam(params, lr=0.1, use_flat=True)
        pruned = asp.prune_trained_model(params, opt)
        opt.set_parameters(pruned)
        m = np.asarray(asp.masks[0])
        p = opt.step([jnp.ones((8, 8))])
        np.testing.assert_array_equal(np.asarray(p[0])[~m], 0.0)
        # a second step keeps the internal flat master masked too
        p = opt.step([jnp.ones((8, 8))])
        np.testing.assert_array_equal(np.asarray(p[0])[~m], 0.0)

    def test_zero_adam_respects_masks(self):
        from apex_tpu.optimizers.distributed_fused_adam import (
            DistributedFusedAdam)
        mesh = get_mesh("data")
        params = [jax.random.normal(jax.random.PRNGKey(1), (8, 16))]
        asp = ASP()
        opt = DistributedFusedAdam(params, mesh, lr=0.1)
        pruned = asp.prune_trained_model(params, opt)
        opt.set_parameters(pruned)
        m = np.asarray(asp.masks[0])
        p = opt.step([jnp.ones((8, 16))])
        np.testing.assert_array_equal(np.asarray(p[0])[~m], 0.0)


class TestSpatialBottleneck:
    @pytest.mark.slow
    def test_matches_unsharded_bottleneck(self):
        """H-sharded SpatialBottleneck == Bottleneck on the full input
        (the reference's spatial-parallel correctness property)."""
        from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck
        mesh = get_mesh("spatial")
        C_in, C_mid, C_out = 8, 4, 8
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8 * 4, 6, C_in),
                              jnp.float32)
        full = Bottleneck(C_in, C_mid, C_out, compute_dtype=jnp.float32)
        vfull = full.init(jax.random.PRNGKey(1), x)
        sp = SpatialBottleneck(C_in, C_mid, C_out,
                               compute_dtype=jnp.float32,
                               spatial_axis_name="spatial")
        # same param shapes/names → reuse the full variables
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(), P(None, "spatial")),
                           out_specs=P(None, "spatial"), check_vma=False)
        def run(v, xb):
            y, _ = sp.apply(v, xb, use_running_average=False,
                            mutable=["batch_stats"])
            return y

        y_sp = run(vfull, x)
        y_full, _ = full.apply(vfull, x, use_running_average=False,
                               mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_sp), np.asarray(y_full),
                                   atol=1e-4, rtol=1e-4)


class TestGroupNormPallas:
    def test_pallas_path_matches_jnp(self):
        from apex_tpu.contrib.group_norm import _gn_jnp, group_norm_nhwc
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 32))  # HW=16
        w = 1 + 0.1 * jax.random.normal(jax.random.PRNGKey(1), (32,))
        b = 0.1 * jax.random.normal(jax.random.PRNGKey(2), (32,))
        for act in ("", "silu"):
            y = group_norm_nhwc(x, 8, w, b, act=act)  # pallas (16 % 8 == 0)
            ref = _gn_jnp(x, 8, w, b, 1e-5, act)
            np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                       atol=1e-5, rtol=1e-5)

    def test_pallas_grads_match_jnp(self):
        from apex_tpu.contrib.group_norm import _gn_jnp, group_norm_nhwc
        x = jax.random.normal(jax.random.PRNGKey(3), (2, 4, 4, 16))
        w = 1 + 0.1 * jax.random.normal(jax.random.PRNGKey(4), (16,))
        b = 0.1 * jax.random.normal(jax.random.PRNGKey(5), (16,))
        for act in ("", "silu"):
            gp = jax.grad(lambda x, w, b: jnp.sum(
                group_norm_nhwc(x, 4, w, b, act=act) ** 2), (0, 1, 2))(
                    x, w, b)
            gr = jax.grad(lambda x, w, b: jnp.sum(
                _gn_jnp(x, 4, w, b, 1e-5, act) ** 2), (0, 1, 2))(x, w, b)
            for a, r in zip(gp, gr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                           atol=1e-4, rtol=1e-4)

    def test_odd_hw_falls_back(self):
        from apex_tpu.contrib.group_norm import group_norm_nhwc
        x = jax.random.normal(jax.random.PRNGKey(6), (1, 3, 3, 8))  # HW=9
        y = group_norm_nhwc(x, 2)
        assert bool(jnp.all(jnp.isfinite(y)))


class TestGroupNormOnePass:
    """Round-3: one-pass algorithm + selection heuristic (VERDICT r2 item 8;
    reference one-pass group_norm_nhwc_one_pass_*.cu, selection
    group_norm.py:193-209)."""

    def test_one_pass_matches_two_pass_and_jnp(self):
        from apex_tpu.contrib.group_norm import _gn_jnp
        from apex_tpu.ops.pallas.group_norm_kernel import \
            group_norm_nhwc_pallas
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 64)) * 2 + 1
        w = 1 + 0.1 * jax.random.normal(jax.random.PRNGKey(1), (64,))
        b = 0.1 * jax.random.normal(jax.random.PRNGKey(2), (64,))
        for act in ("", "silu"):
            y1, m1, r1 = group_norm_nhwc_pallas(x, 8, w, b, act=act,
                                                algo="one_pass")
            y2, m2, r2 = group_norm_nhwc_pallas(x, 8, w, b, act=act,
                                                algo="two_pass")
            ref = _gn_jnp(x, 8, w, b, 1e-5, act)
            np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                       atol=1e-5, rtol=1e-5)
            np.testing.assert_allclose(np.asarray(y1), np.asarray(ref),
                                       atol=1e-5, rtol=1e-5)
            np.testing.assert_allclose(np.asarray(m1), np.asarray(m2),
                                       atol=1e-5, rtol=1e-5)
            np.testing.assert_allclose(np.asarray(r1), np.asarray(r2),
                                       atol=1e-4, rtol=1e-4)

    def test_one_pass_bf16_and_no_affine(self):
        from apex_tpu.ops.pallas.group_norm_kernel import \
            group_norm_nhwc_pallas
        x = jax.random.normal(jax.random.PRNGKey(3), (1, 8, 8, 128),
                              jnp.bfloat16)
        y1, _, _ = group_norm_nhwc_pallas(x, 16, algo="one_pass")
        y2, _, _ = group_norm_nhwc_pallas(x, 16, algo="two_pass")
        assert y1.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(y1, np.float32), np.asarray(y2, np.float32),
            atol=2e-2, rtol=2e-2)

    def test_selection_heuristic(self):
        from apex_tpu.ops.pallas.group_norm_kernel import (
            _ONE_PASS_SLAB_ELEMS, one_pass_ok)
        assert one_pass_ok(2, 64, 256)               # small slab
        assert not one_pass_ok(2, 63, 256)           # sublane misaligned
        big_hw = _ONE_PASS_SLAB_ELEMS // 256 + 8
        big_hw -= big_hw % 8
        assert not one_pass_ok(2, big_hw, 256)       # slab too large

    def test_frontend_algo_override_and_grads(self):
        from apex_tpu.contrib.group_norm import group_norm_nhwc
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 4, 4, 32))
        w = 1 + 0.1 * jax.random.normal(jax.random.PRNGKey(5), (32,))
        b = 0.1 * jax.random.normal(jax.random.PRNGKey(6), (32,))
        outs, grads = [], []
        for algo in ("one_pass", "two_pass"):
            outs.append(group_norm_nhwc(x, 8, w, b, act="silu", algo=algo))
            grads.append(jax.grad(lambda x: jnp.sum(group_norm_nhwc(
                x, 8, w, b, act="silu", algo=algo) ** 2))(x))
        np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(outs[1]),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grads[0]),
                                   np.asarray(grads[1]),
                                   atol=1e-4, rtol=1e-4)


class TestPermutationSearch:
    """Round-2 permutation-search parity (VERDICT item 10): the reference's
    bounded-exhaustive + greedy-swap phases (permutation_search_kernels/
    exhaustive_search.py, channel_swap.py) reimplemented vectorized."""

    def _adversarial(self, seed=0, rows=16, cols=16):
        """Matrix where the identity stripe grouping is provably bad: half
        the stripes are all-large (2:4 must drop two large values each),
        half all-small — regrouping to 2 large + 2 small per stripe keeps
        every large value."""
        rng = np.random.default_rng(seed)
        m = rng.normal(size=(rows, cols)) * 0.01
        for s in range(0, cols // 4, 2):
            m[:, s * 4:s * 4 + 4] += rng.normal(size=(rows, 4)) * 3.0
        return m

    def test_canonical_permutation_count(self):
        from apex_tpu.contrib.sparsity.permutation_lib import \
            canonical_window_permutations
        import math
        # P = C! / ((4!)^G * G!) — the reference's analytical count
        # (exhaustive_search.py predict_unique_combinations)
        for c in (8, 12):
            g = c // 4
            want = (math.factorial(c)
                    // (math.factorial(4) ** g * math.factorial(g)))
            assert canonical_window_permutations(c).shape == (want, c)

    def test_exhaustive_improves_adversarial(self):
        from apex_tpu.contrib.sparsity.permutation_lib import (
            exhaustive_search, sum_after_2_to_4)
        m = self._adversarial()
        base = sum_after_2_to_4(m)
        pm, perm = exhaustive_search(m)
        got = sum_after_2_to_4(pm)
        assert got > base * 1.05, (base, got)
        np.testing.assert_allclose(pm, m[:, perm])  # perm consistent
        assert sorted(perm.tolist()) == list(range(m.shape[1]))

    def test_exhaustive_matches_bruteforce_small(self):
        """On an 8-column matrix the window IS the whole matrix: the search
        must find the global optimum over all 35 canonical permutations."""
        from apex_tpu.contrib.sparsity.permutation_lib import (
            canonical_window_permutations, exhaustive_search,
            sum_after_2_to_4)
        rng = np.random.default_rng(3)
        m = rng.normal(size=(8, 8))
        best = max(sum_after_2_to_4(m[:, p])
                   for p in canonical_window_permutations(8))
        _, perm = exhaustive_search(m)
        np.testing.assert_allclose(sum_after_2_to_4(m[:, perm]), best,
                                   rtol=1e-12)

    def test_greedy_improves_and_converges(self):
        from apex_tpu.contrib.sparsity.permutation_lib import (
            greedy_channel_swaps, sum_after_2_to_4)
        m = self._adversarial(seed=5)
        base = sum_after_2_to_4(m)
        pm, perm = greedy_channel_swaps(m)
        assert sum_after_2_to_4(pm) > base
        # convergence: a second run from the result finds nothing
        pm2, perm2 = greedy_channel_swaps(pm)
        np.testing.assert_allclose(pm2, pm)

    def test_entry_point_strategies(self):
        from apex_tpu.contrib.sparsity.permutation_lib import (
            accelerated_search_for_good_permutation, sum_after_2_to_4)
        m = self._adversarial(seed=7)
        base = sum_after_2_to_4(m)
        for strat in ("exhaustive", "progressive channel swap"):
            pm, _ = accelerated_search_for_good_permutation(
                m, {"strategy": strat})
            assert sum_after_2_to_4(pm) >= base

    def test_asp_wrapper_preserves_function_contract(self):
        """permuted_w == w[:, perm] (so the producer's output permutation
        keeps the network function unchanged)."""
        from apex_tpu.contrib.sparsity.permutation_lib import \
            permute_channels_to_preserve_magnitude
        w = jnp.asarray(self._adversarial(seed=9), jnp.float32)
        pw, perm = permute_channels_to_preserve_magnitude(w)
        np.testing.assert_allclose(np.asarray(pw),
                                   np.asarray(w)[:, perm])


class TestASPCheckpointFlow:
    """The reference's two-part checkpointing flow
    (apex/contrib/sparsity/test/checkpointing_test_part1.py → part2):
    train dense → prune → train sparse → checkpoint; then restore into a
    FRESH model/optimizer/ASP and verify masks + sparsity survive continued
    training."""

    def _loss_grads(self, params, x):
        def loss(ps):
            h = x @ ps[0]
            return jnp.mean((h + ps[1]) ** 2)
        return jax.value_and_grad(loss)(params)

    def test_prune_checkpoint_restore_retrain(self, tmp_path):
        from apex_tpu.utils import checkpoint as ckpt

        x = jax.random.normal(jax.random.PRNGKey(9), (4, 16))
        params = [jax.random.normal(jax.random.PRNGKey(0), (16, 16)),
                  jnp.zeros((16,))]
        opt = FusedAdam(params, lr=0.05)
        # part 1: dense steps, then prune, then sparse steps
        p = opt.parameters
        for _ in range(2):
            _, g = self._loss_grads(p, x)
            p = opt.step(g)
        asp = ASP()
        pruned = asp.prune_trained_model(p, opt)
        opt.set_parameters(pruned)
        p = opt.parameters
        for _ in range(2):
            _, g = self._loss_grads(p, x)
            p = opt.step(g)
        m = np.asarray(asp.masks[0])
        np.testing.assert_array_equal(np.asarray(p[0])[~m], 0.0)
        # the string `pattern` field rides outside the array tree (the
        # reference stores it in the torch pickle; npz holds arrays only)
        ckpt.save_numpy(str(tmp_path / "part1.npz"),
                        {"params": p, "opt": opt.state_dict(),
                         "asp_masks": asp.state_dict()["masks"]})

        # part 2: fresh everything, restore, keep training sparse
        params2 = [jnp.zeros((16, 16)), jnp.zeros((16,))]
        tmpl = {"params": params2,
                "opt": FusedAdam(params2, lr=0.05).state_dict(),
                "asp_masks": ASP().init_model_for_pruning(
                    params2).state_dict()["masks"]}
        restored = ckpt.restore_numpy(str(tmp_path / "part1.npz"), tmpl)
        opt2 = FusedAdam(restored["params"], lr=0.05)
        opt2.load_state_dict(restored["opt"])
        asp2 = ASP()
        asp2.load_state_dict({"pattern": "m4n2_1d",
                              "masks": restored["asp_masks"]})
        opt2.set_parameters(jax.tree_util.tree_map(
            lambda q, mk: q * mk, restored["params"], asp2.masks))
        asp2.wrap_optimizer(opt2)  # part2 re-attaches ASP to the new opt
        np.testing.assert_array_equal(np.asarray(asp2.masks[0]), m)
        p2 = opt2.parameters
        for _ in range(3):
            _, g = self._loss_grads(p2, x)
            p2 = opt2.step(g)
        # sparsity maintained through post-restore training
        np.testing.assert_array_equal(np.asarray(p2[0])[~m], 0.0)


class TestPeerMemoryPool:
    """Real arena semantics (reference peer_memory.py:6-106): one device
    allocation, aligned bump sub-allocation, exhaustion asserts, dynamic
    reset, per-peer device views."""

    def test_allocation_accounting_and_views(self):
        from apex_tpu.contrib.peer_memory import PeerMemoryPool
        pool = PeerMemoryPool(static_size=4096, dynamic_size=4096,
                              peer_ranks=[0, 1, 2])
        ts = pool.allocate_peer_tensors((8, 16), jnp.float32,
                                        channels_last=False, dynamic=False)
        assert len(ts) == 3  # one view per peer rank
        assert ts[0].shape == (8, 16) and ts[0].dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(ts[0]), 0.0)
        # second static allocation starts at an aligned, disjoint offset
        t2 = pool.allocate_peer_tensors((4, 4), jnp.bfloat16,
                                        channels_last=True, dynamic=False)
        r0, r1 = pool.allocations
        assert r1["offset"] % pool.alignment == 0
        assert r1["offset"] >= r0["offset"] + r0["nbytes"]
        assert r1["channels_last"] is True
        assert t2[0].dtype == jnp.bfloat16
        # dynamic allocations live in the dynamic half and reset() drops
        # only them
        pool.allocate_peer_tensors((16,), jnp.int32, False, dynamic=True)
        assert pool.allocations[-1]["offset"] >= pool.static_size
        assert pool.dynamic_offset > 0
        pool.reset()
        assert pool.dynamic_offset == 0
        # records stay positionally stable: dynamic ones are marked freed
        # (cached indices keep resolving), statics stay live
        assert len(pool.allocations) == 3
        assert pool.allocations[2]["freed"]
        with pytest.raises(RuntimeError, match="freed by reset"):
            pool.view(2)
        pool.view(0)  # static index still valid after reset

    def test_exhaustion_asserts(self):
        from apex_tpu.contrib.peer_memory import PeerMemoryPool
        pool = PeerMemoryPool(static_size=1024, dynamic_size=512)
        with pytest.raises(AssertionError, match="Static"):
            pool.allocate_peer_tensors((1024,), jnp.float32, False, False)
        with pytest.raises(AssertionError, match="Dynamic"):
            pool.allocate_peer_tensors((512,), jnp.float32, False, True)

    def test_view_rematerializes(self):
        from apex_tpu.contrib.peer_memory import PeerMemoryPool
        pool = PeerMemoryPool(static_size=4096)
        t = pool.allocate_peer_tensors((8, 8), jnp.float32, False, False)[0]
        again = pool.view(0)
        assert again.shape == t.shape and again.dtype == t.dtype
        np.testing.assert_array_equal(np.asarray(again), np.asarray(t))

    def test_freed_pool_refuses(self):
        from apex_tpu.contrib.peer_memory import PeerMemoryPool
        pool = PeerMemoryPool(static_size=1024)
        pool.free()
        with pytest.raises(RuntimeError):
            pool.allocate_peer_tensors((4,), jnp.float32, False, False)
