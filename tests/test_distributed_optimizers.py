"""ZeRO optimizer tests on the 8-device CPU mesh — the dist_adam test pattern
(apex/contrib/test/optimizers/test_dist_adam.py: distributed optimizer vs
single-device reference on identical inputs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.optimizers.distributed_fused_adam import (DistributedFusedAdam,
                                                        _join_f32, _split_f32)
from apex_tpu.optimizers.distributed_fused_lamb import DistributedFusedLAMB
from apex_tpu.parallel import get_mesh

SHAPES = [(37,), (4, 11), (64, 3, 3), (128,)]
STEPS = 4


def _params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), len(SHAPES))
    return [jax.random.normal(k, s, jnp.float32) for k, s in zip(ks, SHAPES)]


def _grads(step):
    ks = jax.random.split(jax.random.PRNGKey(100 + step), len(SHAPES))
    return [jax.random.normal(k, s, jnp.float32) for k, s in zip(ks, SHAPES)]


@pytest.fixture(scope="module")
def mesh():
    return get_mesh("data")


class TestRemainderSplit:
    def test_exact_roundtrip(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1024,), jnp.float32)
        hi, lo = _split_f32(x)
        assert hi.dtype == jnp.bfloat16 and lo.dtype == jnp.uint16
        np.testing.assert_array_equal(np.asarray(_join_f32(hi, lo)),
                                      np.asarray(x))


class TestDistributedFusedAdam:
    @pytest.mark.parametrize("remainders", [False, True])
    def test_matches_single_device_fused_adam(self, mesh, remainders):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2, weight_decay=0.01,
                                    store_param_remainders=remainders)
        ref = FusedAdam(params, lr=1e-2, weight_decay=0.01)
        for s in range(1, STEPS + 1):
            g = _grads(s)
            dopt.step(g)
            ref.step(g)
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_state_is_sharded(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2)
        shards = dopt._m.addressable_shards
        assert len(shards) == 8
        assert shards[0].data.shape[0] == dopt._n // 8

    def test_found_inf_noop(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2)
        before = [np.asarray(p) for p in params]
        dopt.step(_grads(1), found_inf=True)
        for b, a in zip(before, dopt.parameters):
            np.testing.assert_array_equal(b, np.asarray(a))
        assert int(dopt._step) == 0

    def test_checkpoint_v1_and_v2_roundtrip(self, mesh):
        params = _params()
        d1 = DistributedFusedAdam(params, mesh, lr=1e-2)
        d1.step(_grads(1))
        # v1 (gathered)
        sd = d1.state_dict()
        d2 = DistributedFusedAdam(_params(seed=5), mesh, lr=1e-2)
        d2.load_state_dict(sd)
        # v2 (sharded)
        ssd = d1.sharded_state_dict()
        d3 = DistributedFusedAdam(_params(seed=6), mesh, lr=1e-2)
        d3.load_state_dict(ssd)
        g = _grads(2)
        d1.step(g)
        d2.step(g)
        d3.step(g)
        for a, b, c in zip(d1.parameters, d2.parameters, d3.parameters):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))

    def test_bf16_grad_sync_dtype(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2,
                                    grad_sync_dtype=jnp.bfloat16)
        dopt.step(_grads(1))
        for p in dopt.parameters:
            assert bool(jnp.all(jnp.isfinite(p)))


class TestDistAdamRound2Depth:
    """Reference-parity depth added in round 2 (VERDICT item 3):
    param groups (:270+), integrated clip (:2275), scaled states (:2694),
    grad accumulation, world-size-resharding checkpoints (:3059-3329)."""

    def test_param_groups_per_group_hyperparams(self, mesh):
        p_decay = _params(seed=0)
        p_nodecay = _params(seed=1)
        dopt = DistributedFusedAdam(
            [{"params": p_decay, "weight_decay": 0.05},
             {"params": p_nodecay, "weight_decay": 0.0, "lr": 3e-3,
              "betas": (0.8, 0.95)}],
            mesh, lr=1e-2)
        r_decay = FusedAdam(p_decay, lr=1e-2, weight_decay=0.05)
        r_nodecay = FusedAdam(p_nodecay, lr=3e-3, weight_decay=0.0,
                              betas=(0.8, 0.95))
        for s in range(1, STEPS + 1):
            g0, g1 = _grads(s), _grads(s + 50)
            dopt.step([g0, g1])
            r_decay.step(g0)
            r_nodecay.step(g1)
        got0, got1 = dopt.parameters
        for a, b in zip(got0, r_decay.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)
        for a, b in zip(got1, r_nodecay.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_runtime_group_lr_change(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam([{"params": params, "lr": 1e-2}], mesh)
        ref = FusedAdam(params, lr=1e-2)
        dopt.step([_grads(1)])
        ref.step(_grads(1))
        dopt.param_groups[0]["lr"] = 1e-3  # scheduler-style mutation
        dopt.step([_grads(2)])
        ref.step(_grads(2), lr=1e-3)
        for a, b in zip(dopt.parameters[0], ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_integrated_clip_grad_norm(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2, max_grad_norm=0.5)
        ref = FusedAdam(params, lr=1e-2)
        for s in range(1, 3):
            g = _grads(s)
            dopt.step(g)
            # reference: clip manually then step
            flat = jnp.concatenate([jnp.ravel(x) for x in g])
            norm = jnp.sqrt(jnp.sum(flat * flat))
            coef = jnp.minimum(1.0, 0.5 / (norm + 1e-6))
            ref.step([x * coef for x in g])
            np.testing.assert_allclose(float(dopt.grad_norm_last_step),
                                       float(norm), rtol=1e-5)
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_with_scaled_states(self, mesh):
        """fp16 state + per-block scales tracks the fp32-state optimizer
        closely (the reference's scaled-state fidelity property)."""
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2,
                                    with_scaled_states=True)
        ref = FusedAdam(params, lr=1e-2)
        for s in range(1, STEPS + 1):
            g = _grads(s)
            dopt.step(g)
            ref.step(g)
        assert dopt._m.dtype == jnp.float16
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-3)

    def test_scaled_states_checkpoint_roundtrip(self, mesh):
        params = _params()
        d1 = DistributedFusedAdam(params, mesh, lr=1e-2,
                                  with_scaled_states=True)
        d1.step(_grads(1))
        d2 = DistributedFusedAdam(_params(seed=9), mesh, lr=1e-2,
                                  with_scaled_states=True)
        d2.load_state_dict(d1.state_dict())
        g = _grads(2)
        d1.step(g)
        d2.step(g)
        for a, b in zip(d1.parameters, d2.parameters):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_grad_accumulation(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2)
        ref = FusedAdam(params, lr=1e-2)
        micro = [_grads(1), _grads(2), _grads(3)]
        for g in micro:
            dopt.accumulate(g)
        dopt.step()  # consumes the accumulation buffer
        summed = [sum(gs) for gs in zip(*micro)]
        ref.step(summed)
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)
        with pytest.raises(ValueError):
            dopt.step()  # buffer consumed; must not silently reuse

    def test_checkpoint_resharding_world8_to_world4(self):
        """Save sharded (v2) at world=8, load at world=4 — the whole point
        of v2 checkpoints (ref :3059-3329)."""
        from apex_tpu.parallel import make_mesh
        params = _params()
        m8 = get_mesh("data")
        d8 = DistributedFusedAdam(params, m8, lr=1e-2)
        d8.step(_grads(1))
        ssd = d8.sharded_state_dict()
        assert ssd["world"] == 8

        m4 = make_mesh([4], ["data"], jax.devices()[:4])
        d4 = DistributedFusedAdam(_params(seed=7), m4, lr=1e-2)
        d4.load_state_dict(ssd)
        g = _grads(2)
        d8.step(g)
        d4.step(g)
        for a, b in zip(d8.parameters, d4.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-7, rtol=1e-7)
        # and back up: world=4 → world=8
        d8b = DistributedFusedAdam(_params(seed=8), m8, lr=1e-2)
        d8b.load_state_dict(d4.sharded_state_dict())
        g = _grads(3)
        d4.step(g)
        d8b.step(g)
        for a, b in zip(d4.parameters, d8b.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-7, rtol=1e-7)


class TestDistributedFusedLAMB:
    def test_matches_single_device_fused_lamb(self, mesh):
        params = _params()
        dopt = DistributedFusedLAMB(params, mesh, lr=1e-2, weight_decay=0.01,
                                    max_grad_norm=1.0)
        ref = FusedLAMB(params, lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
        for s in range(1, STEPS + 1):
            g = _grads(s)
            dopt.step(g)
            ref.step(g)
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_accumulation_step_is_noop(self, mesh):
        params = _params()
        dopt = DistributedFusedLAMB(params, mesh, lr=1e-2)
        before = [np.asarray(p) for p in dopt.parameters]
        dopt.set_is_accumulation_step(True)
        dopt.step(_grads(1))
        for b, a in zip(before, dopt.parameters):
            np.testing.assert_array_equal(b, np.asarray(a))
        dopt.set_is_accumulation_step(False)
        dopt.step(_grads(1))
        assert not np.allclose(before[0], np.asarray(dopt.parameters[0]))

    def test_accumulation_folds_grads(self, mesh):
        """Accumulate g1,g2 then step with g3 ≡ one step with g1+g2+g3
        (reference :787 skip-sync-while-accumulating flow)."""
        g1, g2, g3 = _grads(1), _grads(2), _grads(3)
        acc = DistributedFusedLAMB(_params(), mesh, lr=1e-2,
                                   weight_decay=0.01)
        acc.set_is_accumulation_step(True)
        acc.step(g1)
        acc.step(g2)
        acc.set_is_accumulation_step(False)
        acc.step(g3)
        ref = DistributedFusedLAMB(_params(), mesh, lr=1e-2,
                                   weight_decay=0.01)
        ref.step([a + b + c for a, b, c in zip(g1, g2, g3)])
        for a, b in zip(acc.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    @pytest.mark.parametrize("full_ar", [False, True])
    def test_grad_sync_modes_same_numerics(self, mesh, full_ar):
        """full-AR vs RS+AR (reference :845 vs :903): identical numerics."""
        dopt = DistributedFusedLAMB(_params(), mesh, lr=1e-2,
                                    weight_decay=0.01, max_grad_norm=1.0,
                                    full_ar=full_ar)
        ref = FusedLAMB(_params(), lr=1e-2, weight_decay=0.01,
                        max_grad_norm=1.0)
        for s in range(1, STEPS + 1):
            g = _grads(s)
            dopt.step(g)
            ref.step(g)
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_grad_sync_modes_different_collectives(self, mesh):
        """The two modes must COMPILE differently: full_ar keeps the grad
        buffer replicated (all-reduce-shaped sync), RS+AR constrains it to
        the shard (reduce-scatter/dynamic-slice shaped). Assert on the
        optimized HLO rather than timing."""
        import re
        texts = {}
        for full_ar in (False, True):
            dopt = DistributedFusedLAMB(_params(), mesh, lr=1e-2,
                                        full_ar=full_ar)
            dopt.step(_grads(1))  # builds + compiles the step
            with dopt.mesh:
                lowered = dopt._jit.lower(
                    dopt._master, dopt._m, dopt._v, _grads(2), dopt._acc,
                    dopt._step, jnp.float32(1e-2), jnp.float32(1.0),
                    jnp.asarray(False))
            texts[full_ar] = lowered.compile().as_text()
        ops = {fa: {op: len(re.findall(op, t)) for op in
                    ("all-reduce", "reduce-scatter", "all-gather",
                     "dynamic-slice")} for fa, t in texts.items()}
        # replicated grads (full_ar) need no gather before the whole-tensor
        # trust-ratio phase; the sharded mode does — the compiled gather
        # structure must differ
        assert ops[False]["all-gather"] != ops[True]["all-gather"], ops

    def test_clip_after_ar_uses_global_norm(self, mesh):
        """clip_after_ar=True (reference :944-975): one global L2 norm of
        the synced gradient; a step at max_grad_norm=1 equals a no-clip
        step on grads pre-scaled by that global norm."""
        g = [10.0 * x for x in _grads(1)]
        gnorm = np.sqrt(sum(float(np.sum(np.square(np.asarray(x))))
                            for x in g))
        assert gnorm > 1.0  # the clip must actually engage
        dopt = DistributedFusedLAMB(_params(), mesh, lr=1e-2,
                                    weight_decay=0.01, max_grad_norm=1.0,
                                    clip_after_ar=True)
        dopt.step(g)
        ref = DistributedFusedLAMB(_params(), mesh, lr=1e-2,
                                   weight_decay=0.01, max_grad_norm=0.0)
        ref.step([x / gnorm for x in g])
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    @staticmethod
    def _shard_spanning(seed, scales=(1.0, 1.0, 1.0)):
        """Params/grads big enough that the flat buffer's real data spans
        several of the 8 flat shards (the tiny module-level SHAPES all fit
        in shard 0, where per-shard and global clips coincide)."""
        shapes = [(3000,), (2500,), (700,)]
        ks = jax.random.split(jax.random.PRNGKey(seed), len(shapes))
        return [s * jax.random.normal(k, sh, jnp.float32)
                for s, k, sh in zip(scales, ks, shapes)]

    def test_clip_before_ar_uses_local_shard_norms(self, mesh):
        """clip_after_ar=False (reference :981-996): each device clips its
        own flat shard by the shard-local norm — no collective feeds the
        clip coefficient. Verified against a manual per-shard clip of the
        same flat layout fed to a no-clip optimizer."""
        from apex_tpu.utils.flatten import flat_spec, flatten, unflatten

        params = self._shard_spanning(0)
        g = self._shard_spanning(7, scales=(5.0, 0.01, 3.0))
        dopt = DistributedFusedLAMB(params, mesh, lr=1e-2,
                                    weight_decay=0.01, max_grad_norm=1.0,
                                    clip_after_ar=False)
        dopt.step(g)

        world = mesh.shape["data"]
        spec = flat_spec(params)
        fg = np.asarray(flatten(g, spec, dtype=jnp.float32, pad_to=dopt._n))
        rows = fg.reshape(world, dopt._n // world)
        local = np.sqrt((rows ** 2).sum(axis=1, keepdims=True))
        assert (local > 1.0).any()  # some shards must clip...
        assert (local <= 1.0).any()  # ...and some must not
        coeff = np.minimum(1.0 / (1e-6 + local), 1.0)
        clipped = unflatten(jnp.asarray((rows * coeff).reshape(-1),
                                        jnp.float32), spec)
        ref = DistributedFusedLAMB(self._shard_spanning(0), mesh, lr=1e-2,
                                   weight_decay=0.01, max_grad_norm=0.0)
        ref.step(clipped)
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_clip_before_ar_full_ar_uses_uniform_coeff(self, mesh):
        """clip_after_ar=False + full_ar=True: grads are replicated, so
        the reference's EXACT pre-AR semantics — one uniform coefficient
        from the full-gradient norm (:983-996, coeff = min(1,
        max_gn/(1e-6+||g||))) — applies, collective-free."""
        g = self._shard_spanning(5, scales=(4.0, 0.02, 2.0))
        gnorm = np.sqrt(sum(float(np.sum(np.square(np.asarray(x))))
                            for x in g))
        assert gnorm > 1.0
        dopt = DistributedFusedLAMB(self._shard_spanning(0), mesh,
                                    lr=1e-2, weight_decay=0.01,
                                    max_grad_norm=1.0,
                                    clip_after_ar=False, full_ar=True)
        dopt.step(g)
        coeff = min(1.0, 1.0 / (1e-6 + gnorm))
        ref = DistributedFusedLAMB(self._shard_spanning(0), mesh, lr=1e-2,
                                   weight_decay=0.01, max_grad_norm=0.0)
        ref.step([coeff * x for x in g])
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_clip_points_differ_when_energy_is_concentrated(self, mesh):
        """A gradient whose energy sits in one flat shard must clip
        DIFFERENTLY at the two clip points (the reference's pre-AR clip is
        per-rank-inconsistent by design) — guards against clip_after_ar
        silently collapsing to one path."""
        # hot first tensor, cold rest: the global clip crushes the cold
        # shards, the local clip leaves them alone
        g = self._shard_spanning(9, scales=(20.0, 0.05, 0.05))
        outs = {}
        for flag in (True, False):
            o = DistributedFusedLAMB(self._shard_spanning(0), mesh,
                                     lr=1e-2, max_grad_norm=1.0,
                                     clip_after_ar=flag)
            o.step(g)
            outs[flag] = [np.asarray(p) for p in o.parameters]
        assert not all(
            np.allclose(a, b, atol=1e-7)
            for a, b in zip(outs[True], outs[False]))


class TestAbstractState:
    """abstract_state=True builds compile-only instances (state as sharded
    shape structs, used by tools/stack_aot.py) — runtime entry points must
    refuse with a clear error instead of failing deep inside jax."""

    def test_state_is_structs_and_step_refuses(self, mesh):
        a = DistributedFusedAdam(_params(), mesh, lr=1e-3,
                                 abstract_state=True)
        assert isinstance(a._master, jax.ShapeDtypeStruct)
        with pytest.raises(RuntimeError, match="abstract_state"):
            a.step(_grads(1))
        with pytest.raises(RuntimeError, match="abstract_state"):
            a.accumulate(_grads(1))
        for fn in (a.state_dict, a.sharded_state_dict,
                   lambda: a.load_state_dict({})):
            with pytest.raises(RuntimeError, match="abstract_state"):
                fn()
        lamb = DistributedFusedLAMB(_params(), mesh, lr=1e-3,
                                    abstract_state=True)
        assert isinstance(lamb._master, jax.ShapeDtypeStruct)
        with pytest.raises(RuntimeError, match="abstract_state"):
            lamb.step(_grads(1))
        with pytest.raises(RuntimeError, match="abstract_state"):
            lamb.state_dict()
        with pytest.raises(RuntimeError, match="abstract_state"):
            lamb.load_state_dict({})


class TestRedundant2DGrid:
    def test_state_sharded_over_data_replicated_over_redundant(self):
        """The reference's 2D process grid (distributed_fused_adam.py:316-328):
        state sharded over the distributed group, replicated over the
        orthogonal redundant group — on TPU this is NamedSharding over a 2D
        mesh (P('data') leaves the 'red' axis replicated)."""
        from apex_tpu.parallel import make_mesh
        mesh2d = make_mesh([4, 2], ["data", "red"])
        params = _params()
        opt = DistributedFusedAdam(params, mesh2d, lr=1e-2,
                                   redundant_axis="red")
        opt.step(_grads(1))
        # 8 devices, 4-way sharded, 2-way replicated → 8 addressable shards
        # but only 4 distinct shard indices
        shards = opt._m.addressable_shards
        assert len(shards) == 8
        starts = sorted(set(s.index[0].start or 0 for s in shards))
        assert len(starts) == 4
        # replicas hold identical bytes
        by_start = {}
        for s in shards:
            key = s.index[0].start or 0
            if key in by_start:
                np.testing.assert_array_equal(np.asarray(s.data),
                                              by_start[key])
            else:
                by_start[key] = np.asarray(s.data)

    def test_2d_matches_1d_results(self):
        from apex_tpu.parallel import get_mesh, make_mesh
        params = _params()
        o1 = DistributedFusedAdam(params, get_mesh("data"), lr=1e-2)
        o2 = DistributedFusedAdam(params, make_mesh([4, 2], ["data", "red"]),
                                  lr=1e-2, redundant_axis="red")
        for s in range(1, 3):
            g = _grads(s)
            o1.step(g)
            o2.step(g)
        for a, b in zip(o1.parameters, o2.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-7, rtol=1e-7)

    def test_2d_sharded_checkpoint_roundtrip(self):
        """v2 checkpoint on the 2D grid must dedup replica shards (the
        review-found double-count crash)."""
        from apex_tpu.parallel import make_mesh
        mesh2d = make_mesh([4, 2], ["data", "red"])
        params = _params()
        o1 = DistributedFusedAdam(params, mesh2d, lr=1e-2,
                                  redundant_axis="red")
        o1.step(_grads(1))
        ssd = o1.sharded_state_dict()
        assert len(ssd["m"]) == 4  # unique shards only, replicas deduped
        o2 = DistributedFusedAdam(_params(seed=3), mesh2d, lr=1e-2,
                                  redundant_axis="red")
        o2.load_state_dict(ssd)
        g = _grads(2)
        o1.step(g)
        o2.step(g)
        for a, b in zip(o1.parameters, o2.parameters):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_redundant_axis_must_be_mesh_axis(self):
        with pytest.raises(ValueError):
            DistributedFusedAdam(_params(), get_mesh("data"), lr=1e-2,
                                 redundant_axis="red")


class TestLAMBAccumulationScaling:
    def test_overflowed_microbatch_contributes_nothing(self, mesh):
        g1, g2 = _grads(1), _grads(2)
        bad = [jnp.full_like(g, jnp.inf) for g in g1]
        acc = DistributedFusedLAMB(_params(), mesh, lr=1e-2)
        acc.set_is_accumulation_step(True)
        acc.step(g1, inv_scale=0.5)
        acc.step(bad, found_inf=True)  # must be dropped, not folded
        acc.set_is_accumulation_step(False)
        acc.step(g2)
        ref = DistributedFusedLAMB(_params(), mesh, lr=1e-2)
        ref.step([a * 0.5 + b for a, b in zip(g1, g2)])
        for a, b in zip(acc.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_acc_buffer_checkpointed(self, mesh):
        g1 = _grads(1)
        d1 = DistributedFusedLAMB(_params(), mesh, lr=1e-2)
        d1.set_is_accumulation_step(True)
        d1.step(g1)
        sd = d1.state_dict()
        assert sd["acc"] is not None
        d2 = DistributedFusedLAMB(_params(seed=3), mesh, lr=1e-2)
        d2.load_state_dict(sd)
        d2.step(_grads(2))
        d1.set_is_accumulation_step(False)
        d1.step(_grads(2))
        for a, b in zip(d1.parameters, d2.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)
