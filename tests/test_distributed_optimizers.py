"""ZeRO optimizer tests on the 8-device CPU mesh — the dist_adam test pattern
(apex/contrib/test/optimizers/test_dist_adam.py: distributed optimizer vs
single-device reference on identical inputs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.optimizers.distributed_fused_adam import (DistributedFusedAdam,
                                                        _join_f32, _split_f32)
from apex_tpu.optimizers.distributed_fused_lamb import DistributedFusedLAMB
from apex_tpu.parallel import get_mesh

SHAPES = [(37,), (4, 11), (64, 3, 3), (128,)]
STEPS = 4


def _params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), len(SHAPES))
    return [jax.random.normal(k, s, jnp.float32) for k, s in zip(ks, SHAPES)]


def _grads(step):
    ks = jax.random.split(jax.random.PRNGKey(100 + step), len(SHAPES))
    return [jax.random.normal(k, s, jnp.float32) for k, s in zip(ks, SHAPES)]


@pytest.fixture(scope="module")
def mesh():
    return get_mesh("data")


class TestRemainderSplit:
    def test_exact_roundtrip(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1024,), jnp.float32)
        hi, lo = _split_f32(x)
        assert hi.dtype == jnp.bfloat16 and lo.dtype == jnp.uint16
        np.testing.assert_array_equal(np.asarray(_join_f32(hi, lo)),
                                      np.asarray(x))


class TestDistributedFusedAdam:
    @pytest.mark.parametrize("remainders", [False, True])
    def test_matches_single_device_fused_adam(self, mesh, remainders):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2, weight_decay=0.01,
                                    store_param_remainders=remainders)
        ref = FusedAdam(params, lr=1e-2, weight_decay=0.01)
        for s in range(1, STEPS + 1):
            g = _grads(s)
            dopt.step(g)
            ref.step(g)
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_state_is_sharded(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2)
        shards = dopt._m.addressable_shards
        assert len(shards) == 8
        assert shards[0].data.shape[0] == dopt._n // 8

    def test_found_inf_noop(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2)
        before = [np.asarray(p) for p in params]
        dopt.step(_grads(1), found_inf=True)
        for b, a in zip(before, dopt.parameters):
            np.testing.assert_array_equal(b, np.asarray(a))
        assert int(dopt._step) == 0

    def test_checkpoint_v1_and_v2_roundtrip(self, mesh):
        params = _params()
        d1 = DistributedFusedAdam(params, mesh, lr=1e-2)
        d1.step(_grads(1))
        # v1 (gathered)
        sd = d1.state_dict()
        d2 = DistributedFusedAdam(_params(seed=5), mesh, lr=1e-2)
        d2.load_state_dict(sd)
        # v2 (sharded)
        ssd = d1.sharded_state_dict()
        d3 = DistributedFusedAdam(_params(seed=6), mesh, lr=1e-2)
        d3.load_state_dict(ssd)
        g = _grads(2)
        d1.step(g)
        d2.step(g)
        d3.step(g)
        for a, b, c in zip(d1.parameters, d2.parameters, d3.parameters):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))

    def test_bf16_grad_sync_dtype(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2,
                                    grad_sync_dtype=jnp.bfloat16)
        dopt.step(_grads(1))
        for p in dopt.parameters:
            assert bool(jnp.all(jnp.isfinite(p)))


class TestDistributedFusedLAMB:
    def test_matches_single_device_fused_lamb(self, mesh):
        params = _params()
        dopt = DistributedFusedLAMB(params, mesh, lr=1e-2, weight_decay=0.01,
                                    max_grad_norm=1.0)
        ref = FusedLAMB(params, lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
        for s in range(1, STEPS + 1):
            g = _grads(s)
            dopt.step(g)
            ref.step(g)
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_accumulation_step_is_noop(self, mesh):
        params = _params()
        dopt = DistributedFusedLAMB(params, mesh, lr=1e-2)
        before = [np.asarray(p) for p in dopt.parameters]
        dopt.set_is_accumulation_step(True)
        dopt.step(_grads(1))
        for b, a in zip(before, dopt.parameters):
            np.testing.assert_array_equal(b, np.asarray(a))
        dopt.set_is_accumulation_step(False)
        dopt.step(_grads(1))
        assert not np.allclose(before[0], np.asarray(dopt.parameters[0]))


class TestRedundant2DGrid:
    def test_state_sharded_over_data_replicated_over_redundant(self):
        """The reference's 2D process grid (distributed_fused_adam.py:316-328):
        state sharded over the distributed group, replicated over the
        orthogonal redundant group — on TPU this is NamedSharding over a 2D
        mesh (P('data') leaves the 'red' axis replicated)."""
        from apex_tpu.parallel import make_mesh
        mesh2d = make_mesh([4, 2], ["data", "red"])
        params = _params()
        opt = DistributedFusedAdam(params, mesh2d, lr=1e-2,
                                   redundant_axis="red")
        opt.step(_grads(1))
        # 8 devices, 4-way sharded, 2-way replicated → 8 addressable shards
        # but only 4 distinct shard indices
        shards = opt._m.addressable_shards
        assert len(shards) == 8
        starts = sorted(set(s.index[0].start or 0 for s in shards))
        assert len(starts) == 4
        # replicas hold identical bytes
        by_start = {}
        for s in shards:
            key = s.index[0].start or 0
            if key in by_start:
                np.testing.assert_array_equal(np.asarray(s.data),
                                              by_start[key])
            else:
                by_start[key] = np.asarray(s.data)

    def test_2d_matches_1d_results(self):
        from apex_tpu.parallel import get_mesh, make_mesh
        params = _params()
        o1 = DistributedFusedAdam(params, get_mesh("data"), lr=1e-2)
        o2 = DistributedFusedAdam(params, make_mesh([4, 2], ["data", "red"]),
                                  lr=1e-2, redundant_axis="red")
        for s in range(1, 3):
            g = _grads(s)
            o1.step(g)
            o2.step(g)
        for a, b in zip(o1.parameters, o2.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-7, rtol=1e-7)

    def test_2d_sharded_checkpoint_roundtrip(self):
        """v2 checkpoint on the 2D grid must dedup replica shards (the
        review-found double-count crash)."""
        from apex_tpu.parallel import make_mesh
        mesh2d = make_mesh([4, 2], ["data", "red"])
        params = _params()
        o1 = DistributedFusedAdam(params, mesh2d, lr=1e-2,
                                  redundant_axis="red")
        o1.step(_grads(1))
        ssd = o1.sharded_state_dict()
        assert len(ssd["m"]) == 4  # unique shards only, replicas deduped
        o2 = DistributedFusedAdam(_params(seed=3), mesh2d, lr=1e-2,
                                  redundant_axis="red")
        o2.load_state_dict(ssd)
        g = _grads(2)
        o1.step(g)
        o2.step(g)
        for a, b in zip(o1.parameters, o2.parameters):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_redundant_axis_must_be_mesh_axis(self):
        with pytest.raises(ValueError):
            DistributedFusedAdam(_params(), get_mesh("data"), lr=1e-2,
                                 redundant_axis="red")
