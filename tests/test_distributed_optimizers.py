"""ZeRO optimizer tests on the 8-device CPU mesh — the dist_adam test pattern
(apex/contrib/test/optimizers/test_dist_adam.py: distributed optimizer vs
single-device reference on identical inputs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.optimizers.distributed_fused_adam import (DistributedFusedAdam,
                                                        _join_f32, _split_f32)
from apex_tpu.optimizers.distributed_fused_lamb import DistributedFusedLAMB
from apex_tpu.parallel import get_mesh

SHAPES = [(37,), (4, 11), (64, 3, 3), (128,)]
STEPS = 4


def _params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), len(SHAPES))
    return [jax.random.normal(k, s, jnp.float32) for k, s in zip(ks, SHAPES)]


def _grads(step):
    ks = jax.random.split(jax.random.PRNGKey(100 + step), len(SHAPES))
    return [jax.random.normal(k, s, jnp.float32) for k, s in zip(ks, SHAPES)]


@pytest.fixture(scope="module")
def mesh():
    return get_mesh("data")


class TestRemainderSplit:
    def test_exact_roundtrip(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1024,), jnp.float32)
        hi, lo = _split_f32(x)
        assert hi.dtype == jnp.bfloat16 and lo.dtype == jnp.uint16
        np.testing.assert_array_equal(np.asarray(_join_f32(hi, lo)),
                                      np.asarray(x))


class TestDistributedFusedAdam:
    @pytest.mark.parametrize("remainders", [False, True])
    def test_matches_single_device_fused_adam(self, mesh, remainders):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2, weight_decay=0.01,
                                    store_param_remainders=remainders)
        ref = FusedAdam(params, lr=1e-2, weight_decay=0.01)
        for s in range(1, STEPS + 1):
            g = _grads(s)
            dopt.step(g)
            ref.step(g)
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)

    def test_state_is_sharded(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2)
        shards = dopt._m.addressable_shards
        assert len(shards) == 8
        assert shards[0].data.shape[0] == dopt._n // 8

    def test_found_inf_noop(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2)
        before = [np.asarray(p) for p in params]
        dopt.step(_grads(1), found_inf=True)
        for b, a in zip(before, dopt.parameters):
            np.testing.assert_array_equal(b, np.asarray(a))
        assert int(dopt._step) == 0

    def test_checkpoint_v1_and_v2_roundtrip(self, mesh):
        params = _params()
        d1 = DistributedFusedAdam(params, mesh, lr=1e-2)
        d1.step(_grads(1))
        # v1 (gathered)
        sd = d1.state_dict()
        d2 = DistributedFusedAdam(_params(seed=5), mesh, lr=1e-2)
        d2.load_state_dict(sd)
        # v2 (sharded)
        ssd = d1.sharded_state_dict()
        d3 = DistributedFusedAdam(_params(seed=6), mesh, lr=1e-2)
        d3.load_state_dict(ssd)
        g = _grads(2)
        d1.step(g)
        d2.step(g)
        d3.step(g)
        for a, b, c in zip(d1.parameters, d2.parameters, d3.parameters):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))

    def test_bf16_grad_sync_dtype(self, mesh):
        params = _params()
        dopt = DistributedFusedAdam(params, mesh, lr=1e-2,
                                    grad_sync_dtype=jnp.bfloat16)
        dopt.step(_grads(1))
        for p in dopt.parameters:
            assert bool(jnp.all(jnp.isfinite(p)))


class TestDistributedFusedLAMB:
    def test_matches_single_device_fused_lamb(self, mesh):
        params = _params()
        dopt = DistributedFusedLAMB(params, mesh, lr=1e-2, weight_decay=0.01,
                                    max_grad_norm=1.0)
        ref = FusedLAMB(params, lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
        for s in range(1, STEPS + 1):
            g = _grads(s)
            dopt.step(g)
            ref.step(g)
        for a, b in zip(dopt.parameters, ref.parameters):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_accumulation_step_is_noop(self, mesh):
        params = _params()
        dopt = DistributedFusedLAMB(params, mesh, lr=1e-2)
        before = [np.asarray(p) for p in dopt.parameters]
        dopt.set_is_accumulation_step(True)
        dopt.step(_grads(1))
        for b, a in zip(before, dopt.parameters):
            np.testing.assert_array_equal(b, np.asarray(a))
        dopt.set_is_accumulation_step(False)
        dopt.step(_grads(1))
        assert not np.allclose(before[0], np.asarray(dopt.parameters[0]))
