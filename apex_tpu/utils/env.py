"""Backend detection helpers.

Pallas TPU kernels run compiled on TPU and in interpreter mode everywhere else
(CPU test meshes, ``xla_force_host_platform_device_count`` virtual devices).
"""

import functools
import os

import jax


@functools.lru_cache(maxsize=None)
def platform_is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - no devices at all
        return False


def interpret_default() -> bool:
    """Whether pallas_call should run in interpret mode (True off-TPU).

    ``APEX_TPU_FORCE_COMPILED=1`` forces the compiled (Mosaic) lowering even
    when the default backend is CPU — used by tools/mosaic_aot.py to AOT-
    compile the kernel zoo against a deviceless TPU topology
    (jax.experimental.topologies), where the host backend is CPU but the
    jit target is a compile-only v5e client."""
    if os.environ.get("APEX_TPU_FORCE_COMPILED") == "1":
        return False
    return not platform_is_tpu()


def device_kind() -> str:
    """The attached device's ``device_kind`` string ("unknown" when no
    backend is reachable) — the identity check_regression's device gate
    compares between a capture and its baseline."""
    try:
        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def git_sha(cwd: str = None) -> str:
    """Short git sha of ``cwd`` (default: this repo checkout), or
    "unknown" (wheel installs have no .git)."""
    import subprocess

    if cwd is None:
        cwd = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=cwd,
            capture_output=True, text=True, timeout=10
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def capture_provenance() -> dict:
    """``device_kind`` / ``interpret_mode`` / git sha / timestamp — the
    stamp every bench capture carries so ``tools/check_regression.py``
    can refuse to gate a CPU-smoke/interpret capture against real-chip
    numbers (one builder; bench.py and apex-tpu-bench both use it)."""
    import time

    return {"device_kind": device_kind(),
            "interpret_mode": bool(interpret_default()),
            "git": git_sha(),
            "captured": time.strftime("%Y-%m-%dT%H:%M:%S")}
