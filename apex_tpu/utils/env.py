"""Backend detection helpers.

Pallas TPU kernels run compiled on TPU and in interpreter mode everywhere else
(CPU test meshes, ``xla_force_host_platform_device_count`` virtual devices).
"""

import functools

import jax


@functools.lru_cache(maxsize=None)
def platform_is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - no devices at all
        return False


def interpret_default() -> bool:
    """Whether pallas_call should run in interpret mode (True off-TPU)."""
    return not platform_is_tpu()
