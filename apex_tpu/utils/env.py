"""Backend detection helpers.

Pallas TPU kernels run compiled on TPU and in interpreter mode everywhere else
(CPU test meshes, ``xla_force_host_platform_device_count`` virtual devices).
"""

import functools
import os

import jax


@functools.lru_cache(maxsize=None)
def platform_is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - no devices at all
        return False


def interpret_default() -> bool:
    """Whether pallas_call should run in interpret mode (True off-TPU).

    ``APEX_TPU_FORCE_COMPILED=1`` forces the compiled (Mosaic) lowering even
    when the default backend is CPU — used by tools/mosaic_aot.py to AOT-
    compile the kernel zoo against a deviceless TPU topology
    (jax.experimental.topologies), where the host backend is CPU but the
    jit target is a compile-only v5e client."""
    if os.environ.get("APEX_TPU_FORCE_COMPILED") == "1":
        return False
    return not platform_is_tpu()
