"""Metrics / logging / observability — SURVEY §5 "metrics/logging".

The reference has no first-class subsystem: ``apex.deprecated_warning``
(apex/__init__.py:37-43), the print-once pattern of ``one_time_warning``
(apex/contrib/group_norm/group_norm.py:22), and per-example AverageMeters
(examples/imagenet/main_amp.py). The TPU framework makes these first-class:

- ``deprecated_warning`` / ``one_time_warning`` — exact-capability ports.
- ``AverageMeter`` — the examples' running-average pattern.
- ``MetricLogger`` — structured per-step metric logging (console and/or
  JSONL), with device-array coercion deferred to flush time so logging never
  forces a mid-step host sync (the TPU analog of "don't .item() in the hot
  loop").
"""

from __future__ import annotations

import json
import sys
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional

_seen_warnings: set = set()

# process-local event bus: every publish_event/structured_warning record is
# handed to these callbacks, so in-process consumers (the goodput ledger,
# a Telemetry sink mirroring events into its JSONL) see the same stream a
# log pipeline would scrape from stderr — without parsing stderr.
_event_subscribers: List[Callable[[Dict[str, Any]], None]] = []

# subscribers already reported as raising — the isolation contract is
# "reported once per subscriber", independent of how many times (or with
# how many distinct messages) it keeps raising
_broken_subscribers: set = set()

# guards the subscriber list and the broken-subscriber set: publishers run
# on any thread (watchdog heartbeat, scheduler, bus subscribers that
# publish), and (un)subscribe can race a concurrent publish's bookkeeping
# (apexlint APX002 keeps this discipline)
_bus_lock = threading.Lock()


def subscribe_events(callback: Callable[[Dict[str, Any]], None]
                     ) -> Callable[[], None]:
    """Register ``callback(record)`` for every published event record.

    Returns an unsubscribe callable (idempotent). Subscribers must be
    cheap and non-throwing; a raising subscriber is reported once and the
    event still reaches the remaining subscribers.
    """
    with _bus_lock:
        _event_subscribers.append(callback)

    def _unsubscribe() -> None:
        with _bus_lock:
            try:
                _event_subscribers.remove(callback)
            except ValueError:
                pass
            # drop the broken-subscriber mark with the subscription: ids
            # of gc'd callables get reused, and a later unrelated
            # subscriber at the same address must not inherit the
            # suppression
            _broken_subscribers.discard(id(callback))

    return _unsubscribe


def publish_event(event: str, *, level: str = "info", stream=None,
                  emit: bool = False, **fields) -> Dict[str, Any]:
    """Build an event record, notify subscribers, optionally print it.

    ``emit=True`` prints one JSON line (``structured_warning``'s behavior);
    ``emit=False`` is for high-rate or purely internal events (per-step
    overflow skips, checkpoint stall timings) that monitoring consumers
    subscribe to but that must not spam stderr.
    """
    rec: Dict[str, Any] = {"level": level, "event": event}
    rec.update(fields)
    if emit:
        print(json.dumps(rec, sort_keys=True, default=float),
              file=stream or sys.stderr, flush=True)
    # iterate a snapshot: a subscriber that (un)subscribes during delivery
    # (a flight recorder detaching itself, a one-shot waiter) must not
    # perturb this publish's fan-out
    with _bus_lock:
        subscribers = list(_event_subscribers)
    for cb in subscribers:
        try:
            cb(rec)
        except Exception as e:  # a broken consumer must not kill training
            with _bus_lock:
                # re-check membership: an unsubscribe that raced this
                # delivery already pruned the mark, and re-adding it for
                # a now-gone callback would leak a stale id that a later
                # subscriber at the same address could inherit
                first_raise = (cb in _event_subscribers
                               and id(cb) not in _broken_subscribers)
                if first_raise:
                    _broken_subscribers.add(id(cb))
            if first_raise:
                # warn outside the lock: one_time_warning writes stderr
                one_time_warning(
                    f"event subscriber {cb!r} raised {type(e).__name__}: "
                    f"{e} (reported once; the event still reaches the "
                    f"remaining subscribers)")
    return rec


def is_rank_zero() -> bool:
    """True on the process that owns console output (jax process 0).

    Multihost components gate their *console* announcements through this so
    an N-host event prints one banner, not N interleaved ones — the bus
    record (``publish_event``) still fires on every rank for per-host
    consumers (goodput ledgers, JSONL mirrors). Degrades to True when no
    backend is reachable, so single-process tools keep printing.
    """
    try:
        import jax  # deferred: logging must stay importable without a backend

        return jax.process_index() == 0
    except Exception:
        return True


def deprecated_warning(msg: str) -> None:
    """apex.deprecated_warning parity (apex/__init__.py:37-43): emit once per
    distinct message. FutureWarning, as in the reference's
    DeprecatedFeatureWarning(FutureWarning) — unlike DeprecationWarning it is
    shown under default filters, so users actually see it."""
    if msg in _seen_warnings:
        return
    _seen_warnings.add(msg)
    warnings.warn(msg, FutureWarning, stacklevel=2)


def one_time_warning(msg: str) -> None:
    """group_norm.py:22 parity: print a warning once per distinct message."""
    if msg in _seen_warnings:
        return
    _seen_warnings.add(msg)
    print(f"Warning: {msg}", file=sys.stderr)


def structured_warning(event: str, stream=None, **fields) -> Dict[str, Any]:
    """Emit a machine-parseable warning record (one JSON line to stderr).

    The resilience subsystem reports degraded-mode transitions through this
    — checkpoint skipped as corrupt, save retry, preemption requested,
    loss-scale growth frozen — so a log pipeline can alert on ``event``
    instead of scraping free-text warnings. Returns the record (tests
    assert on it). Device scalars in ``fields`` are coerced to floats.
    """
    return publish_event(event, level="warning", stream=stream, emit=True,
                         **fields)


class AverageMeter:
    """Running average (examples/imagenet/main_amp.py AverageMeter)."""

    def __init__(self, name: str = "", fmt: str = ":f"):
        self.name = name
        self.fmt = fmt
        self.reset()

    def reset(self):
        self.val = 0.0
        self.sum = 0.0
        self.count = 0
        self.avg = 0.0

    def update(self, val, n: int = 1):
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self):
        return ("{name} {val" + self.fmt + "} ({avg" + self.fmt + "})"
                ).format(name=self.name, val=self.val, avg=self.avg)


class MetricLogger:
    """Structured step metrics with deferred host sync.

    ``log(step, **metrics)`` buffers metric values (device arrays stay
    device arrays); ``flush()`` coerces to floats (ONE host sync for the
    whole buffer), updates running meters, and writes console/JSONL output.
    """

    def __init__(self, jsonl_path: Optional[str] = None,
                 print_every: int = 0, stream=None):
        self.jsonl_path = jsonl_path
        self.print_every = print_every
        self.stream = stream or sys.stderr
        self.meters: Dict[str, AverageMeter] = {}
        self._buffer: list = []
        # monotonic, not time.time(): the per-row `t` is a duration since
        # logger construction, and wall clock steps under NTP (APX005)
        self._t0 = time.monotonic()

    def log(self, step: int, **metrics: Any) -> None:
        self._buffer.append((step, time.monotonic() - self._t0, metrics))
        if self.print_every and step % self.print_every == 0:
            self.flush()

    def flush(self) -> None:
        if not self._buffer:
            return
        import jax  # deferred: logging must stay importable without a backend

        # ONE host sync for the whole buffer: batch-transfer every buffered
        # device array in a single device_get (per-value float() would pay
        # one blocking round-trip per metric per step)
        host = jax.device_get([list(metrics.values())
                               for _, _, metrics in self._buffer])
        rows = []
        for (step, t, metrics), vals in zip(self._buffer, host):
            row = {"step": step, "t": round(t, 3)}
            for k, val in zip(metrics.keys(), vals):
                v = float(val)
                row[k] = v
                self.meters.setdefault(k, AverageMeter(k, ":.4f")).update(v)
            rows.append(row)
        self._buffer.clear()
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                for row in rows:
                    f.write(json.dumps(row) + "\n")
        if self.print_every:
            last = rows[-1]
            parts = [f"step {last['step']}"] + [
                str(m) for k, m in sorted(self.meters.items())]
            print("  ".join(parts), file=self.stream)

    def summary(self) -> Dict[str, float]:
        self.flush()
        return {k: m.avg for k, m in self.meters.items()}
