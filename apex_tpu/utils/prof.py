"""Tracing / profiling — the framework's observability layer.

Reference status (SURVEY §5): apex has no first-class tracing subsystem —
ad-hoc NVTX ranges (``torch.cuda.nvtx``) and ``cudaProfilerStart`` in tests,
``--prof`` iteration caps in examples. The TPU framework makes it first-class:

- ``profile(logdir)``: context manager over ``jax.profiler`` producing a
  TensorBoard-loadable device trace (the nsys/nvtx equivalent).
- ``annotate(name)`` / ``annotate_function``: named trace ranges (the NVTX
  ``range_push/pop`` analog) that show up in the trace viewer.
- ``StepTimer``: the examples' AverageMeter, with proper device sync.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax


# logdir of the live profile() region, if any — jax.profiler raises an
# opaque internal error on nested start_trace; we fail with context first
_active_profile: Optional[str] = None


@contextlib.contextmanager
def profile(logdir: str = "/tmp/apex_tpu_trace"):
    """Capture a device trace for the enclosed region (≈ nsys profile).

    Not reentrant (one device trace per process at a time): a nested call
    raises ``RuntimeError`` naming the already-active logdir instead of
    jax's opaque "trace already started" internals.
    """
    global _active_profile
    if _active_profile is not None:
        raise RuntimeError(
            f"profile() is not reentrant: a device trace is already being "
            f"captured to {_active_profile!r} — close it before opening "
            f"another (use annotate() for nested named ranges)")
    jax.profiler.start_trace(logdir)
    _active_profile = logdir
    try:
        yield logdir
    finally:
        _active_profile = None
        jax.profiler.stop_trace()


def annotate(name: str, **attrs):
    """Named range inside a trace (≈ nvtx.range_push/pop).

    Always opens a ``jax.profiler.TraceAnnotation`` (visible in the
    device-trace viewer). When the process span tracer is enabled
    (:func:`apex_tpu.monitor.trace.set_tracer`, or
    ``Telemetry(trace_jsonl=...)``), the range ALSO opens a span in the
    trace tree with ``attrs`` attached — host annotations and the span
    timeline stay in lockstep because they are the same call.
    """
    from apex_tpu.monitor.trace import get_tracer

    tracer = get_tracer()
    if tracer.enabled:
        # the tracer's span ctx enters the TraceAnnotation itself
        return tracer.span(name, **attrs)
    return jax.profiler.TraceAnnotation(name)


def annotate_function(fn, name: Optional[str] = None):
    """Decorator form (≈ nvtx.annotate)."""
    return jax.profiler.annotate_function(fn, name=name)


class StepTimer:
    """Average/last step timing with device synchronization (the examples'
    AverageMeter; ``block`` forces completion like cudaDeviceSynchronize).

    Context-manager form times the enclosed region::

        timer = StepTimer()
        with timer:                      # start()/stop() around the body
            out = step(state)
            timer.block(out)             # sync on `out` at exit: honest
                                         # wall clock on an async runtime

    The explicit ``start()``/``stop(block_on=...)`` pair remains for loops
    that want manual control.
    """

    def __init__(self):
        self.reset()

    def __enter__(self) -> "StepTimer":
        self.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        block_on, self._block_on = self._block_on, None
        if exc_type is not None:
            # aborted step: recording its partial duration would silently
            # skew avg/total low — drop the window instead
            self._t0 = None
            return
        self.stop(block_on=block_on)

    def block(self, block_on) -> "StepTimer":
        """Arm the enclosing ``with`` block to ``block_until_ready`` on
        ``block_on`` when it exits."""
        self._block_on = block_on
        return self

    def reset(self):
        self.count = 0
        self.total = 0.0
        self.last = 0.0
        self._t0 = None
        self._block_on = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, block_on=None):
        if self._t0 is None:
            raise RuntimeError(
                "StepTimer.stop() called before start() (or after reset()) "
                "— call start() at the top of the step")
        if block_on is not None:
            jax.block_until_ready(block_on)
        self.last = time.perf_counter() - self._t0
        self.total += self.last
        self.count += 1
        return self.last

    @property
    def avg(self) -> float:
        return self.total / max(self.count, 1)


def _costs_module():
    """``apex_tpu.monitor.costs`` WITHOUT triggering the monitor package
    ``__init__`` (which imports telemetry → this module: a cycle, and
    ``apex_tpu/__init__`` imports utils before monitor). The module is
    import-time stdlib-only by contract, so a direct by-path load is
    cheap; registered under its canonical name so the package import
    later reuses this instance instead of making a second copy."""
    import importlib.util
    import os
    import sys

    mod = sys.modules.get("apex_tpu.monitor.costs")
    if mod is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "monitor", "costs.py")
        spec = importlib.util.spec_from_file_location(
            "apex_tpu.monitor.costs", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["apex_tpu.monitor.costs"] = mod
        spec.loader.exec_module(mod)
    return mod


# chip peaks for roofline reporting (bf16 TFLOPs, HBM GB/s) — derived
# from the ledger's chip-spec table (monitor/costs.py owns the numbers;
# the "cpu" fallback entry is non-gating there and excluded here, where
# peaks always mean real silicon)
CHIP_PEAKS = {
    chip: {"hbm_gbps": spec["hbm_gbps"], "tflops": spec["tflops"]}
    for chip, spec in _costs_module().CHIP_SPECS.items() if spec["gating"]
}

# device_kind substrings → CHIP_PEAKS generation, most specific first
# (``"v5"`` alone is the v5p kind string "TPU v5"; the lite parts say so)
_KIND_TO_GEN = (
    ("v5e", "v5e"), ("v5 lite", "v5e"), ("v5litepod", "v5e"),
    ("v6e", "v6e"), ("v6 lite", "v6e"), ("trillium", "v6e"),
    ("v5p", "v5p"), ("v5", "v5p"),
)


def detect_chip(devices=None) -> Optional[str]:
    """Map the attached TPU's ``device_kind`` to a :data:`CHIP_PEAKS` key.

    Returns ``None`` off-TPU, when no backend is reachable, or for an
    unrecognized TPU kind (reported once via ``one_time_warning`` so new
    generations fail loudly instead of silently using v5e peaks).
    ``devices`` is injectable for tests; defaults to ``jax.devices()``.
    """
    from apex_tpu.utils.logging import one_time_warning

    if devices is None:
        try:
            devices = jax.devices()
        except Exception:  # backend init can fail (no relay, bad env)
            return None
    if not devices or getattr(devices[0], "platform", None) != "tpu":
        return None
    kind = str(getattr(devices[0], "device_kind", "")).lower()
    for pat, gen in _KIND_TO_GEN:
        if pat in kind:
            return gen
    one_time_warning(
        f"unknown TPU device_kind {kind!r}: roofline peaks fall back to "
        f"PALLAS_AXON_TPU_GEN — add the new generation to "
        f"apex_tpu.utils.prof.CHIP_PEAKS/_KIND_TO_GEN")
    return None


def roofline(fn, *args, chip: str | None = None,
             measured_ms: float | None = None) -> dict:
    """Compile ``fn(*args)`` and report XLA's own cost model against the
    chip roofline — the first-class version of the analysis the reference
    does ad hoc with nvprof (SURVEY §5 tracing row).

    Returns ``{flops, bytes, t_mxu_ms, t_hbm_ms, bound, ideal_ms}`` plus,
    when ``measured_ms`` is given, ``achieved_frac`` (ideal/measured —
    how close the step runs to its own roofline) and the per-resource
    fractions. ``chip`` defaults to the generation auto-detected from
    ``jax.devices()[0].device_kind`` (:func:`detect_chip`), then the
    ``PALLAS_AXON_TPU_GEN`` env var, then v5e.

    Caveat on ``bytes``: XLA's "bytes accessed" counts every operand's
    bytes per op, including VMEM-resident reuse that never touches HBM,
    so ``t_hbm_ms`` is an UPPER bound on memory time and fusion-heavy
    programs (conv nets) can legitimately run faster than ``ideal_ms`` —
    ``achieved_frac > 1`` means "beat the operand-byte model", not an
    error (observed: ResNet-50 b128 measures 55 ms vs a 79 ms
    operand-byte bound). ``t_mxu_ms`` has no such slack; ``mxu_frac`` is
    the trustworthy utilization number for compute-bound steps.
    """
    import os

    chip = (chip or detect_chip()
            or os.environ.get("PALLAS_AXON_TPU_GEN", "v5e"))
    peaks = CHIP_PEAKS.get(chip, CHIP_PEAKS["v5e"])
    compiled = jax.jit(fn).lower(*args).compile()
    rec = _costs_module().xla_cost_record(compiled) or {}
    flops = rec.get("flops", 0.0)
    nbytes = rec.get("bytes_accessed", 0.0)
    t_mxu = flops / (peaks["tflops"] * 1e12) * 1e3
    t_hbm = nbytes / (peaks["hbm_gbps"] * 1e9) * 1e3
    out = {"chip": chip, "flops": flops, "bytes": nbytes,
           "t_mxu_ms": t_mxu, "t_hbm_ms": t_hbm,
           "bound": "mxu" if t_mxu > t_hbm else "hbm",
           "ideal_ms": max(t_mxu, t_hbm)}
    if measured_ms is not None and measured_ms > 0:
        out["measured_ms"] = measured_ms
        out["achieved_frac"] = out["ideal_ms"] / measured_ms
        out["mxu_frac"] = t_mxu / measured_ms
        out["hbm_frac"] = t_hbm / measured_ms
    return out
