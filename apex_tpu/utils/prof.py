"""Tracing / profiling — the framework's observability layer.

Reference status (SURVEY §5): apex has no first-class tracing subsystem —
ad-hoc NVTX ranges (``torch.cuda.nvtx``) and ``cudaProfilerStart`` in tests,
``--prof`` iteration caps in examples. The TPU framework makes it first-class:

- ``profile(logdir)``: context manager over ``jax.profiler`` producing a
  TensorBoard-loadable device trace (the nsys/nvtx equivalent).
- ``annotate(name)`` / ``annotate_function``: named trace ranges (the NVTX
  ``range_push/pop`` analog) that show up in the trace viewer.
- ``StepTimer``: the examples' AverageMeter, with proper device sync.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax


@contextlib.contextmanager
def profile(logdir: str = "/tmp/apex_tpu_trace"):
    """Capture a device trace for the enclosed region (≈ nsys profile)."""
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named range inside a trace (≈ nvtx.range_push/pop)."""
    return jax.profiler.TraceAnnotation(name)


def annotate_function(fn, name: Optional[str] = None):
    """Decorator form (≈ nvtx.annotate)."""
    return jax.profiler.annotate_function(fn, name=name)


class StepTimer:
    """Average/last step timing with device synchronization (the examples'
    AverageMeter; ``block`` forces completion like cudaDeviceSynchronize)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.count = 0
        self.total = 0.0
        self.last = 0.0
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, block_on=None):
        if block_on is not None:
            jax.block_until_ready(block_on)
        self.last = time.perf_counter() - self._t0
        self.total += self.last
        self.count += 1
        return self.last

    @property
    def avg(self) -> float:
        return self.total / max(self.count, 1)
