from apex_tpu.utils.flatten import flatten, unflatten, FlatSpec, flat_spec  # noqa: F401
from apex_tpu.utils.env import interpret_default, platform_is_tpu  # noqa: F401
from apex_tpu.utils import checkpoint  # noqa: F401
from apex_tpu.utils import prof  # noqa: F401
