from apex_tpu.utils.flatten import flatten, unflatten, FlatSpec, flat_spec  # noqa: F401
from apex_tpu.utils.env import interpret_default, platform_is_tpu  # noqa: F401
from apex_tpu.utils import checkpoint  # noqa: F401
from apex_tpu.utils import prof  # noqa: F401
from apex_tpu.utils import logging  # noqa: F401
from apex_tpu.utils.logging import (  # noqa: F401
    AverageMeter, MetricLogger, deprecated_warning, one_time_warning)
from apex_tpu.utils import benchtime  # noqa: F401
