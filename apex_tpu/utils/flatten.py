"""Flatten / unflatten — TPU equivalent of the ``apex_C`` extension.

Reference: ``csrc/flatten_unflatten.cpp:4-13`` (``flatten``/``unflatten`` over
``torch.utils._flatten_dense_tensors``) — the primitive under flat-bucket DDP
all-reduce and the ZeRO optimizers' contiguous buffers
(``apex/contrib/optimizers/distributed_fused_adam.py:1074-1195``).

On TPU the flat buffer is the idiomatic layout for collectives *and* for the
fused optimizer kernels: one ``psum``/``psum_scatter`` over one contiguous
array, one Pallas kernel over one contiguous array. We keep offsets 128-lane
aligned so slices of the flat buffer remain tileable.

The offset/size planning is host-side bookkeeping; a C++ twin of the planner
lives in ``apex_tpu/_csrc`` (optional native module) — this module is the
always-available implementation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.utils.tiling import round_up as _round_up
import numpy as np

LANE = 128  # TPU lane width; keep per-leaf offsets aligned to it.


@dataclasses.dataclass(frozen=True)
class FlatSpec:
    """Static packing plan for a list/pytree of arrays into one flat buffer."""

    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[Any, ...]
    offsets: tuple[int, ...]
    padded_sizes: tuple[int, ...]
    total_size: int
    treedef: Any = None

    @property
    def num_leaves(self) -> int:
        return len(self.shapes)


def flat_spec(tensors: Sequence[jax.Array] | Any, align: int = LANE) -> FlatSpec:
    """Compute the packing plan. Accepts a list or arbitrary pytree.

    Planning runs through the native helper (apex_tpu/_csrc) when compiled —
    the host-side C++ twin of the reference's ParameterFragment/bucket math —
    with a bit-identical Python fallback.
    """
    from apex_tpu._native.api import plan_flat as _plan_flat

    leaves, treedef = jax.tree_util.tree_flatten(tensors)
    sizes = [int(np.prod(l.shape)) if l.shape else 1 for l in leaves]
    offsets, padded, total = _plan_flat(sizes, align)
    return FlatSpec(
        shapes=tuple(tuple(l.shape) for l in leaves),
        dtypes=tuple(l.dtype for l in leaves),
        offsets=tuple(int(o) for o in offsets),
        padded_sizes=tuple(int(p) for p in padded),
        total_size=int(total),
        treedef=treedef,
    )


def flatten(tensors: Sequence[jax.Array] | Any, spec: FlatSpec | None = None,
            dtype=None, pad_to: int | None = None) -> jax.Array:
    """Pack arrays into one contiguous 1-D buffer (ref csrc/flatten_unflatten.cpp:12).

    All leaves are cast to ``dtype`` (default: dtype of the first leaf). Padding
    between leaves is zero-filled so norms over the flat buffer are exact.
    """
    leaves = jax.tree_util.tree_leaves(tensors)
    if spec is None:
        spec = flat_spec(tensors)
    dtype = dtype or spec.dtypes[0]
    parts = []
    for leaf, shape, padded in zip(leaves, spec.shapes, spec.padded_sizes):
        n = int(np.prod(shape)) if shape else 1
        v = jnp.ravel(leaf).astype(dtype)
        if padded != n:
            v = jnp.pad(v, (0, padded - n))
        parts.append(v)
    flat = jnp.concatenate(parts) if parts else jnp.zeros((0,), dtype)
    total = spec.total_size if pad_to is None else _round_up(spec.total_size, pad_to)
    if total != flat.size:
        flat = jnp.pad(flat, (0, total - flat.size))
    return flat


def unflatten(flat: jax.Array, spec: FlatSpec, like: Any = None,
              cast: bool = True):
    """Slice the flat buffer back into the original shapes/dtypes
    (ref csrc/flatten_unflatten.cpp:13).

    Returns the original pytree structure when the spec was built from a
    pytree. ``cast=False`` keeps the flat buffer's dtype (e.g. fp32 master
    views of bf16 params).
    """
    out = []
    for shape, dtype, off, _ in zip(spec.shapes, spec.dtypes, spec.offsets,
                                    spec.padded_sizes):
        n = int(np.prod(shape)) if shape else 1
        piece = jax.lax.dynamic_slice_in_dim(flat, off, n, axis=0)
        piece = piece.reshape(shape)
        out.append(piece.astype(dtype) if cast else piece)
    if spec.treedef is not None:
        return jax.tree_util.tree_unflatten(spec.treedef, out)
    return out
