"""Reliable device timing for remote/async JAX backends.

On tunneled TPU runtimes (axon relay), ``jax.block_until_ready`` can return
before remote execution finishes, so wall-clock around dispatched calls
measures RPC dispatch latency, not compute (observed: a 22 GB-traffic kernel
"timing" at 0.16 ms). The only trustworthy signal is a data-dependent host
fetch: run K steps inside ONE jitted ``lax.fori_loop`` (the TPU analog of the
reference's CUDA-graph "capturable" motivation — amortize launch overhead,
csrc/multi_tensor_adam.cu capturable variants), then fetch one element of the
result; subtract the measured fetch floor; divide by K.
"""

from __future__ import annotations

import time

import numpy as np


def fetch_scalar(x):
    """Host-fetch a (tiny) array, forcing the producing computation to finish."""
    import jax

    return np.asarray(jax.device_get(x))


def measure_fetch_floor(reps: int = 8) -> float:
    """Seconds of pure dispatch+fetch round-trip for a trivial computation."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def tiny(x):
        return x * 2.0

    for _ in range(3):
        fetch_scalar(tiny(jnp.float32(1.0)))
    t0 = time.perf_counter()
    for i in range(reps):
        fetch_scalar(tiny(jnp.float32(2.0 + i)))
    return (time.perf_counter() - t0) / reps


def timed_steps(step_fn, init_state, iters: int, *, consts=(), witness=None,
                floor_s: float | None = None, donate: bool = True) -> float:
    """Milliseconds per step of ``step_fn`` amortized over ``iters`` chained
    executions inside one compiled loop.

    ``step_fn(i, state, *consts) -> state`` must be jit-traceable with
    matching state structure/dtypes (so the loop carry aliases in place).
    ``consts`` are loop-invariant operands (grads, activations, weights):
    they MUST be passed here rather than closed over — a closed-over device
    array becomes a jaxpr CONSTANT, which (a) is embedded literally in the
    HLO shipped to the compiler (a 2 GB grad buffer once turned the remote
    AOT compile into a multi-GB upload that never returned) and (b) cannot
    alias or donate. ``witness(state)`` selects a tiny slice to fetch
    (default: first leaf's [0] element). State buffers are donated by
    default so 1B-param-scale benches fit in HBM without loop-entry copies.
    """
    import functools

    import jax

    if floor_s is None:
        floor_s = measure_fetch_floor()

    def default_witness(state):
        leaf = jax.tree_util.tree_leaves(state)[0]
        return leaf.ravel()[0]

    witness = witness or default_witness

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def many(state, *consts):
        def body(i, s):
            return step_fn(i, s, *consts)
        return jax.lax.fori_loop(0, iters, body, state)

    out = many(init_state, *consts)
    fetch_scalar(witness(out))  # compile + first run
    # regenerate the donated carry from the (finished) previous output:
    # rebinding out -> init keeps one live copy only
    init2 = out
    t0 = time.perf_counter()
    out = many(init2, *consts)
    fetch_scalar(witness(out))
    elapsed = time.perf_counter() - t0
    # floor is measured separately and can exceed a fast run's elapsed time;
    # clamp so consumers dividing by the result never see <= 0
    corrected = max(elapsed - floor_s, 0.05 * elapsed)
    return corrected / iters * 1e3
