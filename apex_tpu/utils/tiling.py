"""Shared tiling arithmetic for the Pallas kernels and flat-buffer packing
(the TPU analog of the reference's chunking math in
csrc/multi_tensor_apply.cuh:13-23)."""

from __future__ import annotations


def round_up(n: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``n``."""
    return -(-n // m) * m
