"""Version compatibility shims for the jax API surface (non-Pallas).

jax moved ``shard_map`` out of ``jax.experimental`` (``from jax import
shard_map``) and renamed its replication-check kwarg ``check_rep`` →
``check_vma`` in the same breath. Call sites across the package, tools,
and tests use the new spelling; this shim resolves whichever the installed
jax provides and translates the kwarg, so the whole distributed surface
imports — and the parallel test tier collects — on either side of the
move. (The Pallas-side twin lives in :mod:`apex_tpu.ops.pallas._compat`.)
"""

from __future__ import annotations

try:  # new location: jax >= 0.6
    from jax import shard_map as _shard_map
    _OLD_KWARG = False
except ImportError:  # old location, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map
    _OLD_KWARG = True


def shard_map(f, **kwargs):
    """``jax.shard_map`` with the new-style ``check_vma`` kwarg on any jax."""
    if _OLD_KWARG and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)


def axis_size(axis_name):
    """``jax.lax.axis_size`` on any jax.

    Older jax has no ``lax.axis_size``; ``lax.psum(1, name)`` is the
    classic spelling and constant-folds to a static Python int under
    shard_map (axis sizes are known at trace time), which is what every
    caller here needs (reshape dims, ppermute tables).
    """
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
