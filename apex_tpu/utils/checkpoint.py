"""Checkpoint / resume — the framework's persistence layer (SURVEY §5).

Reference surface: optimizer ``state_dict``/``load_state_dict`` everywhere;
the non-trivial piece is DistributedFusedAdam's v1 gather-on-root
(distributed_fused_adam.py:2907) vs v2 sharded save with per-bucket gather on
load (:3059-3329). SURVEY maps v2 to "orbax-style sharded checkpoint".

This module provides both flavors over any pytree (train state, flax
variables, optimizer.state_dict()):
- ``save`` / ``restore``: orbax-backed sharded checkpointing — each device
  writes its own shards, restore re-shards to the current mesh layout
  (the v2 semantics, generalized).
- ``save_numpy`` / ``restore_numpy``: single-file .npz gather-on-host
  (v1 semantics; also the fallback when orbax is unavailable).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def save(path: str, tree: Any) -> None:
    """Sharded (v2-style) checkpoint via orbax (synchronous)."""
    save_async(path, tree).wait()


class AsyncSaveHandle:
    """Handle for an in-flight async save; ``wait()`` blocks until the
    checkpoint is durable, then releases the writer. ``wait()`` is
    idempotent after success; after a writer failure every call re-raises
    (a failed save must never later read as durable). A handle dropped
    without a successful ``wait()`` warns at collection time (the
    checkpoint on disk may be partial)."""

    def __init__(self, ckptr, path: str):
        self._ckptr = ckptr
        self._path = path
        self._done = False
        self._error: Optional[RuntimeError] = None

    def wait(self) -> None:
        if self._done:
            return
        if self._error is not None:
            # the writer already failed: every later wait() must stay loud —
            # returning quietly would report an unwritten checkpoint durable
            raise self._error
        try:
            self._ckptr.wait_until_finished()
        except Exception as e:
            # a background-writer failure would otherwise surface as an
            # opaque orbax error long after save_async returned; name the
            # checkpoint it belongs to and release the writer
            try:
                self._ckptr.close()
            except Exception:
                pass
            self._error = RuntimeError(
                f"async checkpoint save to {self._path!r} failed: {e}")
            raise self._error from e
        self._done = True
        self._ckptr.close()

    def __del__(self):
        # warn ONLY: running the unbounded blocking flush from a finalizer
        # could stall whatever thread happens to trigger collection (or
        # interpreter shutdown) indefinitely, and a flush failure here
        # would be silently swallowed anyway. The caller owns durability;
        # a dropped handle means an unverified checkpoint, and the warning
        # says so.
        if not self._done and self._error is None:
            # (a handle whose wait() already raised was surfaced loudly to
            # the caller — no second warning at collection time)
            import warnings

            warnings.warn(
                f"AsyncSaveHandle for {self._path!r} was never wait()ed — "
                "the checkpoint may be incomplete on disk; call wait() "
                "before dropping the handle",
                RuntimeWarning, stacklevel=2)


def save_async(path: str, tree: Any) -> AsyncSaveHandle:
    """Async sharded save: device arrays are handed to orbax's background
    writer and training can continue immediately — the TPU analog of the
    GDS no-host-bounce direct path the reference's
    ``gpu_direct_storage/benchmark_save.py`` measures. Call ``.wait()`` on
    the returned handle before relying on the checkpoint (or before exit).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=True)
    return AsyncSaveHandle(ckptr, path)


def restore(path: str, like: Optional[Any] = None) -> Any:
    """Restore an orbax checkpoint; ``like`` (a pytree of arrays or
    ShapeDtypeStructs, optionally carrying shardings) re-shards onto the
    current mesh — the v2 'all-gather on load into the new layout'."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        target = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding",
                                                            None)), like)
        return ckptr.restore(path, target)
    return ckptr.restore(path)


def save_numpy(path: str, tree: Any) -> None:
    """Gather-on-host single-file save (v1 semantics), atomic on POSIX.

    The archive is staged to ``<path>.npz.tmp`` and published with
    ``os.replace`` — a crash mid-save leaves the previous checkpoint (or
    nothing) rather than a truncated ``.npz`` for restore to choke on.
    """
    leaves, _ = jax.tree_util.tree_flatten(tree)
    final = path if path.endswith(".npz") else path + ".npz"
    tmp = final + ".tmp"
    # structure is reconstructed from `like` on restore (a PyTreeDef is not
    # serializable); only the leaves are stored
    with open(tmp, "wb") as f:
        np.savez(f, **{f"leaf_{i}": np.asarray(l)
                       for i, l in enumerate(leaves)})
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)


def restore_numpy(path: str, like: Any) -> Any:
    """Restore a save_numpy checkpoint into the structure of ``like``.

    Accepts the path with or without the ``.npz`` suffix (matching whatever
    ``save_numpy`` was given). numpy stores extension dtypes (bfloat16, fp8)
    as raw void bytes; they are viewed back through the dtype recorded in
    ``like``.
    """
    candidates = ([path] if path.endswith(".npz")
                  else [path + ".npz", path])
    for cand in candidates:
        if os.path.isfile(cand):
            break
    else:
        raise FileNotFoundError(
            "no checkpoint at " + " or ".join(repr(c) for c in candidates))
    data = np.load(cand)
    leaves, treedef = jax.tree_util.tree_flatten(like)
    out = []
    for i, ref in enumerate(leaves):
        arr = data[f"leaf_{i}"]
        if arr.dtype.kind == "V" and hasattr(ref, "dtype"):
            arr = arr.view(ref.dtype)
        out.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out)
