"""Console entry point (``apex-tpu-bench``) — runs the repo benchmark suite.

Delegates to the repo-root bench.py when present (the driver's interface),
else runs the packaged headline benchmark inline.
"""

from __future__ import annotations

import os
import runpy
import sys


def _inline_bench() -> None:
    """Packaged fallback: the headline fused-Adam benchmark at wheel-install
    scale (no repo checkout). Same metric semantics as bench.py."""
    import json
    import time

    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.pallas.fused_adam_kernel import fused_adam_flat

    on_tpu = jax.default_backend() == "tpu"
    n = (1_000_000_000 if on_tpu else 1_048_576) // 1024 * 1024
    p = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.bfloat16) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.bfloat16)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    p, m, v = fused_adam_flat(p, g, m, v, lr=1e-3, weight_decay=0.01,
                              step=jnp.int32(1), inv_scale=1.0)
    p.block_until_ready()
    iters = 20 if on_tpu else 2
    t0 = time.perf_counter()
    for i in range(iters):
        p, m, v = fused_adam_flat(p, g, m, v, lr=1e-3, weight_decay=0.01,
                                  step=jnp.int32(2 + i), inv_scale=1.0)
    p.block_until_ready()
    ms = (time.perf_counter() - t0) / iters * 1e3
    ref_ms = n * 22 / (1555e9 * 0.85) * 1e3
    print(json.dumps({
        "metric": f"fused_adam_step_ms_at_{n // 1_000_000}M_params"
                  f"_bf16p_f32state",
        "value": round(ms, 3), "unit": "ms",
        "vs_baseline": round(ref_ms / ms, 3)}))


def main() -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bench = os.path.join(here, "bench.py")
    if os.path.exists(bench):
        sys.argv = [bench] + sys.argv[1:]
        runpy.run_path(bench, run_name="__main__")
        return
    _inline_bench()


if __name__ == "__main__":
    main()
