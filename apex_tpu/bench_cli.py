"""Console entry point (``apex-tpu-bench``) — runs the repo benchmark suite.

Delegates to the repo-root bench.py when present (the driver's interface),
else runs the packaged headline benchmark inline.
"""

from __future__ import annotations

import os
import runpy
import sys


def _inline_bench() -> None:
    """Packaged fallback: the headline fused-Adam benchmark at wheel-install
    scale (no repo checkout). Same metric semantics and timing methodology
    as bench.py: (rows, 128) native-tiled state (a 1-D arg would pay a
    multi-GB relayout copy at 1B params) and fori_loop+fetch timing via
    ``apex_tpu.utils.benchtime`` (per-dispatch wall clock is unreliable on
    remote/async runtimes)."""
    import json

    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.pallas.fused_adam_kernel import LANE, fused_adam_flat
    from apex_tpu.utils.benchtime import measure_fetch_floor, timed_steps

    on_tpu = jax.default_backend() == "tpu"
    n = 999_999_488 if on_tpu else 1_048_576
    rows = n // LANE
    p = jax.random.normal(jax.random.PRNGKey(0), (rows, LANE),
                          jnp.bfloat16) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (rows, LANE), jnp.bfloat16)
    m = jnp.zeros((rows, LANE), jnp.float32)
    v = jnp.zeros((rows, LANE), jnp.float32)

    def step(i, st, g):
        p, m, v = st
        return tuple(fused_adam_flat(p, g, m, v, lr=1e-3, weight_decay=0.01,
                                     step=i + 1, inv_scale=1.0))

    ms = timed_steps(step, (p, m, v), iters=30 if on_tpu else 2,
                     consts=(g,), floor_s=measure_fetch_floor())
    ref_ms = n * 22 / (1555e9 * 0.85) * 1e3
    print(json.dumps({
        "metric": f"fused_adam_step_ms_at_{n // 1_000_000}M_params"
                  f"_bf16p_f32state",
        "value": round(ms, 3), "unit": "ms",
        "vs_baseline": round(ref_ms / ms, 3)}))


def main() -> None:
    # a preempted bench run (SIGTERM from the scheduler) exits cleanly with
    # a structured record instead of a stack trace mid-measurement; there is
    # no step boundary to poll, so the guard raises to unwind immediately
    from apex_tpu.resilience import PreemptionGuard
    from apex_tpu.utils.logging import structured_warning

    with PreemptionGuard(raise_on_signal=True) as guard:
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        bench = os.path.join(here, "bench.py")
        if os.path.exists(bench):
            sys.argv = [bench] + sys.argv[1:]
            runpy.run_path(bench, run_name="__main__")
        else:
            _inline_bench()
    if guard.should_stop():
        structured_warning("bench_preempted",
                           signal=guard.received_signal,
                           action="results above this line are complete")
        # a truncated run must not read as a successful benchmark to the
        # caller's exit-code check; keep the conventional signal status
        sys.exit(128 + guard.received_signal)


if __name__ == "__main__":
    main()
