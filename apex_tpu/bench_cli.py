"""Console entry point (``apex-tpu-bench``) — runs the repo benchmark suite.

Delegates to the repo-root bench.py when present (the driver's interface),
else runs the packaged headline benchmark inline.

``apex-tpu-bench --telemetry-jsonl PATH [--steps N]`` instead runs the
telemetry-instrumented train bench: a single-jit LM train step (amp dynamic
loss scaling + fused Adam) with in-graph :class:`TrainMetrics`, streamed
through :class:`apex_tpu.monitor.Telemetry` so every step lands in PATH as
``{step, loss, grad_norm, loss_scale, step_ms, tokens_per_s, mfu, ...}``.
Feed the JSONL to ``tools/check_regression.py`` against a committed
baseline to gate perf claims in CI (docs/observability.md).

``apex-tpu-bench --kernels fused_adam_1b,layer_norm [--emit-baseline
[PATH]]`` runs just that subset of the bench suite against the
already-selected backend (no relay probing / cache polling — this is the
per-kernel path of the perf gate, docs/performance.md). With
``--emit-baseline`` the capture is written as a suite-format JSON
(default ``BENCH_BASELINE.json``) ready to commit and enforce with
``tools/check_regression.py CURRENT --suite BENCH_BASELINE.json`` —
refreshing the committed gate is one command.

``apex-tpu-bench --serve [--steps N]`` runs the serving micro-bench
(apex_tpu.serve continuous batching on the tiny fp32 GPT-2): decode
tokens/s, p50/p99 per-token latency, and TTFT as a ``serve_decode``
BENCH_SUITE entry — same ``--emit-baseline`` + check_regression suite
workflow as the kernel gate (docs/serving.md). ``--page-size``/
``--num-pages``/``--prefix-cache`` swap in the paged KV pool, and
``--prompt-len MIN:MAX`` + ``--shared-prefix N`` script the
mixed-length multi-tenant workload the pool's
``resident_tokens_per_hbm_byte`` / ``prefix_hit_rate`` capacity claims
are measured on (docs/serving.md "Paged KV pool and prefix caching").
``--replicas N`` runs the same workload over N thread-backed engine
replicas under the fleet controller (``--hedge-ms``/``--heartbeat-ms``
shape routing): the entry gains the fleet resilience counters
(``failovers``/``hedge_fired``/``replica_dead``/``migrations`` — all
lower-is-better, a 0→N failover storm gates as a regression) and the
workload provenance records replicas/hedge_ms/heartbeat_ms so fleet
counters are never gated across incomparable configs
(docs/serving.md "Fleet failover and draining"). ``--trace-jsonl`` (+
``--trace-sample``) arms cross-replica request journeys — the fleet
trace at PATH, one Chrome-trace per replica at PATH.rK, seeded head
sampling with tail capture — ``--flight-recorder`` arms per-replica
postmortems, and the live-metrics flags serve/commit the merged fleet
registry view; the entry stamps ``trace_promoted`` (lower-is-better)
plus traced/trace_sample workload provenance so traced and untraced
captures never gate against each other (docs/observability.md "Fleet
request journeys").
"""

from __future__ import annotations

import os
import runpy
import sys


def _inline_bench() -> None:
    """Packaged fallback: the headline fused-Adam benchmark at wheel-install
    scale (no repo checkout). Same metric semantics and timing methodology
    as bench.py: (rows, 128) native-tiled state (a 1-D arg would pay a
    multi-GB relayout copy at 1B params) and fori_loop+fetch timing via
    ``apex_tpu.utils.benchtime`` (per-dispatch wall clock is unreliable on
    remote/async runtimes)."""
    import json

    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.pallas.fused_adam_kernel import LANE, fused_adam_flat
    from apex_tpu.utils.benchtime import measure_fetch_floor, timed_steps

    on_tpu = jax.default_backend() == "tpu"
    n = 999_999_488 if on_tpu else 1_048_576
    rows = n // LANE
    p = jax.random.normal(jax.random.PRNGKey(0), (rows, LANE),
                          jnp.bfloat16) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (rows, LANE), jnp.bfloat16)
    m = jnp.zeros((rows, LANE), jnp.float32)
    v = jnp.zeros((rows, LANE), jnp.float32)

    def step(i, st, g):
        p, m, v = st
        return tuple(fused_adam_flat(p, g, m, v, lr=1e-3, weight_decay=0.01,
                                     step=i + 1, inv_scale=1.0))

    ms = timed_steps(step, (p, m, v), iters=30 if on_tpu else 2,
                     consts=(g,), floor_s=measure_fetch_floor())
    ref_ms = n * 22 / (1555e9 * 0.85) * 1e3
    print(json.dumps({
        "metric": f"fused_adam_step_ms_at_{n // 1_000_000}M_params"
                  f"_bf16p_f32state",
        "value": round(ms, 3), "unit": "ms",
        "vs_baseline": round(ref_ms / ms, 3)}))


def _make_telemetry_step(batch: int = 8, seq: int = 33, vocab: int = 128,
                         hidden: int = 64, init_scale: float = 2.0 ** 12):
    """Build the instrumented LM train step for the telemetry bench.

    Returns ``(step, state, tokens, tokens_per_step)`` where ``step`` is
    ONE jitted callable — ``step(i, state, tokens) -> (state, metrics)``
    with ``state = (params, m, v, scaler_state)``. Loss scaling, gradient
    computation, the fused-Adam update (``found_inf`` no-op flag), the
    scale state machine, and the full :class:`TrainMetrics` collection all
    trace into that single call: there is nothing for the host to sync on
    mid-step, and tests assert no callbacks are traced in.
    """
    import jax
    import jax.numpy as jnp

    from apex_tpu.amp.grad_scaler import DynamicGradScaler
    from apex_tpu.monitor.metrics import collect_metrics
    from apex_tpu.optimizers.functional import adam_update

    scaler = DynamicGradScaler(init_scale=init_scale)
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    params = {
        "emb": jax.random.normal(keys[0], (vocab, hidden)) * 0.02,
        "w1": jax.random.normal(keys[1], (hidden, hidden)) * 0.1,
        "b1": jnp.zeros((hidden,)),
        "head": jax.random.normal(keys[2], (hidden, vocab)) * 0.02,
    }
    zeros = lambda p: jnp.zeros_like(p, jnp.float32)  # noqa: E731
    state = (params, jax.tree_util.tree_map(zeros, params),
             jax.tree_util.tree_map(zeros, params), scaler.init())
    tokens = jax.random.randint(keys[3], (batch, seq), 0, vocab, jnp.int32)

    def step(i, state, tokens):
        params, m, v, sstate = state

        def loss_fn(p):
            x = p["emb"][tokens[:, :-1]]
            h = jnp.tanh(x @ p["w1"] + p["b1"])
            logp = jax.nn.log_softmax((h @ p["head"]).astype(jnp.float32))
            nll = -jnp.take_along_axis(logp, tokens[:, 1:, None], axis=-1)
            loss = jnp.mean(nll)
            return scaler.scale(loss, sstate), loss

        (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # fused unscale + grad-norm + overflow probe: ONE pass over grads
        grads, grad_norm, found_inf = scaler.unscale_and_norm(grads, sstate)
        new_p, m, v = adam_update(params, grads, m, v, step=i + 1, lr=1e-2,
                                  found_inf=found_inf)
        tm = collect_metrics(
            params=new_p,
            updates=jax.tree_util.tree_map(lambda n, o: n - o, new_p,
                                           params),
            scaler_state=sstate, grad_norm=grad_norm, found_inf=found_inf,
            loss=loss)
        return (new_p, m, v, scaler.update(sstate, found_inf)), tm

    return jax.jit(step), state, tokens, float(batch * (seq - 1))


def _telemetry_bench(jsonl_path: "str | None", steps: int = 8,
                     watchdog_timeout: "float | None" = None,
                     trace_jsonl: "str | None" = None,
                     flight_path: "str | None" = None) -> None:
    """Run the instrumented train loop and stream telemetry to JSONL.

    ``trace_jsonl`` additionally enables span-tree tracing for the run
    (one trace per step: ``train_step`` root over the jitted dispatch and
    the completion fetch) exported as Perfetto-loadable Chrome-trace
    JSON, plus per-step HBM sampling and the calibrated step's static
    memory reservation. ``flight_path`` arms a crash-time flight
    recorder: a preemption or watchdog escalation mid-bench leaves a
    postmortem dump instead of a silent log tail.
    """
    import contextlib
    import json

    import jax

    from apex_tpu.monitor import Telemetry

    step, state, tokens, tokens_per_step = _make_telemetry_step()
    tel = Telemetry(jsonl_path, tokens_per_step=tokens_per_step,
                    trace_jsonl=trace_jsonl)
    mem = None
    if trace_jsonl:
        from apex_tpu.monitor.memory import MemoryAccountant
        # every 16 steps: allocator reads are for trends, not hot loops
        mem = MemoryAccountant(every=16)
    flight = None
    if flight_path:
        from apex_tpu.monitor.flight import FlightRecorder
        flight = FlightRecorder(flight_path, tracer=tel.tracer).attach()
    # optional collective watchdog: a step that wedges (stuck collective,
    # straggler host) becomes a collective_stall event in the JSONL —
    # visible in the capture — instead of a silently hung benchmark
    wd = None
    if watchdog_timeout:
        from apex_tpu.resilience import CollectiveWatchdog
        wd = CollectiveWatchdog(timeout_s=watchdog_timeout)
    try:
        # flight.guard: a fatal step exception (XLA error, OOM) has no
        # bus record — the guard is what turns it into a postmortem dump
        with (flight.guard("telemetry_bench") if flight is not None
              else contextlib.nullcontext()):
            tel.calibrate(step, 0, state, tokens)  # MFU from cost model
            # compile outside the timed window so row 1's step_ms is a
            # step, not the trace+compile
            state, tm = step(0, state, tokens)
            jax.block_until_ready(tm)
            tel.start()
            # per-step spans ONLY under --trace-jsonl: each tel.span
            # publishes a "span" bus event, and the telemetry mirror
            # appends one JSONL line per event — per-step writes are the
            # price of opting into tracing, not of plain telemetry
            # (whose events stay low-rate by design)
            step_span = (tel.span if tel.tracer is not None
                         else lambda name: contextlib.nullcontext())
            for i in range(1, steps + 1):
                with (wd.watch("train_step") if wd is not None
                      else contextlib.nullcontext()):
                    with step_span("train_step"):
                        state, tm = step(i, state, tokens)
                        # the loop's ONE host transfer — the overflow
                        # flag it needs anyway; its data dependency also
                        # makes step_ms honest wall clock (and gives the
                        # watchdog a real completion boundary)
                        skipped = bool(jax.device_get(tm.found_inf))
                if mem is not None:
                    mem.tick("train_step", step=i)
                tel.log_step(i, metrics=tm, skipped=skipped)
            summary = tel.summary()
    finally:
        # teardown runs on the failure path too: the recorder must not
        # stay subscribed, the process tracer must be restored, and the
        # Chrome trace must be terminated
        if wd is not None:
            wd.stop()
        if flight is not None:
            flight.detach()
        tel.close()
    print(json.dumps({
        "metric": "telemetry_train_step_ms_lm_tiny",
        "value": round(summary["metrics"].get("step_ms", -1.0), 3),
        "unit": "ms", "steps": steps, "jsonl": jsonl_path,
        "goodput": summary["goodput"]["goodput_frac"]}))


def _train_chaos_bench(steps: int = 12, world: int = 1,
                       grad_shards: "int | None" = None,
                       emit_baseline: "str | None" = None,
                       tp: int = 1) -> None:
    """Trainer chaos smoke (``--train-chaos``): run the production
    trainer under its supervisor through a seeded crash + mid-save-crash
    + preemption/relaunch schedule, and emit a suite-shaped
    ``train_chaos`` entry.

    The headline value is steps/s (higher-is-better); the resilience
    counters (``restarts``/``preempt_drains``/``steps_retried`` — all
    lower-is-better to the gate) ride the entry so a chaos capture that
    suddenly restarts more gates as a regression. Trainer workload
    provenance (world size, gradient-shard parallelism, amp dtype) nests
    under ``workload`` so elastic captures never gate against
    incomparable configs (the serve-bench precedent)."""
    import json
    import tempfile
    import time

    from apex_tpu.resilience import FaultInjector
    from apex_tpu.train import TrainConfig, TrainSupervisor

    g = grad_shards if grad_shards is not None else max(1, world)
    steps = max(6, int(steps))
    config = TrainConfig(steps=steps, batch=8, seq=16, world=world,
                         grad_shards=g, seed=0, tp=tp)
    with tempfile.TemporaryDirectory() as ckpt_dir:
        import dataclasses

        config = dataclasses.replace(config, checkpoint_dir=ckpt_dir,
                                     save_every=max(1, steps // 4))
        # the seeded schedule: a fatal step error (warm restart), a death
        # mid-checkpoint-commit (previous step must restore), and one
        # coordinated preemption drain + same-topology relaunch
        inj = (FaultInjector(seed=0)
               .crash_on_train_step(steps // 3)
               .crash_during_checkpoint_save(
                   (steps // 2) - (steps // 2) % config.save_every)
               .preempt_at_step(2 * steps // 3))
        supervisor = TrainSupervisor(config, injector=inj,
                                     max_restarts=3,
                                     world_schedule=[world, world])
        t0 = time.perf_counter()
        report = supervisor.run()
        wall = time.perf_counter() - t0
    counts = supervisor.trace_counts()
    suite = {
        "train_chaos": {
            "metric": "train_chaos_steps_per_s",
            "value": round(report["goodput"]["steps"] / wall, 3),
            "unit": "steps_per_s",
            # lower-is-better resilience counters (the gate knows all
            # three; a 0 -> N storm off this baseline is a regression)
            "restarts": report["restarts"],
            "preempt_drains": report["preempt_drains"],
            "steps_retried": report["steps_retried"],
            "goodput_frac": round(report["goodput"]["goodput_frac"], 6),
            # recompiles across the whole chaos run (lower-is-better to
            # the gate via the "recompile" hint; the contract is exactly
            # one trace — >1 means a restart recompiled)
            "step_recompiles": counts["shard_grads"],
            # storage-health counters off the goodput ledger: a healthy
            # run holds both at 0, so a bit-rot quarantine storm or
            # unexpected reshard churn on restore gates as a regression
            "ckpt_quarantined": report["goodput"]["events"].get(
                "train_ckpt_quarantined", 0),
            "topology_restored": report["goodput"]["events"].get(
                "train_topology_restored", 0),
            "bench_wall_s": round(wall, 3),
            "workload": {"steps": steps, "batch": config.batch,
                         "seq": config.seq,
                         "world": world, "grad_shards": g,
                         # tensor-axis provenance: a dp×tp capture is
                         # incomparable with a legacy dp-only baseline
                         # (missing key reads as tp=1), so the gate
                         # refuses instead of pretending to compare
                         "tp": tp,
                         "amp_dtype": config.amp,
                         "save_every": config.save_every,
                         "max_restarts": 3},
            "complete": False,
        },
    }
    if emit_baseline:
        bench = _load_bench_module()
        bench.atomic_write_json(emit_baseline, suite)
        print(json.dumps({"baseline": emit_baseline,
                          "kernels": ["train_chaos"]}))
    else:
        print(json.dumps(suite, indent=1))


def _load_bench_module():
    """Import the repo checkout's bench.py (the suite/baseline machinery
    lives there, not in the wheel). Exits 2 with a clear message on a
    wheel-only install — shared by the kernel-subset and serve modes."""
    import importlib.util

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bench_path = os.path.join(here, "bench.py")
    if not os.path.exists(bench_path):
        print("apex-tpu-bench: --kernels/--emit-baseline need the repo "
              "checkout's bench.py (wheel installs carry only the inline "
              "headline bench)", file=sys.stderr)
        raise SystemExit(2)
    spec = importlib.util.spec_from_file_location("apex_tpu_bench_suite",
                                                  bench_path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def _parse_prompt_lens(spec: str) -> "tuple[int, int]":
    """``"8"`` -> (8, 8); ``"4:24"`` -> (4, 24) — the mixed-length range
    scripted prompts are drawn from (uniform, seeded)."""
    lo, _, hi = spec.partition(":")
    lo = int(lo)
    hi = int(hi) if hi else lo
    if lo < 1 or hi < lo:
        raise ValueError(f"--prompt-len {spec!r}: need MIN[:MAX] with "
                         f"1 <= MIN <= MAX")
    return lo, hi


def _serve_bench(steps: int, num_slots: int = 4,
                 emit_baseline: "str | None" = None,
                 deadline_ms: "float | None" = None,
                 max_queue: "int | None" = None,
                 shed_policy: str = "reject-newest",
                 max_len: int = 64,
                 prompt_len: str = "8",
                 shared_prefix: int = 0,
                 page_size: "int | None" = None,
                 num_pages: "int | None" = None,
                 prefix_cache: bool = False,
                 metrics_port: "int | None" = None,
                 metrics_snapshot: "str | None" = None,
                 tenants: int = 0,
                 replicas: int = 1,
                 hedge_ms: "float | None" = None,
                 heartbeat_ms: "float | None" = None,
                 trace_jsonl: "str | None" = None,
                 trace_sample: "float | None" = None,
                 flight_recorder: "str | None" = None,
                 tp: int = 1,
                 tp_sync: str = "exact",
                 disagg: bool = False,
                 roles: "str | None" = None,
                 diurnal: bool = False,
                 cost_ledger: "str | None" = None,
                 chip_spec: "str | None" = None,
                 spec_draft_len: "int | None" = None,
                 decode_policy: "str | None" = None,
                 kv_quant: "str | None" = None) -> None:
    """Serving micro-bench: a scripted continuous-batching workload on the
    tiny fp32 GPT-2 — tokens/s, p50/p99 per-token decode latency, and TTFT
    in the BENCH_SUITE entry shape, ready for the check_regression suite
    gate (``tools/check_regression.py CURRENT --suite BASELINE --kernels
    serve_decode``). Latency metrics are lower-is-better; the gate knows —
    as are the overload SLO fields (``rejected``, ``deadline_exceeded``,
    ``shed_rate``) the entry carries when ``--deadline-ms``/``--max-queue``
    shape the workload.

    The paged-pool knobs (``--page-size``/``--num-pages``/
    ``--prefix-cache``) plus the workload shapers (``--prompt-len
    MIN:MAX`` mixed lengths, ``--shared-prefix N`` a fleet-wide system
    prompt every request starts with) are what the capacity claim is
    measured on: ``resident_tokens_per_hbm_byte`` (peak resident tokens
    over the engine's KV reservation — the number paging multiplies at
    equal HBM budget) and ``prefix_hit_rate`` (admissions served partly
    from shared prefix pages) land in the entry, higher-is-better, and
    every pool/workload knob rides the nested ``workload`` provenance so
    the gate never compares incomparable configs (PR-8 precedent).

    ``--metrics-port`` serves live Prometheus/JSON scrapes while the
    bench runs and ``--metrics-snapshot`` commits the mergeable
    per-rank snapshot at exit (``tools/metrics_merge.py`` folds these,
    and ``check_regression`` gates them directly — the live scrape and
    this bench produce comparably gateable artifacts); ``--tenants N``
    labels the scripted workload round-robin for a per-tenant view.

    ``--tp N`` shards the bench engine over an N-device mesh
    (docs/serving.md "Tensor-parallel decode") — the serve_decode entry
    then measures the SHARDED step's tokens/s (the scaling curve), the
    mesh shape rides the ``workload`` provenance, and
    ``check_regression`` refuses to gate across mesh shapes outright.
    ``--tp-sync`` picks the per-layer collective mode (exact = the
    bit-identical oracle; overlap/relaxed trade exactness for less or
    hidden collective pressure).

    ``--cost-ledger PATH`` additionally commits the device-independent
    compiled-step cost ledger (``apex_tpu.cost_ledger/v1``: per-phase
    FLOPs/HBM bytes extracted from the SAME AOT artifacts the bench
    ran, roofline-priced per ``--chip-spec``) — the wall-clock-free
    regression artifact ``check_regression`` gates and
    ``tools/cost_diff.py`` attributes. See docs/performance.md "Cost
    ledgers and roofline gating".
    """
    import dataclasses
    import json
    import time

    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models.gpt2 import GPT2Config
    from apex_tpu.serve.engine import Engine, EngineConfig, init_gpt2_params
    from apex_tpu.serve.scheduler import Request, ServeScheduler

    # resolve the baseline writer BEFORE benching: a wheel-only install
    # must fail in milliseconds, not after the engine compiles and runs
    bench = _load_bench_module() if emit_baseline else None

    from apex_tpu.utils.env import capture_provenance

    try:
        plo, phi = _parse_prompt_lens(prompt_len)
    except ValueError as e:
        raise SystemExit(f"apex-tpu-bench: {e}")
    # tensor-parallel + fleet flag matrix (PR-10 precedent:
    # inert/contradictory flags are loud usage errors before any
    # compile, never silent no-ops)
    if tp < 1:
        raise SystemExit(f"apex-tpu-bench: --tp {tp} must be >= 1")
    if tp_sync != "exact" and tp == 1:
        raise SystemExit(
            f"apex-tpu-bench: --tp-sync {tp_sync} relaxes cross-rank "
            f"synchronization; it needs --tp >= 2 (a single chip has "
            f"no collectives to overlap or relax)")
    if replicas < 1:
        raise SystemExit(f"apex-tpu-bench: --replicas {replicas} must "
                         f"be >= 1")
    # speculative-decoding matrix (same discipline, same as
    # apex-tpu-serve): refused in milliseconds, before any compile
    if spec_draft_len is not None and spec_draft_len < 1:
        raise SystemExit(
            f"apex-tpu-bench: --spec-draft-len {spec_draft_len} must "
            f"be >= 1 (it is the drafter's proposal width; omit the "
            f"flag for one-token decode)")
    spec_k = spec_draft_len or 0
    if decode_policy is not None:
        from apex_tpu.serve.spec import parse_policy

        try:
            parse_policy(decode_policy, spec_draft_len=spec_k)
        except ValueError as e:
            raise SystemExit(f"apex-tpu-bench: --decode-policy: {e}")
    # KV-quantization matrix (same discipline): the bench engine is
    # fp32 by construction, so only the codec itself and the spec
    # conflict need refusing before any compile
    if kv_quant is not None:
        if spec_k:
            raise SystemExit(
                f"apex-tpu-bench: --kv-quant {kv_quant} is incompatible "
                f"with --spec-draft-len {spec_k}: the speculative "
                f"acceptance oracle is bit-exact, the quantized cache "
                f"is tolerance-gated (drop one)")
        from apex_tpu.quant.kv import check_kv_codec

        try:
            check_kv_codec(kv_quant)
        except ValueError as e:
            raise SystemExit(f"apex-tpu-bench: --kv-quant: {e}")
    # cost-ledger matrix (same inert/contradictory-flag discipline):
    # validated against the ledger module's own chip-spec table BEFORE
    # any params/compile work
    if chip_spec is not None or cost_ledger:
        import os as _os

        from apex_tpu.monitor import costs

        if chip_spec is not None and not cost_ledger:
            raise SystemExit(
                "apex-tpu-bench: --chip-spec prices the cost ledger's "
                "roofline; it needs --cost-ledger")
        if chip_spec is not None and chip_spec not in costs.CHIP_SPECS:
            raise SystemExit(
                f"apex-tpu-bench: unknown --chip-spec {chip_spec!r}; "
                f"known specs: {', '.join(sorted(costs.CHIP_SPECS))}")
        if cost_ledger and metrics_snapshot and (
                _os.path.abspath(cost_ledger)
                == _os.path.abspath(metrics_snapshot)):
            raise SystemExit(
                f"apex-tpu-bench: --cost-ledger and --metrics-snapshot "
                f"both write {cost_ledger!r} — the second atomic commit "
                f"would clobber the first (pick two paths)")
    # disaggregation matrix (PR-10 precedent, same as apex-tpu-serve)
    role_split = None
    if roles is not None and not disagg:
        raise SystemExit(
            "apex-tpu-bench: --roles splits a DISAGGREGATED fleet; it "
            "needs --disagg")
    if disagg:
        if not page_size or not prefix_cache:
            raise SystemExit(
                "apex-tpu-bench: --disagg streams prompt pages through "
                "the prefix index; it needs --page-size and "
                "--prefix-cache")
        if roles is not None:
            pr, sep, de = str(roles).partition(":")
            try:
                role_split = (int(pr), int(de)) if sep else None
            except ValueError:
                role_split = None
            if role_split is None or min(role_split) < 1:
                raise SystemExit(
                    f"apex-tpu-bench: --roles {roles!r}: want P:D "
                    f"positive integers (e.g. 1:2)")
            if replicas > 1 and replicas != sum(role_split):
                raise SystemExit(
                    f"apex-tpu-bench: --roles {roles} is a "
                    f"{sum(role_split)}-replica fleet; --replicas "
                    f"{replicas} contradicts it (drop one)")
            replicas = sum(role_split)
        else:
            if replicas < 2:
                raise SystemExit(
                    "apex-tpu-bench: --disagg needs --replicas >= 2 "
                    "(one prefill + at least one decode) or an "
                    "explicit --roles P:D")
            role_split = (1, replicas - 1)
    if diurnal and replicas < 2:
        raise SystemExit(
            "apex-tpu-bench: --diurnal drives a FLEET through the "
            "day curve; it needs --replicas >= 2 (or --disagg)")
    if replicas == 1 and (hedge_ms is not None
                          or heartbeat_ms is not None):
        raise SystemExit(
            "apex-tpu-bench: --hedge-ms/--heartbeat-ms are fleet "
            "routing; they need --replicas >= 2 (one replica has "
            "nowhere to hedge or fail over to)")
    if heartbeat_ms is not None and heartbeat_ms <= 0:
        # a falsy-coerced default would be a silent no-op of the exact
        # class this matrix refuses
        raise SystemExit(f"apex-tpu-bench: --heartbeat-ms "
                         f"{heartbeat_ms:g} must be > 0")
    if trace_sample is not None:
        if not trace_jsonl:
            # sampling a file that will never exist is the inert-flag
            # class this matrix refuses
            raise SystemExit(
                "apex-tpu-bench: --trace-sample needs --trace-jsonl "
                "(it decides which journeys reach that file)")
        if not 0.0 < trace_sample <= 1.0:
            raise SystemExit(f"apex-tpu-bench: --trace-sample "
                             f"{trace_sample:g} must be in (0, 1]")
    # live metrics: same wiring as apex-tpu-serve — registries + the
    # optional pull endpoint on a daemon thread, atomic snapshots at
    # exit; the scrape-vs-bench comparability is the point
    # (check_regression gates either artifact with the same direction
    # hints). Fleet captures (PR 13) get one registry per replica, the
    # merged pull endpoint at /metrics, and PATH.rK + merged PATH
    # snapshots. Armed BEFORE the engines pay for params + compiles: an
    # inert --tenants or an unbindable port must fail in milliseconds
    metrics = exporter = registries = per_metrics = None
    if role_split:
        replica_specs = [(f"p{i}", "prefill")
                         for i in range(role_split[0])] \
            + [(f"d{i}", "decode") for i in range(role_split[1])]
    else:
        replica_specs = [(f"r{i}", "unified") for i in range(replicas)]
    replica_ids = [rid for rid, _ in replica_specs]
    if tenants > 0 and metrics_port is None and not metrics_snapshot:
        # the labels would reach no observable output — the armed-but-
        # inert flag class this PR makes a loud usage error everywhere
        raise SystemExit(
            "apex-tpu-bench: --tenants labels the live metrics; it "
            "needs --metrics-port and/or --metrics-snapshot to be "
            "observable")
    if metrics_port is not None or metrics_snapshot:
        from apex_tpu.monitor.export import (FleetMetricsExporter,
                                             MetricsExporter,
                                             MetricsRegistry)
        from apex_tpu.serve.metrics import ServeMetrics

        # provenance rides the snapshot meta: check_regression's
        # device-mismatch guard reads it, so a CPU-smoke snapshot can
        # never silently gate real-chip numbers
        metrics_meta = capture_provenance()
        if replicas > 1:
            registries = {rid: MetricsRegistry() for rid in replica_ids}
            per_metrics = {rid: ServeMetrics(registry=reg)
                           for rid, reg in registries.items()}
            if metrics_port is not None:
                try:
                    exporter = FleetMetricsExporter(
                        registries, port=metrics_port,
                        meta=metrics_meta).start()
                except OSError as e:
                    raise SystemExit(
                        f"apex-tpu-bench: cannot bind --metrics-port "
                        f"{metrics_port}: {e}")
                print(f"apex-tpu-bench: fleet metrics at {exporter.url} "
                      f"(per-replica at /metrics/rK)", file=sys.stderr)
        else:
            metrics = ServeMetrics()
            if metrics_port is not None:
                try:
                    exporter = MetricsExporter(
                        metrics.registry, port=metrics_port,
                        snapshot_path=metrics_snapshot,
                        meta=metrics_meta).start()
                except OSError as e:
                    raise SystemExit(
                        f"apex-tpu-bench: cannot bind --metrics-port "
                        f"{metrics_port}: {e}")
                print(f"apex-tpu-bench: metrics at {exporter.url}",
                      file=sys.stderr)
    # tracing (PR 13): the fleet harness (journeys + PATH.rK files +
    # tail capture) for --replicas N, a single tracer + tail-capture
    # router otherwise — both stream through the same sampling policy
    harness = router = tracer = None
    if trace_jsonl:
        rate = 1.0 if trace_sample is None else trace_sample
        if replicas > 1:
            from apex_tpu.serve.fleet import FleetTraceHarness

            harness = FleetTraceHarness(trace_jsonl, replica_ids,
                                        sample_rate=rate)
        else:
            from apex_tpu.monitor.trace import (ChromeTraceWriter,
                                                TailCaptureRouter,
                                                Tracer)

            tracer = Tracer()
            router = TailCaptureRouter(
                {"": ChromeTraceWriter(trace_jsonl, subscribe=False)},
                sample_rate=rate)
    cfg = GPT2Config.tiny()
    if max_len > cfg.n_positions:
        # the tiny preset caps context at its n_positions; a deeper bench
        # workload (e.g. the 32-1024 mixed sweep) needs longer rope/wpe
        cfg = dataclasses.replace(cfg, n_positions=max_len)
    cfg = dataclasses.replace(cfg, compute_dtype=jnp.float32)
    if cfg.n_head % tp:
        # before paying for params: the mesh shards whole heads
        raise SystemExit(
            f"apex-tpu-bench: --tp {tp} must divide the bench model's "
            f"n_head={cfg.n_head} (the serving mesh shards whole heads)")
    params = init_gpt2_params(cfg)
    try:
        # one param pytree shared by every replica (read-only): the
        # fleet bit-exactness story needs identical weights everywhere
        engines = [Engine(cfg, params,
                          EngineConfig(num_slots=num_slots,
                                       max_len=max_len,
                                       temperature=0.0,
                                       page_size=page_size,
                                       num_pages=num_pages,
                                       prefix_cache=prefix_cache,
                                       tp=tp, tp_sync=tp_sync,
                                       spec_draft_len=spec_k,
                                       decode_policy=decode_policy,
                                       kv_quant=kv_quant),
                          seed=0)
                   for _ in range(replicas)]
    except ValueError as e:
        # bad pool geometry (page_size not dividing max_len, undersized
        # num_pages, ...) is a usage error, same as the prefix check below
        raise SystemExit(f"apex-tpu-bench: {e}")
    engine = engines[0]
    if shared_prefix + phi >= max_len:
        raise SystemExit(
            f"apex-tpu-bench: --shared-prefix {shared_prefix} + "
            f"--prompt-len max {phi} leaves no room to generate under "
            f"--serve-max-len {max_len}")
    # warm EVERY reachable prefill bucket, not just the longest prompt's:
    # mixed-length batches and prefix-hit tails (the scan covers only the
    # unshared remainder) land on smaller pow2 buckets, and a fresh
    # compile inside the timed region would corrupt the TTFT/p99 the
    # gate compares — log-many buckets, all paid before the clock
    top = shared_prefix + phi
    buckets, b = [], 1
    while b < top:
        buckets.append(b)
        b *= 2
    buckets.append(top)
    for e in engines:
        e.aot_compile(buckets)
    rng = np.random.RandomState(0)

    def _admission():
        if max_queue is None:
            return None
        from apex_tpu.serve.resilience import AdmissionController

        return AdmissionController(max_queue=max_queue,
                                   shed_policy=shed_policy)

    # enough requests to keep every slot busy and exercise backfill
    n_requests = max(2 * num_slots * replicas,
                     (steps * num_slots) // 8 + 1)
    system = [int(t) for t in rng.randint(0, cfg.vocab_size,
                                          shared_prefix)]
    specs = []
    for i in range(n_requests):
        plen = int(rng.randint(plo, phi + 1))
        tail = [int(t) for t in rng.randint(0, cfg.vocab_size, plen)]
        specs.append(Request(
            request_id=f"bench-{i}", tokens=system + tail,
            max_new_tokens=8, deadline_ms=deadline_ms,
            tenant=f"tenant-{i % tenants}" if tenants > 0 else None))
    fleet = None
    recorders = []
    fleet_flight = single_flight = None
    if replicas > 1:
        from apex_tpu.serve.disagg import DisaggController
        from apex_tpu.serve.fleet import EngineReplica, FleetController

        # CPU-tolerant death budget (2s at the default interval): a
        # fabricated death on a healthy bench fleet would stamp nonzero
        # failovers/replica_dead into lower-is-better gated counters —
        # flunking the regression gate off machine noise
        fleet_cls = DisaggController if role_split else FleetController
        fleet = fleet_cls(
            [EngineReplica(
                rid, e, role=role, admission=_admission(),
                metrics=per_metrics[rid] if per_metrics else None,
                tracer=harness.tracer_for(rid) if harness else None)
             for (rid, role), e in zip(replica_specs, engines)],
            heartbeat_ms=50.0 if heartbeat_ms is None else heartbeat_ms,
            suspect_misses=20, dead_misses=40, hedge_ms=hedge_ms,
            tracer=harness.fleet_tracer if harness else None)
        if flight_recorder:
            from apex_tpu.serve.fleet import attach_fleet_recorders

            # per-replica postmortems + the fleet-plane recorder — the
            # ONE wiring shared with apex-tpu-serve --replicas
            recorders = attach_fleet_recorders(fleet, flight_recorder,
                                               harness)
            fleet_flight = recorders[-1]
        if not diurnal:
            for spec in specs:
                fleet.submit(spec)
    else:
        if flight_recorder:
            from apex_tpu.monitor.flight import FlightRecorder

            single_flight = FlightRecorder(flight_recorder,
                                           tracer=tracer).attach()
            recorders.append(single_flight)
        sched = ServeScheduler(engine, admission=_admission(),
                               metrics=metrics, tracer=tracer,
                               flight_recorder=single_flight)
        for spec in specs:
            sched.submit(spec)
    t0 = time.perf_counter()
    try:
        import contextlib

        # the fleet runs the whole request set (its workload bound is
        # n_requests, which --steps sized above); the liveness bound
        # scales with it so a long-but-healthy run never trips a
        # TimeoutError mid-bench
        with (fleet_flight.guard("fleet") if fleet_flight is not None
              else contextlib.nullcontext()):
            if fleet is not None and diurnal:
                # one compressed "day": requests arrive along the
                # seeded sinusoidal curve (trough -> peak -> trough)
                # while the control loop pumps, then the fleet finishes
                # the backlog — total volume sized to the --steps
                # workload so the entry stays comparable in scale
                from apex_tpu.serve.disagg import DiurnalTraffic

                day_s = 2.0
                traffic = DiurnalTraffic(
                    day_s=day_s, seed=0,
                    capacity_scale=(len(specs) / day_s)
                    / (2_000_000 * 8.0 / 86400.0),
                    prompt_lens=list(range(plo, phi + 1)),
                    max_new_tokens=8, vocab=cfg.vocab_size,
                    id_prefix="bench-diurnal")
                fleet.start()
                traffic.start()
                t_end = time.perf_counter() + day_s
                while time.perf_counter() < t_end:
                    for r in traffic.due():
                        if system or deadline_ms is not None \
                                or tenants > 0:
                            r = dataclasses.replace(
                                r, tokens=system + list(r.tokens),
                                deadline_ms=deadline_ms,
                                tenant=f"tenant-{traffic.emitted % tenants}"
                                if tenants > 0 else None)
                        fleet.submit(r)
                    fleet.pump()
                    time.sleep(0.002)
                stats = fleet.run(
                    max_wall_s=max(60.0, 2.0 * max(traffic.emitted, 1)))
            elif fleet is not None:
                stats = fleet.run(max_wall_s=max(60.0, 2.0 * len(specs)))
            else:
                stats = sched.run(max_steps=steps)
        # measured BEFORE the finally teardown: exporter.stop() blocks on
        # the HTTP server's shutdown poll + thread join + snapshot I/O,
        # and bench_wall_s gates lower-is-better — teardown noise must
        # not read as a perf regression of the metrics-armed capture
        wall = time.perf_counter() - t0
    finally:
        if exporter is not None:
            exporter.stop()
        if metrics_snapshot and registries is not None:
            # per-replica mergeable snapshots at PATH.rK plus the
            # metrics_merge fleet view at PATH itself (the serve CLI's
            # contract), all atomic, provenance meta on each
            from apex_tpu.monitor.export import (atomic_write_json,
                                                 merge_snapshots)

            docs = []
            for rid, reg in registries.items():
                doc = reg.snapshot(meta={**(metrics_meta or {}),
                                         "replica": rid})
                atomic_write_json(f"{metrics_snapshot}.{rid}", doc)
                docs.append(doc)
            atomic_write_json(metrics_snapshot, merge_snapshots(docs))
        elif exporter is None and metrics is not None \
                and metrics_snapshot:
            from apex_tpu.monitor.export import write_snapshot

            write_snapshot(metrics.registry, metrics_snapshot,
                           meta=metrics_meta)
        for fr in recorders:
            fr.detach()
        if harness is not None:
            harness.close()
        if router is not None:
            router.close()
    s = stats.summary()
    if fleet is not None:
        # fleet-wide capacity/hit aggregates the single path reads off
        # its one scheduler; summed over replicas here
        peak_resident = sum(h.scheduler.peak_resident_tokens
                            for h in fleet.handles)
        kv_bytes = sum(h.engine.kv_cache_bytes for h in fleet.handles)
        admitted = sum(h.scheduler.admitted for h in fleet.handles)
        prefix_hits = sum(h.scheduler.prefix_hits for h in fleet.handles)
        s["prefix_hit_rate"] = round(prefix_hits / admitted, 4) \
            if admitted else 0.0
        s["peak_resident_tokens"] = peak_resident
        # speculative aggregates the single path reads off its one
        # scheduler summary; pooled over replicas here (fleet-wide
        # tokens over fleet-wide slot-steps, NOT a mean of ratios)
        slot_steps = sum(h.scheduler.decode_slot_steps
                         for h in fleet.handles)
        dec_tokens = sum(h.scheduler.decode_tokens
                         for h in fleet.handles)
        proposed = sum(h.scheduler.spec_proposed for h in fleet.handles)
        accepted = sum(h.scheduler.spec_accepted for h in fleet.handles)
        s["accepted_tokens_per_step"] = round(
            dec_tokens / slot_steps, 4) if slot_steps else 0.0
        s["spec_accept_rate"] = round(
            accepted / proposed, 4) if proposed else 0.0
    else:
        kv_bytes = engine.kv_cache_bytes
    suite = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        # capture provenance: a CPU-smoke capture must be identifiable as
        # one — check_regression flags a device_kind mismatch between
        # capture and baseline instead of gating apples against oranges
        **capture_provenance(),
        "serve_decode": {
            "metric": "serve_decode_tokens_per_s",
            "value": s["tokens_per_s"], "unit": "tokens_per_s",
            "p50_ms": s["p50_step_ms"], "p99_ms": s["p99_step_ms"],
            "ttft_ms": s["ttft_p50_ms"],
            # overload SLO fields (lower-is-better; check_regression
            # knows) — zero on the default unbounded/no-deadline workload
            "rejected": s["rejected"],
            "deadline_exceeded": s["deadline_exceeded"],
            "shed_rate": s["shed_rate"],
            # paged-pool effectiveness (higher-is-better; the gate
            # knows): peak resident tokens per byte of KV reservation —
            # the capacity number paging multiplies at equal HBM budget —
            # and the fraction of admissions served partly from shared
            # prefix pages
            # significant digits, not decimal places: a production-scale
            # pool puts this gate metric near 1e-8, where round(x, 9)
            # would quantize away a real 5-10% capacity regression
            "resident_tokens_per_hbm_byte": float(
                f"{s['peak_resident_tokens'] / max(kv_bytes, 1):.6g}"),
            "prefix_hit_rate": s["prefix_hit_rate"],
            # fleet resilience counters (lower-is-better; the gate
            # knows failover/hedge_fired/replica_dead) — only stamped
            # by fleet captures, so single-replica baselines simply
            # skip them instead of gating a missing field
            **({"failovers": s["failovers"],
                "hedge_fired": s["hedge_fired"],
                "replica_dead": s["replica_dead"],
                "migrations": s["migrations"]}
               if fleet is not None else {}),
            # disaggregated captures only: refused handoffs are
            # certification failures (lower-is-better, the gate knows
            # "handoff_refused"); pages_migrated is the streaming
            # volume the refusal rate is read against
            **({"handoff_refused": s["handoffs_refused"],
                "pages_migrated": s["pages_migrated"]}
               if role_split else {}),
            # traced captures only (lower-is-better; the gate knows):
            # every promoted journey is a bad-outcome request the tail
            # capture had to rescue — untraced baselines simply skip it
            **({"trace_promoted": (harness.stats() if harness is not None
                                   else router.stats())["promoted"]}
               if trace_jsonl else {}),
            # speculative captures only (all higher-is-better; the gate
            # knows tokens/_per_s/accept_rate): tokens committed per
            # verify step (1.0 is the one-token floor), the draft
            # acceptance fraction, and the throughput restated under a
            # spec-specific name so the gate can hold the speculative
            # rate by name — one-token baselines simply skip all three,
            # and the workload axes below make cross-config comparisons
            # a refusal, not a skew
            **({"accepted_tokens_per_step": s["accepted_tokens_per_step"],
                "spec_accept_rate": s["spec_accept_rate"],
                "spec_tokens_per_s": s["tokens_per_s"]}
               if spec_k else {}),
            "bench_wall_s": round(wall, 3),
            # workload config nested as a dict: check_regression lifts
            # only numeric scalars, so a capture with different
            # --steps/--serve-slots than the baseline gates on PERF
            # fields alone, not on its own configuration
            # the overload knobs ride along so a capture whose SLO
            # counters were shaped by a different config is identifiable
            # (nested dict: never lifted into the gated metrics)
            "workload": {"steps": s["decode_steps"],
                         "new_tokens": s["new_tokens"],
                         "slots": num_slots,
                         "deadline_ms": deadline_ms,
                         "max_queue": max_queue,
                         "shed_policy": shed_policy,
                         # pool geometry provenance: a capture whose
                         # capacity/hit-rate numbers were shaped by a
                         # different page_size (or no paging at all) is
                         # identifiable, never silently gated against
                         "max_len": max_len,
                         "page_size": page_size or 0,
                         "num_pages": engine._num_pages
                         if page_size else 0,
                         "prefix_cache": bool(prefix_cache),
                         "prompt_len": prompt_len,
                         "shared_prefix": shared_prefix,
                         "kv_cache_bytes": kv_bytes,
                         # fleet shape provenance: counters shaped by a
                         # different replica count / hedge / heartbeat
                         # config are identifiable, never silently
                         # gated across incomparable configs
                         "replicas": replicas,
                         "hedge_ms": hedge_ms,
                         "heartbeat_ms": heartbeat_ms,
                         # mesh shape provenance: a tp=2 capture's
                         # tokens/s measures a sharded step (collective
                         # latency included) — check_regression REFUSES
                         # to gate it against a different mesh shape
                         # (incomparable_entries), not merely flags it
                         "tp": tp,
                         "tp_sync": tp_sync if tp > 1 else None,
                         # disaggregation provenance: a disaggregated
                         # (or diurnal-arrival) capture measures a
                         # different serving pipeline — the gate
                         # REFUSES to compare across these axes
                         # (incomparable_entries), not merely flags it
                         "disagg": bool(role_split),
                         "roles": f"{role_split[0]}:{role_split[1]}"
                         if role_split else None,
                         "diurnal": bool(diurnal),
                         # trace provenance (PR-8 incomparable-config
                         # precedent): a traced capture pays host-side
                         # span work per request — it must never gate
                         # against an untraced baseline as if the two
                         # measured the same thing
                         "traced": bool(trace_jsonl),
                         "trace_sample": (
                             1.0 if trace_sample is None
                             else trace_sample)
                         if trace_jsonl else None,
                         # speculative provenance: a spec capture's
                         # tokens/s rides draft-acceptance luck and its
                         # step time carries draft_len + 1 positions —
                         # the gate REFUSES to compare across these
                         # axes (missing key = speculation off, the
                         # pre-spec default, so legacy baselines refuse
                         # rather than silently gate)
                         "spec": bool(spec_k),
                         "draft_len": spec_k,
                         "decode_policy": decode_policy,
                         # quantization provenance: a quantized
                         # capture's capacity/latency numbers are a
                         # different workload — the gate refuses to
                         # compare across codec or block (missing key
                         # = unquantized, the pre-quant default)
                         "kv_quant": kv_quant,
                         "quant_block": int(engine.quant_block)},
            # a subset capture, not the full committed suite
            "complete": False,
        },
    }
    if cost_ledger:
        # device-independent companion artifact: the per-executable cost
        # ledger extracted from the SAME AOT artifacts the bench just
        # ran (no re-trace, no re-lower — Engine.cost_ledger resolves
        # from the retained lowerings), provenance-stamped so
        # check_regression can refuse cross-device/cross-workload gates
        from apex_tpu.monitor import costs
        from apex_tpu.monitor.export import atomic_write_json

        ledger = engine.cost_ledger(chip=chip_spec)
        ledger["meta"] = capture_provenance()
        atomic_write_json(cost_ledger, ledger)
        print(f"apex-tpu-bench: cost ledger (chip="
              f"{ledger['chip_spec']}, gating={ledger['gating']}, "
              f"schema={costs.LEDGER_SCHEMA}) at {cost_ledger}",
              file=sys.stderr)
    if bench is not None:
        # same contract as the kernel-subset gate: atomic publish via the
        # repo bench module (loaded up front — a torn gate file is worse
        # than no gate file)
        bench.atomic_write_json(emit_baseline, suite)
        print(json.dumps({"baseline": emit_baseline,
                          "kernels": ["serve_decode"]}))
    else:
        print(json.dumps(suite, indent=1))


def _subset_bench(kernels: str | None, emit_baseline: str | None) -> None:
    """Run a bench-suite subset directly (no worker/cache indirection) and
    optionally write it as a committed-baseline artifact."""
    import json

    bench = _load_bench_module()

    import jax
    import jax.numpy as jnp

    from apex_tpu.utils.logging import subscribe_events

    backend = jax.default_backend()
    only = None
    if kernels:
        only = [k.strip() for k in kernels.split(",") if k.strip()]
    # record which autotuned configs the benched kernels selected (cache
    # hits publish kernel_autotune on the bus) — the baseline artifact then
    # carries its own tuning provenance
    autotune: list = []
    unsub = subscribe_events(
        lambda rec: autotune.append(
            {k: rec[k] for k in ("kernel", "key", "params", "source")
             if k in rec})
        if rec.get("event") == "kernel_autotune" else None)
    try:
        suite = bench.run_suite(jax, jnp, backend, out_path=None, only=only)
    finally:
        unsub()
    if autotune:
        suite["autotune"] = autotune
    if emit_baseline:
        bench.atomic_write_json(emit_baseline, suite)
        print(json.dumps({"baseline": emit_baseline, "backend": backend,
                          "kernels": suite.get("subset") or
                          [n for n, _ in bench.BENCHES]}))
    else:
        print(json.dumps({k: v for k, v in suite.items()
                          if isinstance(v, dict)}, indent=1))


def main() -> None:
    # a preempted bench run (SIGTERM from the scheduler) exits cleanly with
    # a structured record instead of a stack trace mid-measurement; there is
    # no step boundary to poll, so the guard raises to unwind immediately
    from apex_tpu.resilience import PreemptionGuard
    from apex_tpu.utils.logging import is_rank_zero, publish_event

    with PreemptionGuard(raise_on_signal=True) as guard:
        # --flight-recorder selects this mode too: silently dropping the
        # flag would mean the requested postmortem recorder never armed —
        # the exact silent-death failure it exists to prevent. With
        # --serve, --trace-jsonl/--flight-recorder belong to the SERVE
        # bench (PR 13: fleet journeys + per-replica postmortems), so
        # those two no longer force the telemetry train bench —
        # but --telemetry-jsonl stays a train-bench flag, and with
        # --serve it must keep hitting the loud mode conflict below
        # (the serve bench has no event mirror; swallowing the flag
        # would be the silent-no-op class this matrix refuses)
        has_serve = any(a == "--serve" for a in sys.argv[1:])
        serve_only = [a for a in sys.argv[1:]
                      if a.split("=", 1)[0] in ("--disagg", "--roles",
                                                "--diurnal",
                                                "--cost-ledger",
                                                "--chip-spec",
                                                "--spec-draft-len",
                                                "--decode-policy",
                                                "--kv-quant")]
        if serve_only and not has_serve:
            # without --serve these would silently fall through to the
            # kernel bench — the inert-flag class this matrix refuses
            print(f"apex-tpu-bench: {serve_only[0]} shapes the serving "
                  f"bench; it needs --serve", file=sys.stderr)
            sys.exit(2)
        has_train_chaos = any(a == "--train-chaos" for a in sys.argv[1:])
        has_telemetry = any(
            a.split("=", 1)[0] == "--telemetry-jsonl"
            for a in sys.argv[1:]) or (
            any(a.split("=", 1)[0] in ("--trace-jsonl",
                                       "--flight-recorder")
                for a in sys.argv[1:]) and not has_serve)
        # --emit-baseline is shared by the serve, train-chaos, and
        # kernel-subset modes; --kernels is NOT valid with --serve or
        # --train-chaos and must keep refusing
        has_subset = any(a.split("=", 1)[0] == "--kernels"
                         for a in sys.argv[1:]) or (
            any(a.split("=", 1)[0] == "--emit-baseline"
                for a in sys.argv[1:]) and not has_serve
            and not has_train_chaos)
        if sum((has_telemetry, has_subset, has_serve,
                has_train_chaos)) > 1:
            # parse_known_args would silently swallow the other mode's
            # flags — refuse instead of pretending both ran
            print("apex-tpu-bench: --telemetry-jsonl, --serve, "
                  "--train-chaos, and --kernels/--emit-baseline are "
                  "separate modes; run them as separate invocations",
                  file=sys.stderr)
            sys.exit(2)
        if has_train_chaos:
            import argparse

            ap = argparse.ArgumentParser(prog="apex-tpu-bench")
            ap.add_argument("--train-chaos", action="store_true")
            ap.add_argument("--steps", type=int, default=12,
                            help="train steps the chaos schedule runs "
                                 "over (min 6 so every fault fires)")
            ap.add_argument("--world", type=int, default=1,
                            help="data-parallel degree (thread-faked "
                                 "ranks; must divide --grad-shards)")
            ap.add_argument("--grad-shards", type=int, default=None,
                            help="fixed micro-shard count (default: "
                                 "world)")
            ap.add_argument("--tp", type=int, default=1,
                            help="tensor-parallel degree: each micro-"
                                 "shard's grad runs over the head-axis "
                                 "mesh (bit-identical to --tp 1); "
                                 "stamped into workload provenance")
            ap.add_argument("--emit-baseline", nargs="?",
                            const="BENCH_BASELINE_TRAIN.json",
                            default=None,
                            help="write the capture as a suite JSON "
                                 "(default BENCH_BASELINE_TRAIN.json)")
            args, _ = ap.parse_known_args(sys.argv[1:])
            shards = (args.grad_shards if args.grad_shards is not None
                      else max(1, args.world))
            # the full geometry contract, as a loud exit-2 BEFORE any
            # params/compile work (the TrainConfig would refuse anyway,
            # but as a traceback, not a usage error): world | shards
            # AND shards | the fixed bench batch of 8
            if args.world < 1 or shards % args.world or 8 % shards:
                print(f"apex-tpu-bench: --train-chaos needs --world "
                      f">= 1 dividing --grad-shards (got {args.world}/"
                      f"{shards}), and --grad-shards dividing the "
                      f"bench batch of 8", file=sys.stderr)
                sys.exit(2)
            if args.tp < 1 or 32 % args.tp:
                # the bench model's hidden is the TrainConfig default
                # (32); same loud pre-compile refusal as the trainer CLI
                print(f"apex-tpu-bench: --train-chaos --tp {args.tp} "
                      f"must be >= 1 and divide the bench model's "
                      f"hidden of 32", file=sys.stderr)
                sys.exit(2)
            _train_chaos_bench(args.steps, args.world, args.grad_shards,
                               args.emit_baseline, tp=args.tp)
        elif has_serve:
            import argparse

            ap = argparse.ArgumentParser(prog="apex-tpu-bench")
            ap.add_argument("--serve", action="store_true")
            ap.add_argument("--steps", type=int, default=16,
                            help="decode steps to run (the workload "
                                 "keeps slots busy with backfill)")
            ap.add_argument("--serve-slots", type=int, default=4)
            ap.add_argument("--deadline-ms", type=float, default=None,
                            help="per-request latency budget; misses "
                                 "show up as deadline_exceeded in the "
                                 "serve_decode entry")
            ap.add_argument("--max-queue", type=int, default=None,
                            help="bound the admission backlog; overflow "
                                 "is shed per --shed-policy and counted "
                                 "in rejected/shed_rate")
            ap.add_argument("--shed-policy", default="reject-newest",
                            choices=["reject-newest", "shed-oldest",
                                     "priority"])
            ap.add_argument("--serve-max-len", type=int, default=64,
                            help="per-request context bound (prompt + "
                                 "generated); deep mixed-length "
                                 "workloads need it above the default")
            ap.add_argument("--prompt-len", default="8",
                            help="scripted prompt length: N, or MIN:MAX "
                                 "for a seeded mixed-length workload")
            ap.add_argument("--shared-prefix", type=int, default=0,
                            help="every prompt starts with this many "
                                 "shared tokens (the fleet-wide system "
                                 "prompt --prefix-cache deduplicates)")
            ap.add_argument("--page-size", type=int, default=None,
                            help="tokens per KV page: paged block pool "
                                 "instead of per-slot reservation")
            ap.add_argument("--num-pages", type=int, default=None,
                            help="pool pages incl. the null page "
                                 "(default: slot-cache-equivalent "
                                 "capacity; smaller overcommits)")
            ap.add_argument("--prefix-cache", action="store_true",
                            help="share read-only prompt-prefix pages "
                                 "across requests (needs --page-size)")
            ap.add_argument("--emit-baseline", nargs="?",
                            const="BENCH_BASELINE_SERVE.json",
                            default=None,
                            help="write the capture as a suite JSON "
                                 "(default BENCH_BASELINE_SERVE.json)")
            ap.add_argument("--metrics-port", type=int, default=None,
                            help="serve live Prometheus /metrics + JSON "
                                 "/metrics.json while the bench runs "
                                 "(0 = ephemeral port)")
            ap.add_argument("--metrics-snapshot", default=None,
                            help="commit an atomic mergeable metrics "
                                 "snapshot at exit (gateable by "
                                 "check_regression, mergeable by "
                                 "tools/metrics_merge.py)")
            ap.add_argument("--tenants", type=int, default=0,
                            help="label the scripted workload round-"
                                 "robin across N tenants (per-tenant "
                                 "series in the live metrics)")
            ap.add_argument("--replicas", type=int, default=1,
                            help="run the workload over N thread-backed "
                                 "engine replicas under the fleet "
                                 "controller; the entry gains failovers/"
                                 "hedge_fired/replica_dead/migrations")
            ap.add_argument("--hedge-ms", type=float, default=None,
                            help="hedged dispatch threshold (needs "
                                 "--replicas >= 2)")
            ap.add_argument("--heartbeat-ms", type=float, default=None,
                            help="replica heartbeat interval (needs "
                                 "--replicas >= 2; default 50)")
            ap.add_argument("--trace-jsonl", default=None,
                            help="per-request span traces as Perfetto-"
                                 "loadable Chrome-trace JSON; with "
                                 "--replicas N the fleet journey lands "
                                 "here plus one file per replica at "
                                 "PATH.rK")
            ap.add_argument("--trace-sample", type=float, default=None,
                            help="seeded head-sampling rate over "
                                 "request journeys; bad outcomes are "
                                 "always promoted (needs --trace-jsonl)")
            ap.add_argument("--flight-recorder", default=None,
                            help="crash-time postmortem dump path; with "
                                 "--replicas N one recorder per replica "
                                 "(PATH.rK, auto-dump on that replica's "
                                 "death) plus the fleet-plane PATH")
            ap.add_argument("--tp", type=int, default=1,
                            help="tensor-parallel mesh size: shard the "
                                 "bench engine (params + KV pool on the "
                                 "head axis) over N devices — the "
                                 "serve_decode tokens/s scaling curve; "
                                 "workload provenance records it so the "
                                 "gate never compares mesh shapes")
            ap.add_argument("--tp-sync", default="exact",
                            choices=["exact", "overlap", "relaxed"],
                            help="per-layer cross-rank sync under --tp "
                                 ">= 2 (exact = bit-identical oracle)")
            ap.add_argument("--disagg", action="store_true",
                            help="disaggregated prefill/decode fleet: "
                                 "dedicated prefill replicas stream "
                                 "certified KV pages into the decode "
                                 "pool (needs --page-size + "
                                 "--prefix-cache and --replicas >= 2 "
                                 "or --roles)")
            ap.add_argument("--roles", default=None, metavar="P:D",
                            help="prefill:decode replica split (needs "
                                 "--disagg; default 1:(replicas-1))")
            ap.add_argument("--diurnal", action="store_true",
                            help="drive the fleet through one seeded "
                                 "compressed diurnal day instead of an "
                                 "upfront burst (needs --replicas >= 2 "
                                 "or --disagg)")
            ap.add_argument("--cost-ledger", default=None, metavar="PATH",
                            help="write the device-independent compiled-"
                                 "step cost ledger (per-phase FLOPs/HBM "
                                 "bytes from the benched AOT artifacts, "
                                 "apex_tpu.cost_ledger/v1) — gateable by "
                                 "check_regression, diffable by "
                                 "tools/cost_diff.py")
            ap.add_argument("--chip-spec", default=None,
                            help="price the ledger roofline against this "
                                 "chip generation (e.g. v5p, v6e; "
                                 "default: detected chip, else the non-"
                                 "gating cpu spec; needs --cost-ledger)")
            ap.add_argument("--spec-draft-len", type=int, default=None,
                            metavar="K",
                            help="speculative decoding: host n-gram "
                                 "drafts of K tokens per slot verified "
                                 "by one compiled K+1-position step — "
                                 "the entry gains accepted_tokens_per_"
                                 "step / spec_accept_rate / spec_tokens"
                                 "_per_s (higher-is-better) and spec "
                                 "workload provenance the gate refuses "
                                 "to compare across")
            ap.add_argument("--decode-policy", default=None,
                            metavar="POLICY",
                            help="decode-policy seam: greedy | "
                                 "top_p[=P] | min_p[=M] | spec(POLICY) "
                                 "with optional ',t=T' (beam-like "
                                 "policies are refused — no exact "
                                 "per-token acceptance test exists)")
            ap.add_argument("--kv-quant", default=None,
                            choices=["int8", "mxfp8"],
                            help="block-scale KV-cache quantization: "
                                 "K/V pages as codec bytes + per-"
                                 "(token, head) fp32 scales — the "
                                 "entry's resident_tokens_per_hbm_byte "
                                 "carries the capacity win and the "
                                 "kv_quant/quant_block workload axes "
                                 "refuse fp32 baselines (incompatible "
                                 "with --spec-draft-len)")
            args, _ = ap.parse_known_args(sys.argv[1:])
            _serve_bench(args.steps, args.serve_slots,
                         args.emit_baseline,
                         deadline_ms=args.deadline_ms,
                         max_queue=args.max_queue,
                         shed_policy=args.shed_policy,
                         max_len=args.serve_max_len,
                         prompt_len=args.prompt_len,
                         shared_prefix=args.shared_prefix,
                         page_size=args.page_size,
                         num_pages=args.num_pages,
                         prefix_cache=args.prefix_cache,
                         metrics_port=args.metrics_port,
                         metrics_snapshot=args.metrics_snapshot,
                         tenants=args.tenants,
                         replicas=args.replicas,
                         hedge_ms=args.hedge_ms,
                         heartbeat_ms=args.heartbeat_ms,
                         trace_jsonl=args.trace_jsonl,
                         trace_sample=args.trace_sample,
                         flight_recorder=args.flight_recorder,
                         tp=args.tp, tp_sync=args.tp_sync,
                         disagg=args.disagg, roles=args.roles,
                         diurnal=args.diurnal,
                         cost_ledger=args.cost_ledger,
                         chip_spec=args.chip_spec,
                         spec_draft_len=args.spec_draft_len,
                         decode_policy=args.decode_policy,
                         kv_quant=args.kv_quant)
        elif has_telemetry:
            import argparse

            ap = argparse.ArgumentParser(prog="apex-tpu-bench")
            ap.add_argument("--telemetry-jsonl", default=None)
            ap.add_argument("--trace-jsonl", default=None,
                            help="write per-step span traces as "
                                 "Perfetto-loadable Chrome-trace JSON "
                                 "(usable with or without "
                                 "--telemetry-jsonl)")
            ap.add_argument("--flight-recorder", default=None,
                            help="crash-time flight-recorder dump path")
            ap.add_argument("--steps", type=int, default=8)
            ap.add_argument("--watchdog-timeout", type=float, default=None,
                            help="seconds a train step may block before a "
                                 "collective_stall event lands in the JSONL")
            args, _ = ap.parse_known_args(sys.argv[1:])
            _telemetry_bench(args.telemetry_jsonl, args.steps,
                             watchdog_timeout=args.watchdog_timeout,
                             trace_jsonl=args.trace_jsonl,
                             flight_path=args.flight_recorder)
        elif has_subset:
            import argparse

            ap = argparse.ArgumentParser(prog="apex-tpu-bench")
            ap.add_argument("--kernels", default=None,
                            help="comma-separated bench subset "
                                 "(e.g. fused_adam_1b,layer_norm)")
            ap.add_argument("--emit-baseline", nargs="?",
                            const="BENCH_BASELINE.json", default=None,
                            help="write the capture as a committed-"
                                 "baseline suite JSON (default "
                                 "BENCH_BASELINE.json)")
            args, _ = ap.parse_known_args(sys.argv[1:])
            _subset_bench(args.kernels, args.emit_baseline)
        else:
            here = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
            bench = os.path.join(here, "bench.py")
            if os.path.exists(bench):
                sys.argv = [bench] + sys.argv[1:]
                runpy.run_path(bench, run_name="__main__")
            else:
                _inline_bench()
    if guard.should_stop():
        # console record on rank 0 only (multi-host bench: one banner);
        # the bus event fires everywhere for per-host consumers
        publish_event("bench_preempted", level="warning",
                      emit=is_rank_zero(),
                      signal=guard.received_signal,
                      action="results above this line are complete")
        # a truncated run must not read as a successful benchmark to the
        # caller's exit-code check; keep the conventional signal status
        sys.exit(128 + guard.received_signal)


if __name__ == "__main__":
    main()
