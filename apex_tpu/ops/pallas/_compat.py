"""Version compatibility shims for the Pallas TPU API surface.

jax renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams`` (same
constructor: ``dimension_semantics``, ``vmem_limit_bytes``, ...). Kernels
import the resolved name from here so the package imports — and the whole
test tier collects — on either side of the rename.
"""

from __future__ import annotations

import jax.experimental.pallas.tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")
