"""Pallas TPU kernel for the fused Adam/AdamW update over flat parameter buffers.

TPU-native equivalent of ``csrc/multi_tensor_adam.cu`` (``AdamFunctor`` :24,
``AdamCapturableFunctor`` :111+, ``AdamCapturableMasterFunctor``) launched through
``csrc/multi_tensor_apply.cuh:32-103``.

Design: instead of packing ≤110 tensor pointers into kernel args per launch, the
TPU framework keeps each dtype-group of params/grads/state as ONE contiguous
flat buffer (see apex_tpu.utils.flatten) and runs a single Pallas kernel gridded
over 128-lane tiles of that buffer. This is both the launch-count win the CUDA
harness chases and the HBM-streaming-friendly layout XLA wants.

"Capturable" semantics are inherent: lr / step / inv_scale / found_inf enter as
traced scalars in SMEM, so the whole update lives inside one jitted step with no
host sync — the same goal the CUDA-graph-capturable variant serves.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.utils.env import interpret_default

LANE = 128
DEFAULT_BLOCK_ROWS = 512  # (512, 128) fp32 block = 256 KiB / operand

ADAM_MODE_L2 = 0     # Adam with L2 regularization (grad += wd * p)
ADAM_MODE_ADAMW = 1  # decoupled weight decay (multi_tensor_adam.cu:16-19)

# scalar layout in SMEM: [lr, beta1, beta2, eps, wd, bc1, bc2, inv_scale, noop]
_NS = 9


def _adam_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref,
                 p_out, m_out, v_out, *, mode: int):
    lr = scal_ref[0, 0]
    beta1 = scal_ref[0, 1]
    beta2 = scal_ref[0, 2]
    eps = scal_ref[0, 3]
    wd = scal_ref[0, 4]
    bc1 = scal_ref[0, 5]          # 1 - beta1**step (or 1.0)
    bc2 = scal_ref[0, 6]
    inv_scale = scal_ref[0, 7]    # grad unscale factor (1.0 when no loss scaling)
    noop = scal_ref[0, 8]         # found_inf: 1.0 => skip update

    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * inv_scale
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)

    if mode == ADAM_MODE_L2:
        g = g + wd * p
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if mode == ADAM_MODE_ADAMW:
        update = update + wd * p
    p_new = p - lr * update

    keep = noop != 0.0
    p_out[...] = jnp.where(keep, p, p_new).astype(p_out.dtype)
    m_out[...] = jnp.where(keep, m, m_new).astype(m_out.dtype)
    v_out[...] = jnp.where(keep, v, v_new).astype(v_out.dtype)


def _master_adam_kernel(scal_ref, pm_ref, g_ref, m_ref, v_ref,
                        pm_out, p_lp_out, m_out, v_out, *, mode: int):
    """Master-weight variant (≈ AdamCapturableMasterFunctor, depth 5):
    fp32 master params updated; low-precision model copy written out."""
    lr = scal_ref[0, 0]
    beta1 = scal_ref[0, 1]
    beta2 = scal_ref[0, 2]
    eps = scal_ref[0, 3]
    wd = scal_ref[0, 4]
    bc1 = scal_ref[0, 5]
    bc2 = scal_ref[0, 6]
    inv_scale = scal_ref[0, 7]
    noop = scal_ref[0, 8]

    p = pm_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32) * inv_scale
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)

    if mode == ADAM_MODE_L2:
        g = g + wd * p
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if mode == ADAM_MODE_ADAMW:
        update = update + wd * p
    p_new = p - lr * update

    keep = noop != 0.0
    p_sel = jnp.where(keep, p, p_new)
    pm_out[...] = p_sel
    p_lp_out[...] = p_sel.astype(p_lp_out.dtype)
    m_out[...] = jnp.where(keep, m, m_new).astype(m_out.dtype)
    v_out[...] = jnp.where(keep, v, v_new).astype(v_out.dtype)


SUBLANE = 8
TILE = LANE * SUBLANE  # minimum flat-buffer granularity (1024 elements)


def _as_rows(x: jax.Array):
    n = x.size
    assert n % TILE == 0, "flat buffers must be (8*128)-element padded"
    return x.reshape(n // LANE, LANE)


def _pick_block_rows(rows: int) -> int:
    """Fixed streaming block; the grid is ``pl.cdiv(rows, br)`` and Mosaic
    masks the ragged tail block (safe: every kernel using this is elementwise
    per row, so out-of-bounds garbage reads never feed an in-bounds write).
    A divisor search here is a perf trap — at 999M elements the largest
    divisor ≤512 of rows is 16, which once produced a 488k-step grid."""
    return min(DEFAULT_BLOCK_ROWS, rows)


def _flat_block_rows(kernel: str, rows: int, dtype, interpret: bool,
                     block_rows) -> int:
    """Streaming-block resolution shared by every flat optimizer kernel:
    explicit arg > (compiled only) tuned cache entry > heuristic. In
    interpret mode the grid executes cell-by-cell in Python, so CPU tests
    always pay ONE kernel invocation — and, per the tune contract, the
    cache is never consulted there.

    Entries are keyed dtype-agnostic (``dtype=None``): the streaming
    block depends on the row count, not the element type, and the master-
    weight variant (fp32 params) must share the entries warmed on the
    bf16 bench shapes rather than silently missing them. ``dtype`` stays
    a parameter for call-site symmetry with the other kernels."""
    del dtype
    if block_rows:
        return block_rows
    if interpret:
        return rows
    from apex_tpu.tune.api import pow2_bucket, tuned_params

    def ok(p):
        br = p["block_rows"]
        return isinstance(br, int) and br > 0 and br % SUBLANE == 0

    br = tuned_params(
        kernel, (("rows", pow2_bucket(rows)),),
        {"block_rows": _pick_block_rows(rows)},
        dtype=None, interpret=interpret, validate=ok)["block_rows"]
    return min(br, rows)


def _pack_scalars(lr, beta1, beta2, eps, weight_decay, step,
                  bias_correction, inv_scale, found_inf):
    one = jnp.float32(1.0)
    stepf = jnp.asarray(step, jnp.float32)
    if bias_correction:
        bc1 = one - jnp.power(jnp.float32(beta1), stepf)
        bc2 = one - jnp.power(jnp.float32(beta2), stepf)
    else:
        bc1 = one
        bc2 = one
    return jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.float32(beta1), jnp.float32(beta2),
        jnp.float32(eps), jnp.asarray(weight_decay, jnp.float32), bc1, bc2,
        jnp.asarray(inv_scale, jnp.float32),
        jnp.asarray(found_inf, jnp.float32),
    ]).reshape(1, _NS)


@functools.partial(jax.jit, static_argnames=("mode", "bias_correction",
                                             "block_rows", "interpret"),
                   donate_argnums=(0, 2, 3))
def fused_adam_flat(p: jax.Array, g: jax.Array, m: jax.Array, v: jax.Array,
                    lr, beta1: float = 0.9, beta2: float = 0.999,
                    eps: float = 1e-8, weight_decay=0.0, step=1,
                    mode: int = ADAM_MODE_ADAMW, bias_correction: bool = True,
                    inv_scale=1.0, found_inf=False,
                    block_rows: int | None = None,
                    interpret: bool | None = None):
    """One fused Adam step over flat 1-D buffers. Returns ``(p, m, v)``.

    ``p``/``m``/``v`` are donated (in-place update, like the CUDA kernels).
    ``lr``/``step``/``inv_scale``/``found_inf`` may be traced scalars
    (capturable semantics, fused_adam.py:234-308 of the reference frontend).
    """
    if interpret is None:
        interpret = interpret_default()
    scal = _pack_scalars(lr, beta1, beta2, eps, weight_decay, step,
                         bias_correction, inv_scale,
                         jnp.asarray(found_inf, jnp.float32))
    p2, g2, m2, v2 = _as_rows(p), _as_rows(g), _as_rows(m), _as_rows(v)
    rows = p2.shape[0]
    br = _flat_block_rows("fused_adam", rows, p2.dtype, interpret,
                          block_rows)
    grid = (pl.cdiv(rows, br),)

    def dspec():
        return pl.BlockSpec((br, LANE), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        functools.partial(_adam_kernel, mode=mode),
        grid=grid,
        in_specs=[pl.BlockSpec((1, _NS), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
                  dspec(), dspec(), dspec(), dspec()],
        out_specs=[dspec(), dspec(), dspec()],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(m2.shape, m2.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v2.dtype)],
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(scal, p2, g2, m2, v2)
    p_new, m_new, v_new = out
    return p_new.reshape(p.shape), m_new.reshape(m.shape), v_new.reshape(v.shape)


@functools.partial(jax.jit, static_argnames=("mode", "bias_correction",
                                             "block_rows", "interpret",
                                             "lp_dtype"),
                   donate_argnums=(0, 2, 3))
def fused_adam_flat_master(p_master: jax.Array, g: jax.Array, m: jax.Array,
                           v: jax.Array, lr, beta1: float = 0.9,
                           beta2: float = 0.999, eps: float = 1e-8,
                           weight_decay=0.0, step=1,
                           mode: int = ADAM_MODE_ADAMW,
                           bias_correction: bool = True,
                           inv_scale=1.0, found_inf=False,
                           lp_dtype=jnp.bfloat16,
                           block_rows: int | None = None,
                           interpret: bool | None = None):
    """Master-weight fused Adam: fp32 master update + low-precision param copy.

    Returns ``(p_master, p_lowprec, m, v)`` — ≈ AdamCapturableMasterFunctor /
    ``multi_tensor_fused_adam_with_param_remainders`` use case
    (apex/contrib/csrc/optimizers/multi_tensor_distopt_adam.cpp:20-29).
    """
    if interpret is None:
        interpret = interpret_default()
    scal = _pack_scalars(lr, beta1, beta2, eps, weight_decay, step,
                         bias_correction, inv_scale,
                         jnp.asarray(found_inf, jnp.float32))
    p2, g2, m2, v2 = _as_rows(p_master), _as_rows(g), _as_rows(m), _as_rows(v)
    rows = p2.shape[0]
    # same streaming pattern (one extra lp write) — shares fused_adam's
    # tuned entries rather than fragmenting the cache
    br = _flat_block_rows("fused_adam", rows, p2.dtype, interpret,
                          block_rows)
    grid = (pl.cdiv(rows, br),)

    def dspec():
        return pl.BlockSpec((br, LANE), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        functools.partial(_master_adam_kernel, mode=mode),
        grid=grid,
        in_specs=[pl.BlockSpec((1, _NS), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
                  dspec(), dspec(), dspec(), dspec()],
        out_specs=[dspec(), dspec(), dspec(), dspec()],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, jnp.float32),
                   jax.ShapeDtypeStruct(p2.shape, lp_dtype),
                   jax.ShapeDtypeStruct(m2.shape, m2.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v2.dtype)],
        input_output_aliases={1: 0, 3: 2, 4: 3},
        interpret=interpret,
    )(scal, p2, g2, m2, v2)
    pm, plp, m_new, v_new = out
    return (pm.reshape(p_master.shape), plp.reshape(p_master.shape),
            m_new.reshape(m.shape), v_new.reshape(v.shape))
