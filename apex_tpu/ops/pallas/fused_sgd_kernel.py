"""Pallas TPU kernel for the fused SGD update over flat parameter buffers —
kernel-layer equivalent of ``csrc/multi_tensor_sgd_kernel.cu`` (``SGDFunctor``
with momentum / dampening / nesterov / wd-before-or-after-momentum, depths
2-4 incl. the fp16 model-weight copy-out).

Same flat-buffer layout and capturable-scalar conventions as
fused_adam_kernel.py (one kernel over the whole 128-lane-padded param group).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.pallas.fused_adam_kernel import (LANE, SUBLANE, _as_rows,
                                                   _flat_block_rows)
from apex_tpu.utils.env import interpret_default

_f32 = jnp.float32
# scalars: [lr, momentum, dampening, wd, inv_scale, noop, first_step]
_NS = 7


def _sgd_kernel(scal_ref, p_ref, g_ref, b_ref, p_out, b_out, *,
                nesterov: bool, wd_after_momentum: bool):
    lr = scal_ref[0, 0]
    momentum = scal_ref[0, 1]
    dampening = scal_ref[0, 2]
    wd = scal_ref[0, 3]
    inv_scale = scal_ref[0, 4]
    noop = scal_ref[0, 5]
    first = scal_ref[0, 6]

    p = p_ref[...].astype(_f32)
    g = g_ref[...].astype(_f32) * inv_scale
    buf = b_ref[...].astype(_f32)

    if not wd_after_momentum:
        g = g + wd * p
    b_new = jnp.where(first != 0.0, g,
                      momentum * buf + (1.0 - dampening) * g)
    use_momentum = momentum != 0.0
    if nesterov:
        d = jnp.where(use_momentum, g + momentum * b_new, g)
    else:
        d = jnp.where(use_momentum, b_new, g)
    if wd_after_momentum:
        d = d + wd * p
    p_new = p - lr * d

    keep = noop != 0.0
    p_out[...] = jnp.where(keep, p, p_new).astype(p_out.dtype)
    b_out[...] = jnp.where(keep, buf,
                           jnp.where(use_momentum, b_new, buf)
                           ).astype(b_out.dtype)


@functools.partial(jax.jit, static_argnames=("nesterov", "wd_after_momentum",
                                             "block_rows", "interpret"),
                   donate_argnums=(0, 2))
def fused_sgd_flat(p: jax.Array, g: jax.Array, momentum_buf: jax.Array,
                   lr, momentum: float = 0.0, dampening: float = 0.0,
                   weight_decay=0.0, nesterov: bool = False,
                   wd_after_momentum: bool = False, inv_scale=1.0,
                   found_inf=False, first_step=False,
                   block_rows: int | None = None,
                   interpret: bool | None = None):
    """One fused SGD step over flat 1-D buffers. Returns ``(p, momentum_buf)``.
    ``p``/``momentum_buf`` donated; scalars may be traced (capturable)."""
    if interpret is None:
        interpret = interpret_default()
    scal = jnp.stack([
        jnp.asarray(lr, _f32), _f32(momentum), _f32(dampening),
        jnp.asarray(weight_decay, _f32), jnp.asarray(inv_scale, _f32),
        jnp.asarray(found_inf, _f32), jnp.asarray(first_step, _f32),
    ]).reshape(1, _NS)
    p2, g2, b2 = _as_rows(p), _as_rows(g), _as_rows(momentum_buf)
    rows = p2.shape[0]
    br = _flat_block_rows("fused_sgd", rows, p2.dtype, interpret,
                          block_rows)
    grid = (pl.cdiv(rows, br),)

    def dspec():
        return pl.BlockSpec((br, LANE), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    p_new, b_new = pl.pallas_call(
        functools.partial(_sgd_kernel, nesterov=nesterov,
                          wd_after_momentum=wd_after_momentum),
        grid=grid,
        in_specs=[pl.BlockSpec((1, _NS), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
                  dspec(), dspec(), dspec()],
        out_specs=[dspec(), dspec()],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(b2.shape, b2.dtype)],
        input_output_aliases={1: 0, 3: 1},
        interpret=interpret,
    )(scal, p2, g2, b2)
    return p_new.reshape(p.shape), b_new.reshape(momentum_buf.shape)
