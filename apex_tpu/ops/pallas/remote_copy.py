"""Peer-to-peer device copies — Pallas TPU remote DMA.

The TPU materialization of the reference's peer-memory machinery
(``apex/contrib/peer_memory/peer_memory.py`` raw IPC buffers +
``peer_halo_exchanger_1d.py`` direct puts, and the ``nccl_p2p`` send/recv
pairs): ``pltpu.make_async_remote_copy`` issues a one-sided RDMA put over
ICI from this chip's buffer into a neighbor's, synchronized by DMA
semaphores — no collective, no host involvement. This is the same
hardware path XLA's ``ppermute`` lowers to, exposed as a kernel so halo
payloads can move while the surrounding kernel computes (the latency
hiding the reference's peer pools exist for).

Used by ``contrib.peer_memory`` / ``parallel.halo`` as the opt-in
``transport="rdma"`` path; the default XLA-collective path remains for
callers that prefer compiler-scheduled comm. Both are parity-tested
against each other on the virtual CPU mesh (interpret mode executes the
remote copies faithfully).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.utils.compat import axis_size

from apex_tpu.utils.env import interpret_default


def _shift_kernel(x_ref, o_ref, send_sem, recv_sem, *, axis_name, shift):
    my = jax.lax.axis_index(axis_name)
    n = axis_size(axis_name)
    dst = jax.lax.rem(my + shift + n, n)
    rdma = pltpu.make_async_remote_copy(
        src_ref=x_ref, dst_ref=o_ref, send_sem=send_sem, recv_sem=recv_sem,
        device_id=dst, device_id_type=pltpu.DeviceIdType.LOGICAL)
    rdma.start()
    rdma.wait()


def peer_shift(x: jax.Array, axis_name: str, shift: int = 1,
               interpret: bool | None = None) -> jax.Array:
    """Ring-shift ``x`` by ``shift`` positions along ``axis_name`` via a
    one-sided RDMA put (each device receives the shard of the device
    ``shift`` places behind it). Call inside ``shard_map``. Equivalent to
    ``jax.lax.ppermute`` with the ring permutation — implemented as an
    explicit peer copy, the ``nccl_p2p.nccl_send``/``nccl_recv`` pair."""
    if interpret is None:
        interpret = interpret_default()
    return pl.pallas_call(
        functools.partial(_shift_kernel, axis_name=axis_name, shift=shift),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        interpret=interpret,
    )(x)


def _tile_rows(dtype) -> int:
    """Minimum sublane (second-minor) tile for ``dtype`` — HBM memref
    slices must be tile-aligned on this axis (Mosaic rejects e.g. a 2-row
    f32 slice of an (8,128)-tiled ref; caught by tools/mosaic_aot.py)."""
    return {1: 32, 2: 16}.get(jnp.dtype(dtype).itemsize, 8)


def _halo_plan(rows: int, halo: int, dtype) -> tuple[int, bool, int]:
    """(send_rows, full, buf_rows) for a ``(rows, ...)`` halo exchange —
    the single source of the landing-buffer shape contract shared by
    ``halo_exchange_rdma`` and ``halo_buf_rows``."""
    t = _tile_rows(dtype)
    send_rows = -(-halo // t) * t  # halo rounded up to the sublane tile
    # whole-ref transfer when the shard is too small for an aligned edge
    # slice (also covers shards whose row count breaks the high-edge
    # slice's tile alignment)
    full = send_rows >= rows or rows % t != 0
    return send_rows, full, (rows if full else send_rows)


def halo_buf_rows(rows: int, halo: int, dtype) -> int:
    """Rows of the landing buffer ``halo_exchange_rdma`` uses for a
    ``(rows, ...)`` input — whole sublane tiles, or the full ref when the
    shard is small/unaligned. Exposed so callers (PeerMemoryPool) can
    pre-allocate aliasable landing buffers of the right shape."""
    return _halo_plan(rows, halo, dtype)[2]


def _halo_kernel(x_ref, lo_ref, hi_ref, slo, shi, rlo, rhi, *,
                 axis_name, send_rows, full):
    """Send my low edge to the LEFT neighbor's ``hi`` buffer and my high
    edge to the RIGHT neighbor's ``lo`` buffer (periodic ring; the wrapper
    zeroes wrap-around halos for non-periodic semantics).

    ``send_rows`` is the halo rounded UP to the dtype's sublane tile: HBM
    slices must be tile-aligned, so we over-send whole tiles and the
    wrapper slices the true halo out of the landed buffer. ``full`` ships
    the entire ref (no slice at all) when the shard is too small or not
    tile-aligned."""
    my = jax.lax.axis_index(axis_name)
    n = axis_size(axis_name)
    left = jax.lax.rem(my - 1 + n, n)
    right = jax.lax.rem(my + 1, n)
    if full:
        src_lo = src_hi = x_ref
    else:
        src_lo = x_ref.at[pl.ds(0, send_rows)]
        src_hi = x_ref.at[pl.ds(x_ref.shape[0] - send_rows, send_rows)]
    # my low-edge tiles -> left neighbor's hi_ref
    put_lo = pltpu.make_async_remote_copy(
        src_ref=src_lo, dst_ref=hi_ref,
        send_sem=slo, recv_sem=rhi,
        device_id=left, device_id_type=pltpu.DeviceIdType.LOGICAL)
    # my high-edge tiles -> right neighbor's lo_ref
    put_hi = pltpu.make_async_remote_copy(
        src_ref=src_hi, dst_ref=lo_ref, send_sem=shi, recv_sem=rlo,
        device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
    put_lo.start()
    put_hi.start()
    put_lo.wait()
    put_hi.wait()


def halo_exchange_rdma(x: jax.Array, axis_name: str, halo: int,
                       periodic: bool = False,
                       bufs=None, return_bufs: bool = False,
                       interpret: bool | None = None):
    """1-D halo exchange over leading axis via peer RDMA puts: returns
    ``(lo, hi)`` — the ``halo`` rows received from the left and right
    neighbors (≈ ``PeerHaloExchanger1d`` over a ``PeerMemoryPool``,
    peer_halo_exchanger_1d.py). ``periodic=False`` zeroes the wrap-around
    halos at the ring edges, matching the halo exchangers' boundary
    convention in ``parallel.halo``.

    ``bufs=(lo_buf, hi_buf)`` — optional pre-allocated landing buffers of
    shape ``(halo_buf_rows(rows, halo, dtype),) + x.shape[1:]`` (e.g. from
    a PeerMemoryPool arena). They are DONATED: the remote puts land in
    their storage via input/output aliasing instead of fresh HBM each
    call. ``return_bufs=True`` additionally returns the landed full
    buffers ``(lo_buf', hi_buf')`` so the caller can thread them into the
    next call (functional buffer reuse — the reference peer pool's
    no-per-iteration-allocation property, peer_memory.py:29-42, requires
    this threading; re-materializing views from the arena each call would
    allocate fresh storage and defeat the point)."""
    if interpret is None:
        interpret = interpret_default()
    rows = x.shape[0]
    send_rows, full, buf_rows = _halo_plan(rows, halo, x.dtype)
    kernel = functools.partial(_halo_kernel, axis_name=axis_name,
                               send_rows=send_rows, full=full)
    out_shape = [
        jax.ShapeDtypeStruct((buf_rows,) + x.shape[1:], x.dtype),
        jax.ShapeDtypeStruct((buf_rows,) + x.shape[1:], x.dtype),
    ]
    out_specs = [pl.BlockSpec(memory_space=pl.ANY),
                 pl.BlockSpec(memory_space=pl.ANY)]
    sems = [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA]
    if bufs is not None:
        lo_in, hi_in = bufs
        want = (buf_rows,) + x.shape[1:]
        if lo_in.shape != want or hi_in.shape != want or \
                lo_in.dtype != x.dtype or hi_in.dtype != x.dtype:
            raise ValueError(
                f"landing buffers must be {want} {x.dtype} (use "
                f"halo_buf_rows); got {lo_in.shape}/{hi_in.shape} "
                f"{lo_in.dtype}")

        def kernel_aliased(x_ref, lo_in_ref, hi_in_ref, lo_ref, hi_ref,
                           *sems_):
            del lo_in_ref, hi_in_ref  # same storage as lo_ref/hi_ref
            kernel(x_ref, lo_ref, hi_ref, *sems_)

        lo_buf, hi_buf = pl.pallas_call(
            kernel_aliased,
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
            out_specs=out_specs,
            scratch_shapes=sems,
            input_output_aliases={1: 0, 2: 1},
            interpret=interpret,
        )(x, lo_in, hi_in)
    else:
        lo_buf, hi_buf = pl.pallas_call(
            kernel,
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=out_specs,
            scratch_shapes=sems,
            interpret=interpret,
        )(x)
    # the landed buffers carry whole tiles; the true halo is the left
    # neighbor's LAST rows / right neighbor's FIRST rows
    lo = jax.lax.slice_in_dim(lo_buf, buf_rows - halo, buf_rows, axis=0)
    hi = jax.lax.slice_in_dim(hi_buf, 0, halo, axis=0)
    if return_bufs:
        out_bufs = (lo_buf, hi_buf)
    if not periodic:
        idx = jax.lax.axis_index(axis_name)
        n = axis_size(axis_name)
        lo = jnp.where(idx == 0, jnp.zeros_like(lo), lo)
        hi = jnp.where(idx == n - 1, jnp.zeros_like(hi), hi)
    if return_bufs:
        return lo, hi, out_bufs
    return lo, hi
