"""Pallas TPU kernels for fused LayerNorm / RMSNorm forward + backward.

TPU-native equivalent of ``csrc/layer_norm_cuda_kernel.cu``:
- fwd ``cuApplyLayerNorm``/``cuApplyRMSNorm`` (:366,373) with rowwise Welford
  stats (:52) → here a rowwise mean/var in fp32 on the VPU.
- bwd two-stage dgamma/dbeta (``cuComputePartGradGammaBeta`` :482 →
  per-grid-block partials; ``cuComputeGradGammaBeta`` :557 → final XLA reduce)
  and ``cuComputeGradInput`` (:609) → per-row dx kernel.
- ``memory_efficient`` saves (output, invvar) and reconstructs the input from
  the output in backward (reference frontend fused_layer_norm.py:53-56).

Stats are always fp32 regardless of IO dtype (mixed-dtype paths of
``layer_norm_cuda.cpp:253-269``).

Layout: input reshaped to (rows, hidden); grid over row-blocks; gamma/beta
broadcast to every block. Hidden sizes not 128-lane friendly fall back to the
jnp reference implementation in apex_tpu/normalization/fused_layer_norm.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.pallas.tiling import norm_block_rows
from apex_tpu.tune.api import pow2_bucket, tuned_params
from apex_tpu.utils.env import interpret_default

_f32 = jnp.float32


SUBLANE = 8


def _pick_block_rows(rows: int, hidden: int) -> int:
    # keep ~4 operand blocks under a few MiB of VMEM; rows is a multiple of
    # 8 — shared heuristic (ops/pallas/tiling.py), also the autotuner's
    # default candidate
    return norm_block_rows(rows, hidden)


def _block_rows(rows: int, hidden: int, dtype, interpret: bool,
                block_rows: int | None = None) -> int:
    """Row-block resolution: explicit arg > tuned cache entry > heuristic.
    The tuned entry must still tile the CONCRETE row count exactly (the
    backward accumulates dgamma across grid steps, so a ragged tail block
    is not acceptable here)."""
    if block_rows is not None:
        return block_rows

    def ok(p):
        br = p["block_rows"]
        return (isinstance(br, int) and br >= SUBLANE
                and br % SUBLANE == 0 and rows % br == 0)

    return tuned_params(
        "layer_norm", (("rows", pow2_bucket(rows)), ("hidden", hidden)),
        {"block_rows": _pick_block_rows(rows, hidden)},
        dtype=dtype, interpret=interpret, validate=ok)["block_rows"]


def _pad_rows(x: jax.Array):
    rows = x.shape[0]
    pad = (-rows) % SUBLANE
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    return x, rows


# ---------------------------------------------------------------- forward


def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, invvar_ref, *,
                   eps: float, rms: bool, affine: bool):
    x = x_ref[...].astype(_f32)
    if rms:
        var = jnp.mean(x * x, axis=1, keepdims=True)
        rstd = jax.lax.rsqrt(var + eps)
        xhat = x * rstd
        mean_ref[...] = jnp.zeros_like(rstd)
    else:
        mu = jnp.mean(x, axis=1, keepdims=True)
        xc = x - mu
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
        rstd = jax.lax.rsqrt(var + eps)
        xhat = xc * rstd
        mean_ref[...] = mu
    invvar_ref[...] = rstd
    if affine:
        y = xhat * g_ref[...].astype(_f32)
        if b_ref is not None:
            y = y + b_ref[...].astype(_f32)
    else:
        y = xhat
    y_ref[...] = y.astype(y_ref.dtype)


def ln_fwd_pallas(x2: jax.Array, gamma, beta, *, eps: float, rms: bool,
                  interpret: bool | None = None,
                  block_rows: int | None = None):
    """x2: (rows, hidden). Returns (y, mean, invvar) with fp32 stats."""
    if interpret is None:
        interpret = interpret_default()
    x2, true_rows = _pad_rows(x2)
    rows, hidden = x2.shape
    br = _block_rows(rows, hidden, x2.dtype, interpret, block_rows)
    grid = (pl.cdiv(rows, br),)
    affine = gamma is not None
    has_beta = beta is not None

    in_specs = [pl.BlockSpec((br, hidden), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)]
    args = [x2]
    if affine:
        in_specs.append(pl.BlockSpec((1, hidden), lambda i: (0, 0),
                                     memory_space=pltpu.VMEM))
        args.append(gamma.reshape(1, hidden))
    if has_beta:
        in_specs.append(pl.BlockSpec((1, hidden), lambda i: (0, 0),
                                     memory_space=pltpu.VMEM))
        args.append(beta.reshape(1, hidden))

    def kernel(*refs):
        if affine and has_beta:
            x_ref, g_ref, b_ref, y_ref, mean_ref, invvar_ref = refs
        elif affine:
            x_ref, g_ref, y_ref, mean_ref, invvar_ref = refs
            b_ref = None
        else:
            x_ref, y_ref, mean_ref, invvar_ref = refs
            g_ref = b_ref = None
        _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, invvar_ref,
                       eps=eps, rms=rms, affine=affine)

    y, mean, invvar = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((br, hidden), lambda i: (i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((br, 1), lambda i: (i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((br, 1), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((rows, hidden), x2.dtype),
                   jax.ShapeDtypeStruct((rows, 1), _f32),
                   jax.ShapeDtypeStruct((rows, 1), _f32)],
        interpret=interpret,
    )(*args)
    if true_rows != rows:
        y, mean, invvar = y[:true_rows], mean[:true_rows], invvar[:true_rows]
    return y, mean, invvar


# ---------------------------------------------------------------- backward


def _ln_bwd_kernel(dy_ref, s_ref, g_ref, b_ref, mean_ref, invvar_ref,
                   dx_ref, dgp_ref, dbp_ref, *, rms: bool, affine: bool,
                   memory_efficient: bool):
    dy = dy_ref[...].astype(_f32)
    s = s_ref[...].astype(_f32)  # x (normal) or y (memory_efficient)
    rstd = invvar_ref[...]
    hidden = dy.shape[1]

    if memory_efficient:
        # reconstruct xhat from output (layer_norm_cuda_kernel.cu MemoryEfficient)
        if affine:
            g = g_ref[...].astype(_f32)
            if not rms and b_ref is not None:
                xhat = (s - b_ref[...].astype(_f32)) / g
            else:
                xhat = s / g
        else:
            xhat = s
    else:
        if rms:
            xhat = s * rstd
        else:
            xhat = (s - mean_ref[...]) * rstd

    wdy = dy * g_ref[...].astype(_f32) if affine else dy
    c1 = jnp.mean(xhat * wdy, axis=1, keepdims=True)
    if rms:
        dx = (wdy - xhat * c1) * rstd
    else:
        c2 = jnp.mean(wdy, axis=1, keepdims=True)
        dx = (wdy - xhat * c1 - c2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)
    if affine:
        # dgamma/dbeta accumulated across the (sequential) grid into one
        # (1, hidden) block — the role of the two-stage partial buffers in
        # cuComputePartGradGammaBeta/cuComputeGradGammaBeta (:482,:557).
        first = pl.program_id(0) == 0

        @pl.when(first)
        def _init():
            dgp_ref[...] = jnp.zeros_like(dgp_ref)
            if dbp_ref is not None:
                dbp_ref[...] = jnp.zeros_like(dbp_ref)

        dgp_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
        if dbp_ref is not None:
            dbp_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


def ln_bwd_pallas(dy2, saved2, gamma, beta, mean, invvar, *, rms: bool,
                  memory_efficient: bool, interpret: bool | None = None,
                  block_rows: int | None = None):
    """Returns (dx, dgamma|None, dbeta|None). saved2 = x2 or y2 (mem-efficient)."""
    if interpret is None:
        interpret = interpret_default()
    dy2, true_rows = _pad_rows(dy2)
    saved2, _ = _pad_rows(saved2)
    mean, _ = _pad_rows(mean)
    invvar, _ = _pad_rows(invvar)
    rows, hidden = dy2.shape
    br = _block_rows(rows, hidden, dy2.dtype, interpret, block_rows)
    nblk = pl.cdiv(rows, br)
    affine = gamma is not None
    has_beta = beta is not None

    in_specs = [
        pl.BlockSpec((br, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((br, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM),
    ]
    args = [dy2, saved2]
    if affine:
        in_specs.append(pl.BlockSpec((1, hidden), lambda i: (0, 0),
                                     memory_space=pltpu.VMEM))
        args.append(gamma.reshape(1, hidden))
    if has_beta:
        in_specs.append(pl.BlockSpec((1, hidden), lambda i: (0, 0),
                                     memory_space=pltpu.VMEM))
        args.append(beta.reshape(1, hidden))
    in_specs += [
        pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
    ]
    args += [mean, invvar]

    out_specs = [pl.BlockSpec((br, hidden), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((rows, hidden), dy2.dtype)]
    if affine:
        out_specs.append(pl.BlockSpec((1, hidden), lambda i: (0, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((1, hidden), _f32))
        if has_beta:
            out_specs.append(pl.BlockSpec((1, hidden), lambda i: (0, 0),
                                          memory_space=pltpu.VMEM))
            out_shape.append(jax.ShapeDtypeStruct((1, hidden), _f32))

    def kernel(*refs):
        i = 0
        dy_ref = refs[i]; i += 1
        s_ref = refs[i]; i += 1
        g_ref = b_ref = None
        if affine:
            g_ref = refs[i]; i += 1
        if has_beta:
            b_ref = refs[i]; i += 1
        mean_ref = refs[i]; i += 1
        invvar_ref = refs[i]; i += 1
        dx_ref = refs[i]; i += 1
        dgp_ref = dbp_ref = None
        if affine:
            dgp_ref = refs[i]; i += 1
        if has_beta:
            dbp_ref = refs[i]; i += 1
        _ln_bwd_kernel(dy_ref, s_ref, g_ref, b_ref, mean_ref, invvar_ref,
                       dx_ref, dgp_ref, dbp_ref, rms=rms, affine=affine,
                       memory_efficient=memory_efficient)

    out = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*args)
    dx = out[0][:true_rows]
    dgamma = dbeta = None
    if affine:
        dgamma = out[1].reshape(hidden)
        if has_beta:
            dbeta = out[2].reshape(hidden)
    return dx, dgamma, dbeta
