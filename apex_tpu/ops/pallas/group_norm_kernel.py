"""Pallas TPU kernels for NHWC GroupNorm (+ fused SiLU) — the kernel-layer
equivalent of ``group_norm_cuda`` / ``group_norm_v2_cuda``
(apex/contrib/csrc/group_norm*: one-pass & two-pass NHWC algorithms across 27
per-channel-count instantiations; SURVEY §2.3).

TPU design: the two-pass structure survives (pass 1: per-(sample, group)
sum/sumsq partials accumulated across HW tiles; pass 2: normalize + affine +
SiLU fused over the same tiles) but ONE kernel pair covers every channel
count — per-shape instantiation is the Mosaic compiler's job. Stats fp32.
The backward uses the saved (mean, rstd) in one fused XLA chain (the
dgamma/dbeta reductions are column sums XLA already tiles well).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.utils.env import interpret_default

_f32 = jnp.float32


def pallas_ok(n: int, hw: int, c: int) -> bool:
    """Shape guard: HW tiles need 8-sublane alignment."""
    return hw % 8 == 0


def _pick_hw_block(hw: int, c: int) -> int:
    budget = max((2 * 1024 * 1024) // max(c * 4, 1), 8)
    blk = 1 << (budget.bit_length() - 1)
    blk = min(blk, hw)
    while hw % blk != 0 and blk > 8:
        blk //= 2
    return max(blk, 8)


def _stats_kernel(x_ref, sel_ref, sum_ref, sq_ref):
    """Per-group partials via an MXU matmul with the (C, G) group-selector —
    no lane-dim reshapes (Mosaic-unfriendly)."""
    h = pl.program_id(1)

    @pl.when(h == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    x = x_ref[0].astype(_f32)                     # (hwb, C)
    sel = sel_ref[...]                            # (C, G) one-hot
    csum = jnp.sum(x, axis=0, keepdims=True)      # (1, C)
    csq = jnp.sum(x * x, axis=0, keepdims=True)
    # HIGHEST: keep full fp32 operand mantissas on the MXU — these are
    # large per-channel sums and default (bf16-operand) precision would put
    # ~1e-3 relative error into the group statistics
    sum_ref[...] += jnp.dot(csum, sel, preferred_element_type=_f32,
                            precision=jax.lax.Precision.HIGHEST)[None]
    sq_ref[...] += jnp.dot(csq, sel, preferred_element_type=_f32,
                           precision=jax.lax.Precision.HIGHEST)[None]


def _apply_kernel(x_ref, mean_ref, rstd_ref, w_ref, b_ref, y_ref, *,
                  act: str):
    x = x_ref[0].astype(_f32)                     # (hwb, C)
    y = (x - mean_ref[0]) * rstd_ref[0]
    if w_ref is not None:
        y = y * w_ref[...].astype(_f32)
    if b_ref is not None:
        y = y + b_ref[...].astype(_f32)
    if act == "silu":
        y = y * jax.nn.sigmoid(y)
    y_ref[0] = y.astype(y_ref.dtype)


def group_norm_nhwc_pallas(x: jax.Array, num_groups: int,
                           weight: Optional[jax.Array] = None,
                           bias: Optional[jax.Array] = None,
                           eps: float = 1e-5, act: str = "",
                           interpret: Optional[bool] = None):
    """Forward: returns (y, mean, rstd) with mean/rstd (N, G) fp32."""
    if interpret is None:
        interpret = interpret_default()
    n, h, w, c = x.shape
    g = num_groups
    hw = h * w
    x3 = x.reshape(n, hw, c)
    hwb = _pick_hw_block(hw, c)
    grid = (n, hw // hwb)

    xspec = pl.BlockSpec((1, hwb, c), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM)
    gspec = pl.BlockSpec((1, 1, g), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM)
    selspec = pl.BlockSpec((c, g), lambda i, j: (0, 0),
                           memory_space=pltpu.VMEM)
    cpg = c // g
    sel = (jax.lax.broadcasted_iota(jnp.int32, (c, g), 0) // cpg
           == jax.lax.broadcasted_iota(jnp.int32, (c, g), 1)).astype(_f32)

    sums, sqs = pl.pallas_call(
        _stats_kernel,
        grid=grid,
        in_specs=[xspec, selspec],
        out_specs=[gspec, gspec],
        out_shape=[jax.ShapeDtypeStruct((n, 1, g), _f32),
                   jax.ShapeDtypeStruct((n, 1, g), _f32)],
        interpret=interpret,
    )(x3, sel)
    cnt = _f32(hw * (c // g))
    mean = sums[:, 0] / cnt                                    # (N, G)
    var = sqs[:, 0] / cnt - mean * mean
    rstd = jax.lax.rsqrt(var + eps)

    mean_c = jnp.repeat(mean, cpg, axis=1).reshape(n, 1, c)
    rstd_c = jnp.repeat(rstd, cpg, axis=1).reshape(n, 1, c)

    cspec = pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM)
    in_specs = [xspec, cspec, cspec]
    args = [x3, mean_c, rstd_c]
    wspec = pl.BlockSpec((1, c), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM)
    if weight is not None:
        in_specs.append(wspec)
        args.append(weight.reshape(1, c))
    if bias is not None:
        in_specs.append(wspec)
        args.append(bias.reshape(1, c))

    def kernel(*refs):
        if weight is not None and bias is not None:
            x_ref, m_ref, r_ref, w_ref, b_ref, y_ref = refs
        elif weight is not None:
            x_ref, m_ref, r_ref, w_ref, y_ref = refs
            b_ref = None
        else:
            x_ref, m_ref, r_ref, y_ref = refs
            w_ref = b_ref = None
        _apply_kernel(x_ref, m_ref, r_ref, w_ref, b_ref, y_ref, act=act)

    y = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=xspec,
        out_shape=jax.ShapeDtypeStruct((n, hw, c), x.dtype),
        interpret=interpret,
    )(*args)
    return y.reshape(n, h, w, c), mean, rstd
