"""Pallas TPU kernels for NHWC GroupNorm (+ fused SiLU) — the kernel-layer
equivalent of ``group_norm_cuda`` / ``group_norm_v2_cuda``
(apex/contrib/csrc/group_norm*: one-pass & two-pass NHWC algorithms across 27
per-channel-count instantiations; SURVEY §2.3).

TPU design: BOTH reference algorithms, selected like the reference selects
them (``group_norm.py:193-209`` keys one-pass on channels-per-group and SM
resources; here the analogous resource bound is the VMEM slab):

- **one-pass** (``_one_pass_kernel``): the whole (HW, C) sample slab lives
  in VMEM for one grid step — stats AND normalize+affine+SiLU happen on a
  single HBM read of x (1R + 1W total), halving traffic exactly where the
  reference's one-pass wins. Selected when the slab fits
  (:func:`one_pass_ok`).
- **two-pass** (``_stats_kernel`` + ``_apply_kernel``): per-(sample, group)
  sum/sumsq partials accumulated across HW tiles, then a second sweep
  normalizes (2R + 1W) — covers arbitrarily large HW.

ONE kernel pair covers every channel count — per-shape instantiation is the
Mosaic compiler's job. Stats fp32. The backward uses the saved (mean, rstd)
in one fused XLA chain (the dgamma/dbeta reductions are column sums XLA
already tiles well).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops.pallas._compat import CompilerParams as _CompilerParams
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops.pallas.tiling import groupnorm_hw_block
from apex_tpu.tune.api import pow2_bucket, tuned_params
from apex_tpu.utils.env import interpret_default

_f32 = jnp.float32


def pallas_ok(n: int, hw: int, c: int) -> bool:
    """Shape guard: HW tiles need 8-sublane alignment."""
    return hw % 8 == 0


# one-pass slab budget: the (hw, c) block is double-buffered by Mosaic for
# BOTH x and y (4 windows) plus the in-kernel fp32 temporaries — a 2 MiB
# fp32 payload bounds the worst case (~10 MiB) under the ~16 MiB VMEM.
_ONE_PASS_SLAB_ELEMS = (2 * 1024 * 1024) // 4


def one_pass_ok(n: int, hw: int, c: int) -> bool:
    """TPU translation of the reference's one-pass eligibility rule
    (apex/contrib/group_norm/group_norm.py:193-209 picks one-pass by
    channels-per-group / SM capacity): one-pass needs the full per-sample
    (HW, C) slab resident so stats and apply share one read of x."""
    return pallas_ok(n, hw, c) and hw * c <= _ONE_PASS_SLAB_ELEMS


def _pick_hw_block(hw: int, c: int) -> int:
    # shared heuristic (ops/pallas/tiling.py), also the autotuner's
    # default candidate
    return groupnorm_hw_block(hw, c)


def _hw_block(hw: int, c: int, dtype, interpret: bool,
              hw_block: int | None = None) -> int:
    """HW-tile resolution: explicit arg > tuned cache entry > heuristic.
    The stats kernel accumulates per-group partials across HW tiles AND the
    grid floor-divides hw, so a block that does not tile ``hw`` exactly
    would silently drop the tail rows — explicit values are validated
    (ValueError), tuned entries rejected back to the heuristic."""
    def ok(p):
        blk = p["hw_block"]
        return (isinstance(blk, int) and blk >= 8 and blk % 8 == 0
                and hw % blk == 0)

    if hw_block is not None:
        if not ok({"hw_block": hw_block}):
            raise ValueError(
                f"group_norm hw_block={hw_block!r} invalid for hw={hw}: "
                f"must be a positive multiple of 8 that divides hw (the "
                f"two-pass grid floor-divides hw, so a non-divisor would "
                f"silently skip the HW tail)")
        return hw_block

    return tuned_params(
        "group_norm", (("hw", pow2_bucket(hw)), ("c", c)),
        {"hw_block": _pick_hw_block(hw, c)},
        dtype=dtype, interpret=interpret, validate=ok)["hw_block"]


def _make_sel(c: int, g: int):
    """(C, G) one-hot group-selector matrix (contiguous groups)."""
    return (jax.lax.broadcasted_iota(jnp.int32, (c, g), 0) // (c // g)
            == jax.lax.broadcasted_iota(jnp.int32, (c, g), 1)).astype(_f32)


def _append_wb(in_specs, args, weight, bias, c, wspec):
    """Append the optional affine operands (shared by both drivers)."""
    if weight is not None:
        in_specs.append(wspec)
        args.append(weight.reshape(1, c))
    if bias is not None:
        in_specs.append(wspec)
        args.append(bias.reshape(1, c))


def _split_wb(refs, n_head: int, has_w: bool, has_b: bool):
    """Split *refs laid out as [head..., w?, b?, tail...] →
    (head_refs, w_ref, b_ref, tail_refs) — the single unpacking convention
    for both drivers' kernels."""
    head = refs[:n_head]
    idx = n_head
    w_ref = b_ref = None
    if has_w:
        w_ref = refs[idx]
        idx += 1
    if has_b:
        b_ref = refs[idx]
        idx += 1
    return head, w_ref, b_ref, refs[idx:]


def _stats_kernel(x_ref, sel_ref, sum_ref, sq_ref):
    """Per-group partials via an MXU matmul with the (C, G) group-selector —
    no lane-dim reshapes (Mosaic-unfriendly)."""
    h = pl.program_id(1)

    @pl.when(h == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    x = x_ref[0].astype(_f32)                     # (hwb, C)
    sel = sel_ref[...]                            # (C, G) one-hot
    csum = jnp.sum(x, axis=0, keepdims=True)      # (1, C)
    csq = jnp.sum(x * x, axis=0, keepdims=True)
    # HIGHEST: keep full fp32 operand mantissas on the MXU — these are
    # large per-channel sums and default (bf16-operand) precision would put
    # ~1e-3 relative error into the group statistics
    sum_ref[...] += jnp.dot(csum, sel, preferred_element_type=_f32,
                            precision=jax.lax.Precision.HIGHEST)[None]
    sq_ref[...] += jnp.dot(csq, sel, preferred_element_type=_f32,
                           precision=jax.lax.Precision.HIGHEST)[None]


def _apply_kernel(x_ref, mean_ref, rstd_ref, w_ref, b_ref, y_ref, *,
                  act: str):
    x = x_ref[0].astype(_f32)                     # (hwb, C)
    y = (x - mean_ref[0]) * rstd_ref[0]
    if w_ref is not None:
        y = y * w_ref[...].astype(_f32)
    if b_ref is not None:
        y = y + b_ref[...].astype(_f32)
    if act == "silu":
        y = y * jax.nn.sigmoid(y)
    y_ref[0] = y.astype(y_ref.dtype)


def _one_pass_kernel(x_ref, sel_ref, selt_ref, w_ref, b_ref,
                     y_ref, mean_ref, rstd_ref, *, act: str, eps: float,
                     cnt: float):
    """Whole-sample slab: stats + normalize + affine + activation on ONE
    read of x (the reference's one-pass structure,
    group_norm_nhwc_one_pass_*.cu)."""
    x = x_ref[0].astype(_f32)                     # (hw, C)
    sel = sel_ref[...]                            # (C, G) one-hot
    csum = jnp.sum(x, axis=0, keepdims=True)      # (1, C)
    csq = jnp.sum(x * x, axis=0, keepdims=True)
    # HIGHEST precision — same rationale as _stats_kernel
    gsum = jnp.dot(csum, sel, preferred_element_type=_f32,
                   precision=jax.lax.Precision.HIGHEST)      # (1, G)
    gsq = jnp.dot(csq, sel, preferred_element_type=_f32,
                  precision=jax.lax.Precision.HIGHEST)
    mean = gsum / cnt
    var = gsq / cnt - mean * mean
    rstd = jax.lax.rsqrt(var + eps)
    mean_ref[0] = mean
    rstd_ref[0] = rstd
    selt = selt_ref[...]                          # (G, C) one-hot
    # HIGHEST: default (bf16-operand) precision would round the fp32 group
    # stats to ~2^-9 relative before normalization (same hazard as the
    # stats dots above)
    mean_c = jnp.dot(mean, selt, preferred_element_type=_f32,
                     precision=jax.lax.Precision.HIGHEST)     # (1, C)
    rstd_c = jnp.dot(rstd, selt, preferred_element_type=_f32,
                     precision=jax.lax.Precision.HIGHEST)
    y = (x - mean_c) * rstd_c
    if w_ref is not None:
        y = y * w_ref[...].astype(_f32)
    if b_ref is not None:
        y = y + b_ref[...].astype(_f32)
    if act == "silu":
        y = y * jax.nn.sigmoid(y)
    y_ref[0] = y.astype(y_ref.dtype)


def _group_norm_one_pass(x3, n, hw, c, g, weight, bias, eps, act,
                         interpret):
    sel = _make_sel(c, g)
    xspec = pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
    gspec = pl.BlockSpec((1, 1, g), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
    wspec = pl.BlockSpec((1, c), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)
    in_specs = [xspec,
                pl.BlockSpec((c, g), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((g, c), lambda i: (0, 0),
                             memory_space=pltpu.VMEM)]
    args = [x3, sel, sel.T]
    _append_wb(in_specs, args, weight, bias, c, wspec)

    def kernel(*refs):
        (x_ref, s_ref, st_ref), w_ref, b_ref, tail = _split_wb(
            refs, 3, weight is not None, bias is not None)
        y_ref, m_ref, r_ref = tail
        _one_pass_kernel(x_ref, s_ref, st_ref, w_ref, b_ref,
                         y_ref, m_ref, r_ref, act=act, eps=eps,
                         cnt=float(hw * (c // g)))

    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=in_specs,
        out_specs=[xspec, gspec, gspec],
        out_shape=[jax.ShapeDtypeStruct((n, hw, c), x3.dtype),
                   jax.ShapeDtypeStruct((n, 1, g), _f32),
                   jax.ShapeDtypeStruct((n, 1, g), _f32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*args)
    return y, mean[:, 0], rstd[:, 0]


def group_norm_nhwc_pallas(x: jax.Array, num_groups: int,
                           weight: Optional[jax.Array] = None,
                           bias: Optional[jax.Array] = None,
                           eps: float = 1e-5, act: str = "",
                           interpret: Optional[bool] = None,
                           algo: str = "auto",
                           hw_block: Optional[int] = None):
    """Forward: returns (y, mean, rstd) with mean/rstd (N, G) fp32.

    ``algo``: "auto" (one-pass when the sample slab fits VMEM — the
    reference's selection rule translated, group_norm.py:193-209),
    "one_pass", or "two_pass". ``hw_block`` overrides the tuned/heuristic
    two-pass HW tile (the autotuner's probe path)."""
    if interpret is None:
        interpret = interpret_default()
    n, h, w, c = x.shape
    if algo == "auto":
        algo = "one_pass" if one_pass_ok(n, h * w, c) else "two_pass"
    elif algo not in ("one_pass", "two_pass"):
        raise ValueError(f"algo must be auto|one_pass|two_pass, got {algo!r}")
    if algo == "one_pass":
        g = num_groups
        y, mean, rstd = _group_norm_one_pass(
            x.reshape(n, h * w, c), n, h * w, c, g, weight, bias, eps, act,
            interpret)
        return y.reshape(n, h, w, c), mean, rstd
    g = num_groups
    hw = h * w
    x3 = x.reshape(n, hw, c)
    hwb = _hw_block(hw, c, x.dtype, interpret, hw_block)
    grid = (n, hw // hwb)

    xspec = pl.BlockSpec((1, hwb, c), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM)
    gspec = pl.BlockSpec((1, 1, g), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM)
    selspec = pl.BlockSpec((c, g), lambda i, j: (0, 0),
                           memory_space=pltpu.VMEM)
    cpg = c // g
    sel = _make_sel(c, g)

    sums, sqs = pl.pallas_call(
        _stats_kernel,
        grid=grid,
        in_specs=[xspec, selspec],
        out_specs=[gspec, gspec],
        out_shape=[jax.ShapeDtypeStruct((n, 1, g), _f32),
                   jax.ShapeDtypeStruct((n, 1, g), _f32)],
        interpret=interpret,
    )(x3, sel)
    cnt = _f32(hw * (c // g))
    mean = sums[:, 0] / cnt                                    # (N, G)
    var = sqs[:, 0] / cnt - mean * mean
    rstd = jax.lax.rsqrt(var + eps)

    mean_c = jnp.repeat(mean, cpg, axis=1).reshape(n, 1, c)
    rstd_c = jnp.repeat(rstd, cpg, axis=1).reshape(n, 1, c)

    cspec = pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM)
    in_specs = [xspec, cspec, cspec]
    args = [x3, mean_c, rstd_c]
    wspec = pl.BlockSpec((1, c), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM)
    _append_wb(in_specs, args, weight, bias, c, wspec)

    def kernel(*refs):
        (x_ref, m_ref, r_ref), w_ref, b_ref, tail = _split_wb(
            refs, 3, weight is not None, bias is not None)
        _apply_kernel(x_ref, m_ref, r_ref, w_ref, b_ref, tail[0], act=act)

    y = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=xspec,
        out_shape=jax.ShapeDtypeStruct((n, hw, c), x.dtype),
        interpret=interpret,
    )(*args)
    return y.reshape(n, h, w, c), mean, rstd
